"""obs.goodput — token-level waste attribution for the serving tier.

The serving stack deliberately burns device work in half a dozen places
— speculative verify rows past the accepted prefix, recompute-on-resume
after preemption/evacuation, COW page copies, migration transport, idle
padded slots in the fixed-shape paged step — and until this module
nothing totaled useful vs wasted tokens, so "does spec_k=4 pay for
itself?" had no instrument. The :class:`WorkLedger` is the device-spend
counterpart of the step-phase profiler (obs/stepprof.py): where stepprof
partitions the iteration *wall*, the ledger partitions the iteration's
dispatched *token-rows*.

Every device token-row a serving iteration dispatches is attributed to
exactly one category:

=============  ======================================================
category       covers
=============  ======================================================
useful         committed output tokens + cold prefill of new positions
spec_rejected  verify rows past the accepted prefix (rolled back)
recompute      re-prefill of positions computed before a preempt /
               evacuation / backend-fallback resume
overhead       COW page copies and disagg migration block transport
idle           padded rows: empty decode slots, unused candidate
               columns, prefill-slice padding past the real tokens
=============  ======================================================

plus ``prefill_saved`` as an avoided-work CREDIT (prefix-cache hits:
rows that were never dispatched at all — outside the partition).

**Partition invariant**: instrumentation sites record the launch width
independently (:meth:`WorkLedger.dispatch`) from the attribution
(:meth:`WorkLedger.add`), so ``Σ categories == rows dispatched`` is a
real cross-check on the instrumentation, not a tautology —
:func:`check_partition` verifies it on every record, and
``obs.report --check`` re-verifies it on flight-dump records. All row
counts are integers and the only clock read is the iteration boundary
from the serving loop's injectable ``clock=``, so records are
byte-deterministic under a fake clock.

The time dimension (what end-of-run registry snapshots lack): every
``interval`` finished iterations the ledger folds the window's deltas
into a bounded ring of samples (→ ``timeline.json``), evaluates the
windowed **alert rules** against the trailing samples — goodput below
``goodput_floor`` for ``window`` consecutive intervals, or any waste
category's fraction above ``waste_ceiling`` for ``window`` intervals —
and queues fired alerts for the serving loop to dump through the
flight recorder's ``goodput_regression`` trigger kind. Per-record
Perfetto counter tracks export to ``goodput.spans.json`` (own pid lane,
merged by the report's ``*.spans.json`` glob).

Like the request tracer and step profiler, recording costs one
module-global load plus a ``None`` check when disabled.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Any, Callable

# Chrome-trace process id for the goodput counter lane (stepprof owns
# 93_001; commlint 95_000 — this lane slots between them).
GOODPUT_PID = 94_001

# The taxonomy, in render order (postmortem tables, report lane).
CATEGORIES = ("useful", "spec_rejected", "recompute", "overhead", "idle")

# Everything that is not useful — the alert rules' spike candidates.
WASTE_CATEGORIES = ("spec_rejected", "recompute", "overhead", "idle")

TIMELINE_SCHEMA = "tdtpu-goodput-timeline-v1"


def _env_opt_float(var: str) -> float | None:
    raw = os.environ.get(var, "")
    if not raw:
        return None
    try:
        return float(raw)
    except ValueError:
        return None


def _env_int(var: str, default: int) -> int:
    try:
        return int(os.environ.get(var, "") or default)
    except ValueError:
        return default


class WorkLedger:
    """Bounded per-iteration work records + interval samples + alerts.

    One ledger serves every engine in the process (fleet replicas
    included): iterations are single-threaded per engine and the fleet
    tier steps replicas sequentially, so one active-iteration slot
    suffices; records carry ``replica`` and cumulative category totals
    are kept per replica (the router's delta-merge publishes them under
    ``replica=`` labels). Interval samples and alert rules are
    process-wide — the time series watches the tier, not one replica.

    Args:
      run_dir: default directory for :meth:`save`/:meth:`save_timeline`.
      capacity: iteration-record ring bound.
      interval: finished iterations per timeline sample
        (``TDTPU_GOODPUT_INTERVAL``, default 8).
      window: consecutive breaching samples before an alert fires
        (``TDTPU_GOODPUT_WINDOW``, default 3).
      goodput_floor: alert when a sample's goodput fraction is below
        this for ``window`` samples (``TDTPU_GOODPUT_FLOOR``; None
        disables the rule).
      waste_ceiling: alert when any single waste category's fraction of
        the sample's rows exceeds this for ``window`` samples
        (``TDTPU_GOODPUT_WASTE_MAX``; None disables the rule).
    """

    def __init__(self, run_dir: str | None = None, capacity: int = 4096,
                 *, interval: int | None = None, window: int | None = None,
                 goodput_floor: float | None = None,
                 waste_ceiling: float | None = None,
                 timeline_capacity: int = 1024):
        self.run_dir = run_dir
        self.capacity = capacity
        self.interval = (int(interval) if interval is not None
                         else max(1, _env_int("TDTPU_GOODPUT_INTERVAL", 8)))
        self.window = (int(window) if window is not None
                       else max(1, _env_int("TDTPU_GOODPUT_WINDOW", 3)))
        self.goodput_floor = (goodput_floor if goodput_floor is not None
                              else _env_opt_float("TDTPU_GOODPUT_FLOOR"))
        self.waste_ceiling = (waste_ceiling if waste_ceiling is not None
                              else _env_opt_float("TDTPU_GOODPUT_WASTE_MAX"))
        self._records: deque[dict[str, Any]] = deque(maxlen=capacity)
        self._samples: deque[dict[str, Any]] = deque(maxlen=timeline_capacity)
        # Wall-clock rebase for the Perfetto merge (obs/stepprof.py
        # recipe): caller clocks are perf_counter-like seconds.
        self._epoch_s = time.perf_counter()
        self._wall_epoch_us = time.time_ns() / 1e3
        # Per-replica cumulative totals: {replica: {category: rows}} plus
        # "rows"/"prefill_saved" keys — the registry/flight evidence.
        self._cum: dict[str, dict[str, int]] = {}
        # Process-wide totals + interval bookkeeping for the sampler.
        self._g_cum: dict[str, int] = {}
        self._g_saved = 0
        self._g_rows = 0
        self._n_finished = 0
        self._last_sample: dict[str, Any] = {"rows": 0, "saved": 0,
                                             "work": {}}
        self._sample_seq = 0
        # Windowed alert-rule streaks + fired alerts (all / unconsumed).
        self._floor_streak = 0
        self._waste_streaks: dict[str, int] = {}
        self.alerts: list[dict[str, Any]] = []
        self._pending_alerts: list[dict[str, Any]] = []
        # Active-iteration state.
        self._it: int | None = None
        self._t_begin: float | None = None
        self._rows = 0
        self._acc: dict[str, int] = {}
        self._saved = 0
        self._replica: str | None = None
        self.clock: Callable[[], float] = time.perf_counter

    # -- lifecycle ----------------------------------------------------

    def active(self) -> bool:
        return self._t_begin is not None

    def begin_iteration(self, it: int, t: float, *,
                        clock: Callable[[], float] | None = None,
                        replica: str | None = None) -> None:
        if self._t_begin is not None:
            # A crashed iteration never reached finish — close it so
            # the ring stays a partition per record, not across them.
            self.finish_iteration(t, aborted=True)
        self._it = int(it)
        self._t_begin = float(t)
        self._rows = 0
        self._acc = {}
        self._saved = 0
        # Normalized to str: an integer replica id 0 must stay a
        # distinct lane, not collapse into the unlabeled "" key.
        self._replica = str(replica) if replica is not None else None
        if clock is not None:
            self.clock = clock

    def dispatch(self, rows: int) -> None:
        """Record ``rows`` device token-rows launched. Deliberately
        SEPARATE from :meth:`add`: the partition invariant cross-checks
        the two, so a site that miscounts its split gets caught by
        :func:`check_partition` instead of silently summing true."""
        if self._t_begin is None:
            return
        self._rows += int(rows)

    def add(self, category: str, rows: int) -> None:
        """Attribute ``rows`` of the dispatched work to one category."""
        if self._t_begin is None:
            return
        if category not in CATEGORIES:
            raise ValueError(
                f"unknown work category {category!r}: the goodput "
                f"taxonomy is {CATEGORIES} (docs/observability.md "
                "\"Goodput & waste attribution\") — a new waste class "
                "must be added there, not invented at the call site")
        n = int(rows)
        if n:
            self._acc[category] = self._acc.get(category, 0) + n

    def credit_saved(self, rows: int) -> None:
        """Avoided-work credit (prefix hits): rows that were NEVER
        dispatched — outside the partition, reported alongside it."""
        if self._t_begin is None:
            return
        self._saved += int(rows)

    def finish_iteration(self, t: float, **extra: Any) -> dict[str, Any]:
        """Close the window; returns (and stores) the work record."""
        if self._t_begin is None:
            return {}
        work = {c: self._acc[c] for c in CATEGORIES if c in self._acc}
        rows = self._rows
        useful = work.get("useful", 0)
        frac = round(useful / rows, 6) if rows else 1.0
        rkey = self._replica if self._replica is not None else ""
        cum = self._cum.setdefault(rkey, {})
        for c, n in work.items():
            cum[c] = cum.get(c, 0) + n
            self._g_cum[c] = self._g_cum.get(c, 0) + n
        cum["rows"] = cum.get("rows", 0) + rows
        cum["prefill_saved"] = cum.get("prefill_saved", 0) + self._saved
        self._g_rows += rows
        self._g_saved += self._saved
        cum_rows = cum["rows"]
        frac_cum = (round(cum.get("useful", 0) / cum_rows, 6)
                    if cum_rows else 1.0)
        rec: dict[str, Any] = {
            "it": self._it,
            "t0": round(self._t_begin, 6),
            "rows": rows,
            "work": work,
            "goodput_frac": frac,
            "prefill_saved": self._saved,
            "rows_cum": cum_rows,
            "goodput_frac_cum": frac_cum,
        }
        if self._replica is not None:
            rec["replica"] = self._replica
        if extra:
            rec.update(extra)
        self._records.append(rec)
        self._it = None
        self._t_begin = None
        self._rows = 0
        self._acc = {}
        self._saved = 0
        self._n_finished += 1
        if self._n_finished % self.interval == 0:
            self._close_sample(t)
        return rec

    # -- interval time-series + windowed alert rules ------------------

    def _close_sample(self, t: float) -> None:
        last = self._last_sample
        d_rows = self._g_rows - last["rows"]
        d_work = {c: self._g_cum.get(c, 0) - last["work"].get(c, 0)
                  for c in CATEGORIES
                  if self._g_cum.get(c, 0) - last["work"].get(c, 0)}
        d_saved = self._g_saved - last["saved"]
        frac = (round(d_work.get("useful", 0) / d_rows, 6)
                if d_rows else 1.0)
        sample = {
            "n": self._sample_seq,
            "t": round(float(t), 6),
            "iters": self.interval,
            "rows": d_rows,
            "work": d_work,
            "goodput_frac": frac,
            "prefill_saved": d_saved,
        }
        self._sample_seq += 1
        self._samples.append(sample)
        self._last_sample = {"rows": self._g_rows, "saved": self._g_saved,
                             "work": dict(self._g_cum)}
        self._evaluate_rules(sample)

    def _fire(self, rule: str, reason: str, sample: dict) -> None:
        alert = {"rule": rule, "reason": reason, "sample": sample["n"],
                 "window": self.window}
        self.alerts.append(alert)
        self._pending_alerts.append(alert)

    def _evaluate_rules(self, sample: dict[str, Any]) -> None:
        # Idle tiers (rows == 0) breach nothing: goodput is vacuously
        # 1.0 and every waste fraction 0 — the streak resets below.
        rows = sample["rows"]
        if self.goodput_floor is not None:
            if rows and sample["goodput_frac"] < self.goodput_floor:
                self._floor_streak += 1
            else:
                self._floor_streak = 0
            if self._floor_streak >= self.window:
                self._fire(
                    "goodput_floor",
                    f"goodput_frac {sample['goodput_frac']:.4f} below "
                    f"floor {self.goodput_floor:.4f} for "
                    f"{self._floor_streak} consecutive intervals "
                    f"(interval={self.interval} iters, sample "
                    f"{sample['n']})", sample)
                self._floor_streak = 0
        if self.waste_ceiling is not None:
            for cat in WASTE_CATEGORIES:
                w_frac = (sample["work"].get(cat, 0) / rows) if rows else 0.0
                if rows and w_frac > self.waste_ceiling:
                    streak = self._waste_streaks.get(cat, 0) + 1
                else:
                    streak = 0
                self._waste_streaks[cat] = streak
                if streak >= self.window:
                    self._fire(
                        f"waste_spike:{cat}",
                        f"waste category '{cat}' at {w_frac:.4f} of "
                        f"dispatched rows (> {self.waste_ceiling:.4f}) "
                        f"for {streak} consecutive intervals (sample "
                        f"{sample['n']})", sample)
                    self._waste_streaks[cat] = 0

    def consume_alerts(self) -> list[dict[str, Any]]:
        """Drain the unconsumed alert queue (the serving loop dumps each
        through the flight recorder's ``goodput_regression`` kind)."""
        out, self._pending_alerts = self._pending_alerts, []
        return out

    # -- queries ------------------------------------------------------

    def records(self) -> list[dict[str, Any]]:
        return list(self._records)

    def has_records(self) -> bool:
        return bool(self._records)

    def cumulative(self, replica: str | None = None) -> dict[str, int]:
        """Per-replica cumulative totals: category rows plus ``rows``
        and ``prefill_saved`` keys (empty dict before any record)."""
        return dict(self._cum.get(str(replica) if replica is not None
                                  else "", {}))

    def cumulative_all(self) -> dict[str, int]:
        """Process-wide cumulative totals across every replica lane:
        category rows plus ``rows`` and ``prefill_saved`` keys."""
        return {**self._g_cum, "rows": self._g_rows,
                "prefill_saved": self._g_saved}

    def goodput_frac(self, replica: str | None = None) -> float:
        """Cumulative useful/dispatched for one replica lane (1.0 while
        nothing has been dispatched — vacuously all-useful)."""
        cum = self._cum.get(str(replica) if replica is not None else "")
        if not cum or not cum.get("rows"):
            return 1.0
        return round(cum.get("useful", 0) / cum["rows"], 6)

    def timeline(self) -> dict[str, Any]:
        """The ``timeline.json`` payload: interval samples + cumulative
        totals + every fired alert."""
        return {
            "schema": TIMELINE_SCHEMA,
            "interval": self.interval,
            "window": self.window,
            "goodput_floor": self.goodput_floor,
            "waste_ceiling": self.waste_ceiling,
            "samples": list(self._samples),
            "cumulative": {k or "": dict(v) for k, v in self._cum.items()},
            "alerts": list(self.alerts),
        }

    # -- span export --------------------------------------------------

    def _ts_us(self, t: float) -> float:
        return self._wall_epoch_us + (t - self._epoch_s) * 1e6

    def to_chrome(self) -> dict[str, Any]:
        """Perfetto counter tracks ("C" events): one ``work_tokens``
        multi-series counter (a stacked area per category) and one
        ``goodput_frac`` counter per record, per replica lane."""
        events: list[dict[str, Any]] = [{
            "name": "process_name", "ph": "M", "pid": GOODPUT_PID,
            "tid": 0, "args": {"name": "serving goodput"},
        }]
        for rec in self._records:
            label = rec.get("replica")
            suffix = f"/{label}" if label is not None else ""
            ts = self._ts_us(rec["t0"])
            events.append({
                "name": f"work_tokens{suffix}", "ph": "C",
                "pid": GOODPUT_PID, "tid": 0, "ts": ts,
                "args": {c: rec["work"].get(c, 0) for c in CATEGORIES},
            })
            events.append({
                "name": f"goodput_frac{suffix}", "ph": "C",
                "pid": GOODPUT_PID, "tid": 0, "ts": ts,
                "args": {"goodput_frac": rec["goodput_frac"]},
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def save(self, path: str | None = None) -> str:
        """Write ``goodput.spans.json`` (fixed stem: the report's
        ``*.spans.json`` glob merges it into the Perfetto view)."""
        if path is None:
            base = self.run_dir or "."
            os.makedirs(base, exist_ok=True)
            path = os.path.join(base, "goodput.spans.json")
        with open(path, "w") as f:
            json.dump(self.to_chrome(), f)
        return path

    def save_timeline(self, path: str | None = None) -> str:
        """Write the interval time-series to ``timeline.json``."""
        if path is None:
            base = self.run_dir or "."
            os.makedirs(base, exist_ok=True)
            path = os.path.join(base, "timeline.json")
        with open(path, "w") as f:
            json.dump(self.timeline(), f)
        return path


# -- module-global switchboard (mirrors obs/stepprof.py) ---------------

_LEDGER: WorkLedger | None = None


def enable(run_dir: str | None = None, capacity: int = 4096,
           **kw: Any) -> WorkLedger:
    global _LEDGER
    _LEDGER = WorkLedger(run_dir=run_dir, capacity=capacity, **kw)
    return _LEDGER


def disable() -> None:
    global _LEDGER
    _LEDGER = None


def get_ledger() -> WorkLedger | None:
    return _LEDGER


def set_ledger(gl: WorkLedger | None) -> WorkLedger | None:
    """Swap the active ledger, returning the previous one (bench rungs
    ledger a replay without clobbering an enclosing run)."""
    global _LEDGER
    prev, _LEDGER = _LEDGER, gl
    return prev


def is_enabled() -> bool:
    return _LEDGER is not None


def check_partition(rec: dict[str, Any]) -> str | None:
    """Verify Σ categories == rows dispatched on one work record;
    returns a problem string or None. Shared by obs.report --check,
    loadgen phase 13, and the partition-invariant tests so the contract
    cannot drift. Exact integer equality — there is no float tolerance
    to hide a miscounted row behind."""
    work = rec.get("work")
    if not isinstance(work, dict):
        return "work record missing 'work' dict"
    rows = rec.get("rows")
    if not isinstance(rows, int) or isinstance(rows, bool) or rows < 0:
        return f"work record 'rows' not a non-negative int: {rows!r}"
    total = 0
    for k, v in work.items():
        if k not in CATEGORIES:
            return (f"unknown work category {k!r} (taxonomy: "
                    f"{CATEGORIES})")
        if not isinstance(v, int) or isinstance(v, bool) or v < 0:
            return f"category {k!r} has non-int/negative value {v!r}"
        total += v
    if total != rows:
        return (f"partition invariant broken: sum(work)={total} != "
                f"rows={rows} (iter {rec.get('it')})")
    frac = rec.get("goodput_frac")
    if frac is not None and not (isinstance(frac, (int, float))
                                 and -1e-9 <= frac <= 1.0 + 1e-9):
        return f"goodput_frac {frac!r} outside [0, 1]"
    saved = rec.get("prefill_saved")
    if saved is not None and (not isinstance(saved, int)
                              or isinstance(saved, bool) or saved < 0):
        return f"prefill_saved not a non-negative int: {saved!r}"
    return None
