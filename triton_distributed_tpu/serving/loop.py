"""ServingEngine — the continuous-batching loop over ``Engine``.

``Engine.serve`` is one fixed batch and one ``gen_len``; this module is
the request-level tier above it (ROADMAP open item #1): a
:class:`ServingEngine` owns ONE shared
:class:`~triton_distributed_tpu.models.kv_cache.PagedModelCache` pool
(``max_batch`` decode slots over ``num_pages`` pages + one reserved
scratch page), a host :class:`~.scheduler.Scheduler`, and per iteration
runs one *mixed* step:

1. **admissions** — WAITING requests take a free slot + their prompt's
   page reservation (backpressure otherwise);
2. **one chunked-prefill slice** for the oldest PREFILLING request
   (``models/dense.dense_prefill_slice`` into a shared linear buffer;
   the final slice's last real row yields the first token and the
   buffer scatters into the slot's pages);
3. **page growth** for the in-flight decode batch, preempting the
   lowest-priority sequence under page pressure (free pages,
   recompute-on-resume);
4. **one paged decode step** over every RUNNING slot through the
   engine's jitted ``dense_decode_step_paged`` path — heterogeneous
   lengths via the shared page table + ``kv_lens``; idle slots point at
   the scratch page with ``kv_lens`` 0, so their (discarded) lane is
   harmless.

SLO coupling (docs/serving.md): each iteration the live watchdog
(obs/slo.py) is evaluated against the serving registry; a violation
streak SHRINKS the scheduler's admission cap, a clean streak regrows it,
and the section is forwarded to the engine's PR-6 demotion ladder
(``Engine._slo_streak_update``) so backend demotion cooperates with
admission control. The ``tdtpu_serve_tokens_per_s`` gauge is published
as a ROLLING-WINDOW rate here (Engine.serve's per-call value is
meaningless under many small interleaved steps).

Greedy decoding end to end, so per-request output is token-identical to
a sequential ``Engine.serve`` call (tests/test_serving.py pins it,
including a preempt/resume).
"""

from __future__ import annotations

import collections
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from triton_distributed_tpu.models import sampling
from triton_distributed_tpu.models.dense import (
    dense_last_logits, dense_prefill_slice,
)
from triton_distributed_tpu.models.engine import Engine
from triton_distributed_tpu.models.kv_cache import (
    PageAllocator, init_kv_cache, init_paged_model_cache, kv_cache_specs,
    paged_cache_specs,
)
from triton_distributed_tpu.obs import goodput as obs_goodput
from triton_distributed_tpu.obs import metrics as obs_metrics
from triton_distributed_tpu.obs import reqtrace as obs_reqtrace
from triton_distributed_tpu.obs import stepprof as obs_stepprof
from triton_distributed_tpu.obs import trace as obs_trace
from triton_distributed_tpu.serving.request import Request, RequestState
from triton_distributed_tpu.serving.scheduler import (
    AdmitResult, Scheduler,
)


class ServingConfigError(ValueError):
    """A serving-tier sizing/backend parameter is invalid — named, at
    construction (the ``_check_decode_step_config`` style)."""


def _env_int(var: str, default: int) -> int:
    try:
        return int(os.environ.get(var, "") or default)
    except ValueError:
        return default


class ServingEngine:
    """Continuous-batching serving tier over an ``Engine`` with
    ``page_size`` set.

    Args:
      engine: an :class:`Engine` constructed with ``page_size`` (the
        paged decode path is the whole point). ``backend="megakernel"``
        (round 9) decodes through the PAGED persistent kernel
        (:class:`~triton_distributed_tpu.megakernel.serving.
        PagedMegakernelDecoder`): one row block per slot over shared
        per-(layer, kv-head) pools whose pages are the allocator's page
        ids one-to-one; requires ``page_size == TILE`` (128) and a
        single-rank dense model — an incompatible configuration raises
        :class:`~triton_distributed_tpu.resilience.
        BackendUnsupportedError` through the PR-6 demotion ladder
        (demote, don't die) rather than hard-rejecting. Mixed chunked
        prefill stays on the dense path either way; only decode goes
        persistent.
      max_batch: decode slots (the in-flight batch width; one jit trace).
      num_pages: shared KV pool size in pages (default: every slot can
        hold its full ``max_pages`` allotment — no pressure; size it
        smaller to oversubscribe). One extra scratch page is always
        added for idle slots' discarded writes.
      kv_hbm_budget: alternative pool sizing — BYTES of HBM for the KV
        pool; ``num_pages`` becomes what the budget buys at the
        engine's ``kv_dtype`` (models/kv_cache.kv_pool_pages_for_budget).
        This is the fp8-KV admission lever: ``kv_dtype=float8_e4m3fn``
        halves the page tile, so the same budget holds 2× (vs bf16; 4×
        vs f32) resident sequences and the scheduler's admission /
        preemption / RequestTooLargeError bounds pick the wider pool up
        with no logic change. The resident pool is published as the
        ``tdtpu_kv_pages_resident`` gauge. Mutually exclusive with
        ``num_pages``.
      prefill_chunk: tokens per prefill slice (must be a multiple of
        ``engine.page_size``; default one page) — the knob trading TTFT
        against decode-batch stall per iteration.
      max_waiting: waiting-queue bound (admission backpressure beyond).
      slo_cfg: explicit :class:`~triton_distributed_tpu.obs.slo.SLOConfig`
        for the admission controller (default: the ``TDTPU_SLO_*`` env,
        evaluated only under an active obs run).
      slo_every: evaluate the SLO watchdog every N iterations (default
        1). The watchdog's stall rule globs the run directory per
        evaluation — on a long-running loop with a large obs run dir,
        raise this to keep the hot loop off the filesystem.
      fleet: a :class:`~triton_distributed_tpu.resilience.fleet.
        HealthLedger` to score rank health against (default: one over
        the engine's mesh). The fleet preflight runs every iteration:
        a confirmed-dead rank EVACUATES the tier to the survivor
        sub-mesh (preempt everything, re-partition, recompute-on-
        resume), suspicion narrows admission, and after
        ``TDTPU_REJOIN_AFTER`` clean iterations with the loss cleared a
        rejoin probe re-expands to the full mesh — docs/resilience.md
        "Fleet degradation". ``TDTPU_DEMOTION_LADDER=0`` opts out: the
        named ``RankLossError`` propagates instead.
      spec_k: speculative-decode draft depth (ISSUE 14, docs/serving.md
        "Speculative decode"). 0 (default) keeps today's one-token
        decode path byte-identical; k > 0 self-drafts up to k candidate
        tokens per RUNNING slot from the request's own prompt+generated
        history (serving/spec.NGramProposer — deterministic, no second
        model) and scores the whole k+1 window in ONE decode launch
        (``models/dense.dense_verify_step_paged`` on the jitted paths;
        the megakernel's windowed draft-and-verify rows on the
        persistent lane). Greedy longest-accepted-prefix verification
        (``models/sampling.accept_longest_prefix``) makes the output
        token-identical to one-token decode; the tokens/s ledger counts
        ACCEPTED tokens only, and rejected drafts roll back both the
        device positions (kv_len truncation) and their page
        reservations (``PageAllocator.free_tail``) — pool occupancy
        returns to the one-token baseline every iteration. A transient
        failure inside a verify step falls the lane back to one-token
        decode with recompute-on-resume parity (never dies).
      prefix_cache: enable the prefix-reuse subsystem (ISSUE 15,
        docs/serving.md "Prefix cache"): a radix index over token-id
        prefixes (serving/prefix.py) is consulted at admission, a warm
        request SHARES the resident pages covering its prompt prefix
        (refcounted — share = +ref, free = −ref, physical free only at
        zero) and prefills only its divergent suffix; a shared page
        that would be written is first copied to a private page
        (copy-on-write — on both the xla paged path and the megakernel
        paged workspace), and cold cached chains evict in
        refcount×recency order under pool pressure, so the scheduler's
        admission budget sees them as available capacity. Warm serve is
        token-identical to cold serve (tests + loadgen dryrun phase 10
        pin it). False (default) keeps every pre-prefix path
        byte-identical.
      kv_host_budget_bytes: host-RAM budget for the second-chance KV
        tier (ISSUE 20, serving/kvtier.py; requires ``prefix_cache``).
        When > 0, a prefix chain the refcount×recency eviction would
        physically free is first swapped to host RAM (at stored pool
        width, checksum-stamped), and a later admission whose prompt
        re-walks the chain streams it back through the disagg
        MigrationStream transport shape instead of re-prefilling —
        zero cold-prefill tokens for the restored positions, byte-exact
        parity with a never-evicted run. Default None reads
        ``TDTPU_KV_HOST_BUDGET_BYTES`` (0 = tier off, every pre-tier
        path byte-identical).
      async_loop: split each iteration into PLAN (pure host: admission,
        radix match, drafts, page ops, table builds) and COMMIT (block
        on the PREVIOUS iteration's decode launch), so iteration i+1's
        host planning overlaps iteration i's device step (ISSUE 20,
        ROADMAP item 3(ii) — the host bubble stepprof measures).
        Token-exact vs the synchronous loop: greedy per-request streams
        are batching-invariant, and the functional (donated-jit) pool
        threading means any host-side page mutation for i+1 is ordered
        after the in-flight launch's reads by XLA data dependence — the
        COW guard + ``note_launch`` discipline stay the hazard set.
        Default None reads ``TDTPU_ASYNC_LOOP`` (0 = synchronous,
        byte-identical to today).
    """

    def __init__(self, engine: Engine, *, max_batch: int = 4,
                 num_pages: int | None = None,
                 kv_hbm_budget: int | None = None,
                 prefill_chunk: int | None = None,
                 max_waiting: int = 64, slo_cfg=None, slo_every: int = 1,
                 fleet=None, clock=time.perf_counter, spec_k: int = 0,
                 prefix_cache: bool = False, metrics_registry=None,
                 replica_id: str | int | None = None,
                 kv_host_budget_bytes: int | None = None,
                 async_loop: bool | None = None):
        if engine.page_size is None:
            raise ServingConfigError(
                "engine has no paged cache: construct Engine(page_size=...) "
                "— the serving tier schedules against the PagedModelCache "
                "pool (argument engine)")
        page = engine.page_size
        chunk = prefill_chunk if prefill_chunk is not None else page
        if chunk < 1 or chunk % page:
            raise ServingConfigError(
                f"prefill_chunk = {chunk} invalid: must be a positive "
                f"multiple of page_size ({page}) so prefill slices scatter "
                "whole pages — argument prefill_chunk")
        if max_batch < 1:
            raise ServingConfigError(
                f"max_batch = {max_batch} invalid: the decode batch needs "
                "at least one slot — argument max_batch")
        self.engine = engine
        self.cfg = engine.cfg
        self.page = page
        self.max_pages = engine.max_pages
        self.max_batch = max_batch
        self.chunk = chunk
        self.clock = clock
        self.slo_cfg = slo_cfg
        # Fleet namespacing (ISSUE 17, docs/fleet.md): a replica tier
        # publishes into its OWN registry (the router merges them back
        # with replica= labels) so N replicas never silently sum gauges
        # like tdtpu_kv_pages_resident; the replica id also stamps the
        # flight recorder's dumps.
        self.metrics_registry = metrics_registry
        self.replica_id = None if replica_id is None else str(replica_id)
        # Prefill buffer: whole chunks covering max_seq (chunk % page == 0
        # keeps it page-aligned for the scatter reshape).
        self.s_buf = -(-engine.max_seq // chunk) * chunk
        # Per-sequence capacity also honors the engine's own max_seq
        # contract: both page and chunk rounding can exceed it, and an
        # admitted request longer than max_seq could never be replayed
        # through the sequential parity oracle (Engine.serve rejects it).
        capacity = min(self.max_pages * page, self.s_buf, engine.max_seq)
        self.kv_dtype = engine.kv_dtype
        if kv_hbm_budget is not None:
            if num_pages is not None:
                raise ServingConfigError(
                    "pass num_pages OR kv_hbm_budget, not both — two "
                    "pool sizes cannot both hold (arguments num_pages / "
                    "kv_hbm_budget)")
            from triton_distributed_tpu.models.kv_cache import (
                kv_pool_pages_for_budget,
            )

            num_pages = kv_pool_pages_for_budget(
                self.cfg, page_size=page, hbm_bytes=kv_hbm_budget,
                kv_dtype=self.kv_dtype,
                num_kv_heads=self.cfg.num_kv_heads // engine.n_total)
        pool_pages = (num_pages if num_pages is not None
                      else max_batch * self.max_pages)
        if pool_pages < 1:
            raise ServingConfigError(
                f"num_pages = {pool_pages} invalid: the shared pool needs "
                "at least one page — argument num_pages")
        self.num_pages = pool_pages
        self.scratch_page = pool_pages        # last pool row, never owned
        # Speculative decode lane (ISSUE 14): resolved BEFORE the
        # megakernel lane builds — the persistent program's candidate
        # window is a compile-time shape.
        if spec_k < 0 or int(spec_k) != spec_k:
            raise ServingConfigError(
                f"spec_k = {spec_k} invalid: the draft depth is a "
                "non-negative integer (0 disables speculative decode) — "
                "argument spec_k")
        self.spec_k = int(spec_k)
        self._spec_fallback = False     # one-token fallback after a fault
        self._drafts: dict[str, list[int]] = {}
        self._last_spec = (0, 0)        # (drafted, accepted drafts)/iter
        if self.spec_k:
            from triton_distributed_tpu.serving.spec import NGramProposer

            self._proposer = NGramProposer(self.spec_k)
        else:
            self._proposer = None
        # Flight recorder (ISSUE 13, obs/flight.py): the last N
        # iterations + trigger chain, dumped on demotion / evacuation /
        # SLO shrink. Created BEFORE the megakernel lane so a
        # construction-time demotion is already dump-able.
        from triton_distributed_tpu.obs import flight as obs_flight

        self.flight = obs_flight.FlightRecorder(
            _env_int("TDTPU_FLIGHT_CAPACITY", 128),
            replica_id=self.replica_id)
        self._flight_rung = engine._rung
        # Megakernel serving lane (round 9): decode through the PAGED
        # persistent kernel when the configuration supports it; a
        # workspace/page-shape mismatch raises the TRANSIENT
        # BackendUnsupportedError and DEMOTES through the engine's PR-6
        # ladder instead of killing construction.
        self._mk = None
        self._mk_ws = None
        if engine.backend == "megakernel":
            from triton_distributed_tpu.resilience import (
                BackendUnsupportedError,
            )

            try:
                self._mk = self._build_megakernel_lane(pool_pages)
            except BackendUnsupportedError as exc:
                self._demote_backend(str(exc))
        cache = init_paged_model_cache(
            self.cfg, max_batch, page_size=page, max_pages=self.max_pages,
            num_pages=pool_pages + 1, kv_dtype=self.kv_dtype)
        self._cache = self._put_sharded(
            cache, paged_cache_specs(engine.shard_axes))
        self._pf_cache = self._put_sharded(
            init_kv_cache(self.cfg, 1, self.s_buf),
            kv_cache_specs(engine.shard_axes))
        # With the persistent backend active the pool carries the
        # megakernel workspace's reserved scratch page as a REAL,
        # reserved pool row — the admission/budget math sees it (and can
        # never hand it out or oversubscribe against it).
        if self._mk is not None:
            allocator = PageAllocator(pool_pages + 1, self.max_pages,
                                      reserved=(self.scratch_page,))
        else:
            allocator = PageAllocator(pool_pages, self.max_pages)
        # Refcount/COW lifetime sanitizer (analysis/page_audit.py):
        # TDTPU_PAGE_AUDIT=1 shadows every allocator event and audits
        # each iteration's launches + holdings, feeding the flight
        # recorder's page_events ride-along for offline replay.
        self.page_audit = None
        self._last_page_events: list[dict] = []
        self._last_page_live: dict = {}
        if _env_int("TDTPU_PAGE_AUDIT", 0):
            from triton_distributed_tpu.analysis.page_audit import (
                PageAuditor,
            )

            self.page_audit = PageAuditor(page)
            allocator.on_event = self.page_audit.record
        # Prefix-reuse subsystem (ISSUE 15, docs/serving.md "Prefix
        # cache"): the radix index + cache pins register themselves as
        # the allocator's reclaim hooks, so admission and page growth
        # treat cold cached chains as evictable capacity.
        self.prefix = None
        self.kvtier = None
        self._kvtier_chaos = None       # chaos hook for restore streams
        if prefix_cache:
            from triton_distributed_tpu.serving.prefix import PrefixCache

            self.prefix = PrefixCache(allocator, page)
            # Host-RAM KV tier (ISSUE 20, serving/kvtier.py): a
            # second-chance store behind the radix cache's eviction —
            # chains the refcount×recency reclaim would physically free
            # are swapped to pinned host buffers at stored width and
            # streamed back on a later radix hit. Off unless a budget is
            # configured, keeping every pre-tier path byte-identical.
            from triton_distributed_tpu.serving.kvtier import (
                HostKVTier, host_kv_budget_bytes,
            )

            budget = (host_kv_budget_bytes() if kv_host_budget_bytes is None
                      else int(kv_host_budget_bytes))
            tier = HostKVTier(budget, page_size=page,
                              fetch=self._kvtier_fetch)
            if tier.enabled:
                self.kvtier = tier
                self.prefix.attach_host_tier(tier)
        self.sched = Scheduler(
            num_slots=max_batch,
            allocator=allocator,
            page_size=page, capacity_tokens=capacity,
            max_waiting=max_waiting, on_event=self._req_event,
            prefix=self.prefix)
        self._jits: dict = {}
        self._jits_backend = engine.backend
        # Async double-buffered loop (ISSUE 20): when on, each decode
        # dispatch is stashed instead of awaited, and the NEXT
        # iteration's commit point (after its host planning) blocks on
        # it — ``_pending`` is the one in-flight launch.
        self.async_loop = (bool(_env_int("TDTPU_ASYNC_LOOP", 0))
                           if async_loop is None else bool(async_loop))
        self._pending: dict | None = None
        self.slo_every = max(1, int(slo_every))
        self._iter = 0
        self._t0: float | None = None
        self.total_tokens = 0
        self._rate_events: collections.deque = collections.deque()
        self._rate_window_s = float(
            os.environ.get("TDTPU_SERVE_RATE_WINDOW_S", "") or 5.0)
        self._viol_streak = 0
        self._clean_streak = 0
        self._finished: list[Request] = []
        # Fleet-health state (ISSUE 11, docs/resilience.md): the ledger
        # scores rank suspicion from the evidence streams; the full-mesh
        # context is kept for the rejoin probe. The strong ref keeps the
        # weakly-registered ledger subscribed for this tier's lifetime.
        from triton_distributed_tpu.resilience import fleet as fleet_mod

        self.fleet = (fleet if fleet is not None
                      else fleet_mod.HealthLedger.for_context(engine.ctx))
        self._full_ctx = engine.ctx
        self._full_rank_ids = [
            int(d.id) for d in
            np.asarray(engine.ctx.mesh.devices).ravel()]
        self.evacuated = False
        self.evacuation_preemptions = 0   # evacuation/rejoin recomputes
        self.fleet_log: list[dict] = []   # evacuation / rejoin records
        self._clean_since_evac = 0
        self._rejoin_after = _env_int("TDTPU_REJOIN_AFTER", 8)

    # -- megakernel serving lane (round 9) ----------------------------------
    def _build_megakernel_lane(self, pool_pages: int):
        """The paged persistent-kernel decoder, or a named
        BackendUnsupportedError describing exactly which dimension the
        lane cannot serve (page shape, TP degree, model geometry)."""
        from triton_distributed_tpu.megakernel.serving import (
            PagedMegakernelDecoder, validate_megakernel_cfg,
        )
        from triton_distributed_tpu.megakernel.tasks import TILE
        from triton_distributed_tpu.resilience import (
            BackendUnsupportedError,
        )

        eng = self.engine
        if eng.n_total > 1:
            raise BackendUnsupportedError(
                f"megakernel serving lane is single-rank for now (TP "
                f"mesh of {eng.n_total}) — demoting to the jitted paths")
        if self.page != TILE:
            raise BackendUnsupportedError(
                f"megakernel paged workspace needs page_size == TILE "
                f"({TILE}); engine has page_size={self.page} — pool "
                "pages must line up one-to-one with workspace KV tiles")
        try:
            validate_megakernel_cfg(self.cfg, self.max_pages * TILE)
        except ValueError as exc:
            raise BackendUnsupportedError(
                f"megakernel cannot serve this model: {exc}") from exc
        wdt = (jnp.float32 if jnp.dtype(self.cfg.dtype) == jnp.float32
               else jnp.bfloat16)
        try:
            return PagedMegakernelDecoder(
                self.cfg, eng.params, num_slots=self.max_batch,
                num_pages=pool_pages, max_pages=self.max_pages, dtype=wdt,
                kv_dtype=self.kv_dtype,
                # The candidate window is a compile-time program shape,
                # resolved from the spec state at BUILD time (ctor, a
                # backend re-promotion probe, or a post-fault rebuild —
                # every path goes through here). _decode's dispatch
                # consults the LANE's compiled window, not the spec
                # flag, so a lane built windowless can never be handed
                # a wins>1 step.
                spec_window=(self.spec_k + 1 if self._spec_enabled()
                             else 1))
        except ValueError as exc:
            # e.g. an unservable kv_dtype: named + transient, so the
            # tier demotes to the dense paged path (which serves any
            # pool dtype) instead of dying (round-12 surface update —
            # the fp8-KV combo itself is SUPPORTED, not excluded).
            raise BackendUnsupportedError(
                f"megakernel paged lane cannot serve this "
                f"configuration: {exc}") from exc

    def _demote_backend(self, reason: str) -> None:
        """Fall one rung down the engine's PR-6 ladder (megakernel →
        overlap → xla); with the ladder disabled or exhausted the named
        error propagates — demotion must never silently mask a config
        the operator pinned."""
        from triton_distributed_tpu.resilience import (
            BackendUnsupportedError,
        )

        eng = self.engine
        if eng._rung + 1 < len(eng._ladder):
            eng._set_rung(eng._rung + 1, reason)
            self._flight_dump("backend_demotion", reason)
        else:
            raise BackendUnsupportedError(reason)

    def _put_sharded(self, tree, specs, mesh=None):
        """``device_put`` with per-leaf :class:`NamedSharding` resolved
        against ``mesh`` (default: the engine's CURRENT mesh) — the one
        home for the spec tree-map, so every pool/buffer build (init,
        repartition rebuild, prefill-buffer reset, disagg role meshes)
        shards identically."""
        mesh = self.engine.ctx.mesh if mesh is None else mesh
        return jax.device_put(
            tree, jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                               is_leaf=lambda x: isinstance(x, P)))

    # -- jitted pieces ------------------------------------------------------
    def _first_call(self, key, fn, what: str, eng=None):
        """The engine's first-call compile routing, against THIS tier's
        jit cache: the first invocation runs under a ``jit_compile`` span
        and flags the wall time as compile-dominated, then the raw
        executable replaces the wrapper in ``self._jits``. ``eng``: the
        engine whose compile flag the call stamps (the disagg tier's
        prefill-lane jits pass their prefill engine)."""
        eng = eng if eng is not None else self.engine

        def first(*args):
            eng._jit_compiled_last_call = True
            with obs_trace.span("jit_compile", what=what, key=str(key)):
                out = fn(*args)
            self._jits[key] = fn
            return out

        return first

    def _slice_jit(self):
        key = "pf_slice"
        if key not in self._jits:
            eng = self.engine
            mode = eng._decode_mode()
            tiles = eng._flash_tiles(self.chunk, self.s_buf)
            extra = ({"inter_axis": eng.inter_axis, "n_inter": eng.n_inter}
                     if eng.hierarchical else {})

            def step(params, ids, cache, start):
                return dense_prefill_slice(
                    params, self.cfg, ids, cache, start, axis=eng.axis,
                    num_ranks=eng.n, mode=mode, flash_tiles=tiles, **extra)

            fn = eng._shard(step, in_specs=(eng.param_specs, P(),
                                            kv_cache_specs(eng.shard_axes),
                                            P()),
                            out_specs=(P(), kv_cache_specs(eng.shard_axes)))
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(2,)), "serving_prefill")
        return self._jits[key]

    def _logits_jit(self):
        key = "pf_logits"
        if key not in self._jits:
            eng = self.engine
            extra = ({"inter_axis": eng.inter_axis, "n_inter": eng.n_inter}
                     if eng.hierarchical else {})

            def step(params, x_last):
                logits = dense_last_logits(params, self.cfg, x_last,
                                           axis=eng.axis, num_ranks=eng.n,
                                           **extra)
                return sampling.greedy(logits)

            fn = eng._shard(step, in_specs=(eng.param_specs, P()),
                            out_specs=P())
            self._jits[key] = self._first_call(
                key, jax.jit(fn), "serving_logits")
        return self._jits[key]

    def _scatter_jit(self, n_pages: int, skip: int = 0):
        """``skip``: leading buffer pages NOT written (a warm
        admission's shared prefix pages — writing a shared page, even
        with identical bytes, is what the COW discipline exists to
        forbid; the suffix scatter starts at the first private page)."""
        key = ("scatter", n_pages, skip)
        if key not in self._jits:
            eng = self.engine
            L, page, s_buf = self.cfg.num_layers, self.page, self.s_buf

            def step(cache, k_lin, v_lin, pages):
                # The chunked-prefill scatter is a pool WRITE: narrow
                # kv_dtype pools quantize here through the saturating
                # cast (fp8 KV — plain astype would NaN hot values).
                from triton_distributed_tpu.models.fp8 import saturate_cast

                def to_pages(x):  # (L, 1, S_buf, hkv, d) local shard
                    x = x[:, 0].reshape(L, s_buf // page, page,
                                        *x.shape[3:])
                    return x[:, skip:skip + n_pages]

                kp = cache.k_pools.at[:, pages].set(
                    saturate_cast(to_pages(k_lin), cache.k_pools.dtype))
                vp = cache.v_pools.at[:, pages].set(
                    saturate_cast(to_pages(v_lin), cache.v_pools.dtype))
                return cache._replace(k_pools=kp, v_pools=vp)

            kv_spec = kv_cache_specs(eng.shard_axes)
            fn = eng._shard(
                step,
                in_specs=(paged_cache_specs(eng.shard_axes),
                          kv_spec.k, kv_spec.v, P()),
                out_specs=paged_cache_specs(eng.shard_axes))
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(0,)), "serving_scatter")
        return self._jits[key]

    # -- prefix-reuse lane (ISSUE 15, docs/serving.md "Prefix cache") --------
    def _gather_jit(self, n_pages: int):
        """Inverse of the scatter: pull a warm request's shared prefix
        pages out of the pool into the linear prefill buffer, so the
        divergent-suffix slices attend the resident KV. Narrow (fp8)
        pools dequantize here; for same-dtype pools the bytes are
        exactly what the original prefill scattered, so the suffix math
        is bit-identical to a cold prefill of the same tokens."""
        key = ("gather", n_pages)
        if key not in self._jits:
            eng = self.engine
            L, page = self.cfg.num_layers, self.page

            def step(pf, cache, pages):
                def from_pages(pool, dst):   # (L, P, page, hkv, d) shard
                    x = pool[:, pages].astype(dst.dtype)
                    x = x.reshape(L, 1, n_pages * page, *x.shape[3:])
                    return dst.at[:, :, :n_pages * page].set(x)

                return pf._replace(k=from_pages(cache.k_pools, pf.k),
                                   v=from_pages(cache.v_pools, pf.v))

            kv_spec = kv_cache_specs(eng.shard_axes)
            fn = eng._shard(
                step,
                in_specs=(kv_spec, paged_cache_specs(eng.shard_axes),
                          P()),
                out_specs=kv_spec)
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(0,)), "prefix_gather")
        return self._jits[key]

    # -- host-RAM KV tier (ISSUE 20, serving/kvtier.py) ----------------------
    def _kvtier_fetch(self, page: int):
        """One pool page's (k, v) bytes as host arrays at STORED width —
        the tier's swap-out reader. Cache-only pages (refcount 1, held
        by the radix index alone) are never an in-flight launch's append
        target, so the device→host copy reads settled bytes; fp8 pools
        swap at fp8 width (the gather dequantizes on restore exactly as
        it would have from the device page)."""
        return (np.asarray(self._cache.k_pools[:, page]),
                np.asarray(self._cache.v_pools[:, page]))

    def _kvtier_fill_jit(self):
        """One restored host chunk → the prefill buffer at its token
        offset. The buffer (not the pool) is the restore target: the
        completion scatter then lands restored positions in the
        request's OWN fresh pages through the same saturating-cast
        write path as recomputed tokens — no second pool-write path to
        keep megakernel workspaces or fp8 quantization in sync with."""
        key = "kvtier_fill"
        if key not in self._jits:
            eng = self.engine

            def step(pf, k, v, start):
                k = k.astype(pf.k.dtype)
                v = v.astype(pf.v.dtype)
                return pf._replace(
                    k=jax.lax.dynamic_update_slice(
                        pf.k, k, (0, 0, start, 0, 0)),
                    v=jax.lax.dynamic_update_slice(
                        pf.v, v, (0, 0, start, 0, 0)))

            kv_spec = kv_cache_specs(eng.shard_axes)
            fn = eng._shard(
                step, in_specs=(kv_spec, kv_spec.k, kv_spec.v, P()),
                out_specs=kv_spec)
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(0,)), "kvtier_fill")
        return self._jits[key]

    def _kvtier_restore(self, req: Request, n_restore: int) -> None:
        """Stream a warm admission's host-resident chain back into the
        prefill buffer through the disagg double-buffer transport shape
        (MigrationStream pointed at host memory): H2D for chunk i+1
        overlaps the buffer fill for chunk i, every landing re-verified
        against the checksum stamped at swap-out. Any failure raises the
        named TRANSIENT migration-error family — the prefill-fault path
        preempts for a cold recompute, and the failed chain is dropped
        from the tier FIRST so the resume cannot walk back into the same
        failure. Tokens are never wrong, only slower."""
        from triton_distributed_tpu.disagg.migrate import MigrationStream

        keys = list(req._kvtier_pending)
        req._kvtier_pending = []
        tier = self.kvtier
        if tier is None or n_restore <= 0 or not keys:
            return
        n_restore = min(n_restore, len(keys))
        eng = self.engine
        device_hit = req.prefix_hit_tokens - req.restored_tokens
        first_page = device_hit // self.page
        kv_spec = kv_cache_specs(eng.shard_axes)
        fill = self._kvtier_fill_jit()
        t0 = self.clock()
        try:
            blocks = []
            for i in range(n_restore):
                k, v = tier.chunk(keys[i], chunk_idx=i)
                # (L, 1, page, hkv, d): the prefill buffer's own layout,
                # so the staged device block shards like a slice write.
                blocks.append((k[:, None], v[:, None]))
            dst = [first_page + i for i in range(n_restore)]

            def put(kv):
                return self._put_sharded(kv, (kv_spec.k, kv_spec.v))

            def land(idx, kv, pages):
                self._pf_set(req, fill(
                    self._pf_get(req), kv[0], kv[1],
                    jnp.int32(int(pages[0]) * self.page)))

            stream = MigrationStream(
                req.req_id, blocks, [[d] for d in dst], put,
                clock=self.clock, chaos_hook=self._kvtier_chaos)
            with obs_trace.span("serving.kvtier_restore",
                                req=req.req_id, pages=n_restore):
                while not stream.advance(land):
                    pass
        except Exception as exc:
            from triton_distributed_tpu import resilience

            if resilience.is_transient(exc):
                tier.restore_failures += 1
                tier.drop_chain(keys)
            raise
        restored = n_restore * self.page
        pool_pages = self.sched.allocator.pages(req.req_id)
        for d in dst:
            if d < len(pool_pages):
                self.sched.allocator.note_swap("swap_in", pool_pages[d])
        tier.note_restored(n_restore)
        req.restored_tokens_total += restored
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            # Host→device transport rows are pure overhead (ISSUE 19);
            # the restored POSITIONS themselves are the gather restart's
            # prefill_saved credit, same as a device-resident hit.
            gl.dispatch(restored)
            gl.add("overhead", restored)
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.span(req.req_id, "kvtier_restore", t0, self.clock(),
                    pages=n_restore, tokens=restored)
        if self._observing():
            self._reg().histogram(
                obs_metrics.KV_HOST_RESTORE_MS,
                "one warm admission's whole host-chain restore (host "
                "RAM -> prefill buffer), ms",
                buckets=obs_metrics.MIGRATE_BUCKETS_MS,
            ).observe((self.clock() - t0) * 1e3)

    def _copy_page_jit(self):
        """One pool-page copy — the copy half of copy-on-write: the
        new private page receives the shared page's bytes before the
        divergent append writes it (src/dst are traced scalars, so one
        trace serves every COW)."""
        key = "cow_copy"
        if key not in self._jits:
            eng = self.engine

            def step(cache, src, dst):
                kp = cache.k_pools.at[:, dst].set(cache.k_pools[:, src])
                vp = cache.v_pools.at[:, dst].set(cache.v_pools[:, src])
                return cache._replace(k_pools=kp, v_pools=vp)

            fn = eng._shard(
                step,
                in_specs=(paged_cache_specs(eng.shard_axes), P(), P()),
                out_specs=paged_cache_specs(eng.shard_axes))
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(0,)), "prefix_cow_copy")
        return self._jits[key]

    def _cow_shared_appends(
            self, ready: list[Request],
    ) -> tuple[list[Request], list[Request]]:
        """Copy-on-write guard before every decode/verify launch: any
        append-target page still carrying OTHER readers (refcount > 1 —
        another sharer or the prefix cache) is first replaced by a
        private copy (allocator row rewrite + one page copy, mirrored
        into the megakernel workspace when that lane is live). A
        request whose COW cannot get a page (pool dry even after
        reclaim) preempts itself — recompute-on-resume is always
        state-correct. Returns ``(still_ready, pool_preempted)`` so the
        iteration accounting (SERVE_PREEMPTIONS, flight record, summary)
        sees the guard's evictions like any other page-pressure
        preemption. A transient fault in a copy launch on the
        megakernel lane demotes (don't die) exactly like a fault in the
        decode step itself — those preemptions are counted by the
        demote path, not returned here."""
        if self.prefix is None:
            return ready, []
        alloc = self.sched.allocator
        out: list[Request] = []
        evicted: list[Request] = []
        spec = self._spec_enabled()
        for req in ready:
            pages = alloc.pages(req.req_id)
            win = (1 + len(self._drafts.get(req.req_id, []))
                   if spec else 1)
            ti = req.kv_len // self.page
            last_ti = (req.kv_len + win - 1) // self.page
            ok = True
            for idx in range(ti, min(last_ti + 1, len(pages))):
                old = pages[idx]
                if alloc.ref_count(old) <= 1:
                    continue
                new = alloc.cow_page(req.req_id, old)
                if new is None:
                    self.sched._preempt(req)
                    evicted.append(req)
                    ok = False
                    break
                try:
                    self._cache = self._copy_page_jit()(
                        self._cache, jnp.int32(old), jnp.int32(new))
                    if self._mk is not None and self._mk_ws is not None:
                        self._mk_ws = self._mk.copy_page(self._mk_ws,
                                                         old, new)
                except Exception as exc:
                    from triton_distributed_tpu import resilience

                    if self._mk is None or not resilience.is_transient(
                            exc):
                        # Dense lane: the donated pool state is the
                        # step()-level fault machinery's to judge (fleet
                        # retry/evacuation), same as a fault in the
                        # dense decode launch itself.
                        raise
                    self._mk_decode_failed(
                        [r for r in ready if r not in evicted], exc)
                    return [], evicted
                gl = obs_goodput.get_ledger()
                if gl is not None and gl.active():
                    # One COW copy moves a page of resident KV to a
                    # private page — pure overhead rows (ISSUE 19).
                    gl.dispatch(self.page)
                    gl.add("overhead", self.page)
                with obs_trace.span("serving.prefix_cow", req=req.req_id,
                                    src=old, dst=new):
                    pass
            if ok:
                out.append(req)
        return out, evicted

    # -- speculative decode lane (ISSUE 14) ----------------------------------
    def _spec_enabled(self) -> bool:
        return self.spec_k > 0 and not self._spec_fallback

    def _plan_drafts(self) -> dict[str, int]:
        """Draft up to ``spec_k`` candidates per RUNNING slot from its
        own history (host-side, deterministic) and return the per-request
        token reservation (1 + draft length) the scheduler's page growth
        covers this iteration. Drafts are clamped so the window can
        never exceed the request's remaining budget (k+1 accepted tokens
        max) — which also bounds the transient page reservation by the
        request's admitted ``page_budget``."""
        extra: dict[str, int] = {}
        self._drafts.clear()
        w = self._proposer.window_tokens
        for req in self.sched.running():
            remaining = req.max_new_tokens - len(req.tokens)
            k_max = min(self.spec_k, remaining - 1)
            if k_max > 0:
                # Only the proposer's trailing window — req.text would
                # copy the whole prompt+generated per slot per iteration.
                tail = req.tokens[-w:]
                if len(tail) < w:
                    tail = req.prompt[-(w - len(tail)):] + tail
                draft = self._proposer.propose(tail, k_max)
            else:
                draft = []
            self._drafts[req.req_id] = draft
            extra[req.req_id] = 1 + len(draft)
        return extra

    def _verify_jit(self):
        """The jitted k+1-position verify step (the xla/dense lane's
        draft-and-verify launch): one trace per serving tier — the
        window is a fixed shape, slots with shorter (or no) drafts ride
        padding columns whose appends land past the truncation point."""
        key = ("verify", self.spec_k + 1)
        if key not in self._jits:
            from triton_distributed_tpu.models.dense import (
                dense_verify_step_paged,
            )

            eng = self.engine
            mode = eng._decode_mode()

            def step(params, tokens, cache):
                logits, cache = dense_verify_step_paged(
                    params, self.cfg, tokens, cache, axis=eng.axis,
                    num_ranks=eng.n, mode=mode)
                b, w, v = logits.shape
                ver = sampling.greedy(logits.reshape(b * w, v))
                return ver.reshape(b, w), cache

            fn = eng._shard(
                step,
                in_specs=(eng.param_specs, P(),
                          paged_cache_specs(eng.shard_axes)),
                out_specs=(P(), paged_cache_specs(eng.shard_axes)))
            self._jits[key] = self._first_call(
                key, jax.jit(fn, donate_argnums=(2,)), "serving_verify")
        return self._jits[key]

    def _spec_disable(self, reason: str) -> None:
        """Transient failure INSIDE a verify step: fall the lane back to
        one-token decode (chaos contract: fall back, never die). The
        paged cache was donated into the failed jit and the rebuild
        wipes the prefill buffer too, so EVERY in-flight request
        preempts (the ``_evacuate`` discipline — preempting only the
        decode batch would leave a mid-chunked-prefill request's
        ``prefill_pos`` pointing into a zeroed buffer) and recomputes on
        resume — token parity holds because the one-token path replays
        the same greedy stream."""
        import warnings

        self._spec_fallback = True
        self._drafts.clear()
        self._preempt_all()
        self._rebuild_device_state()
        self.flight.note("spec_fallback", reason, self._iter)
        if self._observing():
            self._reg().counter(
                "tdtpu_spec_fallbacks_total",
                "speculative lane disabled after a transient verify "
                "failure (one-token decode from here)").inc()
        warnings.warn(
            f"speculative decode fell back to one-token decode: {reason}",
            RuntimeWarning, stacklevel=3)

    # -- submission ----------------------------------------------------------
    def submit(self, prompt, max_new_tokens: int, *, priority: int = 0,
               req_id: str | None = None
               ) -> tuple[Request, AdmitResult]:
        """Queue one request. Returns (request, admission verdict);
        on :data:`AdmitResult.QUEUE_FULL` the request is NOT queued —
        the caller sheds or retries (open-loop generators retry)."""
        kw = {"req_id": req_id} if req_id is not None else {}
        req = Request(prompt=[int(t) for t in np.asarray(prompt).ravel()],
                      max_new_tokens=int(max_new_tokens),
                      priority=priority, **kw)
        res = self.sched.admit(req, self.clock())
        if res is AdmitResult.ADMITTED:
            rt = obs_reqtrace.get_tracer()
            if rt is not None:
                rt.arrival(req.req_id,
                           req.t_arrival if req.t_arrival is not None
                           else self.clock())
        if res is AdmitResult.QUEUE_FULL and self._observing():
            self._reg().counter(
                obs_metrics.SERVE_REJECTS,
                "requests refused at admission (queue/pool backpressure)"
            ).inc()
        return req, res

    # -- the mixed iteration --------------------------------------------------
    def step(self) -> dict:
        """One scheduler iteration (fleet preflight → admit → prefill
        slice → page growth / preemption → decode). Returns a host-side
        summary dict; ``summary["fleet"]`` names a fleet action when one
        happened this iteration: ``"evacuated"`` / ``"rejoined"``
        (geometry transitions) or ``"retried"`` (a rank-attributable
        failure absorbed below the evacuation threshold — geometry
        kept, in-flight work recomputed)."""
        now = self.clock()
        if self._t0 is None:
            self._t0 = now
        sp = obs_stepprof.get_profiler()
        if sp is not None:
            # Step-phase timeline (ISSUE 18): the window opens on the
            # loop's injected clock so records are byte-deterministic
            # under a fake clock; every phase below telescopes into it.
            sp.begin_iteration(self._iter, now, clock=self.clock,
                               replica=self.replica_id)
        gl = obs_goodput.get_ledger()
        if gl is not None:
            # Work ledger (ISSUE 19): every device token-row the
            # iteration dispatches below attributes into exactly one
            # goodput category; the partition closes in the finally.
            gl.begin_iteration(self._iter, now, clock=self.clock,
                               replica=self.replica_id)
        try:
            with obs_stepprof.phase("preflight"):
                fleet_event = self._fleet_preflight()
                self._sync_backend()
            try:
                summary = self._step_work(now)
            except Exception as exc:
                handled = self._fleet_on_failure(exc)
                if not handled:
                    raise
                self._iter += 1
                fleet_event = fleet_event or handled
                summary = {"iter": self._iter, "admitted": [],
                           "prefilled": None, "preempted": [], "decoded": 0,
                           "waiting": len(self.sched.waiting),
                           "active": self.sched.active_count,
                           "free_pages": self.sched.allocator.free_count,
                           "admit_cap": self.sched.admit_cap}
            if fleet_event:
                summary["fleet"] = fleet_event
            return summary
        finally:
            # Goodput close runs FIRST: _step_profile_close clears
            # _last_flight_rec, and both closes patch that same dict.
            if gl is not None and gl.active():
                grec = gl.finish_iteration(self.clock())
                self._goodput_close(grec, gl)
            if sp is not None and sp.active():
                rec = sp.finish_iteration(self.clock())
                self._step_profile_close(rec)

    def _sync_backend(self) -> None:
        # The demotion ladder (driven from _slo_tick below, or by the
        # engine's own serve) swaps the backend and clears the ENGINE's
        # jit cache; this tier's slice/logits jits captured the OLD
        # backend's mode at build time, so they must drop too — a
        # demoted engine must not keep prefilling through the collective
        # stack the demotion routed around.
        if self.engine._rung > self._flight_rung:
            # The engine's OWN ladder (SLO streaks inside
            # _slo_streak_update, serve-path retries) demoted since we
            # last looked — the serving-side _demote_backend path dumps
            # at the demotion site, so only engine-internal moves land
            # here.
            self._flight_dump(
                "backend_demotion",
                f"engine ladder moved to rung {self.engine._rung} "
                f"({self.engine.backend})")
        else:
            self._flight_rung = self.engine._rung
        if self.engine.backend != self._jits_backend:
            self._jits.clear()
            self._jits_backend = self.engine.backend
            if self._mk is not None and self.engine.backend != "megakernel":
                # The ladder (SLO streaks, transient failures) moved off
                # the persistent backend: in-flight decode state lives in
                # the megakernel pools, so running sequences recompute
                # through the dense path (preempt-resume).
                self._abort_pending()
                self._mk = None
                self._mk_ws = None
                for req in list(self.sched.running()):
                    self.sched._preempt(req)
            elif self._mk is None and self.engine.backend == "megakernel":
                # Re-promotion probe back onto the persistent backend.
                from triton_distributed_tpu.resilience import (
                    BackendUnsupportedError,
                )

                try:
                    self._mk = self._build_megakernel_lane(self.num_pages)
                except BackendUnsupportedError as exc:
                    self._demote_backend(str(exc))
                else:
                    self._abort_pending()
                    if self.prefix is not None:
                        # The re-promoted lane starts a FRESH paged
                        # workspace: indexed chains are not resident in
                        # it, so a warm hit would read unwritten tiles.
                        self.prefix.invalidate()
                    for req in list(self.sched.running()):
                        self.sched._preempt(req)

    def _step_work(self, now: float) -> dict:
        # Per-iteration spec evidence: reset so iterations that run no
        # verify step (prefill-only, post-fallback, empty batch) record
        # zeros in the flight ring instead of the last launch's counts.
        self._last_spec = (0, 0)
        with obs_stepprof.phase("admit"):
            # Admission scheduling includes the radix prefix match on
            # warm submits — host-side planning, all of it.
            admitted = self.sched.schedule_admissions()
            head = self.sched.prefill_head()
        prefilled = None
        if head is not None:
            with obs_stepprof.phase("prefill"):
                prefilled = self._prefill_slice(head)
        # Disagg hook (docs/disagg.md): between the prefill slice and the
        # decode batch, the disaggregated tier advances its in-flight
        # KV-migration streams (one double-buffer rotation each) so the
        # DCN transfers ride under this iteration's decode step. The
        # monolithic tier has nothing to move.
        with obs_stepprof.phase("migrate"):
            self._advance_migrations()
        # Async commit point (ISSUE 20): the host planning above (admit,
        # radix match, prefill-slice setup, migration rotation) ran while
        # LAST iteration's decode launch was still in flight; only now
        # does the loop block on its tokens and run the tail bookkeeping.
        # Draft/pages/cow stay below the commit because they read kv_len
        # and token tails the commit advances.
        if self.async_loop:
            self._commit_pending()
        # Speculative drafting happens BEFORE page growth so the whole
        # candidate window's reservation rides the same growth pass
        # (preempted victims drop their drafts with their pages).
        if self._spec_enabled():
            with obs_stepprof.phase("draft"):
                extra = self._plan_drafts()
        else:
            extra = None
        with obs_stepprof.phase("pages"):
            ready, preempted = self.sched.ensure_decode_pages(extra=extra)
        # Prefix COW guard (ISSUE 15): no append may target a page that
        # still carries other readers — replace with a private copy (or
        # preempt) BEFORE any launch writes the pools. Runs here, not
        # inside _decode, so its preemptions land in this iteration's
        # accounting (counter, summary, flight record) and ``decoded``
        # reflects the batch that actually stepped.
        if ready:
            with obs_stepprof.phase("cow"):
                ready, cow_evicted = self._cow_shared_appends(ready)
            preempted = list(preempted) + cow_evicted
        decoded = len(ready)
        if ready:
            if self.page_audit is not None:
                self._audit_launch(ready)
            self._decode(ready)
        with obs_stepprof.phase("accounting"):
            if self.prefix is not None:
                self.prefix.note_peak()
            self._iter += 1
            if self.page_audit is not None:
                self._audit_iteration()
            obs_on = self._observing()
            if obs_on:
                reg = self._reg()
                if preempted:
                    reg.counter(obs_metrics.SERVE_PREEMPTIONS,
                                "sequences evicted under page pressure "
                                "(recompute-on-resume)").inc(len(preempted))
                self._publish_gauges(reg)
                self._flight_record_iteration(now, admitted, prefilled,
                                              preempted, decoded)
            self._slo_tick()
            if self.fleet is not None:
                # Clean iteration: soft suspicion decays (flap damping)
                # and the rejoin streak advances while evacuated.
                self.fleet.observe_clean()
                if self.evacuated:
                    self._clean_since_evac += 1
        return {"iter": self._iter, "admitted": [r.req_id for r in admitted],
                "prefilled": prefilled,
                "preempted": [r.req_id for r in preempted],
                "decoded": decoded,
                "waiting": len(self.sched.waiting),
                "active": self.sched.active_count,
                "free_pages": self.sched.allocator.free_count,
                "admit_cap": self.sched.admit_cap}

    def run(self, *, max_iters: int = 100_000) -> list[Request]:
        """Drive until every queued request finishes; returns them in
        finish order. Raises if ``max_iters`` elapses with work left
        (a scheduling deadlock must be loud, never a silent hang)."""
        start = len(self._finished)
        it = 0
        while self.sched.has_work():
            if it >= max_iters:
                raise RuntimeError(
                    f"serving loop still has work after {max_iters} "
                    f"iterations (waiting={len(self.sched.waiting)}, "
                    f"active={self.sched.active_count}) — scheduling "
                    "deadlock or max_iters too small")
            self.step()
            it += 1
        return self._finished[start:]

    # -- internals ------------------------------------------------------------
    def _observing(self) -> bool:
        return obs_trace.get_tracer() is not None or self.slo_cfg is not None

    def _reg(self):
        """The registry this tier publishes into: its private
        per-replica registry when the fleet router namespaced it,
        otherwise the process-global one."""
        return (self.metrics_registry if self.metrics_registry is not None
                else obs_metrics.registry())

    # -- request-scoped tracing + flight recorder (ISSUE 13) ------------------
    def _req_event(self, req: Request, kind: str) -> None:
        """Scheduler lifecycle observer → request-tracer mark (one
        global load + None check when tracing is off)."""
        rt = obs_reqtrace.get_tracer()
        if rt is None:
            return
        state = {"prefilling": "PREFILLING", "preempted": "PREEMPTED",
                 "finished": "FINISHED"}.get(kind)
        if state is not None:
            rt.mark(req.req_id, state, self.clock())

    def _publish_ttft_breakdown(self, bd: dict) -> None:
        reg = self._reg()
        helps = {
            "queue_ms": "TTFT component: time WAITING/PREEMPTED "
                        "(admission + re-admission waits), ms",
            "prefill_ms": "TTFT component: time PREFILLING (chunked "
                          "slices + their scheduling gaps), ms",
            "migrate_ms": "TTFT component: time MIGRATING (disagg KV "
                          "stream), ms",
            "decode_ms": "TTFT component: RUNNING until the first "
                         "decode step lands, ms",
        }
        for comp, series in obs_metrics.TTFT_COMPONENT_SERIES.items():
            reg.histogram(series, helps[comp],
                          buckets=obs_metrics.TTFT_BUCKETS_MS
                          ).observe(bd[comp])

    def _flight_counters(self) -> dict[str, float]:
        """Count-valued series only — deterministic under seeded runs
        with an injected clock (histogram latencies are not)."""
        reg = self._reg()
        out: dict[str, float] = {}
        for name in (obs_metrics.SERVE_FINISHED,
                     obs_metrics.SERVE_PREEMPTIONS,
                     obs_metrics.SERVE_REJECTS,
                     obs_metrics.SERVE_EVAC_PREEMPTIONS,
                     obs_metrics.KV_MIGRATE_FAILURES,
                     obs_metrics.DISAGG_DEMOTIONS,
                     "tdtpu_engine_demotions_total",
                     "tdtpu_tokens_generated_total"):
            m = reg.get(name)
            if m is not None:
                out[name] = m.value
        return out

    def _flight_requests(self) -> list[dict]:
        rt = obs_reqtrace.get_tracer()
        if rt is not None and rt.has_events():
            return rt.records()
        # No request tracer (e.g. slo_cfg-only observation): fall back
        # to the scheduler's live view so the dump still names who paid.
        # (A construction-time demotion fires before the scheduler
        # exists — nothing was in flight, so an empty list is exact.)
        sched = getattr(self, "sched", None)
        if sched is None:
            return []
        return [{"req_id": r.req_id, "state": r.state.name,
                 "kv_len": r.kv_len, "preemptions": r.preemptions}
                for r in list(sched.active) + list(sched.waiting)]

    def _flight_dump(self, kind: str, reason: str) -> None:
        """Write a postmortem dump (best-effort: the recorder must never
        cost the serve it is documenting)."""
        eng = self.engine
        self._flight_rung = eng._rung
        try:
            cfg = {"max_batch": self.max_batch,
                   "num_pages": self.num_pages, "page_size": self.page,
                   "prefill_chunk": self.chunk, "backend": eng.backend,
                   "rung": eng._rung,
                   "kv_dtype": (str(jnp.dtype(self.kv_dtype))
                                if self.kv_dtype is not None else None)}
            if self.replica_id is not None:
                cfg["replica"] = self.replica_id
            self.flight.dump(kind, reason, getattr(self, "_iter", 0),
                             config=cfg,
                             requests=self._flight_requests(),
                             counters=self._flight_counters())
        except Exception as exc:
            import warnings

            warnings.warn(
                f"flight-recorder dump failed: {type(exc).__name__}: "
                f"{exc}", RuntimeWarning, stacklevel=2)

    # -- page-audit tick (analysis/page_audit.py) ----------------------------
    def _audit_launch(self, ready: list[Request]) -> None:
        """Audit the page set this iteration's decode/verify launch
        reads and the append targets it writes (pre-launch state: the
        COW guard has run, kv_lens not yet advanced)."""
        alloc = self.sched.allocator
        spec = self._spec_enabled()
        for req in ready:
            pages = alloc.pages(req.req_id)
            win = (1 + len(self._drafts.get(req.req_id, []))
                   if spec else 1)
            ti = req.kv_len // self.page
            last_ti = (req.kv_len + win - 1) // self.page
            reads = pages[:-(-req.kv_len // self.page)]
            appends = pages[ti:min(last_ti + 1, len(pages))]
            self.page_audit.note_launch(
                reads, appends,
                site=f"decode iter {self._iter} req {req.req_id}")

    def _audit_iteration(self) -> None:
        """Close the auditor's iteration: leak checks against the live
        request set, and stash the event buffer for the flight record."""
        live = {}
        for r in self.sched.active:
            live[str(r.req_id)] = (r.kv_len
                                   if r.state is RequestState.RUNNING
                                   else None)
        self._last_page_live = live
        self._last_page_events = self.page_audit.end_iteration(live)

    def _flight_record_iteration(self, now: float, admitted, prefilled,
                                 preempted, decoded: int) -> None:
        alloc = self.sched.allocator
        usable = max(alloc.usable_pages, 1)
        running = self.sched.running()
        rec_extra = {}
        if self.spec_k:
            rec_extra["spec"] = {"drafted": self._last_spec[0],
                                 "accepted_drafts": self._last_spec[1],
                                 "fallback": self._spec_fallback}
        if self.page_audit is not None:
            rec_extra["page_events"] = self._last_page_events
            rec_extra["page_live"] = self._last_page_live
            rec_extra["page_size"] = self.page
            rec_extra["page_audit_violations"] = len(
                self.page_audit.violations)
        if self.prefix is not None:
            rec_extra["prefix"] = {
                "hits": self.prefix.hits,
                "lookups": self.prefix.lookups,
                "tokens_saved": self.prefix.tokens_saved,
                "pages_held": self.prefix.pages_held,
                "pages_shared": self.prefix.pages_shared(),
                "evictions": self.prefix.evictions,
            }
        # Kept by reference: the step profiler's phase vector for THIS
        # iteration is only complete after step() returns, so
        # _step_profile_close patches it in place (the flight ring
        # stores the dict itself, not a copy).
        self._last_flight_rec = {
            **rec_extra,
            "iter": self._iter, "t": round(now, 6),
            "admitted": [r.req_id for r in admitted],
            "prefilled": prefilled,
            "preempted": [r.req_id for r in preempted],
            "decoded": decoded,
            "waiting": len(self.sched.waiting),
            "active": self.sched.active_count,
            "running": len(running),
            "free_pages": alloc.free_count,
            "pool_occupancy_frac": round(
                1.0 - alloc.free_count / usable, 4),
            "admit_cap": self.sched.admit_cap,
            "kv_lens": {r.req_id: r.kv_len for r in running},
            "backend": self.engine.backend,
            "rung": self.engine._rung,
            "evacuated": self.evacuated,
            "slo_violation_streak": self._viol_streak,
            "fleet_suspects": (len(self.fleet.suspects())
                               if self.fleet is not None else 0),
        }
        self.flight.record(self._last_flight_rec)

    def _step_profile_close(self, rec: dict) -> None:
        """Fold the finished iteration's phase record (ISSUE 18) into
        the flight ring and the metrics registry. Runs in step()'s
        ``finally`` — after the summary — so the ``accounting`` phase
        covers the flight record, gauges, and SLO tick it just timed."""
        if not rec:
            return
        flight_rec = getattr(self, "_last_flight_rec", None)
        if flight_rec is not None and "phases" not in flight_rec:
            # Satellite 2: dumps carry the phase vector + cumulative
            # host/device milliseconds alongside page_events.
            flight_rec["phases"] = rec["phases"]
            flight_rec["wall_ms"] = rec["wall_ms"]
            flight_rec["host_ms"] = rec["host_ms"]
            flight_rec["device_ms"] = rec["device_ms"]
            flight_rec["host_bubble_frac"] = rec["host_bubble_frac"]
            flight_rec["host_ms_cum"] = rec["host_ms_cum"]
            flight_rec["device_ms_cum"] = rec["device_ms_cum"]
        self._last_flight_rec = None
        if not self._observing():
            return
        reg = self._reg()
        reg.gauge(
            obs_metrics.SERVE_HOST_BUBBLE_FRAC,
            "host milliseconds not overlapped with the device / "
            "iteration wall — the synchronous-loop bubble ROADMAP "
            "item 3's async loop must kill").set(rec["host_bubble_frac"])
        reg.histogram(
            obs_metrics.SERVE_STEP_HOST_MS,
            "host-attributed milliseconds per serving iteration"
            ).observe(rec["host_ms"])
        reg.histogram(
            obs_metrics.SERVE_STEP_DEVICE_MS,
            "device-attributed milliseconds per serving iteration "
            "(prefill / migrate / device-wait phases)"
            ).observe(rec["device_ms"])
        for phase_name, ms in rec["phases"].items():
            reg.histogram(
                f"{obs_metrics.SERVE_PHASE_MS_PREFIX}_{phase_name}",
                f"step-phase '{phase_name}' milliseconds per iteration "
                "(obs/stepprof.py taxonomy)").observe(ms)

    def _goodput_close(self, rec: dict, gl) -> None:
        """Fold the finished iteration's work record (ISSUE 19) into
        the flight ring and the metrics registry, then drain any fired
        windowed alert into a ``goodput_regression`` flight dump. Runs
        in step()'s ``finally``, BEFORE _step_profile_close (which
        clears the shared flight-record reference)."""
        if not rec:
            return
        flight_rec = getattr(self, "_last_flight_rec", None)
        if flight_rec is not None and "goodput" not in flight_rec:
            # Dumps carry the work partition alongside the phase vector
            # — obs.report --check re-verifies it on every dumped
            # record, postmortem renders the goodput table from it.
            flight_rec["goodput"] = {
                "rows": rec["rows"],
                "work": rec["work"],
                "goodput_frac": rec["goodput_frac"],
                "prefill_saved": rec["prefill_saved"],
                "goodput_frac_cum": rec["goodput_frac_cum"],
            }
        if self._observing():
            reg = self._reg()
            reg.gauge(
                obs_metrics.SERVE_GOODPUT_FRAC,
                "cumulative useful/dispatched device token-row fraction "
                "(obs/goodput.py taxonomy — the waste categories are "
                "the labeled work-tokens counter)"
                ).set(rec["goodput_frac_cum"])
            for cat, n in rec["work"].items():
                reg.counter(
                    obs_metrics.WORK_TOKENS,
                    "device token-rows dispatched, by goodput category "
                    "(obs/goodput.py: useful / spec_rejected / "
                    "recompute / overhead / idle)",
                    labels={"category": cat}).inc(n)
        # Windowed alert rules (goodput below floor / waste spiking for
        # W intervals) fire through the established trigger chain.
        for alert in gl.consume_alerts():
            self.flight.note("goodput_regression", alert["reason"],
                             self._iter, rule=alert["rule"])
            self._flight_dump("goodput_regression",
                              f"{alert['rule']}: {alert['reason']}")

    def _prefill_lane(self, req: Request):
        """(engine, slice_fn, logits_fn) the prefill stage runs through
        for ``req``. The disaggregated tier (disagg/engine.py)
        overrides this to the PREFILL role's engine and jits while it
        is active — except for a prefix-hit admission, whose short
        suffix prefills on the DECODE engine directly (the disagg
        skip); here prefill and decode share one engine."""
        return self.engine, self._slice_jit(), self._logits_jit()

    def _pf_get(self, req: Request):
        """The linear prefill buffer ``req``'s slices read/write — the
        disagg tier routes warm admissions to a decode-mesh buffer."""
        return self._pf_cache

    def _pf_set(self, req: Request, cache) -> None:
        self._pf_cache = cache

    def _advance_migrations(self) -> int:
        """Disagg hook: advance in-flight KV-migration streams by one
        double-buffer rotation each (disagg/engine.py). The monolithic
        tier migrates nothing."""
        return 0

    # -- fleet elasticity (ISSUE 11, docs/resilience.md) ----------------------
    def _mesh_rank_ids(self) -> list[int]:
        """Device ids of the engine's CURRENT mesh, cached on context
        identity — the geometry only changes at evacuate/rejoin (which
        install a fresh DistContext), and the preflight runs every
        iteration of the hot loop."""
        ctx = self.engine.ctx
        cached = getattr(self, "_mesh_ids_cache", None)
        if cached is None or cached[0] is not ctx:
            cached = (ctx, [int(d.id) for d in
                            np.asarray(ctx.mesh.devices).ravel()])
            self._mesh_ids_cache = cached
        return cached[1]

    def _count_fleet_preemptions(self, reg, n: int) -> None:
        if n:
            reg.counter(
                obs_metrics.SERVE_EVAC_PREEMPTIONS,
                "sequences recomputed because the fleet preempted them "
                "(evacuation / rejoin / suspect-rank retry)").inc(n)

    def _fleet_preflight(self) -> str | None:
        """Per-iteration fleet health pass: fold the lost-rank registry
        into the ledger, EVACUATE when a rank of the current mesh is
        confirmed dead, narrow admission on fresh suspicion (flap
        damping: a straggler costs width, never membership), and fire
        the rejoin probe once the loss has cleared for
        ``TDTPU_REJOIN_AFTER`` clean iterations."""
        if self.fleet is None:
            return None
        from triton_distributed_tpu.resilience import faults as faults_mod

        lost = faults_mod.lost_ranks()
        self.fleet.sync_lost(lost)
        mesh_ids = set(self._mesh_rank_ids())
        dead_here = sorted(r for r in self.fleet.dead() if r in mesh_ids)
        if dead_here:
            self._evacuate(dead_here,
                           reason=f"rank(s) {dead_here} confirmed dead")
            return "evacuated"
        if (self.fleet.consume_new_suspicion() and self.fleet.suspects()
                and self.sched.admit_cap > 1):
            cap = self.sched.shrink_admission()
            with obs_trace.span("serving.admission_shrink", cap=cap,
                                reason="fleet_suspicion"):
                pass
            self.flight.note(
                "fleet_suspicion",
                f"suspect rank(s) {sorted(self.fleet.suspects())} "
                f"narrowed admission to {cap}", self._iter)
        if (self.evacuated and self._clean_since_evac >= self._rejoin_after
                and not (set(self._full_rank_ids) & set(lost))):
            self._rejoin()
            return "rejoined"
        return None

    def _fleet_on_failure(self, exc: BaseException) -> str | None:
        """Transient, rank-attributable step failure: score the ledger
        and either evacuate (confirmed dead — returns ``"evacuated"``)
        or preempt-and-recompute on the KEPT geometry (suspicion — a
        slow-but-alive rank must not be evicted on one strike; returns
        ``"retried"``). Returns None when the failure is not the fleet's
        to handle (the caller re-raises)."""
        from triton_distributed_tpu import resilience
        from triton_distributed_tpu.resilience import fleet as fleet_mod

        if self.fleet is None or not resilience.is_transient(exc):
            return None
        if os.environ.get("TDTPU_DEMOTION_LADDER", "1") == "0":
            return None
        rank = self.fleet.observe_error(exc)
        if rank is None or rank not in self._mesh_rank_ids():
            return None
        if self.fleet.verdict(rank) is fleet_mod.HealthVerdict.DEAD:
            mesh_ids = set(self._mesh_rank_ids())
            dead_here = sorted(r for r in self.fleet.dead()
                               if r in mesh_ids)
            self._evacuate(
                dead_here or [rank],
                reason=f"{type(exc).__name__}: {str(exc)[:120]}", exc=exc)
            return "evacuated"
        # Suspicion, not a verdict: the in-flight step's device state is
        # unknown (a failed donated jit may have consumed the cache), so
        # preempt everything and rebuild — recompute-on-resume is always
        # state-correct, and the geometry survives the flap.
        n = self._preempt_all()
        self._rebuild_device_state()
        self.flight.note(
            "fleet_step_fault",
            f"{type(exc).__name__} attributed to rank {rank}: "
            f"{str(exc)[:120]}", self._iter, rank=rank)
        if self._observing():
            reg = self._reg()
            reg.counter(obs_metrics.FLEET_STEP_FAULTS,
                        "rank-attributable step failures absorbed below "
                        "the evacuation threshold").inc()
            self._count_fleet_preemptions(reg, n)
        return "retried"

    def _preempt_all(self, *, evacuation: bool = False) -> int:
        """Preempt every in-flight request (recompute-on-resume). First-
        submission accounting is untouched: ``t_arrival`` and any stamped
        ``t_first_token`` survive, so an evacuated request keeps its real
        TTFT evidence. ``evacuation=True`` (the survivor-mesh path only)
        stamps ``req.evacuations`` — the record flag must not fire for a
        rejoin probe or a sub-threshold transient-fault rebuild."""
        self._abort_pending()
        evicted = list(self.sched.active)
        for req in evicted:
            self.sched._preempt(req)
            if evacuation:
                req.evacuations += 1
        self.evacuation_preemptions += len(evicted)
        return len(evicted)

    def _rebuild_device_state(self) -> None:
        """Fresh KV pools + prefill buffer on the engine's CURRENT mesh
        and a cleared jit cache — the serving-side half of a
        repartition (jits rebuild lazily through ``_first_call``)."""
        self._abort_pending()
        eng = self.engine
        cache = init_paged_model_cache(
            self.cfg, self.max_batch, page_size=self.page,
            max_pages=self.max_pages, num_pages=self.num_pages + 1,
            kv_dtype=self.kv_dtype)
        self._cache = self._put_sharded(
            cache, paged_cache_specs(eng.shard_axes))
        self._pf_cache = self._put_sharded(
            init_kv_cache(self.cfg, 1, self.s_buf),
            kv_cache_specs(eng.shard_axes))
        if self.prefix is not None:
            # The pools were just zeroed: every indexed chain's bytes
            # are gone — a stale hit would serve garbage KV.
            self.prefix.invalidate()
        self._jits.clear()
        self._jits_backend = eng.backend
        self._mk = None
        self._mk_ws = None
        if eng.backend == "megakernel":
            from triton_distributed_tpu.resilience import (
                BackendUnsupportedError,
            )

            try:
                self._mk = self._build_megakernel_lane(self.num_pages)
            except BackendUnsupportedError as exc:
                # The mesh ladder composes with the backend ladder:
                # geometry demoted first; backend only now, because the
                # survivor mesh cannot host the persistent lane.
                self._demote_backend(str(exc))
                self._jits_backend = eng.backend

    def _evacuate(self, dead: list[int], reason: str,
                  exc: BaseException | None = None) -> None:
        """Confirmed-dead verdict: preempt all in-flight requests,
        re-partition onto the survivor sub-mesh (TP=8 → TP=4 style),
        host-reshard params, rebuild pools/jits, resume with
        recompute-on-resume. ``TDTPU_DEMOTION_LADDER=0`` opts out — the
        named error propagates (geometry demotion must never mask a
        config the operator pinned)."""
        from triton_distributed_tpu.resilience import fleet as fleet_mod
        from triton_distributed_tpu.resilience.faults import RankLossError

        if os.environ.get("TDTPU_DEMOTION_LADDER", "1") == "0":
            if exc is not None:
                raise exc
            raise RankLossError(
                f"rank(s) {dead} confirmed dead and TDTPU_DEMOTION_LADDER"
                f"=0 pins the geometry — {reason}", rank=dead[0])
        sub = fleet_mod.survivor_context(
            self._full_ctx, self.fleet.dead(), axis=self.engine.axis,
            num_kv_heads=self.cfg.num_kv_heads)
        if sub is None:
            raise (exc if exc is not None else RankLossError(
                f"rank(s) {dead} dead and no survivor TP geometry exists "
                f"(num_kv_heads {self.cfg.num_kv_heads}) — {reason}",
                rank=dead[0]))
        n_evicted = self._preempt_all(evacuation=True)
        old_n = self.engine.n_total
        self.engine.repartition(sub, reason=reason)
        self._rebuild_device_state()
        self.evacuated = True
        self._clean_since_evac = 0
        rec = {"event": "evacuation", "dead": sorted(dead),
               "reason": reason, "from_ranks": old_n,
               "to_ranks": self.engine.n_total, "preempted": n_evicted}
        self.fleet_log.append(rec)
        self._flight_dump(
            "evacuation", f"rank(s) {sorted(dead)} dead: {reason} "
            f"({old_n} -> {self.engine.n_total} ranks, "
            f"{n_evicted} preempted)")
        with obs_trace.span("fleet.evacuation", dead=str(sorted(dead)),
                            reason=reason, from_ranks=old_n,
                            to_ranks=self.engine.n_total,
                            preempted=n_evicted):
            pass
        if self._observing():
            reg = self._reg()
            reg.counter(obs_metrics.FLEET_EVACUATIONS,
                        "survivor-mesh evacuations (rank confirmed dead)"
                        ).inc()
            self._count_fleet_preemptions(reg, n_evicted)
            self._publish_fleet_gauges(reg)
        import warnings

        warnings.warn(
            f"fleet evacuated rank(s) {sorted(dead)}: {old_n} -> "
            f"{self.engine.n_total} ranks ({reason})", RuntimeWarning,
            stacklevel=3)

    def _rejoin(self) -> None:
        """Rejoin probe (the clean-streak mirror of evacuation): after
        ``TDTPU_REJOIN_AFTER`` clean iterations with the loss cleared,
        re-expand to the full mesh. In-flight requests preempt and
        recompute, so a probe that fails — the rank dies again — just
        evacuates again without losing any request."""
        n_evicted = self._preempt_all()
        old_n = self.engine.n_total
        self.engine.repartition(self._full_ctx, reason="fleet rejoin probe")
        self._rebuild_device_state()
        for r in self._full_rank_ids:
            self.fleet.absolve(r)
        self.evacuated = False
        self._clean_since_evac = 0
        rec = {"event": "rejoin", "from_ranks": old_n,
               "to_ranks": self.engine.n_total, "preempted": n_evicted}
        self.fleet_log.append(rec)
        self.flight.note("rejoin", f"rejoined full mesh ({old_n} -> "
                         f"{self.engine.n_total} ranks)", self._iter)
        with obs_trace.span("fleet.rejoin", from_ranks=old_n,
                            to_ranks=self.engine.n_total,
                            preempted=n_evicted):
            pass
        if self._observing():
            reg = self._reg()
            reg.counter(obs_metrics.FLEET_REJOINS,
                        "full-mesh rejoins after a cleared rank loss"
                        ).inc()
            self._count_fleet_preemptions(reg, n_evicted)
            self._publish_fleet_gauges(reg)
        import warnings

        warnings.warn(
            f"fleet rejoined the full mesh: {old_n} -> "
            f"{self.engine.n_total} ranks", RuntimeWarning, stacklevel=3)

    def _publish_fleet_gauges(self, reg) -> None:
        reg.gauge(obs_metrics.FLEET_RANKS_ALIVE,
                  "ranks of the full serving mesh not confirmed dead"
                  ).set(len(self.fleet.alive()))
        reg.gauge(obs_metrics.FLEET_SUSPECTS,
                  "ranks under suspicion (admission narrowed, not "
                  "evicted)").set(len(self.fleet.suspects()))

    def _prefill_slice(self, req: Request) -> str:
        text = req.text
        T = len(text)
        try:
            if req.prefill_pos == 0 and req.prefix_hit_tokens > 0:
                self._prefix_gather(req)
            start = req.prefill_pos
            ids = np.zeros((1, self.chunk), np.int32)
            real = text[start:start + self.chunk]
            ids[0, :len(real)] = real
            eng, slice_fn, logits_fn = self._prefill_lane(req)
            eng._jit_compiled_last_call = False
            t0 = self.clock()
            with obs_trace.span("serving.prefill_slice", req=req.req_id,
                                start=start, tokens=len(real)):
                x, pf = slice_fn(
                    eng.params, jnp.asarray(ids), self._pf_get(req),
                    jnp.int32(start))
                self._pf_set(req, pf)
        except Exception as exc:
            from triton_distributed_tpu import resilience
            from triton_distributed_tpu.resilience import fleet as fleet_mod

            if (not resilience.is_transient(exc)
                    or fleet_mod.attribute_rank(exc) is not None
                    or os.environ.get("TDTPU_DEMOTION_LADDER", "1")
                    == "0"):
                # Rank-attributable failures are the FLEET's to judge
                # (evacuate / retry on kept geometry); non-transient
                # errors and a pinned ladder propagate.
                raise
            self._prefill_fault(req, exc)
            return req.req_id
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.span(req.req_id, "prefill_slice", t0, self.clock(),
                    start=start, tokens=len(real))
        # Goodput attribution (ISSUE 19): the slice launch always
        # computes ``chunk`` rows. Rows covering positions this request
        # already computed before a preempt/evacuation/fallback are
        # recompute; fresh positions are useful (cold prefill); the
        # fixed-shape padding past the real tokens is idle. The
        # per-request counter accrues unconditionally so loadgen's
        # request_records reconcile against the ledger aggregates.
        redo = max(0, min(start + len(real), req.computed_high) - start)
        if redo:
            req.recompute_tokens += redo
        req.computed_high = max(req.computed_high, start + len(real))
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            gl.dispatch(self.chunk)
            gl.add("recompute", redo)
            gl.add("useful", len(real) - redo)
            gl.add("idle", self.chunk - len(real))
        req.prefill_pos = min(start + self.chunk, T)
        done = req.prefill_pos >= T
        if done:
            row = (T - 1) - start
            tok = logits_fn(eng.params, x[row:row + 1])
            tok = int(np.asarray(tok)[0])
            now = self.clock()
            req.tokens.append(tok)
            req.kv_len = T
            self.total_tokens += 1
            self._rate_events.append((now, 1))
            first = req.t_first_token is None
            if first:
                req.t_first_token = now
            if self._observing():
                reg = self._reg()
                reg.counter("tdtpu_tokens_generated_total",
                            "decode tokens generated").inc()
                if first:
                    reg.histogram(
                        obs_metrics.SERVE_TTFT_MS,
                        "request time-to-first-token (arrival -> first "
                        "token), ms",
                        buckets=obs_metrics.TTFT_BUCKETS_MS,
                    ).observe((now - req.t_arrival) * 1e3)
                Engine._observe_step(
                    reg, (now - t0) * 1e3, eng._jit_compiled_last_call,
                    "tdtpu_prefill_latency_ms",
                    "prefill wall latency (device-synced only in sync "
                    "runs)")
            self._complete_prefill(req)
        return req.req_id

    def _prefill_fault(self, req: Request, exc: BaseException) -> None:
        """Transient, non-rank-attributable failure inside a prefill
        slice (or a warm admission's prefix gather): retry by
        recompute — the head request preempts (its pages release their
        references; shared pages stay intact for their other readers)
        and the prefill buffer is rebuilt (it was donated into the
        failed jit, so its state is unknown). The paged pools were NOT
        an operand, so resident chains — including every shared prefix
        page — are untouched, and the resumed request re-admits warm
        off the surviving index."""
        import warnings

        # Buffer reset FIRST: preemption zeroes req.prefix_hit_tokens,
        # and the disagg override routes on warmness — resetting after
        # would rebuild the wrong buffer and leave the donated warm
        # buffer live for the next admission to trip over.
        self._reset_pf_buffer(req)
        self.sched._preempt(req)
        self.flight.note(
            "prefill_fault",
            f"{type(exc).__name__} in prefill of {req.req_id}: "
            f"{str(exc)[:120]} (preempt + recompute-on-resume)",
            self._iter, req=req.req_id)
        if self._observing():
            self._reg().counter(
                "tdtpu_serve_prefill_faults_total",
                "transient prefill-slice failures absorbed by "
                "preempt + recompute-on-resume").inc()
        warnings.warn(
            f"prefill slice of {req.req_id} failed transiently "
            f"({type(exc).__name__}); preempted for recompute-on-"
            "resume", RuntimeWarning, stacklevel=3)

    def _reset_pf_buffer(self, req: Request) -> None:
        """Fresh zeroed prefill buffer after a failed (donated) slice
        jit — the disagg tier overrides to target the right mesh."""
        self._pf_cache = self._put_sharded(
            init_kv_cache(self.cfg, 1, self.s_buf),
            kv_cache_specs(self.engine.shard_axes))

    def _prefix_gather(self, req: Request) -> None:
        """Warm-admission restart (docs/serving.md "Prefix cache"): pull
        the shared prefix pages into the prefill buffer and move
        ``prefill_pos`` past them, so only the divergent suffix
        prefills. The restart is CHUNK-aligned (slices are a fixed
        grid): tokens between the aligned restart and the token-granular
        hit recompute into the buffer — identical values by content
        addressing, so the COW'd boundary page's merged content is
        exact either way.

        Host-tier extension (ISSUE 20): when part of the hit lives in
        host RAM (``req.restored_tokens``), only the device-shared
        prefix gathers from the pool; the host-resident chunks stream
        into the buffer right after it via :meth:`_kvtier_restore`.
        Both land in the same linear buffer the suffix slices attend,
        so the downstream math cannot tell a restored position from a
        device-resident one — that is the parity argument."""
        hit = req.prefix_hit_tokens
        restart = hit - hit % self.chunk
        device_hit = hit - req.restored_tokens
        n_gather = min(restart, device_hit) // self.page
        t0 = self.clock()
        if n_gather:
            pages = self.sched.allocator.pages(req.req_id)[:n_gather]
            buf = self._gather_jit(n_gather)(
                self._pf_get(req), self._cache,
                jnp.asarray(pages, jnp.int32))
            self._pf_set(req, buf)
        if req._kvtier_pending:
            # Chunk-aligned restarts can strand trailing host chunks
            # (they stay resident in the tier); restore only the pages
            # the restart actually skips past.
            self._kvtier_restore(
                req, max(0, restart - device_hit) // self.page)
        req.prefill_pos = restart
        with obs_trace.span("serving.prefix_hit", req=req.req_id,
                            hit_tokens=hit, restart=restart):
            pass
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.span(req.req_id, "prefix_gather", t0, self.clock(),
                    hit_tokens=hit, restart=restart)
        if restart:
            # Avoided-work credit (ISSUE 19): the skipped prefix rows
            # were never dispatched — outside the partition, reported
            # alongside it as prefill_saved.
            gl = obs_goodput.get_ledger()
            if gl is not None and gl.active():
                gl.credit_saved(restart)
        if restart and self._observing():
            self._reg().counter(
                obs_metrics.PREFIX_TOKENS_SAVED,
                "prefill tokens skipped because a shared resident "
                "prefix covered them (warm admissions)").inc(restart)

    def _complete_prefill(self, req: Request) -> None:
        """Prefill finished (first token already recorded, ``req.kv_len``
        = prompt length): hand the buffered KV to the decode stage. Here
        the buffer scatters page-aligned into the shared pool and the
        request joins the decode batch; the disaggregated tier instead
        starts a migration stream to the decode slice's pool.

        Warm admissions scatter only from the first PRIVATE page on:
        the shared prefix pages are already resident and must never be
        written (the partially-matched boundary page's replacement — the
        first fresh page — receives the merged prefix+suffix content
        from the buffer: that is the copy half of its copy-on-write)."""
        n_pages = -(-req.kv_len // self.page)
        pages = self.sched.allocator.pages(req.req_id)[:n_pages]
        skip = 0
        if self.prefix is not None and req.prefix_hit_tokens > 0:
            # Device-SHARED pages only: host-restored positions sit in
            # the buffer like recomputed tokens and scatter into this
            # request's own fresh pages below (re-indexing them makes
            # the chain device-resident again for the next admission).
            skip = (req.prefix_hit_tokens - req.restored_tokens) \
                // self.page
            if req._prefix_partial is not None:
                # The merged content lands in the private replacement;
                # the read hold on the shared boundary page drops.
                self.prefix.unpin(req._prefix_partial)
                req._prefix_partial = None
        buf = self._pf_get(req)
        if self._mk is not None:
            # The megakernel workspace is the decode-time source of
            # truth: a finished prefill's pages scatter in here too
            # (the paged _cache keeps the dense fallback viable).
            if self._mk_ws is None:
                self._mk_ws = self._mk.start()
            self._mk_ws = self._mk.load_prefill(
                self._mk_ws, buf.k, buf.v, pages[skip:],
                first_page=skip)
        self._cache = self._scatter_jit(n_pages - skip, skip)(
            self._cache, buf.k, buf.v,
            jnp.asarray(pages[skip:], jnp.int32))
        if self.prefix is not None:
            # Index the chain (full pages only) for future admissions:
            # the cache pins each newly indexed page resident.
            self.prefix.insert(req.text[:req.kv_len], pages)
        req.advance(RequestState.RUNNING)
        rt = obs_reqtrace.get_tracer()
        if rt is not None:
            rt.mark(req.req_id, "RUNNING", self.clock())
        if req.done:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.final_backend = self.engine.backend
        rt = obs_reqtrace.get_tracer()
        if rt is not None and rt.breakdown(req.req_id) is None:
            # Requests that never decode (max_new_tokens == 1, or a
            # mid-flight finish): their decomposition window closes at
            # the first token — decode component 0 by construction.
            end = (req.t_first_token if req.t_first_token is not None
                   else self.clock())
            bd = rt.close_window(req.req_id, end)
            if bd is not None and self._observing():
                self._publish_ttft_breakdown(bd)
        self.sched.finish(req, self.clock())
        self._finished.append(req)
        if self._observing():
            reg = self._reg()
            reg.counter(obs_metrics.SERVE_FINISHED,
                        "requests served to completion").inc()
            tpot = req.tpot_s
            if tpot is not None:
                reg.histogram(
                    obs_metrics.SERVE_TPOT_MS,
                    "request mean time-per-output-token after the "
                    "first, ms").observe(tpot * 1e3)

    def _decode(self, ready: list[Request]) -> None:
        eng = self.engine
        alloc = self.sched.allocator
        if self._spec_enabled() and (
                (self._mk.spec_w > 1) if self._mk is not None
                else any(self._drafts.get(r.req_id) for r in ready)):
            # Dense lane with EVERY draft empty falls through to the
            # one-token step below — the verify window of 1 computes the
            # same token at (spec_k+1)× the GEMM rows and attention
            # walks, so paying it buys nothing. The megakernel lane
            # routes by its COMPILED window (extra candidate rows ride
            # the block padding for free there; and a lane built
            # windowless must never receive a wins>1 step).
            self._decode_spec(ready)
            return
        with obs_stepprof.phase("decode_dispatch"):
            toks = np.zeros((self.max_batch,), np.int32)
            lens = np.zeros((self.max_batch,), np.int32)
            # Unmapped entries are -1 so the megakernel decoder's
            # page-coverage guard can SEE them (it treats negatives as
            # scratch and validates kv_len against the mapped count); the
            # dense path substitutes the scratch page below.
            table = np.full((self.max_batch, self.max_pages), -1, np.int32)
            for req in ready:
                toks[req.slot] = req.tokens[-1]
                lens[req.slot] = req.kv_len
                pages = alloc.pages(req.req_id)
                table[req.slot, :len(pages)] = pages
        if self._mk is not None:
            try:
                self._decode_megakernel(ready, toks, lens, table)
                return
            except Exception as exc:
                from triton_distributed_tpu import resilience

                if not resilience.is_transient(exc):
                    raise
                self._mk_decode_failed(ready, exc)
                return
        table[table < 0] = self.scratch_page
        cache = self._cache._replace(page_table=jnp.asarray(table),
                                     kv_lens=jnp.asarray(lens))
        eng._jit_compiled_last_call = False
        t0 = self.clock()
        with obs_trace.span("serving.decode_step", batch=len(ready)):
            with obs_stepprof.phase("decode_dispatch"):
                tok, self._cache = eng._decode_run(jnp.asarray(toks), cache)
            if self.async_loop:
                self._stash_pending("dense", ready, tok, t0,
                                    cold=eng._jit_compiled_last_call,
                                    rows=self.max_batch)
                return
            with obs_stepprof.phase("device_wait"):
                tok_np = np.asarray(tok)    # host sync: the loop needs them
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            # One-token dense step: max_batch rows, one committed token
            # per ready slot, empty slots pad the fixed shape.
            gl.dispatch(self.max_batch)
            gl.add("useful", len(ready))
            gl.add("idle", self.max_batch - len(ready))
        self._decode_tail(ready,
                          {r.req_id: [int(tok_np[r.slot])] for r in ready},
                          t0, eng._jit_compiled_last_call)

    def _mk_decode_failed(self, ready: list[Request], exc) -> None:
        """Transient megakernel failure mid-serve: demote (don't die) and
        recompute the in-flight batch through the dense path — their
        decode-time KV lived in the megakernel pools, so
        recompute-on-resume is the only state-correct hand-off."""
        self._abort_pending()
        self._demote_backend(
            f"megakernel decode failed: {type(exc).__name__}: "
            f"{str(exc)[:120]}")
        self._mk = None
        self._mk_ws = None
        for req in list(ready):
            self.sched._preempt(req)
        if self._observing():
            # NOT the page-pressure counter: an operator alert
            # keyed on pool sizing must not fire for a backend
            # fault.
            self._reg().counter(
                "tdtpu_serve_backend_demote_preemptions_total",
                "in-flight sequences recomputed because the "
                "decode backend demoted mid-serve").inc(len(ready))

    def _decode_megakernel(self, ready: list[Request], toks, lens,
                           table) -> None:
        """One paged persistent-kernel decode step over every slot (the
        round-9 megakernel serving lane): the host rewrites queue words
        from the allocator's page ids, ONE pallas launch decodes the
        whole heterogeneous batch, and the in-kernel APPEND_KV tasks
        advance each slot's pool pages."""
        if self._mk_ws is None:
            self._mk_ws = self._mk.start()
        t0 = self.clock()
        with obs_trace.span("serving.decode_step_megakernel",
                            batch=len(ready)):
            with obs_stepprof.phase("decode_dispatch"):
                # The decoder's host queue-word rewrite telescopes its
                # own ``retarget`` slice out of this phase.
                self._mk_ws, tok = self._mk.step(self._mk_ws, toks, lens,
                                                 table)
            if self.async_loop:
                self._stash_pending("mk", ready, tok, t0,
                                    cold=self._mk.last_step_cold,
                                    rows=self._mk.last_step_rows)
                return
            with obs_stepprof.phase("device_wait"):
                tok_np = np.asarray(tok)  # host sync: the loop needs them
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            # The persistent program covers EVERY slot block — use the
            # decoder's own launch accounting (megakernel/serving.py),
            # not an assumption about the lane's shape.
            rows = self._mk.last_step_rows
            gl.dispatch(rows)
            gl.add("useful", len(ready))
            gl.add("idle", rows - len(ready))
        self._decode_tail(ready,
                          {r.req_id: [int(tok_np[r.slot])] for r in ready},
                          t0, self._mk.last_step_cold)

    def _decode_spec(self, ready: list[Request]) -> None:
        """Speculative draft-and-verify decode (ISSUE 14): the candidate
        window [last accepted token, draft_1..draft_k] of every RUNNING
        slot scores in ONE launch; the host keeps the longest accepted
        prefix per slot and rolls rejected positions back (kv_len
        truncation + page-tail release) — the ledger counts accepted
        tokens only."""
        eng = self.engine
        alloc = self.sched.allocator
        W = self.spec_k + 1
        with obs_stepprof.phase("decode_dispatch"):
            toks = np.zeros((self.max_batch, W), np.int32)
            lens = np.zeros((self.max_batch,), np.int32)
            wins = np.ones((self.max_batch,), np.int32)
            table = np.full((self.max_batch, self.max_pages), -1, np.int32)
            drafts: dict[str, list[int]] = {}
            for req in ready:
                d = self._drafts.get(req.req_id, [])
                drafts[req.req_id] = d
                toks[req.slot, 0] = req.tokens[-1]
                if d:
                    toks[req.slot, 1:1 + len(d)] = d
                wins[req.slot] = 1 + len(d)
                lens[req.slot] = req.kv_len
                pages = alloc.pages(req.req_id)
                table[req.slot, :len(pages)] = pages
        if self._mk is not None:
            # The lane was compiled with spec_window == W (it rebuilds
            # through _build_megakernel_lane on every spec-state change).
            try:
                if self._mk_ws is None:
                    self._mk_ws = self._mk.start()
                t0 = self.clock()
                with obs_trace.span("serving.verify_step_megakernel",
                                    batch=len(ready), window=W):
                    with obs_stepprof.phase("decode_dispatch"):
                        self._mk_ws, ver = self._mk.step(
                            self._mk_ws, toks, lens, table, wins)
                    if self.async_loop:
                        self._stash_pending(
                            "mk_spec", ready, ver, t0,
                            cold=self._mk.last_step_cold,
                            rows=self._mk.last_step_rows, drafts=drafts)
                        return
                    with obs_stepprof.phase("device_wait"):
                        ver_np = np.asarray(ver)
            except Exception as exc:
                from triton_distributed_tpu import resilience

                if not resilience.is_transient(exc):
                    raise
                self._mk_decode_failed(ready, exc)
                return
            self._spec_tail(ready, drafts, ver_np, t0,
                            self._mk.last_step_cold)
            return
        table[table < 0] = self.scratch_page
        cache = self._cache._replace(page_table=jnp.asarray(table),
                                     kv_lens=jnp.asarray(lens))
        eng._jit_compiled_last_call = False
        t0 = self.clock()
        try:
            with obs_trace.span("serving.verify_step", batch=len(ready),
                                window=W):
                with obs_stepprof.phase("decode_dispatch"):
                    ver, self._cache = self._verify_jit()(
                        eng.params, jnp.asarray(toks), cache)
                if self.async_loop:
                    self._stash_pending("spec", ready, ver, t0,
                                        cold=eng._jit_compiled_last_call,
                                        rows=None, drafts=drafts)
                    return
                with obs_stepprof.phase("device_wait"):
                    ver_np = np.asarray(ver)
        except Exception as exc:
            from triton_distributed_tpu import resilience
            from triton_distributed_tpu.resilience import fleet as fleet_mod

            if not resilience.is_transient(exc):
                raise
            if fleet_mod.attribute_rank(exc) is not None:
                # A rank-attributable failure is the FLEET's to judge
                # (evacuate / retry on kept geometry) — disabling the
                # spec lane would mask the real fault and forfeit the
                # lane for a problem it did not cause.
                raise
            self._spec_disable(
                f"verify step failed: {type(exc).__name__}: "
                f"{str(exc)[:120]}")
            return
        self._spec_tail(ready, drafts, ver_np, t0,
                        eng._jit_compiled_last_call)

    def _spec_tail(self, ready: list[Request], drafts: dict,
                   ver_np, t0: float, cold: bool) -> None:
        """Acceptance + rollback: keep each slot's longest accepted
        prefix (models/sampling.accept_longest_prefix — the shared
        rule), publish the accept-rate evidence, then release every
        page the accepted prefix does not occupy (append-then-truncate:
        rejected-draft KV bytes never stay resident)."""
        alloc = self.sched.allocator
        accepted: dict[str, list[int]] = {}
        drafted_total = 0
        accepted_drafts = 0
        for req in ready:
            d = drafts.get(req.req_id, [])
            acc = sampling.accept_longest_prefix(
                d, ver_np[req.slot][:len(d) + 1])
            accepted[req.req_id] = [int(t) for t in acc]
            drafted_total += len(d)
            accepted_drafts += len(acc) - 1
            req.drafted_tokens += len(d)
            req.accepted_draft_tokens += len(acc) - 1
            # Verify rows past the accepted prefix are rolled back —
            # per-request waste evidence (ISSUE 19), unconditional so
            # request_records reconcile against the ledger.
            req.rejected_tokens += (1 + len(d)) - len(acc)
        self._last_spec = (drafted_total, accepted_drafts)
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            # The attribution rule lives with the acceptance rule
            # (serving/spec.py): accepted rows are useful, live rows
            # past the accepted prefix are spec_rejected, padding
            # columns and empty slots are idle.
            from triton_distributed_tpu.serving.spec import (
                attribute_verify_rows,
            )

            rows = (self._mk.last_step_rows if self._mk is not None
                    else int(ver_np.shape[0]) * int(ver_np.shape[1]))
            split = attribute_verify_rows(
                rows,
                [1 + len(drafts.get(r.req_id, [])) for r in ready],
                [len(accepted[r.req_id]) for r in ready])
            gl.dispatch(rows)
            for cat, n in split.items():
                gl.add(cat, n)
        if self._observing():
            reg = self._reg()
            reg.counter(obs_metrics.SPEC_DRAFT_TOKENS,
                        "draft candidate tokens proposed to verify "
                        "steps").inc(drafted_total)
            reg.counter(obs_metrics.SPEC_ACCEPTED_TOKENS,
                        "draft tokens the greedy verifier accepted"
                        ).inc(accepted_drafts)
            reg.gauge(obs_metrics.SPEC_ACCEPT_RATE,
                      "per-iteration accepted/drafted draft-token ratio "
                      "(1.0 when nothing was drafted — vacuously "
                      "accepted)").set(
                accepted_drafts / drafted_total if drafted_total else 1.0)
        self._decode_tail(ready, accepted, t0, cold)
        for req in ready:
            # FINISHED requests already freed everything (free_tail is a
            # no-op for unknown owners); RUNNING ones shrink to exactly
            # ceil(kv_len / page) — the one-token post-step baseline the
            # occupancy gauge is asserted against.
            alloc.free_tail(req.req_id, -(-req.kv_len // self.page))

    def _decode_tail(self, ready: list[Request], new_tokens: dict,
                     t0: float, cold: bool) -> None:
        """The per-step bookkeeping EVERY decode backend shares (metrics,
        rolling rate, token append/finish) — one copy, so a dense-path
        change can never silently skip the persistent lane.
        ``new_tokens``: req_id → tokens this step produced (singleton
        lists on the one-token paths; 1..k+1 accepted tokens from the
        spec lane — the ledger and the rolling tokens/s gauge count
        exactly what was accepted)."""
        with obs_stepprof.phase("accounting"):
            now = self.clock()
            total = sum(len(v) for v in new_tokens.values())
            rt = obs_reqtrace.get_tracer()
            if rt is not None:
                backend = self.engine.backend
                for req in ready:
                    rt.span(req.req_id, "decode_step", t0, now,
                            backend=backend,
                            tokens=len(new_tokens[req.req_id]))
                    if rt.breakdown(req.req_id) is None:
                        # This request's FIRST decode step: close its
                        # TTFT decomposition window and publish the
                        # components.
                        bd = rt.close_window(req.req_id, now)
                        if bd is not None and self._observing():
                            self._publish_ttft_breakdown(bd)
            if self._observing():
                reg = self._reg()
                reg.counter("tdtpu_tokens_generated_total",
                            "decode tokens generated").inc(total)
                Engine._observe_step(
                    reg, (now - t0) * 1e3, cold,
                    "tdtpu_decode_step_latency_ms",
                    "one decode step, wall (device-synced only in sync "
                    "runs)")
            self.total_tokens += total
            self._rate_events.append((now, total))
            for req in list(ready):
                ts = new_tokens[req.req_id]
                req.tokens.extend(ts)
                req.kv_len += len(ts)
                # Decode appends KV for the consumed positions — the
                # recompute detector's lifetime high-water (ISSUE 19).
                req.computed_high = max(req.computed_high, req.kv_len)
                if req.done:
                    self._finish(req)

    # -- async double-buffered loop (ISSUE 20) --------------------------------
    def _stash_pending(self, kind: str, ready: list[Request], out, t0,
                       *, cold: bool, rows, drafts=None) -> None:
        """Park a dispatched decode/verify launch for the NEXT
        iteration's commit point instead of blocking on it here. The
        launch's outputs (tokens + the already-threaded pool state) are
        device futures; every host-side plan step that runs before the
        commit either touches host structures only or issues jits whose
        operands are the launch's OUTPUT pools — XLA data dependence is
        the fence. The stepprof overlap window opens now: host time
        until the commit closes it is overlap, not bubble."""
        self._pending = {"kind": kind, "ready": list(ready), "out": out,
                         "t0": t0, "cold": bool(cold), "rows": rows,
                         "drafts": drafts}
        sp = obs_stepprof.get_profiler()
        if sp is not None and sp.active():
            sp.overlap_begin(self.clock())

    def _abort_pending(self) -> None:
        """Drop the in-flight launch without committing its tokens —
        every caller (evacuation, device-state rebuild, backend switch)
        preempts the affected requests, so recompute-on-resume replays
        the same greedy stream and parity holds; the launch's tokens
        are simply never observed."""
        if self._pending is None:
            return
        self._pending = None
        sp = obs_stepprof.get_profiler()
        if sp is not None and sp.active():
            sp.overlap_end(self.clock())

    def _commit_pending(self) -> None:
        """The async loop's commit point: block on the decode/verify
        launch stashed LAST iteration and run the tail bookkeeping the
        synchronous loop ran inline. Requests that left RUNNING since
        the dispatch (backend switch, evacuation already abort the whole
        launch; a mid-flight migrate preemption only sheds its own row)
        are dropped — their rows are computed-but-unobserved, exactly a
        sync preemption's waste shape. Failures route by launch kind:
        megakernel faults demote, dense verify faults disable the spec
        lane, dense decode faults go to the fleet machinery — the same
        triage the sync loop does at dispatch."""
        pend = self._pending
        if pend is None:
            return
        self._pending = None
        sp = obs_stepprof.get_profiler()
        if sp is not None and sp.active():
            # Close the overlap window BEFORE blocking: the wait itself
            # is device time, not overlapped host work.
            sp.overlap_end(self.clock())
        kind = pend["kind"]
        try:
            with obs_stepprof.phase("device_wait"):
                out_np = np.asarray(pend["out"])
        except Exception as exc:
            from triton_distributed_tpu import resilience
            from triton_distributed_tpu.resilience import fleet as fleet_mod

            if not resilience.is_transient(exc):
                raise
            alive = [r for r in pend["ready"]
                     if r.state is RequestState.RUNNING]
            if kind in ("mk", "mk_spec"):
                self._mk_decode_failed(alive, exc)
                return
            if (kind == "spec"
                    and fleet_mod.attribute_rank(exc) is None
                    and os.environ.get("TDTPU_DEMOTION_LADDER", "1")
                    != "0"):
                self._spec_disable(
                    f"verify step failed at commit: "
                    f"{type(exc).__name__}: {str(exc)[:120]}")
                return
            # Dense decode (or rank-attributable) transients are the
            # step()-level fleet machinery's to judge, same as sync.
            raise
        alive = [r for r in pend["ready"]
                 if r.state is RequestState.RUNNING]
        if kind in ("spec", "mk_spec"):
            self._spec_tail(alive, pend["drafts"], out_np, pend["t0"],
                            pend["cold"])
            return
        gl = obs_goodput.get_ledger()
        if gl is not None and gl.active():
            gl.dispatch(pend["rows"])
            gl.add("useful", len(alive))
            gl.add("idle", pend["rows"] - len(alive))
        self._decode_tail(
            alive, {r.req_id: [int(out_np[r.slot])] for r in alive},
            pend["t0"], pend["cold"])

    def _publish_gauges(self, reg) -> None:
        reg.gauge(obs_metrics.SERVE_QUEUE_DEPTH,
                  "requests waiting for admission"
                  ).set(len(self.sched.waiting))
        reg.gauge(obs_metrics.SERVE_FREE_PAGES,
                  "free pages in the shared KV pool"
                  ).set(self.sched.allocator.free_count)
        reg.gauge(obs_metrics.SERVE_ACTIVE,
                  "requests prefilling or decoding"
                  ).set(self.sched.active_count)
        reg.gauge(obs_metrics.SERVE_RUNNING_SLOTS,
                  "decode slots occupied by RUNNING sequences this "
                  "iteration").set(len(self.sched.running()))
        usable = max(self.sched.allocator.usable_pages, 1)
        reg.gauge(obs_metrics.KV_POOL_OCCUPANCY,
                  "fraction of usable KV pool pages currently allocated"
                  ).set(1.0 - self.sched.allocator.free_count / usable)
        reg.gauge(obs_metrics.SERVE_ADMIT_CAP,
                  "SLO-driven admission width (slots)"
                  ).set(self.sched.admit_cap)
        reg.gauge(
            obs_metrics.KV_PAGES_RESIDENT,
            "KV pool pages resident at the configured dtype (the fp8-KV "
            "doubled-pool evidence: fixed HBM, half-size page tiles)"
            ).set(self.num_pages)
        reg.gauge(
            obs_metrics.SERVE_TOKENS_PER_S,
            "generated tokens/s — rolling window under ServingEngine, "
            "per-call under Engine.serve").set(self._rolling_rate())
        if self.prefix is not None:
            reg.gauge(
                obs_metrics.PREFIX_PAGES_SHARED,
                "cached prefix pages with live readers beyond the "
                "cache's own pin (refcount > 1)"
                ).set(self.prefix.pages_shared())
            reg.gauge(
                obs_metrics.PREFIX_HIT_RATE,
                "cumulative warm-admission fraction (prefix-index hits "
                "/ lookups)").set(self.prefix.hit_rate())
        # Host-tier lane (ISSUE 20): published UNCONDITIONALLY so every
        # observed serving run carries the series (zeros when no tier is
        # configured) — the report's kv-tier gate keys on presence, and
        # absence should mean "pre-tier run dir", not "tier off".
        tier = self.kvtier
        reg.gauge(obs_metrics.KV_HOST_PAGES,
                  "prefix-chain pages resident in the host-RAM KV tier"
                  ).set(tier.pages if tier is not None else 0)
        reg.gauge(obs_metrics.KV_HOST_BYTES,
                  "host-RAM bytes the KV tier holds (bounded by "
                  "TDTPU_KV_HOST_BUDGET_BYTES)"
                  ).set(tier.bytes_held if tier is not None else 0)
        for name, help_, cur in (
                (obs_metrics.KV_HOST_SWAPOUTS,
                 "evicted prefix-chain pages swapped to host RAM "
                 "instead of physically freed",
                 tier.swap_outs if tier is not None else 0),
                (obs_metrics.KV_HOST_RESTORES,
                 "host-tier pages streamed back into the prefill path "
                 "on warm admissions (swap-ins)",
                 tier.restores if tier is not None else 0),
                (obs_metrics.KV_HOST_EVICTIONS,
                 "chunks the host tier's own LRU dropped to stay "
                 "inside its byte budget",
                 tier.host_evictions if tier is not None else 0),
                (obs_metrics.KV_HOST_RESTORE_FAILURES,
                 "chain restores that failed in a named way "
                 "(checksum / transport) and fell back to cold prefill",
                 tier.restore_failures if tier is not None else 0)):
            # Reconcile the counter to the tier's own stats: swap-outs
            # happen inside the allocator's reclaim hook where no
            # registry is in scope, so event sites cannot inc directly.
            c = reg.counter(name, help_)
            if cur > c.value:
                c.inc(cur - c.value)
        if self.fleet is not None:
            self._publish_fleet_gauges(reg)

    def _rolling_rate(self) -> float:
        """Tokens/s over the trailing window — the throughput the SLO
        watchdog's floor judges (a per-call gauge is meaningless across
        many small interleaved steps — ISSUE 7 satellite)."""
        now = self.clock()
        w = self._rate_window_s
        while self._rate_events and self._rate_events[0][0] < now - w:
            self._rate_events.popleft()
        total = sum(n for _, n in self._rate_events)
        since_start = now - self._t0 if self._t0 is not None else 0.0
        elapsed = min(w, max(since_start, 1e-6))
        return total / max(elapsed, 1e-6)

    def _slo_tick(self) -> None:
        """Admission control from the live SLO watchdog: violation
        streak shrinks the admitted width, clean streak regrows it; the
        section also feeds the engine's demotion ladder (PR 6)."""
        if not self._observing() or self._iter % self.slo_every:
            return
        if not self.sched.has_work():
            # An idle tier violates no one: with a tokens/s floor set,
            # the rolling rate decaying to 0 between arrivals would
            # otherwise accrue a violation streak and shrink admission
            # to 1 with no load present — an inverted feedback.
            return
        try:
            from triton_distributed_tpu import obs
            from triton_distributed_tpu.obs import slo as obs_slo

            section = obs_slo.check_serving(
                self._reg(), run_dir=obs.active_run_dir(),
                cfg=self.slo_cfg, clock=self.clock)
        except Exception as e:   # the watchdog must never cost the serve
            import warnings

            warnings.warn(f"SLO watchdog failed: {type(e).__name__}: {e}",
                          RuntimeWarning, stacklevel=2)
            return
        if section.get("violations", 0):
            self._viol_streak += 1
            self._clean_streak = 0
        else:
            self._clean_streak += 1
            self._viol_streak = 0
        if self._viol_streak >= _env_int("TDTPU_ADMIT_SHRINK_AFTER", 2):
            old_cap = self.sched.admit_cap
            cap = self.sched.shrink_admission()
            self._viol_streak = 0
            with obs_trace.span("serving.admission_shrink", cap=cap):
                pass
            violated = [r["rule"] for r in section.get("rules", ())
                        if r.get("status") == "violation"]
            reason = (f"violation streak shrank admission to {cap} "
                      f"(rules: {', '.join(violated) or 'unknown'})")
            if cap < old_cap:
                self._flight_dump("slo_violation", reason)
            else:
                # Cap already at the floor: one dump per actual
                # narrowing, not one per streak — the chain still
                # records that the violations kept coming.
                self.flight.note("slo_violation", reason, self._iter)
        elif self._clean_streak >= _env_int("TDTPU_ADMIT_GROW_AFTER", 4):
            if self.sched.admit_cap < self.sched.num_slots:
                cap = self.sched.grow_admission()
                with obs_trace.span("serving.admission_grow", cap=cap):
                    pass
            self._clean_streak = 0
        # Cooperate with the backend demotion ladder: the engine consumes
        # the same section its own serve() would have produced.
        self.engine._last_slo_section = section
        self.engine._slo_streak_update()
