"""Iteration-level request scheduler over the paged KV pool.

The vLLM-style continuous-batching schedule (PagedAttention, SOSP'23;
Orca, OSDI'22) as pure host logic — no jax in this module, so every
decision is unit-testable and deterministic:

* **admission** against the free-page budget of the shared
  :class:`~triton_distributed_tpu.models.kv_cache.PageAllocator`:
  a request moves WAITING → PREFILLING only when a decode slot is free,
  the active count is under the (SLO-driven) admission cap, and the pool
  can reserve every page its prompt will scatter into;
* **backpressure**: :meth:`Scheduler.admit` returns
  :data:`AdmitResult.QUEUE_FULL` when the waiting queue is at capacity
  or the page pool is exhausted — callers shed load instead of queueing
  unboundedly;
* **preemption** under page pressure: when a running sequence needs its
  next page and the pool is dry, the lowest-priority (then youngest)
  active sequence is evicted — pages freed, recompute-on-resume
  (its ``prompt + tokens`` re-prefills on re-admission);
* **SLO coupling**: :meth:`shrink_admission` / :meth:`grow_admission`
  move the admission cap; the serving loop drives them from the live
  SLO watchdog's violation/clean streaks (obs/slo.py).

The loop (serving/loop.py) calls, per iteration:
``schedule_admissions`` → ``prefill_head`` (one chunk slice) →
``ensure_decode_pages`` → decode the ready batch — one *mixed* step.
"""

from __future__ import annotations

import enum

from triton_distributed_tpu.models.kv_cache import PageAllocator
from triton_distributed_tpu.serving.request import Request, RequestState


class AdmitResult(enum.Enum):
    ADMITTED = "admitted"
    QUEUE_FULL = "queue_full"


class SchedulerConfigError(ValueError):
    """A scheduler sizing parameter is invalid — named, up front."""


class RequestTooLargeError(ValueError):
    """The request can never fit its sequence's page budget — rejected
    at admission (named), not discovered mid-decode."""


class Scheduler:
    """Host-side continuous-batching scheduler state machine."""

    def __init__(self, *, num_slots: int, allocator: PageAllocator,
                 page_size: int, capacity_tokens: int,
                 max_waiting: int = 64, on_event=None, prefix=None):
        if num_slots < 1:
            raise SchedulerConfigError(
                f"num_slots = {num_slots} invalid: the decode batch needs "
                "at least one slot — argument num_slots (ServingEngine "
                "max_batch)")
        if max_waiting < 1:
            raise SchedulerConfigError(
                f"max_waiting = {max_waiting} invalid: the waiting queue "
                "needs at least one entry — argument max_waiting")
        if capacity_tokens < 1:
            raise SchedulerConfigError(
                f"capacity_tokens = {capacity_tokens} invalid — derived "
                "from max_pages * page_size and the prefill buffer; check "
                "ServingEngine's engine.max_seq / page_size arguments")
        self.num_slots = num_slots
        self.allocator = allocator
        self.page_size = page_size
        self.capacity_tokens = capacity_tokens
        self.max_waiting = max_waiting
        # Lifecycle observer (ISSUE 13): called as on_event(req, kind)
        # for kind in {"prefilling", "preempted", "finished"} right
        # after the transition lands. The serving loop timestamps these
        # into the request tracer (obs/reqtrace.py); a failing observer
        # must never break scheduling, so calls are exception-guarded.
        self.on_event = on_event
        # Prefix cache (serving/prefix.py, docs/serving.md "Prefix
        # cache"): consulted at admission so a warm request shares the
        # resident pages covering its prompt prefix (+1 ref each) and
        # reserves fresh pages only for the divergent suffix. None = the
        # pre-prefix admission path, byte-identical.
        self.prefix = prefix
        self.admit_cap = num_slots       # SLO-driven admission width
        self.waiting: list[Request] = []
        self.active: list[Request] = []  # PREFILLING + RUNNING, admit order
        self._free_slots = set(range(num_slots))
        self._seq = 0

    def _notify(self, req: Request, kind: str) -> None:
        if self.on_event is None:
            return
        try:
            self.on_event(req, kind)
        except Exception as exc:
            import warnings

            warnings.warn(
                f"scheduler on_event observer failed for {req.req_id} "
                f"({kind}): {type(exc).__name__}: {exc}", RuntimeWarning,
                stacklevel=3)

    # -- views --------------------------------------------------------------
    @property
    def active_count(self) -> int:
        return len(self.active)

    def running(self) -> list[Request]:
        return [r for r in self.active if r.state is RequestState.RUNNING]

    def has_work(self) -> bool:
        return bool(self.waiting or self.active)

    # -- admission -----------------------------------------------------------
    def admit(self, req: Request, now: float) -> AdmitResult:
        """Queue a new request, or refuse it (backpressure). Raises
        :class:`RequestTooLargeError` for a request that can NEVER be
        served with this pool geometry — that is a sizing error, not
        load."""
        if req.final_kv_len > self.capacity_tokens:
            raise RequestTooLargeError(
                f"request {req.req_id}: prompt {len(req.prompt)} + "
                f"max_new_tokens {req.max_new_tokens} needs "
                f"{req.final_kv_len} KV positions, over the per-sequence "
                f"capacity {self.capacity_tokens} (max_pages * page_size, "
                "bounded by the prefill buffer) — reject up front rather "
                "than dying mid-generation")
        if req.page_budget(self.page_size) > self.allocator.usable_pages:
            raise RequestTooLargeError(
                f"request {req.req_id} needs "
                f"{req.page_budget(self.page_size)} pages at completion "
                f"but the whole pool holds {self.allocator.usable_pages} "
                f"usable (num_pages {self.allocator.num_pages} minus "
                f"{len(self.allocator.reserved)} reserved) — it could "
                "only ever cycle through self-preemption")
        if len(self.waiting) >= self.max_waiting:
            return AdmitResult.QUEUE_FULL
        if (self.allocator.free_count == 0
                and self.allocator.reclaimable() == 0):
            # Pool exhausted: nothing admitted from the queue can make
            # progress, so shed load at the door instead of queueing.
            # Cold cached prefix chains count as available capacity —
            # the allocator's reclaim hook evicts them on demand.
            return AdmitResult.QUEUE_FULL
        if req.arrival_seq < 0:
            req.arrival_seq = self._seq
            self._seq += 1
            req.t_arrival = now
        self.waiting.append(req)
        return AdmitResult.ADMITTED

    def _pick_waiting(self) -> Request | None:
        if not self.waiting:
            return None
        # Highest priority first; FIFO (original admission order) within.
        return min(self.waiting, key=lambda r: (-r.priority, r.arrival_seq))

    def schedule_admissions(self) -> list[Request]:
        """WAITING/PREEMPTED → PREFILLING while a slot is free, the
        admission cap has room, and the pool can reserve the full
        prefill scatter (ceil(len(text)/page) pages). With a prefix
        cache attached, the request's prompt is matched against the
        radix index first: hit pages are SHARED (+1 ref each, no fresh
        allocation, no re-prefill) and only the divergent suffix
        reserves fresh pages — ``req.prefix_hit_tokens`` records where
        the prefill restarts."""
        admitted: list[Request] = []
        while (self.waiting and self._free_slots
               and self.active_count < self.admit_cap):
            req = self._pick_waiting()
            n_pages = max(1, -(-len(req.text) // self.page_size))
            hit, full, partial = (self.prefix.match(req.text)
                                  if self.prefix is not None
                                  else (0, [], None))
            # Host-tier extension (ISSUE 20): chunks evicted to host RAM
            # can extend a page-aligned device hit — the chain keys are
            # noted here and the serving loop streams their bytes into
            # the prefill buffer before the gather. The restored
            # positions still allocate FRESH device pages (they are part
            # of the suffix reservation below), so the page budget is
            # unchanged; only the prefill compute is skipped. A partial
            # tail match already covers more positions than the aligned
            # tier walk could, so the two are mutually exclusive.
            tier = self.prefix.host_tier if self.prefix is not None else None
            tier_keys = (tier.match(req.text, hit)
                         if tier is not None and partial is None else [])
            if partial is not None:
                # Pin BEFORE the suffix allocation: a cold (cache-only)
                # partially-matched page is otherwise evictable by the
                # reclaim hook alloc_pages may invoke, and pinning a
                # physically-freed page is a PageRefError. The read-hold
                # lasts until the COW at prefill-complete (or a
                # preemption) releases it.
                self.prefix.pin(partial)
            if full:
                self.allocator.share_pages(req.req_id, full)
            if self.allocator.alloc_pages(req.req_id,
                                          n_pages - len(full)) is None:
                # Undo the holds: stays queued whole.
                if partial is not None:
                    self.prefix.unpin(partial)
                if full:
                    self.allocator.free_pages(req.req_id)
                break                # pool short: stays queued
            restored = len(tier_keys) * self.page_size
            req.prefix_hit_tokens = hit + restored
            req.restored_tokens = restored
            req._kvtier_pending = list(tier_keys)
            if req.prefix_hit_tokens:
                req.prefix_hit_tokens_total += req.prefix_hit_tokens
            if self.prefix is not None:
                # Stats + recency move only on the COMMITTED admission
                # (match is a read-only probe — see PrefixCache.match).
                # DEVICE hit only: host-tier recency moves when the
                # chunks actually restore.
                self.prefix.commit_match(req.text, hit)
            if partial is not None:
                req._prefix_partial = partial
            self.waiting.remove(req)
            req.slot = min(self._free_slots)
            self._free_slots.discard(req.slot)
            req.prefill_pos = 0
            req.kv_len = 0
            req.advance(RequestState.PREFILLING)
            self.active.append(req)
            admitted.append(req)
            self._notify(req, "prefilling")
        return admitted

    def prefill_head(self) -> Request | None:
        """The one request whose prefill advances this iteration (oldest
        admitted first — slices of later admissions queue behind it, so
        the shared prefill buffer only ever holds one partial prompt)."""
        for r in self.active:
            if r.state is RequestState.PREFILLING:
                return r
        return None

    # -- preemption / page growth -------------------------------------------
    def _preempt(self, req: Request) -> None:
        self.allocator.free_pages(req.req_id)
        if req._prefix_partial is not None:
            # Drop the partial-page read hold; shared full pages were
            # released by free_pages (their other readers keep theirs).
            self.prefix.unpin(req._prefix_partial)
            req._prefix_partial = None
        req.prefix_hit_tokens = 0    # re-admission re-matches the index
        req.restored_tokens = 0      # host-tier chunks re-match too
        req._kvtier_pending = []
        if req.slot is not None:
            self._free_slots.add(req.slot)
        req.slot = None
        req.kv_len = 0
        req.prefill_pos = 0
        req.preemptions += 1
        req.advance(RequestState.PREEMPTED)
        self.active.remove(req)
        self.waiting.append(req)
        self._notify(req, "preempted")

    def _victim(self) -> Request | None:
        """Lowest priority, then youngest (latest admission) — the
        sequence whose recompute costs the least seniority."""
        if not self.active:
            return None
        return min(self.active, key=lambda r: (r.priority, -r.arrival_seq))

    def ensure_decode_pages(self, extra: dict | None = None
                            ) -> tuple[list[Request], list[Request]]:
        """Grow each running sequence's page allotment to cover its next
        KV write, preempting under page pressure. Returns
        (ready-to-decode requests in slot order, preempted victims).

        ``extra`` maps req_id → tokens this step will append (default 1
        everywhere) — the speculative-decode lane reserves its whole
        candidate window (1 + drafted) up front and rolls the unused
        tail back after acceptance (``PageAllocator.free_tail``)."""
        preempted: list[Request] = []
        ready: list[Request] = []
        for req in sorted(self.running(), key=lambda r: r.slot):
            if req.state is not RequestState.RUNNING:
                continue             # preempted by an earlier slot's growth
            ok = True
            need = 1 if extra is None else max(1, extra.get(req.req_id, 1))
            while len(self.allocator.pages(req.req_id)) \
                    < req.pages_needed(self.page_size, extra=need):
                if self.allocator.alloc_pages(req.req_id, 1) is not None:
                    continue
                victim = self._victim()
                if victim is None or victim is req:
                    # Nothing lower-priority to evict: this sequence
                    # yields its own pages and resumes later.
                    self._preempt(req)
                    preempted.append(req)
                    ok = False
                    break
                self._preempt(victim)
                preempted.append(victim)
                if victim in ready:
                    ready.remove(victim)
            if ok:
                ready.append(req)
        return ready, preempted

    # -- completion ----------------------------------------------------------
    def finish(self, req: Request, now: float) -> None:
        self.allocator.free_pages(req.req_id)
        if req._prefix_partial is not None:   # defensive: COW unpins first
            self.prefix.unpin(req._prefix_partial)
            req._prefix_partial = None
        if req.slot is not None:
            self._free_slots.add(req.slot)
        req.slot = None
        req.t_finish = now
        req.advance(RequestState.FINISHED)
        if req in self.active:
            self.active.remove(req)
        self._notify(req, "finished")

    # -- SLO-driven admission width ------------------------------------------
    def shrink_admission(self) -> int:
        """Violation streak: narrow the admitted batch (never below 1 —
        a fully closed door would deadlock the queue)."""
        self.admit_cap = max(1, min(self.admit_cap, self.num_slots) - 1)
        return self.admit_cap

    def grow_admission(self) -> int:
        """Clean streak: re-open one slot of admission width."""
        self.admit_cap = min(self.num_slots, self.admit_cap + 1)
        return self.admit_cap
