"""Request lifecycle for the continuous-batching serving tier.

A :class:`Request` is one user generation job moving through the
iteration-level schedule (docs/serving.md)::

    WAITING ──▶ PREFILLING ──▶ RUNNING ──▶ FINISHED
                    ▲              │
                    └─ PREEMPTED ◀─┘   (pages freed; recompute-on-resume)

The disaggregated tier (docs/disagg.md) inserts MIGRATING between
PREFILLING and RUNNING: a finished prefill's paged KV blocks stream from
the prefill slice's pool to the decode slice's over DCN, and only the
completed migration joins the decode batch. A migration can be preempted
mid-stream (decode-pool pressure or a migration fault) — the stream is
cancelled, decode pages freed, recompute-on-resume like any preemption::

    PREFILLING ──▶ MIGRATING ──▶ RUNNING
                       │
                       └──▶ PREEMPTED

State transitions are validated (:meth:`Request.advance` raises on an
illegal edge), timestamps are stamped by the serving loop through the
clock it owns (arrival, first token, finish — the TTFT/TPOT source), and
the page-budget accounting view (:meth:`Request.page_budget`,
:meth:`Request.pages_needed`) is what the scheduler admits and grows
against.

Token bookkeeping: ``tokens`` holds every generated token (the first one
comes from prefill logits, like ``Engine.serve``); ``text`` is
``prompt + tokens`` — the ids whose KV a (re)compute must cover, so a
preempted request resumes by prefilling ``text`` and the final slice's
logits yield its NEXT token (identical math to the decode step it
replaces: both see KV for exactly ``len(text)`` positions).
"""

from __future__ import annotations

import dataclasses
import enum
import itertools


class RequestState(enum.Enum):
    WAITING = "waiting"
    PREFILLING = "prefilling"
    MIGRATING = "migrating"          # disagg tier only (docs/disagg.md)
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"


_EDGES: dict[RequestState, tuple[RequestState, ...]] = {
    RequestState.WAITING: (RequestState.PREFILLING,),
    RequestState.PREFILLING: (RequestState.MIGRATING, RequestState.RUNNING,
                              RequestState.PREEMPTED,
                              RequestState.FINISHED),
    RequestState.MIGRATING: (RequestState.RUNNING, RequestState.PREEMPTED),
    RequestState.RUNNING: (RequestState.PREEMPTED, RequestState.FINISHED),
    RequestState.PREEMPTED: (RequestState.PREFILLING,),
    RequestState.FINISHED: (),
}

_IDS = itertools.count()


def _next_id() -> str:
    return f"req-{next(_IDS)}"


@dataclasses.dataclass
class Request:
    """One generation job. ``priority``: higher = preempted later (the
    scheduler evicts the lowest-priority, youngest sequence first)."""

    prompt: list[int]
    max_new_tokens: int
    priority: int = 0
    req_id: str = dataclasses.field(default_factory=_next_id)

    state: RequestState = RequestState.WAITING
    tokens: list[int] = dataclasses.field(default_factory=list)
    slot: int | None = None          # decode-batch row while active
    kv_len: int = 0                  # positions currently in the paged pool
    prefill_pos: int = 0             # tokens of ``text`` prefilled (attempt)
    preemptions: int = 0
    migrations: int = 0              # completed KV migrations (disagg tier)
    evacuations: int = 0             # fleet preempt-alls this request rode
    drafted_tokens: int = 0          # spec lane: draft candidates proposed
    accepted_draft_tokens: int = 0   # spec lane: drafts the verifier kept
    # Prefix-reuse lane (docs/serving.md "Prefix cache"): tokens of the
    # CURRENT admission's prompt covered by shared resident pages (the
    # prefill work skipped), and the cumulative total across
    # re-admissions — the per-request warm-serve evidence loadgen's
    # request_records carries.
    prefix_hit_tokens: int = 0       # this admission's hit (reset on preempt)
    prefix_hit_tokens_total: int = 0
    # Host-tier lane (ISSUE 20, serving/kvtier.py): tokens of THIS
    # admission's hit that live in host RAM rather than device pages —
    # a subset of prefix_hit_tokens; the serving loop streams their
    # chunks back into the prefill buffer before the gather. The
    # cumulative total is the per-request swap-in evidence
    # request_records carries.
    restored_tokens: int = 0         # this admission (reset on preempt)
    restored_tokens_total: int = 0   # chunks that actually streamed back
    # Host-tier chain keys awaiting restore for this admission (set by
    # the scheduler, consumed by the loop's _kvtier_restore).
    _kvtier_pending: list = dataclasses.field(default_factory=list)
    # Goodput / waste-attribution lane (ISSUE 19, obs/goodput.py): the
    # per-request halves of the work ledger's recompute/spec_rejected
    # categories — loadgen's request_records reconcile their sums
    # against the ledger aggregates. ``computed_high`` is the lifetime
    # high-water of computed KV positions (it survives preemption,
    # unlike kv_len/prefill_pos) — re-prefilled rows below it are
    # recompute, above it cold useful work.
    recompute_tokens: int = 0        # re-prefilled rows of lost KV
    rejected_tokens: int = 0         # verify rows past the accepted prefix
    computed_high: int = 0           # recompute detector (never resets)
    _prefix_partial: int | None = None   # pinned partially-matched page
    final_backend: str | None = None  # engine backend at finish time
    arrival_seq: int = -1            # admission order stamp (scheduler)

    t_arrival: float | None = None
    t_first_token: float | None = None
    t_finish: float | None = None

    def __post_init__(self):
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens = {self.max_new_tokens} invalid: a "
                "request must generate at least one token — argument "
                "max_new_tokens")
        if len(self.prompt) < 1:
            raise ValueError("empty prompt: a request needs at least one "
                             "prompt token — argument prompt")

    # -- lifecycle ---------------------------------------------------------
    def advance(self, new: RequestState) -> None:
        if new not in _EDGES[self.state]:
            raise ValueError(
                f"illegal request transition {self.state.name} -> "
                f"{new.name} for {self.req_id} (valid: "
                f"{[s.name for s in _EDGES[self.state]]})")
        self.state = new

    @property
    def done(self) -> bool:
        return len(self.tokens) >= self.max_new_tokens

    @property
    def wasted_tokens(self) -> int:
        """Device token-rows this request burned beyond its useful work
        (ISSUE 19): recompute-on-resume re-prefills + rejected verify
        rows. COW/migration overhead is pool-level, not per-request."""
        return self.recompute_tokens + self.rejected_tokens

    # -- page-budget accounting view --------------------------------------
    @property
    def text(self) -> list[int]:
        """prompt + generated so far — what a (re)compute prefills."""
        return list(self.prompt) + list(self.tokens)

    @property
    def final_kv_len(self) -> int:
        """KV positions at completion: the last generated token's KV is
        never written (no decode step consumes it)."""
        return len(self.prompt) + self.max_new_tokens - 1

    def page_budget(self, page_size: int) -> int:
        """Pages this request can ever hold — what admission checks
        against the per-sequence ``max_pages`` row capacity."""
        return -(-self.final_kv_len // page_size)

    def pages_needed(self, page_size: int, extra: int = 0) -> int:
        """Pages required to hold ``kv_len + extra`` positions — the
        decode loop asks with ``extra=1`` (the next write target)."""
        return -(-(self.kv_len + extra) // page_size)

    # -- latency view ------------------------------------------------------
    @property
    def ttft_s(self) -> float | None:
        if self.t_first_token is None or self.t_arrival is None:
            return None
        return self.t_first_token - self.t_arrival

    @property
    def tpot_s(self) -> float | None:
        """Mean time per output token after the first (None until
        finished or with a single token)."""
        if (self.t_finish is None or self.t_first_token is None
                or len(self.tokens) < 2):
            return None
        return (self.t_finish - self.t_first_token) / (len(self.tokens) - 1)
