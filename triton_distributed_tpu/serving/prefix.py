"""Prefix-reuse subsystem: radix-indexed copy-on-write page sharing.

ROADMAP open item #1 — the single biggest TTFT lever a production fleet
has: millions of users share system prompts, few-shot preambles and
conversation history, yet a naive serving tier re-prefills every
admission from token 0. The PR-7 page-table indirection makes sharing a
refcount away (vLLM's PagedAttention showed block-level KV sharing;
SGLang's RadixAttention showed a radix tree over token prefixes is the
right index for automatic multi-tenant reuse):

* a **radix/trie index** maps token-id prefixes (page-granularity
  chunks, plus a partial-tail extension inside the next chunk) to
  RESIDENT pool page ids. Consulted at admission, so a warm request
  only prefills its divergent suffix — the hit pages are shared
  (``PageAllocator.share_pages``: +1 reference each) and the prefill
  restarts at ``hit_tokens``;
* **refcounted pages**: share = +ref, free = −ref, physical free only
  at zero — preempting or finishing one sharer can never free bytes
  another request (or the cache itself) still reads;
* **copy-on-write**: a shared page that would be WRITTEN (the divergent
  suffix landing inside a partially-matched page, or any append whose
  target still carries other readers) is first replaced by a private
  copy (``PageAllocator.cow_page`` + a one-page pool copy) and the
  request's table row rewritten — on both the xla paged path and the
  megakernel paged workspace (tables are data there, so COW is a
  host-side row rewrite + one page-tile copy);
* **eviction ordered by refcount×recency**: the cache holds one
  reference per indexed page, so hot shared chains (live sharers →
  refcount > 1) are never evictable, and among cold cache-only pages
  the least-recently-matched LEAVES release first. Eviction is wired
  into the allocator's ``reclaim`` hook, so the scheduler's admission
  budget and page growth see cached-cold pages as available capacity.

This module is PURE HOST logic (no jax): the gather/scatter/page-copy
jits live in serving/loop.py and megakernel/serving.py. Determinism:
the index, eviction order and hit scoring depend only on token ids and
a logical clock, so seeded serving runs replay bit-identically.
"""

from __future__ import annotations

from triton_distributed_tpu.models.kv_cache import PageAllocator


class PrefixConfigError(ValueError):
    """A prefix-cache parameter is invalid — named, up front (the
    ``_check_decode_step_config`` style)."""


class _Node:
    """One page-granularity chunk of a cached token chain."""

    __slots__ = ("page", "children", "last_use")

    def __init__(self, page: int, clock: int):
        self.page = page
        self.children: dict[tuple, _Node] = {}
        self.last_use = clock


class PrefixCache:
    """Radix index + refcount pins over a serving tier's page pool.

    One per :class:`~triton_distributed_tpu.serving.loop.ServingEngine`
    (``prefix_cache=True``). The cache owns one allocator reference per
    indexed page (``incref`` at insert), releases it at eviction or
    invalidation, and registers itself as the allocator's
    ``reclaim``/``reclaimable`` hooks so pool-pressure paths (admission
    reservation, decode page growth, COW) evict cold chains instead of
    shedding load.

    Content addressing: page ``i`` of a chain holds KV for positions
    ``[i*page_size, (i+1)*page_size)`` of some token sequence, and KV at
    a position depends only on the tokens at and before it — so a chunk
    chain keyed by token ids is valid for ANY request whose prompt
    starts with those tokens. A partial tail match (the first ``r``
    tokens of the next chunk) shares that page read-only: its first
    ``r`` positions are valid, and the first divergent write triggers
    COW.
    """

    def __init__(self, allocator: PageAllocator, page_size: int):
        if page_size < 1:
            raise PrefixConfigError(
                f"page_size = {page_size} invalid: prefix chunks are "
                "pages — argument page_size")
        self.allocator = allocator
        self.page_size = page_size
        self._root = _Node(-1, 0)
        self._clock = 0
        self._pages: set[int] = set()     # pages the cache holds a ref on
        # pages_shared memo: the scan over _pages is O(pool) and sits on
        # the per-iteration serving path, but its inputs only change
        # when a refcount moves (allocator.ref_epoch) — most decode
        # iterations reuse the cached value.
        self._shared_memo = (-1, 0)       # (ref_epoch, value)
        # match/commit_match walk memo: the scheduler probes then
        # commits the SAME prompt within one admission, so the second
        # radix walk is redundant unless the tree changed in between.
        self._tree_epoch = 0
        self._walk_memo = None            # (tokens obj, tree_epoch, walk)
        # Evidence (obs satellite): lookups/hits are per-admission,
        # tokens_saved is the prefill work warm admissions skipped.
        self.lookups = 0
        self.hits = 0
        self.tokens_saved = 0
        self.evictions = 0
        self.pages_shared_peak = 0
        # Coverage event hook (ISSUE 17): the fleet router subscribes
        # here to shadow WHICH prefixes this tier has resident — fed by
        # index/hit/invalidate events, never by probing device state.
        # Called as on_event(kind, tokens) with kind in
        # {"insert", "hit", "invalidate"} (tokens is None for
        # invalidate). Must never cost a serve: failures propagate to
        # the subscriber, not swallowed here.
        self.on_event = None
        # Host-RAM second tier (ISSUE 20): when set, reclaim() copies
        # each cache-only chunk to host RAM before the decref that
        # physically frees it — see attach_host_tier().
        self.host_tier = None
        allocator.reclaim = self.reclaim
        allocator.reclaimable = self.reclaimable

    def attach_host_tier(self, tier) -> None:
        """Install a :class:`~triton_distributed_tpu.serving.kvtier.
        HostKVTier`: evicted cache-only chunks are swapped to host RAM
        (at stored width, checksum-stamped) instead of dying with the
        decref, and the serving loop restores them on a later radix
        hit. The tier's entries are content-addressed by full token
        chains, so they stay valid across device page reuse — but NOT
        across :meth:`invalidate` (which clears the tier too: a device
        rebuild may change mesh geometry, and restored bytes must be
        bit-exact with what a cold prefill would produce)."""
        self.host_tier = tier

    def note_peak(self) -> int:
        """Sample the live shared-page count into the peak stat (the
        serving loop calls this each iteration — the dryrun's
        nonzero-shared-pages evidence)."""
        s = self.pages_shared()
        if s > self.pages_shared_peak:
            self.pages_shared_peak = s
        return s

    # -- views ---------------------------------------------------------------
    @property
    def pages_held(self) -> int:
        """Pages the cache currently pins resident."""
        return len(self._pages)

    def pages_shared(self) -> int:
        """Cached pages with live readers beyond the cache's own pin
        (refcount > 1) — the ``tdtpu_prefix_pages_shared`` gauge.
        Memoized on the allocator's refcount epoch: the O(pages_held)
        scan only reruns after a refcount actually moved, so pure
        decode iterations pay one integer compare."""
        epoch = self.allocator.ref_epoch
        if self._shared_memo[0] != epoch:
            self._shared_memo = (epoch, sum(
                1 for p in self._pages
                if self.allocator.ref_count(p) > 1))
        return self._shared_memo[1]

    def hit_rate(self) -> float:
        """Cumulative warm-admission fraction (0.0 before any lookup)."""
        return self.hits / self.lookups if self.lookups else 0.0

    # -- index ---------------------------------------------------------------
    def _chunks(self, tokens) -> list[tuple]:
        ps = self.page_size
        toks = [int(t) for t in tokens]
        return [tuple(toks[i:i + ps])
                for i in range(0, len(toks) - ps + 1, ps)]

    def insert(self, tokens, pages) -> int:
        """Index the FULL pages of ``tokens`` (a request whose prefill
        just scattered them — ``pages[i]`` holds positions
        ``[i*page, (i+1)*page)``). New nodes pin their page (+1 ref);
        an existing node keeps its page (first chain wins — both hold
        identical bytes by content addressing). Returns the number of
        pages newly indexed."""
        self._clock += 1
        node = self._root
        added = 0
        for i, chunk in enumerate(self._chunks(tokens)):
            child = node.children.get(chunk)
            if child is None:
                page = int(pages[i])
                if self.allocator.ref_count(page) < 1:
                    # Never index a page with no live holder: the chain
                    # under insertion must still own it.
                    break
                self.allocator.incref(page)
                self._pages.add(page)
                child = _Node(page, self._clock)
                node.children[chunk] = child
                self._tree_epoch += 1
                added += 1
            child.last_use = self._clock
            node = child
        if self.on_event is not None:
            # Full token chain, not just the newly-added tail: coverage
            # includes the pre-existing shared spine of the chain.
            self.on_event("insert", [int(t) for t in tokens])
        return added

    def _walk(self, tokens):
        """Longest resident prefix of ``tokens`` capped at
        ``len(tokens) - 1``: (hit_tokens, full_pages, partial_page,
        matched nodes). Pure read — no recency or stat mutation."""
        toks = [int(t) for t in tokens]
        cap = len(toks) - 1
        node = self._root
        full: list[int] = []
        nodes: list[_Node] = []
        ps = self.page_size
        pos = 0
        while pos + ps <= cap:
            chunk = tuple(toks[pos:pos + ps])
            child = node.children.get(chunk)
            if child is None:
                break
            full.append(child.page)
            nodes.append(child)
            node = child
            pos += ps
        # Partial tail: the longest common prefix between the remaining
        # tokens and any child chunk — that child's page holds valid KV
        # for exactly those positions (a divergent suffix inside the
        # page is the canonical COW trigger). Ties keep the first
        # (insertion-ordered) child: deterministic under a fixed seed.
        partial = None
        rem = toks[pos:cap]
        if rem:
            best = 0
            best_child = None
            for chunk, child in node.children.items():
                length = 0
                for a, b in zip(rem, chunk):
                    if a != b:
                        break
                    length += 1
                if length > best:
                    best = length
                    best_child = child
            if best_child is not None:
                nodes.append(best_child)
                partial = best_child.page
                pos += best
        return pos, full, partial, nodes

    def match(self, tokens) -> tuple[int, list[int], int | None]:
        """Longest resident prefix of ``tokens``, capped at
        ``len(tokens) - 1`` (at least one token must prefill — its
        logits produce the next token). Returns ``(hit_tokens,
        full_pages, partial_page)``:

        * ``full_pages`` — shared whole pages covering
          ``hit_tokens // page_size`` chunks (share these);
        * ``partial_page`` — a page whose first ``hit_tokens % page``
          positions are valid (pin read-only; the suffix write into it
          COWs), or None when the hit is page-aligned.

        READ-ONLY: a scheduler may probe the same queued request every
        iteration while the pool is short, so recency and the
        hit/lookup stats move only on :meth:`commit_match` (the
        committed admission) — otherwise a stuck request would inflate
        the hit rate and distort the recency eviction order."""
        walk = self._walk(tokens)
        # Remember the walk for the commit that typically follows in
        # the same admission (keyed by object identity — holding the
        # prompt list keeps its id stable — and tree shape).
        self._walk_memo = (tokens, self._tree_epoch, walk)
        pos, full, partial, _nodes = walk
        return pos, full, partial

    def commit_match(self, tokens, hit_tokens: int) -> None:
        """Record an ADMITTED lookup: one lookup (one hit when
        ``hit_tokens`` > 0), ``tokens_saved`` grows by the shared
        tokens, and recency bumps along the matched path. Note
        ``tokens_saved`` counts tokens covered by shared pages at
        admission; the ``tdtpu_prefill_tokens_saved_total`` counter
        counts the chunk-aligned prefill work actually skipped — the
        partial-page tail recomputes into the buffer, so the counter
        can trail this stat by up to a chunk per admission."""
        self._clock += 1
        self.lookups += 1
        if hit_tokens > 0:
            self.hits += 1
            self.tokens_saved += hit_tokens
            memo = self._walk_memo
            if (memo is not None and memo[0] is tokens
                    and memo[1] == self._tree_epoch):
                nodes = memo[2][3]
            else:
                nodes = self._walk(tokens)[3]
            for node in nodes:
                node.last_use = self._clock
            if self.on_event is not None:
                self.on_event("hit", [int(t) for t in tokens][:hit_tokens])

    # -- pins (partial-page read holds) --------------------------------------
    def pin(self, page: int) -> None:
        """Read-hold on a partially-matched page between admission and
        the COW at prefill-complete (+1 ref, outside any owner list)."""
        self.allocator.incref(page)

    def unpin(self, page: int) -> None:
        self.allocator.decref(page)

    # -- eviction ------------------------------------------------------------
    def _evictable(self) -> list[tuple[int, _Node, _Node, tuple, tuple]]:
        """(last_use, node, parent, key, chain) for every LEAF whose
        page only the cache holds (refcount == 1): releasing anything
        else either frees nothing (live sharers) or breaks a deeper
        chain. ``chain`` is the full token prefix through the leaf —
        the host tier's content address for the chunk (eviction is
        leaf-first, so deep chunks swap out first and the tier's
        chunk-by-chunk walk re-assembles chains from any device-resident
        boundary)."""
        out = []

        def walk(parent, prefix):
            for key, node in parent.children.items():
                chain = prefix + key
                if node.children:
                    walk(node, chain)
                elif self.allocator.ref_count(node.page) == 1:
                    out.append((node.last_use, node, parent, key, chain))

        walk(self._root, ())
        return out

    def reclaim(self, n: int) -> int:
        """Release up to ``n`` pages back to the pool, coldest evictable
        leaves first (refcount×recency: pages with live sharers are
        never candidates, so hot shared prefixes outlive cold private
        tails by construction). Evicting a leaf can expose its parent
        as the next candidate, so the scan repeats until satisfied or
        dry. Returns the count physically freed."""
        freed = 0
        while freed < n:
            cands = self._evictable()
            if not cands:
                break
            cands.sort(key=lambda c: c[0])
            for _, node, parent, key, chain in cands:
                if freed >= n:
                    break
                if self.host_tier is not None:
                    # Second chance BEFORE the decref frees the bytes:
                    # the fetch must read the pool page while the cache
                    # still owns it. A refused swap (tier disabled,
                    # over-budget chunk) just means the chunk dies the
                    # old way.
                    if self.host_tier.swap_out(chain, node.page):
                        self.allocator.note_swap("swap_out", node.page)
                del parent.children[key]
                self._tree_epoch += 1
                self._pages.discard(node.page)
                if self.allocator.decref(node.page):
                    freed += 1
                self.evictions += 1
        return freed

    def reclaimable(self) -> int:
        """Pages :meth:`reclaim` could free right now — admission
        counts them as available capacity. Conservative: every cached
        page with no live sharer frees once its subtree of cold
        descendants goes with it, so the count is all cache-only
        pages."""
        return sum(1 for p in self._pages
                   if self.allocator.ref_count(p) == 1)

    def invalidate(self) -> int:
        """Drop the whole index and every cache reference — REQUIRED
        whenever the pool bytes stop being the indexed content (device
        rebuild, evacuation, a fresh megakernel workspace): a stale hit
        would serve garbage KV. Live sharers keep their own references;
        the cache simply stops advertising the chains. Returns the
        count of references released."""
        released = 0
        for p in sorted(self._pages):
            self.allocator.decref(p)
            released += 1
        self._pages.clear()
        self._root = _Node(-1, self._clock)
        self._tree_epoch += 1
        self._walk_memo = None
        if self.host_tier is not None:
            # Host copies predate whatever forced the invalidation
            # (mesh-geometry change, fresh workspace) — restoring them
            # could break bit-exact parity with a cold prefill.
            self.host_tier.clear()
        if self.on_event is not None:
            self.on_event("invalidate", None)
        return released
