"""Self-drafting speculative-decode proposer for the serving tier.

Prompt-lookup / n-gram drafting (Saxena 2023, "Prompt Lookup Decoding";
the self-drafting arm of Leviathan et al. 2023): candidate tokens are
proposed from the request's OWN history — find the most recent earlier
occurrence of the trailing n-gram of ``prompt + generated`` and propose
the tokens that followed it. No second model, no device work, and fully
deterministic, so a seeded serving run with drafting on replays
bit-identically (the repo's token-parity oracle culture extends to the
draft stream).

The verify side lives in ``models/dense.dense_verify_step_paged`` (xla)
and the megakernel draft-and-verify queue rows
(``megakernel/serving.PagedMegakernelDecoder(spec_window=...)``);
acceptance is ``models/sampling.accept_longest_prefix`` — greedy
verification makes the whole lane lossless (docs/serving.md
"Speculative decode").
"""

from __future__ import annotations

import os


class SpecConfigError(ValueError):
    """A speculative-decode parameter is invalid — named, up front (the
    ``_check_decode_step_config`` style)."""


def attribute_verify_rows(rows: int, wins, accepted) -> dict[str, int]:
    """Goodput attribution for ONE draft-and-verify launch (ISSUE 19,
    obs/goodput.py taxonomy): ``rows`` is the launch's total dispatched
    token-rows (B × W — every slot pays the full compiled window),
    ``wins`` the live per-slot candidate windows (1 + draft length) and
    ``accepted`` the per-slot accepted token counts (longest accepted
    prefix + the bonus token). The rule lives HERE, next to the
    acceptance rule it mirrors, so the serving loop and the tests share
    one definition:

    * accepted rows committed output → ``useful``;
    * live rows past the accepted prefix (rolled back by the
      append-then-truncate discipline) → ``spec_rejected``;
    * padding columns past each live window + whole empty slots →
      ``idle``.

    Σ of the three == ``rows`` by construction; the serving loop's work
    ledger still cross-checks it against the independently recorded
    dispatch width (check_partition)."""
    live = int(sum(int(w) for w in wins))
    acc = int(sum(int(a) for a in accepted))
    if acc > live or live > rows:
        raise SpecConfigError(
            f"verify-row attribution impossible: accepted {acc} rows of "
            f"{live} live of {rows} dispatched — each bound must not "
            "exceed the next (arguments rows/wins/accepted)")
    return {"useful": acc, "spec_rejected": live - acc,
            "idle": rows - live}


def _env_int(var: str, default: int) -> int:
    try:
        return int(os.environ.get(var, "") or default)
    except ValueError:
        return default


class NGramProposer:
    """Per-slot deterministic n-gram draft of up to ``k`` tokens.

    ``ngram`` is the LONGEST suffix matched (falling back to shorter
    suffixes down to ``min_ngram`` — a longer match is stronger evidence
    the continuation repeats); ``lookback`` bounds how far back the scan
    walks (host cost stays O(lookback) per step on long generations).
    Defaults come from ``TDTPU_SPEC_NGRAM`` (3), ``TDTPU_SPEC_MIN_NGRAM``
    (1) and ``TDTPU_SPEC_LOOKBACK`` (512). ``propose`` returns 0..k
    tokens — an empty draft just means this step verifies one position,
    i.e. plain one-token decode for that slot.
    """

    def __init__(self, k: int, *, ngram: int | None = None,
                 min_ngram: int | None = None,
                 lookback: int | None = None):
        if k < 1:
            raise SpecConfigError(
                f"k = {k} invalid: a proposer drafts at least one "
                "candidate token (spec_k=0 disables the lane instead) — "
                "argument k")
        self.k = int(k)
        self.ngram = (int(ngram) if ngram is not None
                      else max(1, _env_int("TDTPU_SPEC_NGRAM", 3)))
        self.min_ngram = (int(min_ngram) if min_ngram is not None
                          else max(1, _env_int("TDTPU_SPEC_MIN_NGRAM", 1)))
        if self.min_ngram > self.ngram:
            raise SpecConfigError(
                f"min_ngram = {self.min_ngram} > ngram = {self.ngram}: "
                "the fallback ladder must descend — arguments "
                "ngram/min_ngram (TDTPU_SPEC_NGRAM/TDTPU_SPEC_MIN_NGRAM)")
        self.lookback = (int(lookback) if lookback is not None
                         else max(1, _env_int("TDTPU_SPEC_LOOKBACK", 512)))

    @property
    def window_tokens(self) -> int:
        """Trailing history tokens the proposer ever examines — hot-path
        callers slice to this instead of materializing whole
        prompt+generated lists per slot per iteration."""
        return self.lookback + self.ngram

    def propose(self, history, max_tokens: int | None = None) -> list[int]:
        """Draft up to ``min(k, max_tokens)`` tokens continuing
        ``history`` (the request's ``prompt + tokens``). Most recent
        match wins (recency beats frequency for repetitive serving
        traffic); longest n-gram wins over shorter fallbacks. Only the
        trailing ``window_tokens`` are examined, so host cost per call
        is bounded by the lookback, not the sequence length."""
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        if cap < 1:
            return []
        hist = [int(t) for t in history[-self.window_tokens:]]
        n = len(hist)
        for g in range(min(self.ngram, n - 1), self.min_ngram - 1, -1):
            key = hist[n - g:]
            # Scan backwards for the most recent earlier occurrence whose
            # continuation is non-empty (an occurrence ending at the very
            # tail IS the query itself).
            for s in range(n - g - 1, -1, -1):
                if hist[s:s + g] == key:
                    cont = hist[s + g:s + g + cap]
                    if cont:
                        return cont
        return []
