"""Deterministic open-loop load generator + the serving bench rung.

A :class:`LoadSpec` (seeded) expands into a fixed arrival trace —
(arrival iteration, prompt, max_new_tokens, priority) tuples — that
:func:`run_trace` replays open-loop against a
:class:`~triton_distributed_tpu.serving.loop.ServingEngine`: arrivals
are submitted on schedule whether or not the system keeps up, rejected
submissions retry next iteration (each rejection counted — the
backpressure evidence), and the loop steps until drained.

Two consumers:

* ``bench.py`` — :func:`serving_bench_rung` measures tokens/s and p99
  TTFT/TPOT at N concurrent streams on the Qwen3-8B TP=8 shard shapes
  (the ledger rungs ``serve_tokens_per_s_concurrent`` /
  ``serve_ttft_p99_ms``, gate-banded from r7);
* CI — ``python -m triton_distributed_tpu.serving.loadgen --dryrun``
  replays a seeded 8-request trace through a tiny model on CPU and
  ASSERTS the serving tier's contract: every request finishes,
  per-request token parity vs sequential ``Engine.serve`` (including a
  request that was preempted and resumed mid-decode), admission
  backpressure fires when the page pool is exhausted, and an SLO
  violation streak shrinks the admitted batch — writing
  ``serving-report.json`` for the artifact upload.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys
import time

import numpy as np

from triton_distributed_tpu.obs import goodput as obs_goodput
from triton_distributed_tpu.obs import reqtrace as obs_reqtrace
from triton_distributed_tpu.obs import stepprof as obs_stepprof
from triton_distributed_tpu.serving.scheduler import AdmitResult


@dataclasses.dataclass(frozen=True)
class LoadSpec:
    """Seeded open-loop workload shape.

    Shared-prefix traffic (ISSUE 15, docs/serving.md "Prefix cache"):
    ``prefix_families > 0`` turns the trace into PROMPT FAMILIES — each
    family shares a common preamble of ``prefix_len`` tokens (seeded by
    ``prefix_seed``, INDEPENDENT of the trace seed, so two traces with
    different seeds still share the same family preambles — the
    warm-measurement shape), and each request appends its own divergent
    tail of ``prompt_len`` tokens. Requests round-robin over families.
    """

    n_requests: int = 8
    seed: int = 0
    prompt_len: tuple[int, int] = (4, 12)       # inclusive range
    max_new: tuple[int, int] = (4, 8)
    mean_interarrival_iters: float = 1.0        # 0 = burst at iter 0
    priorities: tuple[int, ...] = (0,)
    vocab: int = 256
    prefix_families: int = 0                    # 0 = no shared preambles
    prefix_len: int = 12                        # preamble tokens / family
    prefix_seed: int = 1234


def build_trace(spec: LoadSpec) -> list[dict]:
    """Expand the spec into a fixed arrival trace (same seed, same
    trace — bit-reproducible serving runs). With ``prefix_families``
    set, ``prompt_len`` sizes each request's divergent TAIL and the
    family preamble rides in front of it."""
    rng = np.random.default_rng(spec.seed)
    families = []
    if spec.prefix_families > 0:
        frng = np.random.default_rng(spec.prefix_seed)
        families = [frng.integers(0, spec.vocab, spec.prefix_len).tolist()
                    for _ in range(spec.prefix_families)]
    trace = []
    it = 0
    for i in range(spec.n_requests):
        if spec.mean_interarrival_iters > 0 and i > 0:
            it += int(rng.geometric(
                1.0 / (1.0 + spec.mean_interarrival_iters)) - 1)
        prompt = rng.integers(
            0, spec.vocab,
            int(rng.integers(spec.prompt_len[0],
                             spec.prompt_len[1] + 1))).tolist()
        fam = None
        if families:
            fam = i % len(families)
            prompt = families[fam] + prompt
        trace.append({
            "req_id": f"lg-{spec.seed}-{i}",
            "arrival_iter": it,
            "prompt": prompt,
            "max_new_tokens": int(rng.integers(spec.max_new[0],
                                               spec.max_new[1] + 1)),
            "priority": int(rng.choice(spec.priorities)),
            **({"family": fam} if fam is not None else {}),
        })
    return trace


def request_records(reqs) -> list[dict]:
    """The per-request record array (ISSUE 13): one row per request —
    id, arrival, TTFT/TPOT, preempted/migrated/evacuated flags, final
    backend — plus the TTFT decomposition when a request tracer was
    active. ``obs.postmortem`` and the serving-report artifact consume
    it; the dryrun asserts it reconciles with the aggregate counters."""
    rt = obs_reqtrace.get_tracer()
    out = []
    for r in sorted(reqs, key=lambda r: (r.arrival_seq, r.req_id)):
        rec = {
            "req_id": r.req_id,
            "arrival_s": r.t_arrival,
            "ttft_ms": (round(r.ttft_s * 1e3, 3)
                        if r.ttft_s is not None else None),
            "tpot_ms": (round(r.tpot_s * 1e3, 3)
                        if r.tpot_s is not None else None),
            "tokens": len(r.tokens),
            "preemptions": r.preemptions,
            "preempted": r.preemptions > 0,
            "migrated": r.migrations > 0,
            "evacuated": r.evacuations > 0,
            "drafted": r.drafted_tokens,
            "accepted": r.accepted_draft_tokens,
            "prefix_hit_tokens": r.prefix_hit_tokens_total,
            "restored_tokens": r.restored_tokens_total,
            "recompute_tokens": r.recompute_tokens,
            "rejected_tokens": r.rejected_tokens,
            "wasted_tokens": r.wasted_tokens,
            "final_backend": r.final_backend,
            "state": r.state.name,
        }
        if rt is not None:
            bd = rt.breakdown(r.req_id)
            if bd is not None:
                rec["ttft_breakdown_ms"] = {k: round(v, 3)
                                            for k, v in bd.items()}
        out.append(rec)
    return out


def run_trace(se, trace: list[dict], *, max_iters: int = 100_000) -> dict:
    """Replay an arrival trace open-loop. Returns the run report:
    per-request latency stats, reject/preemption counts, throughput,
    and the ``request_records`` array (one row per request)."""
    pending = sorted(trace, key=lambda t: t["arrival_iter"])
    requests = {}
    rejects = 0
    it = 0
    t0 = time.perf_counter()
    while pending or se.sched.has_work():
        if it >= max_iters:
            raise RuntimeError(
                f"loadgen still has work after {max_iters} iterations "
                f"({len(pending)} unsubmitted) — deadlock or max_iters "
                "too small")
        still = []
        for item in pending:
            if item["arrival_iter"] > it:
                still.append(item)
                continue
            # TTFT is measured from the request's ARRIVAL (its first
            # submission attempt), not from the attempt that finally got
            # admitted — otherwise the shed-and-retry wait vanishes from
            # the latency evidence in exactly the backpressure regime
            # the generator exists to measure.
            item.setdefault("_t_first_try", se.clock())
            req, res = se.submit(item["prompt"], item["max_new_tokens"],
                                 priority=item["priority"],
                                 req_id=item["req_id"])
            if res is AdmitResult.QUEUE_FULL:
                rejects += 1          # open-loop: retry next iteration
                still.append(item)
            else:
                req.t_arrival = item["_t_first_try"]
                # Keep the request tracer's window on the same clock
                # origin: the shed-and-retry wait belongs in the TTFT
                # queue component (obs/reqtrace.py).
                rt = obs_reqtrace.get_tracer()
                if rt is not None:
                    rt.rebase_arrival(req.req_id, req.t_arrival)
                requests[req.req_id] = req
        pending = still
        se.step()
        it += 1
    wall_s = time.perf_counter() - t0
    reqs = list(requests.values())
    tokens = sum(len(r.tokens) for r in reqs)
    ttfts = sorted(r.ttft_s * 1e3 for r in reqs if r.ttft_s is not None)
    tpots = sorted(r.tpot_s * 1e3 for r in reqs if r.tpot_s is not None)

    def p99(xs):
        return round(xs[min(len(xs) - 1, int(0.99 * len(xs)))], 3) \
            if xs else None

    return {
        "n_requests": len(reqs),
        "iterations": it,
        "wall_s": round(wall_s, 4),
        "tokens": tokens,
        "tokens_per_s": round(tokens / max(wall_s, 1e-9), 3),
        "ttft_p99_ms": p99(ttfts),
        "tpot_p99_ms": p99(tpots),
        "admission_rejects": rejects,
        "preemptions": sum(r.preemptions for r in reqs),
        "all_finished": all(r.state.name == "FINISHED" for r in reqs),
        "request_records": request_records(reqs),
        "requests": reqs,
    }


def sequential_reference(engine, trace: list[dict]) -> dict[str, list[int]]:
    """Per-request golden tokens: one ``Engine.serve`` call each (the
    parity oracle — greedy, so continuous batching must reproduce it)."""
    import jax.numpy as jnp
    import numpy as _np

    out = {}
    for item in trace:
        ids = jnp.asarray([item["prompt"]], jnp.int32)
        toks = engine.serve(ids, gen_len=item["max_new_tokens"])
        out[item["req_id"]] = _np.asarray(toks)[0].tolist()
    return out


# ---------------------------------------------------------------------------
# The CPU dryrun proof (CI smoke).
# ---------------------------------------------------------------------------

def _tiny_serving(engine=None, **serving_kw):
    """(engine, ServingEngine) on a 1-device CPU mesh + tiny model."""
    import jax

    from triton_distributed_tpu.models import (
        Engine, init_dense_llm, tiny_config,
    )
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.serving.loop import ServingEngine

    if engine is None:
        cfg = tiny_config()
        params = init_dense_llm(jax.random.key(0), cfg)
        ctx = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                     devices=jax.devices()[:1])
        engine = Engine(cfg, params, ctx, backend="xla", max_seq=64,
                        page_size=4)
    return engine, ServingEngine(engine, **serving_kw)


def dryrun(json_path: str | None, flight_dir: str | None = None) -> int:
    """The seeded 8-request CPU proof (acceptance criteria of ISSUE 7):
    (a) per-request token parity vs sequential serve incl. a
    preempt/resume, (b) admission backpressure on pool exhaustion,
    (c) SLO violation streak shrinks the admitted batch. Phase 8
    (ISSUE 13) adds the request-tracing + flight-recorder round-trip:
    ``flight_dir`` keeps its obs run directory (dumps + request
    timelines) for CI's postmortem step. Phase 9 (ISSUE 14) proves
    greedy speculative decode token-identical to sequential one-token
    serve on BOTH backends (xla + megakernel, incl. preempt/resume)
    with the rejected-draft page rollback asserted every iteration.
    Phase 10 (ISSUE 15) proves the prefix-reuse subsystem: a
    shared-prefix trace served warm is token-identical to the cold
    sequential oracle on both backends with a nonzero shared-page
    count, exact refcounted pool occupancy, and a decode-pool hit on
    the disagg tier that skips the prefill role + migration stream.
    Phase 11 (ISSUE 17) proves the fleet router over four virtual CPU
    replicas: parity + spread + replica-labeled metrics, prefix
    affinity strictly beating round_robin on warm prefill tokens, a
    mid-serve replica kill drained onto siblings (parity kept) and
    re-admitted after the rejoin probe, and an autoscaler
    shrink-then-grow round trip — with one named page auditor per
    replica. Phase 12 (ISSUE 18) proves the step-phase profiler on
    every tier in the sweep: per-iteration phase vectors that
    PARTITION the iteration wall with a nonzero host-bubble fraction
    (plus per-replica labels on the fleet), written to
    ``step-profile.json`` beside the flight dumps. Phase 13 (ISSUE 19)
    proves the goodput work ledger on every tier: per-iteration
    category partitions, per-request waste reconciliation, and
    byte-identical replays under a counter clock. Phase 14 (ISSUE 20)
    proves KV tiering to host RAM + the async double-buffered loop: a
    forced chain eviction swaps to host, the warm re-admission
    restores with zero cold prefill and exact parity, and the async
    replay is a byte-identical pure reordering of the sync one with
    nonzero plan/device overlap."""
    import os

    from triton_distributed_tpu.runtime.utils import (
        ensure_virtual_cpu_devices,
    )

    # Phase 6 (the fleet round-trip) needs a 2-device virtual mesh; in
    # an already-initialized process the flag is inert and the phase
    # guards on the actual device count.
    ensure_virtual_cpu_devices(2)
    import jax

    jax.config.update("jax_platforms", "cpu")
    from triton_distributed_tpu.runtime.interpret_workarounds import (
        apply_interpret_workarounds,
    )

    apply_interpret_workarounds()

    failures: list[str] = []

    # Page-audit lane (analysis/page_audit.py): every engine this dryrun
    # builds runs with the shadow-state lifetime sanitizer LIVE, and
    # every phase must close with zero violations — leaks, double-frees,
    # use-after-free and COW-before-append across preemption, eviction,
    # migration, spec rollback and prefix sharing are all in scope.
    audit_prev = os.environ.get("TDTPU_PAGE_AUDIT")
    os.environ["TDTPU_PAGE_AUDIT"] = "1"
    page_audits: dict[str, dict] = {}

    def _audit(phase: str, se_) -> None:
        aud = getattr(se_, "page_audit", None)
        if aud is None:
            failures.append(
                f"{phase}: engine has no live page auditor — the "
                "TDTPU_PAGE_AUDIT wiring regressed")
            return
        s = aud.summary()
        page_audits[phase] = s
        if not s["ok"]:
            kinds = [v["kind"] for v in s["violations"][:6]]
            failures.append(
                f"{phase}: page-audit violations {kinds} "
                f"({len(s['violations'])} total) — see report")

    # Phase 1 — seeded trace under page pressure: parity + preemption.
    # num_pages 8 against 4 slots wanting up to ceil(19/4)=5 pages each
    # forces eviction mid-decode; the preempted request recomputes on
    # resume and must still match its sequential tokens.
    spec = LoadSpec(n_requests=8, seed=0, mean_interarrival_iters=1.0)
    trace = build_trace(spec)
    engine, se = _tiny_serving(max_batch=4, num_pages=8, prefill_chunk=4,
                               max_waiting=8)
    report = run_trace(se, trace)
    reqs = report.pop("requests")
    golden = sequential_reference(engine, trace)
    mismatches = [r.req_id for r in reqs if r.tokens != golden[r.req_id]]
    preempted_ok = [r.req_id for r in reqs
                    if r.preemptions > 0 and r.tokens == golden[r.req_id]]
    if not report["all_finished"]:
        failures.append("not every request reached FINISHED")
    if mismatches:
        failures.append(f"token parity broken vs sequential serve: "
                        f"{mismatches}")
    if not preempted_ok:
        failures.append("no request was preempted+resumed with parity — "
                        "the pool sizing no longer exercises eviction")
    # The per-request record array must reconcile with the aggregate
    # counters it rides beside (ISSUE 13): same request set, same
    # preemption total, same token total, everyone FINISHED.
    recs = report["request_records"]
    reconciled = (
        len(recs) == report["n_requests"]
        and sum(r["preemptions"] for r in recs) == report["preemptions"]
        and sum(r["tokens"] for r in recs) == report["tokens"]
        and all(r["state"] == "FINISHED" for r in recs)
        and all(r["ttft_ms"] is not None for r in recs))
    if not reconciled:
        failures.append(
            "per-request records do not reconcile with the aggregate "
            "counters (n/preemptions/tokens/finished/ttft)")
    report["records_reconciled"] = reconciled
    report["parity_ok"] = not mismatches
    report["preempted_with_parity"] = preempted_ok
    report["per_request"] = [
        {"req_id": r.req_id, "prompt_len": len(r.prompt),
         "max_new_tokens": r.max_new_tokens, "tokens": r.tokens,
         "preemptions": r.preemptions,
         "ttft_ms": round(r.ttft_s * 1e3, 3) if r.ttft_s else None}
        for r in reqs]
    _audit("phase1-pressure", se)

    # Phase 2 — backpressure: a pool of 2 pages is fully reserved by the
    # first admission (prompt 5, max_new 3 → final KV 7 ≤ 2 pages);
    # while it decodes, further submits must be refused, not queued.
    _, se2 = _tiny_serving(engine, max_batch=2, num_pages=2,
                           prefill_chunk=4, max_waiting=4)
    _, res_a = se2.submit(list(range(1, 6)), 3)
    for _ in range(2):
        se2.step()                 # let it occupy the pool
    _, res_b = se2.submit(list(range(1, 6)), 3)
    backpressure = (res_a is AdmitResult.ADMITTED
                    and res_b is AdmitResult.QUEUE_FULL)
    if not backpressure:
        failures.append(
            f"admission backpressure did not fire on an exhausted pool "
            f"(first={res_a}, second={res_b})")
    report["backpressure_fired"] = backpressure
    se2.run()                      # drain phase-2 work
    _audit("phase2-backpressure", se2)

    # Phase 3 — SLO coupling: an impossible tokens/s floor must shrink
    # the admitted batch within the shrink-streak budget.
    from triton_distributed_tpu.obs.slo import SLOConfig

    _, se3 = _tiny_serving(engine, max_batch=4, prefill_chunk=4,
                           slo_cfg=SLOConfig(tokens_per_s_min=1e12))
    cap0 = se3.sched.admit_cap
    for item in build_trace(LoadSpec(n_requests=4, seed=1,
                                     mean_interarrival_iters=0.0)):
        se3.submit(item["prompt"], item["max_new_tokens"],
                   req_id=item["req_id"] + "-slo")
    se3.run()
    slo_shrunk = se3.sched.admit_cap < cap0
    if not slo_shrunk:
        failures.append(
            f"SLO violation streak did not shrink admission "
            f"(cap {cap0} -> {se3.sched.admit_cap})")
    report["slo_admission"] = {"initial_cap": cap0,
                               "final_cap": se3.sched.admit_cap,
                               "shrunk": slo_shrunk}
    _audit("phase3-slo", se3)

    # Phase 4 (round 9) — megakernel serving lane: the same parity
    # contract on the PAGED persistent kernel (page_size == TILE): every
    # request token-identical to sequential Engine.serve, including one
    # preempted under page pressure and resumed (recompute) ON the paged
    # workspace, with the lane still active at the end (no silent
    # demotion).
    import numpy as _np

    from triton_distributed_tpu.models import Engine, init_dense_llm
    from triton_distributed_tpu.models.config import ModelConfig
    from triton_distributed_tpu.runtime import initialize_distributed

    mk_cfg = ModelConfig(hidden_size=256, intermediate_size=256,
                         num_layers=2, num_heads=2, num_kv_heads=1,
                         head_dim=128, vocab_size=512, qk_norm=True,
                         dtype="float32")
    mk_params = init_dense_llm(jax.random.PRNGKey(1), mk_cfg)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    rng = _np.random.default_rng(9)
    # r0 crosses the 128-position page boundary mid-decode; with a
    # 2-page pool + both slots occupied, r1 (lower priority) is evicted
    # and recomputes on resume.
    mk_trace = [
        {"req_id": "mk-0", "arrival_iter": 0,
         "prompt": rng.integers(0, 512, 126).tolist(),
         "max_new_tokens": 6, "priority": 1},
        {"req_id": "mk-1", "arrival_iter": 0,
         "prompt": rng.integers(0, 512, 100).tolist(),
         "max_new_tokens": 4, "priority": 0},
    ]
    mk_engine = Engine(mk_cfg, mk_params, ctx1, backend="megakernel",
                       max_seq=256, page_size=128)
    from triton_distributed_tpu.serving.loop import ServingEngine

    se4 = ServingEngine(mk_engine, max_batch=2, num_pages=2,
                        prefill_chunk=128)
    mk_report = run_trace(se4, mk_trace)
    mk_reqs = mk_report.pop("requests")
    oracle = Engine(mk_cfg, mk_params, ctx1, backend="xla", max_seq=256)
    mk_golden = sequential_reference(oracle, mk_trace)
    mk_mismatch = [r.req_id for r in mk_reqs
                   if r.tokens != mk_golden[r.req_id]]
    mk_preempted = [r.req_id for r in mk_reqs
                    if r.preemptions > 0
                    and r.tokens == mk_golden[r.req_id]]
    if se4._mk is None or mk_engine.backend != "megakernel":
        failures.append(
            f"megakernel serving lane silently demoted (backend now "
            f"{mk_engine.backend!r}) — the parity it reported is not "
            "the persistent kernel's")
    if mk_mismatch:
        failures.append("megakernel serving token parity broken vs "
                        f"sequential serve: {mk_mismatch}")
    if not mk_preempted:
        failures.append("no request was preempted+resumed with parity on "
                        "the paged megakernel workspace")
    report["megakernel_lane"] = {
        "parity_ok": not mk_mismatch,
        "preempted_with_parity": mk_preempted,
        "iterations": mk_report["iterations"],
        "all_finished": mk_report["all_finished"],
    }
    _audit("phase4-megakernel", se4)

    # Phase 5 (round 10) — disaggregated tier (docs/disagg.md): the same
    # per-request parity contract with prefill and decode on SEPARATE
    # role meshes and every finished prefill crossing a KV-migration
    # stream (checksummed, double-buffered, decode-side page ids from
    # the DECODE allocator), including one request preempted DURING its
    # migration and resumed by recompute.
    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )
    from triton_distributed_tpu.models import Engine as _Engine

    pctx, dctx = role_contexts(jax.devices()[:2])
    dg_cfg = engine.cfg
    dg_params = engine.params
    dg_pe = _Engine(dg_cfg, dg_params, pctx, backend="xla", max_seq=64)
    dg_de = _Engine(dg_cfg, dg_params, dctx, backend="xla", max_seq=64,
                    page_size=4)
    se5 = DisaggServingEngine(dg_pe, dg_de, max_batch=2, num_pages=5,
                              prefill_chunk=4, block_pages=1)
    dg_trace = [
        # High-priority long decode: its page growth drains the pool.
        {"req_id": "dg-0", "arrival_iter": 0,
         "prompt": list(range(10, 16)), "max_new_tokens": 10,
         "priority": 1},
        # Low-priority 3-page prompt: 3 migration blocks at block_pages=1
        # — the eviction window the preempt-during-migration proof needs.
        {"req_id": "dg-1", "arrival_iter": 1,
         "prompt": list(range(30, 42)), "max_new_tokens": 4,
         "priority": 0},
        # Late 1-page arrival: admits behind dg-1's resumed allocation,
        # so its migration lands at a non-zero decode page id — the
        # page-table-rewrite evidence (src pages are always 0..n-1).
        {"req_id": "dg-2", "arrival_iter": 2,
         "prompt": list(range(50, 54)), "max_new_tokens": 2,
         "priority": 0},
    ]
    dg_report = run_trace(se5, dg_trace)
    dg_reqs = dg_report.pop("requests")
    dg_golden = sequential_reference(engine, dg_trace)
    dg_mismatch = [r.req_id for r in dg_reqs
                   if r.tokens != dg_golden[r.req_id]]
    if not se5.disagg_active:
        failures.append(
            f"disagg tier silently demoted ({se5.demotion_reason!r}) — "
            "the parity it reported is the monolithic path's")
    if dg_mismatch:
        failures.append("disagg token parity broken vs sequential "
                        f"serve: {dg_mismatch}")
    if se5.migration_preemptions < 1:
        failures.append(
            "no request was preempted DURING its KV migration — the "
            "pool sizing no longer exercises the mid-stream eviction "
            "round-trip")
    rewrites = [m for m in se5.migrations_log
                if m["src_pages"] != m["dst_pages"]]
    if not rewrites:
        failures.append(
            "every migration landed at identity page ids — the "
            "page-table rewrite is no longer exercised")
    report["disagg"] = {
        "parity_ok": not dg_mismatch,
        "migrations": len(se5.migrations_log),
        "migration_preemptions": se5.migration_preemptions,
        "page_id_rewrites": len(rewrites),
        "all_finished": dg_report["all_finished"],
    }
    _audit("phase5-disagg", se5)

    # Phase 6 (ISSUE 11) — elastic fleet: a TP=2 serving tier loses
    # rank 1 mid-serve, EVACUATES to the TP=1 survivor mesh (every
    # in-flight request preempted, engine re-partitioned, params
    # host-resharded, recompute-on-resume), keeps per-request token
    # parity AND first-submission TTFT accounting, then REJOINS the
    # full mesh after the fault clears — the post-rejoin request must
    # also be token-identical (docs/resilience.md "Fleet degradation").
    import warnings as _warnings

    from triton_distributed_tpu.resilience import faults as _faults

    if len(jax.devices()) < 2:
        failures.append(
            "fleet phase needs >= 2 virtual CPU devices "
            "(--xla_force_host_platform_device_count applied too late?)")
    else:
        fl_cfg = engine.cfg
        fl_params = engine.params
        ctx_fl = initialize_distributed(mesh_shape=(2,), axis_names=("tp",),
                                        devices=jax.devices()[:2])
        fl_oracle = _Engine(fl_cfg, fl_params, ctx_fl, backend="xla",
                            max_seq=64)
        fl_trace = [
            {"req_id": "fl-0", "arrival_iter": 0,
             "prompt": list(range(10, 16)), "max_new_tokens": 6,
             "priority": 0},
            {"req_id": "fl-1", "arrival_iter": 0,
             "prompt": list(range(30, 38)), "max_new_tokens": 5,
             "priority": 0},
        ]
        fl_golden = sequential_reference(fl_oracle, fl_trace)
        fl_eng = _Engine(fl_cfg, fl_params, ctx_fl, backend="xla",
                         max_seq=64, page_size=4)
        from triton_distributed_tpu.serving.loop import (
            ServingEngine as _ServingEngine,
        )

        # The rejoin streak is resolved at CONSTRUCTION (ServingEngine
        # reads TDTPU_REJOIN_AFTER once) — set it before building the tier.
        rejoin_env = os.environ.get("TDTPU_REJOIN_AFTER")
        os.environ["TDTPU_REJOIN_AFTER"] = "3"
        se6 = _ServingEngine(fl_eng, max_batch=2, prefill_chunk=4)
        fl_reqs = {}
        for item in fl_trace:
            req, res = se6.submit(item["prompt"], item["max_new_tokens"],
                                  req_id=item["req_id"])
            assert res is AdmitResult.ADMITTED, res
            fl_reqs[req.req_id] = req
        for _ in range(3):
            se6.step()                  # first tokens land on the full mesh
        ttft_before = {rid: r.t_first_token for rid, r in fl_reqs.items()
                       if r.t_first_token is not None}
        try:
            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore", RuntimeWarning)
                _faults.mark_rank_lost(1)           # the mid-serve kill
                se6.run()
                fl_parity = [rid for rid, r in fl_reqs.items()
                             if r.tokens != fl_golden[rid]]
                evacuated = se6.evacuated and fl_eng.n_total == 1
                _faults.clear_rank_loss(1)          # repaired -> probe
                post_req, _ = se6.submit(fl_trace[0]["prompt"],
                                         fl_trace[0]["max_new_tokens"],
                                         req_id="fl-post")
                se6.run()
        finally:
            _faults.clear_rank_loss()
            if rejoin_env is None:
                os.environ.pop("TDTPU_REJOIN_AFTER", None)
            else:
                os.environ["TDTPU_REJOIN_AFTER"] = rejoin_env
        rejoined = (not se6.evacuated) and fl_eng.n_total == 2
        ttft_kept = all(fl_reqs[rid].t_first_token == t
                        for rid, t in ttft_before.items())
        if not evacuated:
            failures.append(
                "rank loss did not evacuate the tier to the survivor mesh")
        if fl_parity:
            failures.append("fleet evacuation broke token parity vs "
                            f"sequential serve: {fl_parity}")
        if se6.evacuation_preemptions < 1:
            failures.append(
                "no request was preempted by the evacuation — the kill "
                "no longer lands mid-serve")
        if not ttft_kept:
            failures.append(
                "evacuation reset first-submission TTFT accounting")
        if not rejoined:
            failures.append(
                "the rejoin probe did not re-expand to the full mesh after "
                "the fault cleared")
        if post_req.tokens != fl_golden["fl-0"]:
            failures.append("post-rejoin token parity broken vs sequential "
                            "serve")
        report["fleet"] = {
            "evacuated": evacuated,
            "parity_ok": not fl_parity,
            "evacuation_preemptions": se6.evacuation_preemptions,
            "ttft_first_submission_kept": ttft_kept,
            "rejoined": rejoined,
            "post_rejoin_parity": post_req.tokens == fl_golden["fl-0"],
            "events": [e["event"] for e in se6.fleet_log],
        }
        _audit("phase6-fleet", se6)

    # Phase 7 (round 12) — fp8 KV cache: (a) at a FIXED HBM budget the
    # e4m3 pool holds exactly 2× the bf16 pages (4× the f32 pages),
    # verified through the tdtpu_kv_pages_resident gauge the serving
    # loop publishes; (b) per-request token parity vs the sequential
    # QUANTIZED serve (Engine.serve with the same kv_dtype — the
    # quantize-then-attend golden) including a preempt/resume
    # round-trip on the fp8 pool (COW-style page reuse across requests
    # never mixes dtypes: the pool is one e4m3 array).
    import tempfile

    import jax.numpy as _jnp

    from triton_distributed_tpu import obs as _obs
    from triton_distributed_tpu.models import Engine as _E
    from triton_distributed_tpu.models.kv_cache import (
        kv_pool_pages_for_budget,
    )
    from triton_distributed_tpu.obs import metrics as _om

    f8 = _jnp.float8_e4m3fn
    f8_cfg = engine.cfg
    # One page's bf16 cost × 4 pages = the budget both pools share: the
    # e4m3 pool then holds 8 — the SAME pressure shape as phase 1's
    # 8-page pool, so the trace still forces a mid-decode eviction (the
    # preempt/resume proof runs ON the doubled fp8 pool).
    from triton_distributed_tpu.models.kv_cache import kv_page_bytes

    budget = 4 * kv_page_bytes(f8_cfg, page_size=4,
                               kv_dtype=_jnp.bfloat16)
    pages_bf16 = kv_pool_pages_for_budget(
        f8_cfg, page_size=4, hbm_bytes=budget, kv_dtype=_jnp.bfloat16)
    pages_f8 = kv_pool_pages_for_budget(
        f8_cfg, page_size=4, hbm_bytes=budget, kv_dtype=f8)
    doubled = pages_f8 == 2 * pages_bf16
    if not doubled:
        failures.append(
            f"fp8 pool did not double at fixed HBM: {pages_bf16} bf16 "
            f"pages vs {pages_f8} e4m3 pages at the same budget")
    f8_eng = _E(f8_cfg, engine.params, engine.ctx, backend="xla",
                max_seq=64, page_size=4, kv_dtype=f8)
    f8_trace = build_trace(LoadSpec(n_requests=8, seed=0,
                                    mean_interarrival_iters=1.0))
    from triton_distributed_tpu.serving.loop import (
        ServingEngine as _ServingEngineKV,
    )

    with tempfile.TemporaryDirectory() as run_dir:
        _obs.start_run(run_dir)
        try:
            se7 = _ServingEngineKV(f8_eng, max_batch=4,
                                   kv_hbm_budget=budget, prefill_chunk=4,
                                   max_waiting=8)
            gauge_pages = se7.num_pages
            f8_report = run_trace(se7, f8_trace)
            snap = _om.registry().snapshot()
        finally:
            _obs.finish_run()
    gauge = (snap.get(_om.KV_PAGES_RESIDENT) or {}).get("value")
    if gauge != gauge_pages or gauge != pages_f8:
        failures.append(
            f"tdtpu_kv_pages_resident gauge ({gauge}) does not report "
            f"the resident e4m3 pool ({pages_f8} pages at the fixed "
            "budget)")
    f8_reqs = f8_report.pop("requests")
    f8_golden = sequential_reference(f8_eng, f8_trace)
    f8_mismatch = [r.req_id for r in f8_reqs
                   if r.tokens != f8_golden[r.req_id]]
    f8_preempted = [r.req_id for r in f8_reqs
                    if r.preemptions > 0
                    and r.tokens == f8_golden[r.req_id]]
    if f8_mismatch:
        failures.append("fp8-KV token parity broken vs sequential "
                        f"quantized serve: {f8_mismatch}")
    if not f8_preempted:
        failures.append(
            "no fp8-KV request was preempted+resumed with parity — the "
            "fixed budget no longer exercises eviction on the e4m3 pool")
    report["fp8_kv"] = {
        "budget_bytes": budget,
        "pages_bf16": pages_bf16,
        "pages_fp8": pages_f8,
        "pool_doubled": doubled,
        "gauge_pages_resident": gauge,
        "parity_ok": not f8_mismatch,
        "preempted_with_parity": f8_preempted,
        "all_finished": f8_report["all_finished"],
    }
    _audit("phase7-fp8kv", se7)

    # Phase 8 (ISSUE 13) — request tracing + flight recorder: a traced
    # serving run under an impossible tokens/s floor must (a) leave
    # per-request timelines (requests.spans.json) whose TTFT components
    # PARTITION each request's window, (b) dump the flight ring when the
    # SLO violation streak shrinks admission, (c) validate under
    # ``obs.postmortem --check`` (rc 0), and (d) reconcile the
    # per-request record array against the run's own metric counters.
    from triton_distributed_tpu.obs import postmortem as _pm

    run_dir = flight_dir or tempfile.mkdtemp(prefix="tdtpu-flight-")
    _obs.start_run(run_dir)
    try:
        _, se8 = _tiny_serving(engine, max_batch=4, num_pages=8,
                               prefill_chunk=4, max_waiting=8,
                               slo_cfg=SLOConfig(tokens_per_s_min=1e12))
        rep8 = run_trace(se8, build_trace(spec))    # phase 1's shape
        rep8.pop("requests")
        recs8 = rep8["request_records"]
        snap8 = _om.registry().snapshot()
    finally:
        _obs.finish_run()
    # THIS run's recorder, not a directory glob: a stale dump from a
    # previous session in a reused --flight-dir must neither satisfy
    # the produced-a-dump assertion nor be misreported as this run's.
    dumps = list(se8.flight.dumps)
    if not dumps:
        failures.append(
            "phase 8: the SLO-driven admission shrink produced no "
            "flight-recorder dump")
    elif any(_pm.main([p, "--check", "--quiet"]) != 0 for p in dumps):
        failures.append(
            "phase 8: obs.postmortem --check rejected a flight dump")
    if not os.path.exists(os.path.join(run_dir, "requests.spans.json")):
        failures.append(
            "phase 8: the traced serving run left no request-timeline "
            "lane (requests.spans.json)")
    bad_bd = [r["req_id"] for r in recs8
              if not r.get("ttft_breakdown_ms")
              or abs(sum(r["ttft_breakdown_ms"][k] for k in
                         ("queue_ms", "prefill_ms", "migrate_ms",
                          "decode_ms"))
                     - r["ttft_breakdown_ms"]["total_ms"]) > 0.01]
    if bad_bd:
        failures.append(
            f"phase 8: TTFT components do not partition the window for "
            f"{bad_bd}")
    finished8 = (snap8.get(_om.SERVE_FINISHED) or {}).get("value")
    if (finished8 != len(recs8)
            or not all(r["state"] == "FINISHED" for r in recs8)):
        failures.append(
            f"phase 8: per-request records ({len(recs8)} finished rows) "
            f"do not reconcile with {_om.SERVE_FINISHED} = {finished8}")
    report["reqtrace"] = {
        "run_dir": run_dir,
        "flight_dumps": [os.path.basename(p) for p in dumps],
        "n_records": len(recs8),
        "breakdown_partition_ok": not bad_bd,
        "preemptions": rep8["preemptions"],
    }
    _audit("phase8-reqtrace", se8)

    # Phase 9 (ISSUE 14) — speculative decode: greedy draft-and-verify
    # (spec_k > 0) must be TOKEN-IDENTICAL to sequential one-token
    # Engine.serve on BOTH backends — xla (dense_verify_step_paged, incl.
    # a preempt/resume round-trip under page pressure) and megakernel
    # (the windowed draft-and-verify queue rows). Rejected drafts must
    # never leave KV bytes resident: every running request's page count
    # returns to exactly ceil(kv_len / page) after each iteration, and
    # the pool drains completely at the end.
    from triton_distributed_tpu.serving.loop import (
        ServingEngine as _SpecServing,
    )

    sp_trace = build_trace(spec)                 # phase 1's seeded shape
    se9 = _SpecServing(engine, max_batch=4, num_pages=8, prefill_chunk=4,
                       max_waiting=8, spec_k=2)
    sp_occupancy_ok = [True]
    sp_orig_step = se9.step

    def _sp_checked_step():
        out = sp_orig_step()
        for r in se9.sched.running():
            held = len(se9.sched.allocator.pages(r.req_id))
            if held != -(-r.kv_len // se9.page):
                sp_occupancy_ok[0] = False
        return out

    se9.step = _sp_checked_step
    sp_report = run_trace(se9, sp_trace)
    sp_reqs = sp_report.pop("requests")
    sp_mismatch = [r.req_id for r in sp_reqs
                   if r.tokens != golden[r.req_id]]
    sp_preempted = [r.req_id for r in sp_reqs
                    if r.preemptions > 0 and r.tokens == golden[r.req_id]]
    sp_drafted = sum(r.drafted_tokens for r in sp_reqs)
    sp_recs = sp_report["request_records"]
    if sp_mismatch:
        failures.append("spec-decode token parity broken vs sequential "
                        f"one-token serve (xla): {sp_mismatch}")
    if not sp_preempted:
        failures.append(
            "no spec-decode request was preempted+resumed with parity — "
            "the pool sizing no longer exercises eviction under the "
            "candidate-window reservations")
    if not sp_occupancy_ok[0]:
        failures.append(
            "spec-decode rollback left pages resident beyond the "
            "accepted prefix (occupancy did not return to the one-token "
            "baseline)")
    if sp_drafted < 1:
        failures.append(
            "the spec proposer drafted nothing over the whole trace — "
            "the lane ran as plain one-token decode and proved nothing")
    if se9._spec_fallback:
        failures.append("spec lane silently fell back to one-token "
                        "decode during the parity run")
    if any("drafted" not in r or "accepted" not in r for r in sp_recs):
        failures.append("request_records rows lost their per-request "
                        "accepted/drafted spec fields")
    # Megakernel half: the SAME contract on the persistent kernel's
    # windowed draft-and-verify rows (repetitive prompts so the drafts
    # actually fire), including a preempt/resume on the paged workspace.
    mk_sp_eng = Engine(mk_cfg, mk_params, ctx1, backend="megakernel",
                       max_seq=256, page_size=128)
    sp_pat = rng.integers(0, 512, 7).tolist()
    mk_sp_trace = [
        {"req_id": "mksp-0", "arrival_iter": 0,
         "prompt": (sp_pat * 19)[:126], "max_new_tokens": 8,
         "priority": 1},
        {"req_id": "mksp-1", "arrival_iter": 0,
         "prompt": (sp_pat * 16)[:100], "max_new_tokens": 6,
         "priority": 0},
    ]
    mk_sp_golden = sequential_reference(oracle, mk_sp_trace)
    se9mk = _SpecServing(mk_sp_eng, max_batch=2, num_pages=2,
                         prefill_chunk=128, spec_k=2)
    mk_sp_report = run_trace(se9mk, mk_sp_trace)
    mk_sp_reqs = mk_sp_report.pop("requests")
    mk_sp_mismatch = [r.req_id for r in mk_sp_reqs
                      if r.tokens != mk_sp_golden[r.req_id]]
    mk_sp_preempted = [r.req_id for r in mk_sp_reqs
                       if r.preemptions > 0
                       and r.tokens == mk_sp_golden[r.req_id]]
    if se9mk._mk is None or mk_sp_eng.backend != "megakernel":
        failures.append(
            f"megakernel spec lane silently demoted (backend now "
            f"{mk_sp_eng.backend!r}) — the parity it reported is not "
            "the windowed persistent kernel's")
    if mk_sp_mismatch:
        failures.append("megakernel spec-decode token parity broken vs "
                        f"sequential serve: {mk_sp_mismatch}")
    if not mk_sp_preempted:
        failures.append("no megakernel spec request was preempted+"
                        "resumed with parity on the paged workspace")
    report["spec_decode"] = {
        "parity_ok": not sp_mismatch,
        "preempted_with_parity": sp_preempted,
        "drafted": sp_drafted,
        "accepted_drafts": sum(r.accepted_draft_tokens for r in sp_reqs),
        "occupancy_baseline_ok": sp_occupancy_ok[0],
        "megakernel_parity_ok": not mk_sp_mismatch,
        "megakernel_preempted_with_parity": mk_sp_preempted,
        "megakernel_drafted": sum(r.drafted_tokens for r in mk_sp_reqs),
        "megakernel_accepted_drafts": sum(
            r.accepted_draft_tokens for r in mk_sp_reqs),
    }
    _audit("phase9-spec", se9)
    _audit("phase9-spec-megakernel", se9mk)

    # Phase 10 (ISSUE 15) — prefix-reuse subsystem (docs/serving.md
    # "Prefix cache"): a shared-prefix trace (prompt families with a
    # common preamble + divergent tails) served WARM must be
    # token-identical to the sequential cold oracle on BOTH backends,
    # with a nonzero shared-page count, tdtpu_prefill_tokens_saved_total
    # > 0, and EXACT pool occupancy (refcounted pages counted once).
    # Disagg: a decode-pool prefix hit admits without invoking the
    # prefill role or the migration stream.
    from triton_distributed_tpu.serving.loop import (
        ServingEngine as _PrefixServing,
    )

    px_spec = LoadSpec(n_requests=6, seed=3, prompt_len=(3, 6),
                       max_new=(4, 6), mean_interarrival_iters=2.0,
                       prefix_families=2, prefix_len=12)
    px_trace = build_trace(px_spec)
    px_golden = sequential_reference(engine, px_trace)
    with tempfile.TemporaryDirectory() as px_dir:
        _obs.start_run(px_dir)
        try:
            se10 = _PrefixServing(engine, max_batch=4, num_pages=24,
                                  prefill_chunk=4, max_waiting=8,
                                  prefix_cache=True)
            px_report = run_trace(se10, px_trace)
            px_snap = _om.registry().snapshot()
        finally:
            _obs.finish_run()
    px_reqs = px_report.pop("requests")
    px_mismatch = [r.req_id for r in px_reqs
                   if r.tokens != px_golden[r.req_id]]
    px_warm = [r.req_id for r in px_reqs if r.prefix_hit_tokens_total > 0]
    saved = (px_snap.get(_om.PREFIX_TOKENS_SAVED) or {}).get("value", 0)
    hit_rate = (px_snap.get(_om.PREFIX_HIT_RATE) or {}).get("value")
    alloc10 = se10.sched.allocator
    used10 = {p for o in list(alloc10._owned.values()) for p in o}
    used10 |= se10.prefix._pages
    occupancy_exact = (len(used10)
                       == alloc10.usable_pages - alloc10.free_count)
    if px_mismatch:
        failures.append("warm serve token parity broken vs cold "
                        f"sequential serve: {px_mismatch}")
    if not px_warm:
        failures.append("no request admitted warm — the shared-prefix "
                        "trace no longer exercises the radix index")
    if se10.prefix.pages_held < 1:
        failures.append("prefix cache holds no resident pages after the "
                        "trace — nothing was indexed")
    if se10.prefix.pages_shared_peak < 1:
        failures.append(
            "no page was ever shared across readers during the trace "
            "(pages_shared peak 0) — the families no longer overlap in "
            "flight")
    if not saved or saved <= 0:
        failures.append(
            f"tdtpu_prefill_tokens_saved_total = {saved!r}: warm "
            "admissions saved no prefill work")
    if hit_rate is None:
        failures.append("prefix-enabled run missing the "
                        f"{_om.PREFIX_HIT_RATE} gauge")
    if not occupancy_exact:
        failures.append(
            "pool occupancy accounting not exact under sharing "
            f"({len(used10)} unique held pages vs "
            f"{alloc10.usable_pages - alloc10.free_count} non-free)")
    # Megakernel half: the SAME warm contract on the paged persistent
    # workspace — the second request's prefix (incl. an in-page
    # divergence COW) reads the resident pool tiles.
    px_rng = _np.random.default_rng(15)
    px_base = px_rng.integers(0, 512, 140).tolist()
    mk_px_trace = [
        {"req_id": "px-mk-0", "arrival_iter": 0, "prompt": px_base,
         "max_new_tokens": 4, "priority": 0},
        {"req_id": "px-mk-1", "arrival_iter": 3,
         "prompt": px_base[:132] + px_rng.integers(0, 512, 8).tolist(),
         "max_new_tokens": 4, "priority": 0},
    ]
    mk_px_golden = sequential_reference(oracle, mk_px_trace)
    mk_px_eng = Engine(mk_cfg, mk_params, ctx1, backend="megakernel",
                       max_seq=256, page_size=128)
    se10mk = _PrefixServing(mk_px_eng, max_batch=2, num_pages=4,
                            prefill_chunk=128, prefix_cache=True)
    mk_px_report = run_trace(se10mk, mk_px_trace)
    mk_px_reqs = mk_px_report.pop("requests")
    mk_px_mismatch = [r.req_id for r in mk_px_reqs
                      if r.tokens != mk_px_golden[r.req_id]]
    mk_px_warm = [r.req_id for r in mk_px_reqs
                  if r.prefix_hit_tokens_total > 0]
    if se10mk._mk is None or mk_px_eng.backend != "megakernel":
        failures.append(
            f"megakernel prefix lane silently demoted (backend now "
            f"{mk_px_eng.backend!r}) — the warm parity it reported is "
            "not the persistent kernel's")
    if mk_px_mismatch:
        failures.append("megakernel warm serve token parity broken vs "
                        f"cold sequential serve: {mk_px_mismatch}")
    if not mk_px_warm:
        failures.append("no megakernel request admitted warm off the "
                        "paged workspace's resident pages")
    # Disagg half: the decode-pool hit must skip the prefill role AND
    # the migration stream entirely.
    dg_px_pe = _Engine(engine.cfg, engine.params, pctx, backend="xla",
                       max_seq=64)
    dg_px_de = _Engine(engine.cfg, engine.params, dctx, backend="xla",
                       max_seq=64, page_size=4)
    se10dg = DisaggServingEngine(dg_px_pe, dg_px_de, max_batch=2,
                                 num_pages=16, prefill_chunk=4,
                                 block_pages=1, prefix_cache=True)
    dg_px_trace = [
        {"req_id": "px-dg-0", "arrival_iter": 0,
         "prompt": px_trace[0]["prompt"], "max_new_tokens": 4,
         "priority": 0},
        # Arrives AFTER px-dg-0's migration lands (prefill slices +
        # one block rotation per iteration), so the admission scores a
        # decode-pool hit instead of racing the cold prefill.
        {"req_id": "px-dg-1", "arrival_iter": 14,
         "prompt": px_trace[0]["prompt"][:14] + [99, 98, 97],
         "max_new_tokens": 4, "priority": 0},
    ]
    dg_px_golden = sequential_reference(engine, dg_px_trace)
    dg_px_report = run_trace(se10dg, dg_px_trace)
    dg_px_reqs = {r.req_id: r for r in dg_px_report.pop("requests")}
    dg_warm = dg_px_reqs["px-dg-1"]
    dg_px_mismatch = [rid for rid, r in dg_px_reqs.items()
                      if r.tokens != dg_px_golden[rid]]
    if not se10dg.disagg_active:
        failures.append(
            f"disagg prefix tier silently demoted "
            f"({se10dg.demotion_reason!r})")
    if dg_px_mismatch:
        failures.append("disagg warm serve token parity broken vs cold "
                        f"sequential serve: {dg_px_mismatch}")
    if dg_warm.prefix_hit_tokens_total < 1:
        failures.append("the disagg follow-up request did not admit "
                        "warm off the decode pool's index")
    if se10dg.prefix_disagg_skips < 1 or dg_warm.migrations != 0:
        failures.append(
            "the decode-pool prefix hit did not skip the prefill role "
            f"+ migration stream (skips={se10dg.prefix_disagg_skips}, "
            f"warm migrations={dg_warm.migrations})")
    if [m["req_id"] for m in se10dg.migrations_log] != ["px-dg-0"]:
        failures.append(
            "migration stream saw an unexpected request set "
            f"({[m['req_id'] for m in se10dg.migrations_log]}) — only "
            "the cold admission should migrate")
    report["prefix"] = {
        "parity_ok": not px_mismatch,
        "warm_requests": px_warm,
        "tokens_saved_total": saved,
        "hit_rate": hit_rate,
        "pages_shared_peak": se10.prefix.pages_shared_peak,
        "pages_held": se10.prefix.pages_held,
        "occupancy_exact": occupancy_exact,
        "megakernel_parity_ok": not mk_px_mismatch,
        "megakernel_warm_requests": mk_px_warm,
        "disagg_parity_ok": not dg_px_mismatch,
        "disagg_skips": se10dg.prefix_disagg_skips,
        "disagg_warm_hit_tokens": dg_warm.prefix_hit_tokens_total,
    }
    _audit("phase10-prefix", se10)
    _audit("phase10-prefix-megakernel", se10mk)
    _audit("phase10-prefix-disagg", se10dg)

    # Phase 11 (ISSUE 17) — multi-replica fleet router (docs/fleet.md):
    # four full serving replicas on CPU behind one admission door. All
    # seeded: (a) per-request token parity vs the sequential oracle
    # with the work actually SPREAD across replicas, and the merged
    # registry carrying replica="..."-labeled series; (b) warm
    # shared-prefix traffic routes to the prefix-holding replica
    # (affinity hits > 0) and prefills STRICTLY fewer tokens than the
    # same trace under round_robin; (c) a replica's rank dies
    # mid-serve — the router drains it, its in-flight requests finish
    # on siblings with parity, and the rejoin probe re-admits it;
    # (d) the autoscaler shrinks an idle fleet then grows it back
    # under queue pressure; per-replica page audits stay clean.
    from triton_distributed_tpu.fleet import (
        Autoscaler, FleetRouter, ReplicaHandle,
    )

    def _mk_fleet(n=4, *, struck=None, policy="affinity",
                  autoscaler=None, **kw):
        kw.setdefault("max_batch", 2)
        kw.setdefault("num_pages", 16)
        kw.setdefault("prefill_chunk", 4)
        kw.setdefault("max_waiting", 8)
        kw.setdefault("prefix_cache", True)
        reps = []
        for i in range(n):
            # Only the to-be-struck replica gets a 2-device mesh: its
            # ledger alone sees rank 1 die, so the kill is surgical.
            devs = (jax.devices()[:2] if i == struck
                    else jax.devices()[:1])
            rctx = initialize_distributed(mesh_shape=(len(devs),),
                                          axis_names=("tp",),
                                          devices=devs)
            reng = _Engine(engine.cfg, engine.params, rctx,
                           backend="xla", max_seq=64, page_size=4)
            reps.append(ReplicaHandle.build(str(i), reng, **kw))
        return FleetRouter(reps, policy=policy, autoscaler=autoscaler)

    # (a) parity + spread + labeled metrics, inside an obs run so the
    # router publishes its lane into the snapshotted registry.
    fr_spec = LoadSpec(n_requests=8, seed=7, mean_interarrival_iters=1.0)
    fr_trace = build_trace(fr_spec)
    fr_golden = sequential_reference(engine, fr_trace)
    with tempfile.TemporaryDirectory() as fr_dir:
        _obs.start_run(fr_dir)
        try:
            router11 = _mk_fleet(4)
            fr_report = run_trace(router11, fr_trace)
            fr_snap = _om.registry().snapshot()
        finally:
            _obs.finish_run()
    fr_reqs = fr_report.pop("requests")
    fr_mismatch = [r.req_id for r in fr_reqs
                   if r.tokens != fr_golden[r.req_id]]
    fr_spread = sorted(rid for rid, rep in router11.replicas.items()
                       if rep.routed > 0)
    if not fr_report["all_finished"]:
        failures.append("fleet: not every routed request reached "
                        "FINISHED")
    if fr_mismatch:
        failures.append("fleet token parity broken vs sequential "
                        f"serve: {fr_mismatch}")
    if len(fr_spread) < 2:
        failures.append(
            f"fleet routed everything to {fr_spread} — the router no "
            "longer spreads cold traffic")
    fr_routed_pub = (fr_snap.get(_om.FLEET_ROUTED) or {}).get("value", 0)
    fr_labeled = sorted({k.split('replica="')[1].split('"')[0]
                         for k in fr_snap if 'replica="' in k})
    if fr_routed_pub != len(fr_trace):
        failures.append(
            f"{_om.FLEET_ROUTED} = {fr_routed_pub!r} in the obs "
            f"snapshot (expected {len(fr_trace)})")
    if len(fr_labeled) < 2:
        failures.append(
            "the merged registry carries replica=-labeled series for "
            f"{fr_labeled} only — per-replica namespacing regressed")
    if router11.sheds:
        failures.append(f"fleet shed {router11.sheds} request(s) on an "
                        "uncontended trace")

    # (b) affinity vs round_robin A/B: same warm two-wave trace, two
    # fresh fleets — affinity must route warm requests to the replica
    # holding their family preamble and so prefill strictly less.
    ab_spec = LoadSpec(n_requests=6, seed=8, prompt_len=(3, 5),
                       max_new=(3, 4), mean_interarrival_iters=2.0,
                       prefix_families=2, prefix_len=12)
    ab_trace = build_trace(ab_spec)
    ab_golden = sequential_reference(engine, ab_trace)
    # Cold seed: the FIRST request of each family only, so each family
    # preamble becomes resident on exactly one replica. (Seeding the
    # whole trace would spread every family over every replica and
    # round_robin would ride the warm pages for free.)
    ab_seen, ab_cold = set(), []
    for t in ab_trace:
        fam_key = tuple(t["prompt"][:12])
        if fam_key not in ab_seen:
            ab_seen.add(fam_key)
            ab_cold.append(t)
    ab_prefill = {}
    ab_routers = {}
    for pol in ("affinity", "round_robin"):
        r_ab = _mk_fleet(3, policy=pol)
        run_trace(r_ab, [dict(t) for t in ab_cold])    # cold: populate
        warm_trace = [dict(t, req_id=t["req_id"] + "-w")
                      for t in ab_trace]
        warm_report = run_trace(r_ab, warm_trace)
        warm_reqs = warm_report.pop("requests")
        ab_bad = [q.req_id for q in warm_reqs
                  if q.tokens != ab_golden[q.req_id[:-2]]]
        if ab_bad:
            failures.append(f"fleet {pol} warm pass broke token parity "
                            f"vs sequential serve: {ab_bad}")
        ab_prefill[pol] = sum(len(q.prompt) - q.prefix_hit_tokens_total
                              for q in warm_reqs)
        ab_routers[pol] = r_ab
    if ab_routers["affinity"].affinity_hits < 1:
        failures.append("warm traffic scored no affinity-routed "
                        "admissions — the shadow index is not fed")
    if not ab_prefill["affinity"] < ab_prefill["round_robin"]:
        failures.append(
            "prefix-affinity routing did not beat round_robin on warm "
            f"traffic (prefill tokens {ab_prefill['affinity']} vs "
            f"{ab_prefill['round_robin']})")

    # (c) kill-one-replica round trip. Distinct prompts so the cold
    # fallback SPREADS work (warm families would all colonise one
    # replica and the struck one would be idle at kill time).
    report_drain = None
    if len(jax.devices()) < 2:
        failures.append("fleet drain segment needs >= 2 virtual CPU "
                        "devices")
    else:
        rejoin_prev = os.environ.get("TDTPU_REJOIN_AFTER")
        os.environ["TDTPU_REJOIN_AFTER"] = "3"
        try:
            router_dr = _mk_fleet(3, struck=1)
        finally:
            if rejoin_prev is None:
                os.environ.pop("TDTPU_REJOIN_AFTER", None)
            else:
                os.environ["TDTPU_REJOIN_AFTER"] = rejoin_prev
        dr_trace = [
            {"req_id": f"fl11-{i}", "arrival_iter": 0,
             "prompt": [13 + 7 * i, 5, 91, 2 + i, 44, 8 + i],
             "max_new_tokens": 4 + (i % 2), "priority": 0}
            for i in range(6)
        ]
        dr_golden = sequential_reference(engine, dr_trace)
        dr_reqs = {}
        for item in dr_trace:
            rq, rs = router_dr.submit(item["prompt"],
                                      item["max_new_tokens"],
                                      req_id=item["req_id"])
            if rs is not AdmitResult.ADMITTED:
                failures.append(f"fleet drain segment: {item['req_id']} "
                                f"refused admission ({rs})")
            else:
                dr_reqs[rq.req_id] = rq
        for _ in range(2):
            router_dr.step()           # first tokens land fleet-wide
        try:
            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore", RuntimeWarning)
                _faults.mark_rank_lost(1)     # replica 1's rank dies
                for _ in range(4):
                    router_dr.step()
                dr_drained = router_dr.replicas["1"].draining
                dr_moved = router_dr.drain_moves
                _faults.clear_rank_loss(1)    # repaired -> rejoin probe
                router_dr.run()
        finally:
            _faults.clear_rank_loss()
        dr_mismatch = [rid for rid, r in dr_reqs.items()
                       if r.tokens != dr_golden[rid]]
        dr_finished = all(r.state.name == "FINISHED"
                          for r in dr_reqs.values())
        if not dr_drained:
            failures.append("the router did not drain the replica whose "
                            "tier evacuated")
        if dr_moved < 1:
            failures.append("the drain moved no in-flight requests — "
                            "the kill no longer lands mid-serve")
        if dr_mismatch:
            failures.append("drained requests broke token parity on "
                            f"their sibling replicas: {dr_mismatch}")
        if not dr_finished:
            failures.append("not every request survived the replica "
                            "kill to FINISHED")
        if router_dr.readmits < 1 or router_dr.replicas["1"].draining:
            failures.append("the drained replica was never re-admitted "
                            "after its rejoin probe")
        report_drain = {
            "drained": dr_drained, "moved": dr_moved,
            "parity_ok": not dr_mismatch,
            "readmitted": router_dr.readmits >= 1,
            "events": [e["event"] for e in router_dr.fleet_log],
        }

    # (d) autoscaler round trip: idle fleet shrinks, queue-pressure
    # burst grows it back — decisions named and step-stamped.
    as_router = _mk_fleet(3, autoscaler=Autoscaler(min_replicas=1,
                                                   cooldown=2,
                                                   queue_high=1.0))
    as_router.submit([7, 8, 9], 2, req_id="as-warm")
    as_router.run()                    # near-idle: shrink fires
    as_shrunk = as_router.autoscaler.shrinks
    for item in build_trace(LoadSpec(n_requests=8, seed=9,
                                     mean_interarrival_iters=0.0)):
        as_router.submit(item["prompt"], item["max_new_tokens"],
                         req_id=item["req_id"] + "-as")
    as_router.run()                    # queue pressure: grow fires
    as_grown = as_router.autoscaler.grows
    if as_shrunk < 1:
        failures.append("the autoscaler never shrank the idle fleet")
    if as_grown < 1:
        failures.append("the autoscaler never grew the fleet back "
                        "under queue pressure")
    as_actions = [d["action"] for d in as_router.autoscaler.log]
    if "shrink" not in as_actions or "grow" not in as_actions[
            as_actions.index("shrink"):]:
        failures.append("autoscaler log lacks the shrink-then-grow "
                        f"sequence: {as_actions}")

    # Per-replica audits (TDTPU_PAGE_AUDIT=1 is still live): one
    # auditor per allocator, each report named with its replica id.
    for rid in sorted(router11.replicas):
        _audit(f"phase11-fleet-replica{rid}", router11.replicas[rid].se)
    audit_names = {rid: rep.op
                   for rid, rep in router11.page_audit_reports().items()}
    if audit_names != {rid: f"replica{rid}"
                       for rid in router11.replicas}:
        failures.append("per-replica page-audit reports are not named "
                        f"by replica id: {audit_names}")

    report["fleet_router"] = {
        "parity_ok": not fr_mismatch,
        "replicas_routed": fr_spread,
        "replica_labels": fr_labeled,
        "affinity_hits": ab_routers["affinity"].affinity_hits,
        "prefill_tokens": ab_prefill,
        "drain": report_drain,
        "autoscale": list(as_router.autoscaler.log),
        "describe": router11.describe(),
    }
    if flight_dir:
        # Next to the flight dumps: CI's obs artifact carries the
        # fleet evidence alongside the postmortem inputs.
        with open(os.path.join(flight_dir, "fleet-report.json"),
                  "w") as f:
            json.dump(report["fleet_router"], f, indent=2, default=str)

    # Phase 12 (ISSUE 18) — step-phase profiler: EVERY serving tier in
    # the sweep (xla, megakernel, disagg, fleet router) must produce
    # per-iteration phase records whose named phases PARTITION the
    # iteration wall (stepprof.check_partition under the loop's own
    # clock) with a nonzero host-bubble fraction; the fleet's records
    # must carry >= 2 replica labels. The summary lands in
    # step-profile.json next to the flight dumps for CI's artifact.
    step_profile: dict[str, dict] = {}

    def _profiled_replay(name: str, se_, trace_):
        prof12 = obs_stepprof.StepProfiler()
        prev12 = obs_stepprof.set_profiler(prof12)
        try:
            run_trace(se_, [dict(t) for t in trace_])
        finally:
            obs_stepprof.set_profiler(prev12)
        recs12 = prof12.records()
        if not recs12:
            failures.append(f"phase 12: {name} produced no step-phase "
                            "records — the profiler hook regressed")
            step_profile[name] = {"iterations": 0, "invariant_ok": False}
            return recs12
        bad12 = []
        for r in recs12:
            prob = obs_stepprof.check_partition(r)
            if prob is not None:
                bad12.append(f"iter {r['it']}: {prob}")
        if bad12:
            failures.append(
                f"phase 12: {name} phase vectors do not partition the "
                f"iteration wall: {bad12[:4]}")
        wall12 = sum(r["wall_ms"] for r in recs12)
        host12 = sum(r["host_ms"] for r in recs12)
        bubble12 = (host12 / wall12) if wall12 else 0.0
        if not bubble12 > 0.0:
            failures.append(
                f"phase 12: {name} host-bubble fraction is zero over "
                f"{len(recs12)} iterations — attribution lost")
        step_profile[name] = {
            "iterations": len(recs12),
            "wall_ms": round(wall12, 3),
            "host_ms": round(host12, 3),
            "device_ms": round(sum(r["device_ms"] for r in recs12), 3),
            "host_bubble_frac": round(bubble12, 4),
            "invariant_ok": not bad12,
            "phases_seen": sorted({p for r in recs12
                                   for p, v in r["phases"].items()
                                   if v > 0}),
        }
        return recs12

    _, se12 = _tiny_serving(engine, max_batch=4, num_pages=8,
                            prefill_chunk=4, max_waiting=8)
    _profiled_replay("xla", se12, trace)
    _audit("phase12-stepprof-xla", se12)
    se12mk = ServingEngine(mk_engine, max_batch=2, num_pages=2,
                           prefill_chunk=128)
    mk12 = _profiled_replay("megakernel", se12mk, mk_trace)
    if mk12 and not any(r["phases"].get("retarget", 0) > 0
                        for r in mk12):
        failures.append(
            "phase 12: no megakernel iteration attributed time to the "
            "queue-retarget phase — the persistent-lane slice regressed")
    _audit("phase12-stepprof-megakernel", se12mk)
    se12dg = DisaggServingEngine(dg_pe, dg_de, max_batch=2, num_pages=5,
                                 prefill_chunk=4, block_pages=1)
    dg12 = _profiled_replay("disagg", se12dg, dg_trace)
    if dg12 and not any(r["phases"].get("migrate", 0) > 0 for r in dg12):
        failures.append(
            "phase 12: no disagg iteration attributed time to the "
            "KV-migration-advance phase")
    _audit("phase12-stepprof-disagg", se12dg)
    router12 = _mk_fleet(2)
    fl12 = _profiled_replay(
        "fleet", router12,
        build_trace(LoadSpec(n_requests=6, seed=12,
                             mean_interarrival_iters=0.0)))
    fl_reps = sorted({r.get("replica") for r in fl12} - {None})
    if len(fl_reps) < 2:
        failures.append(
            f"phase 12: fleet step records carry replica labels "
            f"{fl_reps} — per-replica attribution regressed")
    step_profile.setdefault("fleet", {})["replicas"] = fl_reps
    report["step_profile"] = step_profile
    if flight_dir:
        # Next to the flight dumps: CI's obs artifact carries the
        # host-bubble evidence alongside the postmortem inputs.
        with open(os.path.join(flight_dir, "step-profile.json"),
                  "w") as f:
            json.dump(step_profile, f, indent=2)

    # Phase 13 (ISSUE 19) — goodput work ledger: EVERY serving tier in
    # the sweep must produce per-iteration work records whose categories
    # PARTITION the dispatched token-rows (goodput.check_partition), and
    # the ledger's recompute / spec_rejected lanes must reconcile
    # EXACTLY with the per-request waste counters (request_records
    # carries them — both are fed by the same instrumentation sites).
    # The xla tier replays twice under a deterministic counter clock and
    # must produce byte-identical record streams; the fleet's records
    # must carry >= 2 replica lanes. timeline.json + goodput.spans.json
    # land next to the flight dumps so ``obs.report --check`` gates the
    # goodput lane on CI's artifact.
    goodput13: dict[str, dict] = {}

    class _Tick13:
        """Deterministic counter clock: the loop's only time source, so
        two replays of the same trace are byte-identical."""

        def __init__(self):
            self.t = 0.0

        def __call__(self) -> float:
            self.t = round(self.t + 0.001, 6)
            return self.t

    def _ledgered_replay(name: str, se_, trace_):
        gl13 = obs_goodput.WorkLedger(interval=2)
        prev13 = obs_goodput.set_ledger(gl13)
        try:
            rep13 = run_trace(se_, [dict(t) for t in trace_])
        finally:
            obs_goodput.set_ledger(prev13)
        recs13 = gl13.records()
        if not recs13:
            failures.append(f"phase 13: {name} produced no work records "
                            "— the ledger hook regressed")
            goodput13[name] = {"iterations": 0, "invariant_ok": False}
            return gl13, rep13
        bad13 = []
        for r in recs13:
            prob = obs_goodput.check_partition(r)
            if prob is not None:
                bad13.append(f"iter {r['it']}: {prob}")
        if bad13:
            failures.append(
                f"phase 13: {name} work records break the partition "
                f"invariant: {bad13[:4]}")
        cum13 = gl13.cumulative_all()
        reqs13 = rep13.get("requests") or []
        req_recompute = sum(r.recompute_tokens for r in reqs13)
        req_rejected = sum(r.rejected_tokens for r in reqs13)
        if req_recompute != cum13.get("recompute", 0):
            failures.append(
                f"phase 13: {name} per-request recompute_tokens "
                f"({req_recompute}) do not reconcile with the ledger's "
                f"recompute lane ({cum13.get('recompute', 0)})")
        if req_rejected != cum13.get("spec_rejected", 0):
            failures.append(
                f"phase 13: {name} per-request rejected_tokens "
                f"({req_rejected}) do not reconcile with the ledger's "
                f"spec_rejected lane ({cum13.get('spec_rejected', 0)})")
        goodput13[name] = {
            "iterations": len(recs13),
            "rows": cum13.get("rows", 0),
            "work": {c: cum13[c] for c in obs_goodput.CATEGORIES
                     if c in cum13},
            "goodput_frac": (round(cum13.get("useful", 0)
                                   / cum13["rows"], 4)
                             if cum13.get("rows") else 1.0),
            "prefill_saved": cum13.get("prefill_saved", 0),
            "invariant_ok": not bad13,
            "reconciled": (req_recompute == cum13.get("recompute", 0)
                           and req_rejected
                           == cum13.get("spec_rejected", 0)),
        }
        return gl13, rep13

    _, se13a = _tiny_serving(engine, max_batch=4, num_pages=8,
                             prefill_chunk=4, max_waiting=8,
                             clock=_Tick13())
    gl13a, _ = _ledgered_replay("xla", se13a, trace)
    if not any(r["work"].get("useful", 0) > 0 for r in gl13a.records()):
        failures.append("phase 13: no xla iteration attributed useful "
                        "rows — the decode/prefill hooks regressed")
    # Byte-determinism: a second fresh tier under its own counter clock
    # replaying the SAME trace must serialize to the SAME bytes.
    _, se13b = _tiny_serving(engine, max_batch=4, num_pages=8,
                             prefill_chunk=4, max_waiting=8,
                             clock=_Tick13())
    gl13b, _ = _ledgered_replay("xla-replay", se13b, trace)
    if (json.dumps(gl13a.records(), sort_keys=True)
            != json.dumps(gl13b.records(), sort_keys=True)):
        failures.append(
            "phase 13: two replays of the same trace under the counter "
            "clock produced different work-record bytes — the ledger "
            "leaked a wall-clock or ordering dependence")
    se13mk = ServingEngine(mk_engine, max_batch=2, num_pages=2,
                           prefill_chunk=128)
    gl13mk, _ = _ledgered_replay("megakernel", se13mk, mk_trace)
    se13dg = DisaggServingEngine(dg_pe, dg_de, max_batch=2, num_pages=5,
                                 prefill_chunk=4, block_pages=1)
    gl13dg, _ = _ledgered_replay("disagg", se13dg, dg_trace)
    if gl13dg.cumulative_all().get("overhead", 0) <= 0:
        failures.append(
            "phase 13: disagg replay attributed no overhead rows — the "
            "KV-migration transport accounting regressed")
    router13 = _mk_fleet(2)
    gl13fl, _ = _ledgered_replay(
        "fleet", router13,
        build_trace(LoadSpec(n_requests=6, seed=13,
                             mean_interarrival_iters=0.0)))
    fl13_reps = sorted({r.get("replica") for r in gl13fl.records()}
                       - {None})
    if len(fl13_reps) < 2:
        failures.append(
            f"phase 13: fleet work records carry replica lanes "
            f"{fl13_reps} — per-replica ledger attribution regressed")
    goodput13.setdefault("fleet", {})["replicas"] = fl13_reps
    report["goodput"] = goodput13
    if flight_dir:
        # Next to the flight dumps: the fleet ledger's counter tracks
        # (richest lane set — per-replica series) + interval timeline,
        # so CI's obs artifact carries the goodput evidence and
        # ``obs.report --check`` gates the lane.
        gl13fl.save(os.path.join(flight_dir, "goodput.spans.json"))
        gl13fl.save_timeline(os.path.join(flight_dir, "timeline.json"))

    # Phase 14 (ISSUE 20) — KV tiering to host RAM + the async
    # double-buffered loop: a host-budgeted tier over a device pool
    # sized to force chain eviction must swap the cache-only chain OUT
    # to host instead of dropping it, then serve the warm re-admission
    # by RESTORING it — zero cold prefill over the restored span
    # (prefill_saved credit in the ledger, host-transport rows in the
    # overhead lane, tdtpu_kv_host_{swapouts,restores}_total in the
    # registry) — token-identical to the cold sequential oracle. The
    # SAME trace replayed sync and async under counter clocks must
    # produce byte-identical token-relevant request records, with the
    # goodput partition invariant holding every async iteration and
    # nonzero plan/device overlap in the async step profile (and none
    # in the sync profile — overlap windows only open when a launch is
    # held across the commit boundary).
    pre14 = list(range(10, 22))
    kv_trace = [
        # A chain the radix index keeps after FINISH (6 pages at
        # page_size 4: 16 prompt + 5 generated tokens).
        {"req_id": "kt-warmup", "arrival_iter": 0,
         "prompt": pre14 + [3, 5, 8, 9], "max_new_tokens": 5,
         "priority": 0},
        # A fat cold request (8 of the pool's 10 pages): reclaim MUST
        # eat the cache-only chain, and with a host budget attached the
        # physical free becomes a swap-out.
        {"req_id": "kt-pressure", "arrival_iter": 12,
         "prompt": list(range(30, 58)), "max_new_tokens": 4,
         "priority": 0},
        # The warm re-admission: its prefix now lives on HOST only.
        {"req_id": "kt-warm", "arrival_iter": 30,
         "prompt": pre14 + [3, 5, 8, 9], "max_new_tokens": 5,
         "priority": 0},
    ]
    kv_golden = sequential_reference(engine, kv_trace)

    def _kv_replay(async_loop: bool):
        """One counter-clocked replay of kv_trace through a fresh
        host-budgeted tier inside its own obs run: returns (se, report,
        profiler records, ledger, registry snapshot). The RUN's own
        step-profiler and work ledger are the evidence — start_run
        installs them, so a privately-swapped pair would be shadowed."""
        with tempfile.TemporaryDirectory() as kv_dir:
            _obs.start_run(kv_dir)
            try:
                _, se14_ = _tiny_serving(
                    engine, max_batch=2, num_pages=10,
                    prefill_chunk=4, max_waiting=8,
                    prefix_cache=True,
                    kv_host_budget_bytes=1 << 30,
                    async_loop=async_loop, clock=_Tick13())
                prof14 = obs_stepprof.get_profiler()
                gl14 = obs_goodput.get_ledger()
                rep14_ = run_trace(se14_, [dict(t) for t in kv_trace])
                prof14_recs = (prof14.records()
                               if prof14 is not None else [])
                snap14_ = _om.registry().snapshot()
            finally:
                _obs.finish_run()
        return se14_, rep14_, prof14_recs, gl14, snap14_

    se14, rep14, prof14s, gl14s, kv_snap = _kv_replay(async_loop=False)
    se14a, rep14a, prof14a, gl14a, kv_snap_a = _kv_replay(async_loop=True)
    kv_tier = se14.kvtier
    if kv_tier is None or se14a.kvtier is None:
        failures.append(
            "phase 14: the host tier did not attach under an explicit "
            "kv_host_budget_bytes — the ctor wiring regressed")
    for label, rep_, se_ in (("sync", rep14, se14),
                             ("async", rep14a, se14a)):
        kv_reqs = {r.req_id: r for r in rep_.pop("requests")}
        kv_mismatch = [rid for rid, r in kv_reqs.items()
                       if r.tokens != kv_golden[rid]]
        if kv_mismatch or not rep_["all_finished"]:
            failures.append(
                f"phase 14: {label} replay broke token parity vs the "
                f"cold sequential oracle: {kv_mismatch} "
                f"(all_finished={rep_['all_finished']})")
        tier_ = se_.kvtier
        if tier_ is not None and tier_.swap_outs < 1:
            failures.append(
                f"phase 14: {label} replay swapped no chain to host — "
                "the device pool sizing no longer forces eviction of "
                "the cache-only chain")
        warm_ = kv_reqs.get("kt-warm")
        if tier_ is not None and (
                tier_.restores < 1 or warm_ is None
                or warm_.restored_tokens_total < 1):
            failures.append(
                f"phase 14: {label} warm re-admission did not restore "
                f"from the host tier (restores="
                f"{tier_.restores if tier_ else None}, restored_tokens="
                f"{warm_.restored_tokens_total if warm_ else None})")
        if warm_ is not None and warm_.restored_tokens_total > 0 \
                and warm_.prefix_hit_tokens_total \
                < warm_.restored_tokens_total:
            failures.append(
                f"phase 14: {label} warm request counts more restored "
                "tokens than admitted hit tokens — the restored span "
                "was cold-prefilled anyway")
    # Ledger evidence (sync replay): restored tokens ride the
    # prefill_saved CREDIT; the host->device transport is the only
    # overhead source in this tier, so the overhead lane reconciles
    # EXACTLY with the per-request restored counters.
    cum14 = gl14s.cumulative_all() if gl14s is not None else {}
    restored14 = sum(r["restored_tokens"] for r in rep14["request_records"])
    if cum14.get("prefill_saved", 0) < 1:
        failures.append(
            "phase 14: warm restore credited no prefill_saved rows in "
            "the work ledger")
    if cum14.get("overhead", 0) != restored14:
        failures.append(
            f"phase 14: ledger overhead lane ({cum14.get('overhead', 0)}) "
            f"does not reconcile with the per-request restored tokens "
            f"({restored14}) — the host-transport accounting regressed")
    bad14 = [f"iter {r['it']}: {p}"
             for r in (gl14a.records() if gl14a is not None else [])
             if (p := obs_goodput.check_partition(r)) is not None]
    if bad14:
        failures.append(
            f"phase 14: async work records break the partition "
            f"invariant: {bad14[:4]}")
    # Registry evidence: the kv-tier lane obs.report --check gates on.
    for snap_, lbl_ in ((kv_snap, "sync"), (kv_snap_a, "async")):
        so14 = (snap_.get(_om.KV_HOST_SWAPOUTS) or {}).get("value", 0)
        rs14 = (snap_.get(_om.KV_HOST_RESTORES) or {}).get("value", 0)
        if not so14 or not rs14:
            failures.append(
                f"phase 14: {lbl_} registry kv-tier lane empty "
                f"(swapouts={so14!r}, restores={rs14!r}) — the gauge "
                "publication regressed")
        if _om.KV_HOST_RESTORE_MS not in snap_:
            failures.append(
                f"phase 14: {lbl_} run carries no "
                f"{_om.KV_HOST_RESTORE_MS} histogram")
    # Byte-identity: the async loop reorders WHEN host work happens,
    # never WHAT tokens come out — so the token-relevant record fields
    # (everything except wall-clock-derived latencies) serialize to the
    # SAME bytes.
    _kv_fields = ("req_id", "tokens", "preemptions", "prefix_hit_tokens",
                  "restored_tokens", "recompute_tokens",
                  "rejected_tokens", "drafted", "accepted", "state")

    def _kv_bytes(rep_):
        return json.dumps([{k: r[k] for k in _kv_fields}
                           for r in rep_["request_records"]],
                          sort_keys=True)

    if _kv_bytes(rep14) != _kv_bytes(rep14a):
        failures.append(
            "phase 14: async and sync replays of the same trace under "
            "counter clocks produced different token-relevant request "
            "records — the double-buffered loop is not a pure "
            "reordering")
    async_overlap = sum(r.get("overlapped_ms", 0.0) for r in prof14a)
    if not any(r.get("overlapped_ms", 0.0) > 0 for r in prof14a):
        failures.append(
            "phase 14: no async iteration overlapped host work with "
            "the in-flight device step — the plan/commit split is not "
            "buying anything")
    if any(r.get("overlapped_ms", 0.0) > 0 for r in prof14s):
        failures.append(
            "phase 14: the SYNC loop recorded overlap windows — "
            "overlap_begin leaked outside the pending-launch path")
    report["kv_tier"] = {
        "parity_ok": not any(f.startswith("phase 14") for f in failures),
        "swap_outs": kv_tier.swap_outs if kv_tier else None,
        "restores": kv_tier.restores if kv_tier else None,
        "host_evictions": kv_tier.host_evictions if kv_tier else None,
        "restored_tokens": restored14,
        "prefill_saved": cum14.get("prefill_saved", 0),
        "async_overlapped_ms": round(async_overlap, 3),
        "async_iterations": len(prof14a),
        "records_byte_identical": _kv_bytes(rep14) == _kv_bytes(rep14a),
    }
    _audit("phase14-kvtier", se14)
    _audit("phase14-kvtier-async", se14a)

    if audit_prev is None:
        os.environ.pop("TDTPU_PAGE_AUDIT", None)
    else:
        os.environ["TDTPU_PAGE_AUDIT"] = audit_prev
    audited_clean = bool(page_audits) and all(
        a["ok"] for a in page_audits.values())
    report["page_audit"] = {"ok": audited_clean, "phases": page_audits}
    if flight_dir:
        # Next to the flight dumps, so CI's obs artifact carries it and
        # ``obs.report --check`` can gate on recorded violations.
        with open(os.path.join(flight_dir, "page-audit.json"), "w") as f:
            json.dump(report["page_audit"], f, indent=2)

    report["failures"] = failures
    if json_path:
        with open(json_path, "w") as f:
            json.dump(report, f, indent=2)
    print(json.dumps({k: v for k, v in report.items()
                      if k != "per_request"}, indent=2))
    if failures:
        for msg in failures:
            print(f"DRYRUN FAIL: {msg}", file=sys.stderr)
        return 1
    print("serving dryrun: all assertions passed")
    return 0


# ---------------------------------------------------------------------------
# The TPU bench rung (bench.py).
# ---------------------------------------------------------------------------

def _bench_shard_config():
    """The Qwen3-8B TP=8 PER-DEVICE shard shape every serving rung
    measures — ONE definition, so the monolithic, megakernel and disagg
    rows always race identical models (they are gate-compared)."""
    from triton_distributed_tpu.models.config import ModelConfig

    return ModelConfig(hidden_size=4096, intermediate_size=1536,
                       num_layers=36, num_heads=4, num_kv_heads=1,
                       head_dim=128, vocab_size=151936, qk_norm=True)

def serving_bench_rung(n_streams: int = 8, prompt_len: int = 128,
                       max_new: int = 16, *, backend: str = "xla",
                       page_size: int = 64, kv_dtype=None,
                       spec_k: int = 0,
                       async_loop: bool = False) -> dict:
    """Tokens/s + p99 TTFT/TPOT at ``n_streams`` concurrent streams on
    the Qwen3-8B TP=8 PER-DEVICE shard shapes (the same single-chip
    pricing discipline as the decode rungs: n=1, no ICI in the number;
    host scheduler dispatch IS included — that is what a serving tier
    costs). One warmup replay compiles every trace, the second replay is
    the measurement.

    ``backend="megakernel"`` (round 9) serves decode through the paged
    persistent kernel (page_size must be TILE = 128 there — the lane's
    pool pages are workspace KV tiles); bench.py races it against the
    xla rung in the same window (`serve_tokens_per_s_megakernel`).

    ``kv_dtype`` (round 12): the paged pool's storage dtype —
    ``float8_e4m3fn`` is the fp8-KV rung (half the decode DMA bytes;
    bench.py races it against the full-width rung in the same window,
    `serve_tokens_per_s_fp8kv`).

    ``spec_k`` (round 14): the speculative draft depth — the
    accepted-tokens/s ledger rung (`serve_tokens_per_s_spec`) races the
    one-token rung in the same window and reports the measured accept
    rate (`spec_accept_rate` — accepted drafts / drafted, from the
    per-request ledger, so no obs run is required). The workload gains
    a repeated-phrase prompt shape when spec is on: lookup drafting
    exists for exactly that traffic.

    ``async_loop`` (ISSUE 20): the double-buffered plan/commit split —
    iteration i+1's host work runs while iteration i's device step is
    in flight. bench.py races it against the sync rung in the same
    window: ``serve_host_bubble_frac`` must come out strictly LOWER
    async (that is the whole point of the split) at exact token
    parity."""
    import jax
    import jax.random as jrandom

    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.serving.loop import ServingEngine

    cfg = _bench_shard_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    engine = Engine(cfg, params, ctx1, backend=backend, max_seq=512,
                    page_size=page_size, kv_dtype=kv_dtype)
    se = ServingEngine(engine, max_batch=n_streams, prefill_chunk=128,
                       spec_k=spec_k, async_loop=async_loop)
    if backend == "megakernel" and se._mk is None:
        # The rung exists to price the persistent lane; silently racing
        # a demoted dense loop would mislabel the ledger row.
        raise RuntimeError(
            f"megakernel serving lane demoted at construction (engine "
            f"backend now {engine.backend!r}) — rung not measurable")

    def make_trace(seed: int) -> list[dict]:
        spec = LoadSpec(n_requests=n_streams, seed=seed,
                        prompt_len=(prompt_len, prompt_len),
                        max_new=(max_new, max_new),
                        mean_interarrival_iters=0.0, vocab=cfg.vocab_size)
        trace = build_trace(spec)
        if spec_k > 0:
            # Repeated-phrase prompts (seeded): the shared-preamble /
            # template traffic shape prompt-lookup drafting pays off on.
            rng = np.random.default_rng(seed + 1000)
            for item in trace:
                phrase = rng.integers(0, cfg.vocab_size, 8).tolist()
                reps = -(-len(item["prompt"]) // len(phrase))
                item["prompt"] = (phrase * reps)[:len(item["prompt"])]
        return trace

    run_trace(se, make_trace(0))                           # warmup/compile
    # Step-phase profile of the MEASURED replay only (ISSUE 18): a
    # private profiler swapped in around the second replay, so an
    # enclosing obs run's profiler (if any) neither pollutes nor is
    # polluted by the rung's phase records.
    prof = obs_stepprof.StepProfiler()
    prev_prof = obs_stepprof.set_profiler(prof)
    # Work ledger of the MEASURED replay only (ISSUE 19): same private
    # swap discipline as the profiler above.
    gl = obs_goodput.WorkLedger()
    prev_gl = obs_goodput.set_ledger(gl)
    try:
        report = run_trace(se, make_trace(1))
    finally:
        obs_stepprof.set_profiler(prev_prof)
        obs_goodput.set_ledger(prev_gl)
    prof_recs = prof.records()
    reqs = report.pop("requests")
    out = {
        "serve_tokens_per_s_concurrent": report["tokens_per_s"],
        "serve_ttft_p99_ms": report["ttft_p99_ms"],
        "serve_tpot_p99_ms": report["tpot_p99_ms"],
        "serve_concurrent_streams": n_streams,
        "serve_comm": f"none (n=1 shard; {backend} decode path); host "
                      "scheduler + per-iteration dispatch included — "
                      "the serving tier's real cost, unlike the pure "
                      "decode-chain rungs",
    }
    if prof_recs:
        # Host-bubble rungs (ISSUE 18): the fraction of measured-replay
        # iteration wall spent in host-attributed phases, and the p99
        # per-iteration host milliseconds — the synchronous-loop
        # overhead the ledger tracks downward.
        wall = sum(r["wall_ms"] for r in prof_recs)
        host = sum(r["host_ms"] for r in prof_recs)
        out["serve_host_bubble_frac"] = (round(host / wall, 4)
                                         if wall else None)
        from triton_distributed_tpu.obs.metrics import percentile
        out["serve_step_host_ms_p99"] = round(
            percentile([r["host_ms"] for r in prof_recs], 99), 4)
    if gl.has_records():
        # Goodput rung (ISSUE 19): the cumulative useful fraction of
        # dispatched device token-rows over the measured replay — the
        # waste (spec rejections, recompute, overhead, padding) the
        # ledger tracks upward toward 1.0.
        out["serve_goodput_frac"] = round(gl.goodput_frac(), 4)
    if spec_k > 0:
        drafted = sum(r.drafted_tokens for r in reqs)
        accepted = sum(r.accepted_draft_tokens for r in reqs)
        if se._spec_fallback:
            raise RuntimeError(
                "speculative lane fell back to one-token decode during "
                "the measurement — rung not measurable as spec")
        out["spec_drafted_tokens"] = drafted
        out["spec_accepted_tokens"] = accepted
        out["spec_accept_rate"] = (round(accepted / drafted, 4)
                                   if drafted else None)
        out["spec_k"] = spec_k
    return out


def warm_serving_bench_rung(n_streams: int = 8, prompt_len: int = 128,
                            max_new: int = 16, *,
                            page_size: int = 64) -> dict:
    """The prefix-cache rung (ISSUE 15, docs/serving.md "Prefix
    cache"): the same open-loop workload as :func:`serving_bench_rung`
    but with SHARED-PREFIX traffic (two prompt families, 128-token
    preambles + divergent tails) served twice through ONE
    prefix-enabled tier — the first replay compiles AND populates the
    radix index, the second replay is the WARM measurement (every
    admission hits a resident preamble and prefills only its tail).
    bench.py races it against the cold rung in the same window
    (`serve_ttft_p99_ms_warm` / `serve_tokens_per_s_warm`): the TTFT
    delta is what the prefix cache buys a multi-tenant fleet."""
    import jax
    import jax.random as jrandom

    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.serving.loop import ServingEngine

    cfg = _bench_shard_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=512,
                    page_size=page_size)
    se = ServingEngine(engine, max_batch=n_streams, prefill_chunk=128,
                       prefix_cache=True)

    def make_trace(seed: int) -> list[dict]:
        # prefix_seed is fixed (LoadSpec default), so both replays share
        # the SAME family preambles — the warm shape — while the tails
        # and arrival jitter vary with the trace seed.
        spec = LoadSpec(n_requests=n_streams, seed=seed,
                        prompt_len=(max(1, prompt_len - 128),
                                    max(1, prompt_len - 128)),
                        max_new=(max_new, max_new),
                        mean_interarrival_iters=0.0, vocab=cfg.vocab_size,
                        prefix_families=2, prefix_len=128)
        return build_trace(spec)

    run_trace(se, make_trace(0))              # warmup: compile + index
    report = run_trace(se, make_trace(1))     # warm measurement
    reqs = report.pop("requests")
    warm = [r for r in reqs if r.prefix_hit_tokens_total > 0]
    if not warm:
        raise RuntimeError(
            "no measurement request admitted warm — the rung would "
            "mislabel a cold run as prefix-cache throughput")
    return {
        "serve_tokens_per_s_warm": report["tokens_per_s"],
        "serve_ttft_p99_ms_warm": report["ttft_p99_ms"],
        "serve_warm_requests": len(warm),
        "serve_prefill_tokens_saved": se.prefix.tokens_saved,
        "serve_prefix_hit_rate": round(se.prefix.hit_rate(), 4),
        "serve_warm_comm": "none (n=1 shard; prefix-cache warm replay "
                           "— shared 128-token preambles resident, "
                           "only divergent tails prefill)",
    }


def kvtier_serving_bench_rung(n_streams: int = 8, prompt_len: int = 128,
                              max_new: int = 16, *,
                              page_size: int = 64) -> dict:
    """The host KV-tier rung (ISSUE 20, docs/serving.md "KV tiering"):
    the warm rung's shared-prefix workload over a device pool sized so
    a burst of COLD traffic evicts the cached family chains — with a
    host budget attached, the eviction SWAPS them to pinned host
    buffers instead of dropping them. The measured replay then admits
    warm off the HOST tier: every warm TTFT includes the checksummed
    host→device restore stream, and that p99
    (``serve_ttft_p99_ms_swapin``) raced against the device-resident
    warm rung's ``serve_ttft_p99_ms_warm`` in the same window is what
    the tier costs — against ``serve_ttft_p99_ms`` (cold) it is what
    the tier buys. ``kv_host_restore_ms`` is the per-restore p99."""
    import jax
    import jax.random as jrandom

    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.runtime import initialize_distributed
    from triton_distributed_tpu.serving.loop import ServingEngine

    cfg = _bench_shard_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])
    engine = Engine(cfg, params, ctx1, backend="xla", max_seq=512,
                    page_size=page_size)
    # Pool sizing: 8 concurrent 144-token requests need 24 pages; 28
    # leaves too little slack to ALSO keep the finished family chains
    # device-resident through the cold burst — reclaim must swap them.
    se = ServingEngine(engine, max_batch=n_streams, num_pages=28,
                       prefill_chunk=128, prefix_cache=True,
                       kv_host_budget_bytes=4 << 30)
    if se.kvtier is None:
        raise RuntimeError("host KV tier did not attach — rung not "
                           "measurable")
    restore_ms: list[float] = []
    orig_restore = se._kvtier_restore

    def timed_restore(req, n_restore, _o=orig_restore):
        t0 = time.perf_counter()
        out = _o(req, n_restore)
        restore_ms.append((time.perf_counter() - t0) * 1e3)
        return out

    se._kvtier_restore = timed_restore

    def make_warm_trace(seed: int) -> list[dict]:
        # Same family discipline as the warm rung: fixed prefix_seed,
        # page-aligned 128-token preambles, divergent tails.
        spec = LoadSpec(n_requests=n_streams, seed=seed,
                        prompt_len=(max(1, prompt_len - 128),
                                    max(1, prompt_len - 128)),
                        max_new=(max_new, max_new),
                        mean_interarrival_iters=0.0, vocab=cfg.vocab_size,
                        prefix_families=2, prefix_len=128)
        return build_trace(spec)

    def make_cold_trace(seed: int) -> list[dict]:
        spec = LoadSpec(n_requests=n_streams, seed=seed,
                        prompt_len=(prompt_len, prompt_len),
                        max_new=(max_new, max_new),
                        mean_interarrival_iters=0.0, vocab=cfg.vocab_size)
        return build_trace(spec)

    run_trace(se, make_warm_trace(0))     # warmup: compile + index
    run_trace(se, make_cold_trace(7))     # cold burst: force swap-out
    if se.kvtier.swap_outs < 1:
        raise RuntimeError(
            "cold burst swapped no chain to host — the pool sizing no "
            "longer forces eviction; rung not measurable")
    restore_ms.clear()
    report = run_trace(se, make_warm_trace(1))   # host-warm measurement
    reqs = report.pop("requests")
    swapin = sorted(r.ttft_s * 1e3 for r in reqs
                    if r.restored_tokens_total > 0 and r.ttft_s is not None)
    if not swapin or not restore_ms:
        raise RuntimeError(
            "no measurement request restored from the host tier — the "
            "rung would mislabel a device-warm run as swap-in TTFT")
    from triton_distributed_tpu.obs.metrics import percentile
    return {
        "serve_ttft_p99_ms_swapin": round(percentile(swapin, 99), 3),
        "kv_host_restore_ms": round(percentile(restore_ms, 99), 3),
        "serve_swapin_requests": len(swapin),
        "kv_host_swap_outs": se.kvtier.swap_outs,
        "kv_host_restores": se.kvtier.restores,
        "serve_swapin_comm": (
            "none (n=1 shard; warm admissions restore evicted family "
            "chains from pinned host RAM through the checksummed "
            "double-buffered stream — restore cost is IN the TTFT)"),
    }


def disagg_serving_bench_rung(n_streams: int = 8, prompt_len: int = 128,
                              max_new: int = 16, *,
                              page_size: int = 64) -> dict:
    """The disaggregated tier's rung (round 10, docs/disagg.md): the
    same open-loop workload as :func:`serving_bench_rung`, served
    through a :class:`~triton_distributed_tpu.disagg.engine.
    DisaggServingEngine` — prefill role on the first device, decode role
    on the second (falling back to one shared device on single-chip
    hosts), every finished prefill crossing a checksummed KV-migration
    stream. bench.py races it against the monolithic rung in the SAME
    window (`serve_tokens_per_s_disagg`); the number includes the full
    migration cost — that is what disaggregation buys or pays."""
    import jax
    import jax.random as jrandom

    from triton_distributed_tpu.disagg import (
        DisaggServingEngine, role_contexts,
    )
    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.models.dense import init_dense_llm

    cfg = _bench_shard_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    pctx, dctx = role_contexts(jax.devices()[:2])
    pe = Engine(cfg, params, pctx, backend="xla", max_seq=512)
    de = Engine(cfg, params, dctx, backend="xla", max_seq=512,
                page_size=page_size)
    se = DisaggServingEngine(pe, de, max_batch=n_streams,
                             prefill_chunk=128)
    spec = LoadSpec(n_requests=n_streams, seed=0,
                    prompt_len=(prompt_len, prompt_len),
                    max_new=(max_new, max_new),
                    mean_interarrival_iters=0.0, vocab=cfg.vocab_size)
    run_trace(se, build_trace(spec))                       # warmup/compile
    if not se.disagg_active:
        # The rung prices the role-split path; a demoted run would
        # mislabel the ledger row as disagg throughput.
        raise RuntimeError(
            f"disagg tier demoted during warmup "
            f"({se.demotion_reason!r}) — rung not measurable")
    spec2 = dataclasses.replace(spec, seed=1)
    report = run_trace(se, build_trace(spec2))
    report.pop("requests")
    if not se.disagg_active:
        raise RuntimeError(
            f"disagg tier demoted mid-measurement "
            f"({se.demotion_reason!r}) — rung not measurable")
    two_dev = pe.ctx.mesh.devices.ravel()[0] != de.ctx.mesh.devices.ravel()[0]
    return {
        "serve_tokens_per_s_disagg": report["tokens_per_s"],
        "serve_ttft_p99_ms_disagg": report["ttft_p99_ms"],
        "serve_disagg_migrations": len(se.migrations_log),
        "serve_disagg_comm": (
            f"prefill/decode roles on "
            f"{'two chips (KV blocks cross device_put/DCN)' if two_dev else 'one shared chip (degenerate roles)'}"
            "; checksummed double-buffered migration included in the "
            "number"),
    }


def fleet_serving_bench_rung(n_replicas: int = 4, n_streams: int = 8,
                             prompt_len: int = 128, max_new: int = 16,
                             *, page_size: int = 64) -> dict:
    """The fleet router's rung (ISSUE 17, docs/fleet.md): the open-loop
    workload of :func:`serving_bench_rung` scaled to ``n_replicas``×
    the requests, served through a :class:`~triton_distributed_tpu.
    fleet.FleetRouter` over ``n_replicas`` full replicas of the same
    Qwen3-8B shard. Virtual replicas SERIALIZE on one host, so the
    rung reports the parallel-equivalent makespan — per router
    iteration the SLOWEST replica step is what a real data-parallel
    fleet would wait on, so the wall is Σ max-per-iteration — and
    bench.py races it against a 1-replica fleet measured identically
    in the same window (`serve_tokens_per_s_fleet` +
    `serve_fleet_scaling_x`): near-linear scaling is what the router
    must not tax away in routing/drain bookkeeping."""
    import jax
    import jax.random as jrandom

    from triton_distributed_tpu.fleet import FleetRouter, ReplicaHandle
    from triton_distributed_tpu.models import Engine
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.runtime import initialize_distributed

    cfg = _bench_shard_config()
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    ctx1 = initialize_distributed(mesh_shape=(1,), axis_names=("tp",),
                                  devices=jax.devices()[:1])

    def build_router(n):
        durs: list[float] = []
        reps = []
        for i in range(n):
            eng = Engine(cfg, params, ctx1, backend="xla", max_seq=512,
                         page_size=page_size)
            rep = ReplicaHandle.build(str(i), eng, max_batch=n_streams,
                                      prefill_chunk=128)
            orig = rep.se.step

            def timed_step(_orig=orig):
                t0 = time.perf_counter()
                out = _orig()
                durs.append(time.perf_counter() - t0)
                return out

            rep.se.step = timed_step
            reps.append(rep)
        router = FleetRouter(reps)
        iter_maxes: list[float] = []
        orig_step = router.step

        def step():
            durs.clear()
            out = orig_step()
            if durs:
                iter_maxes.append(max(durs))
            return out

        router.step = step
        router._iter_maxes = iter_maxes
        return router

    def make_trace(n_requests, seed):
        spec = LoadSpec(n_requests=n_requests, seed=seed,
                        prompt_len=(prompt_len, prompt_len),
                        max_new=(max_new, max_new),
                        mean_interarrival_iters=0.0,
                        vocab=cfg.vocab_size)
        return build_trace(spec)

    def measure(n):
        router = build_router(n)
        run_trace(router, make_trace(n * n_streams, 0))  # warmup/compile
        router._iter_maxes.clear()
        report = run_trace(router, make_trace(n * n_streams, 1))
        report.pop("requests")
        if not report["all_finished"] or router.sheds:
            raise RuntimeError(
                f"fleet rung not measurable: finished="
                f"{report['all_finished']}, sheds={router.sheds} — a "
                "shed or hung request would mislabel the ledger row")
        wall = max(sum(router._iter_maxes), 1e-9)
        return report["tokens"] / wall

    single_tps = measure(1)
    fleet_tps = measure(n_replicas)
    return {
        "serve_tokens_per_s_fleet": round(fleet_tps, 3),
        "serve_fleet_scaling_x": round(fleet_tps / max(single_tps, 1e-9),
                                       3),
        "serve_fleet_replicas": n_replicas,
        "serve_fleet_comm": (
            f"none ({n_replicas} data-parallel n=1 shards, no ICI; "
            "parallel-equivalent makespan = per-iteration max replica "
            "step; router admission/bookkeeping included)"),
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m triton_distributed_tpu.serving.loadgen",
        description="Deterministic open-loop load generator for the "
                    "continuous-batching serving tier (docs/serving.md).")
    ap.add_argument("--dryrun", action="store_true",
                    help="seeded 8-request CPU proof: parity vs "
                         "sequential serve (incl. preempt/resume), "
                         "backpressure, SLO admission shrink")
    ap.add_argument("--json", default=None,
                    help="write the run report to this path")
    ap.add_argument("--flight-dir", default=None,
                    help="keep phase 8's obs run directory (flight "
                         "dumps + request timelines) here for "
                         "obs.postmortem / the CI artifact (default: a "
                         "temp dir)")
    args = ap.parse_args(argv)
    if args.dryrun:
        return dryrun(args.json, flight_dir=args.flight_dir)
    ap.error("only --dryrun is wired as a CLI entry today; the bench "
             "rung runs through bench.py (serving_bench_rung)")
    return 2


if __name__ == "__main__":
    sys.exit(main())
