"""Host-RAM KV tier: second-chance storage for evicted prefix chains.

ISSUE 20 (ROADMAP item 3, the Mooncake-style KV tiering half): the
radix prefix cache (serving/prefix.py) is HBM-sized — when
refcount×recency eviction frees a cache-only chain, the chain's KV
bytes die and the next warm request pays a cold prefill. This module
gives those pages a second tier: at eviction time the prefix cache
swaps each cache-only chunk to a pinned host buffer (a plain numpy
copy on CPU builds — the pinned-allocation discipline is the TPU
runtime's), and a later admission whose prompt extends past the
device-resident hit restores the chain back into the pool through the
disagg :class:`~triton_distributed_tpu.disagg.migrate.MigrationStream`
double-buffer transport (serving/loop.py owns that wiring).

Content addressing makes this safe (docs/serving.md "Prefix cache"):
KV at a position depends only on the tokens at and before it, so a
host entry keyed by the FULL token prefix through its chunk is valid
for any request whose prompt starts with those tokens — the entry
outlives the device page id it was copied from.

Integrity: every entry is stamped with a float32 checksum at swap-out
and RE-VERIFIED at restore time (the bytes sat in host RAM for
arbitrarily long); a mismatch drops the entry and raises the named
TRANSIENT :class:`HostTierIntegrityError`, so the serving loop's
prefill-fault path degrades the request to a cold(er) prefill — never
wrong tokens. fp8 pools swap at STORED width (the entry holds the
pool's e4m3 bytes, not a dequantized copy), so host budget and
restore bytes both price the real pool page.

Budget: ``TDTPU_KV_HOST_BUDGET_BYTES`` (or the ``budget_bytes`` ctor
arg) bounds resident host bytes; the tier runs its own recency (LRU)
eviction when over budget. Budget 0 disables the tier.

PURE HOST module (numpy only): the device hops — the pool-page fetch
at swap-out and the restore stream's ``put``/scatter — are callbacks
installed by the serving loop.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Callable

import numpy as np


class HostTierError(RuntimeError):
    """A host-tier restore failed in a named way (entry evicted
    mid-restore, chunk dropped by the chaos plane) — TRANSIENT by
    design (``transient = True`` is the marker
    ``resilience.is_transient`` honors): the serving loop preempts the
    restoring request and recomputes the chain as a cold prefill."""

    transient = True


class HostTierIntegrityError(HostTierError):
    """A host entry's checksum no longer matches the bytes about to
    restore — corrupt host RAM (or a chaos injection) detected BEFORE
    the chunk re-enters the pool. The entry is dropped, so the retry
    admission prefills the positions cold instead of re-reading the
    same corrupt copy."""


def host_kv_budget_bytes() -> int:
    """Host-tier byte budget from ``TDTPU_KV_HOST_BUDGET_BYTES``
    (default 0 = tier disabled)."""
    try:
        return int(os.environ.get("TDTPU_KV_HOST_BUDGET_BYTES", "") or 0)
    except ValueError:
        return 0


def _checksum(k: np.ndarray, v: np.ndarray) -> float:
    """f32 sum of both halves, one fixed routine for stamp and verify
    (numpy on host bytes both times, so summation order — and
    therefore the float — is identical unless the bytes changed)."""
    return float(np.asarray(k, np.float32).sum()
                 + np.asarray(v, np.float32).sum())


@dataclasses.dataclass
class _HostChunk:
    """One swapped-out page: host copies of its (k, v) pool bytes at
    stored width, the integrity stamp, and the recency key."""

    k: np.ndarray
    v: np.ndarray
    checksum: float
    last_use: int
    nbytes: int


class HostKVTier:
    """Bounded host-RAM store of evicted prefix-cache chunks.

    Entries are content-addressed by the FULL token-id prefix through
    the chunk (``tuple(tokens[:end])``) — the same keying discipline as
    the radix index, flattened: a chain of n chunks becomes n entries,
    and :meth:`match` re-walks them chunk-by-chunk from any
    device-resident hit boundary.

    Args:
      budget_bytes: resident host-byte ceiling (None reads
        ``TDTPU_KV_HOST_BUDGET_BYTES``; <= 0 disables — every
        ``swap_out`` is refused and ``match`` finds nothing).
      page_size: tokens per page/chunk (must equal the pool's).
      fetch: ``fetch(page) -> (k, v)`` numpy copies of one pool page at
        stored width — installed by the serving loop (it owns the
        device arrays). Swap-outs are refused until it is set.
    """

    def __init__(self, budget_bytes: int | None = None, *,
                 page_size: int, fetch: Callable | None = None):
        if page_size < 1:
            raise ValueError(
                f"page_size = {page_size} invalid: host-tier chunks are "
                "pool pages — argument page_size")
        self.budget_bytes = (host_kv_budget_bytes() if budget_bytes is None
                             else int(budget_bytes))
        self.page_size = page_size
        self.fetch = fetch
        # Fault-injection point for the chaos plane (resilience/chaos.py):
        # hook(chunk_idx, (k, v)) -> (k, v) | None per restored chunk —
        # None models a chunk lost between host RAM and the pool, a
        # mutated pair models corruption past the checksum stamp.
        self.chaos_hook = None
        self._entries: dict[tuple[int, ...], _HostChunk] = {}
        self._clock = 0                  # logical recency counter
        self.bytes_held = 0
        # Evidence (obs satellite + loadgen phase 14).
        self.swap_outs = 0               # pages copied to host
        self.restores = 0                # pages streamed back to the pool
        self.host_evictions = 0          # entries LRU-dropped over budget
        self.restore_failures = 0        # streams that raised (named)
        self.integrity_failures = 0      # checksum mismatches on restore

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    @property
    def pages(self) -> int:
        """Entries (= pages) currently resident in host RAM."""
        return len(self._entries)

    # -- swap-out (called by PrefixCache.reclaim) ---------------------------
    def swap_out(self, chain, page: int) -> bool:
        """Copy pool ``page`` — the chunk holding positions
        ``[len(chain) - page_size, len(chain))`` of token prefix
        ``chain`` — into host RAM before the eviction decref frees it.
        Returns True when the entry is resident afterwards (already
        present counts: the bytes are identical by content
        addressing, only recency refreshes)."""
        if not self.enabled:
            return False
        key = tuple(int(t) for t in chain)
        self._clock += 1
        ent = self._entries.get(key)
        if ent is not None:
            ent.last_use = self._clock
            return True
        if self.fetch is None:
            return False
        k, v = self.fetch(int(page))
        k = np.asarray(k)
        v = np.asarray(v)
        nbytes = int(k.nbytes + v.nbytes)
        if nbytes > self.budget_bytes:
            return False                 # one chunk can never fit
        self._entries[key] = _HostChunk(k, v, _checksum(k, v),
                                        self._clock, nbytes)
        self.bytes_held += nbytes
        self.swap_outs += 1
        self._evict_to_budget()
        return True

    def _evict_to_budget(self) -> None:
        """The tier's OWN recency eviction: drop least-recently-used
        entries until under budget (insertion just bumped the newest,
        so it is never the victim)."""
        while self.bytes_held > self.budget_bytes and self._entries:
            key = min(self._entries, key=lambda s: self._entries[s].last_use)
            self._drop(key)
            self.host_evictions += 1

    def _drop(self, key: tuple[int, ...]) -> bool:
        ent = self._entries.pop(key, None)
        if ent is None:
            return False
        self.bytes_held -= ent.nbytes
        return True

    def drop_chain(self, keys) -> int:
        """Forget entries (a failed restore's pending chain: the retry
        admission must degrade to a cold prefill, not re-walk into the
        same failure). Returns the count actually dropped."""
        return sum(1 for key in keys if self._drop(tuple(key)))

    def clear(self) -> int:
        """Drop everything — the prefix cache calls this from
        ``invalidate()``: after a device rebuild/evacuation the mesh
        geometry (and so the collective reassociation the KV floats
        rode) may have changed, and bit-exact parity is the contract."""
        n = len(self._entries)
        self._entries.clear()
        self.bytes_held = 0
        return n

    # -- admission-side walk -------------------------------------------------
    def match(self, tokens, start: int) -> list[tuple[int, ...]]:
        """Chunk keys resident in host RAM extending a device-side hit:
        walks ``tokens`` chunk-by-chunk from page-aligned ``start``,
        capped at ``len(tokens) - 1`` (at least one token must prefill
        — its logits produce the next token). READ-ONLY, like
        ``PrefixCache.match``: recency moves only when a chunk actually
        restores."""
        if not self._entries or start % self.page_size:
            return []
        toks = [int(t) for t in tokens]
        cap = len(toks) - 1
        keys: list[tuple[int, ...]] = []
        pos = int(start)
        while pos + self.page_size <= cap:
            key = tuple(toks[:pos + self.page_size])
            if key not in self._entries:
                break
            keys.append(key)
            pos += self.page_size
        return keys

    # -- restore-side assembly ----------------------------------------------
    def chunk(self, key, *, chunk_idx: int = 0
              ) -> tuple[np.ndarray, np.ndarray]:
        """The host bytes for one chain key, checksum re-verified (the
        restore-side half of the swap-out stamp). Raises the named
        transient :class:`HostTierError` family when the entry is gone
        (tier eviction raced the restore) or fails verification — the
        corrupt/raced entry is dropped first, so a retry admission
        degrades to a cold prefill instead of looping."""
        key = tuple(int(t) for t in key)
        ent = self._entries.get(key)
        if ent is None:
            raise HostTierError(
                f"host-tier chunk {chunk_idx} (chain of {len(key)} "
                "tokens) evicted between admission match and restore — "
                "the request recomputes these positions cold")
        k, v = ent.k, ent.v
        if self.chaos_hook is not None:
            kv = self.chaos_hook(chunk_idx, (k, v))
            if kv is None:
                self._drop(key)
                raise HostTierError(
                    f"host-tier chunk {chunk_idx} lost between host RAM "
                    "and the pool — restore incomplete, the pages must "
                    "not enter the decode batch")
            k, v = kv
        got = _checksum(k, v)
        if got != ent.checksum:
            self._drop(key)
            self.integrity_failures += 1
            raise HostTierIntegrityError(
                f"host-tier chunk {chunk_idx} checksum mismatch on "
                f"restore (stamped {ent.checksum!r}, read {got!r}) — "
                "corrupt host copy dropped before entering the pool")
        self._clock += 1
        ent.last_use = self._clock
        return k, v

    def note_restored(self, n_pages: int) -> None:
        """Count pages a completed restore streamed back (the serving
        loop calls this once the whole chain landed)."""
        self.restores += int(n_pages)

    def stats(self) -> dict:
        return {
            "pages": self.pages,
            "bytes_held": self.bytes_held,
            "budget_bytes": self.budget_bytes,
            "swap_outs": self.swap_outs,
            "restores": self.restores,
            "host_evictions": self.host_evictions,
            "restore_failures": self.restore_failures,
            "integrity_failures": self.integrity_failures,
        }
