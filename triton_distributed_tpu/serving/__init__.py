"""Continuous-batching serving tier (ISSUE 7, ROADMAP open item #1).

The request-level layer above ``models/engine.Engine``: a vLLM-style
iteration-level schedule (Orca, OSDI'22; PagedAttention, SOSP'23) over
the repo's own paged KV pool, chunked prefill and SLO watchdog —
docs/serving.md.

* :mod:`~triton_distributed_tpu.serving.request` — request lifecycle
  (WAITING → PREFILLING → RUNNING → PREEMPTED → FINISHED) + latency /
  page-budget accounting;
* :mod:`~triton_distributed_tpu.serving.scheduler` — pure-host
  admission/preemption state machine over the page allocator;
* :mod:`~triton_distributed_tpu.serving.loop` — :class:`ServingEngine`,
  the mixed prefill+decode iteration driver;
* :mod:`~triton_distributed_tpu.serving.loadgen` — deterministic
  open-loop load generator, the CPU dryrun proof and the bench rung;
* :mod:`~triton_distributed_tpu.serving.spec` — self-drafting
  speculative-decode proposer (prompt lookup; ``ServingEngine(spec_k=)``
  is the lane's switch — docs/serving.md "Speculative decode");
* :mod:`~triton_distributed_tpu.serving.prefix` — radix-indexed
  copy-on-write prefix cache for multi-tenant reuse
  (``ServingEngine(prefix_cache=True)`` — docs/serving.md "Prefix
  cache").
"""

from triton_distributed_tpu.serving.request import (  # noqa: F401
    Request, RequestState,
)
from triton_distributed_tpu.serving.scheduler import (  # noqa: F401
    AdmitResult, RequestTooLargeError, Scheduler, SchedulerConfigError,
)
from triton_distributed_tpu.serving.loop import (  # noqa: F401
    ServingConfigError, ServingEngine,
)
from triton_distributed_tpu.serving.spec import (  # noqa: F401
    NGramProposer, SpecConfigError,
)
from triton_distributed_tpu.serving.prefix import (  # noqa: F401
    PrefixCache, PrefixConfigError,
)

__all__ = ["Request", "RequestState", "AdmitResult", "Scheduler",
           "SchedulerConfigError", "RequestTooLargeError",
           "ServingConfigError", "ServingEngine", "NGramProposer",
           "SpecConfigError", "PrefixCache", "PrefixConfigError"]
