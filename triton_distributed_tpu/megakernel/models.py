"""MegaKernel model assembly — a whole decode step as one task queue.

Reference: ``mega_triton_kernel/models/qwen3.py`` + ``model_builder.py``
(make_qkv_proj / make_attn / make_o_proj / fc / silu_mul / rms_norm / add /
allreduce assemble a Qwen3 decode step replayed as one persistent kernel —
the 3.33 ms headline path, BASELINE.md).

TPU assembly for a TP-sharded Qwen3-style layer (per device):

    x ── rms_norm ── q/k/v proj ── per-head qk-norm + RoPE ──
      attn_decode per q head (cached KV + in-step current token) ──
      o-proj ── AllReduce ── +residual ──
      rms_norm ── gate/up proj ── silu·mul ── down proj ── AllReduce ──
      +residual

The current token's k/v join each attention task's softmax directly
(ATTN_DECODE c0/d0 operands); with ``inkernel_append=True`` the cache is
then appended IN-KERNEL by APPEND_KV tasks (matching the reference's
in-kernel append; the WAR hazard on the cache tiles orders the append
after the attention reads), retargeted per position by
``advance_queue_pos``. Without the flag the host appends after the step
(pure-functional update — the test-friendly default). Constraints:
head_dim == TILE (128, the Qwen3 value), batch <= TILE,
hidden/ffn_local/head counts multiples of TILE where tiled.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers.common import rope_cos_sin
from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
from triton_distributed_tpu.megakernel.tasks import (
    TILE, MatHandle, TensorHandle,
)


def broadcast_rows(vec: np.ndarray) -> np.ndarray:
    """A (cols,) vector as the (TILE, cols) broadcast tensor the RMS_NORM /
    ROPE tasks read (row-replicated; tile (0, j) carries columns of j)."""
    return np.broadcast_to(np.asarray(vec, np.float32),
                           (TILE, vec.shape[-1])).copy()


def rope_tables(pos: int, head_dim: int, theta: float) -> tuple[np.ndarray, np.ndarray]:
    """(TILE, min(head_dim·?, TILE)) cos/sin tables at ``pos`` (HF
    half-split: each half repeats the head_dim/2 table). head_dim < TILE
    pads the tables to the TILE-wide tile the padded-head layout feeds
    (columns >= head_dim are zero — the head's pad lanes stay zero)."""
    cos, sin = rope_cos_sin(jnp.asarray([pos]), head_dim, theta)
    cos, sin = np.asarray(cos)[0], np.asarray(sin)[0]
    cos2 = np.concatenate([cos, cos])
    sin2 = np.concatenate([sin, sin])
    if head_dim < TILE:
        pad = np.zeros(TILE - head_dim, np.float32)
        cos2 = np.concatenate([cos2, pad])
        sin2 = np.concatenate([sin2, pad])
    return broadcast_rows(cos2), broadcast_rows(sin2)


def pad_head_cols(w, head_dim: int):
    """(K, h·head_dim) → (K, h·TILE): each head's columns land in the low
    ``head_dim`` lanes of its own tile, pad lanes zero — the head_dim <
    TILE layout (round 9; at head_dim == TILE this is the identity)."""
    if head_dim == TILE:
        return w
    w = jnp.asarray(w)
    k, hd_total = w.shape
    h = hd_total // head_dim
    w = w.reshape(k, h, head_dim)
    w = jnp.pad(w, ((0, 0), (0, 0), (0, TILE - head_dim)))
    return w.reshape(k, h * TILE)


def pad_head_rows(w, head_dim: int):
    """(h·head_dim, N) → (h·TILE, N): the row-parallel (o-proj) twin of
    :func:`pad_head_cols` — pad rows are zero, so the attention output's
    zero pad lanes contribute nothing to the product."""
    if head_dim == TILE:
        return w
    w = jnp.asarray(w)
    hd_total, n = w.shape
    h = hd_total // head_dim
    w = w.reshape(h, head_dim, n)
    w = jnp.pad(w, ((0, 0), (0, TILE - head_dim), (0, 0)))
    return w.reshape(h * TILE, n)


def pad_head_vec(vec, head_dim: int) -> np.ndarray:
    """A (head_dim,) per-head norm weight padded to the (TILE,) tile row
    the broadcast q/k-norm tensors store."""
    vec = np.asarray(vec, np.float32)
    if head_dim == TILE:
        return vec
    return np.concatenate([vec, np.zeros(TILE - head_dim, np.float32)])


def _col(t: TensorHandle, j: int) -> TensorHandle:
    """Single column-tile view (valid because activations have rt == 1)."""
    assert t.rt == 1
    return TensorHandle(t.base + j, TILE, TILE)


@dataclasses.dataclass
class DecodeLayerHandles:
    """Workspace handles for one layer's weights + caches + outputs.

    Two weight layouts exist (use :func:`feed_layer_weights` to feed
    either): the round-5 MATRIX layout (default for dense bf16/fp32 —
    ``wqkv``/``w_gateup`` are fused MatHandles, ``wo``/``w_down`` are
    MatHandles, and ``wq/wk/wv/w_gate/w_up`` are None) and the tiled
    layout (fp8 / MoE-FFN — every field is a TensorHandle)."""

    attn_norm: TensorHandle     # (TILE, hidden) broadcast
    mlp_norm: TensorHandle
    q_norm: TensorHandle        # (TILE, d) broadcast (Qwen3 qk-norm)
    k_norm: TensorHandle
    wq: TensorHandle | None     # (hidden, hq_local*d)
    wk: TensorHandle | None     # (hidden, hkv_local*d)
    wv: TensorHandle | None
    wo: TensorHandle | MatHandle    # (hq_local*d, hidden)
    w_gate: TensorHandle | None     # (hidden, ffn_local)
    w_up: TensorHandle | None
    w_down: TensorHandle | MatHandle  # (ffn_local, hidden)
    kT: list[TensorHandle]      # per kv head: (d, S) keys transposed
    v: list[TensorHandle]       # per kv head: (S, d)
    k_new: TensorHandle         # (TILE, hkv_local*d) — this step's k (out)
    v_new: TensorHandle
    # MoE FFN (Qwen3-MoE decode; None = dense MLP). Router cols padded to
    # TILE (zero weights → zero logits, masked by MOE_TOPK's E bound).
    moe_router: TensorHandle | None = None   # (hidden, TILE)
    moe_w_gate: TensorHandle | None = None   # (E·hidden, ffn_local)
    moe_w_up: TensorHandle | None = None
    moe_w_down: TensorHandle | None = None   # (E·ffn_local, hidden)
    # Matrix-workspace layout (round 5 — see class docstring):
    wqkv: MatHandle | None = None       # (hidden, (hq+2*hkv)*d) fused
    w_gateup: MatHandle | None = None   # (hidden, ffn_local) pair
    qkv_out: TensorHandle | None = None  # (TILE, (hq+2*hkv)*d) q|k|v row


def feed_layer_weights(feeds: dict, h: DecodeLayerHandles, *, wq, wk, wv,
                       wo, w_gate=None, w_up=None, w_down=None,
                       head_dim: int = TILE) -> dict:
    """Insert one layer's projection/MLP weights into ``feeds`` in
    whichever layout the program was built with (matrix or tiled) —
    callers pass the natural per-matrix values and never see the fused
    qkv / interleaved gate|up storage. ``head_dim`` < TILE: q/k/v columns
    and o-proj rows are padded per head into TILE-wide groups (the
    padded-head layout the round-9 head_dim-64 programs use)."""
    wq = pad_head_cols(wq, head_dim)
    wk = pad_head_cols(wk, head_dim)
    wv = pad_head_cols(wv, head_dim)
    wo = pad_head_rows(wo, head_dim)
    if h.wqkv is not None:
        feeds[h.wqkv] = jnp.concatenate(
            [jnp.asarray(wq), jnp.asarray(wk), jnp.asarray(wv)], axis=1)
    else:
        feeds[h.wq] = wq
        feeds[h.wk] = wk
        feeds[h.wv] = wv
    feeds[h.wo] = wo
    if h.moe_w_gate is not None:
        # MoE layer: the expert FFN feeds through the moe_w_* handles;
        # dense-FFN values passed here are ignored (h.w_gate may be None
        # in the matrix layout — keying feeds by None would surface later
        # as an opaque split_feeds crash).
        return feeds
    if (w_gate is None) != (w_up is None):
        # A lone half would surface much later as an opaque
        # jnp.asarray(None) crash inside scatter_mat — fail at the call.
        raise ValueError(
            "feed_layer_weights needs BOTH w_gate and w_up (or neither); "
            f"got w_gate={'set' if w_gate is not None else None}, "
            f"w_up={'set' if w_up is not None else None}")
    if w_gate is not None:
        if h.w_gateup is not None:
            feeds[h.w_gateup] = (w_gate, w_up)
        else:
            feeds[h.w_gate] = w_gate
            feeds[h.w_up] = w_up
    if w_down is not None:
        feeds[h.w_down] = w_down
    return feeds


@dataclasses.dataclass
class DecodeStepProgram:
    """Builder + handles for a full decode step."""

    mb: MegaKernelBuilder
    x: TensorHandle
    layers: list[DecodeLayerHandles]
    cos: TensorHandle
    sin: TensorHandle
    x_out: TensorHandle
    # build_decode_step(final_norm=True): the final RMSNorm weight handle
    # (broadcast rows) — the norm runs IN-KERNEL, fused into the last
    # layer's residual tail, and x_out is already normalized.
    fnorm: TensorHandle | None = None
    # Row-blocked emission (round 9, batch > TILE): per-block output rows
    # (block 0 == x_out — single-block programs keep the old contract).
    x_out_blocks: list[TensorHandle] | None = None
    blocks: int = 1
    # Paged-serving retarget metadata (build_decode_step with
    # kv_pool_pages): per block, the emitted ATTN_DECODE_PAGED /
    # APPEND_KV task ids with their pool base tiles — the host rewrites
    # these rows (+ their table DATA rows) each step. See
    # megakernel/serving.PagedMegakernelDecoder.
    paged_meta: dict | None = None


def row_block(t: TensorHandle, b: int) -> TensorHandle:
    """Row-block ``b`` of a (bt·TILE, cols) tensor as its own (TILE,
    cols) view — row-major tile ids make block b's tiles contiguous at
    ``base + b·ct`` (the round-9 row-blocked emission's addressing)."""
    return TensorHandle(t.base + b * t.ct, TILE, t.cols)


def advance_queue_pos(base_queue, pos: int, num_exec: int | None = None):
    """Re-target a compiled decode queue to position ``pos`` WITHOUT
    recompiling: ATTN_DECODE's valid_len (word 6) and visited-tile count
    (word 4) are runtime queue words, so one host-side int32 edit per step
    retargets every attention task — the decode loop replays ONE compiled
    kernel. RoPE tables are workspace inputs: feed ``rope_tables(pos, ...)``
    alongside. (The reference re-enqueues task params the same way,
    model_builder.py enque_tasks/run.)

    ``base_queue`` must come from a program built at ``pos = max_seq - 1``
    (full cache capacity in word 4); returns an updated int32 copy.
    """
    from triton_distributed_tpu.megakernel.tasks import TaskType

    # base_queue may be a CompiledMegaKernel (preferred — carries the
    # executable/data row split) or a raw queue array.
    if hasattr(base_queue, "queue"):
        if num_exec is None:
            num_exec = base_queue.num_exec
        base_queue = base_queue.queue
    q = np.asarray(base_queue).copy()
    attn = ((q[:, 0] == int(TaskType.ATTN_DECODE))
            | (q[:, 0] == int(TaskType.ATTN_DECODE_PAGED))
            | (q[:, 0] == int(TaskType.ATTN_DECODE_PAGED_F8))
            | (q[:, 0] == int(TaskType.ATTN_DECODE_GQA)))
    if num_exec is not None:
        # Rows beyond the executable prefix are page-table DATA — their
        # words must never be interpreted as task fields.
        attn[num_exec:] = False
    elif np.any((q[:, 0] == int(TaskType.ATTN_DECODE_PAGED))
                | (q[:, 0] == int(TaskType.ATTN_DECODE_PAGED_F8))):
        # Paged programs append raw tile-id DATA rows after the tasks; a
        # row starting with 8/9 would match the mask and get corrupted.
        raise ValueError(
            "queue contains ATTN_DECODE_PAGED tasks: pass the "
            "CompiledMegaKernel (or num_exec=) so page-table DATA rows "
            "are not misread as tasks")
    need = -(-pos // TILE)
    if np.any(q[attn, 4] < need):
        raise ValueError(
            f"base queue visits {int(q[attn, 4].min())} cache "
            f"tiles but pos {pos} needs {need} — build the program at "
            "pos = max_seq - 1 (silently dropping cache positions would "
            "corrupt the softmax)")
    if pos < 1 and np.any(q[attn, 8] < 0):
        raise ValueError("pos 0 with a cache-only attention task would be "
                         "an all-masked softmax")
    q[attn, 6] = pos
    q[attn, 4] = np.minimum(q[attn, 4], need)
    # APPEND_KV rows are self-describing (a_stride/b_stride = cache base
    # tiles): retarget the destination tile + intra-tile column to ``pos``.
    app = ((q[:, 0] == int(TaskType.APPEND_KV))
           | (q[:, 0] == int(TaskType.APPEND_KV_F8)))
    if num_exec is not None:
        app[num_exec:] = False
    ti, col = pos // TILE, pos % TILE
    q[app, 1] = q[app, 5] + ti        # out = kT base tile + pos tile
    q[app, 3] = q[app, 6] + ti        # b0  = v base tile + pos tile
    q[app, 8] = col                   # c0  = intra-tile column/row
    return jnp.asarray(q)


def build_decode_layer(mb: MegaKernelBuilder, x: TensorHandle,
                       h: DecodeLayerHandles, cos: TensorHandle,
                       sin: TensorHandle, *, hq_local: int, hkv_local: int,
                       pos: int, num_ranks: int,
                       eps: float = 1e-6, paged: bool = False,
                       inkernel_append: bool = False,
                       moe_experts: int = 0, moe_topk: int = 0,
                       batch: int = 1,
                       xn: TensorHandle | None = None,
                       out_norm: tuple[TensorHandle, TensorHandle] | None = None,
                       force_ar_tasks: bool = False,
                       head_dim: int = TILE,
                       mat_prefetch: bool = False,
                       paged_tables: list[list[tuple[int, int]]] | None = None,
                       append_pos: int | None = None,
                       meta_out: dict | None = None,
                       spec_append: bool = False):
    """Emit one transformer layer's decode tasks (for ONE row block —
    build_decode_step loops blocks for batch > TILE).

    Round-6 cross-layer contract: ``xn`` is the already-NORMALIZED input
    row (produced by the previous layer's fused tail); ``None`` emits the
    standalone rms_norm (layer 0 / direct callers). ``out_norm`` is
    ``(norm_w, norm_out)`` — the NEXT consumer's norm (the next layer's
    attn norm, or the model's final norm) fused into this layer's
    residual tail, so the residual row never round-trips HBM between the
    add and the norm and the consuming norm task disappears from the
    queue. ``force_ar_tasks`` emits the AllReduce sites even at
    ``num_ranks == 1`` (the n=1-loopback cross-device rung — bench.py).

    Round 9: ``head_dim`` < TILE runs the padded-head layout (each head
    in the low head_dim lanes of its tile — the attention score/value
    math is pad-invariant, only the norm/rope sub-tile span changes).
    ``mat_prefetch`` emits PREFETCH_MAT warms so the o-proj (and, on the
    AR path, gate/up) weight chunk streams under the attention task /
    the ALLREDUCE_ROW barrier. ``paged_tables`` overrides the identity
    page tables with explicit per-kv-head (kT tile, v tile) lists (the
    serving pool form); ``append_pos`` targets in-kernel appends at a
    different build-time position than ``pos`` (the serving build parks
    them on the scratch page); ``meta_out`` collects the emitted
    paged-attention/append task ids for host retargeting.

    Returns ``(x2, x2n)``: the residual-stream output and its fused-norm
    row (``None`` unless ``out_norm`` was given)."""
    hidden = x.cols
    d = TILE                       # head TILE width (padded at head_dim<TILE)
    groups = hq_local // hkv_local
    scale = head_dim ** -0.5
    ar = num_ranks > 1 or force_ar_tasks

    if xn is None:
        xn = mb.tensor(TILE, hidden)
        # No weight prefetches since the strip-fetch GEMM (round 4): one
        # (W, TILE, TILE) strip DMA replaced the per-tile stream, so a
        # single-tile warm would be discarded — each prefetch would cost a
        # dispatch plus a wasted tile fetch. (The PREFETCH task types
        # remain for direct builder use; reference weight-prefetch,
        # SURVEY.md §2.7.)
        mb.rms_norm(xn, x, h.attn_norm, eps)

    if h.wqkv is not None:
        # Matrix path (round 5): ONE fused qkv GEMM_MAT task — the q|k|v
        # output row is contiguous (k_new/v_new are views into qkv_out),
        # the A row loads once for all three projections, and the task
        # body is a static specialized branch (tasks.py GEMM_MAT).
        q = TensorHandle(h.qkv_out.base, TILE, hq_local * d)
        mb.gemm_mat(h.qkv_out, xn, h.wqkv)
        # Round 6: qk-norm + RoPE over ALL q+k heads in ONE task — the
        # norm weights and rope tables load once per layer instead of
        # once per head (hq+hkv-1 dispatches disappear).
        mb.norm_rope_qkv(q, hq_local, h.k_new, hkv_local, h.q_norm,
                         h.k_norm, cos, sin, eps)
    else:
        q = mb.tensor(TILE, hq_local * d)
        mb.gemm(q, xn, h.wq)
        mb.gemm(h.k_new, xn, h.wk)
        mb.gemm(h.v_new, xn, h.wv)
        # Tiled/fp8 layout: k_new is not contiguous after q, so the fused
        # whole-row task cannot apply — per-head qk-norm + RoPE.
        for j in range(hq_local):
            mb.norm_rope(_col(q, j), _col(q, j), h.q_norm, cos, sin, eps)
        for j in range(hkv_local):
            mb.norm_rope(_col(h.k_new, j), _col(h.k_new, j), h.k_norm,
                         cos, sin, eps)

    mat = isinstance(h.wo, MatHandle)
    # Round-9 stall-slice kill: the o-proj's first weight chunk starts
    # streaming NOW — it lands under the attention task(s) the scheduler
    # places in between, instead of serializing after them.
    warm_o = mat_prefetch and mat
    if warm_o:
        mb.prefetch_mat(h.wo)

    attn = mb.tensor(TILE, hq_local * d)
    if paged:
        # Paged cache (reference mega_triton_kernel PagedKVCache): the
        # kT/v handles are PAGE POOLS; each attention task walks an
        # identity page table packed as queue DATA rows, which the host
        # can rewrite per step to remap logical pages onto pool tiles
        # (tables are data, so any allocator works without recompiling).
        # Limitations vs the linear GQA path (deliberate, documented): the
        # paged task is single-head, so a GQA group re-streams its shared
        # KV pool `groups` times and each q-head carries its OWN copy of
        # the kv-head's table — a host remapper must rewrite every task's
        # DATA rows (find them via each task's b0 word), not just one.
        n_pages = h.kT[0].ct
        for j in range(hq_local):
            kv = j // groups
            if paged_tables is not None:
                pages = paged_tables[kv]
            else:
                pages = [(h.kT[kv].tile(0, p), h.v[kv].tile(p, 0))
                         for p in range(n_pages)]
            tid = mb.attn_decode_paged(_col(attn, j), _col(q, j), pages,
                                       valid_len=pos, scale=scale,
                                       k_new=_col(h.k_new, kv),
                                       v_new=_col(h.v_new, kv),
                                       kv8=h.kT[kv].kv8)
            if meta_out is not None:
                meta_out.setdefault("attn", []).append(
                    (tid, h.kT[kv].tile(0, 0), h.v[kv].tile(0, 0)))
    else:
        # One task per KV head: the whole GQA group's q-heads share the KV
        # stream (tiles fetched once per group, not once per head).
        for kv in range(hkv_local):
            mb.attn_decode_gqa(attn, kv * groups, q, kv * groups, groups,
                               h.kT[kv], h.v[kv], valid_len=pos,
                               scale=scale, k_new=_col(h.k_new, kv),
                               v_new=_col(h.v_new, kv))

    if inkernel_append:
        # In-kernel KV append (reference model_builder.py appends inside
        # its attn tasks): the WAR hazards on the cache tiles order these
        # after this layer's attention reads. advance_queue_pos (linear)
        # or the paged-serving host remapper retargets the destination
        # tile/column per step.
        apos = append_pos if append_pos is not None else pos
        for kv in range(hkv_local):
            tid = mb.append_kv(h.kT[kv], h.v[kv], apos,
                               _col(h.k_new, kv), _col(h.v_new, kv))
            if meta_out is not None:
                meta_out.setdefault("append", []).append(
                    (tid, h.kT[kv].tile(0, 0), h.v[kv].tile(0, 0)))
            if spec_append:
                # Speculative draft-and-verify (docs/serving.md): a
                # candidate window can SPAN two page tiles, so each kv
                # head gets a second append row for the spill — the host
                # retargets both per step (or parks the spill via
                # c0 = -1); parked on scratch at build time like the
                # primary, so the WAR edges vs this layer's attention
                # reads are identical.
                tid2 = mb.append_kv(h.kT[kv], h.v[kv], apos,
                                    _col(h.k_new, kv), _col(h.v_new, kv))
                if meta_out is not None:
                    meta_out.setdefault("append", []).append(
                        (tid2, h.kT[kv].tile(0, 0), h.v[kv].tile(0, 0)))

    nw, nout = out_norm if out_norm is not None else (None, None)
    x1 = mb.tensor(TILE, hidden)
    x1n = mb.tensor(TILE, hidden)
    if mat and not ar:
        # Fused o-proj + residual add + THIS layer's mlp norm (epilogue 3
        # — the round-6 mid-layer fusion: the x1 row stays VMEM-resident
        # between the add and the norm, and the rms_norm task disappears).
        mb.gemm_mat(x1, attn, h.wo, residual=x, norm_w=h.mlp_norm,
                    norm_out=x1n, eps=eps, prefetch_first=warm_o)
    else:
        o = mb.tensor(TILE, hidden)
        if mat:
            mb.gemm_mat(o, attn, h.wo, prefetch_first=warm_o)
        else:
            mb.gemm(o, attn, h.wo)
        if ar:
            # Round 9: the gate/up chunk streams UNDER the AllReduce
            # barrier — the warm DMA is local, the AR wait is remote.
            if mat_prefetch and h.w_gateup is not None:
                mb.prefetch_mat(h.w_gateup)
            mb.all_reduce(o)
        # Fused residual add + mlp norm (ADD_NORM — the cross-layer
        # fusion's form for paths where an AllReduce sits between the
        # GEMM and the add).
        mb.add_norm(x1, x, o, h.mlp_norm, x1n, eps)

    if h.moe_w_gate is not None:
        down = mb.tensor(TILE, hidden)
        # Qwen3-MoE FFN: router GEMM → in-kernel top-k/softmax → ONE
        # expert-loop task with data-dependent skipping (tasks.py MOE_FFN;
        # only ~B·topk of E experts stream their weights).
        logits = mb.tensor(TILE, TILE)
        mb.gemm(logits, x1n, h.moe_router)
        wt = mb.tensor(TILE, TILE)
        mb.moe_topk(wt, logits, moe_topk, moe_experts, batch)
        mb.moe_ffn(down, x1n, wt, h.moe_w_gate, h.moe_w_up, h.moe_w_down,
                   moe_experts)
    elif h.w_gateup is not None:
        # Fused gate/up/act: one GEMM_MAT over the interleaved pair with
        # the silu epilogue, then down (+residual when no AR follows —
        # with ``out_norm`` also fusing the NEXT consumer's norm, the
        # round-6 cross-LAYER epilogue).
        warm_gu = mat_prefetch and ar
        act = mb.tensor(TILE, h.w_gateup.n)
        mb.gemm_mat(act, x1n, h.w_gateup, prefetch_first=warm_gu)
        if not ar:
            x2 = mb.tensor(TILE, hidden)
            if nw is not None:
                mb.gemm_mat(x2, act, h.w_down, residual=x1, norm_w=nw,
                            norm_out=nout, eps=eps)
                return x2, nout
            mb.gemm_mat(x2, act, h.w_down, residual=x1)
            return x2, None
        down = mb.tensor(TILE, hidden)
        mb.gemm_mat(down, act, h.w_down)
    else:
        down = mb.tensor(TILE, hidden)
        ffn_local = h.w_gate.cols
        gate = mb.tensor(TILE, ffn_local)
        up = mb.tensor(TILE, ffn_local)
        act = mb.tensor(TILE, ffn_local)
        mb.gemm(gate, x1n, h.w_gate)
        mb.gemm(up, x1n, h.w_up)
        mb.silu_mul(act, gate, up)
        mb.gemm(down, act, h.w_down)
    if ar:
        mb.all_reduce(down)
    x2 = mb.tensor(TILE, hidden)
    if nw is not None:
        # Cross-layer residual-chain fusion across the AR seam: one task
        # produces BOTH x2 and the next layer's normalized input.
        mb.add_norm(x2, x1, down, nw, nout, eps)
        return x2, nout
    mb.add(x2, x1, down)
    return x2, None


def _check_decode_step_config(*, hidden, hq_local, hkv_local, ffn_local,
                              num_layers, max_seq, pos, batch, head_dim,
                              moe_experts, moe_topk,
                              fp8_weights=False,
                              inkernel_append=False, paged=False,
                              kv_fp8=False, seq_blocks=False,
                              spec_window=1) -> None:
    """Named build-time validation: every TILE/geometry constraint raises
    HERE, at build_decode_step time, naming the offending dimension AND
    the ModelConfig field it derives from — not later as an opaque tile
    arithmetic error inside the builder (VERDICT r5 weak #7). Round 9
    lifted the two Qwen3-8B-only dims: head_dim 64 (padded-head layout,
    the 0.6B/1.7B presets) and batch > TILE (row-blocked emission)."""
    if head_dim not in (TILE // 2, TILE):
        raise ValueError(
            f"head_dim = {head_dim} unsupported: the megakernel decode "
            f"assembly packs each head into a lane-aligned tile — "
            f"supported head dims are {TILE // 2} (padded-head layout, "
            f"the Qwen3-0.6B/1.7B presets) and {TILE} — config field "
            "head_dim")
    if hidden % TILE:
        raise ValueError(
            f"hidden = {hidden} is not a multiple of TILE ({TILE}) — "
            "config field hidden_size")
    if ffn_local % TILE:
        raise ValueError(
            f"ffn_local = {ffn_local} is not a multiple of TILE ({TILE}) "
            "— config field intermediate_size (per-rank shard: "
            "intermediate_size / tp must stay a TILE multiple)")
    if max_seq % TILE:
        raise ValueError(
            f"max_seq = {max_seq} is not a multiple of TILE ({TILE}) — "
            "the KV cache is tiled; pad the cache capacity (max_seq "
            "serving argument)")
    if batch < 1:
        raise ValueError(
            f"batch = {batch} invalid: a decode step needs at least one "
            "token row — batch serving argument")
    if batch > TILE:
        # Row-blocked emission (round 9): one task row per TILE-chunk of
        # the batch. The layouts below stay single-block — named here
        # rather than failing later as opaque tile arithmetic.
        if fp8_weights:
            raise ValueError(
                f"batch = {batch} > TILE with fp8_weights: the tiled fp8 "
                "weight layout is single-block — batch > TILE needs the "
                "matrix layout (fp8_weights=False) — batch serving "
                "argument")
        if moe_experts:
            raise ValueError(
                f"batch = {batch} > TILE with MoE: MOE_TOPK masks one "
                "(B, E) logits tile, so the expert router is single-block "
                "— config field num_experts / batch serving argument")
        if inkernel_append and not paged:
            raise ValueError(
                f"batch = {batch} > TILE with inkernel_append on the "
                "linear cache: the append writes row 0 only (batch-1 "
                "serving); the paged serving lane appends per slot — "
                "batch serving argument")
    if kv_fp8:
        # The fp8-pool form (round 12): named surface instead of a silent
        # exclusion — every unsupported combination says exactly which
        # knob conflicts and why.
        if not (paged and seq_blocks):
            raise ValueError(
                "kv_fp8=True requires the paged SERVING pool form "
                "(paged=True with kv_pool_pages): fp8 KV pools live in "
                "the separate read-write fp8 workspace the "
                "ATTN_DECODE_PAGED_F8 / APPEND_KV_F8 tasks address — "
                "the linear cache stays in the workspace dtype "
                "(kv_dtype serving argument)")
        if fp8_weights:
            raise ValueError(
                "kv_fp8=True with fp8_weights=True: the serving pool "
                "form runs the matrix weight layout, which the tiled "
                "fp8-weight programs forgo — pick fp8 KV pools (the "
                "decode-bandwidth lever) or tiled fp8 weights, not both "
                "— kv_dtype / fp8_weights serving arguments")
        if moe_experts:
            raise ValueError(
                "kv_fp8=True with MoE: the megakernel serving lane "
                "covers the dense stack (validate_megakernel_cfg) — "
                "config field num_experts")
    if spec_window != 1:
        # Speculative draft-and-verify (ISSUE 14): named surface for the
        # unsupported combinations — the serving tier wraps these in
        # BackendUnsupportedError and demotes rather than dying.
        if not 1 <= spec_window <= TILE:
            raise ValueError(
                f"spec_window = {spec_window} out of range [1, {TILE}]: "
                "the candidate window rides the rows of one slot's TILE "
                "block — spec_k serving argument")
        if not (paged and seq_blocks and inkernel_append):
            raise ValueError(
                f"spec_window = {spec_window} > 1 requires the paged "
                "SERVING pool form (paged=True with kv_pool_pages and "
                "in-kernel appends): the candidate window folds the "
                "slot's fresh k/v causally and appends it through the "
                "windowed APPEND_KV rows — spec_k serving argument")
        if moe_experts:
            raise ValueError(
                f"spec_window = {spec_window} > 1 with MoE: the "
                "megakernel serving lane covers the dense stack — "
                "config field num_experts")
    if num_layers < 1:
        raise ValueError(f"num_layers = {num_layers} must be >= 1 — "
                         "config field num_layers")
    if hq_local < 1 or hkv_local < 1:
        raise ValueError(
            f"hq_local = {hq_local}, hkv_local = {hkv_local} must be "
            ">= 1 — config fields num_heads / num_kv_heads (per-rank "
            "shards: heads / tp)")
    if hq_local % hkv_local:
        raise ValueError(
            f"hq_local = {hq_local} not divisible by hkv_local = "
            f"{hkv_local}: GQA groups q-heads evenly over kv heads — "
            "config fields num_heads / num_kv_heads")
    if moe_experts and not 1 <= moe_topk <= moe_experts <= TILE:
        raise ValueError(
            f"MoE config needs 1 <= moe_topk ({moe_topk}) <= moe_experts "
            f"({moe_experts}) <= TILE ({TILE}) — config fields "
            "num_experts_per_tok / num_experts")
    if not 0 <= pos < max_seq:
        raise ValueError(f"pos {pos} outside cache capacity {max_seq} "
                         "(the step appends this position's k/v)")


def build_decode_step(*, hidden: int, hq_local: int, hkv_local: int,
                      ffn_local: int, num_layers: int, max_seq: int,
                      pos: int, num_ranks: int = 1,
                      eps: float = 1e-6,
                      paged: bool = False,
                      inkernel_append: bool = False,
                      fp8_weights: bool = False,
                      moe_experts: int = 0, moe_topk: int = 0,
                      batch: int = 1, head_dim: int = TILE,
                      final_norm: bool = False,
                      force_ar_tasks: bool = False,
                      mat_prefetch: bool = False,
                      kv_pool_pages: int | None = None,
                      table_pages: int | None = None,
                      kv_fp8: bool = False,
                      spec_window: int = 1) -> DecodeStepProgram:
    """Assemble a full num_layers decode step (per-device TP view).

    ``hq_local``/``hkv_local``/``ffn_local`` are this device's shards.
    The embedding lookup and the lm_head stay outside (the reference
    megakernel also serves the transformer stack; sampling is host-side).
    ``fp8_weights``: projection/MLP weights live in the float8_e4m3fn
    weight workspace (GEMM_WIDE_W8 streams them at half the bytes;
    quality is the e4m3 quantization's).

    ``moe_experts`` > 0 replaces the dense FFN with the Qwen3-MoE expert
    MLP (router GEMM → MOE_TOPK → one expert-skipping MOE_FFN task per
    layer; ``ffn_local`` becomes the per-expert moe_intermediate shard).
    ``batch`` is the real token count — MOE_TOPK masks padded rows, which
    would otherwise elect experts and defeat the in-kernel skip. MoE
    weights stay in the main workspace (the fp8 lane covers dense
    projections only).

    ``final_norm=True`` (round 6): the model's final RMSNorm runs
    IN-KERNEL, fused into the last layer's residual tail — ``x_out`` is
    the already-normalized row and ``prog.fnorm`` is the norm-weight
    handle to feed (broadcast rows). ``force_ar_tasks``: emit the
    in-kernel AllReduce sites even at ``num_ranks == 1`` (the
    n=1-loopback cross-device rung; compile with ``force_ar=True``).

    Round 9 generalizations:

    * ``batch`` may exceed TILE — ROW-BLOCKED emission: each TILE-chunk
      of the batch gets its own task row per layer (``x`` becomes a
      (ceil(batch/TILE)·TILE, hidden) tensor; per-block outputs ride
      ``x_out_blocks``). Matrix layout only.
    * ``head_dim`` 64: padded-head layout (each head in the low 64 lanes
      of its tile; feed weights through ``feed_layer_weights(head_dim=)``
      and compile with ``compile(head_dim=)``).
    * ``mat_prefetch``: PREFETCH_MAT warms so GEMM_MAT weight chunks
      stream under the attention task / the ALLREDUCE_ROW barrier (the
      stall-slice kill).
    * ``kv_pool_pages``: the paged SERVING form — kT/v become SHARED
      per-(layer, kv-head) pools of that many page tiles (last = the
      scratch page idle slots ride), every row block is an independent
      SEQUENCE slot with its own ``table_pages``-entry page table
      (initially all-scratch; the host rewrites tables/valid
      lengths/append targets per step via ``prog.paged_meta``), per-slot
      rope tables (``cos``/``sin`` get one row block per slot), and
      in-kernel appends parked on the scratch page at build time.
    * ``kv_fp8`` (round 12): the serving pool form's kT/v pools live in
      the float8_e4m3fn KV workspace — ATTN_DECODE_PAGED_F8 streams each
      page at HALF the bytes (widen to fp32 before the softmax dots) and
      APPEND_KV_F8 saturate-casts appends (±448 clamp, the
      models/fp8._to_e4m3 contract). Carry the kv8 workspace through
      every step alongside the main one.
    * ``spec_window`` (round 14, docs/serving.md "Speculative decode"):
      W > 1 compiles the serving pool form's draft-and-verify shape —
      candidate rows 0..W-1 of each slot's TILE block score in one
      launch (causal fresh-k/v window fold in the paged attention rows;
      a second APPEND_KV row per kv head for page-boundary spills; the
      live per-slot window rides queue words, so W = spec_k+1 is the
      only compile-time commitment). W = 1 builds the exact pre-spec
      program.
    """
    seq_blocks = kv_pool_pages is not None
    _check_decode_step_config(
        hidden=hidden, hq_local=hq_local, hkv_local=hkv_local,
        ffn_local=ffn_local, num_layers=num_layers, max_seq=max_seq,
        pos=pos, batch=batch, head_dim=head_dim, moe_experts=moe_experts,
        moe_topk=moe_topk, fp8_weights=fp8_weights,
        inkernel_append=inkernel_append, paged=paged,
        kv_fp8=kv_fp8, seq_blocks=seq_blocks, spec_window=spec_window)
    if seq_blocks and not paged:
        raise ValueError("kv_pool_pages (the serving pool form) requires "
                         "paged=True")
    if batch > TILE and inkernel_append and not seq_blocks:
        # Shared-cache row blocks all append at the SAME position: later
        # blocks would silently overwrite earlier blocks' KV. Only the
        # serving pool form (kv_pool_pages — one SEQUENCE per block, each
        # with its own append target) supports multi-block appends.
        raise ValueError(
            f"batch = {batch} > TILE with inkernel_append on a shared "
            "paged cache: every row block's append targets the same "
            "tile/column (last block wins) — per-block appends need the "
            "serving pool form (kv_pool_pages) — batch serving argument")
    bt = -(-batch // TILE)
    mb = MegaKernelBuilder()
    # The sub-tile span is part of the assembly: compile() inherits it,
    # and an explicit compile(head_dim=) must agree (builder check).
    mb.head_dim = head_dim
    x = mb.tensor(bt * TILE, hidden)
    # Per-slot positions (the serving form) need per-block rope tables;
    # the shared-position batch form keeps one table pair.
    tbt = bt if seq_blocks else 1
    cos = mb.tensor(tbt * TILE, TILE)
    sin = mb.tensor(tbt * TILE, TILE)
    layers: list[DecodeLayerHandles] = []
    d = TILE
    tp = table_pages if table_pages is not None else (kv_pool_pages or 0)
    # Matrix weight layout (round 5) is the default; the fp8 lane keeps
    # the tiled layout (GEMM_WIDE_W8 streams from the fp8 tile workspace).
    use_mat = not fp8_weights
    for _ in range(num_layers):
        moe = moe_experts > 0
        if moe:
            moe_w_gate = mb.tensor(moe_experts * hidden, ffn_local)
            moe_w_up = mb.tensor(moe_experts * hidden, ffn_local)
            moe_w_down = mb.tensor(moe_experts * ffn_local, hidden)
            moe_router = mb.tensor(hidden, TILE)
        if use_mat:
            wqkv = mb.tensor_mat(hidden, (hq_local + 2 * hkv_local) * d)
            wo = mb.tensor_mat(hq_local * d, hidden)
            qkv_out = mb.tensor(bt * TILE, (hq_local + 2 * hkv_local) * d)
            k_new = TensorHandle(qkv_out.base + hq_local, TILE,
                                 hkv_local * d)
            v_new = TensorHandle(qkv_out.base + hq_local + hkv_local,
                                 TILE, hkv_local * d)
            w_gateup = (None if moe
                        else mb.tensor_mat(hidden, ffn_local, pair=True))
            w_down = (moe_w_down if moe
                      else mb.tensor_mat(ffn_local, hidden))
            wq = wk = wv = w_gate = w_up = None
        else:
            wqkv = w_gateup = qkv_out = None
            wq = mb.tensor(hidden, hq_local * d, fp8=fp8_weights)
            wk = mb.tensor(hidden, hkv_local * d, fp8=fp8_weights)
            wv = mb.tensor(hidden, hkv_local * d, fp8=fp8_weights)
            wo = mb.tensor(hq_local * d, hidden, fp8=fp8_weights)
            # On the MoE path the dense-FFN fields alias the expert stacks
            # (unused by the MoE branch; the dataclass keeps them
            # non-optional for the dense majority).
            w_gate = moe_w_gate if moe else mb.tensor(
                hidden, ffn_local, fp8=fp8_weights)
            w_up = moe_w_up if moe else mb.tensor(
                hidden, ffn_local, fp8=fp8_weights)
            w_down = moe_w_down if moe else mb.tensor(
                ffn_local, hidden, fp8=fp8_weights)
            k_new = mb.tensor(TILE, hkv_local * d)
            v_new = mb.tensor(TILE, hkv_local * d)
        if seq_blocks:
            kT = [mb.tensor(d, kv_pool_pages * TILE, kv8=kv_fp8)
                  for _ in range(hkv_local)]
            v = [mb.tensor(kv_pool_pages * TILE, d, kv8=kv_fp8)
                 for _ in range(hkv_local)]
        else:
            kT = [mb.tensor(d, max_seq) for _ in range(hkv_local)]
            v = [mb.tensor(max_seq, d) for _ in range(hkv_local)]
        layers.append(DecodeLayerHandles(
            attn_norm=mb.tensor(TILE, hidden),
            mlp_norm=mb.tensor(TILE, hidden),
            q_norm=mb.tensor(TILE, d),
            k_norm=mb.tensor(TILE, d),
            wq=wq, wk=wk, wv=wv, wo=wo,
            w_gate=w_gate, w_up=w_up, w_down=w_down,
            kT=kT, v=v,
            k_new=k_new, v_new=v_new,
            moe_router=moe_router if moe else None,
            moe_w_gate=moe_w_gate if moe else None,
            moe_w_up=moe_w_up if moe else None,
            moe_w_down=moe_w_down if moe else None,
            wqkv=wqkv, w_gateup=w_gateup, qkv_out=qkv_out,
        ))
    fnorm = mb.tensor(TILE, hidden) if final_norm else None
    # Per-block residual chains (round 9 row-blocked emission; bt == 1 is
    # exactly the old single-chain assembly).
    cur: list[TensorHandle] = [row_block(x, b) for b in range(bt)]
    curn: list[TensorHandle | None] = [None] * bt
    block_meta = [dict() for _ in range(bt)] if paged else None
    scratch = (kv_pool_pages - 1) if seq_blocks else None
    for i, h in enumerate(layers):
        # Cross-layer residual-chain fusion (round 6): each layer's tail
        # also produces the NEXT consumer's normalized row — the next
        # layer's attn-norm input, or (final_norm) the model's final norm.
        if i + 1 < num_layers:
            nw = layers[i + 1].attn_norm
        elif final_norm:
            nw = fnorm
        else:
            nw = None
        nout = mb.tensor(bt * TILE, hidden) if nw is not None else None
        for b in range(bt):
            hb = h
            if bt > 1:
                qkv_b = row_block(h.qkv_out, b)
                hb = dataclasses.replace(
                    h, qkv_out=qkv_b,
                    k_new=TensorHandle(qkv_b.base + hq_local, TILE,
                                       hkv_local * d),
                    v_new=TensorHandle(qkv_b.base + hq_local + hkv_local,
                                       TILE, hkv_local * d))
            if seq_blocks:
                # Slot b's build-time page table: all-scratch entries (the
                # host remaps them to the slot's allocated pool pages each
                # step — tables are DATA rows, no recompile).
                tables = [[(kt_h.tile(0, scratch), v_h.tile(scratch, 0))] * tp
                          for kt_h, v_h in zip(hb.kT, hb.v)]
            else:
                tables = None
            cur[b], curn[b] = build_decode_layer(
                mb, cur[b], hb, row_block(cos, b if seq_blocks else 0),
                row_block(sin, b if seq_blocks else 0),
                hq_local=hq_local,
                hkv_local=hkv_local, pos=pos,
                num_ranks=num_ranks, eps=eps, paged=paged,
                inkernel_append=inkernel_append,
                moe_experts=moe_experts,
                moe_topk=moe_topk, batch=min(batch, TILE), xn=curn[b],
                out_norm=(nw, row_block(nout, b)) if nw is not None
                else None,
                force_ar_tasks=force_ar_tasks,
                head_dim=head_dim, mat_prefetch=mat_prefetch,
                paged_tables=tables,
                append_pos=(scratch * TILE) if seq_blocks else None,
                meta_out=block_meta[b] if block_meta is not None else None,
                spec_append=spec_window > 1)
    outs = [curn[b] if final_norm else cur[b] for b in range(bt)]
    meta = None
    if paged:
        meta = {"blocks": block_meta, "table_pages": tp,
                "pool_pages": kv_pool_pages, "kv_fp8": kv_fp8}
    return DecodeStepProgram(mb=mb, x=x, layers=layers, cos=cos, sin=sin,
                             x_out=outs[0], fnorm=fnorm,
                             x_out_blocks=outs, blocks=bt,
                             paged_meta=meta)
