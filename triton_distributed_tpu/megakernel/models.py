"""MegaKernel model assembly — a whole decode step as one task queue.

Reference: ``mega_triton_kernel/models/qwen3.py`` + ``model_builder.py``
(make_qkv_proj / make_attn / make_o_proj / fc / silu_mul / rms_norm / add /
allreduce assemble a Qwen3 decode step replayed as one persistent kernel —
the 3.33 ms headline path, BASELINE.md).

TPU assembly for a TP-sharded Qwen3-style layer (per device):

    x ── rms_norm ── q/k/v proj ── per-head qk-norm + RoPE ──
      attn_decode per q head (cached KV + in-step current token) ──
      o-proj ── AllReduce ── +residual ──
      rms_norm ── gate/up proj ── silu·mul ── down proj ── AllReduce ──
      +residual

The current token's k/v join each attention task's softmax directly
(ATTN_DECODE c0/d0 operands); with ``inkernel_append=True`` the cache is
then appended IN-KERNEL by APPEND_KV tasks (matching the reference's
in-kernel append; the WAR hazard on the cache tiles orders the append
after the attention reads), retargeted per position by
``advance_queue_pos``. Without the flag the host appends after the step
(pure-functional update — the test-friendly default). Constraints:
head_dim == TILE (128, the Qwen3 value), batch <= TILE,
hidden/ffn_local/head counts multiples of TILE where tiled.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers.common import rope_cos_sin
from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder
from triton_distributed_tpu.megakernel.tasks import (
    TILE, MatHandle, TensorHandle,
)


def broadcast_rows(vec: np.ndarray) -> np.ndarray:
    """A (cols,) vector as the (TILE, cols) broadcast tensor the RMS_NORM /
    ROPE tasks read (row-replicated; tile (0, j) carries columns of j)."""
    return np.broadcast_to(np.asarray(vec, np.float32),
                           (TILE, vec.shape[-1])).copy()


def rope_tables(pos: int, head_dim: int, theta: float) -> tuple[np.ndarray, np.ndarray]:
    """Full-width (TILE, head_dim) cos/sin tables at ``pos`` (HF half-split:
    each half repeats the head_dim/2 table)."""
    cos, sin = rope_cos_sin(jnp.asarray([pos]), head_dim, theta)
    cos, sin = np.asarray(cos)[0], np.asarray(sin)[0]
    return (broadcast_rows(np.concatenate([cos, cos])),
            broadcast_rows(np.concatenate([sin, sin])))


def _col(t: TensorHandle, j: int) -> TensorHandle:
    """Single column-tile view (valid because activations have rt == 1)."""
    assert t.rt == 1
    return TensorHandle(t.base + j, TILE, TILE)


@dataclasses.dataclass
class DecodeLayerHandles:
    """Workspace handles for one layer's weights + caches + outputs.

    Two weight layouts exist (use :func:`feed_layer_weights` to feed
    either): the round-5 MATRIX layout (default for dense bf16/fp32 —
    ``wqkv``/``w_gateup`` are fused MatHandles, ``wo``/``w_down`` are
    MatHandles, and ``wq/wk/wv/w_gate/w_up`` are None) and the tiled
    layout (fp8 / MoE-FFN — every field is a TensorHandle)."""

    attn_norm: TensorHandle     # (TILE, hidden) broadcast
    mlp_norm: TensorHandle
    q_norm: TensorHandle        # (TILE, d) broadcast (Qwen3 qk-norm)
    k_norm: TensorHandle
    wq: TensorHandle | None     # (hidden, hq_local*d)
    wk: TensorHandle | None     # (hidden, hkv_local*d)
    wv: TensorHandle | None
    wo: TensorHandle | MatHandle    # (hq_local*d, hidden)
    w_gate: TensorHandle | None     # (hidden, ffn_local)
    w_up: TensorHandle | None
    w_down: TensorHandle | MatHandle  # (ffn_local, hidden)
    kT: list[TensorHandle]      # per kv head: (d, S) keys transposed
    v: list[TensorHandle]       # per kv head: (S, d)
    k_new: TensorHandle         # (TILE, hkv_local*d) — this step's k (out)
    v_new: TensorHandle
    # MoE FFN (Qwen3-MoE decode; None = dense MLP). Router cols padded to
    # TILE (zero weights → zero logits, masked by MOE_TOPK's E bound).
    moe_router: TensorHandle | None = None   # (hidden, TILE)
    moe_w_gate: TensorHandle | None = None   # (E·hidden, ffn_local)
    moe_w_up: TensorHandle | None = None
    moe_w_down: TensorHandle | None = None   # (E·ffn_local, hidden)
    # Matrix-workspace layout (round 5 — see class docstring):
    wqkv: MatHandle | None = None       # (hidden, (hq+2*hkv)*d) fused
    w_gateup: MatHandle | None = None   # (hidden, ffn_local) pair
    qkv_out: TensorHandle | None = None  # (TILE, (hq+2*hkv)*d) q|k|v row


def feed_layer_weights(feeds: dict, h: DecodeLayerHandles, *, wq, wk, wv,
                       wo, w_gate=None, w_up=None, w_down=None) -> dict:
    """Insert one layer's projection/MLP weights into ``feeds`` in
    whichever layout the program was built with (matrix or tiled) —
    callers pass the natural per-matrix values and never see the fused
    qkv / interleaved gate|up storage."""
    if h.wqkv is not None:
        feeds[h.wqkv] = jnp.concatenate(
            [jnp.asarray(wq), jnp.asarray(wk), jnp.asarray(wv)], axis=1)
    else:
        feeds[h.wq] = wq
        feeds[h.wk] = wk
        feeds[h.wv] = wv
    feeds[h.wo] = wo
    if h.moe_w_gate is not None:
        # MoE layer: the expert FFN feeds through the moe_w_* handles;
        # dense-FFN values passed here are ignored (h.w_gate may be None
        # in the matrix layout — keying feeds by None would surface later
        # as an opaque split_feeds crash).
        return feeds
    if (w_gate is None) != (w_up is None):
        # A lone half would surface much later as an opaque
        # jnp.asarray(None) crash inside scatter_mat — fail at the call.
        raise ValueError(
            "feed_layer_weights needs BOTH w_gate and w_up (or neither); "
            f"got w_gate={'set' if w_gate is not None else None}, "
            f"w_up={'set' if w_up is not None else None}")
    if w_gate is not None:
        if h.w_gateup is not None:
            feeds[h.w_gateup] = (w_gate, w_up)
        else:
            feeds[h.w_gate] = w_gate
            feeds[h.w_up] = w_up
    if w_down is not None:
        feeds[h.w_down] = w_down
    return feeds


@dataclasses.dataclass
class DecodeStepProgram:
    """Builder + handles for a full decode step."""

    mb: MegaKernelBuilder
    x: TensorHandle
    layers: list[DecodeLayerHandles]
    cos: TensorHandle
    sin: TensorHandle
    x_out: TensorHandle
    # build_decode_step(final_norm=True): the final RMSNorm weight handle
    # (broadcast rows) — the norm runs IN-KERNEL, fused into the last
    # layer's residual tail, and x_out is already normalized.
    fnorm: TensorHandle | None = None


def advance_queue_pos(base_queue, pos: int, num_exec: int | None = None):
    """Re-target a compiled decode queue to position ``pos`` WITHOUT
    recompiling: ATTN_DECODE's valid_len (word 6) and visited-tile count
    (word 4) are runtime queue words, so one host-side int32 edit per step
    retargets every attention task — the decode loop replays ONE compiled
    kernel. RoPE tables are workspace inputs: feed ``rope_tables(pos, ...)``
    alongside. (The reference re-enqueues task params the same way,
    model_builder.py enque_tasks/run.)

    ``base_queue`` must come from a program built at ``pos = max_seq - 1``
    (full cache capacity in word 4); returns an updated int32 copy.
    """
    from triton_distributed_tpu.megakernel.tasks import TaskType

    # base_queue may be a CompiledMegaKernel (preferred — carries the
    # executable/data row split) or a raw queue array.
    if hasattr(base_queue, "queue"):
        if num_exec is None:
            num_exec = base_queue.num_exec
        base_queue = base_queue.queue
    q = np.asarray(base_queue).copy()
    attn = ((q[:, 0] == int(TaskType.ATTN_DECODE))
            | (q[:, 0] == int(TaskType.ATTN_DECODE_PAGED))
            | (q[:, 0] == int(TaskType.ATTN_DECODE_GQA)))
    if num_exec is not None:
        # Rows beyond the executable prefix are page-table DATA — their
        # words must never be interpreted as task fields.
        attn[num_exec:] = False
    elif np.any(q[:, 0] == int(TaskType.ATTN_DECODE_PAGED)):
        # Paged programs append raw tile-id DATA rows after the tasks; a
        # row starting with 8/9 would match the mask and get corrupted.
        raise ValueError(
            "queue contains ATTN_DECODE_PAGED tasks: pass the "
            "CompiledMegaKernel (or num_exec=) so page-table DATA rows "
            "are not misread as tasks")
    need = -(-pos // TILE)
    if np.any(q[attn, 4] < need):
        raise ValueError(
            f"base queue visits {int(q[attn, 4].min())} cache "
            f"tiles but pos {pos} needs {need} — build the program at "
            "pos = max_seq - 1 (silently dropping cache positions would "
            "corrupt the softmax)")
    if pos < 1 and np.any(q[attn, 8] < 0):
        raise ValueError("pos 0 with a cache-only attention task would be "
                         "an all-masked softmax")
    q[attn, 6] = pos
    q[attn, 4] = np.minimum(q[attn, 4], need)
    # APPEND_KV rows are self-describing (a_stride/b_stride = cache base
    # tiles): retarget the destination tile + intra-tile column to ``pos``.
    app = q[:, 0] == int(TaskType.APPEND_KV)
    if num_exec is not None:
        app[num_exec:] = False
    ti, col = pos // TILE, pos % TILE
    q[app, 1] = q[app, 5] + ti        # out = kT base tile + pos tile
    q[app, 3] = q[app, 6] + ti        # b0  = v base tile + pos tile
    q[app, 8] = col                   # c0  = intra-tile column/row
    return jnp.asarray(q)


def build_decode_layer(mb: MegaKernelBuilder, x: TensorHandle,
                       h: DecodeLayerHandles, cos: TensorHandle,
                       sin: TensorHandle, *, hq_local: int, hkv_local: int,
                       pos: int, num_ranks: int,
                       eps: float = 1e-6, paged: bool = False,
                       inkernel_append: bool = False,
                       moe_experts: int = 0, moe_topk: int = 0,
                       batch: int = 1,
                       xn: TensorHandle | None = None,
                       out_norm: tuple[TensorHandle, TensorHandle] | None = None,
                       force_ar_tasks: bool = False):
    """Emit one transformer layer's decode tasks.

    Round-6 cross-layer contract: ``xn`` is the already-NORMALIZED input
    row (produced by the previous layer's fused tail); ``None`` emits the
    standalone rms_norm (layer 0 / direct callers). ``out_norm`` is
    ``(norm_w, norm_out)`` — the NEXT consumer's norm (the next layer's
    attn norm, or the model's final norm) fused into this layer's
    residual tail, so the residual row never round-trips HBM between the
    add and the norm and the consuming norm task disappears from the
    queue. ``force_ar_tasks`` emits the AllReduce sites even at
    ``num_ranks == 1`` (the n=1-loopback cross-device rung — bench.py).

    Returns ``(x2, x2n)``: the residual-stream output and its fused-norm
    row (``None`` unless ``out_norm`` was given)."""
    hidden = x.cols
    d = TILE
    groups = hq_local // hkv_local
    scale = d ** -0.5
    ar = num_ranks > 1 or force_ar_tasks

    if xn is None:
        xn = mb.tensor(TILE, hidden)
        # No weight prefetches since the strip-fetch GEMM (round 4): one
        # (W, TILE, TILE) strip DMA replaced the per-tile stream, so a
        # single-tile warm would be discarded — each prefetch would cost a
        # dispatch plus a wasted tile fetch. (The PREFETCH task types
        # remain for direct builder use; reference weight-prefetch,
        # SURVEY.md §2.7.)
        mb.rms_norm(xn, x, h.attn_norm, eps)

    if h.wqkv is not None:
        # Matrix path (round 5): ONE fused qkv GEMM_MAT task — the q|k|v
        # output row is contiguous (k_new/v_new are views into qkv_out),
        # the A row loads once for all three projections, and the task
        # body is a static specialized branch (tasks.py GEMM_MAT).
        q = TensorHandle(h.qkv_out.base, TILE, hq_local * d)
        mb.gemm_mat(h.qkv_out, xn, h.wqkv)
        # Round 6: qk-norm + RoPE over ALL q+k heads in ONE task — the
        # norm weights and rope tables load once per layer instead of
        # once per head (hq+hkv-1 dispatches disappear).
        mb.norm_rope_qkv(q, hq_local, h.k_new, hkv_local, h.q_norm,
                         h.k_norm, cos, sin, eps)
    else:
        q = mb.tensor(TILE, hq_local * d)
        mb.gemm(q, xn, h.wq)
        mb.gemm(h.k_new, xn, h.wk)
        mb.gemm(h.v_new, xn, h.wv)
        # Tiled/fp8 layout: k_new is not contiguous after q, so the fused
        # whole-row task cannot apply — per-head qk-norm + RoPE.
        for j in range(hq_local):
            mb.norm_rope(_col(q, j), _col(q, j), h.q_norm, cos, sin, eps)
        for j in range(hkv_local):
            mb.norm_rope(_col(h.k_new, j), _col(h.k_new, j), h.k_norm,
                         cos, sin, eps)

    attn = mb.tensor(TILE, hq_local * d)
    if paged:
        # Paged cache (reference mega_triton_kernel PagedKVCache): the
        # kT/v handles are PAGE POOLS; each attention task walks an
        # identity page table packed as queue DATA rows, which the host
        # can rewrite per step to remap logical pages onto pool tiles
        # (tables are data, so any allocator works without recompiling).
        # Limitations vs the linear GQA path (deliberate, documented): the
        # paged task is single-head, so a GQA group re-streams its shared
        # KV pool `groups` times and each q-head carries its OWN copy of
        # the kv-head's table — a host remapper must rewrite every task's
        # DATA rows (find them via each task's b0 word), not just one.
        n_pages = h.kT[0].ct
        for j in range(hq_local):
            kv = j // groups
            pages = [(h.kT[kv].tile(0, p), h.v[kv].tile(p, 0))
                     for p in range(n_pages)]
            mb.attn_decode_paged(_col(attn, j), _col(q, j), pages,
                                 valid_len=pos, scale=scale,
                                 k_new=_col(h.k_new, kv),
                                 v_new=_col(h.v_new, kv))
    else:
        # One task per KV head: the whole GQA group's q-heads share the KV
        # stream (tiles fetched once per group, not once per head).
        for kv in range(hkv_local):
            mb.attn_decode_gqa(attn, kv * groups, q, kv * groups, groups,
                               h.kT[kv], h.v[kv], valid_len=pos,
                               scale=scale, k_new=_col(h.k_new, kv),
                               v_new=_col(h.v_new, kv))

    if inkernel_append and not paged:
        # In-kernel KV append (reference model_builder.py appends inside
        # its attn tasks): the WAR hazards on the cache tiles order these
        # after this layer's attention reads. advance_queue_pos retargets
        # the destination tile/column per step.
        for kv in range(hkv_local):
            mb.append_kv(h.kT[kv], h.v[kv], pos, _col(h.k_new, kv),
                         _col(h.v_new, kv))

    mat = isinstance(h.wo, MatHandle)
    nw, nout = out_norm if out_norm is not None else (None, None)
    x1 = mb.tensor(TILE, hidden)
    x1n = mb.tensor(TILE, hidden)
    if mat and not ar:
        # Fused o-proj + residual add + THIS layer's mlp norm (epilogue 3
        # — the round-6 mid-layer fusion: the x1 row stays VMEM-resident
        # between the add and the norm, and the rms_norm task disappears).
        mb.gemm_mat(x1, attn, h.wo, residual=x, norm_w=h.mlp_norm,
                    norm_out=x1n, eps=eps)
    else:
        o = mb.tensor(TILE, hidden)
        if mat:
            mb.gemm_mat(o, attn, h.wo)
        else:
            mb.gemm(o, attn, h.wo)
        if ar:
            mb.all_reduce(o)
        # Fused residual add + mlp norm (ADD_NORM — the cross-layer
        # fusion's form for paths where an AllReduce sits between the
        # GEMM and the add).
        mb.add_norm(x1, x, o, h.mlp_norm, x1n, eps)

    if h.moe_w_gate is not None:
        down = mb.tensor(TILE, hidden)
        # Qwen3-MoE FFN: router GEMM → in-kernel top-k/softmax → ONE
        # expert-loop task with data-dependent skipping (tasks.py MOE_FFN;
        # only ~B·topk of E experts stream their weights).
        logits = mb.tensor(TILE, TILE)
        mb.gemm(logits, x1n, h.moe_router)
        wt = mb.tensor(TILE, TILE)
        mb.moe_topk(wt, logits, moe_topk, moe_experts, batch)
        mb.moe_ffn(down, x1n, wt, h.moe_w_gate, h.moe_w_up, h.moe_w_down,
                   moe_experts)
    elif h.w_gateup is not None:
        # Fused gate/up/act: one GEMM_MAT over the interleaved pair with
        # the silu epilogue, then down (+residual when no AR follows —
        # with ``out_norm`` also fusing the NEXT consumer's norm, the
        # round-6 cross-LAYER epilogue).
        act = mb.tensor(TILE, h.w_gateup.n)
        mb.gemm_mat(act, x1n, h.w_gateup)
        if not ar:
            x2 = mb.tensor(TILE, hidden)
            if nw is not None:
                mb.gemm_mat(x2, act, h.w_down, residual=x1, norm_w=nw,
                            norm_out=nout, eps=eps)
                return x2, nout
            mb.gemm_mat(x2, act, h.w_down, residual=x1)
            return x2, None
        down = mb.tensor(TILE, hidden)
        mb.gemm_mat(down, act, h.w_down)
    else:
        down = mb.tensor(TILE, hidden)
        ffn_local = h.w_gate.cols
        gate = mb.tensor(TILE, ffn_local)
        up = mb.tensor(TILE, ffn_local)
        act = mb.tensor(TILE, ffn_local)
        mb.gemm(gate, x1n, h.w_gate)
        mb.gemm(up, x1n, h.w_up)
        mb.silu_mul(act, gate, up)
        mb.gemm(down, act, h.w_down)
    if ar:
        mb.all_reduce(down)
    x2 = mb.tensor(TILE, hidden)
    if nw is not None:
        # Cross-layer residual-chain fusion across the AR seam: one task
        # produces BOTH x2 and the next layer's normalized input.
        mb.add_norm(x2, x1, down, nw, nout, eps)
        return x2, nout
    mb.add(x2, x1, down)
    return x2, None


def _check_decode_step_config(*, hidden, hq_local, hkv_local, ffn_local,
                              num_layers, max_seq, pos, batch, head_dim,
                              moe_experts, moe_topk) -> None:
    """Named build-time validation: every TILE/geometry constraint raises
    HERE, at build_decode_step time, naming the offending dimension AND
    the ModelConfig field it derives from — not later as an opaque tile
    arithmetic error inside the builder (VERDICT r5 weak #7)."""
    if head_dim != TILE:
        raise ValueError(
            f"head_dim = {head_dim} unsupported: the megakernel decode "
            f"assembly requires head_dim == TILE ({TILE}) — config field "
            "head_dim (the Qwen3 value)")
    if hidden % TILE:
        raise ValueError(
            f"hidden = {hidden} is not a multiple of TILE ({TILE}) — "
            "config field hidden_size")
    if ffn_local % TILE:
        raise ValueError(
            f"ffn_local = {ffn_local} is not a multiple of TILE ({TILE}) "
            "— config field intermediate_size (per-rank shard: "
            "intermediate_size / tp must stay a TILE multiple)")
    if max_seq % TILE:
        raise ValueError(
            f"max_seq = {max_seq} is not a multiple of TILE ({TILE}) — "
            "the KV cache is tiled; pad the cache capacity (max_seq "
            "serving argument)")
    if not 1 <= batch <= TILE:
        raise ValueError(
            f"batch = {batch} outside [1, {TILE}]: one decode step "
            "processes at most one (TILE, hidden) activation row — "
            "batch serving argument")
    if num_layers < 1:
        raise ValueError(f"num_layers = {num_layers} must be >= 1 — "
                         "config field num_layers")
    if hq_local < 1 or hkv_local < 1:
        raise ValueError(
            f"hq_local = {hq_local}, hkv_local = {hkv_local} must be "
            ">= 1 — config fields num_heads / num_kv_heads (per-rank "
            "shards: heads / tp)")
    if hq_local % hkv_local:
        raise ValueError(
            f"hq_local = {hq_local} not divisible by hkv_local = "
            f"{hkv_local}: GQA groups q-heads evenly over kv heads — "
            "config fields num_heads / num_kv_heads")
    if moe_experts and not 1 <= moe_topk <= moe_experts <= TILE:
        raise ValueError(
            f"MoE config needs 1 <= moe_topk ({moe_topk}) <= moe_experts "
            f"({moe_experts}) <= TILE ({TILE}) — config fields "
            "num_experts_per_tok / num_experts")
    if not 0 <= pos < max_seq:
        raise ValueError(f"pos {pos} outside cache capacity {max_seq} "
                         "(the step appends this position's k/v)")


def build_decode_step(*, hidden: int, hq_local: int, hkv_local: int,
                      ffn_local: int, num_layers: int, max_seq: int,
                      pos: int, num_ranks: int = 1,
                      eps: float = 1e-6,
                      paged: bool = False,
                      inkernel_append: bool = False,
                      fp8_weights: bool = False,
                      moe_experts: int = 0, moe_topk: int = 0,
                      batch: int = 1, head_dim: int = TILE,
                      final_norm: bool = False,
                      force_ar_tasks: bool = False) -> DecodeStepProgram:
    """Assemble a full num_layers decode step (per-device TP view).

    ``hq_local``/``hkv_local``/``ffn_local`` are this device's shards;
    head_dim is TILE. The embedding lookup and the lm_head stay outside (the
    reference megakernel also serves the transformer stack; sampling is
    host-side). ``fp8_weights``: projection/MLP weights live in the
    float8_e4m3fn weight workspace (GEMM_WIDE_W8 streams them at half the
    bytes; quality is the e4m3 quantization's).

    ``moe_experts`` > 0 replaces the dense FFN with the Qwen3-MoE expert
    MLP (router GEMM → MOE_TOPK → one expert-skipping MOE_FFN task per
    layer; ``ffn_local`` becomes the per-expert moe_intermediate shard).
    ``batch`` is the real token count — MOE_TOPK masks padded rows, which
    would otherwise elect experts and defeat the in-kernel skip. MoE
    weights stay in the main workspace (the fp8 lane covers dense
    projections only).

    ``final_norm=True`` (round 6): the model's final RMSNorm runs
    IN-KERNEL, fused into the last layer's residual tail — ``x_out`` is
    the already-normalized row and ``prog.fnorm`` is the norm-weight
    handle to feed (broadcast rows). ``force_ar_tasks``: emit the
    in-kernel AllReduce sites even at ``num_ranks == 1`` (the
    n=1-loopback cross-device rung; compile with ``force_ar=True``)."""
    _check_decode_step_config(
        hidden=hidden, hq_local=hq_local, hkv_local=hkv_local,
        ffn_local=ffn_local, num_layers=num_layers, max_seq=max_seq,
        pos=pos, batch=batch, head_dim=head_dim, moe_experts=moe_experts,
        moe_topk=moe_topk)
    mb = MegaKernelBuilder()
    x = mb.tensor(TILE, hidden)
    cos = mb.tensor(TILE, TILE)
    sin = mb.tensor(TILE, TILE)
    layers: list[DecodeLayerHandles] = []
    d = TILE
    # Matrix weight layout (round 5) is the default; the fp8 lane keeps
    # the tiled layout (GEMM_WIDE_W8 streams from the fp8 tile workspace).
    use_mat = not fp8_weights
    for _ in range(num_layers):
        moe = moe_experts > 0
        if moe:
            moe_w_gate = mb.tensor(moe_experts * hidden, ffn_local)
            moe_w_up = mb.tensor(moe_experts * hidden, ffn_local)
            moe_w_down = mb.tensor(moe_experts * ffn_local, hidden)
            moe_router = mb.tensor(hidden, TILE)
        if use_mat:
            wqkv = mb.tensor_mat(hidden, (hq_local + 2 * hkv_local) * d)
            wo = mb.tensor_mat(hq_local * d, hidden)
            qkv_out = mb.tensor(TILE, (hq_local + 2 * hkv_local) * d)
            k_new = TensorHandle(qkv_out.base + hq_local, TILE,
                                 hkv_local * d)
            v_new = TensorHandle(qkv_out.base + hq_local + hkv_local,
                                 TILE, hkv_local * d)
            w_gateup = (None if moe
                        else mb.tensor_mat(hidden, ffn_local, pair=True))
            w_down = (moe_w_down if moe
                      else mb.tensor_mat(ffn_local, hidden))
            wq = wk = wv = w_gate = w_up = None
        else:
            wqkv = w_gateup = qkv_out = None
            wq = mb.tensor(hidden, hq_local * d, fp8=fp8_weights)
            wk = mb.tensor(hidden, hkv_local * d, fp8=fp8_weights)
            wv = mb.tensor(hidden, hkv_local * d, fp8=fp8_weights)
            wo = mb.tensor(hq_local * d, hidden, fp8=fp8_weights)
            # On the MoE path the dense-FFN fields alias the expert stacks
            # (unused by the MoE branch; the dataclass keeps them
            # non-optional for the dense majority).
            w_gate = moe_w_gate if moe else mb.tensor(
                hidden, ffn_local, fp8=fp8_weights)
            w_up = moe_w_up if moe else mb.tensor(
                hidden, ffn_local, fp8=fp8_weights)
            w_down = moe_w_down if moe else mb.tensor(
                ffn_local, hidden, fp8=fp8_weights)
            k_new = mb.tensor(TILE, hkv_local * d)
            v_new = mb.tensor(TILE, hkv_local * d)
        layers.append(DecodeLayerHandles(
            attn_norm=mb.tensor(TILE, hidden),
            mlp_norm=mb.tensor(TILE, hidden),
            q_norm=mb.tensor(TILE, d),
            k_norm=mb.tensor(TILE, d),
            wq=wq, wk=wk, wv=wv, wo=wo,
            w_gate=w_gate, w_up=w_up, w_down=w_down,
            kT=[mb.tensor(d, max_seq) for _ in range(hkv_local)],
            v=[mb.tensor(max_seq, d) for _ in range(hkv_local)],
            k_new=k_new, v_new=v_new,
            moe_router=moe_router if moe else None,
            moe_w_gate=moe_w_gate if moe else None,
            moe_w_up=moe_w_up if moe else None,
            moe_w_down=moe_w_down if moe else None,
            wqkv=wqkv, w_gateup=w_gateup, qkv_out=qkv_out,
        ))

    fnorm = mb.tensor(TILE, hidden) if final_norm else None
    cur = x
    curn = None   # layer 0 emits its own rms_norm (xn=None)
    for i, h in enumerate(layers):
        # Cross-layer residual-chain fusion (round 6): each layer's tail
        # also produces the NEXT consumer's normalized row — the next
        # layer's attn-norm input, or (final_norm) the model's final norm.
        if i + 1 < num_layers:
            nw = layers[i + 1].attn_norm
        elif final_norm:
            nw = fnorm
        else:
            nw = None
        nout = mb.tensor(TILE, hidden) if nw is not None else None
        cur, curn = build_decode_layer(
            mb, cur, h, cos, sin, hq_local=hq_local,
            hkv_local=hkv_local, pos=pos,
            num_ranks=num_ranks, eps=eps, paged=paged,
            inkernel_append=inkernel_append,
            moe_experts=moe_experts,
            moe_topk=moe_topk, batch=batch, xn=curn,
            out_norm=(nw, nout) if nw is not None else None,
            force_ar_tasks=force_ar_tasks)
    return DecodeStepProgram(mb=mb, x=x, layers=layers, cos=cos, sin=sin,
                             x_out=curn if final_norm else cur,
                             fnorm=fnorm)
