"""The persistent MegaKernel — one Pallas launch runs the whole task queue.

Reference: ``mega_triton_kernel/core/code_generator.py:31-89`` (the generated
``MEGA_TRITON_KERNEL``: each SM loops its queue, decodes TaskBaseInfo, waits
the scoreboard, dispatches on task_type) and ``kernels/task_context.py:92-138``
(scoreboard).

TPU shape: the Pallas grid IS the queue loop — grid step t executes task t
(TPU grid steps run sequentially on the core, giving the in-order queue the
reference builds per SM), the int32 task table rides scalar prefetch into
SMEM, and dispatch is a ``lax.switch`` over task handlers. The scoreboard
collapses: same-core dependencies are enforced by the scheduler's topological
order (sequential execution = implicit scoreboard), and cross-device
dependencies (the AllReduce task) synchronize with DMA semaphores + the
barrier semaphore — the only places the reference's ld_acquire spin loops
have a TPU analog.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import any_spec
from triton_distributed_tpu.megakernel.tasks import (
    MAT_COLS, TILE, WORDS, TaskType,
)

PIPE_DEPTH = 4  # outstanding tile-pair loads per task stream
from triton_distributed_tpu.runtime.context import use_interpret


def _mega_kernel(n: int, axis: str, n_tasks: int, max_gqa: int,
                 max_gemm_width: int, mat_specs: tuple, kch_max: int,
                 max_ar: int, force_ar: bool, used_types: tuple | None,
                 head_dim: int,
                 queue_ref, ws_in, ws8, wm, wk8_in, ws_out, slots,
                 wk8_out, va2, vb2, vb8,
                 vbw, vbw8, vacc, vq, vstat, vqg, vaccg, vstatg, vaccw,
                 vaccw_wdt, vrow_a, vrow_b, vrow_o, vmoe_a, vmoe_b,
                 vmoe_o, vbm, vaccm, voutm, vkv8,
                 copy_sem, pipe_sems, send_sems, recv_sem):
    wdt = ws_out.dtype   # workspace dtype (fp32 or bf16); compute is fp32
    step = pl.program_id(0)
    # Double-buffer views: slot 0 is the default for unpipelined tasks.
    va, vb = va2.at[0], vb2.at[0]

    # Step 0: the workspace input is ALIASED to the output (run_queue
    # input_output_aliases) — tasks read and write ws_out in place, no
    # staging copy. Only the cross-device entry barrier remains.
    if n > 1:
        @pl.when(step == 0)
        def _():
            shmem.barrier_all(axis)

    def w(j):
        return queue_ref[step, j]

    out, a0, b0 = w(1), w(2), w(3)
    k_tiles, a_stride, b_stride, arg = w(4), w(5), w(6), w(7)
    c0, d0 = w(8), w(9)

    def load(idx, vref):
        cp = pltpu.make_async_copy(ws_out.at[idx], vref, copy_sem)
        cp.start()
        cp.wait()

    def store(vref, idx):
        cp = pltpu.make_async_copy(vref, ws_out.at[idx], copy_sem)
        cp.start()
        cp.wait()

    # Pipelined pair loads: tile streams (a_of(j), b_of(j)) double-buffered
    # so iteration j's MXU work overlaps iteration j+1's DMA — the intra-
    # task analog of ops/tiling.py's emit_pipeline.
    def pipelined_pairs(a_of, b_of, n_iters, body_fn, init, kv8=False):
        # DEPTH tile-pairs in flight: a single-buffer lookahead cannot hide
        # ~2us DMA latency under a 128x128 dot; 3 outstanding pairs can.
        # b_of=None streams only `a` (the body's b_ref is then invalid) —
        # copy/scale/rms-pass1 would otherwise double their HBM reads.
        # (Prefetch-warm consumption lives in t_gemm_wide, the only task
        # the builder pairs with PREFETCH.)
        # kv8=True (the fp8 KV pool stream, ATTN_DECODE_PAGED_F8): pairs
        # stream from the fp8 pool workspace into the vkv8 scratch — the
        # SAME pipeline at HALF the DMA bytes per tile; the body's refs
        # are then e4m3 slot views (widen before the dots).
        if kv8:
            src = wk8_out
            a_buf = lambda s: vkv8.at[s]                       # noqa: E731
            b_buf = lambda s: vkv8.at[PIPE_DEPTH + s]          # noqa: E731
        else:
            src = ws_out
            a_buf = lambda s: va2.at[s]                        # noqa: E731
            b_buf = lambda s: vb2.at[s]                        # noqa: E731

        def desc(idx, buf_of, slot, sem_i):
            return pltpu.make_async_copy(src.at[idx], buf_of(slot),
                                         pipe_sems.at[sem_i])

        def start(j, slot):
            desc(a_of(j), a_buf, slot, slot * 2).start()
            if b_of is not None:
                desc(b_of(j), b_buf, slot, slot * 2 + 1).start()

        def wait(j, slot):
            desc(a_of(j), a_buf, slot, slot * 2).wait()
            if b_of is not None:
                desc(b_of(j), b_buf, slot, slot * 2 + 1).wait()

        for jj in range(PIPE_DEPTH - 1):
            @pl.when(jj < n_iters)
            def _(jj=jj):
                start(jj, jj)

        def body(j, carry):
            slot = jax.lax.rem(j, PIPE_DEPTH)

            @pl.when(j + PIPE_DEPTH - 1 < n_iters)
            def _():
                start(j + PIPE_DEPTH - 1,
                      jax.lax.rem(j + PIPE_DEPTH - 1, PIPE_DEPTH))

            wait(j, slot)
            return body_fn(j, a_buf(slot), b_buf(slot), carry)

        return jax.lax.fori_loop(0, n_iters, body, init)

    # Elementwise tasks stage the whole row(s) into the resident buffers
    # (chunked DMAs), compute tile-by-tile from VMEM, and store the row
    # back chunked — ~10 DMAs per task instead of a load/store round trip
    # per tile (the round-5 per-task profile's overhead class).
    def _ew_task(fn, binary=True):
        if binary:
            _row_load2(a0, vrow_a, b0, vrow_b, k_tiles)
        else:
            _row_load(a0, vrow_a, k_tiles)

        def body(t, _):
            a = vrow_a[t].astype(jnp.float32)
            b = vrow_b[t].astype(jnp.float32) if binary else a
            vrow_o[t, :, :] = fn(a, b).astype(wdt)
            return 0

        jax.lax.fori_loop(0, k_tiles, body, 0)
        _row_store(vrow_o, out, k_tiles)

    def t_copy():
        _ew_task(lambda a, b: a, binary=False)

    def t_add():
        _ew_task(lambda a, b: a + b)

    def t_silu_mul():
        _ew_task(lambda a, b: jax.nn.silu(a) * b)

    def t_retired():
        # Queue-ABI placeholder for retired task types (GEMM -> GEMM_WIDE,
        # ROPE -> NORM_ROPE): keeps lax.switch indices stable without
        # compiling a dead body. The builder no longer emits them.
        pass

    def t_prefetch():
        # Fire-and-forget warm of tile a0 into the reserved slot; the
        # consuming GEMM (c0 == 1) waits the semaphore at its j=0.
        pltpu.make_async_copy(ws_out.at[a0], vb2.at[PIPE_DEPTH],
                              pipe_sems.at[2 * PIPE_DEPTH]).start()

    # -- whole-row staging (round-5 attribution: the per-task profile
    # measured GEMM tasks at ~1.6 us per k-step + ~6 us fixed, so a w=1
    # task cost the same as w=8 — per-iteration DMA/semaphore OVERHEAD,
    # not bytes, was the decode bound; the fix is fewer, bigger DMAs) ----
    _AC = 8   # tiles per row-chunk DMA (static size; pad covers overfetch)

    def _row_desc(base, buf, c):
        return pltpu.make_async_copy(
            ws_out.at[pl.ds(base + c * _AC, _AC)],
            buf.at[pl.ds(c * _AC, _AC)], copy_sem)

    def _row_load(base, buf, nt):
        """Chunked load of ``nt`` contiguous workspace tiles into ``buf``:
        ceil(nt/8) static-size DMAs, all in flight before the first wait."""
        n_c = (nt + _AC - 1) // _AC

        def st(c, _):
            _row_desc(base, buf, c).start()
            return 0

        def wt(c, _):
            _row_desc(base, buf, c).wait()
            return 0

        jax.lax.fori_loop(0, n_c, st, 0)
        jax.lax.fori_loop(0, n_c, wt, 0)

    def _row_load2(base_a, buf_a, base_b, buf_b, nt):
        """Two rows loaded with ALL chunks of both in flight before any
        wait — the binary elementwise / rms tasks would otherwise pay two
        serial drain latencies."""
        n_c = (nt + _AC - 1) // _AC

        def st(c, _):
            _row_desc(base_a, buf_a, c).start()
            _row_desc(base_b, buf_b, c).start()
            return 0

        def wt(c, _):
            _row_desc(base_a, buf_a, c).wait()
            _row_desc(base_b, buf_b, c).wait()
            return 0

        jax.lax.fori_loop(0, n_c, st, 0)
        jax.lax.fori_loop(0, n_c, wt, 0)

    def _row_store(buf, base, nt):
        """Chunked store of ``nt`` tiles from ``buf``: full 8-tile chunks
        (exact — a chunked OVERstore would clobber neighboring tensors)
        plus per-tile remainder, all overlapped then drained."""
        n_full = nt // _AC

        def cdesc(c):
            return pltpu.make_async_copy(
                buf.at[pl.ds(c * _AC, _AC)],
                ws_out.at[pl.ds(base + c * _AC, _AC)], copy_sem)

        def rdesc(t):
            return pltpu.make_async_copy(buf.at[n_full * _AC + t],
                                         ws_out.at[base + n_full * _AC + t],
                                         copy_sem)

        def st(c, _):
            cdesc(c).start()
            return 0

        def str_(t, _):
            rdesc(t).start()
            return 0

        def wt(c, _):
            cdesc(c).wait()
            return 0

        def wtr(t, _):
            rdesc(t).wait()
            return 0

        jax.lax.fori_loop(0, n_full, st, 0)
        jax.lax.fori_loop(0, nt - n_full * _AC, str_, 0)
        jax.lax.fori_loop(0, n_full, wt, 0)
        jax.lax.fori_loop(0, nt - n_full * _AC, wtr, 0)

    def _gemm_wide_body(b_ws, b_strip):
        # One task computes ``width`` contiguous output column tiles. The
        # A row loads ONCE into the resident row buffer (chunked DMAs),
        # then each pipeline step fetches ONE B strip: a (width,) row for
        # ordinary tasks, or a 4-row SUPER-strip (d0 == 4) when the task
        # spans B's full width (b_stride == width makes 4 consecutive
        # k-rows contiguous) — 4x fewer iterations for the byte-dominant
        # full-width GEMMs. Strip DMA sizes are STATIC (max_gemm_width /
        # the full super width; compile() pads the workspaces so edge
        # overfetch stays in bounds). Per-column fp32 accumulators live in
        # vaccw's leading dim.
        width = arg
        su = d0 == 4
        vaccw[...] = jnp.zeros_like(vaccw)

        # A PREFETCH warm (c0 == 1) targeted the single-tile reserved slot
        # of the old per-tile stream; the strip fetch re-reads that tile
        # anyway, so just CONSUME the outstanding DMA's semaphore (kernel
        # hygiene: exiting with an unawaited DMA is illegal).
        @pl.when(c0 == 1)
        def _():
            pltpu.make_async_copy(b_ws.at[b0], vb2.at[PIPE_DEPTH]
                                  if b_strip is vbw else vb8.at[PIPE_DEPTH],
                                  pipe_sems.at[2 * PIPE_DEPTH]).wait()

        _row_load(a0, vrow_a, k_tiles)

        depth = b_strip.shape[0]
        n_steps = jnp.where(su, k_tiles // 4, k_tiles)

        def sdesc_su(j, slot):
            return pltpu.make_async_copy(
                b_ws.at[pl.ds(b0 + j * 4 * b_stride, b_strip.shape[1])],
                b_strip.at[slot], pipe_sems.at[slot * 2 + 1])

        # Plain fetch width adapts to the buffer (the W8 branch traces in
        # every program; with no fp8 workspace its buffer is 1 tile wide
        # and a static max_gemm_width slice would be out of bounds).
        wpl = min(max_gemm_width, b_strip.shape[1])

        def sdesc_pl(j, slot):
            return pltpu.make_async_copy(
                b_ws.at[pl.ds(b0 + j * b_stride, wpl)],
                b_strip.at[slot].at[pl.ds(0, wpl)],
                pipe_sems.at[slot * 2 + 1])

        def s_start(j, slot):
            @pl.when(su)
            def _():
                sdesc_su(j, slot).start()

            @pl.when(~su)
            def _():
                sdesc_pl(j, slot).start()

        def s_wait(j, slot):
            @pl.when(su)
            def _():
                sdesc_su(j, slot).wait()

            @pl.when(~su)
            def _():
                sdesc_pl(j, slot).wait()

        for jj in range(depth - 1):
            @pl.when(jj < n_steps)
            def _(jj=jj):
                s_start(jj, jj)

        # Dots are STATICALLY unrolled over the max width with w < width
        # predication: each (r, w) dot hits a different static vaccw slot,
        # so consecutive dots are independent and Mosaic can keep the MXU
        # pipeline full — the dynamic-trip fori version serialized them at
        # ~0.1 us each (round-5 profile: the post-DMA-fix residual).
        def jbody(j, _):
            slot = jax.lax.rem(j, depth)
            s_wait(j, slot)

            @pl.when(su)
            def _():
                for r in range(4):
                    a_t = vrow_a[4 * j + r]
                    for w in range(min(max_gemm_width,
                                       b_strip.shape[1] // 4 or 1)):
                        @pl.when(w < width)
                        def _(w=w, r=r, a_t=a_t):
                            vaccw[w, :, :] = vaccw[w] + jnp.dot(
                                a_t, b_strip[slot, r * width + w
                                             ].astype(a_t.dtype),
                                preferred_element_type=jnp.float32)

            @pl.when(~su)
            def _():
                a_t = vrow_a[j]
                for w in range(wpl):
                    @pl.when(w < width)
                    def _(w=w, a_t=a_t):
                        vaccw[w, :, :] = vaccw[w] + jnp.dot(
                            a_t, b_strip[slot, w].astype(a_t.dtype),
                            preferred_element_type=jnp.float32)

            @pl.when(j + depth - 1 < n_steps)
            def _():
                s_start(j + depth - 1,
                        jax.lax.rem(j + depth - 1, depth))

            return 0

        jax.lax.fori_loop(0, n_steps, jbody, 0)

        # Result stores overlap each other (start all, then drain the
        # byte-counting semaphore) instead of a blocking round-trip per
        # output tile.
        def cast_w(w, _):
            vaccw_wdt[w, :, :] = vaccw[w].astype(wdt)
            return 0

        def store_w(w, _):
            pltpu.make_async_copy(vaccw_wdt.at[w], ws_out.at[out + w],
                                  copy_sem).start()
            return 0

        jax.lax.fori_loop(0, width, cast_w, 0)
        jax.lax.fori_loop(0, width, store_w, 0)

        def drain_w(w, _):
            pltpu.make_async_copy(vaccw_wdt.at[w], ws_out.at[out + w],
                                  copy_sem).wait()
            return 0

        jax.lax.fori_loop(0, width, drain_w, 0)

    def t_gemm_wide():
        _gemm_wide_body(ws_out, vbw)

    def t_gemm_wide_w8():
        _gemm_wide_body(ws8, vbw8)

    def t_prefetch_w8():
        # Fire-and-forget warm of fp8 weight tile a0 into vb8's reserved
        # slot (consumed by the next GEMM_WIDE_W8 with c0 == 1).
        pltpu.make_async_copy(ws8.at[a0], vb8.at[PIPE_DEPTH],
                              pipe_sems.at[2 * PIPE_DEPTH]).start()

    def _norm_rope_rows(af, w_row, cosf, sinf, eps):
        """Shared qk-norm + RoPE math over one (TILE, TILE) head tile.
        ``head_dim`` is a STATIC program constant: at head_dim == TILE the
        head fills the tile; at head_dim < TILE the head lives in the low
        ``head_dim`` columns (zero-padded — the projection weights are
        zero there, models.py feed padding), so the norm reduces over
        head_dim and the rotation stays inside the sub-tile (round 9:
        the Qwen3-0.6B/1.7B head_dim-64 presets)."""
        hd = head_dim
        if hd == TILE:
            scale_r = jax.lax.rsqrt(
                jnp.mean(af * af, axis=1, keepdims=True) + eps)
            xn = af * scale_r * w_row
            half = TILE // 2
            rot = jnp.concatenate([-xn[:, half:], xn[:, :half]], axis=1)
        else:
            # Padding is zero, so the all-column sum IS the head_dim sum.
            scale_r = jax.lax.rsqrt(
                jnp.sum(af * af, axis=1, keepdims=True) / hd + eps)
            xn = af * scale_r * w_row
            half = hd // 2
            rot = jnp.concatenate(
                [-xn[:, half:hd], xn[:, :half], xn[:, hd:]], axis=1)
        return xn * cosf + rot * sinf

    def t_norm_rope():
        # Fused per-head qk-norm + RoPE: one load of the head tile instead
        # of the rms_norm task's two streamed passes plus a separate rope
        # task (the norm reduces over this tile's head_dim columns).
        load(a0, va)           # head tile (B, d)
        load(b0, vb)           # norm weight (broadcast rows)
        af = va[...].astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        w_row = vb[...].astype(jnp.float32)
        load(c0, vb)           # cos
        load(d0, vq)           # sin
        va[...] = _norm_rope_rows(af, w_row, vb[...].astype(jnp.float32),
                                  vq[...].astype(jnp.float32), eps
                                  ).astype(wdt)
        store(va, out)

    def t_append_kv():
        # In-kernel KV append (reference appends inside its attn tasks):
        # k_new row 0 -> column c0 of kT cache tile ``out``; v_new row 0 ->
        # row c0 of v cache tile ``b0``. Read-modify-write of the two cache
        # tiles; the scheduler's WAR edges order it after every attention
        # task that read them this step. Speculative window form (queue
        # word 4 = count n >= 1, word 7 = source row offset s): k_new rows
        # s..s+n-1 land at columns c0..c0+n-1 (v rows likewise) — a
        # page-spanning window splits into two rows, the spill row skipped
        # via c0 < 0 when the window stays inside one page tile.
        @pl.when(c0 >= 0)
        def _():
            cnt, src = k_tiles, arg
            rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
            colio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
            load(a0, vq)           # k_new (B, d)
            load(out, va)          # kT cache tile (d, TILE)
            kT_new = vq[...].astype(jnp.float32).T   # (d, B); col j = row j

            @pl.when(cnt == 0)
            def _():               # legacy single-row append (row 0)
                va[...] = jnp.where(
                    colio == c0,
                    jnp.broadcast_to(kT_new[:, 0:1], (TILE, TILE)),
                    va[...].astype(jnp.float32)).astype(wdt)

            @pl.when(cnt > 0)
            def _():
                # Permutation matmul: destination col c takes source row
                # (c - c0 + src); exact — one 1.0 term per column.
                sel = ((rowio == colio - c0 + src) & (colio >= c0)
                       & (colio < c0 + cnt)).astype(jnp.float32)
                new_cols = jnp.dot(kT_new, sel,
                                   preferred_element_type=jnp.float32)
                va[...] = jnp.where((colio >= c0) & (colio < c0 + cnt),
                                    new_cols,
                                    va[...].astype(jnp.float32)
                                    ).astype(wdt)

            store(va, out)
            load(d0, vq)           # v_new (B, d)
            load(b0, va)           # v cache tile (TILE, d)
            vf = vq[...].astype(jnp.float32)

            @pl.when(cnt == 0)
            def _():
                va[...] = jnp.where(
                    rowio == c0,
                    jnp.broadcast_to(vf[0:1, :], (TILE, TILE)),
                    va[...].astype(jnp.float32)).astype(wdt)

            @pl.when(cnt > 0)
            def _():
                sel = ((colio == rowio - c0 + src) & (rowio >= c0)
                       & (rowio < c0 + cnt)).astype(jnp.float32)
                new_rows = jnp.dot(sel, vf,
                                   preferred_element_type=jnp.float32)
                va[...] = jnp.where((rowio >= c0) & (rowio < c0 + cnt),
                                    new_rows,
                                    va[...].astype(jnp.float32)
                                    ).astype(wdt)

            store(va, b0)

    def t_append_kv_f8():
        # APPEND_KV into the fp8 KV-pool workspace (round 12): the new
        # k/v rows come from the MAIN workspace (projection outputs), the
        # cache tiles read-modify-write in the fp8 pool. The cast on
        # append SATURATES to e4m3's ±448 finite range — the
        # models/fp8._to_e4m3 contract; a plain cast would NaN one hot
        # KV element and poison every later softmax over the page.
        # Speculative window form: same word contract as t_append_kv
        # (word 4 = count, word 7 = source offset, c0 < 0 skips the row).
        lim = float(jnp.finfo(jnp.float8_e4m3fn).max)

        @pl.when(c0 >= 0)
        def _():
            cnt, src = k_tiles, arg
            rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
            colio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)

            def rmw(cache_tile, write_mask, new_vals):
                cp = pltpu.make_async_copy(wk8_out.at[cache_tile],
                                           vkv8.at[0], copy_sem)
                cp.start()
                cp.wait()
                merged = jnp.where(write_mask, new_vals,
                                   vkv8[0].astype(jnp.float32))
                vkv8[1, :, :] = jnp.clip(merged, -lim, lim).astype(
                    jnp.float8_e4m3fn)
                cp2 = pltpu.make_async_copy(vkv8.at[1],
                                            wk8_out.at[cache_tile],
                                            copy_sem)
                cp2.start()
                cp2.wait()

            load(a0, vq)       # k_new (B, d) — main workspace
            kT_new = vq[...].astype(jnp.float32).T

            @pl.when(cnt == 0)
            def _():           # legacy single-row append (row 0)
                rmw(out, colio == c0,
                    jnp.broadcast_to(kT_new[:, 0:1], (TILE, TILE)))

            @pl.when(cnt > 0)
            def _():
                sel = ((rowio == colio - c0 + src) & (colio >= c0)
                       & (colio < c0 + cnt)).astype(jnp.float32)
                rmw(out, (colio >= c0) & (colio < c0 + cnt),
                    jnp.dot(kT_new, sel,
                            preferred_element_type=jnp.float32))

            load(d0, vq)       # v_new (B, d)
            vf = vq[...].astype(jnp.float32)

            @pl.when(cnt == 0)
            def _():
                rmw(b0, rowio == c0,
                    jnp.broadcast_to(vf[0:1, :], (TILE, TILE)))

            @pl.when(cnt > 0)
            def _():
                sel = ((colio == rowio - c0 + src) & (rowio >= c0)
                       & (rowio < c0 + cnt)).astype(jnp.float32)
                rmw(b0, (rowio >= c0) & (rowio < c0 + cnt),
                    jnp.dot(sel, vf, preferred_element_type=jnp.float32))

    def t_allreduce():
        # One-shot AR of tile ``out`` (reference tasks/allreduce.py, minus
        # multimem): push to every peer's slot ``me``, reduce all slots,
        # exit barrier so slot reuse by the next AR task is race-free.
        # (Kept for direct builder programs; the decode assembly emits
        # ALLREDUCE_ROW — whole rows per task — since round 6.)
        if n == 1:
            return
        me = dl.rank(axis)
        src = ws_out.at[out]
        local = pltpu.make_async_copy(src, slots.at[me].at[0], copy_sem)
        local.start()
        handles = []
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(shmem.putmem_nbi_block(
                src, slots.at[me].at[0], send_sems.at[i], recv_sem, peer,
                axis))
        local.wait()
        shmem.quiet(*handles)
        shmem.wait_deliveries(src, recv_sem, n - 1)
        vacc[...] = jnp.zeros_like(vacc)
        for r in range(n):
            load_slot = pltpu.make_async_copy(slots.at[r].at[0], va,
                                              copy_sem)
            load_slot.start()
            load_slot.wait()
            vacc[...] = vacc[...] + va[...].astype(jnp.float32)
        va[...] = vacc[...].astype(wdt)
        store(va, out)
        shmem.barrier_all(axis)

    def t_allreduce_row():
        # AllReduce over a whole k_tiles-wide activation row in ONE task:
        # the slab (max_ar static tiles; edge tasks overfetch into the
        # workspace pad) pushes to each peer ONCE, one delivery wait per
        # peer, one exit barrier — vs per-tile push/wait/barrier of the
        # single-tile task (32x fewer remote DMAs and barriers at
        # hidden=4096; the round-6 cross-device queue compaction).
        # ``force_ar`` at n == 1: the full loopback protocol runs against
        # self (one remote self-push + delivery wait per task — the same
        # n=1-loopback discipline as the jit ladder's force_ar_kernel),
        # so single-chip benches can price the in-kernel AR rung.
        if n == 1 and not force_ar:
            return
        me = dl.rank(axis)
        src = ws_out.at[pl.ds(out, max_ar)]
        npush = n - 1 if n > 1 else 1
        if n > 1:
            local = pltpu.make_async_copy(src, slots.at[me], copy_sem)
            local.start()
        handles = []
        for i in range(npush):
            peer = jax.lax.rem(me + 1 + i, n)   # n == 1: peer is self
            handles.append(shmem.putmem_nbi_block(
                src, slots.at[me], send_sems.at[i], recv_sem, peer, axis))
        if n > 1:
            local.wait()
        shmem.quiet(*handles)
        shmem.wait_deliveries(src, recv_sem, npush)

        def tbody(t, _):
            vacc[...] = jnp.zeros_like(vacc)
            for r in range(n):
                load_slot = pltpu.make_async_copy(slots.at[r].at[t], va,
                                                  copy_sem)
                load_slot.start()
                load_slot.wait()
                vacc[...] = vacc[...] + va[...].astype(jnp.float32)
            va[...] = vacc[...].astype(wdt)
            store(va, out + t)
            return 0

        jax.lax.fori_loop(0, k_tiles, tbody, 0)
        if n > 1:
            # Exit barrier: slot reuse by the next AR task must not race a
            # straggler's delivery. At n == 1 (force_ar loopback) the core
            # runs tasks sequentially and the delivery wait above already
            # drained — no barrier (the parity-stream jit rung is likewise
            # barrier-free in steady state).
            shmem.barrier_all(axis)

    def t_scale():
        factor = arg.astype(jnp.float32) * 1e-6
        _ew_task(lambda a, b: a * factor, binary=False)

    def t_rms_norm():
        # One task normalizes a whole row block: k_tiles column tiles of x
        # starting at a0, scaled by the weight tiles at b0 (weight stored as
        # a broadcast (TILE, cols) tensor), written to out. eps arrives
        # fixed-point 1e-9 in arg. Reference tasks/rms_norm.py. The row
        # loads ONCE into the resident buffer; both passes run from VMEM.
        _row_load2(a0, vrow_a, b0, vrow_b, k_tiles)
        vacc[...] = jnp.zeros_like(vacc)

        def pass1(t, _):
            af = vrow_a[t].astype(jnp.float32)
            vacc[:, :1] += jnp.sum(af * af, axis=1, keepdims=True)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass1, 0)
        cols = (k_tiles * TILE).astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        scale = jax.lax.rsqrt(vacc[:, :1] / cols + eps)

        def pass2(t, _):
            vrow_o[t, :, :] = (vrow_a[t].astype(jnp.float32) * scale
                               * vrow_b[t].astype(jnp.float32)).astype(wdt)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass2, 0)
        _row_store(vrow_o, out, k_tiles)

    def t_add_norm():
        # Fused residual add + RMSNorm (round-6 cross-layer fusion for the
        # multi-rank path): x2 = a + b stays VMEM-resident between the
        # add's store and the norm's read — one dispatch and one fewer
        # full-row HBM read than the add + rms_norm task pair. The norm
        # reads the STORED (wdt-rounded) x2 so the result is bit-identical
        # to the unfused pair.
        _row_load2(a0, vrow_a, b0, vrow_b, k_tiles)
        vacc[...] = jnp.zeros_like(vacc)

        def pass1(t, _):
            s = (vrow_a[t].astype(jnp.float32)
                 + vrow_b[t].astype(jnp.float32))
            vrow_o[t, :, :] = s.astype(wdt)
            sf = vrow_o[t].astype(jnp.float32)
            vacc[:, :1] += jnp.sum(sf * sf, axis=1, keepdims=True)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass1, 0)
        _row_store(vrow_o, out, k_tiles)
        _row_load(b_stride, vrow_b, k_tiles)       # norm weight row
        cols = (k_tiles * TILE).astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        scale_n = jax.lax.rsqrt(vacc[:, :1] / cols + eps)

        def pass2(t, _):
            vrow_a[t, :, :] = (vrow_o[t].astype(jnp.float32) * scale_n
                               * vrow_b[t].astype(jnp.float32)).astype(wdt)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass2, 0)
        _row_store(vrow_a, d0, k_tiles)

    def t_norm_rope_qkv():
        # All q+k heads of the fused qkv row in ONE task (round-6 queue
        # compaction): the q/k norm weights and the cos/sin tables load
        # ONCE for the layer; a dynamic fori walks the contiguous head
        # tiles (k heads start at a0 + hq — builder-checked layout).
        load(b0, va2.at[1])         # q_norm weight
        load(a_stride, va2.at[2])   # k_norm weight
        load(c0, va2.at[3])         # cos
        load(d0, vb2.at[1])         # sin
        hq = k_tiles
        eps = arg.astype(jnp.float32) * 1e-9
        cosf = va2[3].astype(jnp.float32)
        sinf = vb2[1].astype(jnp.float32)
        qwf = va2[1].astype(jnp.float32)
        kwf = va2[2].astype(jnp.float32)

        def hbody(h, _):
            load(a0 + h, vq)
            af = vq[...].astype(jnp.float32)
            w_n = jnp.where(h < hq, qwf, kwf)
            va[...] = _norm_rope_rows(af, w_n, cosf, sinf, eps).astype(wdt)
            store(va, a0 + h)
            return 0

        jax.lax.fori_loop(0, hq + b_stride, hbody, 0)

    def _attn_softmax(kt_of, v_of, kv8=False, spec_words=False):
        """Shared online-softmax body: streams (kT_j, V_j) tile pairs by the
        given index functions, then folds in the current token (c0/d0).
        ``kv8``: pairs stream from the fp8 KV-pool workspace at half the
        bytes and WIDEN to fp32 in VMEM before the dots (the
        quantize-then-attend dequant point — accumulation stays fp32
        either way, so parity with the dense fp8-KV paged path is
        exact). ``spec_words`` (the PAGED serving variants only): queue
        word 5 carries the speculative-decode candidate WINDOW — 0 keeps
        the legacy per-row diagonal fold (each batch row its own current
        token), win >= 1 folds the block's fresh k/v CAUSALLY (row i
        attends fresh rows j <= i, j < win — draft-and-verify, row 0
        degenerating to the diagonal fold's row-0 math exactly)."""
        load(a0, vq)
        scale = arg.astype(jnp.float32) * 1e-6
        valid = b_stride
        neg = jnp.float32(-1e30)
        vacc[...] = jnp.zeros_like(vacc)
        m0 = jnp.full((TILE, 1), neg, jnp.float32)
        l0 = jnp.zeros((TILE, 1), jnp.float32)

        def body(j, kt_ref, v_ref, carry):
            m, l = carry
            if kv8:
                kt = kt_ref[...].astype(jnp.float32)
                vv = v_ref[...].astype(jnp.float32)
                qv = vq[...].astype(jnp.float32)
            else:
                kt, vv, qv = kt_ref[...], v_ref[...], vq[...]
            s = jnp.dot(qv, kt,                   # KT_j: (d, TILE)
                        preferred_element_type=jnp.float32) * scale
            col = j * TILE + jax.lax.broadcasted_iota(
                jnp.int32, (TILE, TILE), 1)
            s = jnp.where(col < valid, s, neg)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            pv = jnp.dot(p.astype(vv.dtype), vv,  # V_j: (TILE, d)
                         preferred_element_type=jnp.float32)
            vacc[...] = vacc[...] * corr + pv
            return (m_new, l * corr + jnp.sum(p, axis=1, keepdims=True))

        m, l = pipelined_pairs(kt_of, v_of, k_tiles, body, (m0, l0),
                               kv8=kv8)

        def cur_kv():
            # Current token's k/v arrive full-width from the MAIN
            # workspace. Under kv8 they must QUANTIZE (saturating e4m3
            # round-trip) before joining the softmax: the dense path
            # appends-then-attends, so the current token's contribution
            # there is the STORED e4m3 value — folding the wide value
            # here would break cross-backend token parity on exactly
            # the step each token is current.
            x = vb[...].astype(jnp.float32)
            if kv8:
                lim = float(jnp.finfo(jnp.float8_e4m3fn).max)
                x = jnp.clip(x, -lim, lim).astype(jnp.float8_e4m3fn
                                                  ).astype(jnp.float32)
            return x

        def diag_fold():
            # Current token: per-row dot with each row's own k/v.
            load(c0, vb)                           # k_new: (B, d)
            s_cur = jnp.sum(vq[...].astype(jnp.float32) * cur_kv(),
                            axis=1, keepdims=True) * scale
            m_new = jnp.maximum(m, s_cur)
            p_cur = jnp.exp(s_cur - m_new)
            corr = jnp.exp(m - m_new)
            load(d0, vb)                           # v_new: (B, d)
            vacc[...] = vacc[...] * corr + p_cur * cur_kv()
            vstat[:, :1] = l * corr + p_cur

        def window_fold(win):
            # Speculative verify: the block's fresh k/v (rows 0..win-1 of
            # c0/d0 — the last accepted token plus the drafts) join the
            # softmax CAUSALLY: candidate row i attends fresh rows j <= i.
            # Masked entries underflow to exp(-1e30 - m) == 0.0 exactly,
            # so win == 1 reproduces the diagonal fold's row-0 result
            # bit-for-bit (one matched term plus exact zeros).
            load(c0, vb)                           # k_new: (win.., d)
            s_w = jnp.dot(vq[...].astype(jnp.float32), cur_kv().T,
                          preferred_element_type=jnp.float32) * scale
            rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
            colio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
            s_w = jnp.where((colio <= rowio) & (colio < win), s_w, neg)
            m_new = jnp.maximum(m, jnp.max(s_w, axis=1, keepdims=True))
            p_w = jnp.exp(s_w - m_new)
            corr = jnp.exp(m - m_new)
            load(d0, vb)                           # v_new: (win.., d)
            vacc[...] = vacc[...] * corr + jnp.dot(
                p_w, cur_kv(), preferred_element_type=jnp.float32)
            vstat[:, :1] = l * corr + jnp.sum(p_w, axis=1, keepdims=True)

        @pl.when(c0 >= 0)
        def _():
            if spec_words:
                win = a_stride                     # w(5): 0 = legacy

                @pl.when(win == 0)
                def _():
                    diag_fold()

                @pl.when(win > 0)
                def _():
                    window_fold(win)
            else:
                diag_fold()

        @pl.when(c0 < 0)
        def _():
            vstat[:, :1] = l

        va[...] = (vacc[...] / jnp.maximum(vstat[:, :1], 1e-30)).astype(wdt)
        store(va, out)

    def _paged_table(j_kind):
        # Page-table walk: the j-th (kT, V) tile pair comes from queue DATA
        # rows starting at row b0 — entry pair j at flat offsets (2j, 2j+1).
        # The table rides scalar prefetch (SMEM), so the DMA addresses are
        # data-dependent exactly like ops/paged_attention.py's table walk.
        def of(j):
            f = 2 * j + j_kind
            return queue_ref[b0 + f // WORDS, jax.lax.rem(f, WORDS)]

        return of

    def t_attn_decode_paged():
        _attn_softmax(_paged_table(0), _paged_table(1), spec_words=True)

    def t_attn_decode_paged_f8():
        # The fp8-pool variant (round 12): identical table walk and
        # softmax, but every page tile DMA moves HALF the bytes from the
        # fp8 KV workspace and widens to fp32 in VMEM — the static dtype
        # branch (warm-spec pattern applied to storage dtype).
        _attn_softmax(_paged_table(0), _paged_table(1), kv8=True,
                      spec_words=True)

    def t_attn_decode():
        # Single-token GQA decode for one q head: online-softmax flash
        # attention over S = k_tiles*TILE cached positions, masked to
        # b_stride valid rows. q: one (TILE, TILE) tile (rows = padded
        # batch, cols = head_dim); KT tiles at b0+j (d, TILE); V tiles at
        # a_stride+j (TILE, d). When c0 >= 0, the current token's k/v tiles
        # (c0/d0, each (B, d), one per batch row) join the softmax rowwise —
        # the cache is appended after the step instead of mutated in-kernel.
        # Reference: tasks/flash_attn.py (paged FA decode task).
        _attn_softmax(lambda j: b0 + j, lambda j: a_stride + j)

    def t_attn_decode_gqa():
        # A whole GQA group in one task: g q-heads (tiles a0..a0+g-1) share
        # the kv head's KT/V stream — tiles stream ONCE for the group and
        # g-1 dispatches vanish. Per-head state lives in the group scratch
        # (vqg/vaccg/vstatg: stats col 0 = m, col 1 = l); statically
        # unrolled over max_gqa with h < g masking.
        g = arg >> 24
        scale = (arg & 0xFFFFFF).astype(jnp.float32) * 1e-6
        valid = b_stride
        neg = jnp.float32(-1e30)
        for h in range(max_gqa):
            @pl.when(h < g)
            def _(h=h):
                load(a0 + h, vqg.at[h])
                vaccg[h, :, :] = jnp.zeros_like(vaccg[h])
                vstatg[h, :, 0:1] = jnp.full((TILE, 1), neg, jnp.float32)
                vstatg[h, :, 1:2] = jnp.zeros((TILE, 1), jnp.float32)

        def body(j, kt_ref, v_ref, _):
            col = j * TILE + jax.lax.broadcasted_iota(
                jnp.int32, (TILE, TILE), 1)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    s = jnp.dot(vqg[h], kt_ref[...],
                                preferred_element_type=jnp.float32) * scale
                    s = jnp.where(col < valid, s, neg)
                    m_prev = vstatg[h, :, 0:1]
                    m_new = jnp.maximum(m_prev,
                                        jnp.max(s, axis=1, keepdims=True))
                    p = jnp.exp(s - m_new)
                    corr = jnp.exp(m_prev - m_new)
                    pv = jnp.dot(p.astype(v_ref.dtype), v_ref[...],
                                 preferred_element_type=jnp.float32)
                    vaccg[h, :, :] = vaccg[h] * corr + pv
                    vstatg[h, :, 0:1] = m_new
                    vstatg[h, :, 1:2] = (vstatg[h, :, 1:2] * corr
                                         + jnp.sum(p, axis=1, keepdims=True))
            return 0

        pipelined_pairs(lambda j: b0 + j, lambda j: a_stride + j,
                        k_tiles, body, 0)

        @pl.when(c0 >= 0)
        def _():
            load(c0, vb)                           # k_new: (B, d)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    s_cur = jnp.sum(vqg[h].astype(jnp.float32)
                                    * vb[...].astype(jnp.float32),
                                    axis=1, keepdims=True) * scale
                    m_prev = vstatg[h, :, 0:1]
                    m_new = jnp.maximum(m_prev, s_cur)
                    p_cur = jnp.exp(s_cur - m_new)
                    corr = jnp.exp(m_prev - m_new)
                    vstatg[h, :, 0:1] = m_new
                    # stash p_cur in stats col 2 for the v_new pass
                    vstatg[h, :, 2:3] = p_cur
                    vstatg[h, :, 1:2] = vstatg[h, :, 1:2] * corr + p_cur
                    vaccg[h, :, :] = vaccg[h] * corr
            load(d0, vb)                           # v_new: (B, d)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    vaccg[h, :, :] = (vaccg[h] + vstatg[h, :, 2:3]
                                * vb[...].astype(jnp.float32))

        for h in range(max_gqa):
            @pl.when(h < g)
            def _(h=h):
                va[...] = (vaccg[h] / jnp.maximum(vstatg[h, :, 1:2], 1e-30)
                           ).astype(wdt)
                store(va, out + h)

    def t_moe_topk():
        # Router top-k + softmax over the selected logits (the
        # ops/moe.route_and_sort convention), producing the dense (E, B)
        # TRANSPOSED weight tile MOE_FFN's skip predicate reads. Pure VPU:
        # iterative leftmost-argmax selection, no data-dependent control
        # flow, one transpose at the end.
        # Precision scope: the logits tile arrives in the WORKSPACE dtype
        # — on a bf16 workspace the top-k compares bf16-rounded logits,
        # so experts within ~0.4% relative can swap vs the fp32 router
        # convention (token-identity to the layer path is exact on fp32
        # workspaces; bf16 serving accepts the quantized-router variant,
        # the same class of deviation as its bf16 activations).
        load(a0, va)
        lg = va[...].astype(jnp.float32)
        num_e = b_stride
        batch = d0
        colio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
        rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        neg = jnp.float32(-1e30)
        lg = jnp.where((colio < num_e) & (rowio < batch), lg, neg)
        m0 = jnp.max(lg, axis=1, keepdims=True)

        def body(i, carry):
            work, selmask = carry
            m = jnp.max(work, axis=1, keepdims=True)
            is_m = (work == m) & (work > neg * 0.5)
            idx = jnp.min(jnp.where(is_m, colio, TILE), axis=1,
                          keepdims=True)
            pick = colio == idx
            return jnp.where(pick, neg, work), \
                jnp.where(pick, 1.0, selmask)

        _, selmask = jax.lax.fori_loop(
            0, arg, body, (lg, jnp.zeros((TILE, TILE), jnp.float32)))
        wgt = jnp.where(selmask > 0, jnp.exp(lg - m0), 0.0)
        z = jnp.sum(wgt, axis=1, keepdims=True)
        wgt = wgt / jnp.maximum(z, 1e-30)
        va[...] = wgt.T.astype(wdt)           # (E, B) transposed
        store(va, out)

    def t_moe_ffn():
        # One layer's whole expert MLP: loop experts, SKIP inactive ones
        # before any weight DMA — active experts (≈ B·topk of E) stream
        # gate/up strips per hidden tile and down strips per ffn tile,
        # silu(x@wg)·(x@wu) weighted per token, accumulated into the
        # output row. See tasks.py MOE_FFN for the word layout.
        ht = k_tiles
        num_e = arg & 0xFFFF
        ft = arg >> 16
        wg_base, wu_base, wd_base = a_stride, b_stride, c0

        load(b0, vq)                           # WT (E, B) weight tile
        _row_load(a0, vrow_a, ht)              # xn row resident

        def zo(j, _):
            vmoe_o[j, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
            return 0

        jax.lax.fori_loop(0, ht, zo, 0)
        rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        eye = rowio == jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)

        def ebody(e, _):
            wt = vq[...].astype(jnp.float32)
            w_tok = jnp.sum(jnp.where(rowio == e, wt, 0.0), axis=0)  # (B,)
            active = jnp.sum(w_tok) > 0.0

            @pl.when(active)
            def _():
                # Per-token weight as a column (lane -> sublane via the
                # eye-mask reduction, the flash _col_to_row idiom).
                w_col = jnp.sum(
                    jnp.where(eye, jnp.broadcast_to(w_tok[None, :],
                                                    (TILE, TILE)), 0.0),
                    axis=1, keepdims=True)

                def zf(f, _):
                    vmoe_a[f, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
                    vmoe_b[f, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
                    return 0

                jax.lax.fori_loop(0, ft, zf, 0)

                # Gate/up strips double-buffered as FOUR regions of the
                # 2-slot strip buffer — (slot, offset) pairs: gate lives
                # in slot 0 at offsets {0, MF}, up in slot 1 — exact
                # static-size (MF-tile) fetches, two (gate, up) pairs in
                # flight, so the per-DMA issue latency rides under the
                # previous step's dots.
                mf = vmoe_a.shape[0]

                def gu_desc(j, p):
                    g = pltpu.make_async_copy(
                        ws_out.at[pl.ds(wg_base + (e * ht + j) * ft, mf)],
                        vbw.at[0].at[pl.ds(p * mf, mf)],
                        pipe_sems.at[1 + p])
                    u = pltpu.make_async_copy(
                        ws_out.at[pl.ds(wu_base + (e * ht + j) * ft, mf)],
                        vbw.at[1].at[pl.ds(p * mf, mf)],
                        pipe_sems.at[3 + p])
                    return g, u

                def gu_start(j, p):
                    g, u = gu_desc(j, p)
                    g.start()
                    u.start()

                gu_start(0, 0)

                @pl.when(ht > 1)
                def _():
                    gu_start(1, 1)

                def jbody(j, _):
                    p = jax.lax.rem(j, 2)
                    g, u = gu_desc(j, p)
                    g.wait()
                    u.wait()
                    a = vrow_a[j]

                    def fbody(f, _):
                        vmoe_a[f, :, :] = vmoe_a[f] + jnp.dot(
                            a, vbw[0, p * mf + f].astype(a.dtype),
                            preferred_element_type=jnp.float32)
                        vmoe_b[f, :, :] = vmoe_b[f] + jnp.dot(
                            a, vbw[1, p * mf + f].astype(a.dtype),
                            preferred_element_type=jnp.float32)
                        return 0

                    jax.lax.fori_loop(0, ft, fbody, 0)

                    @pl.when(j + 2 < ht)
                    def _():
                        gu_start(j + 2, p)

                    return 0

                jax.lax.fori_loop(0, ht, jbody, 0)

                def actf(f, _):
                    vmoe_a[f, :, :] = (jax.nn.silu(vmoe_a[f]) * vmoe_b[f]
                                       * w_col)
                    return 0

                jax.lax.fori_loop(0, ft, actf, 0)

                # Down strips: (slot, offset) regions again, MH-tile
                # static fetches, two in flight.
                mh = vmoe_o.shape[0]

                def d_desc(f, p):
                    return pltpu.make_async_copy(
                        ws_out.at[pl.ds(wd_base + (e * ft + f) * ht, mh)],
                        vbw.at[p].at[pl.ds(0, mh)],
                        pipe_sems.at[5 + p])

                d_desc(0, 0).start()

                @pl.when(ft > 1)
                def _():
                    d_desc(1, 1).start()

                def fdown(f, _):
                    p = jax.lax.rem(f, 2)
                    d_desc(f, p).wait()
                    af = vmoe_a[f].astype(wdt)

                    def jh(j, _):
                        vmoe_o[j, :, :] = vmoe_o[j] + jnp.dot(
                            af, vbw[p, j].astype(af.dtype),
                            preferred_element_type=jnp.float32)
                        return 0

                    jax.lax.fori_loop(0, ht, jh, 0)

                    @pl.when(f + 2 < ft)
                    def _():
                        d_desc(f + 2, p).start()

                    return 0

                jax.lax.fori_loop(0, ft, fdown, 0)

            return 0

        jax.lax.fori_loop(0, num_e, ebody, 0)

        def st(j, _):
            va[...] = vmoe_o[j].astype(wdt)
            store(va, out + j)
            return 0

        jax.lax.fori_loop(0, ht, st, 0)

    def _mat_body(sp):
        """One STATIC specialized GEMM_MAT body (tasks.py GEMM_MAT): every
        trip count, fetch size, dot shape, and store offset is a Python
        constant from the spec — the probe-measured cure for the dynamic-
        predication tax (scripts/probe_gemm_task.py). Fully unrolled."""
        n_ch = sp.n_ch
        total = sp.ns * n_ch
        kq = sp.kch // TILE
        spt = (MAT_COLS // 2 if sp.epi == 1 else MAT_COLS) // TILE

        def body():
            def cdesc(t, slot):
                dst = (vbm.at[slot] if sp.kch == kch_max
                       else vbm.at[slot].at[pl.ds(0, sp.kch)])
                # Row offset written as (x * 8) so Mosaic can prove the
                # sublane-tiling divisibility of the dynamic base (every
                # MatHandle base is a multiple of TILE = 128).
                row = (b0 // 8 + t * (sp.kch // 8)) * 8
                return pltpu.make_async_copy(
                    wm.at[pl.ds(row, sp.kch)], dst,
                    pipe_sems.at[slot * 2 + 1])

            def rdesc(s, w_):
                return pltpu.make_async_copy(
                    ws_out.at[c0 + s * spt + w_], vrow_b.at[w_], copy_sem)

            def odesc(s, w_):
                return pltpu.make_async_copy(
                    voutm.at[:, pl.ds(w_ * TILE, TILE)],
                    ws_out.at[out + s * spt + w_], copy_sem)

            def wdesc():
                # The warm descriptor a PREFETCH_MAT task started earlier
                # (same words: its a0 == this task's b0): chunk 0 into
                # the reserved matrix slot on the warm semaphore.
                dst = (vbm.at[2] if sp.kch == kch_max
                       else vbm.at[2].at[pl.ds(0, sp.kch)])
                row = (b0 // 8) * 8
                return pltpu.make_async_copy(
                    wm.at[pl.ds(row, sp.kch)], dst,
                    pipe_sems.at[2 * PIPE_DEPTH + 1])

            # Layer-seam prefetch (round 6): the first weight chunks start
            # streaming BEFORE the A row loads — the A row of a seam task
            # is the previous task's freshly stored output, but the weight
            # chunks are static inputs, so their DMA hides under the A-row
            # landing instead of serializing after it. A warm spec (round
            # 9) goes further: chunk 0 has been streaming into the
            # reserved slot since the PREFETCH_MAT task fired — under
            # whatever tasks the scheduler placed in between.
            if not sp.warm:
                cdesc(0, 0).start()
            if total > 1:
                cdesc(1, 1).start()
            _row_load(a0, vrow_a, sp.kt)
            if sp.epi == 3:
                vacc[...] = jnp.zeros_like(vacc)
            for t in range(total):
                s, j = divmod(t, n_ch)
                slot = 2 if (sp.warm and t == 0) else t % 2
                rw = min(spt, sp.nt_out - s * spt)
                if sp.warm and t == 0:
                    wdesc().wait()
                else:
                    cdesc(t, slot).wait()
                if sp.epi in (2, 3) and j == 0:
                    # residual strip tiles arrive under the dots
                    for w_ in range(rw):
                        rdesc(s, w_).start()
                # fp32 workspaces ask for HIGHEST so the one-kernel step
                # tracks the XLA jit golden (Mosaic's default f32 matmul
                # is a single bf16 pass, ~1e-2 relative at K=1024 — the
                # multi-pass matches XLA's f32 class). bf16 serving keeps
                # the default: operands are bf16 either way.
                prec = (jax.lax.Precision.HIGHEST
                        if wdt == jnp.float32 else None)
                for q in range(kq):
                    d_ = jnp.dot(vrow_a[j * kq + q],
                                 vbm[slot, pl.ds(q * TILE, TILE), :],
                                 preferred_element_type=jnp.float32,
                                 precision=prec)
                    if j == 0 and q == 0:
                        vaccm[...] = d_
                    else:
                        vaccm[...] = vaccm[...] + d_
                if t + 2 < total:
                    cdesc(t + 2, (t + 2) % 2).start()
                if j == n_ch - 1:
                    if sp.epi == 1:
                        half = MAT_COLS // 2
                        voutm[:, :half] = (
                            jax.nn.silu(vaccm[:, :half])
                            * vaccm[:, half:]).astype(wdt)
                    elif sp.epi in (2, 3):
                        for w_ in range(rw):
                            rdesc(s, w_).wait()
                        for w_ in range(rw):
                            voutm[:, pl.ds(w_ * TILE, TILE)] = (
                                vaccm[:, pl.ds(w_ * TILE, TILE)]
                                + vrow_b[w_].astype(jnp.float32)
                            ).astype(wdt)
                        if sp.epi == 3:
                            # Keep the x2 strip VMEM-resident for the fused
                            # norm pass and accumulate its sum-of-squares
                            # (from the STORED wdt values — bit-identical
                            # to an unfused rms_norm reading x2 back).
                            for w_ in range(rw):
                                x2t = voutm[:, w_ * TILE:(w_ + 1) * TILE]
                                vrow_o[s * spt + w_, :, :] = x2t
                                x2f = x2t.astype(jnp.float32)
                                vacc[:, :1] += jnp.sum(
                                    x2f * x2f, axis=1, keepdims=True)
                    else:
                        voutm[...] = vaccm[...].astype(wdt)
                    for w_ in range(rw):
                        odesc(s, w_).start()
                    # Drain before the next strip's epilogue rewrites
                    # voutm (dots in between hide most of the latency).
                    for w_ in range(rw):
                        odesc(s, w_).wait()
            if sp.epi == 3:
                # Epilogue-3 norm pass (cross-layer fusion): xn =
                # rms_norm(x2) * w written to the d0 row — the x2 row
                # never re-reads from HBM, and the consuming layer's norm
                # task disappears from the queue.
                _row_load(b_stride, vrow_b, sp.nt_out)
                cols = jnp.float32(sp.nt_out * TILE)
                eps = (arg >> 8).astype(jnp.float32) * 1e-9
                scale_n = jax.lax.rsqrt(vacc[:, :1] / cols + eps)

                def npass(t2, _):
                    vrow_a[t2, :, :] = (
                        vrow_o[t2].astype(jnp.float32) * scale_n
                        * vrow_b[t2].astype(jnp.float32)).astype(wdt)
                    return 0

                jax.lax.fori_loop(0, sp.nt_out, npass, 0)
                _row_store(vrow_a, d0, sp.nt_out)
            return None

        return body

    def t_gemm_mat():
        if not mat_specs:
            return
        bodies = [_mat_body(sp) for sp in mat_specs]
        if len(bodies) == 1:
            bodies[0]()
        else:
            jax.lax.switch(a_stride, bodies)

    def t_prefetch_mat():
        # Fire-and-forget warm of a GEMM_MAT weight's FIRST chunk into the
        # reserved matrix slot (round 9 stall-slice kill): the DMA flies
        # under whatever tasks the scheduler placed between this and the
        # consuming warm-spec GEMM_MAT — attention at n=1, the
        # ALLREDUCE_ROW barrier at n>1. Words: a0 = wsm row base,
        # a_stride = the consuming task's spec index (static kch).
        if not mat_specs:
            return

        def warm_start(sp):
            def body():
                dst = (vbm.at[2] if sp.kch == kch_max
                       else vbm.at[2].at[pl.ds(0, sp.kch)])
                row = (a0 // 8) * 8
                pltpu.make_async_copy(
                    wm.at[pl.ds(row, sp.kch)], dst,
                    pipe_sems.at[2 * PIPE_DEPTH + 1]).start()
            return body

        bodies = [warm_start(sp) for sp in mat_specs]
        if len(bodies) == 1:
            bodies[0]()
        else:
            jax.lax.switch(a_stride, bodies)

    bodies = [t_copy, t_add, t_silu_mul, t_retired, t_allreduce,
              t_scale, t_rms_norm, t_retired, t_attn_decode,
              t_attn_decode_paged, t_prefetch,
              t_attn_decode_gqa, t_gemm_wide, t_norm_rope,
              t_append_kv, t_gemm_wide_w8, t_prefetch_w8,
              t_moe_topk, t_moe_ffn, t_gemm_mat, t_add_norm,
              t_norm_rope_qkv, t_allreduce_row, t_prefetch_mat,
              t_attn_decode_paged_f8, t_append_kv_f8]
    if used_types is not None:
        # Branch pruning (round 6): a compiled program's task-type set is
        # static — every absent type's handler compiles as the no-op, so
        # build latency scales with the types a program USES, not the
        # whole handler library. Queue positions stay ABI-stable (a row
        # naming a pruned type would silently no-op, exactly like the
        # retired slots — builder.compile derives the set from its own
        # queue, which advance_queue_pos never changes).
        bodies = [b if i in used_types else t_retired
                  for i, b in enumerate(bodies)]
    jax.lax.switch(w(0), bodies)


def _stamp_profile(queue_ref, prof_ref):
    """profile=True: stamp this grid step's execution record — the step
    index plus the task's full queue row (SMEM scalars) — into the step's
    (1, 128) profile-output block. Grid steps run sequentially on the
    core, so the dump is the core's actual in-order dispatch record
    (obs/kernel_profile.py decodes it into per-task timeline lanes).
    Scalar values land in lanes 0..WORDS via lane-masked selects (a plain
    scalar store into a VMEM row is not portably supported); unused lanes
    hold -1."""
    step = pl.program_id(0)
    lane = jax.lax.broadcasted_iota(jnp.int32, (1, 128), 1)
    row = jnp.full((1, 128), -1, jnp.int32)
    vals = [step] + [queue_ref[step, j] for j in range(WORDS)]
    for i, v in enumerate(vals):
        row = jnp.where(lane == i, v, row)
    prof_ref[...] = row


def run_queue(queue, workspace, *, num_ranks: int = 1, axis: str = "tp",
              num_tasks: int | None = None, max_gqa: int = 1,
              max_gemm_width: int = 1, workspace8=None,
              max_moe_h: int = 0, max_moe_f: int = 0,
              max_row: int = 1, max_strip: int = 0,
              workspace_m=None, mat_specs: tuple = (),
              max_ar: int = 1, force_ar: bool = False,
              used_types: tuple | None = None,
              head_dim: int = TILE,
              workspace_kv8=None,
              profile: bool = False):
    """Execute the packed task queue over the workspace in ONE pallas_call.

    queue: (n_rows, WORDS) int32; workspace: (T, TILE, TILE) fp32 or bf16
    (local per device when num_ranks > 1 — call inside shard_map). bf16
    halves every tile DMA; compute stays fp32 on the VPU/MXU.
    CONTRACT: T must include max_gemm_width-1 PAD tiles past the last
    real tile (GEMM_WIDE fetches static full-width B strips; narrower
    edge strips overfetch into the pad) — CompiledMegaKernel.make_workspace
    adds the pad; raw callers must too.
    ``num_tasks``: dispatched rows (default all) — rows beyond are DATA
    (ATTN_DECODE_PAGED page tables) the grid never visits.
    ``max_gqa``: largest ATTN_DECODE_GQA group in the queue (sizes the
    per-head group scratch; 1 when unused).
    ``max_gemm_width``: widest GEMM_WIDE strip (sizes the per-column
    accumulator scratch; 1 when unused).
    ``workspace8``: optional (T8, TILE, TILE) float8_e4m3fn READ-ONLY
    weight workspace (GEMM_WIDE_W8 / PREFETCH_W8 B-tile source — half the
    weight-streaming bytes of bf16).
    ``force_ar``: run the ALLREDUCE_ROW protocol even at num_ranks == 1
    (remote self-push loopback — the cross-device rung's single-chip
    pricing mode; call inside shard_map over a 1-device mesh).
    ``used_types``: the task types the queue dispatches (ints) — every
    other switch branch compiles as a no-op, cutting trace+compile time
    to the handlers a program actually uses. ``None`` (raw callers)
    keeps the full handler library. Rows naming a pruned type silently
    no-op, like the retired slots — pass the set your queue uses.
    ``head_dim``: static per-head width of the NORM_ROPE / NORM_ROPE_QKV
    tasks (the norm reduction span and RoPE rotation half). head_dim <
    TILE heads live zero-padded in the low columns of their tile
    (models.py pads the projection weights), so attention needs no
    change — only the norm/rope sub-tile math does (round 9).
    ``workspace_kv8``: optional (Tk8, TILE, TILE) float8_e4m3fn
    READ-WRITE KV-pool workspace (ATTN_DECODE_PAGED_F8 streams it at
    half the bytes; APPEND_KV_F8 saturate-casts appends into it) —
    aliased in place like the main workspace, and the return becomes
    ``(workspace, workspace_kv8)``.
    ``profile``: add an int32 (n_tasks, 128) profile OUTPUT — each grid
    step stamps [exec_index, *queue_row] into its row (the observability
    per-task dispatch record, obs/kernel_profile.py); the return grows
    ``profile_dump`` as its last element.
    Returns the post-execution workspace(s).
    """
    n_tasks = num_tasks if num_tasks is not None else queue.shape[0]
    assert queue.shape[1] == WORDS
    n = num_ranks
    T = workspace.shape[0]
    wdt = workspace.dtype
    G = max(max_gqa, 1)
    AR = max(max_ar, 1)   # ALLREDUCE_ROW slab width (slots second dim)
    # MoE strips share the GEMM_WIDE strip buffer: it must span the wider
    # of the ffn strips (gate/up, max_moe_f tiles) and the hidden strips
    # (down, max_moe_h tiles). ``max_moe_*=0`` = program has no MoE.
    MH = max(max_moe_h, 1)
    MF = max(max_moe_f, 1)
    W = max(max_gemm_width, max_moe_h, max_moe_f, 1)
    # Resident row buffers: ceil to the 8-tile chunk the row loads use.
    R = -(-max(max_row, 1) // 8) * 8
    # Strip buffer width: the widest fetch any task issues (4-row super
    # strips of full-width GEMMs, or the plain max width); two slots in
    # flight — super strips are big enough that transfer, not issue
    # latency, dominates. Floor 2*MF / MH: the (undispatched) MoE branch
    # still TRACES its static region offsets in every program.
    SW = max(max_strip, W, 2 * MF, MH)
    # Matrix-workspace geometry: chunk buffer sized to the largest spec;
    # a one-row placeholder rides along when the program has no GEMM_MAT
    # tasks (the branch body is then empty — nothing reads it). Same
    # pattern as vbw8 below: with mat_specs empty the GEMM_MAT branch
    # never dispatches, so its vbm/vaccm/voutm scratch shrinks to minimal
    # aligned shapes (8-row sublane, 128-lane) instead of holding ~2 MB of
    # VMEM in every fp8/MoE program (round-5 ADVICE).
    mat_absent = not mat_specs
    kch_max = max((sp.kch for sp in mat_specs), default=TILE)
    m_kch = kch_max if not mat_absent else 8
    m_rows = TILE if not mat_absent else 8
    m_cols = MAT_COLS if not mat_absent else 128
    # The reserved warm slot (vbm[2]) is referenced only by warm-spec
    # GEMM_MAT branches and a dispatchable PREFETCH_MAT handler; programs
    # with neither keep the two-slot footprint.
    warm_possible = (not mat_absent
                     and (any(sp.warm for sp in mat_specs)
                          or used_types is None
                          or int(TaskType.PREFETCH_MAT) in used_types))
    m_slots = 3 if warm_possible else 2
    if workspace_m is None:
        workspace_m = jnp.zeros((1, MAT_COLS), wdt)
    w8_absent = workspace8 is None
    if workspace8 is None:
        workspace8 = jnp.zeros((1, TILE, TILE), jnp.float8_e4m3fn)
    kv8_present = workspace_kv8 is not None
    if workspace_kv8 is None:
        workspace_kv8 = jnp.zeros((1, TILE, TILE), jnp.float8_e4m3fn)
    # The fp8 KV scratch (kT + V double-buffer slots, 2*PIPE_DEPTH tiles)
    # exists full-size only when an fp8-pool handler can dispatch —
    # passed pools, the full handler library (raw callers), or a queue
    # naming the F8 types; everyone else keeps a 2-tile placeholder
    # (same footprint discipline as the warm vbm slot / vbw8 shrink).
    kv8_possible = (kv8_present or used_types is None
                    or int(TaskType.ATTN_DECODE_PAGED_F8) in used_types
                    or int(TaskType.APPEND_KV_F8) in used_types)
    kv8_slots = 2 * PIPE_DEPTH if kv8_possible else 2
    if workspace8.shape[0] < SW + 1:
        # The compiled GEMM_WIDE_W8 branch statically slices strips (and
        # exists in the switch even for programs that never dispatch it)
        # — an undersized placeholder must pad so the slice bound checks
        # out.
        workspace8 = jnp.pad(
            workspace8, ((0, SW + 1 - workspace8.shape[0]), (0, 0), (0, 0)))

    # AR slots ride as a second output: Mosaic has no HBM scratch (see
    # language/core.py kernel_call ``workspaces``).
    # The fp8 KV-pool workspace is a third, ALIASED like the main one
    # (appends mutate it in place; a placeholder tile rides along when
    # the program has no fp8 pools, same as the ws8 input).
    # profile adds a fourth: the (n_tasks, 128) int32 stamp buffer,
    # blocked one row per grid step so each task writes only its own
    # record.
    out_specs = [any_spec(), any_spec(), any_spec()]
    if profile:
        out_specs.append(pl.BlockSpec((1, 128), lambda t, *_pf: (t, 0)))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tasks,),
        in_specs=[any_spec(), any_spec(), any_spec(), any_spec()],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((PIPE_DEPTH, TILE, TILE), wdt),      # va2
            pltpu.VMEM((PIPE_DEPTH + 1, TILE, TILE), wdt),  # vb2 (+pf slot)
            pltpu.VMEM((PIPE_DEPTH + 1, TILE, TILE),
                       jnp.float8_e4m3fn),                  # vb8 (+pf slot)
            pltpu.VMEM((2, SW, TILE, TILE), wdt),           # vbw (B strips)
            # fp8 strip buffer shrinks to 1 tile when the program has no
            # fp8 workspace (the W8 branch still compiles; it adapts via
            # b_strip.shape[1]).
            pltpu.VMEM((2, SW if not w8_absent else 1, TILE, TILE),
                       jnp.float8_e4m3fn),                  # vbw8
            pltpu.VMEM((TILE, TILE), jnp.float32),      # vacc (fp32 accum)
            pltpu.VMEM((TILE, TILE), wdt),              # vq: rope/attn operand
            pltpu.VMEM((TILE, 128), jnp.float32),       # vstat (softmax stats)
            pltpu.VMEM((G, TILE, TILE), wdt),           # vqg (group q tiles)
            pltpu.VMEM((G, TILE, TILE), jnp.float32),   # vaccg
            pltpu.VMEM((G, TILE, 128), jnp.float32),    # vstatg
            pltpu.VMEM((W, TILE, TILE), jnp.float32),   # vaccw (wide GEMM)
            pltpu.VMEM((W, TILE, TILE), wdt),           # vaccw_wdt (stores)
            pltpu.VMEM((R, TILE, TILE), wdt),           # vrow_a (resident)
            pltpu.VMEM((R, TILE, TILE), wdt),           # vrow_b
            pltpu.VMEM((R, TILE, TILE), wdt),           # vrow_o
            pltpu.VMEM((MF, TILE, TILE), jnp.float32),  # vmoe_a (gate/act)
            pltpu.VMEM((MF, TILE, TILE), jnp.float32),  # vmoe_b (up)
            pltpu.VMEM((MH, TILE, TILE), jnp.float32),  # vmoe_o (out acc)
            # vbm: two pipelined chunk slots, plus the reserved WARM slot
            # PREFETCH_MAT streams into (round 9 cross-task overlap) —
            # only when the program can dispatch a warm (no-warm programs
            # keep the 2-slot footprint; a full chunk slot is up to
            # kch_max * MAT_COLS elements of VMEM).
            pltpu.VMEM((m_slots, m_kch, m_cols), wdt),  # vbm (mat chunks)
            pltpu.VMEM((m_rows, m_cols), jnp.float32),  # vaccm (mat accum)
            pltpu.VMEM((m_rows, m_cols), wdt),          # voutm (mat stores)
            # vkv8: the fp8 KV stream's kT/V double-buffer slots (kT in
            # [0, PIPE_DEPTH), V in [PIPE_DEPTH, 2*PIPE_DEPTH)); shrinks
            # to 2 tiles when no fp8-pool handler can dispatch.
            pltpu.VMEM((kv8_slots, TILE, TILE), jnp.float8_e4m3fn),
            pltpu.SemaphoreType.DMA(()),               # copy_sem
            # pipe sems: 2 per pipeline slot, +1 tile-prefetch sem, +1
            # matrix-warm sem (PREFETCH_MAT / warm GEMM_MAT specs).
            pltpu.SemaphoreType.DMA((2 * PIPE_DEPTH + 2,)),  # pipe (+pf sems)
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(_mega_kernel, n, axis, n_tasks, G, W,
                               tuple(mat_specs), kch_max, AR,
                               bool(force_ar),
                               None if used_types is None
                               else tuple(sorted(set(used_types))),
                               int(head_dim))
    if profile:
        base_kernel = kernel

        def kernel(queue_ref, ws_in, ws8_ref, wm_ref, wk8_in_ref, ws_o,
                   slots_o, wk8_o, prof_ref, *scratch):
            _stamp_profile(queue_ref, prof_ref)
            base_kernel(queue_ref, ws_in, ws8_ref, wm_ref, wk8_in_ref,
                        ws_o, slots_o, wk8_o, *scratch)
    interpret = use_interpret()
    if interpret:
        from triton_distributed_tpu.runtime.interpret_workarounds import (
            apply_interpret_workarounds,
        )

        apply_interpret_workarounds()
        from triton_distributed_tpu.language.core import _interpret_params

        interpret_arg = _interpret_params()
    else:
        interpret_arg = False
    params = {}
    if n > 1 or force_ar:
        # force_ar at n == 1 still issues remote (self) DMAs + semaphores
        # and needs the collective id like any cross-device kernel.
        from triton_distributed_tpu.language.core import next_collective_id

        params["collective_id"] = next_collective_id(key=_mega_kernel)
    out_shape = [
        jax.ShapeDtypeStruct((T, TILE, TILE), wdt),
        # AR slots: one max_ar-tile slab per rank (ALLREDUCE_ROW pushes a
        # whole activation row per peer; the single-tile task uses slab 0).
        jax.ShapeDtypeStruct((max(n, 1), AR, TILE, TILE), wdt),
        jax.ShapeDtypeStruct(tuple(workspace_kv8.shape),
                             jnp.float8_e4m3fn),
    ]
    if profile:
        out_shape.append(jax.ShapeDtypeStruct((n_tasks, 128), jnp.int32))
    outs = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=tuple(out_shape),
        compiler_params=pltpu.CompilerParams(has_side_effects=True, **params),
        interpret=interpret_arg,
        # The workspace input IS the output buffer: without the alias the
        # kernel's step-0 staging copy moved the whole multi-GB workspace
        # every step (~140 us at the bench shape — round-5 attribution:
        # the gap between the per-task profile sum and the measured
        # step). Callers in a loop donate the carried workspace and XLA
        # runs the step fully in place; undonated callers get one
        # XLA-level defensive copy instead of an in-kernel one. The fp8
        # KV pool workspace (input 4 → output 2) aliases the same way.
        input_output_aliases={1: 0, 4: 2},
    )(queue, workspace, workspace8, workspace_m, workspace_kv8)
    res = (outs[0], outs[2]) if kv8_present else outs[0]
    if profile:
        prof = outs[3]
        return res + (prof,) if kv8_present else (res, prof)
    return res
