"""The persistent MegaKernel — one Pallas launch runs the whole task queue.

Reference: ``mega_triton_kernel/core/code_generator.py:31-89`` (the generated
``MEGA_TRITON_KERNEL``: each SM loops its queue, decodes TaskBaseInfo, waits
the scoreboard, dispatches on task_type) and ``kernels/task_context.py:92-138``
(scoreboard).

TPU shape: the Pallas grid IS the queue loop — grid step t executes task t
(TPU grid steps run sequentially on the core, giving the in-order queue the
reference builds per SM), the int32 task table rides scalar prefetch into
SMEM, and dispatch is a ``lax.switch`` over task handlers. The scoreboard
collapses: same-core dependencies are enforced by the scheduler's topological
order (sequential execution = implicit scoreboard), and cross-device
dependencies (the AllReduce task) synchronize with DMA semaphores + the
barrier semaphore — the only places the reference's ld_acquire spin loops
have a TPU analog.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import any_spec
from triton_distributed_tpu.megakernel.tasks import TILE, WORDS

PIPE_DEPTH = 4  # outstanding tile-pair loads per task stream
from triton_distributed_tpu.runtime.context import use_interpret


def _mega_kernel(n: int, axis: str, n_tasks: int, max_gqa: int,
                 max_gemm_width: int,
                 queue_ref, ws_in, ws8, ws_out, slots, va2, vb2, vb8, vbw,
                 vbw8, vacc, vq, vstat, vqg, vaccg, vstatg, vaccw,
                 vaccw_wdt, vxn, vmoe_a, vmoe_b, vmoe_o,
                 copy_sem, pipe_sems, send_sems, recv_sem):
    wdt = ws_out.dtype   # workspace dtype (fp32 or bf16); compute is fp32
    step = pl.program_id(0)
    # Double-buffer views: slot 0 is the default for unpipelined tasks.
    va, vb = va2.at[0], vb2.at[0]

    # Step 0: materialize the workspace into the output buffer all tasks
    # read/write (results chain task-to-task within one launch).
    @pl.when(step == 0)
    def _():
        cp = pltpu.make_async_copy(ws_in, ws_out, copy_sem)
        cp.start()
        cp.wait()
        if n > 1:
            shmem.barrier_all(axis)

    def w(j):
        return queue_ref[step, j]

    out, a0, b0 = w(1), w(2), w(3)
    k_tiles, a_stride, b_stride, arg = w(4), w(5), w(6), w(7)
    c0, d0 = w(8), w(9)

    def load(idx, vref):
        cp = pltpu.make_async_copy(ws_out.at[idx], vref, copy_sem)
        cp.start()
        cp.wait()

    def store(vref, idx):
        cp = pltpu.make_async_copy(vref, ws_out.at[idx], copy_sem)
        cp.start()
        cp.wait()

    # Pipelined pair loads: tile streams (a_of(j), b_of(j)) double-buffered
    # so iteration j's MXU work overlaps iteration j+1's DMA — the intra-
    # task analog of ops/tiling.py's emit_pipeline.
    def pipelined_pairs(a_of, b_of, n_iters, body_fn, init):
        # DEPTH tile-pairs in flight: a single-buffer lookahead cannot hide
        # ~2us DMA latency under a 128x128 dot; 3 outstanding pairs can.
        # b_of=None streams only `a` (the body's b_ref is then invalid) —
        # copy/scale/rms-pass1 would otherwise double their HBM reads.
        # (Prefetch-warm consumption lives in t_gemm_wide, the only task
        # the builder pairs with PREFETCH.)
        def desc(idx, vref2, slot, sem_i):
            return pltpu.make_async_copy(ws_out.at[idx], vref2.at[slot],
                                         pipe_sems.at[sem_i])

        def start(j, slot):
            desc(a_of(j), va2, slot, slot * 2).start()
            if b_of is not None:
                desc(b_of(j), vb2, slot, slot * 2 + 1).start()

        def wait(j, slot):
            desc(a_of(j), va2, slot, slot * 2).wait()
            if b_of is not None:
                desc(b_of(j), vb2, slot, slot * 2 + 1).wait()

        for jj in range(PIPE_DEPTH - 1):
            @pl.when(jj < n_iters)
            def _(jj=jj):
                start(jj, jj)

        def body(j, carry):
            slot = jax.lax.rem(j, PIPE_DEPTH)

            @pl.when(j + PIPE_DEPTH - 1 < n_iters)
            def _():
                start(j + PIPE_DEPTH - 1,
                      jax.lax.rem(j + PIPE_DEPTH - 1, PIPE_DEPTH))

            wait(j, slot)
            return body_fn(j, va2.at[slot], vb2.at[slot], carry)

        return jax.lax.fori_loop(0, n_iters, body, init)

    # Elementwise tasks stream a whole tile row (k_tiles tiles) per task,
    # pipelined; unary ops stream a single buffer.
    def _ew_task(fn, binary=True):
        def body(j, a_ref, b_ref, _):
            vq[...] = fn(a_ref[...].astype(jnp.float32),
                         b_ref[...].astype(jnp.float32)).astype(wdt)
            store(vq, out + j)
            return 0

        pipelined_pairs(lambda j: a0 + j,
                        (lambda j: b0 + j) if binary else None,
                        k_tiles, body, 0)

    def t_copy():
        _ew_task(lambda a, b: a, binary=False)

    def t_add():
        _ew_task(lambda a, b: a + b)

    def t_silu_mul():
        _ew_task(lambda a, b: jax.nn.silu(a) * b)

    def t_retired():
        # Queue-ABI placeholder for retired task types (GEMM -> GEMM_WIDE,
        # ROPE -> NORM_ROPE): keeps lax.switch indices stable without
        # compiling a dead body. The builder no longer emits them.
        pass

    def t_prefetch():
        # Fire-and-forget warm of tile a0 into the reserved slot; the
        # consuming GEMM (c0 == 1) waits the semaphore at its j=0.
        pltpu.make_async_copy(ws_out.at[a0], vb2.at[PIPE_DEPTH],
                              pipe_sems.at[2 * PIPE_DEPTH]).start()

    def _gemm_wide_body(b_ws, b_strip):
        # One task computes ``width`` contiguous output column tiles: the A
        # row tiles stream ONCE for the strip and width-1 task dispatches
        # disappear. The strip's B tiles are CONTIGUOUS workspace tiles
        # (b0 + j*b_stride + w), so each k-step fetches the whole
        # (W, TILE, TILE) strip in ONE DMA — the round-4 retraction's
        # diagnosis was ~2000 per-tile fetches per layer-step against a
        # ~55 us streaming roofline, and strip DMAs divide that count by
        # the width. The DMA size is STATIC (full W even for narrower edge
        # strips — compile() pads the workspaces so the overfetch stays in
        # bounds); ``b_strip`` double-buffers over its leading dim (vbw in
        # workspace dtype, vbw8 for GEMM_WIDE_W8 — fp8 tiles upcast at the
        # dot). Per-column fp32 accumulators live in vaccw's leading dim
        # (dynamic leading-dim indexing — lane-dim slicing would not
        # lower).
        width = arg
        vaccw[...] = jnp.zeros_like(vaccw)

        # A PREFETCH warm (c0 == 1) targeted the single-tile reserved slot
        # of the old per-tile stream; the strip fetch re-reads that tile
        # anyway, so just CONSUME the outstanding DMA's semaphore (kernel
        # hygiene: exiting with an unawaited DMA is illegal).
        @pl.when(c0 == 1)
        def _():
            pltpu.make_async_copy(b_ws.at[b0], vb2.at[PIPE_DEPTH]
                                  if b_strip is vbw else vb8.at[PIPE_DEPTH],
                                  pipe_sems.at[2 * PIPE_DEPTH]).wait()

        # Strip pipeline at FULL depth: with only 2 outstanding strips the
        # per-DMA issue/completion latency (~1-2 us) gated every k-step —
        # at 0.3 us of actual strip transfer that latency was the decode
        # GEMMs' real bound (round-5 attribution; the round-4 diagnosis
        # "neither dispatch count nor B granularity" pointed here).
        depth = b_strip.shape[0]

        def sdesc(j, slot):
            return pltpu.make_async_copy(
                b_ws.at[pl.ds(b0 + j * b_stride, b_strip.shape[1])],
                b_strip.at[slot], pipe_sems.at[slot * 2 + 1])

        def adesc(j, slot):
            return pltpu.make_async_copy(ws_out.at[a0 + j * a_stride],
                                         va2.at[slot],
                                         pipe_sems.at[slot * 2])

        for jj in range(PIPE_DEPTH - 1):
            @pl.when(jj < k_tiles)
            def _(jj=jj):
                adesc(jj, jj).start()
                sdesc(jj, jj).start()

        def jbody(j, _):
            slot = jax.lax.rem(j, depth)
            adesc(j, slot).wait()
            sdesc(j, slot).wait()

            def wbody(w, _):
                vaccw[w, :, :] = vaccw[w] + jnp.dot(
                    va2[slot], b_strip[slot, w].astype(va2.dtype),
                    preferred_element_type=jnp.float32)
                return 0

            jax.lax.fori_loop(0, width, wbody, 0)

            @pl.when(j + depth - 1 < k_tiles)
            def _():
                nslot = jax.lax.rem(j + depth - 1, depth)
                adesc(j + depth - 1, nslot).start()
                sdesc(j + depth - 1, nslot).start()

            return 0

        jax.lax.fori_loop(0, k_tiles, jbody, 0)

        # Result stores overlap each other (start all, then drain the
        # byte-counting semaphore) instead of a blocking round-trip per
        # output tile.
        def cast_w(w, _):
            vaccw_wdt[w, :, :] = vaccw[w].astype(wdt)
            return 0

        def store_w(w, _):
            pltpu.make_async_copy(vaccw_wdt.at[w], ws_out.at[out + w],
                                  copy_sem).start()
            return 0

        jax.lax.fori_loop(0, width, cast_w, 0)
        jax.lax.fori_loop(0, width, store_w, 0)

        def drain_w(w, _):
            pltpu.make_async_copy(vaccw_wdt.at[w], ws_out.at[out + w],
                                  copy_sem).wait()
            return 0

        jax.lax.fori_loop(0, width, drain_w, 0)

    def t_gemm_wide():
        _gemm_wide_body(ws_out, vbw)

    def t_gemm_wide_w8():
        _gemm_wide_body(ws8, vbw8)

    def t_prefetch_w8():
        # Fire-and-forget warm of fp8 weight tile a0 into vb8's reserved
        # slot (consumed by the next GEMM_WIDE_W8 with c0 == 1).
        pltpu.make_async_copy(ws8.at[a0], vb8.at[PIPE_DEPTH],
                              pipe_sems.at[2 * PIPE_DEPTH]).start()

    def t_norm_rope():
        # Fused per-head qk-norm + RoPE: one load of the head tile instead
        # of the rms_norm task's two streamed passes plus a separate rope
        # task (head_dim == TILE — the norm reduces over this tile alone).
        load(a0, va)           # head tile (B, d)
        load(b0, vb)           # norm weight (broadcast rows)
        af = va[...].astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        scale_r = jax.lax.rsqrt(
            jnp.mean(af * af, axis=1, keepdims=True) + eps)
        xn = af * scale_r * vb[...].astype(jnp.float32)
        load(c0, vb)           # cos
        load(d0, vq)           # sin
        half = TILE // 2
        rot = jnp.concatenate([-xn[:, half:], xn[:, :half]], axis=1)
        va[...] = (xn * vb[...].astype(jnp.float32)
                   + rot * vq[...].astype(jnp.float32)).astype(wdt)
        store(va, out)

    def t_append_kv():
        # In-kernel KV append (reference appends inside its attn tasks):
        # k_new row 0 -> column c0 of kT cache tile ``out``; v_new row 0 ->
        # row c0 of v cache tile ``b0``. Read-modify-write of the two cache
        # tiles; the scheduler's WAR edges order it after every attention
        # task that read them this step.
        load(a0, vq)           # k_new (B, d)
        load(out, va)          # kT cache tile (d, TILE)
        kcolT = vq[...].astype(jnp.float32).T    # (d, B); col 0 = row 0
        cols = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
        va[...] = jnp.where(cols == c0,
                            jnp.broadcast_to(kcolT[:, 0:1], (TILE, TILE)),
                            va[...].astype(jnp.float32)).astype(wdt)
        store(va, out)
        load(d0, vq)           # v_new (B, d)
        load(b0, va)           # v cache tile (TILE, d)
        rows = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        va[...] = jnp.where(rows == c0,
                            jnp.broadcast_to(vq[0:1, :], (TILE, TILE)),
                            va[...].astype(jnp.float32)).astype(wdt)
        store(va, b0)

    def t_allreduce():
        # One-shot AR of tile ``out`` (reference tasks/allreduce.py, minus
        # multimem): push to every peer's slot ``me``, reduce all slots,
        # exit barrier so slot reuse by the next AR task is race-free.
        if n == 1:
            return
        me = dl.rank(axis)
        src = ws_out.at[out]
        local = pltpu.make_async_copy(src, slots.at[me], copy_sem)
        local.start()
        handles = []
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(shmem.putmem_nbi_block(
                src, slots.at[me], send_sems.at[i], recv_sem, peer, axis))
        local.wait()
        shmem.quiet(*handles)
        shmem.wait_deliveries(src, recv_sem, n - 1)
        vacc[...] = jnp.zeros_like(vacc)
        for r in range(n):
            load_slot = pltpu.make_async_copy(slots.at[r], va, copy_sem)
            load_slot.start()
            load_slot.wait()
            vacc[...] = vacc[...] + va[...].astype(jnp.float32)
        va[...] = vacc[...].astype(wdt)
        store(va, out)
        shmem.barrier_all(axis)

    def t_scale():
        factor = arg.astype(jnp.float32) * 1e-6
        _ew_task(lambda a, b: a * factor, binary=False)

    def t_rms_norm():
        # One task normalizes a whole row block: k_tiles column tiles of x
        # starting at a0, scaled by the weight tiles at b0 (weight stored as
        # a broadcast (TILE, cols) tensor), written to out. eps arrives
        # fixed-point 1e-9 in arg. Reference tasks/rms_norm.py. Both passes
        # stream (x_j, w_j) pairs double-buffered.
        vacc[...] = jnp.zeros_like(vacc)

        def pass1(j, a_ref, _w_ref, _):
            af = a_ref[...].astype(jnp.float32)
            vacc[:, :1] += jnp.sum(af * af, axis=1, keepdims=True)
            return 0

        pipelined_pairs(lambda j: a0 + j, None, k_tiles, pass1, 0)
        cols = (k_tiles * TILE).astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        scale = jax.lax.rsqrt(vacc[:, :1] / cols + eps)

        def pass2(j, a_ref, w_ref, _):
            vq[...] = (a_ref[...].astype(jnp.float32) * scale
                       * w_ref[...].astype(jnp.float32)).astype(wdt)
            store(vq, out + j)
            return 0

        pipelined_pairs(lambda j: a0 + j, lambda j: b0 + j, k_tiles,
                        pass2, 0)

    def _attn_softmax(kt_of, v_of):
        """Shared online-softmax body: streams (kT_j, V_j) tile pairs by the
        given index functions, then folds in the current token (c0/d0)."""
        load(a0, vq)
        scale = arg.astype(jnp.float32) * 1e-6
        valid = b_stride
        neg = jnp.float32(-1e30)
        vacc[...] = jnp.zeros_like(vacc)
        m0 = jnp.full((TILE, 1), neg, jnp.float32)
        l0 = jnp.zeros((TILE, 1), jnp.float32)

        def body(j, kt_ref, v_ref, carry):
            m, l = carry
            s = jnp.dot(vq[...], kt_ref[...],     # KT_j: (d, TILE)
                        preferred_element_type=jnp.float32) * scale
            col = j * TILE + jax.lax.broadcasted_iota(
                jnp.int32, (TILE, TILE), 1)
            s = jnp.where(col < valid, s, neg)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            pv = jnp.dot(p.astype(v_ref.dtype), v_ref[...],  # V_j: (TILE, d)
                         preferred_element_type=jnp.float32)
            vacc[...] = vacc[...] * corr + pv
            return (m_new, l * corr + jnp.sum(p, axis=1, keepdims=True))

        m, l = pipelined_pairs(kt_of, v_of, k_tiles, body, (m0, l0))

        @pl.when(c0 >= 0)
        def _():
            # Current token: per-row dot with each row's own k/v.
            load(c0, vb)                           # k_new: (B, d)
            s_cur = jnp.sum(vq[...].astype(jnp.float32)
                            * vb[...].astype(jnp.float32),
                            axis=1, keepdims=True) * scale
            m_new = jnp.maximum(m, s_cur)
            p_cur = jnp.exp(s_cur - m_new)
            corr = jnp.exp(m - m_new)
            load(d0, vb)                           # v_new: (B, d)
            vacc[...] = vacc[...] * corr + p_cur * vb[...].astype(jnp.float32)
            vstat[:, :1] = l * corr + p_cur

        @pl.when(c0 < 0)
        def _():
            vstat[:, :1] = l

        va[...] = (vacc[...] / jnp.maximum(vstat[:, :1], 1e-30)).astype(wdt)
        store(va, out)

    def t_attn_decode_paged():
        # Page-table walk: the j-th (kT, V) tile pair comes from queue DATA
        # rows starting at row b0 — entry pair j at flat offsets (2j, 2j+1).
        # The table rides scalar prefetch (SMEM), so the DMA addresses are
        # data-dependent exactly like ops/paged_attention.py's table walk.
        def kt_of(j):
            f = 2 * j
            return queue_ref[b0 + f // WORDS, jax.lax.rem(f, WORDS)]

        def v_of(j):
            f = 2 * j + 1
            return queue_ref[b0 + f // WORDS, jax.lax.rem(f, WORDS)]

        _attn_softmax(kt_of, v_of)

    def t_attn_decode():
        # Single-token GQA decode for one q head: online-softmax flash
        # attention over S = k_tiles*TILE cached positions, masked to
        # b_stride valid rows. q: one (TILE, TILE) tile (rows = padded
        # batch, cols = head_dim); KT tiles at b0+j (d, TILE); V tiles at
        # a_stride+j (TILE, d). When c0 >= 0, the current token's k/v tiles
        # (c0/d0, each (B, d), one per batch row) join the softmax rowwise —
        # the cache is appended after the step instead of mutated in-kernel.
        # Reference: tasks/flash_attn.py (paged FA decode task).
        _attn_softmax(lambda j: b0 + j, lambda j: a_stride + j)

    def t_attn_decode_gqa():
        # A whole GQA group in one task: g q-heads (tiles a0..a0+g-1) share
        # the kv head's KT/V stream — tiles stream ONCE for the group and
        # g-1 dispatches vanish. Per-head state lives in the group scratch
        # (vqg/vaccg/vstatg: stats col 0 = m, col 1 = l); statically
        # unrolled over max_gqa with h < g masking.
        g = arg >> 24
        scale = (arg & 0xFFFFFF).astype(jnp.float32) * 1e-6
        valid = b_stride
        neg = jnp.float32(-1e30)
        for h in range(max_gqa):
            @pl.when(h < g)
            def _(h=h):
                load(a0 + h, vqg.at[h])
                vaccg[h, :, :] = jnp.zeros_like(vaccg[h])
                vstatg[h, :, 0:1] = jnp.full((TILE, 1), neg, jnp.float32)
                vstatg[h, :, 1:2] = jnp.zeros((TILE, 1), jnp.float32)

        def body(j, kt_ref, v_ref, _):
            col = j * TILE + jax.lax.broadcasted_iota(
                jnp.int32, (TILE, TILE), 1)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    s = jnp.dot(vqg[h], kt_ref[...],
                                preferred_element_type=jnp.float32) * scale
                    s = jnp.where(col < valid, s, neg)
                    m_prev = vstatg[h, :, 0:1]
                    m_new = jnp.maximum(m_prev,
                                        jnp.max(s, axis=1, keepdims=True))
                    p = jnp.exp(s - m_new)
                    corr = jnp.exp(m_prev - m_new)
                    pv = jnp.dot(p.astype(v_ref.dtype), v_ref[...],
                                 preferred_element_type=jnp.float32)
                    vaccg[h, :, :] = vaccg[h] * corr + pv
                    vstatg[h, :, 0:1] = m_new
                    vstatg[h, :, 1:2] = (vstatg[h, :, 1:2] * corr
                                         + jnp.sum(p, axis=1, keepdims=True))
            return 0

        pipelined_pairs(lambda j: b0 + j, lambda j: a_stride + j,
                        k_tiles, body, 0)

        @pl.when(c0 >= 0)
        def _():
            load(c0, vb)                           # k_new: (B, d)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    s_cur = jnp.sum(vqg[h].astype(jnp.float32)
                                    * vb[...].astype(jnp.float32),
                                    axis=1, keepdims=True) * scale
                    m_prev = vstatg[h, :, 0:1]
                    m_new = jnp.maximum(m_prev, s_cur)
                    p_cur = jnp.exp(s_cur - m_new)
                    corr = jnp.exp(m_prev - m_new)
                    vstatg[h, :, 0:1] = m_new
                    # stash p_cur in stats col 2 for the v_new pass
                    vstatg[h, :, 2:3] = p_cur
                    vstatg[h, :, 1:2] = vstatg[h, :, 1:2] * corr + p_cur
                    vaccg[h, :, :] = vaccg[h] * corr
            load(d0, vb)                           # v_new: (B, d)
            for h in range(max_gqa):
                @pl.when(h < g)
                def _(h=h):
                    vaccg[h, :, :] = (vaccg[h] + vstatg[h, :, 2:3]
                                * vb[...].astype(jnp.float32))

        for h in range(max_gqa):
            @pl.when(h < g)
            def _(h=h):
                va[...] = (vaccg[h] / jnp.maximum(vstatg[h, :, 1:2], 1e-30)
                           ).astype(wdt)
                store(va, out + h)

    def t_moe_topk():
        # Router top-k + softmax over the selected logits (the
        # ops/moe.route_and_sort convention), producing the dense (E, B)
        # TRANSPOSED weight tile MOE_FFN's skip predicate reads. Pure VPU:
        # iterative leftmost-argmax selection, no data-dependent control
        # flow, one transpose at the end.
        load(a0, va)
        lg = va[...].astype(jnp.float32)
        num_e = b_stride
        batch = d0
        colio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)
        rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        neg = jnp.float32(-1e30)
        lg = jnp.where((colio < num_e) & (rowio < batch), lg, neg)
        m0 = jnp.max(lg, axis=1, keepdims=True)

        def body(i, carry):
            work, selmask = carry
            m = jnp.max(work, axis=1, keepdims=True)
            is_m = (work == m) & (work > neg * 0.5)
            idx = jnp.min(jnp.where(is_m, colio, TILE), axis=1,
                          keepdims=True)
            pick = colio == idx
            return jnp.where(pick, neg, work), \
                jnp.where(pick, 1.0, selmask)

        _, selmask = jax.lax.fori_loop(
            0, arg, body, (lg, jnp.zeros((TILE, TILE), jnp.float32)))
        wgt = jnp.where(selmask > 0, jnp.exp(lg - m0), 0.0)
        z = jnp.sum(wgt, axis=1, keepdims=True)
        wgt = wgt / jnp.maximum(z, 1e-30)
        va[...] = wgt.T.astype(wdt)           # (E, B) transposed
        store(va, out)

    def t_moe_ffn():
        # One layer's whole expert MLP: loop experts, SKIP inactive ones
        # before any weight DMA — active experts (≈ B·topk of E) stream
        # gate/up strips per hidden tile and down strips per ffn tile,
        # silu(x@wg)·(x@wu) weighted per token, accumulated into the
        # output row. See tasks.py MOE_FFN for the word layout.
        ht = k_tiles
        num_e = arg & 0xFFFF
        ft = arg >> 16
        wg_base, wu_base, wd_base = a_stride, b_stride, c0
        strip_w = vbw.shape[1]

        load(b0, vq)                           # WT (E, B) weight tile

        def ld_x(j, _):
            cp = pltpu.make_async_copy(ws_out.at[a0 + j], vxn.at[j],
                                       copy_sem)
            cp.start()
            cp.wait()
            vmoe_o[j, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
            return 0

        jax.lax.fori_loop(0, ht, ld_x, 0)
        rowio = jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 0)
        eye = rowio == jax.lax.broadcasted_iota(jnp.int32, (TILE, TILE), 1)

        def ebody(e, _):
            wt = vq[...].astype(jnp.float32)
            w_tok = jnp.sum(jnp.where(rowio == e, wt, 0.0), axis=0)  # (B,)
            active = jnp.sum(w_tok) > 0.0

            @pl.when(active)
            def _():
                # Per-token weight as a column (lane -> sublane via the
                # eye-mask reduction, the flash _col_to_row idiom).
                w_col = jnp.sum(
                    jnp.where(eye, jnp.broadcast_to(w_tok[None, :],
                                                    (TILE, TILE)), 0.0),
                    axis=1, keepdims=True)

                def zf(f, _):
                    vmoe_a[f, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
                    vmoe_b[f, :, :] = jnp.zeros((TILE, TILE), jnp.float32)
                    return 0

                jax.lax.fori_loop(0, ft, zf, 0)

                # Gate/up strips PIPELINED as slot pairs (gate in slot
                # 2p, up in 2p+1; two pairs in flight) — the per-DMA
                # issue latency would otherwise gate every k-step, the
                # exact bound the GEMM_WIDE depth-4 rework removed.
                def gu_desc(j, sp):
                    g = pltpu.make_async_copy(
                        ws_out.at[pl.ds(wg_base + (e * ht + j) * ft,
                                        strip_w)],
                        vbw.at[sp], pipe_sems.at[sp * 2 + 1])
                    u = pltpu.make_async_copy(
                        ws_out.at[pl.ds(wu_base + (e * ht + j) * ft,
                                        strip_w)],
                        vbw.at[sp + 1], pipe_sems.at[sp * 2 + 3])
                    return g, u

                def gu_start(j, sp):
                    g, u = gu_desc(j, sp)
                    g.start()
                    u.start()

                gu_start(0, 0)

                @pl.when(ht > 1)
                def _():
                    gu_start(1, 2)

                def jbody(j, _):
                    sp = jax.lax.rem(j, 2) * 2
                    g, u = gu_desc(j, sp)
                    g.wait()
                    u.wait()
                    a = vxn[j]

                    def fbody(f, _):
                        vmoe_a[f, :, :] = vmoe_a[f] + jnp.dot(
                            a, vbw[sp, f].astype(a.dtype),
                            preferred_element_type=jnp.float32)
                        vmoe_b[f, :, :] = vmoe_b[f] + jnp.dot(
                            a, vbw[sp + 1, f].astype(a.dtype),
                            preferred_element_type=jnp.float32)
                        return 0

                    jax.lax.fori_loop(0, ft, fbody, 0)

                    @pl.when(j + 2 < ht)
                    def _():
                        gu_start(j + 2, sp)

                    return 0

                jax.lax.fori_loop(0, ht, jbody, 0)

                def actf(f, _):
                    vmoe_a[f, :, :] = (jax.nn.silu(vmoe_a[f]) * vmoe_b[f]
                                       * w_col)
                    return 0

                jax.lax.fori_loop(0, ft, actf, 0)

                # Down strips pipelined over all four slots.
                def d_desc(f, slot):
                    return pltpu.make_async_copy(
                        ws_out.at[pl.ds(wd_base + (e * ft + f) * ht,
                                        strip_w)],
                        vbw.at[slot], pipe_sems.at[slot * 2 + 1])

                for ff in range(PIPE_DEPTH - 1):
                    @pl.when(ff < ft)
                    def _(ff=ff):
                        d_desc(ff, ff).start()

                def fdown(f, _):
                    slot = jax.lax.rem(f, PIPE_DEPTH)
                    d_desc(f, slot).wait()
                    af = vmoe_a[f].astype(wdt)

                    def jh(j, _):
                        vmoe_o[j, :, :] = vmoe_o[j] + jnp.dot(
                            af, vbw[slot, j].astype(af.dtype),
                            preferred_element_type=jnp.float32)
                        return 0

                    jax.lax.fori_loop(0, ht, jh, 0)

                    @pl.when(f + PIPE_DEPTH - 1 < ft)
                    def _():
                        d_desc(f + PIPE_DEPTH - 1,
                               jax.lax.rem(f + PIPE_DEPTH - 1,
                                           PIPE_DEPTH)).start()

                    return 0

                jax.lax.fori_loop(0, ft, fdown, 0)

            return 0

        jax.lax.fori_loop(0, num_e, ebody, 0)

        def st(j, _):
            va[...] = vmoe_o[j].astype(wdt)
            store(va, out + j)
            return 0

        jax.lax.fori_loop(0, ht, st, 0)

    jax.lax.switch(w(0), [t_copy, t_add, t_silu_mul, t_retired, t_allreduce,
                          t_scale, t_rms_norm, t_retired, t_attn_decode,
                          t_attn_decode_paged, t_prefetch,
                          t_attn_decode_gqa, t_gemm_wide, t_norm_rope,
                          t_append_kv, t_gemm_wide_w8, t_prefetch_w8,
                          t_moe_topk, t_moe_ffn])


def run_queue(queue, workspace, *, num_ranks: int = 1, axis: str = "tp",
              num_tasks: int | None = None, max_gqa: int = 1,
              max_gemm_width: int = 1, workspace8=None,
              max_moe_h: int = 0, max_moe_f: int = 0):
    """Execute the packed task queue over the workspace in ONE pallas_call.

    queue: (n_rows, WORDS) int32; workspace: (T, TILE, TILE) fp32 or bf16
    (local per device when num_ranks > 1 — call inside shard_map). bf16
    halves every tile DMA; compute stays fp32 on the VPU/MXU.
    CONTRACT: T must include max_gemm_width-1 PAD tiles past the last
    real tile (GEMM_WIDE fetches static full-width B strips; narrower
    edge strips overfetch into the pad) — CompiledMegaKernel.make_workspace
    adds the pad; raw callers must too.
    ``num_tasks``: dispatched rows (default all) — rows beyond are DATA
    (ATTN_DECODE_PAGED page tables) the grid never visits.
    ``max_gqa``: largest ATTN_DECODE_GQA group in the queue (sizes the
    per-head group scratch; 1 when unused).
    ``max_gemm_width``: widest GEMM_WIDE strip (sizes the per-column
    accumulator scratch; 1 when unused).
    ``workspace8``: optional (T8, TILE, TILE) float8_e4m3fn READ-ONLY
    weight workspace (GEMM_WIDE_W8 / PREFETCH_W8 B-tile source — half the
    weight-streaming bytes of bf16).
    Returns the post-execution workspace.
    """
    n_tasks = num_tasks if num_tasks is not None else queue.shape[0]
    assert queue.shape[1] == WORDS
    n = num_ranks
    T = workspace.shape[0]
    wdt = workspace.dtype
    G = max(max_gqa, 1)
    # MoE strips share the GEMM_WIDE strip buffer: it must span the wider
    # of the ffn strips (gate/up, max_moe_f tiles) and the hidden strips
    # (down, max_moe_h tiles). ``max_moe_*=0`` = program has no MoE.
    MH = max(max_moe_h, 1)
    MF = max(max_moe_f, 1)
    W = max(max_gemm_width, max_moe_h, max_moe_f, 1)
    w8_absent = workspace8 is None
    if workspace8 is None:
        workspace8 = jnp.zeros((1, TILE, TILE), jnp.float8_e4m3fn)
    if workspace8.shape[0] < W + 1:
        # The compiled GEMM_WIDE_W8 branch statically slices W-tile strips
        # (and exists in the switch even for programs that never dispatch
        # it) — an undersized placeholder must pad so the slice bound
        # checks out.
        workspace8 = jnp.pad(
            workspace8, ((0, W + 1 - workspace8.shape[0]), (0, 0), (0, 0)))

    # AR slots ride as a second output: Mosaic has no HBM scratch (see
    # language/core.py kernel_call ``workspaces``).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tasks,),
        in_specs=[any_spec(), any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.VMEM((PIPE_DEPTH, TILE, TILE), wdt),      # va2
            pltpu.VMEM((PIPE_DEPTH + 1, TILE, TILE), wdt),  # vb2 (+pf slot)
            pltpu.VMEM((PIPE_DEPTH + 1, TILE, TILE),
                       jnp.float8_e4m3fn),                  # vb8 (+pf slot)
            pltpu.VMEM((PIPE_DEPTH, W, TILE, TILE), wdt),   # vbw (B strips)
            # fp8 strip buffer shrinks to 1 tile when the program has no
            # fp8 workspace (the W8 branch still compiles; it adapts via
            # b_strip.shape[1]) — ~0.5 MB of VMEM saved at W=8.
            pltpu.VMEM((PIPE_DEPTH, W if not w8_absent else 1, TILE, TILE),
                       jnp.float8_e4m3fn),                  # vbw8
            pltpu.VMEM((TILE, TILE), jnp.float32),      # vacc (fp32 accum)
            pltpu.VMEM((TILE, TILE), wdt),              # vq: rope/attn operand
            pltpu.VMEM((TILE, 128), jnp.float32),       # vstat (softmax stats)
            pltpu.VMEM((G, TILE, TILE), wdt),           # vqg (group q tiles)
            pltpu.VMEM((G, TILE, TILE), jnp.float32),   # vaccg
            pltpu.VMEM((G, TILE, 128), jnp.float32),    # vstatg
            pltpu.VMEM((W, TILE, TILE), jnp.float32),   # vaccw (wide GEMM)
            pltpu.VMEM((W, TILE, TILE), wdt),           # vaccw_wdt (stores)
            pltpu.VMEM((MH, TILE, TILE), wdt),          # vxn (MoE x row)
            pltpu.VMEM((MF, TILE, TILE), jnp.float32),  # vmoe_a (gate/act)
            pltpu.VMEM((MF, TILE, TILE), jnp.float32),  # vmoe_b (up)
            pltpu.VMEM((MH, TILE, TILE), jnp.float32),  # vmoe_o (out acc)
            pltpu.SemaphoreType.DMA(()),               # copy_sem
            pltpu.SemaphoreType.DMA((2 * PIPE_DEPTH + 1,)),  # pipe (+pf sem)
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(_mega_kernel, n, axis, n_tasks, G, W)
    interpret = use_interpret()
    if interpret:
        from triton_distributed_tpu.runtime.interpret_workarounds import (
            apply_interpret_workarounds,
        )

        apply_interpret_workarounds()
        from triton_distributed_tpu.language.core import _interpret_params

        interpret_arg = _interpret_params()
    else:
        interpret_arg = False
    params = {}
    if n > 1:
        from triton_distributed_tpu.language.core import next_collective_id

        params["collective_id"] = next_collective_id(key=_mega_kernel)
    ws_out, _slots = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((T, TILE, TILE), wdt),
            jax.ShapeDtypeStruct((max(n, 1), TILE, TILE), wdt),
        ),
        compiler_params=pltpu.CompilerParams(has_side_effects=True, **params),
        interpret=interpret_arg,
    )(queue, workspace, workspace8)
    return ws_out
