"""The persistent MegaKernel — one Pallas launch runs the whole task queue.

Reference: ``mega_triton_kernel/core/code_generator.py:31-89`` (the generated
``MEGA_TRITON_KERNEL``: each SM loops its queue, decodes TaskBaseInfo, waits
the scoreboard, dispatches on task_type) and ``kernels/task_context.py:92-138``
(scoreboard).

TPU shape: the Pallas grid IS the queue loop — grid step t executes task t
(TPU grid steps run sequentially on the core, giving the in-order queue the
reference builds per SM), the int32 task table rides scalar prefetch into
SMEM, and dispatch is a ``lax.switch`` over task handlers. The scoreboard
collapses: same-core dependencies are enforced by the scheduler's topological
order (sequential execution = implicit scoreboard), and cross-device
dependencies (the AllReduce task) synchronize with DMA semaphores + the
barrier semaphore — the only places the reference's ld_acquire spin loops
have a TPU analog.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.megakernel.tasks import TILE, WORDS
from triton_distributed_tpu.runtime.context import use_interpret


def _mega_kernel(n: int, axis: str, n_tasks: int,
                 queue_ref, ws_in, ws_out, slots, va, vb, vacc, vq,
                 copy_sem, send_sems, recv_sem):
    step = pl.program_id(0)

    # Step 0: materialize the workspace into the output buffer all tasks
    # read/write (results chain task-to-task within one launch).
    @pl.when(step == 0)
    def _():
        cp = pltpu.make_async_copy(ws_in, ws_out, copy_sem)
        cp.start()
        cp.wait()
        if n > 1:
            shmem.barrier_all(axis)

    def w(j):
        return queue_ref[step, j]

    out, a0, b0 = w(1), w(2), w(3)
    k_tiles, a_stride, b_stride, arg = w(4), w(5), w(6), w(7)
    c0, d0 = w(8), w(9)

    def load(idx, vref):
        cp = pltpu.make_async_copy(ws_out.at[idx], vref, copy_sem)
        cp.start()
        cp.wait()

    def store(vref, idx):
        cp = pltpu.make_async_copy(vref, ws_out.at[idx], copy_sem)
        cp.start()
        cp.wait()

    def t_copy():
        load(a0, va)
        store(va, out)

    def t_add():
        load(a0, va)
        load(b0, vb)
        va[...] = va[...] + vb[...]
        store(va, out)

    def t_silu_mul():
        load(a0, va)
        load(b0, vb)
        va[...] = jax.nn.silu(va[...]) * vb[...]
        store(va, out)

    def t_gemm():
        vacc[...] = jnp.zeros_like(vacc)

        def body(j, _):
            load(a0 + j * a_stride, va)
            load(b0 + j * b_stride, vb)
            vacc[...] = vacc[...] + jnp.dot(
                va[...], vb[...], preferred_element_type=jnp.float32)
            return 0

        jax.lax.fori_loop(0, k_tiles, body, 0)
        va[...] = vacc[...]
        store(va, out)

    def t_allreduce():
        # One-shot AR of tile ``out`` (reference tasks/allreduce.py, minus
        # multimem): push to every peer's slot ``me``, reduce all slots,
        # exit barrier so slot reuse by the next AR task is race-free.
        if n == 1:
            return
        me = dl.rank(axis)
        src = ws_out.at[out]
        local = pltpu.make_async_copy(src, slots.at[me], copy_sem)
        local.start()
        handles = []
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(shmem.putmem_nbi_block(
                src, slots.at[me], send_sems.at[i], recv_sem, peer, axis))
        local.wait()
        shmem.quiet(*handles)
        shmem.wait_deliveries(src, recv_sem, n - 1)
        vacc[...] = jnp.zeros_like(vacc)
        for r in range(n):
            load_slot = pltpu.make_async_copy(slots.at[r], va, copy_sem)
            load_slot.start()
            load_slot.wait()
            vacc[...] = vacc[...] + va[...]
        va[...] = vacc[...]
        store(va, out)
        shmem.barrier_all(axis)

    def t_scale():
        load(a0, va)
        va[...] = va[...] * (arg.astype(jnp.float32) * 1e-6)
        store(va, out)

    def t_rms_norm():
        # One task normalizes a whole row block: k_tiles column tiles of x
        # starting at a0, scaled by the weight tiles at b0 (weight stored as
        # a broadcast (TILE, cols) tensor), written to out.. . eps arrives
        # fixed-point 1e-9 in arg. Reference tasks/rms_norm.py.
        vacc[...] = jnp.zeros_like(vacc)

        def pass1(j, _):
            load(a0 + j, va)
            vacc[:, :1] += jnp.sum(va[...] * va[...], axis=1, keepdims=True)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass1, 0)
        cols = (k_tiles * TILE).astype(jnp.float32)
        eps = arg.astype(jnp.float32) * 1e-9
        scale = jax.lax.rsqrt(vacc[:, :1] / cols + eps)

        def pass2(j, _):
            load(a0 + j, va)
            load(b0 + j, vb)
            va[...] = va[...] * scale * vb[...]
            store(va, out + j)
            return 0

        jax.lax.fori_loop(0, k_tiles, pass2, 0)

    def t_rope():
        # HF half-split rotation: out = a*cos + rotate_half(a)*sin with
        # rotate_half(a) = concat(-a2, a1). cos/sin are full-width tables
        # (each half repeated), prepared host-side. Reference: the qk-norm+
        # rope task (mega_triton_kernel tasks).
        load(a0, va)
        load(b0, vb)    # cos
        load(arg, vq)   # sin
        half = TILE // 2
        a1, a2 = va[:, :half], va[:, half:]
        rot = jnp.concatenate([-a2, a1], axis=1)
        va[...] = va[...] * vb[...] + rot * vq[...]
        store(va, out)

    def t_attn_decode():
        # Single-token GQA decode for one q head: online-softmax flash
        # attention over S = k_tiles*TILE cached positions, masked to
        # b_stride valid rows. q: one (TILE, TILE) tile (rows = padded
        # batch, cols = head_dim); KT tiles at b0+j (d, TILE); V tiles at
        # a_stride+j (TILE, d). When c0 >= 0, the current token's k/v tiles
        # (c0/d0, each (B, d), one per batch row) join the softmax rowwise —
        # the cache is appended after the step instead of mutated in-kernel.
        # Reference: tasks/flash_attn.py (paged FA decode task).
        load(a0, vq)
        scale = arg.astype(jnp.float32) * 1e-6
        valid = b_stride
        neg = jnp.float32(-1e30)
        vacc[...] = jnp.zeros_like(vacc)
        m0 = jnp.full((TILE, 1), neg, jnp.float32)
        l0 = jnp.zeros((TILE, 1), jnp.float32)

        def body(j, carry):
            m, l = carry
            load(b0 + j, vb)                       # KT_j: (d, TILE)
            s = jnp.dot(vq[...], vb[...],
                        preferred_element_type=jnp.float32) * scale
            col = j * TILE + jax.lax.broadcasted_iota(
                jnp.int32, (TILE, TILE), 1)
            s = jnp.where(col < valid, s, neg)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            corr = jnp.exp(m - m_new)
            load(a_stride + j, vb)                 # V_j: (TILE, d)
            pv = jnp.dot(p.astype(jnp.float32), vb[...],
                         preferred_element_type=jnp.float32)
            vacc[...] = vacc[...] * corr + pv
            return (m_new, l * corr + jnp.sum(p, axis=1, keepdims=True))

        m, l = jax.lax.fori_loop(0, k_tiles, body, (m0, l0))

        @pl.when(c0 >= 0)
        def _():
            # Current token: per-row dot with each row's own k/v.
            load(c0, vb)                           # k_new: (B, d)
            s_cur = jnp.sum(vq[...] * vb[...], axis=1, keepdims=True) * scale
            m_new = jnp.maximum(m, s_cur)
            p_cur = jnp.exp(s_cur - m_new)
            corr = jnp.exp(m - m_new)
            load(d0, vb)                           # v_new: (B, d)
            vacc[...] = vacc[...] * corr + p_cur * vb[...]
            va[:, :1] = l * corr + p_cur

        @pl.when(c0 < 0)
        def _():
            va[:, :1] = l

        va[...] = vacc[...] / jnp.maximum(va[:, :1], 1e-30)
        store(va, out)

    jax.lax.switch(w(0), [t_copy, t_add, t_silu_mul, t_gemm, t_allreduce,
                          t_scale, t_rms_norm, t_rope, t_attn_decode])


def run_queue(queue, workspace, *, num_ranks: int = 1, axis: str = "tp"):
    """Execute the packed task queue over the workspace in ONE pallas_call.

    queue: (n_tasks, WORDS) int32; workspace: (T, TILE, TILE) fp32 (local
    per device when num_ranks > 1 — call inside shard_map).
    Returns the post-execution workspace.
    """
    n_tasks = queue.shape[0]
    assert queue.shape[1] == WORDS
    n = num_ranks
    T = workspace.shape[0]

    # AR slots ride as a second output: Mosaic has no HBM scratch (see
    # language/core.py kernel_call ``workspaces``).
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tasks,),
        in_specs=[any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.VMEM((TILE, TILE), jnp.float32),
            pltpu.VMEM((TILE, TILE), jnp.float32),
            pltpu.VMEM((TILE, TILE), jnp.float32),
            pltpu.VMEM((TILE, TILE), jnp.float32),   # vq: rope/attn operand
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    kernel = functools.partial(_mega_kernel, n, axis, n_tasks)
    interpret = use_interpret()
    if interpret:
        from triton_distributed_tpu.runtime.interpret_workarounds import (
            apply_interpret_workarounds,
        )

        apply_interpret_workarounds()
        from triton_distributed_tpu.language.core import _interpret_params

        interpret_arg = _interpret_params()
    else:
        interpret_arg = False
    params = {}
    if n > 1:
        from triton_distributed_tpu.language.core import next_collective_id

        params["collective_id"] = next_collective_id(key=_mega_kernel)
    ws_out, _slots = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((T, TILE, TILE), jnp.float32),
            jax.ShapeDtypeStruct((max(n, 1), TILE, TILE), jnp.float32),
        ),
        compiler_params=pltpu.CompilerParams(has_side_effects=True, **params),
        interpret=interpret_arg,
    )(queue, workspace)
    return ws_out
