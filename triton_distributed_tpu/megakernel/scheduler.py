"""MegaKernel scheduler — native (C++) task-graph ordering with ctypes.

Reference: ``mega_triton_kernel/core/scheduler.py:40-95`` (queue
construction) — here the ordering itself is the native component
(native/scheduler.cc), compiled on first use with the toolchain's g++ and
cached; a pure-Python Kahn fallback keeps toolchain-free environments
working.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from triton_distributed_tpu.runtime.native import load_native_lib

_SRC = os.path.join(os.path.dirname(__file__), "native", "scheduler.cc")
_lib = None
_lib_loaded = False


def _load_native():
    """Compile + load the C++ scheduler (shared build/load helper)."""
    global _lib, _lib_loaded
    if _lib_loaded:
        return _lib
    _lib_loaded = True
    lib = load_native_lib(_SRC, "scheduler")
    if lib is not None:
        lib.topo_schedule.restype = ctypes.c_int32
        lib.topo_schedule.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return _lib


def topo_schedule(n_tasks: int, edges: list[tuple[int, int]]) -> list[int]:
    """Dependency-respecting execution order (smallest-index-first Kahn).

    Raises ValueError on a dependency cycle.
    """
    lib = _load_native()
    if lib is not None:
        src = np.asarray([e[0] for e in edges], np.int32)
        dst = np.asarray([e[1] for e in edges], np.int32)
        out = np.zeros((n_tasks,), np.int32)
        rc = lib.topo_schedule(
            np.int32(n_tasks), np.int32(len(edges)),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc == 0:
            return out.tolist()
        if rc == -1:
            raise ValueError("task graph has a dependency cycle")
        raise ValueError(f"native scheduler rejected the graph (rc={rc})")
    return _topo_python(n_tasks, edges)


def using_native_scheduler() -> bool:
    return _load_native() is not None


def _topo_python(n_tasks: int, edges: list[tuple[int, int]]) -> list[int]:
    """Fallback Kahn (same order contract as the native path)."""
    import heapq

    succ: list[list[int]] = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        t = heapq.heappop(ready)
        order.append(t)
        for d in succ[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                heapq.heappush(ready, d)
    if len(order) != n_tasks:
        raise ValueError("task graph has a dependency cycle")
    return order
