"""MegaKernel scheduler — native (C++) task-graph ordering with ctypes.

Reference: ``mega_triton_kernel/core/scheduler.py:40-95`` (queue
construction) — here the ordering itself is the native component
(native/scheduler.cc), compiled on first use with the toolchain's g++ and
cached; a pure-Python Kahn fallback keeps toolchain-free environments
working.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from triton_distributed_tpu.runtime.native import load_native_lib

_SRC = os.path.join(os.path.dirname(__file__), "native", "scheduler.cc")
_lib = None
_lib_loaded = False


class ScheduleCycleError(ValueError):
    """Task graph has a dependency cycle; ``cycle`` lists the member task ids.

    ``task_types`` (when the caller supplied them) annotates each member with
    its TaskType name so the diagnostic reads ``12:GEMM_WIDE -> 10:PREFETCH``.
    """

    def __init__(self, cycle: list[int], task_types=None):
        self.cycle = list(cycle)
        if task_types is not None:
            names = []
            for t in self.cycle:
                ty = task_types[t]
                label = getattr(ty, "name", None) or str(ty)
                names.append(f"{t}:{label}")
        else:
            names = [str(t) for t in self.cycle]
        super().__init__(
            "task graph has a dependency cycle: " + " -> ".join(names + names[:1]))


def _find_cycle(n_tasks: int, edges: list[tuple[int, int]]) -> list[int]:
    """Return the task ids of one actual cycle (graph is known cyclic)."""
    succ: list[list[int]] = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    # Peel acyclic fringe; what remains all sits on/feeds cycles.
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    while ready:
        t = ready.pop()
        for d in succ[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                ready.append(d)
    remaining = {i for i in range(n_tasks) if indeg[i] > 0}
    if not remaining:
        return []
    # Walk successors inside the remainder until a node repeats.
    start = min(remaining)
    seen: dict[int, int] = {}
    path: list[int] = []
    node = start
    while node not in seen:
        seen[node] = len(path)
        path.append(node)
        node = next(d for d in succ[node] if d in remaining)
    return path[seen[node]:]


def _load_native():
    """Compile + load the C++ scheduler (shared build/load helper)."""
    global _lib, _lib_loaded
    if _lib_loaded:
        return _lib
    _lib_loaded = True
    lib = load_native_lib(_SRC, "scheduler")
    if lib is not None:
        lib.topo_schedule.restype = ctypes.c_int32
        lib.topo_schedule.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
    _lib = lib
    return _lib


def topo_schedule(
        n_tasks: int, edges: list[tuple[int, int]],
        task_types=None) -> list[int]:
    """Dependency-respecting execution order (smallest-index-first Kahn).

    Raises :class:`ScheduleCycleError` on a dependency cycle, naming the
    member task ids (and types, when ``task_types`` is given).
    """
    lib = _load_native()
    if lib is not None:
        src = np.asarray([e[0] for e in edges], np.int32)
        dst = np.asarray([e[1] for e in edges], np.int32)
        out = np.zeros((n_tasks,), np.int32)
        rc = lib.topo_schedule(
            np.int32(n_tasks), np.int32(len(edges)),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc == 0:
            return out.tolist()
        if rc == -1:
            raise ScheduleCycleError(_find_cycle(n_tasks, edges), task_types)
        raise ValueError(f"native scheduler rejected the graph (rc={rc})")
    return _topo_python(n_tasks, edges, task_types)


def using_native_scheduler() -> bool:
    return _load_native() is not None


def _topo_python(
        n_tasks: int, edges: list[tuple[int, int]],
        task_types=None) -> list[int]:
    """Fallback Kahn (same order contract as the native path)."""
    import heapq

    succ: list[list[int]] = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        t = heapq.heappop(ready)
        order.append(t)
        for d in succ[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                heapq.heappush(ready, d)
    if len(order) != n_tasks:
        raise ScheduleCycleError(_find_cycle(n_tasks, edges), task_types)
    return order
