"""MegaKernel scheduler — native (C++) task-graph ordering with ctypes.

Reference: ``mega_triton_kernel/core/scheduler.py:40-95`` (queue
construction) — here the ordering itself is the native component
(native/scheduler.cc), compiled on first use with the toolchain's g++ and
cached; a pure-Python Kahn fallback keeps toolchain-free environments
working.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "native", "scheduler.cc")
_lib = None
_lib_failed = False


def _cache_dir() -> str:
    d = os.environ.get(
        "TDTPU_NATIVE_CACHE",
        os.path.expanduser("~/.cache/triton_distributed_tpu/native"))
    os.makedirs(d, exist_ok=True)
    return d


def _load_native():
    """Compile + load the C++ scheduler (cached by source hash)."""
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    try:
        with open(_SRC, "rb") as f:
            src = f.read()
        tag = hashlib.sha256(src).hexdigest()[:16]
        so_path = os.path.join(_cache_dir(), f"scheduler_{tag}.so")
        if not os.path.exists(so_path):
            with tempfile.TemporaryDirectory() as td:
                tmp = os.path.join(td, "scheduler.so")
                subprocess.run(
                    ["g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                     _SRC, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, so_path)
        lib = ctypes.CDLL(so_path)
        lib.topo_schedule.restype = ctypes.c_int32
        lib.topo_schedule.argtypes = [
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32)]
        _lib = lib
    except Exception:
        _lib_failed = True
        _lib = None
    return _lib


def topo_schedule(n_tasks: int, edges: list[tuple[int, int]]) -> list[int]:
    """Dependency-respecting execution order (smallest-index-first Kahn).

    Raises ValueError on a dependency cycle.
    """
    lib = _load_native()
    if lib is not None:
        src = np.asarray([e[0] for e in edges], np.int32)
        dst = np.asarray([e[1] for e in edges], np.int32)
        out = np.zeros((n_tasks,), np.int32)
        rc = lib.topo_schedule(
            np.int32(n_tasks), np.int32(len(edges)),
            src.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            dst.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc == 0:
            return out.tolist()
        if rc == -1:
            raise ValueError("task graph has a dependency cycle")
        raise ValueError(f"native scheduler rejected the graph (rc={rc})")
    return _topo_python(n_tasks, edges)


def using_native_scheduler() -> bool:
    return _load_native() is not None


def _topo_python(n_tasks: int, edges: list[tuple[int, int]]) -> list[int]:
    """Fallback Kahn (same order contract as the native path)."""
    import heapq

    succ: list[list[int]] = [[] for _ in range(n_tasks)]
    indeg = [0] * n_tasks
    for s, d in edges:
        succ[s].append(d)
        indeg[d] += 1
    ready = [i for i in range(n_tasks) if indeg[i] == 0]
    heapq.heapify(ready)
    order = []
    while ready:
        t = heapq.heappop(ready)
        order.append(t)
        for d in succ[t]:
            indeg[d] -= 1
            if indeg[d] == 0:
                heapq.heappush(ready, d)
    if len(order) != n_tasks:
        raise ValueError("task graph has a dependency cycle")
    return order
