"""MegaKernel model builder — record ops as tasks, compile once, replay.

Reference: ``mega_triton_kernel/models/model_builder.py:83-406``
(``ModelBuilder.make_qkv_proj/make_attn/…/make_allreduce`` record tasks with
dependencies; ``compile()`` generates the kernel + queues; ``run()`` replays
the persistent kernel).

Usage:
    mb = MegaKernelBuilder()
    x = mb.tensor(128, 256)           # handles into the tiled workspace
    w = mb.tensor(256, 256)
    y = mb.tensor(128, 256)
    mb.gemm(y, x, w)
    mb.all_reduce(y)                  # cross-device task (TP partial sums)
    prog = mb.compile(num_ranks=8)
    outs = prog.run({x: ax, w: aw}, outputs=[y])   # ONE kernel launch
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.megakernel.kernel import run_queue
from triton_distributed_tpu.megakernel.scheduler import topo_schedule
from triton_distributed_tpu.megakernel.tasks import (
    MAT_COLS, TILE, WORDS, MatHandle, MatSpec, Task, TaskType, TensorHandle,
    mat_chunk_rows,
)


class MegaKernelBuilder:
    """Records tensors + tasks; tracks read/write hazards for the scheduler
    (the role of the reference's TaskDependency records,
    core/task_base.py:112-218)."""

    # Hazard-id offset for fp8 weight-workspace tiles: their tile ids live
    # in a separate space, so dependency bookkeeping must not collide them
    # with main-workspace ids.
    _W8_HAZARD = 1 << 30
    # Same for 2D matrix-workspace rows (GEMM_MAT B operands).
    _WM_HAZARD = 1 << 29
    # And for fp8 KV-POOL tiles (ATTN_DECODE_PAGED_F8 / APPEND_KV_F8 —
    # the read-write fp8 pool space): their WAR/WAW edges must order
    # appends after attention reads exactly like main-workspace pools.
    _K8_HAZARD = 1 << 28

    def __init__(self):
        # NORM_ROPE(_QKV) sub-tile span: the program ASSEMBLY sets this
        # (build_decode_step(head_dim=)) so compile() cannot silently
        # mismatch it — a 64-head program compiled at TILE would norm
        # over the zero pad (scale off by sqrt(2)) and rotate the wrong
        # half, wrong tokens with no error.
        self.head_dim = TILE
        self._num_tiles = 0
        self._num_tiles8 = 0
        self._num_tiles_kv8 = 0
        self._num_mrows = 0
        self._mat_specs: list[MatSpec] = []
        self._tasks: list[Task] = []
        self._edges: list[tuple[int, int]] = []
        self._last_writer: dict[int, int] = {}
        self._readers_since_write: dict[int, list[int]] = {}
        # Per-task hazard sets (tile ids in the _W8/_WM/_K8-offset spaces),
        # emission order — exported on the compiled artifact so mklint can
        # re-derive RAW/WAW/WAR independently of the edge list.
        self._reads: list[tuple[int, ...]] = []
        self._writes: list[tuple[int, ...]] = []
        # task id -> flat int list; packed as extra queue rows at compile
        # (page tables for ATTN_DECODE_PAGED — data rows, never dispatched).
        self._task_tables: dict[int, list[int]] = {}
        # Prefetch hand-off: pseudo-resource tile serializing the single
        # reserved slot, and the tile id the pending prefetch warmed.
        self._pf_res: TensorHandle | None = None
        self._pending_pf: int | None = None
        # Matrix-chunk warm hand-off (PREFETCH_MAT, round 9): the pseudo
        # resource serializing the reserved vbm slot, and (task id, wsm
        # base) of the outstanding warm awaiting its consuming GEMM_MAT.
        self._pfm_res: TensorHandle | None = None
        self._pending_pf_mat: tuple[int, int] | None = None

    # -- tensors ------------------------------------------------------------
    def tensor(self, rows: int, cols: int, fp8: bool = False,
               kv8: bool = False) -> TensorHandle:
        """``fp8=True``: allocate in the float8_e4m3fn WEIGHT workspace (a
        separate read-only input — GEMM B operands only; half the
        weight-streaming bytes of bf16). ``kv8=True``: allocate in the
        float8_e4m3fn KV-POOL workspace (read-WRITE, aliased through the
        step) — paged KV pools at half the bytes; ATTN_DECODE_PAGED_F8
        reads it, APPEND_KV_F8 writes it."""
        if rows % TILE or cols % TILE:
            raise ValueError(f"dims must be multiples of {TILE}, got "
                             f"({rows}, {cols})")
        if fp8 and kv8:
            raise ValueError("fp8 (weight) and kv8 (KV pool) are distinct "
                             "workspaces — pick one")
        if fp8:
            h = TensorHandle(self._num_tiles8, rows, cols, fp8=True)
            self._num_tiles8 += h.rt * h.ct
            return h
        if kv8:
            h = TensorHandle(self._num_tiles_kv8, rows, cols, kv8=True)
            self._num_tiles_kv8 += h.rt * h.ct
            return h
        h = TensorHandle(self._num_tiles, rows, cols)
        self._num_tiles += h.rt * h.ct
        return h

    def tensor_mat(self, k: int, n: int, pair: bool = False) -> MatHandle:
        """A (k, n) weight matrix in the 2D MATRIX workspace (GEMM_MAT B
        operand; ``pair=True`` = interleaved gate|up layout, n per half —
        see tasks.py MatHandle)."""
        if k % TILE or n % TILE:
            raise ValueError(f"dims must be multiples of {TILE}, got "
                             f"({k}, {n})")
        mat_chunk_rows(k)   # raises early on an unchunkable K
        h = MatHandle(self._num_mrows, k, n, pair=pair)
        self._num_mrows += h.rows
        return h

    @staticmethod
    def _no_fp8(*handles):
        """fp8-space handles are GEMM B operands only (and kv8 pool
        handles are paged-attention/append operands only): their tile ids
        live in separate spaces starting at 0, so any other op encoding
        them would silently alias main-workspace tiles (data AND
        hazards)."""
        for h in handles:
            if h is not None and getattr(h, "fp8", False):
                raise ValueError(
                    "fp8 weight-workspace tensors can only be GEMM B "
                    "operands (GEMM_WIDE_W8) — other tasks address the "
                    "main workspace")
            if h is not None and getattr(h, "kv8", False):
                raise ValueError(
                    "kv8 pool-workspace tensors can only be paged KV "
                    "pools (ATTN_DECODE_PAGED_F8 / APPEND_KV_F8) — other "
                    "tasks address the main workspace")

    # -- dependency bookkeeping --------------------------------------------
    def _emit(self, task: Task, reads: list[int], writes: list[int]) -> int:
        tid = len(self._tasks)
        for t in reads:
            w = self._last_writer.get(t)
            if w is not None:
                self._edges.append((w, tid))          # RAW
            self._readers_since_write.setdefault(t, []).append(tid)
        for t in writes:
            w = self._last_writer.get(t)
            if w is not None:
                self._edges.append((w, tid))          # WAW
            for r in self._readers_since_write.get(t, []):
                if r != tid:
                    self._edges.append((r, tid))      # WAR
            self._last_writer[t] = tid
            self._readers_since_write[t] = []
        self._tasks.append(task)
        self._reads.append(tuple(reads))
        self._writes.append(tuple(writes))
        return tid

    # -- ops ----------------------------------------------------------------
    def copy(self, out: TensorHandle, a: TensorHandle):
        self._ew(TaskType.COPY, out, a)

    def add(self, out: TensorHandle, a: TensorHandle, b: TensorHandle):
        self._ew(TaskType.ADD, out, a, b)

    def silu_mul(self, out: TensorHandle, gate: TensorHandle,
                 up: TensorHandle):
        self._ew(TaskType.SILU_MUL, out, gate, up)

    def scale(self, out: TensorHandle, a: TensorHandle, factor: float):
        self._ew(TaskType.SCALE, out, a, arg=int(round(factor * 1e6)))

    def _ew(self, tt: TaskType, out, a, b=None, arg: int = 0):
        """One task per ROW of tiles (k_tiles = ct): the kernel streams the
        row's tiles double-buffered, so wide elementwise ops cost one task's
        dispatch instead of ct (the per-tile version serialized ~3 DMA
        round-trips per tile)."""
        self._no_fp8(out, a, b)
        if (out.rt, out.ct) != (a.rt, a.ct) or (b and (b.rt, b.ct) != (a.rt, a.ct)):
            raise ValueError("elementwise shape mismatch")
        for i in range(out.rt):
            reads = [a.tile(i, j) for j in range(a.ct)]
            if b:
                reads += [b.tile(i, j) for j in range(a.ct)]
            self._emit(Task(tt, out.tile(i, 0), a0=a.tile(i, 0),
                            b0=b.tile(i, 0) if b else a.tile(i, 0),
                            k_tiles=a.ct, arg=arg),
                       reads, [out.tile(i, j) for j in range(out.ct)])
            self._max_row = max(getattr(self, "_max_row", 1), a.ct)

    def prefetch(self, weight_tile: int, fp8: bool = False):
        """Start warming ``weight_tile`` into the reserved pipeline slot
        (reference: the weight-prefetch task, SURVEY.md §2.7). The next
        ``gemm(..., prefetch_first=True)`` whose first weight tile equals it
        consumes the warm copy for its j=0 load. One outstanding prefetch at
        a time — the pseudo-resource hazard serializes slot reuse through
        the scheduler, and the builder rejects an unconsumed double-issue.
        ``fp8``: the tile lives in the fp8 weight workspace (PREFETCH_W8 →
        the fp8 reserved slot).
        """
        if self._pending_pf is not None:
            raise ValueError(
                f"prefetch of tile {self._pending_pf[0]} not yet consumed — "
                "one reserved slot, one outstanding prefetch")
        if self._pf_res is None:
            self._pf_res = self.tensor(TILE, TILE)   # hazard token only
        tt = TaskType.PREFETCH_W8 if fp8 else TaskType.PREFETCH
        read_id = int(weight_tile) + (self._W8_HAZARD if fp8 else 0)
        self._emit(Task(tt, out=0, a0=int(weight_tile)),
                   [read_id], [self._pf_res.tile(0, 0)])
        self._pending_pf = (int(weight_tile), fp8)

    def gemm(self, out: TensorHandle, a: TensorHandle, b: TensorHandle,
             prefetch_first: bool = False, width: int = 16):
        """out (M,N) = a (M,K) @ b (K,N) as GEMM_WIDE strips of up to
        ``width`` output column tiles per task (reference make_linear emits
        multi-tile work per task the same way). The A row loads ONCE into
        the kernel's resident row buffer; a task that spans B's FULL width
        with k % 4 == 0 additionally gets the 4-row SUPER-strip fetch
        (d0 = 4: four k-rows are contiguous when b_stride == width) — the
        round-5 fix for the per-k-step DMA-overhead bound the per-task
        profile measured.

        ``prefetch_first``: the first task's f=0 weight tile was warmed by a
        preceding :meth:`prefetch` — it reads the reserved slot instead of
        issuing its own DMA (queue word c0 = 1)."""
        if isinstance(b, MatHandle):
            raise TypeError("matrix-workspace weights go through gemm_mat, "
                            "not gemm")
        if a.cols != b.rows or out.rows != a.rows or out.cols != b.cols:
            raise ValueError("gemm shape mismatch")
        if not 1 <= width <= 16:
            raise ValueError(f"gemm width {width} out of range")
        if a.fp8 or out.fp8:
            raise ValueError("fp8 space holds weights (GEMM B operands) "
                             "only — activations/outputs stay in the main "
                             "workspace")
        if prefetch_first:
            if self._pending_pf != (b.tile(0, 0), b.fp8):
                raise ValueError(
                    f"prefetch_first: pending prefetch {self._pending_pf} "
                    f"does not match this gemm's first weight tile "
                    f"{(b.tile(0, 0), b.fp8)}")
            self._pending_pf = None
        kt = a.ct
        tt = TaskType.GEMM_WIDE_W8 if b.fp8 else TaskType.GEMM_WIDE
        b_off = self._W8_HAZARD if b.fp8 else 0
        first = True
        for i in range(out.rt):
            j = 0
            while j < out.ct:
                wd = min(width, out.ct - j)
                su = 4 if (wd == b.ct and kt % 4 == 0 and kt >= 4) else 0
                reads = [a.tile(i, q) for q in range(kt)]
                reads += [b.tile(q, j + w) + b_off for q in range(kt)
                          for w in range(wd)]
                use_pf = prefetch_first and first
                if use_pf:
                    reads.append(self._pf_res.tile(0, 0))
                self._emit(
                    Task(tt, out.tile(i, j),
                         a0=a.tile(i, 0), b0=b.tile(0, j),
                         k_tiles=kt, a_stride=1, b_stride=b.ct,
                         arg=wd, c0=1 if use_pf else 0, d0=su),
                    reads, [out.tile(i, j + w) for w in range(wd)])
                self._max_gemm_width = max(
                    getattr(self, "_max_gemm_width", 1), wd)
                self._max_strip = max(getattr(self, "_max_strip", 1),
                                      (su or 1) * wd)
                self._max_row = max(getattr(self, "_max_row", 1), kt)
                first = False
                j += wd

    def prefetch_mat(self, w: MatHandle) -> int:
        """Start warming ``w``'s FIRST weight chunk into the reserved
        matrix slot (round 9: the stall-slice kill). The next
        ``gemm_mat(..., w, prefetch_first=True)`` consumes it — its
        chunk-0 DMA has been in flight since THIS task dispatched, so it
        streams under whatever tasks the scheduler places in between
        (attention at n=1; the ALLREDUCE_ROW barrier at n>1). One
        outstanding matrix warm at a time; the spec index the kernel
        branch needs is patched in when the consuming gemm_mat is
        emitted. Returns the task id."""
        if self._pending_pf_mat is not None:
            raise ValueError(
                f"matrix prefetch of wsm base {self._pending_pf_mat[1]} "
                "not yet consumed — one reserved slot, one outstanding "
                "warm (emit the matching gemm_mat(prefetch_first=True))")
        if not isinstance(w, MatHandle):
            raise TypeError("prefetch_mat warms matrix-workspace weights "
                            "(tensor_mat handles)")
        if self._pfm_res is None:
            self._pfm_res = self.tensor(TILE, TILE)   # hazard token only
        tid = self._emit(Task(TaskType.PREFETCH_MAT, out=0, a0=w.base),
                         [self._WM_HAZARD + w.base],
                         [self._pfm_res.tile(0, 0)])
        self._pending_pf_mat = (tid, w.base)
        return tid

    def gemm_mat(self, out: TensorHandle, a: TensorHandle, w: MatHandle,
                 residual: TensorHandle | None = None,
                 norm_w: TensorHandle | None = None,
                 norm_out: TensorHandle | None = None,
                 eps: float = 1e-6, prefetch_first: bool = False):
        """out (TILE, N) = a (TILE, K) @ w — ONE task over the 2D matrix
        workspace, compiled as a STATIC specialized branch (see tasks.py
        GEMM_MAT). ``w.pair``: w holds interleaved gate|up halves and the
        task stores silu(gate_half) * up_half (the fused gate/up/act path —
        out is the (TILE, w.n) activation). ``residual``: fuse ``+=
        residual`` into the store (mutually exclusive with pair).
        ``norm_w``/``norm_out`` (epilogue 3, requires ``residual``): the
        task ALSO stores ``norm_out = rms_norm(out) * norm_w`` — the
        round-6 cross-layer fusion that folds the consuming norm (the next
        layer's attn norm, or this layer's mlp norm after o-proj) into the
        producing GEMM, so the residual row never round-trips HBM between
        the add and the norm."""
        self._no_fp8(out, a, residual, norm_w, norm_out)
        if not isinstance(w, MatHandle):
            raise TypeError("gemm_mat weight must be a tensor_mat handle")
        if a.rt != 1 or out.rt != 1:
            raise ValueError("gemm_mat operates on single activation rows")
        if a.cols != w.k or out.cols != w.n:
            raise ValueError(
                f"gemm_mat shape mismatch: a ({a.rows},{a.cols}) @ w "
                f"({w.k},{w.n}{' pair' if w.pair else ''}) -> out "
                f"({out.rows},{out.cols})")
        if w.pair and residual is not None:
            raise ValueError("pair (silu) and residual epilogues are "
                             "mutually exclusive")
        if residual is not None and (residual.rt != 1
                                     or residual.cols != out.cols):
            # An unchecked narrower residual would read tiles of whatever
            # tensor was allocated after it and silently add garbage.
            raise ValueError(
                f"residual ({residual.rows},{residual.cols}) must match "
                f"out ({out.rows},{out.cols})")
        if (norm_w is None) != (norm_out is None):
            raise ValueError("epilogue 3 needs BOTH norm_w and norm_out")
        if norm_w is not None:
            if residual is None:
                raise ValueError("norm epilogue requires residual (it "
                                 "fuses the residual-chain add + norm)")
            if norm_out.rt != 1 or norm_out.cols != out.cols:
                raise ValueError(
                    f"norm_out ({norm_out.rows},{norm_out.cols}) must "
                    f"match out ({out.rows},{out.cols})")
            if norm_w.rt != 1 or norm_w.ct != out.ct:
                raise ValueError("norm_w must be the broadcast (TILE, N) "
                                 "norm-weight tensor matching out's width")
        if prefetch_first and (self._pending_pf_mat is None
                               or self._pending_pf_mat[1] != w.base):
            raise ValueError(
                f"prefetch_first: pending matrix warm "
                f"{self._pending_pf_mat} does not match this gemm_mat's "
                f"weight base {w.base}")
        epi = 1 if w.pair else (3 if norm_w is not None
                                else 2 if residual is not None else 0)
        spec = MatSpec(kt=a.ct, ns=w.n_strips, nt_out=out.ct,
                       kch=mat_chunk_rows(w.k), epi=epi,
                       warm=1 if prefetch_first else 0)
        try:
            si = self._mat_specs.index(spec)
        except ValueError:
            si = len(self._mat_specs)
            self._mat_specs.append(spec)
        reads = [a.tile(0, q) for q in range(a.ct)]
        reads.append(self._WM_HAZARD + w.base)
        if prefetch_first:
            # The warm task was emitted before its spec existed: patch its
            # spec-index word now (the kernel's PREFETCH_MAT branch needs
            # the static kch), and order this task after it through the
            # reserved-slot pseudo resource.
            pf_tid, _ = self._pending_pf_mat
            self._tasks[pf_tid] = dataclasses.replace(
                self._tasks[pf_tid], a_stride=si)
            reads.append(self._pfm_res.tile(0, 0))
            self._pending_pf_mat = None
        if residual is not None:
            reads += [residual.tile(0, q) for q in range(out.ct)]
        writes = [out.tile(0, j) for j in range(out.ct)]
        arg = epi
        b_stride = d0 = 0
        if epi == 3:
            reads += [norm_w.tile(0, q) for q in range(out.ct)]
            writes += [norm_out.tile(0, j) for j in range(out.ct)]
            arg = epi | (int(round(eps * 1e9)) << 8)
            b_stride, d0 = norm_w.tile(0, 0), norm_out.tile(0, 0)
        self._emit(
            Task(TaskType.GEMM_MAT, out.tile(0, 0), a0=a.tile(0, 0),
                 b0=w.base, k_tiles=a.ct, a_stride=si, b_stride=b_stride,
                 arg=arg,
                 c0=residual.tile(0, 0) if residual is not None else 0,
                 d0=d0),
            reads, writes)
        self._max_row = max(getattr(self, "_max_row", 1), a.ct, out.ct)

    def norm_rope(self, out: TensorHandle, a: TensorHandle,
                  w: TensorHandle, cos: TensorHandle, sin: TensorHandle,
                  eps: float = 1e-6):
        """Fused per-head qk-norm + RoPE over ONE (TILE, TILE) head tile
        (head_dim == TILE — the norm reduces over this tile's columns).
        Replaces the rms_norm + rope task pair per head."""
        self._no_fp8(out, a, w, cos, sin)
        for t in (out, a):
            if t.rt != 1 or t.ct != 1:
                raise ValueError("norm_rope operates on single head tiles")
        for t in (w, cos, sin):
            if t.rt != 1 or t.ct < 1:
                raise ValueError("norm weight / rope tables must be single-"
                                 "row-tile tensors")
        if cos.ct != 1 or sin.ct != 1 or w.ct != 1:
            raise ValueError("norm_rope reads one (TILE, TILE) tile of "
                             "w/cos/sin — wider tables would be silently "
                             "truncated")
        self._emit(
            Task(TaskType.NORM_ROPE, out.tile(0, 0), a0=a.tile(0, 0),
                 b0=w.tile(0, 0), arg=int(round(eps * 1e9)),
                 c0=cos.tile(0, 0), d0=sin.tile(0, 0)),
            [a.tile(0, 0), w.tile(0, 0), cos.tile(0, 0), sin.tile(0, 0)],
            [out.tile(0, 0)])

    def append_kv(self, kT: TensorHandle, v: TensorHandle, pos: int,
                  k_new: TensorHandle, v_new: TensorHandle):
        """In-kernel KV cache append at position ``pos``: k_new's row 0
        becomes column pos of the kT cache, v_new's row 0 becomes row pos
        of the v cache (reference appends in-kernel inside its qkv/attn
        tasks, model_builder.py). The task row is self-describing
        (a_stride/b_stride carry the cache base tiles) so
        advance_queue_pos retargets it per step without recompiling.

        ``kv8`` pool handles (both kT AND v, never mixed) emit the
        APPEND_KV_F8 variant: the new rows clamp to ±448 and cast to
        e4m3 on append (the saturating models/fp8._to_e4m3 contract)."""
        self._no_fp8(k_new, v_new)
        if kT.kv8 != v.kv8:
            raise ValueError(
                "append_kv pools must live in ONE space: kT and v are "
                f"kv8={kT.kv8}/{v.kv8} — a mixed-dtype page pool would "
                "read one space and write the other")
        if not kT.kv8:
            self._no_fp8(kT, v)
        if not 0 <= pos < kT.ct * TILE:
            raise ValueError(f"append pos {pos} outside cache capacity")
        if kT.rt != 1 or v.ct != 1:
            raise ValueError("kT must be (d, S), v (S, d)")
        for t in (k_new, v_new):
            if t.rt != 1 or t.ct != 1:
                raise ValueError("k_new/v_new must be single head tiles")
        ti, col = pos // TILE, pos % TILE
        kt_tile, v_tile = kT.tile(0, ti), v.tile(ti, 0)
        hz = self._K8_HAZARD if kT.kv8 else 0
        tt = TaskType.APPEND_KV_F8 if kT.kv8 else TaskType.APPEND_KV
        return self._emit(
            Task(tt, kt_tile, a0=k_new.tile(0, 0),
                 b0=v_tile, a_stride=kT.tile(0, 0), b_stride=v.tile(0, 0),
                 c0=col, d0=v_new.tile(0, 0)),
            [k_new.tile(0, 0), v_new.tile(0, 0), kt_tile + hz,
             v_tile + hz],
            [kt_tile + hz, v_tile + hz])

    def add_norm(self, out_x2: TensorHandle, a: TensorHandle,
                 b: TensorHandle, w: TensorHandle,
                 out_xn: TensorHandle, eps: float = 1e-6):
        """Fused ``out_x2 = a + b`` and ``out_xn = rms_norm(out_x2) * w``
        in ONE task (tasks.py ADD_NORM — the cross-layer residual-chain
        fusion for paths where an AllReduce sits between the GEMM and the
        add, so the GEMM's own epilogue can't fuse it). ``w`` is the
        broadcast (TILE, cols) norm-weight tensor."""
        self._no_fp8(out_x2, a, b, w, out_xn)
        for t in (out_x2, a, b, out_xn):
            if t.rt != 1 or (t.ct != a.ct):
                raise ValueError("add_norm operates on single-row-tile "
                                 "tensors of equal width")
        if w.ct != a.ct:
            raise ValueError("norm weight width must match the row")
        reads = ([a.tile(0, j) for j in range(a.ct)]
                 + [b.tile(0, j) for j in range(a.ct)]
                 + [w.tile(0, j) for j in range(a.ct)])
        writes = ([out_x2.tile(0, j) for j in range(a.ct)]
                  + [out_xn.tile(0, j) for j in range(a.ct)])
        self._emit(
            Task(TaskType.ADD_NORM, out_x2.tile(0, 0), a0=a.tile(0, 0),
                 b0=b.tile(0, 0), k_tiles=a.ct, b_stride=w.tile(0, 0),
                 arg=int(round(eps * 1e9)), d0=out_xn.tile(0, 0)),
            reads, writes)
        self._max_row = max(getattr(self, "_max_row", 1), a.ct)

    def norm_rope_qkv(self, q: TensorHandle, hq: int, k: TensorHandle,
                      hkv: int, q_norm: TensorHandle, k_norm: TensorHandle,
                      cos: TensorHandle, sin: TensorHandle,
                      eps: float = 1e-6):
        """Per-head qk-norm + RoPE over ALL hq q-heads and hkv k-heads in
        ONE task (tasks.py NORM_ROPE_QKV): norm weights and rope tables
        load once per layer instead of once per head. Requires the fused
        qkv layout — k's head tiles contiguous after q's."""
        self._no_fp8(q, k, q_norm, k_norm, cos, sin)
        if q.rt != 1 or k.rt != 1:
            raise ValueError("q/k must be single-row-tile activations")
        if q.ct < hq or k.ct < hkv:
            raise ValueError(f"head counts ({hq}, {hkv}) exceed tensor "
                             f"widths ({q.ct}, {k.ct})")
        if k.base != q.base + hq:
            raise ValueError(
                "norm_rope_qkv needs k's head tiles contiguous after q's "
                f"(q base {q.base} + hq {hq} != k base {k.base}) — the "
                "fused qkv_out layout; use per-head norm_rope otherwise")
        for t in (q_norm, k_norm, cos, sin):
            if t.rt != 1 or t.ct != 1:
                raise ValueError("norm weights / rope tables must be "
                                 "single (TILE, TILE) tiles")
        head_tiles = [q.tile(0, j) for j in range(hq)] \
            + [k.tile(0, j) for j in range(hkv)]
        reads = head_tiles + [q_norm.tile(0, 0), k_norm.tile(0, 0),
                              cos.tile(0, 0), sin.tile(0, 0)]
        self._emit(
            Task(TaskType.NORM_ROPE_QKV, q.tile(0, 0), a0=q.tile(0, 0),
                 b0=q_norm.tile(0, 0), k_tiles=hq,
                 a_stride=k_norm.tile(0, 0), b_stride=hkv,
                 arg=int(round(eps * 1e9)), c0=cos.tile(0, 0),
                 d0=sin.tile(0, 0)),
            reads, head_tiles)

    def all_reduce(self, t: TensorHandle):
        """Sum ``t`` over ranks in place (reference make_allreduce).

        Emits one ALLREDUCE_ROW task per ROW of tiles (round 6): the whole
        row pushes to each peer as one slab with one delivery wait and one
        exit barrier, where the old per-tile task paid all three per tile
        (the single-tile ALLREDUCE type remains dispatchable for queue-ABI
        compatibility)."""
        self._no_fp8(t)
        for i in range(t.rt):
            row = [t.tile(i, j) for j in range(t.ct)]
            self._emit(Task(TaskType.ALLREDUCE_ROW, t.tile(i, 0),
                            k_tiles=t.ct), row, row)
        self._max_ar = max(getattr(self, "_max_ar", 1), t.ct)

    def rms_norm(self, out: TensorHandle, a: TensorHandle, w: TensorHandle,
                 eps: float = 1e-6):
        """Row-wise RMSNorm over the full width (reference make_rms_norm).

        ``w`` is the norm weight stored broadcast as a (TILE, cols) tensor
        (see models.broadcast_rows); one task per row block.
        """
        self._no_fp8(out, a, w)
        if (out.rt, out.ct) != (a.rt, a.ct) or w.ct != a.ct:
            raise ValueError("rms_norm shape mismatch")
        for i in range(out.rt):
            reads = [a.tile(i, j) for j in range(a.ct)]
            reads += [w.tile(0, j) for j in range(a.ct)]
            self._emit(
                Task(TaskType.RMS_NORM, out.tile(i, 0), a0=a.tile(i, 0),
                     b0=w.tile(0, 0), k_tiles=a.ct,
                     arg=int(round(eps * 1e9))),
                reads, [out.tile(i, j) for j in range(out.ct)])
            self._max_row = max(getattr(self, "_max_row", 1), a.ct)

    def attn_decode(self, out: TensorHandle, q: TensorHandle,
                    kT: TensorHandle, v: TensorHandle, valid_len: int,
                    scale: float, k_new: TensorHandle | None = None,
                    v_new: TensorHandle | None = None):
        """One-token flash-attention decode for ONE head (reference
        make_attn: paged FA decode task).

        q/out: (TILE, TILE) — rows = padded batch, cols = head_dim = TILE;
        kT: (TILE, S) the head's cached keys transposed; v: (S, TILE).
        ``valid_len`` masks cache columns >= valid (runtime-updatable queue
        word). ``k_new``/``v_new`` (each one (TILE, TILE) tile, row b = the
        token batch row b just projected) join the softmax as the current
        position, so the host appends the cache *after* the step.
        """
        self._no_fp8(out, q, kT, v, k_new, v_new)
        if q.rt != 1 or q.ct != 1 or out.rt != 1 or out.ct != 1:
            raise ValueError("q/out must be a single (TILE, TILE) tile")
        if kT.rt != 1 or v.ct != 1 or kT.ct != v.rt:
            raise ValueError("kT must be (TILE, S), v (S, TILE)")
        if (k_new is None) != (v_new is None):
            raise ValueError("pass both k_new and v_new or neither")
        if k_new is None and valid_len < 1:
            raise ValueError("cache-only attention needs valid_len >= 1 "
                             "(all-masked softmax)")
        if valid_len > kT.ct * TILE:
            raise ValueError(
                f"valid_len {valid_len} exceeds cache capacity "
                f"{kT.ct * TILE} — the mask would admit garbage positions")
        if k_new is not None and (k_new.rt != 1 or k_new.ct != 1
                                  or v_new.rt != 1 or v_new.ct != 1):
            raise ValueError("k_new/v_new must be single (TILE, TILE) tiles "
                             "(one head's current k/v — use a _col view)")
        # Fully-masked cache tiles contribute nothing: don't visit them.
        # (k_tiles rides the queue like valid_len, so a host-side queue
        # update for a later position bumps both words consistently.)
        k_tiles = min(kT.ct, -(-valid_len // TILE))
        reads = ([q.tile(0, 0)] + [kT.tile(0, j) for j in range(k_tiles)]
                 + [v.tile(j, 0) for j in range(k_tiles)])
        c0 = d0 = -1
        if k_new is not None:
            c0, d0 = k_new.tile(0, 0), v_new.tile(0, 0)
            reads += [c0, d0]
        self._emit(
            Task(TaskType.ATTN_DECODE, out.tile(0, 0), a0=q.tile(0, 0),
                 b0=kT.tile(0, 0), k_tiles=k_tiles, a_stride=v.tile(0, 0),
                 b_stride=int(valid_len), arg=int(round(scale * 1e6)),
                 c0=c0, d0=d0),
            reads, [out.tile(0, 0)])

    def attn_decode_gqa(self, out: TensorHandle, out_j: int,
                        q: TensorHandle, q_j: int, g: int,
                        kT: TensorHandle, v: TensorHandle, valid_len: int,
                        scale: float, k_new: TensorHandle | None = None,
                        v_new: TensorHandle | None = None):
        """One-token decode for a WHOLE GQA group: the ``g`` q-heads at
        column tiles ``q_j..q_j+g-1`` of ``q`` (outputs at
        ``out_j..out_j+g-1`` of ``out``) attend the shared kv head's
        kT/v — KV streams once for the group instead of once per head.
        """
        self._no_fp8(out, q, kT, v, k_new, v_new)
        if not 1 <= g <= 127:
            raise ValueError(f"group size {g} out of range")
        if q_j + g > q.ct or out_j + g > out.ct:
            raise ValueError(
                f"group [{q_j}, {q_j + g}) exceeds q.ct={q.ct} or "
                f"out.ct={out.ct} — the tiles would alias the next tensor")
        if q.rt != 1 or out.rt != 1:
            raise ValueError("q/out must be single-row-tile activations")
        if not 0 < scale < 16:
            raise ValueError(f"scale {scale} out of the 24-bit arg field")
        if kT.rt != 1 or v.ct != 1 or kT.ct != v.rt:
            raise ValueError("kT must be (TILE, S), v (S, TILE)")
        if (k_new is None) != (v_new is None):
            raise ValueError("pass both k_new and v_new or neither")
        if k_new is None and valid_len < 1:
            raise ValueError("cache-only attention needs valid_len >= 1")
        if valid_len > kT.ct * TILE:
            raise ValueError(f"valid_len {valid_len} exceeds cache "
                             f"capacity {kT.ct * TILE}")
        k_tiles = min(kT.ct, -(-valid_len // TILE))
        q_tiles = [q.tile(0, q_j + h) for h in range(g)]
        out_tiles = [out.tile(0, out_j + h) for h in range(g)]
        reads = (q_tiles + [kT.tile(0, j) for j in range(k_tiles)]
                 + [v.tile(j, 0) for j in range(k_tiles)])
        c0 = d0 = -1
        if k_new is not None:
            if (k_new.rt != 1 or k_new.ct != 1 or v_new.rt != 1
                    or v_new.ct != 1):
                raise ValueError("k_new/v_new must be single (TILE, TILE) "
                                 "tiles (one kv head's current k/v)")
            c0, d0 = k_new.tile(0, 0), v_new.tile(0, 0)
            reads += [c0, d0]
        self._max_gqa = max(getattr(self, "_max_gqa", 1), g)
        self._emit(
            Task(TaskType.ATTN_DECODE_GQA, out_tiles[0], a0=q_tiles[0],
                 b0=kT.tile(0, 0), k_tiles=k_tiles, a_stride=v.tile(0, 0),
                 b_stride=int(valid_len),
                 arg=int(round(scale * 1e6)) | (g << 24), c0=c0, d0=d0),
            reads, out_tiles)

    def attn_decode_paged(self, out: TensorHandle, q: TensorHandle,
                          pages: list[tuple[int, int]], valid_len: int,
                          scale: float, k_new: TensorHandle | None = None,
                          v_new: TensorHandle | None = None,
                          kv8: bool = False):
        """Page-table flash-attention decode for ONE head: the j-th cache
        tile pair (kT tile id, V tile id) comes from ``pages`` — arbitrary
        workspace tiles, so sequences share pools without per-sequence
        max_seq reservations. The table rides extra queue rows (SMEM via
        scalar prefetch — the in-kernel analog of
        ops/paged_attention.py's table walk; reference: the paged FA task,
        mega_triton_kernel tasks/flash_attn.py).

        ``pages[j]``: (kT_tile, v_tile) covering logical positions
        [j·TILE, (j+1)·TILE); kT tiles are (d, TILE) key columns, v tiles
        (TILE, d) value rows — the same layout the linear task uses.
        ``kv8=True``: the page tile ids address the fp8 KV-POOL workspace
        and the ATTN_DECODE_PAGED_F8 variant streams them at half the
        bytes, widening to fp32 before the softmax dots.
        """
        self._no_fp8(out, q, k_new, v_new)
        if q.rt != 1 or q.ct != 1 or out.rt != 1 or out.ct != 1:
            raise ValueError("q/out must be a single (TILE, TILE) tile")
        if (k_new is None) != (v_new is None):
            raise ValueError("pass both k_new and v_new or neither")
        if k_new is None and valid_len < 1:
            raise ValueError("cache-only attention needs valid_len >= 1")
        if valid_len > len(pages) * TILE:
            raise ValueError(
                f"valid_len {valid_len} exceeds table coverage "
                f"{len(pages) * TILE}")
        # valid_len == 0 (empty cache, current token only): visit no pages.
        k_tiles = min(len(pages), -(-valid_len // TILE))
        hz = self._K8_HAZARD if kv8 else 0
        reads = [q.tile(0, 0)]
        flat: list[int] = []
        for kt_t, v_t in pages:
            flat += [int(kt_t), int(v_t)]
        reads += [t + hz for pair in pages[:k_tiles] for t in pair]
        c0 = d0 = -1
        if k_new is not None:
            c0, d0 = k_new.tile(0, 0), v_new.tile(0, 0)
            reads += [c0, d0]
        tt = (TaskType.ATTN_DECODE_PAGED_F8 if kv8
              else TaskType.ATTN_DECODE_PAGED)
        tid = self._emit(
            Task(tt, out.tile(0, 0),
                 a0=q.tile(0, 0), b0=-1,   # b0 patched to table row at compile
                 k_tiles=k_tiles, a_stride=0,
                 b_stride=int(valid_len), arg=int(round(scale * 1e6)),
                 c0=c0, d0=d0),
            reads, [out.tile(0, 0)])
        self._task_tables[tid] = flat
        return tid

    def moe_topk(self, out_wt: TensorHandle, logits: TensorHandle,
                 topk: int, num_experts: int, batch: int):
        """Router top-k + softmax-over-selected into the dense (E, B)
        TRANSPOSED weight tile ``out_wt`` (E = num_experts <= TILE).
        Rows >= ``batch`` and cols >= ``num_experts`` of the logits tile
        are masked (padded regions must not elect experts — an unmasked
        zero-logit pad row would mark ~every expert active and defeat
        MOE_FFN's skip)."""
        self._no_fp8(out_wt, logits)
        if not 1 <= topk <= num_experts <= TILE:
            raise ValueError(
                f"need 1 <= topk ({topk}) <= E ({num_experts}) <= {TILE}")
        if not 1 <= batch <= TILE:
            raise ValueError(f"batch {batch} out of range")
        if logits.rt != 1 or logits.ct != 1 or out_wt.rt != 1 \
                or out_wt.ct != 1:
            raise ValueError("logits/out_wt must be single (TILE, TILE) "
                             "tiles (E <= 128 experts)")
        self._emit(
            Task(TaskType.MOE_TOPK, out_wt.tile(0, 0),
                 a0=logits.tile(0, 0), b_stride=num_experts, arg=topk,
                 d0=batch),
            [logits.tile(0, 0)], [out_wt.tile(0, 0)])

    def moe_ffn(self, out: TensorHandle, xn: TensorHandle,
                wt: TensorHandle, w_gate: TensorHandle, w_up: TensorHandle,
                w_down: TensorHandle, num_experts: int):
        """One task = one layer's whole expert MLP (see tasks.py MOE_FFN).

        xn/out: (TILE, hidden); wt: the (E, B) weight tile from
        :meth:`moe_topk`; w_gate/w_up: (E·hidden, ffn_local) stacked expert
        weights; w_down: (E·ffn_local, hidden). Inactive experts are
        skipped in-kernel before any weight DMA.

        Hazard note: expert weights are host-scattered once and never
        task-written, so their read set is recorded via each tensor's base
        tile (a full per-tile list would be E·HT·FT entries per layer with
        no extra edges to find)."""
        self._no_fp8(out, xn, wt, w_gate, w_up, w_down)
        if out.rt != 1 or xn.rt != 1 or out.ct != xn.ct:
            raise ValueError("xn/out must be (TILE, hidden) rows of equal "
                             "width")
        if wt.rt != 1 or wt.ct != 1:
            raise ValueError("wt must be the single MOE_TOPK output tile")
        ht = xn.ct
        if w_gate.rt % num_experts or w_gate.rt // num_experts != ht:
            raise ValueError(
                f"w_gate rows {w_gate.rows} != E*hidden "
                f"({num_experts}*{xn.cols})")
        ft = w_gate.ct
        if w_up.rt != w_gate.rt or w_up.ct != ft:
            raise ValueError("w_up shape mismatch with w_gate")
        if w_down.rt != num_experts * ft or w_down.ct != ht:
            raise ValueError(
                f"w_down must be (E*ffn_local, hidden), got "
                f"({w_down.rows}, {w_down.cols})")
        if num_experts > TILE:
            raise ValueError(f"E {num_experts} > {TILE} needs multi-tile "
                             "router output (unsupported)")
        reads = ([xn.tile(0, j) for j in range(ht)]
                 + [wt.tile(0, 0), w_gate.tile(0, 0), w_up.tile(0, 0),
                    w_down.tile(0, 0)])
        self._emit(
            Task(TaskType.MOE_FFN, out.tile(0, 0), a0=xn.tile(0, 0),
                 b0=wt.tile(0, 0), k_tiles=ht, a_stride=w_gate.tile(0, 0),
                 b_stride=w_up.tile(0, 0),
                 arg=num_experts | (ft << 16), c0=w_down.tile(0, 0)),
            reads, [out.tile(0, j) for j in range(ht)])
        self._max_moe_h = max(getattr(self, "_max_moe_h", 0), ht)
        self._max_moe_f = max(getattr(self, "_max_moe_f", 0), ft)
        self._max_row = max(getattr(self, "_max_row", 1), ht)
        # MoE strips double-buffer via offset pairs inside the strip
        # buffer: it must hold two gate/up (ft) and two down (ht) strips.
        self._max_strip = max(getattr(self, "_max_strip", 1),
                              2 * ft, 2 * ht)

    # -- compile / run -------------------------------------------------------
    def compile(self, num_ranks: int = 1, axis: str = "tp",
                dtype=jnp.float32,
                force_ar: bool = False,
                head_dim: int | None = None) -> "CompiledMegaKernel":
        # head_dim defaults to the BUILDER's value (set by the assembly);
        # an explicit argument must agree — the three head_dim knobs
        # (build, feed, compile) must never silently diverge.
        if head_dim is None:
            head_dim = self.head_dim
        elif head_dim != self.head_dim:
            raise ValueError(
                f"compile(head_dim={head_dim}) mismatches the program's "
                f"build-time head_dim {self.head_dim} — the norm/rope "
                "sub-tile span is part of the assembly, not a free "
                "compile knob")
        if self._pending_pf is not None:
            raise ValueError(
                f"prefetch of tile {self._pending_pf[0]} never consumed — "
                "the kernel would exit with an outstanding DMA on the "
                "reserved slot (emit the matching gemm(prefetch_first=True))")
        if self._pending_pf_mat is not None:
            raise ValueError(
                f"matrix prefetch of wsm base {self._pending_pf_mat[1]} "
                "never consumed — the kernel would exit with an "
                "outstanding DMA on the reserved matrix slot (emit the "
                "matching gemm_mat(prefetch_first=True))")
        retired = {TaskType.GEMM, TaskType.ROPE}
        for t in self._tasks:
            if t.type in retired:
                # The kernel keeps these switch slots as no-ops for queue-
                # ABI stability; executing one would silently skip work
                # (output tiles never written — garbage from stale
                # workspace data). Fail at build time instead.
                raise ValueError(
                    f"task type {t.type.name} is retired (GEMM -> "
                    "GEMM_WIDE, ROPE -> NORM_ROPE); the kernel would "
                    "no-op it silently")
        order = topo_schedule(len(self._tasks), self._edges,
                              task_types=[t.type for t in self._tasks])
        # Emission-order task id -> queue row (paged-serving hosts retarget
        # per-slot attention/append rows without re-deriving the schedule).
        task_rows = [0] * len(order)
        for pos, t in enumerate(order):
            task_rows[t] = pos
        if num_ranks > 1:
            # Cross-device tasks must execute in the same relative order on
            # every rank (they match by queue position); the deterministic
            # scheduler guarantees it because all ranks build the same graph.
            pass
        rows = [self._tasks[t].encode() for t in order]
        n_exec = len(rows)
        # Page tables pack as DATA rows after the executable tasks (the
        # grid never reaches them); each owning task's b0 becomes its
        # table's absolute starting row.
        for pos, t in enumerate(order):
            flat = self._task_tables.get(t)
            if flat is None:
                continue
            rows[pos][3] = len(rows)
            padded = list(flat) + [0] * (-len(flat) % WORDS)
            for off in range(0, len(padded), WORDS):
                rows.append(padded[off:off + WORDS])
        queue = np.asarray(rows, np.int32).reshape(-1, WORDS)
        # The program's task-type set is static at compile time (the queue
        # only ever changes pos words via advance_queue_pos): run_queue
        # compiles no-op bodies for every OTHER switch branch, so a
        # 3-task-type test program doesn't pay the trace+compile cost of
        # all ~23 handlers (round 6 — the biggest single lever on build
        # latency; the full switch remains the direct-run_queue default).
        used_types = tuple(sorted({int(t.type) for t in self._tasks}))
        return CompiledMegaKernel(queue=jnp.asarray(queue),
                                  num_tiles=self._num_tiles,
                                  num_ranks=num_ranks, axis=axis,
                                  dtype=jnp.dtype(dtype),
                                  num_tiles_kv8=self._num_tiles_kv8,
                                  num_exec=n_exec,
                                  max_gqa=getattr(self, "_max_gqa", 1),
                                  max_gemm_width=getattr(
                                      self, "_max_gemm_width", 1),
                                  num_tiles8=self._num_tiles8,
                                  max_moe_h=getattr(self, "_max_moe_h", 0),
                                  max_moe_f=getattr(self, "_max_moe_f", 0),
                                  max_row=getattr(self, "_max_row", 1),
                                  max_strip=getattr(self, "_max_strip", 1),
                                  num_mrows=self._num_mrows,
                                  mat_specs=tuple(self._mat_specs),
                                  max_ar=getattr(self, "_max_ar", 1),
                                  force_ar=force_ar,
                                  used_types=used_types,
                                  head_dim=int(head_dim),
                                  task_rows=tuple(task_rows),
                                  hazard_edges=tuple(self._edges),
                                  task_reads=tuple(self._reads),
                                  task_writes=tuple(self._writes))


@dataclasses.dataclass
class CompiledMegaKernel:
    """Packed queue + workspace geometry; ``run`` is the single launch."""

    queue: jax.Array
    num_tiles: int
    num_ranks: int
    axis: str
    dtype: jnp.dtype = jnp.dtype(jnp.float32)  # bf16 halves tile DMA bytes
    num_exec: int | None = None   # dispatched rows (rest = page-table data)
    max_gqa: int = 1              # largest GQA group (sizes VMEM scratch)
    max_gemm_width: int = 1       # widest GEMM strip (sizes acc scratch)
    num_tiles8: int = 0           # fp8 weight-workspace tiles (0 = unused)
    num_tiles_kv8: int = 0        # fp8 KV-POOL workspace tiles (0 = none;
    #                               the read-write half-byte paged pools)
    max_moe_h: int = 0            # MoE hidden tiles (0 = no MoE tasks)
    max_moe_f: int = 0            # MoE ffn_local tiles
    max_row: int = 1              # widest resident row (tiles)
    max_strip: int = 1            # widest strip fetch (tiles)
    num_mrows: int = 0            # 2D matrix-workspace rows (0 = unused)
    mat_specs: tuple = ()         # static GEMM_MAT shapes (kernel branches)
    max_ar: int = 1               # widest ALLREDUCE_ROW slab (tiles)
    force_ar: bool = False        # run AR protocol at n=1 (self loopback)
    used_types: tuple | None = None  # task types in the queue (switch
    #                                  branches for the rest compile as
    #                                  no-ops; None = keep every branch)
    head_dim: int = TILE          # NORM_ROPE(_QKV) sub-tile span (< TILE:
    #                               heads zero-padded into their tiles)
    task_rows: tuple | None = None  # emission task id -> queue row (the
    #                                 paged-serving host retarget map)
    hazard_edges: tuple | None = None  # (src, dst) emission-id dependency
    #                                    edges the schedule was derived from
    task_reads: tuple | None = None   # per-task read tile-id sets, emission
    #                                   order (_W8/_WM/_K8 hazard spaces)
    task_writes: tuple | None = None  # per-task write tile-id sets (mklint
    #                                   re-derives RAW/WAW/WAR from these)

    def scatter_input(self, ws: jax.Array, h: TensorHandle,
                      value: jax.Array) -> jax.Array:
        """Write (rows, cols) ``value`` into the tiled workspace (main,
        fp8, or kv8 — ``ws`` must be the matching array for the handle's
        space). Narrow (e4m3) targets quantize through the SATURATING
        cast — the same ±448 clamp the in-kernel append applies, so a
        host-scattered prefill page and an in-kernel appended one store
        identical values."""
        if h.fp8 or h.kv8:
            from triton_distributed_tpu.models.fp8 import _to_e4m3

            value = _to_e4m3(jnp.asarray(value))
            dt = jnp.float8_e4m3fn
        else:
            dt = self.dtype
        tiles = value.astype(dt).reshape(
            h.rt, TILE, h.ct, TILE).transpose(0, 2, 1, 3).reshape(
            h.rt * h.ct, TILE, TILE)
        return jax.lax.dynamic_update_slice(ws, tiles, (h.base, 0, 0))

    def gather_output(self, ws: jax.Array, h: TensorHandle) -> jax.Array:
        if h.fp8:
            # fp8 ids alias main-workspace ids (separate space starting at
            # 0) — gathering one from the main ws returns unrelated tiles.
            raise ValueError("fp8 weight-workspace tensors are read-only "
                             "inputs; gather_output reads the main "
                             "workspace")
        if h.kv8:
            raise ValueError("kv8 pool tensors live in the fp8 KV "
                             "workspace; gather them with gather_kv8 "
                             "from the carried kv8 array")
        tiles = jax.lax.dynamic_slice(
            ws, (h.base, 0, 0), (h.rt * h.ct, TILE, TILE))
        return tiles.reshape(h.rt, h.ct, TILE, TILE).transpose(
            0, 2, 1, 3).reshape(h.rows, h.cols)

    def gather_kv8(self, wkv8: jax.Array, h: TensorHandle) -> jax.Array:
        """Read a kv8 pool tensor from the carried fp8 KV workspace,
        WIDENED to fp32 (the dequantized view parity oracles compare)."""
        if not h.kv8:
            raise ValueError("gather_kv8 reads kv8 pool handles only")
        tiles = jax.lax.dynamic_slice(
            wkv8, (h.base, 0, 0), (h.rt * h.ct, TILE, TILE))
        return tiles.reshape(h.rt, h.ct, TILE, TILE).transpose(
            0, 2, 1, 3).reshape(h.rows, h.cols).astype(jnp.float32)

    @property
    def _strip_pad(self) -> int:
        """Static-size fetches may overrun the last real tile: B strips
        (up to max_strip tiles), the 8-tile row-load chunks, the MoE
        strip fetches, and ALLREDUCE_ROW's static max_ar slab push.
        Padding the workspaces by the worst overfetch keeps every read in
        bounds (stores are always exact)."""
        return max(self.max_strip, self.max_gemm_width, self.max_moe_h,
                   self.max_moe_f, self.max_ar, 8) - 1

    def make_workspace(self, inputs: dict) -> jax.Array:
        """Build the tiled MAIN workspace once (weights + caches +
        activations; fp8-space handles are rejected — use make_workspace8).
        In a serving loop, scatter weights here a single time and update
        only the per-step tensors afterward (scatter_input is jittable)."""
        ws = jnp.zeros((max(self.num_tiles, 1) + self._strip_pad,
                        TILE, TILE), self.dtype)
        for h, v in inputs.items():
            if isinstance(h, MatHandle):
                raise ValueError("matrix handle in main workspace feeds — "
                                 "pass it to make_workspace_mat (or use "
                                 "split_feeds)")
            if h.fp8:
                raise ValueError("fp8 handle in main workspace feeds — "
                                 "pass it to make_workspace8")
            if h.kv8:
                raise ValueError("kv8 pool handle in main workspace feeds "
                                 "— pass it to make_workspace_kv8")
            ws = self.scatter_input(ws, h, v)
        return ws

    @staticmethod
    def split_feeds(feeds: dict) -> tuple[dict, dict, dict]:
        """Split a mixed feeds dict into (main, fp8, matrix) workspace
        feeds — the one-liner every caller of make_workspace* wants.
        kv8 POOL handles are rejected: pools start zeroed
        (:meth:`make_workspace_kv8`) and fill via ``scatter_input`` into
        the carried kv8 array — silently dropping (or mis-routing) a
        pool feed here would corrupt the cache with no error."""
        for h in feeds:
            if not isinstance(h, MatHandle) and getattr(h, "kv8", False):
                raise ValueError(
                    "kv8 pool handle in feeds — scatter_input it into "
                    "the kv8 workspace (make_workspace_kv8) instead")
        main = {h: v for h, v in feeds.items()
                if not isinstance(h, MatHandle) and not h.fp8}
        w8 = {h: v for h, v in feeds.items()
              if not isinstance(h, MatHandle) and h.fp8}
        wm = {h: v for h, v in feeds.items() if isinstance(h, MatHandle)}
        return main, w8, wm

    def scatter_mat(self, wsm: jax.Array, h: MatHandle,
                    value) -> jax.Array:
        """Write a weight matrix into the 2D matrix workspace. ``value``:
        (k, n) array, or for ``h.pair`` a (first, second) tuple of (k, n)
        arrays (gate, up) interleaved per strip."""
        half = MAT_COLS // 2
        if h.pair:
            g, u = value
            g = jnp.asarray(g, self.dtype)
            u = jnp.asarray(u, self.dtype)
            if g.shape != (h.k, h.n) or u.shape != (h.k, h.n):
                raise ValueError(
                    f"pair values must each be ({h.k}, {h.n})")
            pad = h.n_strips * half - h.n
            g = jnp.pad(g, ((0, 0), (0, pad)))
            u = jnp.pad(u, ((0, 0), (0, pad)))
            strips = [jnp.concatenate(
                [g[:, s * half:(s + 1) * half],
                 u[:, s * half:(s + 1) * half]], axis=1)
                for s in range(h.n_strips)]
        else:
            v = jnp.asarray(value, self.dtype)
            if v.shape != (h.k, h.n):
                raise ValueError(f"value must be ({h.k}, {h.n})")
            v = jnp.pad(v, ((0, 0), (0, h.n_strips * MAT_COLS - h.n)))
            strips = [v[:, s * MAT_COLS:(s + 1) * MAT_COLS]
                      for s in range(h.n_strips)]
        return jax.lax.dynamic_update_slice(
            wsm, jnp.concatenate(strips, axis=0), (h.base, 0))

    def make_workspace_mat(self, inputs: dict) -> jax.Array:
        """Build the 2D matrix weight workspace (read-only input of every
        step; pair handles take (gate, up) value tuples)."""
        wsm = jnp.zeros((max(self.num_mrows, 1), MAT_COLS), self.dtype)
        for h, v in inputs.items():
            if not isinstance(h, MatHandle):
                raise ValueError("non-matrix handle in matrix workspace "
                                 "feeds")
            wsm = self.scatter_mat(wsm, h, v)
        return wsm

    def make_workspace8(self, inputs: dict) -> jax.Array:
        """Build the float8_e4m3fn weight workspace (read-only input of
        every step; values quantize to e4m3 on scatter)."""
        ws8 = jnp.zeros((max(self.num_tiles8, 1) + self._strip_pad,
                         TILE, TILE), jnp.float8_e4m3fn)
        for h, v in inputs.items():
            if not h.fp8:
                raise ValueError("non-fp8 handle in fp8 workspace feeds")
            ws8 = self.scatter_input(ws8, h, v)
        return ws8

    def make_workspace_kv8(self, inputs: dict | None = None) -> jax.Array:
        """Build the float8_e4m3fn KV-POOL workspace — the READ-WRITE
        half-byte paged pools ATTN_DECODE_PAGED_F8 streams and
        APPEND_KV_F8 appends into (carry it through every step like the
        main workspace; step() aliases it in place). Pools start zeroed;
        ``inputs`` (kv8 handles → (rows, cols) values) pre-load pages —
        values quantize through the saturating cast."""
        wkv8 = jnp.zeros((max(self.num_tiles_kv8, 1), TILE, TILE),
                         jnp.float8_e4m3fn)
        for h, v in (inputs or {}).items():
            if not getattr(h, "kv8", False):
                raise ValueError("non-kv8 handle in kv8 workspace feeds")
            wkv8 = self.scatter_input(wkv8, h, v)
        return wkv8

    def step(self, ws: jax.Array, queue: jax.Array | None = None,
             ws8: jax.Array | None = None,
             wsm: jax.Array | None = None,
             wkv8: jax.Array | None = None,
             profile: bool = False) -> jax.Array:
        """One queue execution over a prebuilt workspace (jittable; pass an
        advance_queue_pos-updated ``queue`` to retarget without recompile).
        Device-local: wrap in shard_map when num_ranks > 1. ``ws8``: the
        fp8 weight workspace when the program uses one; ``wsm``: the 2D
        matrix weight workspace when the program has GEMM_MAT tasks;
        ``wkv8``: the READ-WRITE fp8 KV-pool workspace when the program
        has kv8 pools — the return then becomes ``(ws, wkv8)`` (both
        carried, both aliased in place).
        ``profile=True``: the observability mode (ISSUE 3) — the kernel
        additionally stamps each task's execution record into an int32
        (num_exec, 128) dump and the return grows ``prof`` as its last
        element; decode it with
        ``obs.kernel_profile.KernelProfile.from_dump``."""
        if self.num_tiles_kv8 and wkv8 is None:
            raise ValueError(
                f"program uses {self.num_tiles_kv8} fp8 KV-pool tiles "
                "but no wkv8 was passed — build it with "
                "make_workspace_kv8 and carry it through every step")
        if wkv8 is not None and not self.num_tiles_kv8:
            raise ValueError(
                "wkv8 passed but this program has no kv8 pool tiles — "
                "was it compiled without the fp8 KV form?")
        if self.num_tiles8 and ws8 is None:
            # The placeholder run_queue substitutes is ONE tile — a W8
            # program would DMA weight tiles from out-of-bounds indices
            # (silent garbage on hardware). Fail loudly instead.
            raise ValueError(
                f"program uses {self.num_tiles8} fp8 weight tiles but no "
                "ws8 was passed — build it with make_workspace8")
        if self.num_mrows and wsm is None:
            raise ValueError(
                f"program uses {self.num_mrows} matrix-workspace rows but "
                "no wsm was passed — build it with make_workspace_mat")
        if wsm is not None:
            # A stale/undersized wsm (e.g. built from a different program)
            # would DMA weight rows from out-of-bounds indices — silent
            # garbage on hardware. Validate against the program instead.
            if wsm.ndim != 2 or wsm.shape[1] != MAT_COLS \
                    or wsm.shape[0] < max(self.num_mrows, 1):
                raise ValueError(
                    f"wsm shape {tuple(wsm.shape)} does not fit this "
                    f"program: need (>= {max(self.num_mrows, 1)}, "
                    f"{MAT_COLS}) — was it built by make_workspace_mat of "
                    "a different program?")
            if wsm.dtype != jnp.dtype(self.dtype):
                raise ValueError(
                    f"wsm dtype {wsm.dtype} != program dtype "
                    f"{jnp.dtype(self.dtype)}")
        return run_queue(self.queue if queue is None else queue, ws,
                         num_ranks=self.num_ranks, axis=self.axis,
                         num_tasks=self.num_exec, max_gqa=self.max_gqa,
                         max_gemm_width=self.max_gemm_width,
                         workspace8=ws8, max_moe_h=self.max_moe_h,
                         max_moe_f=self.max_moe_f, max_row=self.max_row,
                         max_strip=self.max_strip,
                         workspace_m=wsm, mat_specs=self.mat_specs,
                         max_ar=self.max_ar, force_ar=self.force_ar,
                         used_types=self.used_types,
                         head_dim=self.head_dim,
                         workspace_kv8=wkv8, profile=profile)

    def run(self, inputs: dict, outputs: list[TensorHandle],
            _device_local: bool = True):
        """Device-local execution (inside shard_map when num_ranks > 1).
        fp8-space handles in ``inputs`` feed the fp8 weight workspace;
        MatHandle keys feed the 2D matrix workspace."""
        main = {h: v for h, v in inputs.items()
                if not h.fp8 and not isinstance(h, MatHandle)}
        w8 = {h: v for h, v in inputs.items()
              if h.fp8 and not isinstance(h, MatHandle)}
        wm = {h: v for h, v in inputs.items() if isinstance(h, MatHandle)}
        ws8 = self.make_workspace8(w8) if w8 else None
        wsm = self.make_workspace_mat(wm) if wm else None
        ws = self.step(self.make_workspace(main), ws8=ws8, wsm=wsm)
        return [self.gather_output(ws, h) for h in outputs]
