// MegaKernel host-side scheduler — native task-graph ordering.
//
// Reference: python/triton_dist/mega_triton_kernel/core/scheduler.py:40-95
// (static SM work queues, round-robin/zig-zag assignment) and the native
// runtime obligations of SURVEY.md §2.1. On TPU the queue is consumed
// sequentially per device core, so the scheduler's job is a hazard-correct
// topological order that keeps producer→consumer distances short (better
// DMA locality between dependent tiles).
//
// Exposed as a C ABI for ctypes (no pybind11 in this image):
//   topo_schedule(n_tasks, n_edges, edges_src, edges_dst, order_out) -> int
// Returns 0 on success, -1 on cycle. Kahn's algorithm with a
// smallest-ready-index heap: deterministic, stable, and dependency-tight.

#include <cstdint>
#include <queue>
#include <vector>
#include <functional>

extern "C" {

int topo_schedule(int32_t n_tasks, int32_t n_edges, const int32_t* edges_src,
                  const int32_t* edges_dst, int32_t* order_out) {
  std::vector<std::vector<int32_t>> succ(n_tasks);
  std::vector<int32_t> indeg(n_tasks, 0);
  for (int32_t e = 0; e < n_edges; ++e) {
    int32_t s = edges_src[e], d = edges_dst[e];
    if (s < 0 || d < 0 || s >= n_tasks || d >= n_tasks) return -2;
    succ[s].push_back(d);
    indeg[d]++;
  }
  std::priority_queue<int32_t, std::vector<int32_t>, std::greater<int32_t>>
      ready;
  for (int32_t i = 0; i < n_tasks; ++i)
    if (indeg[i] == 0) ready.push(i);
  int32_t emitted = 0;
  while (!ready.empty()) {
    int32_t t = ready.top();
    ready.pop();
    order_out[emitted++] = t;
    for (int32_t d : succ[t])
      if (--indeg[d] == 0) ready.push(d);
  }
  return emitted == n_tasks ? 0 : -1;  // -1: dependency cycle
}

}  // extern "C"
