"""MegaKernel task model — typed tasks over a tiled workspace.

Reference: ``python/triton_dist/mega_triton_kernel/core/task_base.py:150-218``
(``TaskBase``: (task_type, layer/task/tile ids, dependency, io tensor descs,
extra params) encoded to an int tuple) and the per-SM uint32 work queues of
``core/scheduler.py:40-95``.

TPU encoding: every tensor lives in ONE fp32 HBM workspace shaped
``(num_tiles, TILE, TILE)``; a task is ``WORDS`` int32s addressing tiles by
index — so the device kernel needs no pointer decoding, only dynamic leading
indices (the TensorDesc ptr+shape decode of ``kernels/task_context.py:31-50``
collapses to tile ids).
"""

from __future__ import annotations

import dataclasses
import enum

TILE = 128      # square fp32 tile (8×128 sublane-aligned, MXU-shaped)
WORDS = 10      # int32 words per task
MAT_COLS = 1024  # matrix weight workspace width (strip columns)


class TaskType(enum.IntEnum):
    """Device-dispatchable task kinds (reference tasks/*.py builders)."""

    COPY = 0        # out <- a
    ADD = 1         # out <- a + b
    SILU_MUL = 2    # out <- silu(a) * b
    GEMM = 3        # RETIRED (queue-ABI placeholder) — the builder emits
    #                 GEMM_WIDE for all matmuls since round 4
    ALLREDUCE = 4   # out <- sum over ranks of out (one tile, one-shot)
    SCALE = 5       # out <- a * scalar (scalar in word 7 as fixed-point 1e-6)
    RMS_NORM = 6    # out row <- a row * rsqrt(mean(a^2)+eps) * w; one task
    #                 per row of k_tiles column tiles; eps fixed-point 1e-9
    ROPE = 7        # RETIRED (queue-ABI placeholder) — fused into
    #                 NORM_ROPE since round 4
    ATTN_DECODE = 8  # out <- softmax(q @ KT * scale, masked to valid) @ V
    #                 a0=q tile, b0=KT base, a_stride=V base, k_tiles=S/TILE,
    #                 b_stride=valid_len (runtime-updatable), arg=scale*1e6,
    #                 c0/d0 = current-token k/v tiles (-1 = cache only):
    #                 the new token's (B, d) k/v join the softmax rowwise,
    #                 so the cache is appended AFTER the step (no in-kernel
    #                 tile mutation needed)
    ATTN_DECODE_GQA = 11  # ATTN_DECODE for a whole GQA group: g q-heads
    #                 sharing ONE kv head computed in one task — KV tiles
    #                 stream ONCE for the group (vs once per head) and g-1
    #                 task dispatches disappear. q tiles a0..a0+g-1 and out
    #                 tiles out..out+g-1 are contiguous (the model's head
    #                 layout groups q-heads by kv head). g rides the high
    #                 bits of arg: arg = round(scale*1e6) | (g << 24).
    PREFETCH = 10   # fire-and-forget DMA warm: start copying tile a0 into
    #                 the reserved pipeline slot (vb2[PIPE_DEPTH]); the next
    #                 GEMM emitted with prefetch_first=True (queue word
    #                 c0 == 1) consumes it as its j=0 weight tile instead of
    #                 issuing its own load — the first-tile DMA latency hides
    #                 under whatever tasks the scheduler places in between.
    #                 Reference: the weight-prefetch task of
    #                 mega_triton_kernel (SURVEY.md §2.7 task builders).
    ATTN_DECODE_PAGED = 9  # ATTN_DECODE over a PAGE TABLE: the j-th cache
    #                 tile pair comes from table entries (kT tile id, V tile
    #                 id) stored in extra queue rows (scalar-prefetched SMEM
    #                 — data-dependent addressing, the same mechanism as
    #                 ops/paged_attention.py). b0 = table start ROW in the
    #                 queue; entry pair j at flat offsets (2j, 2j+1) within
    #                 rows b0+. Other words as ATTN_DECODE; a_stride is the
    #                 SPECULATIVE candidate window (0 = legacy diagonal
    #                 current-token fold; win >= 1 folds the block's fresh
    #                 k/v causally — row i attends fresh rows j <= i < win,
    #                 the draft-and-verify form, docs/serving.md
    #                 "Speculative decode"). Reference: the paged FA decode
    #                 task of mega_triton_kernel tasks/flash_attn.py.
    GEMM_WIDE = 12  # GEMM over ``arg`` contiguous output column tiles
    #                 (out..out+arg-1) in ONE task: the A row streams once
    #                 for the whole strip (vs once per output tile) and
    #                 arg-1 dispatches disappear — the round-4 answer to the
    #                 ~2.8us/task queue-walk floor (the reference's linear
    #                 task similarly emits multi-tile work per task,
    #                 model_builder.py make_linear). Words as GEMM plus
    #                 arg=width; c0=1 consumes a PREFETCH warm for the
    #                 f=0 weight tile.
    NORM_ROPE = 13  # out <- rope(rms_norm(a) * w): the per-head qk-norm +
    #                 RoPE pair fused into one task (one load of the q/k
    #                 head tile instead of two round-trips; reference fuses
    #                 the same pair in its qkv task). a0 = head tile
    #                 (norm over its TILE columns = head_dim), b0 = norm
    #                 weight tile, c0/d0 = cos/sin tiles, arg = eps 1e-9.
    APPEND_KV = 14  # In-kernel KV cache append (reference does the append
    #                 inside its attention tasks, model_builder.py qkv/attn):
    #                 writes k_new's row 0 (a0, (B,d) tile) into column
    #                 ``c0`` of the kT cache tile ``out`` (d, TILE), and
    #                 v_new's row 0 (d0) into row ``c0`` of the v cache tile
    #                 ``b0`` (TILE, d). a_stride/b_stride carry the kT/v
    #                 tensor BASE tile ids so advance_queue_pos can retarget
    #                 out/b0/c0 per position without recompiling.
    #                 SPECULATIVE window form (docs/serving.md): k_tiles =
    #                 count n >= 1 appends k_new rows arg..arg+n-1 at
    #                 columns c0..c0+n-1 (v rows likewise; k_tiles == 0
    #                 keeps the legacy single-row form); c0 < 0 skips the
    #                 task — the host parks the page-spill row there when a
    #                 candidate window stays inside one page tile.
    GEMM_WIDE_W8 = 15  # GEMM_WIDE whose B (weight) tiles live in the
    #                 float8_e4m3fn weight workspace (separate read-only
    #                 input; tile ids index it, upcast to the compute dtype
    #                 in VMEM) — half the weight-streaming bytes, the
    #                 dominant decode traffic. Reference: its kernels' fp8
    #                 weight payloads (README.md:96-97).
    PREFETCH_W8 = 16  # PREFETCH of an fp8 weight-workspace tile into the
    #                 fp8 reserved slot (consumed by GEMM_WIDE_W8 c0 == 1).
    MOE_TOPK = 17   # Router top-k + softmax-over-selected, one tile: reads
    #                 the (B, E) logits tile a0 (E <= TILE), masks rows >= B
    #                 (d0) and cols >= E (b_stride), picks arg = topk experts
    #                 per row (leftmost tie-break), softmaxes the selected
    #                 logits, and stores the DENSE (E, B) TRANSPOSED weight
    #                 tile to ``out`` — zeros for unselected experts, which
    #                 is what lets MOE_FFN skip inactive experts by a
    #                 column-sum predicate. Matches ops/moe.route_and_sort
    #                 (Qwen norm_topk_prob semantics).
    GEMM_MAT = 19   # GEMM whose B lives in the 2D MATRIX weight workspace
    #                 (wsm, shape (rows, MAT_COLS)): the round-5 answer to
    #                 the genericity tax the on-chip probe measured
    #                 (scripts/probe_gemm_task.py: the GEMM_WIDE body hits
    #                 21us in isolation but 61us in the megakernel — the
    #                 dynamic width/trip-count predication from queue
    #                 scalars is the difference). Weight matrices store as
    #                 vertical 1024-col strips; the kernel fetches (kch,
    #                 1024) 2D chunks and runs few, DEEP dots ((128, kch) @
    #                 (kch, 1024)) in a fully STATIC body selected by spec
    #                 index — the builder registers each distinct (k_tiles,
    #                 n_strips, out_tiles, kch, epilogue) shape and the
    #                 kernel compiles one specialized branch per spec, the
    #                 TPU analog of the reference's per-model generated
    #                 dispatch chain (mega_triton_kernel/core/
    #                 code_generator.py:31-89). Words: out = output row
    #                 tile base, a0 = A row tile base, b0 = wsm ROW base,
    #                 k_tiles (runtime copy), a_stride = SPEC INDEX,
    #                 arg = epilogue (runtime copy), c0 = residual row
    #                 tile base (epilogue 2/3). Epilogues: 0 = plain store;
    #                 1 = silu-pair (strips interleave [gate|up] 512-col
    #                 halves; stores silu(gate)*up — the fused gate/up/act
    #                 path); 2 = += residual (fused o-proj/down + add);
    #                 3 = += residual then rms_norm(result) * w into a
    #                 SECOND output row (b_stride = norm weight base, d0 =
    #                 xn out base, arg = 3 | eps_1e9 << 8) — the round-6
    #                 cross-layer fusion that folds the next norm read into
    #                 the producing GEMM's epilogue.
    ADD_NORM = 20   # Fused residual add + RMSNorm — the round-6 CROSS-LAYER
    #                 fusion for the multi-rank path (x2 = x1 + down after an
    #                 AllReduce, immediately re-read by the next norm): one
    #                 task computes x2 = a + b, stores it, AND stores
    #                 xn = rms_norm(x2) * w — the x2 row never round-trips
    #                 HBM between the add and the norm, and one dispatch
    #                 replaces two. Words: out = x2 row base, a0 = x1 base,
    #                 b0 = addend base, k_tiles = row tiles, b_stride = norm
    #                 weight row base (broadcast tensor), arg = eps 1e-9,
    #                 d0 = xn output row base.
    NORM_ROPE_QKV = 21  # NORM_ROPE over ALL q+k heads of one fused qkv row
    #                 in ONE task: the q_norm/k_norm weights and the cos/sin
    #                 tables load ONCE for the whole layer instead of once
    #                 per head, and hq+hkv-1 dispatches disappear (round-6
    #                 queue compaction: 5 tasks/layer -> 1 at the Qwen3-8B
    #                 shard shape, 144 fewer dispatches at 36 layers).
    #                 Requires the matrix layout's contiguous q|k head tiles
    #                 (k base == q base + hq). Words: out = a0 = q head base
    #                 tile, b0 = q_norm tile, a_stride = k_norm tile,
    #                 k_tiles = hq (q-head count), b_stride = hkv (k-head
    #                 count), arg = eps 1e-9, c0/d0 = cos/sin tiles.
    ALLREDUCE_ROW = 22  # AllReduce over k_tiles CONTIGUOUS tiles (a whole
    #                 activation row) in ONE task: one slab push per peer,
    #                 one delivery wait, one exit barrier — where the
    #                 single-tile ALLREDUCE paid all three PER TILE (32x the
    #                 dispatches, remote DMAs, and barriers at hidden=4096;
    #                 the round-6 cross-device queue compaction). Words:
    #                 out = row base tile, k_tiles = row tiles (<= the
    #                 program's max_ar slab width).
    PREFETCH_MAT = 23  # Fire-and-forget warm of a GEMM_MAT weight's FIRST
    #                 chunk into the reserved matrix slot (vbm[2]): the
    #                 round-9 stall-slice kill — the consuming GEMM_MAT
    #                 (a spec with warm=1) reads chunk 0 from the slot
    #                 instead of serializing its first wsm DMA after the
    #                 preceding task, so the chunk streams UNDER whatever
    #                 long task the scheduler placed in between (attention
    #                 at n=1; the ALLREDUCE_ROW barrier at n>1). Words:
    #                 a0 = wsm row base of the matrix, a_stride = the
    #                 consuming task's SPEC INDEX (static kch per branch).
    #                 Reference: the weight-prefetch task of
    #                 mega_triton_kernel (SURVEY.md §2.7).
    ATTN_DECODE_PAGED_F8 = 24  # ATTN_DECODE_PAGED whose page POOLS live
    #                 in the float8_e4m3fn KV workspace (a separate
    #                 READ-WRITE array with its own tile-id space): each
    #                 table entry's kT/V tile DMA moves HALF the bytes —
    #                 the decode-bandwidth lever (ROADMAP 1a; reference
    #                 fp8 serving payload README.md:96-97) — and tiles
    #                 widen to fp32 in VMEM before the softmax dots
    #                 (quantize-then-attend: parity vs the dense fp8-KV
    #                 paged path is exact). Same word layout as
    #                 ATTN_DECODE_PAGED; a distinct STATIC branch, the
    #                 warm-spec pattern (MatSpec.warm) applied to dtype.
    APPEND_KV_F8 = 25  # APPEND_KV into the fp8 KV pool workspace: the
    #                 new k/v rows (main-workspace activations) clamp to
    #                 e4m3's ±448 finite range and CAST on append (the
    #                 models/fp8._to_e4m3 saturation contract — a plain
    #                 cast would NaN hot KV values), read-modify-write of
    #                 the two fp8 cache tiles. Same words as APPEND_KV.
    MOE_FFN = 18    # One task = one layer's ENTIRE expert MLP: loops the E
    #                 experts; an expert whose (E, B) weight column is all
    #                 zero is SKIPPED before any weight DMA issues — the
    #                 data-dependent sparsity that makes MoE decode stream
    #                 only ~B*topk experts' weights instead of all E.
    #                 Active experts stream gate/up strips (k-major) and
    #                 down strips (f-major) double-use of the GEMM_WIDE
    #                 strip buffer, accumulate silu(x@wg)*(x@wu) per-token-
    #                 weighted into the output row. Words: out = x_out base,
    #                 a0 = xn base, b0 = WT tile (from MOE_TOPK), k_tiles =
    #                 hidden tiles HT, a_stride = w_gate base, b_stride =
    #                 w_up base, arg = E | (ffn_tiles << 16), c0 = w_down
    #                 base. Expert weights are stacked handles:
    #                 w_gate/w_up (E·hidden, ffn_local), w_down
    #                 (E·ffn_local, hidden).


@dataclasses.dataclass(frozen=True)
class Task:
    """One queue entry. Word layout:
    [type, out, a0, b0, k_tiles, a_stride, b_stride, arg, c0, d0]."""

    type: TaskType
    out: int
    a0: int = 0
    b0: int = 0
    k_tiles: int = 0
    a_stride: int = 0
    b_stride: int = 0
    arg: int = 0
    c0: int = 0
    d0: int = 0

    def encode(self) -> list[int]:
        return [int(self.type), self.out, self.a0, self.b0, self.k_tiles,
                self.a_stride, self.b_stride, self.arg, self.c0, self.d0]


@dataclasses.dataclass(frozen=True)
class TensorHandle:
    """A (R, C) tensor as a row-major grid of TILE×TILE tiles.

    ``fp8``: lives in the float8_e4m3fn WEIGHT workspace (a separate
    read-only input array with its own tile-id space) instead of the main
    workspace. ``kv8``: lives in the float8_e4m3fn KV-POOL workspace — a
    separate READ-WRITE array (aliased through the step like the main
    workspace) holding paged KV pools at half the bytes; only
    ATTN_DECODE_PAGED_F8 reads it and APPEND_KV_F8 writes it."""

    base: int
    rows: int
    cols: int
    fp8: bool = False
    kv8: bool = False

    @property
    def rt(self) -> int:
        return self.rows // TILE

    @property
    def ct(self) -> int:
        return self.cols // TILE

    def tile(self, i: int, j: int) -> int:
        return self.base + i * self.ct + j

    def tiles(self) -> list[int]:
        return list(range(self.base, self.base + self.rt * self.ct))


@dataclasses.dataclass(frozen=True)
class MatHandle:
    """A weight matrix in the 2D MATRIX workspace (wsm, (rows, MAT_COLS)).

    A (K, N) matrix stores as ``n_strips`` vertical strips of MAT_COLS
    columns (the last zero-padded), stacked: strip ``s`` occupies wsm rows
    ``[base + s*K, base + (s+1)*K)``. ``pair=True`` marks the interleaved
    gate|up layout: each strip's left MAT_COLS/2 columns come from the
    FIRST matrix of the pair and the right half from the second, so the
    silu-pair epilogue consumes both halves from one fetched chunk."""

    base: int        # starting row in wsm
    k: int           # contraction rows (== K)
    n: int           # real output columns (per matrix; for pair: of EACH)
    pair: bool = False

    fp8 = False      # never lives in the fp8 tile workspace

    @property
    def n_strips(self) -> int:
        if self.pair:
            return -(-self.n // (MAT_COLS // 2))
        return -(-self.n // MAT_COLS)

    @property
    def rows(self) -> int:
        return self.n_strips * self.k


@dataclasses.dataclass(frozen=True)
class MatSpec:
    """Static shape of a GEMM_MAT task — one specialized kernel branch per
    distinct spec (the per-model code generation the reference does in
    core/code_generator.py, expressed as a lax.switch over static bodies).

    ``kch``: contraction rows per fetched chunk (the largest of 512/256/128
    dividing K, capped at K). ``epi``: 0 plain, 1 silu-pair, 2 +residual,
    3 +residual THEN rms_norm into a second output row (the round-6
    cross-layer fusion: the o-proj/down-proj task also produces the NEXT
    norm's output — queue word b_stride = norm weight row base, d0 = xn
    output row base, arg = 3 | (eps_1e9 << 8)).
    ``nt_out``: output width in TILE columns (for pair epi: of the act).
    ``warm``: 1 = chunk 0 was warmed by a preceding PREFETCH_MAT into the
    reserved matrix slot — the branch waits the warm semaphore instead of
    issuing its own first chunk DMA (the round-9 cross-task overlap)."""

    kt: int          # A-row tiles (K / TILE)
    ns: int          # strips
    nt_out: int      # output tiles
    kch: int         # chunk rows
    epi: int         # epilogue kind
    warm: int = 0    # 1 = consume a PREFETCH_MAT warm for chunk 0

    @property
    def n_ch(self) -> int:
        return (self.kt * TILE) // self.kch


def mat_chunk_rows(k: int) -> int:
    """Largest power-of-two chunk row count (<= 512) dividing ``k``."""
    for c in (512, 256, 128):
        if k % c == 0:
            return min(c, k)
    raise ValueError(f"K {k} not a multiple of {TILE}")
