"""Megakernel serving — dense/HF model params in, a decode backend out.

Reference: ``mega_triton_kernel/models/qwen3.py`` (HF weights feeding the
persistent-kernel task graph) + ``model_server.py`` (the serving loop that
replays it — the 3.33 ms headline path, BASELINE.md). Round-2 VERDICT #5:
the megakernel ran only random-feed benches; this module loads real model
params (models/hf_loader.py or init_dense_llm) into DecodeLayerHandles
feeds and exposes the decode loop the Engine drives.

Flow: Engine prefills with the fast batched dense path (linear KV cache),
then the cache is transposed into the megakernel's per-head kT/v workspace
regions and every subsequent token is ONE pallas_call (plus embed/lm_head,
which stay outside the kernel exactly like the reference keeps sampling
host-side). The per-step k/v append runs IN-KERNEL (APPEND_KV tasks,
round 4 — matching the reference's in-kernel append in its qkv/attn
tasks); advance_queue_pos retargets the append destination each step.

TP serving (round 3): with ``num_ranks > 1`` the decoder shards weights
per rank (column-parallel qkv/gate/up, row-parallel o/down, kv-head
split), builds each rank's workspace on its device, and runs the step
under shard_map with the in-kernel AllReduce tasks carrying the TP
reductions — token-identical to the jitted ar backend at TP=1 and TP=8
(tests/test_megakernel_serving.py). Requires a 1-D mesh over the TP axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers.common import rms_norm
from triton_distributed_tpu.obs import stepprof as obs_stepprof
from triton_distributed_tpu.obs import trace as obs_trace
from triton_distributed_tpu.megakernel.models import (
    DecodeStepProgram, advance_queue_pos, broadcast_rows, build_decode_step,
    feed_layer_weights, pad_head_vec, rope_tables,
)
from triton_distributed_tpu.megakernel.tasks import MAT_COLS, TILE, WORDS
from triton_distributed_tpu.models.config import ModelConfig


def validate_megakernel_cfg(cfg: ModelConfig, max_seq: int) -> None:
    if cfg.head_dim not in (TILE // 2, TILE):
        raise ValueError(
            f"megakernel needs head_dim {TILE // 2} (padded-head layout) "
            f"or {TILE} (got {cfg.head_dim})")
    if cfg.hidden_size % TILE or cfg.intermediate_size % TILE:
        raise ValueError("hidden/intermediate sizes must be TILE multiples")
    if max_seq % TILE:
        raise ValueError("max_seq must be a TILE multiple")
    if cfg.is_moe:
        raise ValueError("megakernel serving covers the dense stack")


def weight_feeds(prog: DecodeStepProgram, cfg: ModelConfig,
                 params: dict, *, rank: int = 0,
                 num_ranks: int = 1) -> dict:
    """Map a dense param tree (init_dense_llm / hf_loader layout) onto the
    program's workspace handles — ``rank``'s TP shard (column-parallel
    qkv/gate/up, row-parallel o/down; global view == shard at TP=1)."""
    n = num_ranks
    d = cfg.head_dim
    hq_l = cfg.num_heads // n
    hkv_l = cfg.num_kv_heads // n
    ffn_l = cfg.intermediate_size // n

    def cols(w, width):
        return w[:, rank * width:(rank + 1) * width]

    def rows(w, height):
        return w[rank * height:(rank + 1) * height]

    feeds: dict = {}
    for h, layer in zip(prog.layers, params["layers"]):
        attn = layer["attn"]
        feeds[h.attn_norm] = broadcast_rows(np.asarray(
            layer["attn_norm"], np.float32))
        feeds[h.mlp_norm] = broadcast_rows(np.asarray(
            layer["mlp_norm"], np.float32))
        qn = (np.asarray(attn["q_norm"], np.float32) if cfg.qk_norm
              else np.ones(cfg.head_dim, np.float32))
        kn = (np.asarray(attn["k_norm"], np.float32) if cfg.qk_norm
              else np.ones(cfg.head_dim, np.float32))
        feeds[h.q_norm] = broadcast_rows(pad_head_vec(qn, d))
        feeds[h.k_norm] = broadcast_rows(pad_head_vec(kn, d))
        mlp = layer["mlp"]
        feed_layer_weights(
            feeds, h,
            wq=cols(attn["wq"], hq_l * d),
            wk=cols(attn["wk"], hkv_l * d),
            wv=cols(attn["wv"], hkv_l * d),
            wo=rows(attn["wo"], hq_l * d),
            w_gate=cols(mlp["w_gate"], ffn_l),
            w_up=cols(mlp["w_up"], ffn_l),
            w_down=rows(mlp["w_down"], ffn_l),
            head_dim=d)
    return feeds


def cache_feeds(prog: DecodeStepProgram, cache, *, rank: int = 0,
                num_ranks: int = 1) -> dict:
    """KV cache (models/kv_cache.KVCache, batch 1) → ``rank``'s per-head
    kT/v feeds (kv heads are TP-sharded; head_dim < TILE pads into the
    tile rows/cols — the padded-head layout)."""
    feeds: dict = {}
    k, v = cache.k, cache.v    # (L, 1, S, hkv_global, hd)
    hd = k.shape[-1]
    hkv_l = k.shape[3] // num_ranks
    for li, h in enumerate(prog.layers):
        for kv in range(len(h.kT)):
            g_kv = rank * hkv_l + kv
            kT = k[li, 0, :, g_kv, :].T                   # (hd, S)
            vv = v[li, 0, :, g_kv, :]                     # (S, hd)
            if hd < TILE:
                kT = jnp.pad(kT, ((0, TILE - hd), (0, 0)))
                vv = jnp.pad(vv, ((0, 0), (0, TILE - hd)))
            feeds[h.kT[kv]] = kT
            feeds[h.v[kv]] = vv
    return feeds


class MegakernelDecoder:
    """TP decode loop over the compiled megakernel.

    Build once per (cfg, max_seq, num_ranks); ``start(cache)`` loads a
    prefilled KV cache into the (per-rank) workspace; ``step`` runs one
    token (jitted once — the queue is retargeted per position without
    recompiling, megakernel/models.py advance_queue_pos). With
    ``num_ranks > 1`` the step runs under shard_map and the in-kernel
    AllReduce tasks carry the TP reductions (the reference's multi-GPU
    MegaTritonKernel serving shape).
    """

    def __init__(self, cfg: ModelConfig, params: dict, *, max_seq: int,
                 dtype=jnp.float32, ctx=None, axis: str = "tp",
                 num_ranks: int = 1, fp8_weights: bool = False,
                 profile: bool = False, final_norm: bool = False):
        validate_megakernel_cfg(cfg, max_seq)
        if profile and num_ranks > 1:
            raise ValueError(
                "profile=True is single-rank for now — the per-task dump "
                "is a per-core record and the TP shard_map step does not "
                "yet carry a sharded profile output")
        n = num_ranks
        if cfg.num_heads % n or cfg.num_kv_heads % n or \
                cfg.intermediate_size % n:
            raise ValueError(f"heads/ffn not divisible by TP degree {n}")
        if (cfg.intermediate_size // n) % TILE:
            raise ValueError("per-rank ffn must stay a TILE multiple")
        if n > 1:
            if ctx is None:
                raise ValueError("num_ranks > 1 requires ctx (the mesh "
                                 "hosting the TP axis)")
            if tuple(ctx.mesh.axis_names) != (axis,):
                raise ValueError(
                    f"megakernel TP serving needs a 1-D mesh over "
                    f"{axis!r}; got axes {ctx.mesh.axis_names} — the "
                    "per-rank workspace placement maps rank r to the "
                    "r-th device of that axis")
        self.cfg = cfg
        self.max_seq = max_seq
        self.n = n
        self.axis = axis
        self.ctx = ctx
        # fp8_weights: projection/MLP weights stream from the
        # float8_e4m3fn weight workspace (half the decode-dominant
        # weight bytes; outputs carry the e4m3 quantization — opt-in,
        # token-identity with the bf16 ar path is NOT expected).
        self.fp8_weights = fp8_weights
        # profile: every step also returns the kernel's per-task dispatch
        # dump (obs/kernel_profile.py); the newest dump is kept on
        # ``last_profile`` so serving loops stay (ws, tok)-shaped.
        self.profile = profile
        self.last_profile = None
        # Observability: the first step() of a fresh decoder pays the jit
        # compile; ``last_step_cold`` lets metric recorders keep that
        # sample out of the step-latency percentiles.
        self.warm = False
        self.last_step_cold = True
        # final_norm: the model's final RMSNorm runs IN-KERNEL, fused into
        # the last layer's residual tail (round 6 — one fewer host op
        # between kernel and lm_head). Opt-in: the in-kernel reduction's
        # fp32 accumulation order differs from layers/common.rms_norm at
        # the last ulp, so strict token-identity tests keep the host norm.
        self.final_norm_inkernel = final_norm
        self.prog = build_decode_step(
            hidden=cfg.hidden_size, hq_local=cfg.num_heads // n,
            hkv_local=cfg.num_kv_heads // n,
            ffn_local=cfg.intermediate_size // n,
            num_layers=cfg.num_layers, max_seq=max_seq,
            pos=max_seq - 1, num_ranks=n, eps=cfg.rms_norm_eps,
            inkernel_append=True, fp8_weights=fp8_weights,
            final_norm=final_norm, head_dim=cfg.head_dim,
            mat_prefetch=not fp8_weights)
        self.comp = self.prog.mb.compile(num_ranks=n, axis=axis,
                                         dtype=dtype,
                                         head_dim=cfg.head_dim)
        # Weight feeds computed ONCE (per rank) — start() merges only the
        # cache feeds, so repeated serve() calls never re-slice the model.
        self._weight_feeds = [
            weight_feeds(self.prog, cfg, params, rank=r, num_ranks=n)
            for r in range(n)
        ]
        if final_norm:
            fn = broadcast_rows(np.asarray(params["final_norm"],
                                           np.float32))
            for wf in self._weight_feeds:
                wf[self.prog.fnorm] = fn
        # embed / final_norm / lm_head replicated once up front: passing
        # the Engine's vocab-sharded lm_head through a replicated shard_map
        # spec would insert a full all-gather into every decode step.
        def replicated(x):
            if x is None or n == 1:
                return x
            from jax.sharding import NamedSharding, PartitionSpec as P

            return jax.device_put(jnp.asarray(x),
                                  NamedSharding(ctx.mesh, P()))

        self.embed = replicated(params["embed"])
        self.final_norm = replicated(params["final_norm"])
        self.lm_head = replicated(params.get("lm_head"))
        if n == 1:
            # Donate the workspace: it is ALL the weights + KV — without
            # donation every token would pay a whole-workspace device copy.
            self._step_jit = jax.jit(self._step, donate_argnums=(0,))
        else:
            from jax.sharding import PartitionSpec as P

            mesh = ctx.mesh

            def sharded(ws, embed, final_norm, lm_head, queue, cos, sin,
                        token, ws8, wsm):
                # fp8_weights is a static python flag: without it ws8 is a
                # placeholder tile the kernel never reads (and vice versa
                # for the matrix workspace, which the fp8 layout forgoes).
                ws, tok = self._step(ws[0], embed, final_norm, lm_head,
                                     queue, cos, sin, token,
                                     ws8=ws8[0] if self.fp8_weights
                                     else None,
                                     wsm=wsm[0] if self.comp.num_mrows
                                     else None)
                return ws[None], tok

            fn = jax.shard_map(
                sharded, mesh=mesh,
                in_specs=(P(axis), P(), P(), P(), P(), P(), P(), P(),
                          P(axis), P(axis)),
                out_specs=(P(axis), P()), check_vma=False)
            self._step_jit = jax.jit(fn, donate_argnums=(0,))
            from jax.sharding import NamedSharding

            if not fp8_weights:
                # Placeholder fp8 operand allocated ONCE with its final
                # sharding — a fresh per-step array would add a host
                # allocation + reshard to every token.
                self._ws8 = jax.device_put(
                    jnp.zeros((n, 1, TILE, TILE), jnp.float8_e4m3fn),
                    NamedSharding(mesh, P(axis)))
            if not self.comp.num_mrows:
                self._wsm = jax.device_put(
                    jnp.zeros((n, 1, MAT_COLS), self.comp.dtype),
                    NamedSharding(mesh, P(axis)))

    # -- workspace ----------------------------------------------------------
    def start(self, cache) -> jax.Array:
        """Workspace(s) with weights + the prefilled KV cache loaded:
        (T, TILE, TILE) at TP=1, (n, T, TILE, TILE) sharded over the axis
        otherwise."""
        with obs_trace.span("mk_start", num_ranks=self.n):
            return self._start(cache)

    def _start(self, cache) -> jax.Array:
        if cache.k.shape[1] != 1:
            raise ValueError("megakernel decode is batch-1 "
                             f"(cache batch {cache.k.shape[1]})")
        if cache.max_seq != self.max_seq:
            raise ValueError(f"cache max_seq {cache.max_seq} != decoder "
                             f"max_seq {self.max_seq}")
        if self.n == 1:
            feeds = dict(self._weight_feeds[0])
            feeds.update(cache_feeds(self.prog, cache))
            main, w8, wm = self.comp.split_feeds(feeds)
            self._ws8 = (self.comp.make_workspace8(w8)
                         if self.fp8_weights else None)
            self._wsm = (self.comp.make_workspace_mat(wm)
                         if self.comp.num_mrows else None)
            return self.comp.make_workspace(main)
        from jax.sharding import NamedSharding, PartitionSpec as P

        # Build each rank's workspace ON its device (no n-times stack spike
        # on device 0 — the workspace is the whole model + KV).
        mesh = self.ctx.mesh
        devices = list(mesh.devices.flat)
        shards = []
        ws8_shards = []
        wsm_shards = []
        for r in range(self.n):
            feeds = dict(self._weight_feeds[r])
            feeds.update(cache_feeds(self.prog, cache, rank=r,
                                     num_ranks=self.n))
            main, w8, wm = self.comp.split_feeds(feeds)
            ws_r = self.comp.make_workspace(main)
            shards.append(jax.device_put(ws_r[None], devices[r]))
            if self.fp8_weights:
                ws8_r = self.comp.make_workspace8(w8)
                ws8_shards.append(jax.device_put(ws8_r[None], devices[r]))
            if self.comp.num_mrows:
                wsm_r = self.comp.make_workspace_mat(wm)
                wsm_shards.append(jax.device_put(wsm_r[None], devices[r]))
        shape = (self.n,) + shards[0].shape[1:]
        if self.fp8_weights:
            s8 = (self.n,) + ws8_shards[0].shape[1:]
            self._ws8 = jax.make_array_from_single_device_arrays(
                s8, NamedSharding(mesh, P(self.axis)), ws8_shards)
        if self.comp.num_mrows:
            sm = (self.n,) + wsm_shards[0].shape[1:]
            self._wsm = jax.make_array_from_single_device_arrays(
                sm, NamedSharding(mesh, P(self.axis)), wsm_shards)
        # (fp8 off: keep the __init__-time placeholder — shard_map still
        # needs its array operand.)
        return jax.make_array_from_single_device_arrays(
            shape, NamedSharding(mesh, P(self.axis)), shards)

    # -- one token ----------------------------------------------------------
    def _step(self, ws, embed, final_norm, lm_head, queue, cos, sin, token,
              ws8=None, wsm=None):
        # embed / final_norm / lm_head arrive as ARGUMENTS: closed over,
        # jit would bake them into the trace as inline constants (multi-GB
        # for real checkpoints — the exact hazard bench.py documents).
        # (The position rides the QUEUE: KV append happens in-kernel via
        # APPEND_KV tasks retargeted by advance_queue_pos.)
        x_row = embed[token[0]].astype(jnp.float32)            # (hidden,)
        x = jnp.zeros((TILE, self.cfg.hidden_size), jnp.float32
                      ).at[0].set(x_row)
        ws = self.comp.scatter_input(ws, self.prog.x, x)
        ws = self.comp.scatter_input(ws, self.prog.cos, cos)
        ws = self.comp.scatter_input(ws, self.prog.sin, sin)
        prof = None
        if self.profile:
            ws, prof = self.comp.step(ws, queue, ws8=ws8, wsm=wsm,
                                      profile=True)
        else:
            ws = self.comp.step(ws, queue, ws8=ws8, wsm=wsm)
        x_out = self.comp.gather_output(ws, self.prog.x_out)[0:1]
        if self.final_norm_inkernel:
            # x_out is already the normalized row (fused into the last
            # layer's tail); the fnorm weight was fed with the workspace.
            xn = x_out.astype(jnp.float32)
        else:
            xn = rms_norm(x_out.astype(jnp.float32),
                          final_norm.astype(jnp.float32),
                          self.cfg.rms_norm_eps)
        head = lm_head if lm_head is not None else embed.T
        logits = xn @ head.astype(jnp.float32)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if self.profile:
            return ws, tok, prof
        return ws, tok

    def step(self, ws: jax.Array, token: jax.Array, pos: int):
        """token: (1,) int32; pos: host int (current cache length). Returns
        (workspace', next_token (1,))."""
        if pos >= self.max_seq:
            raise ValueError(
                f"pos {pos} >= max_seq {self.max_seq}: the step appends "
                "this position's k/v — past capacity it would write into "
                "the adjacent workspace region")
        queue = advance_queue_pos(self.comp.queue, pos,
                                  num_exec=self.comp.num_exec)
        cos, sin = rope_tables(pos, self.cfg.head_dim, self.cfg.rope_theta)
        ws8 = getattr(self, "_ws8", None)
        wsm = getattr(self, "_wsm", None)
        self.last_step_cold = not self.warm
        with obs_trace.span("mk_step", pos=pos):
            out = self._step_jit(ws, self.embed, self.final_norm,
                                 self.lm_head, queue, jnp.asarray(cos),
                                 jnp.asarray(sin), token, ws8, wsm)
        # Warm only after a SUCCESSFUL step: if the compiling first call
        # raises, the retry still classifies (and routes) as cold.
        self.warm = True
        if self.profile:
            ws, tok, self.last_profile = out
            return ws, tok
        return out


class PagedMegakernelDecoder:
    """Paged-workspace megakernel decode for the SERVING tier (round 9).

    Every serving slot is one ROW BLOCK of the decode program (row 0 =
    the slot's real token, the same padding discipline as the batch-1
    decoder), with its OWN page table over shared per-(layer, kv-head)
    KV pools. Pool pages line up ONE-TO-ONE with a
    ``models/kv_cache.PagedModelCache`` pool of ``page_size == TILE``:
    pool page ``p`` of the serving cache IS pool tile ``p`` of every
    megakernel pool, so the PR-7 ``PageAllocator``'s page ids drive the
    kernel's tables directly — admission, preemption and resume reuse
    the serving scheduler unchanged. The LAST pool tile is the reserved
    scratch page idle slots ride at ``kv_lens`` 0 (account it under the
    allocator's ``reserved=`` — serving/loop.py does).

    Per step the host rewrites QUEUE WORDS only — per-slot valid
    lengths, visited-tile counts, APPEND_KV targets, and the page-table
    DATA rows — then replays the ONE compiled kernel (the
    tables-are-data contract of the reference's PagedKVCache megakernel
    assembly; no recompile ever). KV appends run IN-KERNEL into the
    pools, so the workspace is the decode-time source of truth;
    ``load_prefill`` scatters a finished chunked prefill's pages in
    (recompute-on-resume re-prefills, so preemption needs no copy-out).
    """

    def __init__(self, cfg: ModelConfig, params: dict, *, num_slots: int,
                 num_pages: int, max_pages: int, dtype=jnp.float32,
                 kv_dtype=None, mat_prefetch: bool = True,
                 spec_window: int = 1):
        capacity = max_pages * TILE
        validate_megakernel_cfg(cfg, capacity)
        if num_slots < 1:
            raise ValueError(f"num_slots = {num_slots} must be >= 1")
        # spec_window (ISSUE 14, docs/serving.md "Speculative decode"):
        # W = spec_k + 1 candidate rows per slot ride rows 0..W-1 of the
        # slot's TILE block — ONE launch scores the last accepted token
        # plus k drafts (causal window fold in ATTN_DECODE_PAGED{,_F8},
        # windowed APPEND_KV{,_F8} rows). W = 1 builds the exact
        # pre-spec program. Range/combos validated by
        # _check_decode_step_config with named errors.
        self.spec_w = int(spec_window)
        if num_pages < 1:
            raise ValueError(f"num_pages = {num_pages} must be >= 1")
        if max_pages < 1:
            # A table longer than the pool is fine (unmapped entries ride
            # the scratch page; the admission budget checks usable pages)
            # — only an empty table is meaningless.
            raise ValueError(f"max_pages = {max_pages} must be >= 1")
        # kv_dtype (round 12): None / the workspace dtype keeps the pools
        # as main-workspace tiles; float8_e4m3fn moves them into the fp8
        # KV workspace — ATTN_DECODE_PAGED_F8 streams pages at HALF the
        # bytes and APPEND_KV_F8 saturate-casts appends, the megakernel
        # half of the fp8 KV serving lane. Anything else is a named
        # error (the serving tier wraps it in BackendUnsupportedError
        # and demotes rather than dying).
        wdt = jnp.dtype(dtype)
        self.kv_fp8 = (kv_dtype is not None
                       and jnp.dtype(kv_dtype) == jnp.float8_e4m3fn)
        if (kv_dtype is not None and not self.kv_fp8
                and jnp.dtype(kv_dtype) != wdt):
            raise ValueError(
                f"megakernel paged lane serves kv_dtype float8_e4m3fn "
                f"(the fp8 pool workspace) or the workspace dtype "
                f"({wdt}); got {jnp.dtype(kv_dtype)} — kv_dtype engine "
                "argument")
        self.cfg = cfg
        self.num_slots = num_slots
        self.num_pages = num_pages          # usable pages (excl. scratch)
        self.max_pages = max_pages
        self.scratch = num_pages            # LAST pool tile, never owned
        self.capacity = capacity
        self.prog = build_decode_step(
            hidden=cfg.hidden_size, hq_local=cfg.num_heads,
            hkv_local=cfg.num_kv_heads, ffn_local=cfg.intermediate_size,
            num_layers=cfg.num_layers, max_seq=capacity,
            pos=capacity - 1, num_ranks=1, eps=cfg.rms_norm_eps,
            paged=True, inkernel_append=True,
            batch=num_slots * TILE, head_dim=cfg.head_dim,
            mat_prefetch=mat_prefetch,
            kv_pool_pages=num_pages + 1, table_pages=max_pages,
            kv_fp8=self.kv_fp8, spec_window=self.spec_w)
        self.comp = self.prog.mb.compile(dtype=dtype,
                                         head_dim=cfg.head_dim)
        self._weight_feeds = weight_feeds(self.prog, cfg, params)
        self.embed = jnp.asarray(params["embed"])
        self.final_norm = jnp.asarray(params["final_norm"])
        self.lm_head = (jnp.asarray(params["lm_head"])
                        if params.get("lm_head") is not None else None)
        # Host retarget map: emission task id -> compiled queue row, per
        # slot — attention rows carry their table DATA start in word 3.
        q0 = np.asarray(self.comp.queue)
        rows = self.comp.task_rows
        self._attn_rows: list[list[tuple[int, int, int, int]]] = []
        self._append_rows: list[list[tuple[int, int, int]]] = []
        for blk in self.prog.paged_meta["blocks"]:
            self._attn_rows.append(
                [(rows[tid], kt0, v0, int(q0[rows[tid], 3]))
                 for tid, kt0, v0 in blk.get("attn", ())])
            self._append_rows.append(
                [(rows[tid], kt0, v0)
                 for tid, kt0, v0 in blk.get("append", ())])
        self._base_queue = q0
        self._table_rows = -(-2 * max_pages // WORDS)
        self._step_jit = jax.jit(self._step, donate_argnums=(0, 1))
        self._load_jits: dict = {}  # (page count, offset) -> jitted loader
        self._copy_jit = None       # COW page-tile copy (copy_page)
        # Rope tables depend only on the integer position: cache the
        # COMPACT (TILE,) row per position (every row of the broadcast
        # table is identical) — ~1 KB per visited position instead of
        # 128 KB, so a long-lived server's cache stays bounded by
        # capacity * 1 KB; broadcast views expand at concat time.
        self._rope_cache: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        self.warm = False
        self.last_step_cold = True
        self.last_step_active = 0       # RUNNING slots in the last launch
        self.last_step_pages = 0        # mapped pool pages in the last launch
        self.last_step_rows = 0         # dispatched token-rows (ISSUE 19)
        self.last_step_live_rows = 0    # live (non-padding) rows
        # The last host-rewritten queue + the slot state it was derived
        # from, for analysis/mklint.py's paged-step checks (references,
        # not copies — _retarget already owns a fresh queue array).
        self.last_retarget: dict | None = None

    # -- workspace ----------------------------------------------------------
    def start(self):
        """Weights loaded, pools zeroed. Returns the carried workspace
        (donate it back through every step) — with ``kv_dtype``
        float8_e4m3fn, a ``(main, kv8)`` PAIR: the fp8 pool workspace
        rides alongside and both alias in place through the step."""
        main, _w8, wm = self.comp.split_feeds(dict(self._weight_feeds))
        self._wsm = (self.comp.make_workspace_mat(wm)
                     if self.comp.num_mrows else None)
        ws = self.comp.make_workspace(main)
        if self.kv_fp8:
            return ws, self.comp.make_workspace_kv8()
        return ws

    def load_prefill(self, ws, k_lin, v_lin, pages: list[int], *,
                     first_page: int = 0):
        """Scatter a finished prefill's KV into the slot's pool pages.
        ``k_lin``/``v_lin``: the linear prefill buffer (L, 1, S_buf,
        hkv, head_dim); page ``pages[i]`` receives positions
        [(first_page+i)*TILE, (first_page+i+1)*TILE) — ``first_page``
        skips a warm admission's shared prefix pages (already resident
        in the workspace and never to be rewritten; docs/serving.md
        "Prefix cache"). ONE jitted donated update per (page count,
        offset) — un-jitted per-tile scatters would each copy the whole
        (multi-GB at the bench shapes) workspace. fp8 pools quantize
        here through the SAME saturating cast the dense scatter uses
        (token parity across backends depends on the two quantizing
        identically)."""
        for p in pages:
            if not 0 <= int(p) < self.num_pages:
                raise ValueError(
                    f"page id {p} outside the usable pool "
                    f"[0, {self.num_pages}) — the scratch page is "
                    "reserved")
        if first_page < 0:
            raise ValueError(
                f"first_page = {first_page} invalid: the buffer offset "
                "counts skipped prefix pages — argument first_page")
        key = (len(pages), first_page)
        fn = self._load_jits.get(key)
        if fn is None:
            fn = jax.jit(functools.partial(self._load_pages, len(pages),
                                           first_page),
                         donate_argnums=(0,))
            self._load_jits[key] = fn
        pg = jnp.asarray(pages, jnp.int32)
        if self.kv_fp8:
            ws_main, wk8 = ws
            return ws_main, fn(wk8, k_lin, v_lin, pg)
        return fn(ws, k_lin, v_lin, pg)

    def copy_page(self, ws, src: int, dst: int):
        """One pool-page copy — the megakernel half of copy-on-write
        (docs/serving.md "Prefix cache"): page tables are DATA here, so
        COW is this host-side tile copy plus the allocator's row
        rewrite. Copies the (kT, v) tiles of every (layer, kv-head)
        pool from ``src`` to ``dst`` in the workspace that owns the KV
        pools (the fp8 KV workspace under ``kv_fp8``)."""
        for name, p in (("src", src), ("dst", dst)):
            if not 0 <= int(p) < self.num_pages:
                raise ValueError(
                    f"copy_page {name} page id {p} outside the usable "
                    f"pool [0, {self.num_pages}) — the scratch page is "
                    "reserved")
        fn = self._copy_jit
        if fn is None:
            fn = jax.jit(self._copy_page_impl, donate_argnums=(0,))
            self._copy_jit = fn
        s, d = jnp.int32(int(src)), jnp.int32(int(dst))
        if self.kv_fp8:
            ws_main, wk8 = ws
            return ws_main, fn(wk8, s, d)
        return fn(ws, s, d)

    def _copy_page_impl(self, ws, src, dst):
        for h in self.prog.layers:
            for kv in range(self.cfg.num_kv_heads):
                for handle in (h.kT[kv], h.v[kv]):
                    t0 = handle.tile(0, 0)
                    tile = jax.lax.dynamic_slice(
                        ws, (t0 + src, 0, 0), (1, TILE, TILE))
                    ws = jax.lax.dynamic_update_slice(
                        ws, tile, (t0 + dst, 0, 0))
        return ws

    def _load_pages(self, n_pages, first_page, ws, k_lin, v_lin, pages):
        # ``ws`` is the MAIN workspace normally, the fp8 KV pool
        # workspace under kv_fp8 (the pool tile ids index whichever
        # space the program allocated them in).
        from triton_distributed_tpu.models.fp8 import saturate_cast

        hd = self.cfg.head_dim
        dt = jnp.float8_e4m3fn if self.kv_fp8 else self.comp.dtype

        def cast(x):
            return saturate_cast(x, dt)
        for li, h in enumerate(self.prog.layers):
            for kv in range(self.cfg.num_kv_heads):
                kT0 = h.kT[kv].tile(0, 0)
                v0 = h.v[kv].tile(0, 0)
                for i in range(n_pages):
                    p = pages[i]
                    b = first_page + i      # buffer page (pool page p)
                    ksl = k_lin[li, 0, b * TILE:(b + 1) * TILE, kv, :]
                    vsl = v_lin[li, 0, b * TILE:(b + 1) * TILE, kv, :]
                    kT = ksl.astype(jnp.float32).T          # (hd, TILE)
                    vv = vsl.astype(jnp.float32)            # (TILE, hd)
                    if hd < TILE:
                        kT = jnp.pad(kT, ((0, TILE - hd), (0, 0)))
                        vv = jnp.pad(vv, ((0, 0), (0, TILE - hd)))
                    ws = jax.lax.dynamic_update_slice(
                        ws, cast(kT)[None], (kT0 + p, 0, 0))
                    ws = jax.lax.dynamic_update_slice(
                        ws, cast(vv)[None], (v0 + p, 0, 0))
        return ws

    # -- per-step host retarget ---------------------------------------------
    def _retarget(self, kv_lens, tables, wins=None) -> jax.Array:
        """Rewrite the compiled queue for this step's slot states:
        kv_lens (B,) ints; tables (B, <=max_pages) pool page ids per
        slot (missing/negative entries ride the scratch page); ``wins``
        (spec programs only): per-slot candidate-window sizes in
        [1, spec_window] — the step appends ``win`` positions and the
        attention rows fold the fresh window causally."""
        spec = self.spec_w > 1
        if wins is None:
            wins = [1] * self.num_slots
        q = self._base_queue.copy()
        for b in range(self.num_slots):
            kvl = int(kv_lens[b])
            win = int(wins[b])
            if not 1 <= win <= self.spec_w:
                raise ValueError(
                    f"slot {b} window {win} outside [1, {self.spec_w}] — "
                    "the program was compiled for spec_window = "
                    f"{self.spec_w}")
            if kvl + win > self.capacity:
                raise ValueError(
                    f"slot {b} kv_len {kvl} (+ window {win}) at capacity "
                    f"{self.capacity}: the step appends these positions "
                    "— evict or stop the sequence (serving scheduler "
                    "contract)")
            pages = [int(p) for p in tables[b] if int(p) >= 0]
            ktiles = -(-kvl // TILE)
            if ktiles > len(pages):
                raise ValueError(
                    f"slot {b} kv_len {kvl} needs {ktiles} mapped pages "
                    f"but the table holds {len(pages)} — the scheduler's "
                    "page growth must run before decode")
            flat: list[int] = []
            for j in range(self.max_pages):
                p = pages[j] if j < len(pages) else self.scratch
                flat.append(p)
            for row, kt0, v0, trow in self._attn_rows[b]:
                q[row, 4] = ktiles
                q[row, 6] = kvl
                if spec:
                    q[row, 5] = win      # causal window fold (kernel.py)
                ent: list[int] = []
                for p in flat:
                    ent += [kt0 + p, v0 + p]
                ent += [0] * (-len(ent) % WORDS)
                q[trow:trow + self._table_rows] = np.asarray(
                    ent, np.int32).reshape(-1, WORDS)
            # Append target: the page(s) holding positions
            # [kv_len, kv_len + win). An ACTIVE slot whose append page is
            # unmapped must fail loudly — the write would silently land
            # on the shared scratch page and the token's KV would be lost
            # (the write-side twin of the read-coverage check above; idle
            # slots park on scratch by design).
            ti, col = kvl // TILE, kvl % TILE
            last_ti = (kvl + win - 1) // TILE
            if (kvl > 0 or pages) and last_ti >= len(pages):
                raise ValueError(
                    f"slot {b} appends at positions [{kvl}, {kvl + win}) "
                    f"(page index {last_ti}) but the table maps "
                    f"{len(pages)} page(s) — the scheduler's page growth "
                    "must run before decode")
            ap = flat[ti] if ti < len(flat) else self.scratch
            if not spec:
                for row, kt0, v0 in self._append_rows[b]:
                    q[row, 1] = kt0 + ap
                    q[row, 3] = v0 + ap
                    q[row, 8] = col
            else:
                # Spec programs emit append rows in (primary, spill)
                # PAIRS per (layer, kv head): the primary takes the first
                # n1 window rows at columns col.., the spill takes the
                # remainder at columns 0.. of the NEXT page tile (parked
                # via c0 = -1 when the window stays inside one tile).
                n1 = min(win, TILE - col)
                rest = win - n1
                ap2 = (flat[ti + 1] if ti + 1 < len(flat)
                       else self.scratch)
                rows_b = self._append_rows[b]
                for i in range(0, len(rows_b), 2):
                    row, kt0, v0 = rows_b[i]
                    q[row, 1] = kt0 + ap
                    q[row, 3] = v0 + ap
                    q[row, 8] = col
                    q[row, 4] = n1       # window count (kernel.py)
                    q[row, 7] = 0        # source row offset
                    row2, kt0b, v0b = rows_b[i + 1]
                    if rest > 0:
                        q[row2, 1] = kt0b + ap2
                        q[row2, 3] = v0b + ap2
                        q[row2, 8] = 0
                        q[row2, 4] = rest
                        q[row2, 7] = n1
                    else:
                        q[row2, 8] = -1  # skip (c0 < 0)
                        q[row2, 4] = 0
                        q[row2, 7] = 0
        self.last_retarget = {
            "queue": q,
            "kv_lens": [int(kv_lens[b]) for b in range(self.num_slots)],
            "tables": [[int(p) for p in tables[b]]
                       for b in range(self.num_slots)],
            "wins": [int(w) for w in wins],
        }
        return jnp.asarray(q)

    def _rope(self, pos: int) -> tuple[np.ndarray, np.ndarray]:
        t = self._rope_cache.get(pos)
        if t is None:
            cos_t, sin_t = rope_tables(pos, self.cfg.head_dim,
                                       self.cfg.rope_theta)
            t = (cos_t[0].copy(), sin_t[0].copy())    # compact rows
            self._rope_cache[pos] = t
        return t

    # -- one step over every slot --------------------------------------------
    def _step(self, ws, wk8, embed, final_norm, lm_head, queue, cos, sin,
              tokens):
        # embed / final_norm / lm_head arrive as ARGUMENTS (the bench.py
        # closed-over-constant hazard). Row b*TILE of block b carries the
        # slot's real token; under spec_window = W > 1 rows b*TILE..
        # b*TILE+W-1 carry the slot's candidate window (last accepted
        # token + drafts); the other rows are padding lanes whose outputs
        # are discarded. ``wk8``: the fp8 KV pool workspace (None unless
        # kv_fp8 — a STATIC branch, like the program form).
        hidden = self.cfg.hidden_size
        B = self.num_slots
        W = self.spec_w
        if W == 1:
            rows = embed[tokens].astype(jnp.float32)        # (B, hidden)
            x = jnp.zeros((B * TILE, hidden), jnp.float32
                          ).at[jnp.arange(B) * TILE].set(rows)
        else:
            rows = embed[tokens.reshape(-1)].astype(jnp.float32)
            idx = (jnp.arange(B)[:, None] * TILE
                   + jnp.arange(W)[None, :]).reshape(-1)
            x = jnp.zeros((B * TILE, hidden), jnp.float32
                          ).at[idx].set(rows)
        ws = self.comp.scatter_input(ws, self.prog.x, x)
        ws = self.comp.scatter_input(ws, self.prog.cos, cos)
        ws = self.comp.scatter_input(ws, self.prog.sin, sin)
        if wk8 is None:
            ws = self.comp.step(ws, queue, wsm=self._wsm)
        else:
            ws, wk8 = self.comp.step(ws, queue, wsm=self._wsm, wkv8=wk8)
        outs = [self.comp.gather_output(ws, h)[0:W]
                for h in self.prog.x_out_blocks]
        x_out = jnp.concatenate(outs, axis=0)           # (B·W, hidden)
        xn = rms_norm(x_out.astype(jnp.float32),
                      final_norm.astype(jnp.float32),
                      self.cfg.rms_norm_eps)
        head = lm_head if lm_head is not None else embed.T
        logits = xn @ head.astype(jnp.float32)
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if W > 1:
            tok = tok.reshape(B, W)
        return ws, wk8, tok

    def step(self, ws, tokens, kv_lens, tables, wins=None):
        """One decode step over every slot. tokens: (B,) int32 (idle
        slots: any id — their lane is discarded); kv_lens: (B,) host
        ints (0 = idle); tables: (B, <=max_pages) pool page ids.
        Returns (workspace', next_tokens (B,)) — the workspace is the
        ``(main, kv8)`` pair under kv_fp8, exactly as start() returned
        it.

        Spec programs (``spec_window`` = W > 1): tokens is (B, W) — the
        last accepted token + drafts per slot, ``wins`` (B,) the live
        window per slot (rows past it are padding; 1 = plain one-token
        decode for that slot) — and the return is (B, W) verifier
        tokens, column j the greedy next-token after consuming the
        window prefix 0..j (feed models/sampling.accept_longest_prefix).
        """
        # The host queue-word / page-table rewrite gets its own
        # step-phase slice (ISSUE 18): under the serving loop it runs
        # nested inside the ``decode_dispatch`` phase and telescopes out
        # — the first number the megakernel's retarget cost shows up in.
        with obs_stepprof.phase("retarget"):
            queue = self._retarget(kv_lens, tables, wins)
        if self.spec_w == 1:
            tabs = [self._rope(int(kv_lens[b]))
                    for b in range(self.num_slots)]
            cos = np.concatenate(
                [np.broadcast_to(t[0], (TILE, TILE)) for t in tabs],
                axis=0)
            sin = np.concatenate(
                [np.broadcast_to(t[1], (TILE, TILE)) for t in tabs],
                axis=0)
        else:
            # Per-ROW positions: row i of slot b rotates at kv_len + i
            # for i < win; rows past the window broadcast the last real
            # position (their k/v are never appended or folded) — O(win)
            # cache lookups per slot, not O(TILE).
            cos_rows, sin_rows = [], []
            for b in range(self.num_slots):
                kvl = int(kv_lens[b])
                win = int(wins[b]) if wins is not None else 1
                per = [self._rope(kvl + i) for i in range(win)]
                pad = np.broadcast_to(per[-1][0], (TILE - win, TILE))
                cos_rows.append(np.stack([t[0] for t in per]))
                cos_rows.append(pad)
                sin_rows.append(np.stack([t[1] for t in per]))
                sin_rows.append(np.broadcast_to(per[-1][1],
                                                (TILE - win, TILE)))
            cos = np.concatenate(cos_rows, axis=0)
            sin = np.concatenate(sin_rows, axis=0)
        self.last_step_cold = not self.warm
        # Step-hook accounting for the request tracer / flight recorder
        # (ISSUE 13): active slots + mapped pages this launch — the
        # serving loop attributes the step to its requests, this span
        # tells the merged timeline what the ONE launch actually carried.
        active = int(sum(1 for b in range(self.num_slots)
                         if int(kv_lens[b]) > 0))
        pages_mapped = int(sum(1 for row in tables for p in row
                               if int(p) >= 0))
        self.last_step_active = active
        self.last_step_pages = pages_mapped
        # Goodput launch accounting (ISSUE 19, obs/goodput.py): the
        # persistent program dispatches every slot's FULL compiled
        # window every step (num_slots × spec_w rows — padding rides
        # the blocks whether or not a slot is live), and the live rows
        # are the per-slot windows of slots with mapped KV. The serving
        # loop's work ledger attributes from THESE numbers, so the
        # lane's real dispatch shape — not an assumption about it — is
        # what the partition invariant checks.
        self.last_step_rows = self.num_slots * self.spec_w
        self.last_step_live_rows = int(sum(
            (int(wins[b]) if wins is not None else 1)
            for b in range(self.num_slots) if int(kv_lens[b]) > 0))
        ws_main, wk8 = (ws if self.kv_fp8 else (ws, None))
        with obs_trace.span("mk_paged_step", slots=self.num_slots,
                            active=active, pages_mapped=pages_mapped):
            ws_main, wk8, tok = self._step_jit(
                ws_main, wk8, self.embed, self.final_norm,
                self.lm_head, queue, jnp.asarray(cos),
                jnp.asarray(sin),
                jnp.asarray(np.asarray(tokens), jnp.int32))
        self.warm = True
        return ((ws_main, wk8) if self.kv_fp8 else ws_main), tok
