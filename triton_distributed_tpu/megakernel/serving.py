"""Megakernel serving — dense/HF model params in, a decode backend out.

Reference: ``mega_triton_kernel/models/qwen3.py`` (HF weights feeding the
persistent-kernel task graph) + ``model_server.py`` (the serving loop that
replays it — the 3.33 ms headline path, BASELINE.md). Round-2 VERDICT #5:
the megakernel ran only random-feed benches; this module loads real model
params (models/hf_loader.py or init_dense_llm) into DecodeLayerHandles
feeds and exposes the decode loop the Engine drives.

Flow: Engine prefills with the fast batched dense path (linear KV cache),
then the cache is transposed into the megakernel's per-head kT/v workspace
regions and every subsequent token is ONE pallas_call (plus embed/lm_head,
which stay outside the kernel exactly like the reference keeps sampling
host-side). The per-step k/v append is a functional workspace column/row
update — the host-side analog of the reference's in-kernel KV append (a
deliberate design delta, see megakernel/models.py docstring).

Single-device view (TP=1): the multi-rank megakernel path (in-kernel AR
tasks) is exercised by tests/test_megakernel_decode.py::test_decode_step_tp8;
serving glue targets the one-chip case the benchmark ladder measures.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from triton_distributed_tpu.layers.common import rms_norm
from triton_distributed_tpu.megakernel.models import (
    DecodeStepProgram, advance_queue_pos, broadcast_rows, build_decode_step,
    rope_tables,
)
from triton_distributed_tpu.megakernel.tasks import TILE
from triton_distributed_tpu.models.config import ModelConfig


def validate_megakernel_cfg(cfg: ModelConfig, max_seq: int) -> None:
    if cfg.head_dim != TILE:
        raise ValueError(f"megakernel needs head_dim == {TILE} "
                         f"(got {cfg.head_dim})")
    if cfg.hidden_size % TILE or cfg.intermediate_size % TILE:
        raise ValueError("hidden/intermediate sizes must be TILE multiples")
    if max_seq % TILE:
        raise ValueError("max_seq must be a TILE multiple")
    if cfg.is_moe:
        raise ValueError("megakernel serving covers the dense stack")


def weight_feeds(prog: DecodeStepProgram, cfg: ModelConfig,
                 params: dict) -> dict:
    """Map a dense param tree (init_dense_llm / hf_loader layout) onto the
    program's workspace handles. Global view == per-device view at TP=1."""
    feeds: dict = {}
    for h, layer in zip(prog.layers, params["layers"]):
        attn = layer["attn"]
        feeds[h.attn_norm] = broadcast_rows(np.asarray(
            layer["attn_norm"], np.float32))
        feeds[h.mlp_norm] = broadcast_rows(np.asarray(
            layer["mlp_norm"], np.float32))
        qn = (np.asarray(attn["q_norm"], np.float32) if cfg.qk_norm
              else np.ones(cfg.head_dim, np.float32))
        kn = (np.asarray(attn["k_norm"], np.float32) if cfg.qk_norm
              else np.ones(cfg.head_dim, np.float32))
        feeds[h.q_norm] = broadcast_rows(qn)
        feeds[h.k_norm] = broadcast_rows(kn)
        feeds[h.wq] = attn["wq"]
        feeds[h.wk] = attn["wk"]
        feeds[h.wv] = attn["wv"]
        feeds[h.wo] = attn["wo"]
        mlp = layer["mlp"]
        feeds[h.w_gate] = mlp["w_gate"]
        feeds[h.w_up] = mlp["w_up"]
        feeds[h.w_down] = mlp["w_down"]
    return feeds


def cache_feeds(prog: DecodeStepProgram, cache) -> dict:
    """KV cache (models/kv_cache.KVCache, batch 1) → per-head kT/v feeds."""
    feeds: dict = {}
    k, v = cache.k, cache.v    # (L, 1, S, hkv, d)
    for li, h in enumerate(prog.layers):
        for kv in range(len(h.kT)):
            feeds[h.kT[kv]] = k[li, 0, :, kv, :].T      # (d, S)
            feeds[h.v[kv]] = v[li, 0, :, kv, :]         # (S, d)
    return feeds


class MegakernelDecoder:
    """One-chip decode loop over the compiled megakernel.

    Build once per (cfg, max_seq); ``start(cache)`` loads a prefilled KV
    cache into the workspace; ``step`` runs one token (jitted once — the
    queue is retargeted per position without recompiling,
    megakernel/models.py advance_queue_pos).
    """

    def __init__(self, cfg: ModelConfig, params: dict, *, max_seq: int,
                 dtype=jnp.float32):
        validate_megakernel_cfg(cfg, max_seq)
        self.cfg = cfg
        self.max_seq = max_seq
        self.prog = build_decode_step(
            hidden=cfg.hidden_size, hq_local=cfg.num_heads,
            hkv_local=cfg.num_kv_heads, ffn_local=cfg.intermediate_size,
            num_layers=cfg.num_layers, max_seq=max_seq,
            pos=max_seq - 1, num_ranks=1, eps=cfg.rms_norm_eps)
        self.comp = self.prog.mb.compile(dtype=dtype)
        self._weights = weight_feeds(self.prog, cfg, params)
        self.embed = params["embed"]
        self.final_norm = params["final_norm"]
        self.lm_head = params.get("lm_head")
        # Donate the workspace: it is ALL the weights + KV — without
        # donation every token would pay a whole-workspace device copy.
        self._step_jit = jax.jit(self._step, donate_argnums=(0,))

    # -- workspace ----------------------------------------------------------
    def start(self, cache) -> jax.Array:
        """Workspace with weights + the prefilled KV cache loaded."""
        if cache.k.shape[1] != 1:
            raise ValueError("megakernel decode is batch-1 "
                             f"(cache batch {cache.k.shape[1]})")
        if cache.max_seq != self.max_seq:
            raise ValueError(f"cache max_seq {cache.max_seq} != decoder "
                             f"max_seq {self.max_seq}")
        feeds = dict(self._weights)
        feeds.update(cache_feeds(self.prog, cache))
        return self.comp.make_workspace(feeds)

    # -- one token ----------------------------------------------------------
    def _append_kv(self, ws: jax.Array, pos: jax.Array) -> jax.Array:
        """Write this step's (normed+roped) k / raw v — produced by the
        kernel into the k_new/v_new handles — into the cache regions at
        column/row ``pos`` (functional update, jit-traced)."""
        d = TILE
        tile_i, intra = pos // TILE, pos % TILE
        for h in self.prog.layers:
            k_new = self.comp.gather_output(ws, h.k_new)[0]   # (hkv*d,)
            v_new = self.comp.gather_output(ws, h.v_new)[0]
            for kv in range(len(h.kT)):
                kcol = k_new[kv * d:(kv + 1) * d].astype(ws.dtype)
                vrow = v_new[kv * d:(kv + 1) * d].astype(ws.dtype)
                ws = ws.at[h.kT[kv].base + tile_i, :, intra].set(kcol)
                ws = ws.at[h.v[kv].base + tile_i, intra, :].set(vrow)
        return ws

    def _step(self, ws, embed, final_norm, lm_head, queue, cos, sin, token,
              pos):
        # embed / final_norm / lm_head arrive as ARGUMENTS: closed over,
        # jit would bake them into the trace as inline constants (multi-GB
        # for real checkpoints — the exact hazard bench.py documents).
        x_row = embed[token[0]].astype(jnp.float32)            # (hidden,)
        x = jnp.zeros((TILE, self.cfg.hidden_size), jnp.float32
                      ).at[0].set(x_row)
        ws = self.comp.scatter_input(ws, self.prog.x, x)
        ws = self.comp.scatter_input(ws, self.prog.cos, cos)
        ws = self.comp.scatter_input(ws, self.prog.sin, sin)
        ws = self.comp.step(ws, queue)
        ws = self._append_kv(ws, pos)
        x_out = self.comp.gather_output(ws, self.prog.x_out)[0:1]
        xn = rms_norm(x_out.astype(jnp.float32),
                      final_norm.astype(jnp.float32),
                      self.cfg.rms_norm_eps)
        head = lm_head if lm_head is not None else embed.T
        logits = xn @ head.astype(jnp.float32)
        return ws, jnp.argmax(logits, axis=-1).astype(jnp.int32)

    def step(self, ws: jax.Array, token: jax.Array, pos: int):
        """token: (1,) int32; pos: host int (current cache length). Returns
        (workspace', next_token (1,))."""
        if pos >= self.max_seq:
            raise ValueError(
                f"pos {pos} >= max_seq {self.max_seq}: the step appends "
                "this position's k/v — past capacity it would write into "
                "the adjacent workspace region")
        queue = advance_queue_pos(self.comp.queue, pos,
                                  num_exec=self.comp.num_exec)
        cos, sin = rope_tables(pos, TILE, self.cfg.rope_theta)
        return self._step_jit(ws, self.embed, self.final_norm, self.lm_head,
                              queue, jnp.asarray(cos), jnp.asarray(sin),
                              token, jnp.int32(pos))
