"""Persistent single-kernel runtime (reference:
``python/triton_dist/mega_triton_kernel/``)."""

from triton_distributed_tpu.megakernel.tasks import (  # noqa: F401
    TILE,
    Task,
    TaskType,
    TensorHandle,
)
from triton_distributed_tpu.megakernel.builder import (  # noqa: F401
    MegaKernelBuilder,
    CompiledMegaKernel,
)
from triton_distributed_tpu.megakernel.scheduler import (  # noqa: F401
    topo_schedule,
    using_native_scheduler,
)
