"""Distributed flash-decode — split-KV GQA decode with inter-rank combine.

Reference: ``python/triton_dist/kernels/nvidia/flash_decode.py`` — local
split-kv kernels (:129), intra-rank combine, and the inter-rank LSE/acc
combine (:482 ``kernel_inter_rank_gqa_fwd_batch_decode_combine_kv``) that
pulls per-rank partial (lse, acc) via low-latency AG; persistent variant
(:1095). This is the SP/CP decode path: the KV cache is sharded over ranks
along the *sequence* axis, every rank attends its shard, and the partials
merge with log-sum-exp rescaling.

TPU design: the per-shard partial attention is a Pallas split-KV kernel —
the paged decode kernel's page-walk + online-softmax machinery run over a
linear-chunk view of the shard (each KV chunk is a "page" of an
identity-mapped table), so long shards decode in flat memory with per-chunk
DMA instead of a materialized (B, hq, S_shard) logits tensor. The tiny
per-rank (acc, lse) partials — (B, hq, d+2) floats — ride either the Pallas
one-shot AllGather (``method="pallas"``, the low-latency AG use case) or
``jax.lax.all_gather`` (``method="xla"``, golden), then combine in fp32.
A dense jnp fallback remains for tiny/odd shard shapes.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import all_gather_local, AllGatherMethod
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _splitkv_chunk(s: int, hkv: int, d: int, itemsize: int) -> int | None:
    """Chunk size for the Pallas split-KV walk, or None for the dense
    fallback. Chunks are divisor-aligned (the linear pool view is a free
    reshape) and sized so two chunk buffers fit comfortably in VMEM."""
    from triton_distributed_tpu.ops.tiling import pick_tile

    if d % 128 or s < 16:
        return None
    c = pick_tile(s, 512, 8)
    if 2 * c * hkv * d * itemsize > 4 * 1024 * 1024:
        return None
    return c


def _partial_decode_attn(q, k, v, kv_len):
    """Partial GQA attention over one KV shard — Pallas split-KV kernel
    (reference flash_decode.py:129-481) with a dense fallback.

    q: (B, hq, d); k/v: (B, S_shard, hkv, d); kv_len: valid rows (traced).
    Returns acc (B, hq, d) fp32 = Σ softmax-numerator · v (UNnormalized,
    max-subtracted), lse-parts (m, l): running max (B, hq) and sum-exp (B, hq).
    """
    from triton_distributed_tpu.ops.paged_attention import (
        PagedKVCache, paged_decode_attention,
    )

    b, hq, d = q.shape
    s, hkv = k.shape[1], k.shape[2]
    chunk = _splitkv_chunk(s, hkv, d, k.dtype.itemsize)
    if chunk is not None:
        nch = s // chunk
        # Linear shard viewed as an identity-paged pool: chunk j of batch i
        # is pool page i·nch + j — contiguity-preserving reshape, no copy.
        pool_view = lambda x: x.reshape(b * nch, chunk, hkv, d)
        table = jnp.arange(b * nch, dtype=jnp.int32).reshape(b, nch)
        lens = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
        cache = PagedKVCache(pool_view(k), pool_view(v), table, lens)
        acc, m, l = paged_decode_attention(q, cache, normalize=False)
        # Dead shards: match the dense path's m_safe=0 convention.
        return acc, jnp.where(l > 0, m, 0.0), l
    group = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, hkv, group, d)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    logits = jnp.einsum("bhgd,bshd->bhgs", qf, kf) / math.sqrt(d)
    valid = jnp.arange(s) < kv_len
    logits = jnp.where(valid[None, None, None], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)                      # (b, hkv, g)
    # All-invalid shard: keep math finite; l=0 marks it dead in the combine.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    p = jnp.where(valid[None, None, None], p, 0.0)
    l = jnp.sum(p, axis=-1)                            # (b, hkv, g)
    acc = jnp.einsum("bhgs,bshd->bhgd", p, vf)         # (b, hkv, g, d)
    return (acc.reshape(b, hq, d), m_safe.reshape(b, hq),
            l.reshape(b, hq))


def combine_partials(accs, ms, ls):
    """Merge split-KV partials over axis 0 (the reference's combine kernel,
    flash_decode.py:482-566): online log-sum-exp across splits.

    accs: (n, B, hq, d); ms/ls: (n, B, hq). Returns (B, hq, d) fp32.
    """
    m_all = jnp.max(jnp.where(ls > 0, ms, -jnp.inf), axis=0)   # (B, hq)
    m_all = jnp.where(jnp.isfinite(m_all), m_all, 0.0)
    scale = jnp.exp(ms - m_all[None]) * (ls > 0)               # (n, B, hq)
    l_tot = jnp.sum(ls * scale, axis=0)                        # (B, hq)
    acc = jnp.sum(accs * scale[..., None], axis=0)             # (B, hq, d)
    return acc / jnp.maximum(l_tot, 1e-30)[..., None]


def flash_decode_local(q: jax.Array, k_shard: jax.Array, v_shard: jax.Array,
                       kv_len: jax.Array, *, axis: str = "tp",
                       num_ranks: int | None = None,
                       method: str = "pallas", ag_state=None):
    """Device-local distributed flash-decode inside shard_map.

    q: (B, hq, d) replicated; k_shard/v_shard: (B, S/n, hkv, d) — this
    rank's sequence shard; kv_len: valid rows in THIS shard (int32 scalar,
    may differ per rank). Returns (B, hq, d) fully-combined attention,
    replicated.

    ``ag_state``: (ws, call_index) from ops/allgather.ag_stream_workspace
    (shape (2, n·B·hq, d+2)) — the decode loop's barrier-free parity AG for
    the partials exchange (the reference's staged low-latency AG layer,
    sp_flash_decode_layer.py). When given, returns (out, ag_state').
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    if ag_state is not None and method != "pallas":
        raise ValueError(
            f"method={method!r} with ag_state: the stream AG would shadow "
            "the requested path — a golden comparison would compare the "
            "stream against itself. Pass one or the other.")
    n = num_ranks
    b, hq, d = q.shape
    acc, m, l = _partial_decode_attn(q, k_shard, v_shard, kv_len)
    if n == 1:
        out = (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
        return (out, ag_state) if ag_state is not None else out

    # Pack partials into one flat fp32 payload: (B·hq, d+2) → AG → combine.
    payload = jnp.concatenate(
        [acc.reshape(b * hq, d), m.reshape(b * hq, 1), l.reshape(b * hq, 1)],
        axis=1)
    if ag_state is not None:
        from triton_distributed_tpu.ops.allgather import all_gather_stream

        ws, idx = ag_state
        gathered, ws, idx = all_gather_stream(payload, ws, idx, axis=axis,
                                              num_ranks=n)
        gathered = gathered.reshape(n, b * hq, d + 2)
        ag_state = (ws, idx)
    elif method == "pallas":
        gathered = all_gather_local(payload, axis=axis, num_ranks=n,
                                    method=AllGatherMethod.FULL_MESH_PUSH)
        gathered = gathered.reshape(n, b * hq, d + 2)
    elif method == "xla":
        gathered = jax.lax.all_gather(payload, axis)
    else:
        raise ValueError(f"unknown method {method!r}")
    accs = gathered[..., :d].reshape(n, b, hq, d)
    ms = gathered[..., d].reshape(n, b, hq)
    ls = gathered[..., d + 1].reshape(n, b, hq)
    out = combine_partials(accs, ms, ls).astype(q.dtype)
    return (out, ag_state) if ag_state is not None else out


def flash_decode(q: jax.Array, k: jax.Array, v: jax.Array,
                 kv_lens: jax.Array, ctx: DistContext | None = None,
                 axis: str = "tp", method: str = "pallas") -> jax.Array:
    """Host-level distributed flash-decode.

    q: (B, hq, d) replicated; k/v: (B, n·S_shard, hkv, d) sequence-sharded
    over ``axis``; kv_lens: (n,) int32 valid rows per shard.
    Returns (B, hq, d) replicated.
    """
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, method, q.shape, k.shape, str(q.dtype))

    def make():
        fn = functools.partial(flash_decode_local, axis=axis, num_ranks=n,
                               method=method)

        def wrapped(ql, kl, vl, lens):
            return fn(ql, kl, vl, lens.reshape(()))

        return wrapped

    jfn = cached_shard_jit(
        ctx, "flash_decode", key, make,
        (P(), P(None, axis), P(None, axis), P(axis)), P())
    return jfn(q, k, v, kv_lens)
