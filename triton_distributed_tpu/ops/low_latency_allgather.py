"""Low-latency AllGather — decode-shaped small-message gathers.

Reference: ``python/triton_dist/kernels/nvidia/low_latency_allgather.py``
(987 LoC: pull / push-2d/3d / LL 8-byte flag+data protocol / multimem,
staged symmetric buffers) and the decode layer
``layers/nvidia/low_latency_allgather_layer.py:30-120``.

TPU collapse of the method space: ICI has uniform links and DMA-delivered
semaphores, so the LL flag+data protocol (which exists because separate
flag writes can pass data writes on NVLink) is unnecessary — a single
full-mesh push whose recv semaphore IS the flag is already the minimal
2-hop-free protocol. What remains valuable from the reference design:

- one fused kernel, no barrier-heavy generic path for tiny payloads;
- the *staged buffer* idea maps to shape-bucketing: decode token counts
  vary step to step, so ``AllGatherLayer`` pads to a bucket, reusing one
  compiled executable instead of recompiling per length
  (reference sp_flash_decode_layer.py:75-77 dynamic buffer shrink).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import (
    AllGatherMethod,
    all_gather_local,
)
from triton_distributed_tpu.ops.tiling import sublane_align
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def fast_allgather_local(x_local: jax.Array, *, axis: str = "tp",
                         num_ranks: int | None = None) -> jax.Array:
    """Device-local low-latency AllGather: always the single-hop full-mesh
    push (latency-optimal; reference ``fast_allgather``)."""
    return all_gather_local(x_local, axis=axis, num_ranks=num_ranks,
                            method=AllGatherMethod.FULL_MESH_PUSH)


def _bucket(m: int, align: int) -> int:
    """Smallest power-of-two multiple of ``align`` >= m (bounded recompiles
    over decode steps)."""
    b = align
    while b < m:
        b *= 2
    return b


class AllGatherLayer:
    """Decode comm layer: bucketed, cached low-latency AG
    (reference ``low_latency_allgather_layer.py:30-120`` — staged symmetric
    buffers + per-stage signals become shape buckets + the jit cache)."""

    def __init__(self, ctx: DistContext | None = None, axis: str = "tp"):
        self.ctx = ctx or get_context()
        self.axis = axis
        self.n = self.ctx.axis_size(axis)

    def __call__(self, x: jax.Array) -> jax.Array:
        """x: (m, cols) sharded rows over ``axis`` globally (m = n·m_local).
        Returns the gathered (m, cols) replicated. Pads m_local up to a
        bucket internally; the pad rows never leave the op."""
        n = self.n
        m, cols = x.shape
        m_local = m // n
        align = sublane_align(x.dtype)
        bucket = _bucket(max(m_local, 1), align)
        key = (self.axis, bucket, cols, str(x.dtype))

        def make():
            fn = functools.partial(fast_allgather_local, axis=self.axis,
                                   num_ranks=n)

            def padded(xl):
                pad = bucket - xl.shape[0]
                xp = jnp.pad(xl, ((0, pad), (0, 0)))
                return fn(xp).reshape(n, bucket, cols)

            return padded

        jfn = cached_shard_jit(self.ctx, "ll_allgather", key, make,
                               P(self.axis), P(None), ici_axes=(self.axis,))
        out = jfn(x)  # (n, bucket, cols) replicated
        return out[:, :m_local].reshape(m, cols)


def fast_allgather(x: jax.Array, ctx: DistContext | None = None,
                   axis: str = "tp") -> jax.Array:
    """One-shot host-level low-latency AllGather (layer-less convenience)."""
    return AllGatherLayer(ctx, axis)(x)
