"""Ulysses sequence parallelism — head-exchange AllToAll attention.

NOT in the reference (SURVEY.md §2.5 marks Ulysses "absent"; the reference
scales sequence length by KV-AllGather + split-KV flash-decode only). Added
here because the AllToAll head exchange is a better fit for TPU than for
the reference's stack: `jax.lax.all_to_all` lowers to a single ICI
all-to-all, and the per-device attention afterwards is a plain
full-sequence flash attention over a head shard — no waits, no symmetric
buffers.

Scheme (DeepSpeed-Ulysses): activations arrive sequence-sharded
(B, S/n, H, d). AllToAll exchanges the head and sequence axes so every
device holds ALL positions for H/n heads; attention runs dense per head
shard; a second AllToAll restores sequence sharding:

    (B, S/n, H, d) ── a2a(H→, ←S) ──> (B, S, H/n, d)
                  ── attention (full S, causal ok) ──
    (B, S, H/n, d) ── a2a(S→, ←H) ──> (B, S/n, H, d)

Communication volume is 2·B·S·H·d/n per device (vs the KV-AllGather's
B·S·H_kv·d·(n-1)/n each step) and, unlike ring attention, needs no
per-step softmax rescaling — at the price of requiring H % n == 0.
"""

from __future__ import annotations

import functools

import jax
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _sdpa(q, k, v, causal: bool, tiles=None):
    """Per-head-shard attention after the exchange: the tiled Pallas flash
    kernel (ops/flash_attention.py) on supported shapes, dense fallback on
    tiny/odd ones. q: (B, S, Hq, d); k/v (B, S, Hkv, d)."""
    from triton_distributed_tpu.ops.flash_attention import shard_attention

    return shard_attention(q, k, v, causal=causal, tiles=tiles)


def ulysses_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                            axis: str = "sp", num_ranks: int | None = None,
                            causal: bool = True,
                            tiles: tuple[int, int] | None = None) -> jax.Array:
    """Device-local Ulysses attention inside shard_map.

    q: (B, S/n, Hq, d); k/v: (B, S/n, Hkv, d) — sequence-sharded.
    Returns (B, S/n, Hq, d). Requires Hq % n == 0 and Hkv % n == 0.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1:
        return _sdpa(q, k, v, causal, tiles)
    hq, hkv = q.shape[2], k.shape[2]
    if hq % n or hkv % n:
        raise ValueError(f"heads ({hq}, {hkv}) not divisible by axis size {n}")

    # Head → sequence exchange: (B, S/n, H, d) -> (B, S, H/n, d).
    a2a = functools.partial(jax.lax.all_to_all, axis_name=axis,
                            split_axis=2, concat_axis=1, tiled=True)
    qg, kg, vg = a2a(q), a2a(k), a2a(v)
    out = _sdpa(qg, kg, vg, causal, tiles)
    # Inverse exchange restores sequence sharding.
    return jax.lax.all_to_all(out, axis_name=axis, split_axis=1,
                              concat_axis=2, tiled=True)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      ctx: DistContext | None = None, axis: str = "tp",
                      causal: bool = True) -> jax.Array:
    """Host-level Ulysses attention: q/k/v (B, S, h*, d) sharded on dim 1."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, causal, q.shape, k.shape, str(q.dtype))

    def make():
        # Post-exchange shapes: full S, heads/n — tile caps resolved at
        # host level, autotuned on-chip when tuning is on (VERDICT r3 #8).
        from triton_distributed_tpu.ops.flash_attention import (
            resolve_flash_tiles,
        )

        tiles = resolve_flash_tiles(q.shape[1], k.shape[1],
                                    max(q.shape[2] // n, 1),
                                    max(k.shape[2] // n, 1), q.shape[3],
                                    q.dtype)
        return functools.partial(ulysses_attention_local, axis=axis,
                                 num_ranks=n, causal=causal, tiles=tiles)

    spec = P(None, axis, None, None)
    jfn = cached_shard_jit(ctx, "ulysses_attention", key, make,
                           (spec, spec, spec), spec, ici_axes=(axis,))
    return jfn(q, k, v)
