"""Multi-axis ICI collectives — one kernel driving TWO torus axes.

Reference: the 2-D NUMA-aware rings of
``python/triton_dist/kernels/nvidia/allgather.py:140-262`` (intra-node 2-D
ring push) and ``:293-378`` (2-D inter-node combo): the reference splits its
rank grid into (NUMA group × intra-group) and keeps both link classes busy.
A TPU v5p slice is the same shape problem with better fabric: the ICI is a
physical 2-D/3-D torus (``runtime/topology.py``), and a collective that
drives only one named mesh axis leaves the other axes' links idle — round-4
VERDICT's top structural gap (#4).

Method space (single kernel each, both axes live concurrently):

- ``all_gather_torus``: pipelined ring-of-rings AG. The inner-axis ring
  gathers this device's row of shards; *as each shard lands it is
  immediately forwarded onto the outer-axis ring* — inner and outer links
  run concurrently, so wall time ≈ max(inner phase, outer phase) instead of
  their sum. Rank order is row-major over (outer, inner), matching
  ``P((ax0, ax1))`` sharding.
- ``all_reduce_torus(method="one_shot")``: hierarchical one-shot — one-shot
  AR along the inner axis, then one-shot of the reduced block along the
  outer axis, in one kernel. Two hops of m bytes per link class vs the flat
  one-shot's (n-1) pushes that must physically route *through* intermediate
  torus chips (oversubscribing links the flat method pretends are
  point-to-point): the latency class for decode activations on a 2-D mesh.
- ``all_reduce_torus(method="two_shot")``: reduce_scatter_torus +
  all_gather_torus — the bandwidth class.
- ``reduce_scatter_torus``: outer-axis ring RS on super-chunks, then
  inner-axis ring RS — each phase keeps every link of its axis busy; phases
  are sequential because reduction carries a true dependency.

Degenerate meshes (either axis of size 1) fall back to the 1-D kernels, and
``n0 == n1 == 1`` is the identity — the single-axis-degenerate contract the
on-chip compile gate checks.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.ops.allreduce import _reduce_slots
from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


# ---------------------------------------------------------------------------
# AllGather: pipelined ring-of-rings.
# ---------------------------------------------------------------------------

def _ag_torus_kernel(n0: int, n1: int, ax0: str, ax1: str, m: int,
                     x_ref, out_ref,
                     y_send_sems, x_send_sems, y_recv_sem, x_recv_sems,
                     copy_sem):
    """Shard S(a,b) lands at out rows [(a·n1+b)·m, …). Schedule:

    - inner (ax1) ring, step t: forward own-row shard S(a, b-t) right —
      the 1-D ring of ops/allgather.py on the inner links.
    - outer (ax0) ring, round (u, t): forward S(a-u, b-t) right along ax0.
      Round (0, t) fires the moment S(a, b-t) exists locally (own shard at
      t=0, else the y-delivery just waited) — this is the pipelining: the
      outer ring starts n1-1 steps before the inner ring finishes.

    Ordering invariants: deliveries between one (src, dst) pair arrive in
    issue order (the same assumption the 1-D ring forwards on), and each
    outer-ring chunk class t has its own recv semaphore so classes never
    miscount each other. Send-semaphore slots are reused across u-rounds
    only after ``wait_send`` of the previous round.
    """
    a = dl.rank(ax0)
    b = dl.rank(ax1)
    shmem.barrier_grid((ax0, ax1))
    right0 = jax.lax.rem(a + 1, n0)
    right1 = jax.lax.rem(b + 1, n1)

    def slot(row, col):
        return out_ref.at[pl.ds((row * n1 + col) * m, m)]

    own = slot(a, b)
    local = pltpu.make_async_copy(x_ref, own, copy_sem)
    local.start()
    local.wait()

    x_handles: list = [None] * n1
    y_handles: list = [None] * max(n1 - 1, 1)
    # Inner ring step t interleaved with outer round (0, t).
    for t in range(n1):
        c = jax.lax.rem(b - t + n1, n1)
        s_c = slot(a, c)
        if t > 0:
            shmem.wait_deliveries(x_ref, y_recv_sem, 1)
        if t < n1 - 1:
            y_handles[t] = shmem.putmem_nbi_block(
                s_c, s_c, y_send_sems.at[t], y_recv_sem, right1, ax1)
        if n0 > 1:
            x_handles[t] = shmem.putmem_nbi_block(
                s_c, s_c, x_send_sems.at[t], x_recv_sems.at[t], right0, ax0)
    # Outer rounds u >= 1: relay what the left x-neighbor delivered.
    for u in range(1, n0 - 1):
        for t in range(n1):
            c = jax.lax.rem(b - t + n1, n1)
            row = jax.lax.rem(a - u + n0, n0)
            s_rc = slot(row, c)
            shmem.wait_deliveries(x_ref, x_recv_sems.at[t], 1)
            x_handles[t].wait_send()
            x_handles[t] = shmem.putmem_nbi_block(
                s_rc, s_rc, x_send_sems.at[t], x_recv_sems.at[t], right0,
                ax0)
    # Final arrivals: one un-consumed delivery per chunk class (round
    # u = n0-1's incoming relay), then drain sends.
    if n0 > 1:
        for t in range(n1):
            shmem.wait_deliveries(x_ref, x_recv_sems.at[t], 1)
        for h in x_handles:
            if h is not None:
                h.wait_send()
    for h in y_handles:
        if h is not None:
            h.wait_send()


def all_gather_torus_local(x_local: jax.Array, *, axes: tuple[str, str],
                           dims: tuple[int, int]) -> jax.Array:
    """Device-local 2-axis AllGather inside shard_map. ``x_local``:
    (m, cols) → (n0·n1·m, cols), rank-major over (axes[0], axes[1])."""
    ax0, ax1 = axes
    n0, n1 = dims
    if n0 * n1 == 1:
        return x_local
    if n0 == 1 or n1 == 1:
        from triton_distributed_tpu.ops.allgather import (
            AllGatherMethod, all_gather_local,
        )

        axis, n = (ax1, n1) if n0 == 1 else (ax0, n0)
        return all_gather_local(x_local, axis=axis, num_ranks=n,
                                method=AllGatherMethod.RING_1D)
    m, cols = x_local.shape
    kernel = functools.partial(_ag_torus_kernel, n0, n1, ax0, ax1, m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n0 * n1 * m, cols), x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n1 - 1, 1),)),   # inner sends
            pltpu.SemaphoreType.DMA((n1,)),               # outer sends
            pltpu.SemaphoreType.DMA(()),                  # inner recv
            pltpu.SemaphoreType.DMA((n1,)),               # outer recv/class
            pltpu.SemaphoreType.DMA(()),                  # local copy
        ],
        uses_barrier=True,
    )(x_local)


# ---------------------------------------------------------------------------
# AllReduce: hierarchical one-shot (latency) / RS+AG composition (bandwidth).
# ---------------------------------------------------------------------------

def _ar_one_shot_torus_kernel(n0: int, n1: int, ax0: str, ax1: str,
                              m: int, tile_m: int,
                              x_ref, out_ref, ws1, ws0, mid, va, vacc,
                              y_send_sems, x_send_sems, y_recv_sem,
                              x_recv_sem, copy_sem):
    """Phase 1: one-shot AR along ax1 (push into slot b of every inner
    peer's ws1, reduce → mid). Phase 2: the same along ax0 on the reduced
    block (ws0, slot a → out). Each phase is the 1-D one-shot of
    ops/allreduce.py:61; the hierarchy keeps every push a single physical
    hop on its torus ring."""
    a = dl.rank(ax0)
    b = dl.rank(ax1)
    shmem.barrier_grid((ax0, ax1))

    # Phase 1 (inner axis).
    local = pltpu.make_async_copy(x_ref, ws1.at[b], copy_sem)
    local.start()
    handles = []
    for i in range(n1 - 1):
        peer = jax.lax.rem(b + 1 + i, n1)
        handles.append(shmem.putmem_nbi_block(
            x_ref, ws1.at[b], y_send_sems.at[i], y_recv_sem, peer, ax1))
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, y_recv_sem, n1 - 1)
    _reduce_slots(n1, m, tile_m, ws1, mid, va, vacc, copy_sem)

    # Phase 2 (outer axis) on the inner-reduced block.
    local = pltpu.make_async_copy(mid, ws0.at[a], copy_sem)
    local.start()
    handles = []
    for i in range(n0 - 1):
        peer = jax.lax.rem(a + 1 + i, n0)
        handles.append(shmem.putmem_nbi_block(
            mid, ws0.at[a], x_send_sems.at[i], x_recv_sem, peer, ax0))
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, x_recv_sem, n0 - 1)
    _reduce_slots(n0, m, tile_m, ws0, out_ref, va, vacc, copy_sem)


def all_reduce_torus_local(x_local: jax.Array, *, axes: tuple[str, str],
                           dims: tuple[int, int],
                           method: str = "one_shot") -> jax.Array:
    """Device-local 2-axis AllReduce inside shard_map. ``x_local``:
    (m, cols) → (m, cols) summed over the n0·n1 grid. ``method``:
    one_shot (hierarchical, latency class), two_shot (RS+AG, bandwidth
    class), or auto (one_shot on a real grid; 1-D cost-model AUTO on
    degenerate meshes)."""
    ax0, ax1 = axes
    n0, n1 = dims
    if n0 * n1 == 1:
        return x_local
    if n0 == 1 or n1 == 1:
        # Degenerate mesh → the 1-D op, with "auto" preserved so its
        # cost-model selection (one/two-shot/tree) still runs.
        from triton_distributed_tpu.ops.allreduce import all_reduce_local

        axis, n = (ax1, n1) if n0 == 1 else (ax0, n0)
        return all_reduce_local(x_local, axis=axis, num_ranks=n,
                                method=method)
    if method == "auto":
        # On a real 2-D grid the hierarchical one-shot is the torus
        # method (every push a single physical hop; see module docstring).
        method = "one_shot"
    if method == "two_shot":
        total = n0 * n1
        m = x_local.shape[0]
        if m % total:
            raise ValueError(
                f"two_shot requires rows {m} divisible by n0*n1 {total}")
        scattered = reduce_scatter_torus_local(x_local, axes=axes,
                                               dims=dims)
        return all_gather_torus_local(scattered, axes=axes, dims=dims)
    if method != "one_shot":
        raise ValueError(f"unknown torus AR method {method!r}")
    m, cols = x_local.shape
    tile_m = pick_tile(m, 512, sublane_align(x_local.dtype))
    kernel = functools.partial(_ar_one_shot_torus_kernel, n0, n1, ax0, ax1,
                               m, tile_m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, cols), x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        workspaces=[
            jax.ShapeDtypeStruct((n1, m, cols), x_local.dtype),
            jax.ShapeDtypeStruct((n0, m, cols), x_local.dtype),
            jax.ShapeDtypeStruct((m, cols), x_local.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.VMEM((tile_m, cols), jnp.float32),
            pltpu.SemaphoreType.DMA((max(n1 - 1, 1),)),
            pltpu.SemaphoreType.DMA((max(n0 - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local)


# ---------------------------------------------------------------------------
# ReduceScatter: outer-ring RS on super-chunks, inner-ring RS on chunks.
# ---------------------------------------------------------------------------

def reduce_scatter_torus_local(x_local: jax.Array, *,
                               axes: tuple[str, str],
                               dims: tuple[int, int]) -> jax.Array:
    """Device-local 2-axis ReduceScatter inside shard_map. ``x_local``:
    (n0·n1·mo, cols) contributions → (mo, cols); device (a, b) owns chunk
    a·n1+b, summed over the whole grid.

    Phase 1: ring RS along ``axes[0]`` treating the rows as n0 super-chunks
    of n1·mo — afterwards this device holds super-chunk ``a`` summed over
    its torus column. Phase 2: ring RS of that block along ``axes[1]``.
    Phases reuse the flow-controlled 1-D ring kernel
    (ops/reduce_scatter._rs_ring_kernel); sequencing is a true data
    dependency (a chunk cannot leave on the inner ring before its outer
    reduction finished), so unlike the AG there is no cross-phase pipeline.
    """
    from triton_distributed_tpu.ops.reduce_scatter import (
        reduce_scatter_local,
    )

    ax0, ax1 = axes
    n0, n1 = dims
    if n0 * n1 == 1:
        return x_local
    if n0 == 1:
        return reduce_scatter_local(x_local, axis=ax1, num_ranks=n1)
    if n1 == 1:
        return reduce_scatter_local(x_local, axis=ax0, num_ranks=n0)
    mt = x_local.shape[0]
    if mt % (n0 * n1):
        raise ValueError(f"rows {mt} not divisible by n0*n1 {n0 * n1}")
    mid = reduce_scatter_local(x_local, axis=ax0, num_ranks=n0)
    return reduce_scatter_local(mid, axis=ax1, num_ranks=n1)


# ---------------------------------------------------------------------------
# Host-level wrappers (golden-testable; the layer composition point is the
# *_local family above).
# ---------------------------------------------------------------------------

def _resolve_axes(ctx: DistContext, axes) -> tuple[tuple[str, str],
                                                   tuple[int, int]]:
    if axes is None:
        names = tuple(ctx.mesh.axis_names)
        if len(names) != 2:
            raise ValueError(
                f"torus collectives need two mesh axes; mesh has {names} — "
                "pass axes=(outer, inner) explicitly on bigger meshes")
        axes = names
    ax0, ax1 = axes
    return (ax0, ax1), (ctx.axis_size(ax0), ctx.axis_size(ax1))


def all_gather_torus(x: jax.Array, ctx: DistContext | None = None,
                     axes: tuple[str, str] | None = None) -> jax.Array:
    """Host-level 2-axis AllGather: ``x`` (n0·n1·m, cols) sharded row-major
    over ``axes`` → replicated."""
    ctx = ctx or get_context()
    (ax0, ax1), dims = _resolve_axes(ctx, axes)
    key = ("ag_torus", ax0, ax1, x.shape, str(x.dtype))

    def make():
        return functools.partial(all_gather_torus_local, axes=(ax0, ax1),
                                 dims=dims)

    jfn = cached_shard_jit(ctx, "all_gather_torus", key, make,
                           P((ax0, ax1)), P(None),
                           ici_axes=(ax0, ax1))
    return jfn(x)


def all_reduce_torus(x: jax.Array, ctx: DistContext | None = None,
                     axes: tuple[str, str] | None = None,
                     method: str = "one_shot") -> jax.Array:
    """Host-level 2-axis AllReduce: ``x`` (n0, n1, m, cols) stacked
    contributions → replicated (m, cols) sum."""
    ctx = ctx or get_context()
    (ax0, ax1), dims = _resolve_axes(ctx, axes)
    key = ("ar_torus", ax0, ax1, method, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(all_reduce_torus_local, axes=(ax0, ax1),
                               dims=dims, method=method)
        return lambda xl: fn(xl[0, 0])

    jfn = cached_shard_jit(ctx, "all_reduce_torus", key, make,
                           P(ax0, ax1), P(None, None),
                           ici_axes=(ax0, ax1))
    return jfn(x)


def reduce_scatter_torus(x: jax.Array, ctx: DistContext | None = None,
                         axes: tuple[str, str] | None = None) -> jax.Array:
    """Host-level 2-axis ReduceScatter: ``x`` (n0, n1, N·mo, cols) stacked
    contributions (N = n0·n1) → (N·mo, cols) scattered row-major over
    ``axes``."""
    ctx = ctx or get_context()
    (ax0, ax1), dims = _resolve_axes(ctx, axes)
    key = ("rs_torus", ax0, ax1, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(reduce_scatter_torus_local, axes=(ax0, ax1),
                               dims=dims)
        return lambda xl: fn(xl[0, 0])

    jfn = cached_shard_jit(ctx, "reduce_scatter_torus", key, make,
                           P(ax0, ax1), P((ax0, ax1)),
                           ici_axes=(ax0, ax1))
    return jfn(x)
