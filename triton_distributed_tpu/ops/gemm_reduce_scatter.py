"""Overlapped GEMM + ReduceScatter — the TP row-parallel pattern.

Reference: ``python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py`` — a
persistent producer GEMM writes tiles and ``notify``s per-tile barriers
(:122-253) while a scatter/ring-reduce consumer completes the reduction
(reduce_scatter.py:617-856); ``gemm_rs`` op at :569.

TPU design (single fused Pallas kernel): the roles invert relative to AG+GEMM —

1. entry barrier;
2. producer loop computes partial-output *row chunks* in swizzled order
   (peer chunks first, own chunk last) and pushes each finished chunk to its
   owner's accumulation workspace slot ``me`` — so the scatter of chunk c
   overlaps the matmul of chunk c+1;
3. consumer phase: wait the n-1 peer deliveries, then reduce workspace slots
   (fp32) into the local output chunk.

out_d = Σ_r partial_r[rows of d], with A k-sharded and B row-sharded (TP
row-parallel: each device holds A(:, k_shard) and B[k_shard, :]).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.ops.tiling import gemm_tiles, matmul_tiles
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


@dataclasses.dataclass(frozen=True)
class GemmRSConfig:
    """Tile configuration (ReduceScatter2DContext analog,
    reduce_scatter.py:47-147). ``straggler``: optional (rank, cycles)
    fault injection — that rank spins before producing, widening race
    windows (reference straggler_option; same hook as AGGemmConfig,
    including the rotating ``("rotate", cycles)`` form resolved against
    the static ``call_index``)."""

    tile_m: int = 512
    tile_n: int = 1024
    tile_k: int = 1024
    straggler: tuple | None = None
    call_index: int = 0


def _gemm_rs_kernel(n: int, axis: str, m_total: int, k: int, ncols: int,
                    tiles, straggler, x_ref, b_ref, out_ref, partial_ref,
                    ws_ref, vacc, send_sems, recv_sem):
    """See module docstring.

    partial_ref: (m_total, ncols) staging for peer-bound partial chunks;
    ws_ref: (n, mc, ncols) accumulation workspace — slot r holds rank r's
    partial for my rows (slot ``me`` is written locally, never remotely).
    """
    me = dl.rank(axis)
    mc = m_total // n
    shmem.barrier_all(axis)
    dl.maybe_straggle(straggler, me)

    tm, tk, tn = tiles

    # --- producer: compute partial chunks, own chunk LAST (peers need theirs
    # shipped earliest; reference's swizzle plays the same trick in reverse).
    # Peer chunks stage through partial_ref then ship to the owner's slot
    # ``me``; my own chunk lands directly in my ws slot ``me``.
    handles = []
    for i in range(n):
        c = jax.lax.rem(me + 1 + i, n)  # me+1, me+2, …, me
        row0 = c * mc
        rows = pl.ds(row0, mc)
        dst = ws_ref.at[me] if i == n - 1 else partial_ref.at[rows]
        matmul_tiles(x_ref.at[rows], b_ref, dst,
                     mc, k, ncols, tm, tk, tn, vacc)
        if i < n - 1:
            handles.append(shmem.putmem_nbi_block(
                partial_ref.at[rows], ws_ref.at[me],
                send_sems.at[i], recv_sem, c, axis))

    # --- consumer: wait the n-1 peer deliveries, then pipelined fp32
    # reduction over all n workspace slots (reference ring_reduce epilogue,
    # reduce_scatter.py:674-826).
    chunk_like = partial_ref.at[pl.ds(0, mc)]
    shmem.wait_deliveries(chunk_like, recv_sem, n - 1)

    def red_body(w_v, o_v, acc_ref):
        s = pl.program_id(2)

        @pl.when(s == 0)
        def _():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        acc_ref[...] += w_v[0].astype(jnp.float32)

        @pl.when(s == n - 1)
        def _():
            o_v[...] = acc_ref[...].astype(o_v.dtype)

    pltpu.emit_pipeline(
        red_body,
        grid=(mc // tm, ncols // tn, n),
        in_specs=[pl.BlockSpec((1, tm, tn), lambda i, j, s: (s, i, j))],
        out_specs=[pl.BlockSpec((tm, tn), lambda i, j, s: (i, j))],
    )(ws_ref, out_ref, scratches=[vacc])
    shmem.quiet(*handles)


def gemm_rs_local(x_local: jax.Array, b_local: jax.Array, axis: str = "tp",
                  num_ranks: int | None = None,
                  cfg: GemmRSConfig = GemmRSConfig()) -> jax.Array:
    """Device-local overlapped GEMM+RS inside an existing shard_map region.

    x_local: (m_total, k_local) activations (k-sharded); b_local:
    (k_local, ncols) weight rows. Returns (m_total/num_ranks, ncols): this
    device's fully-reduced output row chunk.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    m_total, k = x_local.shape
    k2, ncols = b_local.shape
    if k != k2:
        raise ValueError(f"inner dims mismatch: A has k={k}, B has k={k2}")
    if m_total % n:
        raise ValueError(f"rows {m_total} not divisible by num_ranks {n}")
    if n == 1:
        # Degenerate world: still run the real Pallas compute core (see
        # ag_gemm_local) so single-chip compile checks mean something.
        from triton_distributed_tpu.ops.gemm import pallas_matmul

        return pallas_matmul(x_local, b_local, tile_m=cfg.tile_m,
                             tile_n=cfg.tile_n, tile_k=cfg.tile_k)
    mc = m_total // n
    tm, tk, tn = gemm_tiles(mc, k, ncols, x_local.dtype, cfg)
    straggler = dl.resolve_straggler(cfg.straggler, n, cfg.call_index)
    kernel = functools.partial(_gemm_rs_kernel, n, axis, m_total, k, ncols,
                               (tm, tk, tn), straggler)
    out = kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((mc, ncols), x_local.dtype),
        in_specs=[any_spec(), any_spec()],
        out_specs=any_spec(),
        workspaces=[
            jax.ShapeDtypeStruct((m_total, ncols), x_local.dtype),  # staging
            jax.ShapeDtypeStruct((n, mc, ncols), x_local.dtype),    # accum ws
        ],
        scratch_shapes=[
            pltpu.VMEM((tm, tn), jnp.float32),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local, b_local)
    return out


def gemm_rs(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
            axis: str = "tp",
            cfg: GemmRSConfig | None = None) -> jax.Array:
    """Host-level overlapped GEMM+RS (reference ``gemm_rs``
    gemm_reduce_scatter.py:569).

    a: (m, n·k) globally, column(k)-sharded over ``axis``;
    b: (n·k, ncols) globally, row-sharded over ``axis``.
    Returns (m, ncols) row-sharded over ``axis`` — the standard TP
    row-parallel output layout (device d owns rows [d·m/n, (d+1)·m/n)).
    """
    from triton_distributed_tpu.ops.allgather_gemm import resolve_gemm_cfg

    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    cfg = resolve_gemm_cfg(cfg, GemmRSConfig, a.shape[0] // n,
                           a.shape[1] // n, b.shape[1], a.dtype)
    key = (axis, a.shape, b.shape, str(a.dtype), str(b.dtype), cfg)

    def make():
        return functools.partial(gemm_rs_local, axis=axis, num_ranks=n, cfg=cfg)

    jfn = cached_shard_jit(ctx, "gemm_rs", key, make,
                           (P(None, axis), P(axis)), P(axis),
                           ici_axes=(axis,))
    return jfn(a, b)
