"""Hierarchical DCN×ICI overlap — two-level fused ops and the slice pipeline.

Reference: the inter-node headline of Triton-distributed — copy-engine
overlap inside the node plus NVSHMEM inter-node pushes feeding a persistent
consumer GEMM (``allgather.py:293-378`` 2D inter-node ring,
``allgather_gemm.py:158-264`` waiting consumer, charts ``README.md:197-201``)
and the inter-node SP attention (``sp_ag_attention_inter_node.py:504-529``).

TPU mapping (SURVEY.md §7): Pallas remote DMA does not cross DCN, so the two
tiers compose differently —

- **ICI tier**: the existing fused Pallas kernels run *within* the slice
  (per-sub-block delivery semaphores, rank-swizzled consumers:
  ops/allgather_gemm.py, ops/gemm_reduce_scatter.py, the flash partials).
- **DCN tier**: slice-aggregated blocks rotate around the inter-slice ring
  via ``jax.lax.ppermute`` (XLA's DCN-aware collective-permute), and the
  consumer chews each slice's block as it lands. There is no data
  dependence between hop h+1's permute and hop h's consume, so XLA's
  latency-hiding scheduler runs the DCN transfer under the Pallas compute —
  the same overlap form the reference gets from its NVSHMEM proxy thread.

The rotation/consume skeleton is shared machinery (:func:`dcn_slice_pipeline`,
:func:`dcn_ring_reduce`), not three one-off kernels; ops/two_level.py keeps
the plain (barriered) collectives, this module the overlapped producers.

Mesh convention matches two_level.py: 2-D mesh ``(inter_axis, intra_axis)``,
global shard index ``g = inter_idx * n_intra + intra_idx``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import all_gather_local
from triton_distributed_tpu.ops.allgather_gemm import (
    AGGemmConfig, ag_gemm_local, resolve_gemm_cfg,
)
from triton_distributed_tpu.ops.gemm_reduce_scatter import (
    GemmRSConfig, gemm_rs_local,
)
from triton_distributed_tpu.ops.tiling import gemm_tiles
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


# ---------------------------------------------------------------------------
# Shared slice-pipeline machinery.
# ---------------------------------------------------------------------------

def _ring_perm(n: int) -> tuple:
    """Right-rotation permutation for the DCN ring: slice a → a+1."""
    return tuple((i, (i + 1) % n) for i in range(n))


def _mod(x, n: int):
    """Non-negative mod for traced slice indices (lax.rem keeps sign)."""
    return jax.lax.rem(x + 2 * n, n)


def dcn_slice_pipeline(block, state, consume, *, inter_axis: str,
                       n_inter: int, me_inter):
    """Rotate ``block`` around the DCN ring, consuming each arrival.

    ``consume(state, src_slice, block) -> state`` runs once per REMOTE
    slice, with ``src_slice`` the (traced) slice index the block originated
    from — after h hops the resident block came from slice
    ``(me_inter - h) mod n_inter``. The caller consumes its own slice's
    block before entering (hop 0 is local), mirroring the rank-swizzled
    own-chunk-first order of the ICI-tier consumers.

    Overlap contract: hop h+1's ``ppermute`` has no data dependence on hop
    h's ``consume``, so XLA schedules the DCN transfer under the Pallas
    compute (the reference's NVSHMEM-push-feeds-waiting-consumer shape,
    allgather_gemm.py:158-264 — scheduler-driven here instead of
    semaphore-driven because Pallas cannot target DCN).
    """
    perm = _ring_perm(n_inter)
    for h in range(1, n_inter):
        block = jax.lax.ppermute(block, inter_axis, perm)
        state = consume(state, _mod(me_inter - h, n_inter), block)
    return state


def dcn_ring_reduce(produce, *, inter_axis: str, n_inter: int, me_inter):
    """Ring reduce-scatter over per-slice chunks with producer overlap.

    ``produce(c) -> array`` computes this device's (already ICI-reduced)
    partial for slice chunk ``c`` (traced index). Chunk c enters the ring
    at slice c+1 and accumulates rightward, ending fully reduced at slice
    c after n_inter-1 hops; each hop's ppermute overlaps the NEXT chunk's
    ``produce`` (the role-inverted twin of :func:`dcn_slice_pipeline` —
    reference inter-node RS p2p, reduce_scatter.py:506).

    Returns chunk ``me_inter`` summed over all slices, addition ordered
    (me+1, me+2, …, me) — a fixed, testable order.
    """
    perm = _ring_perm(n_inter)
    acc = produce(_mod(me_inter - 1, n_inter))
    for s in range(n_inter - 1):
        sent = jax.lax.ppermute(acc, inter_axis, perm)
        acc = sent + produce(_mod(me_inter - 2 - s, n_inter))
    return acc


def slice_consumer_tiles(m_slice: int, k: int, ncols: int, dtype,
                         cfg: AGGemmConfig) -> tuple[int, int, int]:
    """(tm, tn, tk) the DCN-tier consumer GEMM runs per slice block —
    exposed so the unfused test composition can bit-match the fused op."""
    tm, tk, tn = gemm_tiles(m_slice, k, ncols, dtype, cfg)
    return tm, tn, tk


def _slice_gemm(block, b_local, tiles):
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    tm, tn, tk = tiles
    return pallas_matmul(block, b_local, tile_m=tm, tile_n=tn, tile_k=tk)


# ---------------------------------------------------------------------------
# ag_gemm_2d — two-level AllGather + GEMM.
# ---------------------------------------------------------------------------

def ag_gemm_2d_local(x_local: jax.Array, b_local: jax.Array, *,
                     intra_axis: str = "tp", inter_axis: str = "dcn",
                     n_intra: int | None = None, n_inter: int | None = None,
                     cfg: AGGemmConfig = AGGemmConfig()) -> jax.Array:
    """Device-local hierarchical AG+GEMM inside a (inter, intra) shard_map.

    x_local: (m, k) A shard (global row block ``g = inter·n_intra+intra``);
    b_local: (k, ncols) local B columns. Returns (N·m, ncols),
    N = n_inter·n_intra — all rows for this device's output columns.

    Producer combo: the fused intra-slice kernel overlaps the ICI push-AG
    with the per-sub-block consumer GEMM for the OWN slice's rows and
    hands back the slice-aggregated A block; that block then rotates over
    DCN (one hop per remote slice) while the consumer GEMM chews each
    landed block — both tiers stay busy, DCN carries each slice block
    exactly once (reference 2D inter-node AG, allgather.py:293-378).
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    m, k = x_local.shape
    ncols = b_local.shape[1]
    if n_inter == 1:
        return ag_gemm_local(x_local, b_local, axis=intra_axis,
                             num_ranks=n_intra, cfg=cfg)
    me_inter = jax.lax.axis_index(inter_axis)
    # ICI tier: fused AG+GEMM for the own slice; the gathered block is the
    # DCN payload (no second gather).
    own, block = ag_gemm_local(x_local, b_local, axis=intra_axis,
                               num_ranks=n_intra, cfg=cfg,
                               return_gathered=True)
    tiles = slice_consumer_tiles(n_intra * m, k, ncols, x_local.dtype, cfg)

    # Each slice's result lands directly at its absolute row block
    # (src · n_intra·m) — one write per slice, no stack-and-reorder copy
    # of the full output.
    slice_rows = n_intra * m
    out0 = jnp.zeros((n_inter * slice_rows, ncols), x_local.dtype)
    out0 = jax.lax.dynamic_update_slice_in_dim(
        out0, own, me_inter * slice_rows, axis=0)

    def consume(out, src, blk):
        return jax.lax.dynamic_update_slice_in_dim(
            out, _slice_gemm(blk, b_local, tiles), src * slice_rows, axis=0)

    return dcn_slice_pipeline(block, out0, consume, inter_axis=inter_axis,
                              n_inter=n_inter, me_inter=me_inter)


def ag_gemm_2d(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
               intra_axis: str = "tp", inter_axis: str = "dcn",
               cfg: AGGemmConfig | None = None) -> jax.Array:
    """Host-level hierarchical AG+GEMM.

    a: (N·m, k) globally, row-sharded over BOTH axes (shard g rows at
    block g); b: (k, N_intra-sharded ncols) column-sharded over the intra
    axis only (weights replicated across slices — the multi-slice TP
    layout of BASELINE.md). Returns (N·m, n_intra·ncols) column-sharded
    over the intra axis.
    """
    ctx = ctx or get_context()
    n_intra = ctx.axis_size(intra_axis)
    n_inter = ctx.axis_size(inter_axis)
    N = n_intra * n_inter
    cfg = resolve_gemm_cfg(cfg, AGGemmConfig, a.shape[0] // N, a.shape[1],
                           b.shape[1] // n_intra, a.dtype)
    key = (intra_axis, inter_axis, a.shape, b.shape, str(a.dtype), cfg)

    def make():
        return functools.partial(ag_gemm_2d_local, intra_axis=intra_axis,
                                 inter_axis=inter_axis, n_intra=n_intra,
                                 n_inter=n_inter, cfg=cfg)

    jfn = cached_shard_jit(ctx, "ag_gemm_2d", key, make,
                           (P((inter_axis, intra_axis)), P(None, intra_axis)),
                           P(None, intra_axis), ici_axes=(intra_axis,))
    return jfn(a, b)


# ---------------------------------------------------------------------------
# gemm_rs_2d — two-level GEMM + ReduceScatter.
# ---------------------------------------------------------------------------

def gemm_rs_2d_local(x_local: jax.Array, b_local: jax.Array, *,
                     intra_axis: str = "tp", inter_axis: str = "dcn",
                     n_intra: int | None = None, n_inter: int | None = None,
                     cfg: GemmRSConfig = GemmRSConfig()) -> jax.Array:
    """Device-local hierarchical GEMM+RS inside a (inter, intra) shard_map.

    x_local: (m_total, k_local) activations (k sharded over BOTH axes);
    b_local: (k_local, ncols) weight rows. Returns (m_total/N, ncols):
    this device's fully-reduced global row chunk (g = inter·n_intra+intra).

    Role-inverted composition: per slice-sized row chunk, the fused Pallas
    kernel computes the partial GEMM and reduce-scatters it over ICI
    in-kernel (gemm_rs_local); each finished (mc, ncols) chunk then rides
    the DCN ring accumulating across slices — ICI reduces FIRST, so DCN
    carries 1/n_intra of the bytes, and each hop's transfer overlaps the
    next chunk's fused GEMM+RS.
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    m_total = x_local.shape[0]
    N = n_inter * n_intra
    if m_total % N:
        raise ValueError(f"rows {m_total} not divisible by world {N}")
    if n_inter == 1:
        return gemm_rs_local(x_local, b_local, axis=intra_axis,
                             num_ranks=n_intra, cfg=cfg)
    slice_rows = n_intra * (m_total // N)
    me_inter = jax.lax.axis_index(inter_axis)

    def produce(c):
        rows = jax.lax.dynamic_slice_in_dim(x_local, c * slice_rows,
                                            slice_rows, axis=0)
        return gemm_rs_local(rows, b_local, axis=intra_axis,
                             num_ranks=n_intra, cfg=cfg)

    return dcn_ring_reduce(produce, inter_axis=inter_axis, n_inter=n_inter,
                           me_inter=me_inter)


def gemm_rs_2d(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
               intra_axis: str = "tp", inter_axis: str = "dcn",
               cfg: GemmRSConfig | None = None) -> jax.Array:
    """Host-level hierarchical GEMM+RS.

    a: (m, N·k) globally, column(k)-sharded over both axes; b: (N·k, ncols)
    row-sharded over both axes. Returns (m, ncols) row-sharded by global
    shard index over (inter, intra) — the two-tier row-parallel layout.
    """
    ctx = ctx or get_context()
    n_intra = ctx.axis_size(intra_axis)
    n_inter = ctx.axis_size(inter_axis)
    N = n_intra * n_inter
    cfg = resolve_gemm_cfg(cfg, GemmRSConfig, a.shape[0] // N,
                           a.shape[1] // N, b.shape[1], a.dtype)
    key = (intra_axis, inter_axis, a.shape, b.shape, str(a.dtype), cfg)

    def make():
        return functools.partial(gemm_rs_2d_local, intra_axis=intra_axis,
                                 inter_axis=inter_axis, n_intra=n_intra,
                                 n_inter=n_inter, cfg=cfg)

    jfn = cached_shard_jit(ctx, "gemm_rs_2d", key, make,
                           (P(None, (inter_axis, intra_axis)),
                            P((inter_axis, intra_axis))),
                           P((inter_axis, intra_axis)),
                           ici_axes=(intra_axis,))
    return jfn(a, b)


# ---------------------------------------------------------------------------
# sp_ag_attention_2d — pipelined hierarchical SP attention.
# ---------------------------------------------------------------------------

def sp_ag_attention_2d_local(q: jax.Array, k_shard: jax.Array,
                             v_shard: jax.Array, *,
                             intra_axis: str = "tp",
                             inter_axis: str = "dcn",
                             n_intra: int | None = None,
                             n_inter: int | None = None,
                             causal: bool = True,
                             tiles: tuple[int, int] | None = None
                             ) -> jax.Array:
    """Pipelined hierarchical SP attention: the slice's KV shards gather
    over ICI (Pallas push-AG), then the aggregated slice block ROTATES
    over DCN — each arriving slice's chunks merge into the flash state
    with the online-LSE contract while the next hop is in flight, instead
    of barriering on a full ``jax.lax.all_gather`` (round-5 VERDICT #5;
    reference ``sp_ag_attention_inter_node.py:504-529`` feeding the
    per-chunk-waiting consumer).

    q/k_shard/v_shard: (B, S/N, h*, d) sequence shards by global index
    g = inter·n_intra + intra. Returns (B, S/N, hq, d).
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    from triton_distributed_tpu.ops.flash_attention import (
        _merge, shard_attention_partial,
    )

    b, sq, hq, d = q.shape
    sk, hkv = k_shard.shape[1], k_shard.shape[2]
    me_intra = jax.lax.axis_index(intra_axis)
    me_inter = jax.lax.axis_index(inter_axis)
    g = me_inter * n_intra + me_intra
    q_off = g * sq

    # ICI tier: Pallas AG of the slice's KV shards (flattened 2-D rows).
    flat = jnp.concatenate(
        [k_shard.reshape(b * sk, hkv * d), v_shard.reshape(b * sk, hkv * d)],
        axis=1)
    slice_kv = all_gather_local(flat, axis=intra_axis, num_ranks=n_intra)

    # Diagonal chunk first (locally available; rank-swizzled order).
    state = shard_attention_partial(q, k_shard, v_shard, q_offset=q_off,
                                    k_offset=g * sk, causal=causal,
                                    tiles=tiles)

    def merge_slice(state, src_slice, block):
        kv = block.reshape(n_intra, b, sk, 2, hkv, d)

        def body(j, st):
            r = src_slice * n_intra + j
            acc, m, l = shard_attention_partial(
                q, kv[j, :, :, 0], kv[j, :, :, 1], q_offset=q_off,
                k_offset=r * sk, causal=causal, tiles=tiles)
            keep = (r != g).astype(jnp.float32)  # diagonal chunk done above
            return _merge(st, (acc * keep, m, l * keep))

        return jax.lax.fori_loop(0, n_intra, body, state)

    # Own slice's remaining chunks, then the DCN rotation: slice a's flash
    # merge runs while slice a-1's block is still crossing DCN.
    state = merge_slice(state, me_inter, slice_kv)
    if n_inter > 1:
        state = dcn_slice_pipeline(slice_kv, state, merge_slice,
                                   inter_axis=inter_axis, n_inter=n_inter,
                                   me_inter=me_inter)
    acc, m, l = state
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def sp_ag_attention_2d(q: jax.Array, k: jax.Array, v: jax.Array,
                       ctx: DistContext | None = None,
                       intra_axis: str = "tp", inter_axis: str = "dcn",
                       causal: bool = True) -> jax.Array:
    """Host-level pipelined hierarchical SP attention. q/k/v: (B, S, h*, d)
    sequence(dim 1)-sharded over (inter, intra) by global shard index."""
    ctx = ctx or get_context()
    n_intra = ctx.axis_size(intra_axis)
    n_inter = ctx.axis_size(inter_axis)
    key = (intra_axis, inter_axis, causal, q.shape, k.shape, str(q.dtype))

    def make():
        from triton_distributed_tpu.ops.flash_attention import (
            resolve_flash_tiles,
        )

        N = n_intra * n_inter
        tiles = resolve_flash_tiles(q.shape[1] // N, k.shape[1] // N,
                                    q.shape[2], k.shape[2], q.shape[3],
                                    q.dtype)
        return functools.partial(sp_ag_attention_2d_local,
                                 intra_axis=intra_axis,
                                 inter_axis=inter_axis, n_intra=n_intra,
                                 n_inter=n_inter, causal=causal, tiles=tiles)

    spec = P(None, (inter_axis, intra_axis))
    jfn = cached_shard_jit(ctx, "sp_ag_attention_2d", key, make,
                           (spec, spec, spec), spec, ici_axes=(intra_axis,))
    return jfn(q, k, v)
