"""AllGather producers over ICI.

Reference: ``python/triton_dist/kernels/nvidia/allgather.py`` — 7 methods
(AllGatherMethod enum :46-54): full-mesh push/pull, 1D/2D rings, inter-node
NVSHMEM variants, auto-selected by topology (:57). On a TPU slice the ICI
fabric is a torus with uniform links, so the method space collapses to:

- ``FULL_MESH_PUSH``: every device pushes its shard to all peers
  simultaneously — lowest latency for small messages (the analog of the
  reference's push + the low-latency AG of low_latency_allgather.py).
- ``RING_1D``: bandwidth-optimal neighbor ring — each chunk takes n-1 hops,
  every link busy every step (the analog of cp_engine_producer_all_gather_
  ring_push_1d, allgather.py:140).
- ``XLA``: ``jax.lax.all_gather`` — XLA's own collective, used as golden.

All Pallas variants gather *in place into the output buffer*, so a consumer
kernel given per-chunk semaphores can start compute before the gather
completes — that overlap form lives in ops/allgather_gemm.py.
"""

from __future__ import annotations

import enum
import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


class AllGatherMethod(enum.Enum):
    """Reference enum allgather.py:46-54, collapsed to the TPU method space."""

    AUTO = "auto"
    FULL_MESH_PUSH = "full_mesh_push"
    RING_1D = "ring_1d"
    XLA = "xla"


def get_auto_all_gather_method(nbytes: int, num_ranks: int) -> AllGatherMethod:
    """Perf-model auto-selection (reference allgather.py:57
    ``get_auto_all_gather_method`` picks by NVLink topology probes): small
    payloads favor the single-hop full-mesh push (latency-bound), large
    payloads the ring (which never oversubscribes a link). The crossover is
    computed from the ICI cost models instead of a hard-coded threshold."""
    if num_ranks <= 2:
        return AllGatherMethod.FULL_MESH_PUSH
    from triton_distributed_tpu.runtime.perf_model import (
        allgather_full_mesh_time_s,
        allgather_ring_time_s,
    )

    if (allgather_full_mesh_time_s(nbytes, num_ranks)
            <= allgather_ring_time_s(nbytes, num_ranks)):
        return AllGatherMethod.FULL_MESH_PUSH
    return AllGatherMethod.RING_1D


def _ag_full_mesh_push_kernel(n: int, axis: str, m: int,
                              x_ref, out_ref, send_sems, recv_sem, copy_sem):
    """Every device pushes its local shard into slot ``me`` of every peer's
    output (reference cp_engine_producer_all_gather_full_mesh_push,
    allgather.py:81)."""
    me = dl.rank(axis)
    # Entry barrier: guarantees no peer is still in a previous launch whose
    # buffers our remote writes could land in (role of local_copy_and_
    # barrier_all, allgather_gemm.py:107).
    shmem.barrier_all(axis)
    my_slot = out_ref.at[pl.ds(me * m, m)]
    local = pltpu.make_async_copy(x_ref, my_slot, copy_sem)
    local.start()
    handles = []
    for i in range(n - 1):
        peer = jax.lax.rem(me + 1 + i, n)
        handles.append(
            shmem.putmem_nbi_block(x_ref, my_slot, send_sems.at[i], recv_sem, peer,
                                   axis)
        )
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, recv_sem, n - 1)


def _ag_ring_kernel(n: int, axis: str, m: int,
                    x_ref, out_ref, send_sem, recv_sem, copy_sem):
    """Bandwidth-optimal 1-D ring: forward the chunk received last step
    (reference cp_engine_producer_all_gather_ring_push_1d, allgather.py:140).
    The output buffer doubles as the ring transport: chunks land directly in
    their final slots, so per-chunk readiness is observable by a consumer."""
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    local = pltpu.make_async_copy(x_ref, out_ref.at[pl.ds(me * m, m)], copy_sem)
    local.start()
    local.wait()
    for s in range(n - 1):
        chunk = jax.lax.rem(me - s + n, n)  # chunk acquired at step s-1 (own at s=0)
        slot = out_ref.at[pl.ds(chunk * m, m)]
        h = shmem.putmem_nbi_block(slot, slot, send_sem, recv_sem, right, axis)
        # Receive chunk (me-1-s) from the left before forwarding it next step.
        shmem.wait_deliveries(x_ref, recv_sem, 1)
        h.wait_send()


def _build_ag_call(n: int, axis: str, m: int, cols: int, dtype,
                   method: AllGatherMethod):
    if method == AllGatherMethod.FULL_MESH_PUSH:
        kernel = functools.partial(_ag_full_mesh_push_kernel, n, axis, m)
        scratch = [
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ]
    elif method == AllGatherMethod.RING_1D:
        kernel = functools.partial(_ag_ring_kernel, n, axis, m)
        scratch = [
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ]
    else:  # pragma: no cover
        raise ValueError(f"not a pallas method: {method}")

    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n * m, cols), dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        scratch_shapes=scratch,
        uses_barrier=True,
    )


def all_gather_local(x_local: jax.Array, axis: str = "tp", num_ranks: int | None = None,
                     method: AllGatherMethod | str = AllGatherMethod.AUTO) -> jax.Array:
    """Device-local AllGather for use *inside* an existing shard_map region
    (the composition point for layers). ``x_local``: (m, cols) per device →
    (num_ranks*m, cols) per device."""
    if isinstance(axis, (tuple, list)):
        # Multi-axis form: drive both torus axes in one kernel
        # (ops/multi_axis.py; round-4 VERDICT #4). num_ranks: (n0, n1).
        if num_ranks is None:
            raise ValueError("num_ranks (n0, n1) required inside shard_map")
        mk = method.value if isinstance(method, AllGatherMethod) else str(method)
        if mk == "xla":
            return jax.lax.all_gather(x_local, tuple(axis), tiled=True)
        if mk not in ("auto", "ring_1d"):
            # Reject rather than silently substituting a different kernel
            # for a pinned method (benchmark callers rely on the pin).
            raise ValueError(
                f"method {mk!r} has no multi-axis form; tuple-axis AG "
                "supports auto (ring-of-rings) or xla")
        from triton_distributed_tpu.ops.multi_axis import (
            all_gather_torus_local,
        )

        return all_gather_torus_local(x_local, axes=tuple(axis),
                                      dims=tuple(num_ranks))
    method = AllGatherMethod(method) if not isinstance(method, AllGatherMethod) else method
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1:
        # Degenerate world: identity. Also avoids compiling a barrier/put
        # kernel over a size-1 axis, which crashes Mosaic (observed SIGABRT
        # on v5e) and has nothing to do anyway.
        return x_local
    if method == AllGatherMethod.AUTO:
        # The model's contract is the GLOBAL gathered payload, not the shard.
        method = get_auto_all_gather_method(
            x_local.size * x_local.dtype.itemsize * n, n)
    if method == AllGatherMethod.XLA:
        return jax.lax.all_gather(x_local, axis, tiled=True)
    m, cols = x_local.shape
    return _build_ag_call(n, axis, m, cols, x_local.dtype, method)(x_local)


# ---------------------------------------------------------------------------
# Barrier-free steady-state AG (decode path). Same call_count-parity protocol
# as ops/allreduce.all_reduce_stream (reference low_latency_all_to_all.py
# :125-175); safety argument identical — AG completion waits a delivery from
# EVERY peer, so the DMA-completion chain orders parity-slab reuse.
# ---------------------------------------------------------------------------

def _ag_parity_kernel(n: int, axis: str, m: int, straggler,
                      idx_ref, x_ref, _ws_in, out_ref, ws,
                      send_sems, recv_sems, copy_sem):
    import jax.numpy as jnp

    me = dl.rank(axis)
    p = jax.lax.rem(idx_ref[0], 2)
    straggler = dl.resolve_straggler(straggler, n, idx_ref[0])
    dl.maybe_straggle(straggler, me)
    slab = ws.at[p]                       # (n·m, cols) parity slab
    my_slot = slab.at[pl.ds(me * m, m)]
    local = pltpu.make_async_copy(x_ref, my_slot, copy_sem)
    local.start()
    handles = []
    for i in range(n - 1):
        peer = jax.lax.rem(me + 1 + i, n)
        handles.append(
            shmem.putmem_nbi_block(x_ref, my_slot, send_sems.at[i],
                                   recv_sems.at[p], peer, axis))
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, recv_sems.at[p], n - 1)
    out_cp = pltpu.make_async_copy(slab, out_ref, copy_sem)
    out_cp.start()
    out_cp.wait()


def ag_stream_workspace(n: int, m: int, cols: int, dtype):
    """Persistent (workspace (2, n·m, cols), call_index) pair for
    :func:`all_gather_stream`; allocate once, thread through the loop."""
    import jax.numpy as jnp

    return (jnp.zeros((2, n * m, cols), dtype), jnp.zeros((), jnp.int32))


def all_gather_stream(x_local: jax.Array, ws: jax.Array,
                      call_index: jax.Array, *, axis: str = "tp",
                      num_ranks: int | None = None,
                      straggler: tuple | None = None,
                      force_kernel: bool = False):
    """Barrier-free full-mesh-push AllGather over a persistent parity
    workspace. x_local: (m, cols) → ((n·m, cols), ws', call_index + 1)."""
    import jax.numpy as jnp

    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1 and not force_kernel:
        return x_local, ws, call_index + 1
    m, cols = x_local.shape
    if ws.shape != (2, n * m, cols):
        raise ValueError(f"workspace shape {ws.shape} != (2, {n * m}, {cols})")
    if ws.dtype != x_local.dtype:
        raise ValueError(f"workspace dtype {ws.dtype} != input "
                         f"{x_local.dtype} — allocate ag_stream_workspace "
                         "with the payload dtype")
    from triton_distributed_tpu.language.core import smem_spec

    kernel = functools.partial(_ag_parity_kernel, n, axis, m, straggler)
    out, ws_new = kernel_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n * m, cols), x_local.dtype),
            jax.ShapeDtypeStruct(ws.shape, ws.dtype),
        ),
        in_specs=[smem_spec((1,)), any_spec(), any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={2: 1},
    )(jnp.asarray(call_index, jnp.int32).reshape(1), x_local, ws)
    return out, ws_new, call_index + 1


def all_gather(x: jax.Array, ctx: DistContext | None = None, axis: str = "tp",
               method: AllGatherMethod | str = AllGatherMethod.AUTO,
               stacked: bool = False) -> jax.Array:
    """Host-level AllGather: ``x`` globally (n*m, cols) sharded over ``axis``
    → gathered copy on every device.

    ``stacked=True`` returns the per-device copies stacked as (n, n*m, cols)
    (test introspection); default returns the replicated (n*m, cols) view.
    """
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    method_key = method.value if isinstance(method, AllGatherMethod) else str(method)
    key = (axis, method_key, stacked, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(all_gather_local, axis=axis, num_ranks=n,
                               method=method)
        return (lambda xl: fn(xl)[None]) if stacked else fn

    jfn = cached_shard_jit(ctx, "all_gather", key, make, P(axis),
                           P(axis) if stacked else P(None),
                           ici_axes=(axis,))
    out = jfn(x)
    return out.reshape(n, *x.shape) if stacked else out
