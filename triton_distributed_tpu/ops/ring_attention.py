"""Ring attention — sequence-parallel causal prefill over the ICI ring.

Reference: the SP AllGather-attention family
(``sp_ag_attention_intra_node.py:105`` producer, ``:256`` consumer FA,
``:432`` op) provides long-context prefill by overlapping KV gathering with
blockwise flash attention. SURVEY.md §2.5 notes the reference has *no*
softmax-rescaling ring pipeline — on TPU the ring IS the natural shape: KV
shards rotate around the ICI ring via ``ppermute`` while every device
accumulates blockwise attention with online log-sum-exp rescaling, so each
hop's communication overlaps the previous hop's attention compute (XLA
schedules collective-permute DMA concurrently with the attention kernel —
the copy-engine/consumer split of the reference, expressed at the XLA level).

The per-shard compute is the tiled Pallas flash kernel
(ops/flash_attention.py — reference consumer
``kernel_consumer_flash_attn_forward``, sp_ag_attention_intra_node.py:256):
causality is positional (rank r owns positions [r·S/n, (r+1)·S/n)), handed
to the kernel as (q_offset, k_offset), so shards entirely behind the
diagonal skip their dots in-kernel and fully-hidden shards come back dead
(l = 0) for the merge.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# Re-exported for back-compat: the dense golden + merge lived here in round 2.
from triton_distributed_tpu.ops.flash_attention import (  # noqa: F401
    _block_attn, _merge, shard_attention_partial,
)
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis: str = "sp", num_ranks: int | None = None,
                         causal: bool = True,
                         tiles: tuple | None = None) -> jax.Array:
    """Device-local ring attention inside shard_map.

    q/k/v: (B, S/n, h*, d) — this rank's sequence shard (rank r owns
    positions [r·S/n, (r+1)·S/n)). Returns (B, S/n, hq, d): attention output
    for the local queries over the FULL sequence.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    me = jax.lax.axis_index(axis)
    sq = q.shape[1]
    sk = k.shape[1]
    q_off = me * sq
    from triton_distributed_tpu.ops.flash_attention import (
        DEFAULT_TILE_K, DEFAULT_TILE_Q,
    )

    tq, tk = tiles if tiles else (DEFAULT_TILE_Q, DEFAULT_TILE_K)

    if n == 1:
        acc, m, l = shard_attention_partial(q, k, v, q_offset=q_off,
                                            k_offset=me * sk, causal=causal,
                                            tile_q=tq, tile_k=tk)
        return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    perm = [(i, (i + 1) % n) for i in range(n)]  # shift right

    def partial_for(kc, vc, src):
        # Positional causality: src > me shards come back dead (l=0,
        # compute skipped in-kernel); src < me shards are fully visible.
        return shard_attention_partial(q, kc, vc, q_offset=q_off,
                                       k_offset=src * sk, causal=causal,
                                       tile_q=tq, tile_k=tk)

    # Exactly n-1 rotations, each issued on data the concurrent attention
    # call does NOT consume — hop i+1's ppermute DMA rides under hop i's
    # flash kernel (the copy-engine/consumer split of the reference's SP
    # attention, expressed in the XLA schedule). The last arriving shard is
    # consumed after the loop with no further rotation.
    kc = jax.lax.ppermute(k, axis, perm)         # hop-1 shards in flight...
    vc = jax.lax.ppermute(v, axis, perm)
    state = partial_for(k, v, me)                # ...under the diagonal hop

    def body(i, carry):
        state, kc, vc = carry
        kc_next = jax.lax.ppermute(kc, axis, perm)
        vc_next = jax.lax.ppermute(vc, axis, perm)
        src = jax.lax.rem(me - i + n, n)
        return _merge(state, partial_for(kc, vc, src)), kc_next, vc_next

    state, kc, vc = jax.lax.fori_loop(1, n - 1, body, (state, kc, vc))
    state = _merge(state, partial_for(kc, vc, jax.lax.rem(me + 1, n)))

    acc, m, l = state
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   ctx: DistContext | None = None, axis: str = "tp",
                   causal: bool = True) -> jax.Array:
    """Host-level ring attention. q/k/v: (B, S, h*, d) sequence-sharded over
    ``axis`` (dim 1). Returns (B, S, hq, d) sequence-sharded."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, causal, q.shape, k.shape, str(q.dtype))

    def make():
        # Tile caps resolved HERE (host level, once per shape signature):
        # on-chip autotuned when tuning is on, swept defaults otherwise.
        from triton_distributed_tpu.ops.flash_attention import (
            resolve_flash_tiles,
        )

        tiles = resolve_flash_tiles(q.shape[1] // n, k.shape[1] // n,
                                    q.shape[2], k.shape[2], q.shape[3],
                                    q.dtype)
        return functools.partial(ring_attention_local, axis=axis,
                                 num_ranks=n, causal=causal, tiles=tiles)

    jfn = cached_shard_jit(ctx, "ring_attention", key, make,
                          (P(None, axis), P(None, axis), P(None, axis)),
                          P(None, axis))
    return jfn(q, k, v)
