"""Ring attention — sequence-parallel causal prefill over the ICI ring.

Reference: the SP AllGather-attention family
(``sp_ag_attention_intra_node.py:105`` producer, ``:256`` consumer FA,
``:432`` op) provides long-context prefill by overlapping KV gathering with
blockwise flash attention. SURVEY.md §2.5 notes the reference has *no*
softmax-rescaling ring pipeline — on TPU the ring IS the natural shape: KV
shards rotate around the ICI ring via ``ppermute`` while every device
accumulates blockwise attention with online log-sum-exp rescaling, so each
hop's communication overlaps the previous hop's attention compute (XLA
schedules collective-permute DMA concurrently with the einsums — the
copy-engine/consumer split of the reference, expressed at the XLA level).

Causality with sequence sharding: query block q_r attends KV block k_s iff
s <= r (block-causal), with the diagonal block masked triangularly — the
standard ring-attention schedule.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _block_attn(q, k, v, mask):
    """Unnormalized blockwise attention with running-max stats.

    q: (B, Sq, hq, d); k/v: (B, Sk, hkv, d); mask: (Sq, Sk) bool or None.
    Returns (acc (B,Sq,hq,d) fp32, m (B,Sq,hq), l (B,Sq,hq)).
    """
    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, sq, hkv, g, d)
    logits = jnp.einsum("bqhgd,bkhd->bqhgk", qf,
                        k.astype(jnp.float32)) / math.sqrt(d)
    if mask is not None:
        logits = jnp.where(mask[None, :, None, None, :], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, :, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bqhgk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return (acc.reshape(b, sq, hq, d), m_safe.reshape(b, sq, hq),
            l.reshape(b, sq, hq))


def _merge(state, update):
    """Online LSE merge of two (acc, m, l) blockwise-attention partials."""
    acc0, m0, l0 = state
    acc1, m1, l1 = update
    dead0, dead1 = l0 <= 0, l1 <= 0
    m_new = jnp.where(dead0, m1, jnp.where(dead1, m0, jnp.maximum(m0, m1)))
    s0 = jnp.where(dead0, 0.0, jnp.exp(m0 - m_new))
    s1 = jnp.where(dead1, 0.0, jnp.exp(m1 - m_new))
    return (acc0 * s0[..., None] + acc1 * s1[..., None],
            m_new, l0 * s0 + l1 * s1)


def ring_attention_local(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         axis: str = "sp", num_ranks: int | None = None,
                         causal: bool = True) -> jax.Array:
    """Device-local ring attention inside shard_map.

    q/k/v: (B, S/n, h*, d) — this rank's sequence shard (rank r owns
    positions [r·S/n, (r+1)·S/n)). Returns (B, S/n, hq, d): attention output
    for the local queries over the FULL sequence.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    me = jax.lax.axis_index(axis)
    b, sq, hq, d = q.shape
    sk = k.shape[1]

    diag_mask = (jnp.tril(jnp.ones((sq, sk), bool))
                 if causal and sq == sk else None)

    # Step 0: my own diagonal block.
    state = _block_attn(q, k, v, diag_mask)

    if n > 1:
        perm = [(i, (i + 1) % n) for i in range(n)]  # shift right

        def body(i, carry):
            state, kc, vc = carry
            # Rotate: after i+1 hops I hold the shard of rank me-(i+1).
            kc = jax.lax.ppermute(kc, axis, perm)
            vc = jax.lax.ppermute(vc, axis, perm)
            src = jax.lax.rem(me - (i + 1) + n, n)
            acc, m, l = _block_attn(q, kc, vc, None)
            if causal:
                # Block-causal: only attend shards strictly before mine.
                keep = (src < me).astype(jnp.float32)
                update = (acc * keep, m, l * keep)
            else:
                update = (acc, m, l)
            return _merge(state, update), kc, vc

        (state, _, _) = jax.lax.fori_loop(0, n - 1, body, (state, k, v))

    acc, m, l = state
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   ctx: DistContext | None = None, axis: str = "tp",
                   causal: bool = True) -> jax.Array:
    """Host-level ring attention. q/k/v: (B, S, h*, d) sequence-sharded over
    ``axis`` (dim 1). Returns (B, S, hq, d) sequence-sharded."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, causal, q.shape, k.shape, str(q.dtype))

    def make():
        return functools.partial(ring_attention_local, axis=axis,
                                 num_ranks=n, causal=causal)

    jfn = cached_shard_jit(ctx, "ring_attention", key, make,
                          (P(None, axis), P(None, axis), P(None, axis)),
                          P(None, axis))
    return jfn(q, k, v)
