"""ReduceScatter over ICI.

Reference: ``python/triton_dist/kernels/nvidia/reduce_scatter.py`` — 2D-context
scatter + ring_reduce (:674-826) and sm-based ring-push RS (:327,415). On a TPU
slice the idiomatic form is the classic ring reduce-scatter: chunk c starts at
device c+1, accumulates each hop, and lands fully-reduced at its owner after
n-1 hops — every ICI link busy every step, total traffic (n-1)/n of the input.

Flow control: incoming partials land in a per-step slot (comm has n-1 slots)
so a fast upstream producer can never overwrite a slot the local device has
not consumed; outgoing staging uses 2 slots guarded by the *local* send
semaphore (wait the step-s-2 send before reusing its slot) — both orderings
are single-device-observable, so no cross-device timing assumption exists.
"""

from __future__ import annotations

import functools

import jax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align


def _tiled_add(dst_at, a_at, b_at, m: int, tile_m: int, va, vb, copy_sem):
    """dst[t] = a[t] + b[t] for every row tile, staged through VMEM.

    ``*_at`` are callables tile_index -> ref slice. Serial per tile; the
    overlapped AG+GEMM path has its own fused epilogue, this is the plain
    collective path.
    """
    for t in range(m // tile_m):
        pltpu.make_async_copy(a_at(t), va, copy_sem).start()
        pltpu.make_async_copy(a_at(t), va, copy_sem).wait()
        pltpu.make_async_copy(b_at(t), vb, copy_sem).start()
        pltpu.make_async_copy(b_at(t), vb, copy_sem).wait()
        va[...] = va[...] + vb[...]
        pltpu.make_async_copy(va, dst_at(t), copy_sem).start()
        pltpu.make_async_copy(va, dst_at(t), copy_sem).wait()


def _rs_ring_kernel(n: int, axis: str, m: int, tile_m: int,
                    x_ref, out_ref, comm, stage, va, vb,
                    send_sem, recv_sem, copy_sem):
    """Ring reduce-scatter (see module docstring for the slot protocol).

    x_ref: (n*m, cols) full local rows; out_ref: (m, cols) = Σ_d x_d[me].
    """
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    right = jax.lax.rem(me + 1, n)
    chunk_like = x_ref.at[pl.ds(0, m)]

    def x_chunk(c):
        return x_ref.at[pl.ds(c * m, m)]

    def tile(ref_at, t):
        return ref_at.at[pl.ds(t * tile_m, tile_m)]

    send_handles: list = [None] * (n - 1)
    for s in range(n - 1):
        c = jax.lax.rem(me - 1 - s + 2 * n, n)  # chunk I forward at step s
        if s == 0:
            # First hop: raw local contribution, no staging needed.
            send_handles[0] = shmem.putmem_nbi_block(
                x_chunk(c), comm.at[0], send_sem, recv_sem, right, axis)
            continue
        # Partial for chunk c arrived from the left in slot s-1.
        shmem.wait_deliveries(chunk_like, recv_sem, 1)
        slot = s % 2
        if s >= 2:
            send_handles[s - 2].wait_send()  # stage[slot] free to reuse
        _tiled_add(
            lambda t: tile(stage.at[slot], t),
            lambda t: tile(comm.at[s - 1], t),
            lambda t: tile(x_chunk(c), t),
            m, tile_m, va, vb, copy_sem,
        )
        send_handles[s] = shmem.putmem_nbi_block(
            stage.at[slot], comm.at[s], send_sem, recv_sem, right, axis)
    # Final arrival: my own chunk, fully reduced except my contribution.
    shmem.wait_deliveries(chunk_like, recv_sem, 1)
    _tiled_add(
        lambda t: tile(out_ref, t),
        lambda t: tile(comm.at[n - 2], t),
        lambda t: tile(x_chunk(me), t),
        m, tile_m, va, vb, copy_sem,
    )
    # Drain only the sends not already waited in-loop (steps ≥ 2 waited their
    # s-2 handle; double-waiting would over-consume send_sem bytes and stall).
    for h in send_handles[max(n - 3, 0):]:
        if h is not None:
            h.wait_send()


def reduce_scatter_local(x_local: jax.Array, axis: str = "tp",
                         num_ranks: int | None = None) -> jax.Array:
    """Device-local ring reduce-scatter inside an existing shard_map region.
    ``x_local``: (n*m, cols) per device → (m, cols) per device (chunk ``me``
    summed over all devices)."""
    if isinstance(axis, (tuple, list)):
        # Multi-axis form (ops/multi_axis.py; round-4 VERDICT #4).
        if num_ranks is None:
            raise ValueError("num_ranks (n0, n1) required inside shard_map")
        from triton_distributed_tpu.ops.multi_axis import (
            reduce_scatter_torus_local,
        )

        return reduce_scatter_torus_local(x_local, axes=tuple(axis),
                                          dims=tuple(num_ranks))
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1:
        return x_local
    mt, cols = x_local.shape
    if mt % n:
        raise ValueError(f"rows {mt} not divisible by num_ranks {n}")
    m = mt // n
    # Sublane-aligned staging tiles — Mosaic rejects unaligned HBM slice
    # offsets on real TPU even though interpret mode accepts them.
    tile_m = pick_tile(m, 512, sublane_align(x_local.dtype))
    kernel = functools.partial(_rs_ring_kernel, n, axis, m, tile_m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, cols), x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        workspaces=[
            jax.ShapeDtypeStruct((n - 1, m, cols), x_local.dtype),  # comm slots
            jax.ShapeDtypeStruct((2, m, cols), x_local.dtype),      # stage
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local)


def reduce_scatter(x: jax.Array, ctx: DistContext | None = None,
                   axis: str = "tp") -> jax.Array:
    """Host-level ring reduce-scatter.

    ``x``: every device holds (n*m, cols) of *contributions* — globally the
    array is (n, n*m, cols) stacked over ``axis``. Returns the (n*m, cols)
    result scattered over ``axis`` (device d owns rows [d*m, (d+1)*m)).
    """
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(reduce_scatter_local, axis=axis, num_ranks=n)
        return lambda xl: fn(xl[0])

    return cached_shard_jit(ctx, "reduce_scatter", key, make,
                            P(axis), P(axis), ici_axes=(axis,))(x)
