"""Tiled Pallas flash-attention prefill — the blockwise online-softmax core.

Reference: ``sp_ag_attention_intra_node.py:256``
(``kernel_consumer_flash_attn_forward`` — the blockwise FA consumer that the
reference's SP attention family runs per KV chunk) and the tiled softmax
structure of ``flash_decode.py:129-481``. Round-2 VERDICT.md's top gap: every
prefill path here materialized O(S²) fp32 logits; this kernel replaces them
with a (tile_q × tile_k) VMEM-blockwise online softmax so long-context prefill
runs in flat memory.

TPU shape: grid (B, hq, Sq-tiles, Sk-tiles) with the KV-tile loop innermost;
the fp32 accumulator and running (m, l) stats live in VMEM scratch carried
across the KV steps (TPU grid steps run sequentially on the core — the
persistent-consumer loop of the reference, expressed as the grid). GQA maps
query heads onto KV heads in the BlockSpec index map (h // group), so K/V
tiles are fetched once per query head without a repeated-KV materialization.

Causality is positional: the kernel receives (q_offset, k_offset) through
scalar prefetch (traced values allowed — ring attention passes rank-dependent
offsets), masks ``q_pos >= k_pos``, and *skips the compute of fully-hidden
tiles* — the causal skip the reference gets from its rank-swizzled tile
order. Partial outputs (unnormalized fp32 acc + running max m + sum-exp l)
use the same (acc, m, l) contract as ops/ring_attention.py's ``_merge``, so
ring / SP-AG shards merge across devices unchanged.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.language.core import _interpret_params
from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align
from triton_distributed_tpu.runtime.context import use_interpret

_NEG = -1e30
# VMEM budget for one (q-tile, k-tile) working set; beyond it the tile caps
# degrade (and only shapes no cap can fit fall back to the dense path).
# The budget tracks the physical 16MiB VMEM: _tile_estimate now models the
# full working set including the epilogue temporaries (round-3 advisor
# finding), calibrated so the measured-good configs sit exactly at the
# boundary — bf16 1024x1024 models 15.86MB and compiles; fp32 1024x1024
# models 17.5MB and indeed needs the degrade-to-fit path on real TPU; the
# decimal-16M margin keeps unmeasured whole-dim prime shapes (fp32 997x997,
# 16.65MB modeled) on the dense path rather than betting on ~1% headroom.
_VMEM_BUDGET = 16_000_000
# Default tile caps (single source of truth — the predicate, the dispatcher
# and the public entry points must agree). 1024x1024 measured 33% faster
# than 512x1024 at S=32k on-chip; smaller caps are tried automatically when
# the working-set estimate exceeds the budget (e.g. fp32 payloads).
DEFAULT_TILE_Q = 1024
DEFAULT_TILE_K = 1024


def _tile_estimate(tq: int, tk: int, d: int, itemsize: int) -> int:
    """Working set: q/k/v tiles (double-buffered) + acc/stat scratch +
    the fp32 (tq, tk) logits tile + the ``_col_to_row`` identity-reduction
    temporaries (one fp32 (tq, tq) where-select over two int32 iotas — the
    epilogue's stat relayout) and the two (8, tq) broadcast stat blocks.
    Mosaic's scoped VMEM also runs ~25% over naive double-buffer models
    (measured for the GEMM candidates, ops/tiling.py) — here that headroom
    is what the (tq, tq) temporaries term represents; the calibration
    points are in the _VMEM_BUDGET comment."""
    return (2 * (tq * d + 2 * tk * d) * itemsize
            + (tq * d + 2 * tq * 128 + tq * tk) * 4
            + 2 * tq * tq * 4         # _col_to_row eye (int32 pair) + select
            + 2 * 2 * 8 * tq * 4)     # (8, tq) m/l out blocks, double-buffered


def _fit_ladder(sq: int, sk: int, d: int, q_dtype, k_dtype,
                tile_q: int, tile_k: int) -> list:
    """All (tq, tk) configs within the VMEM budget, best-first (q-tile cap
    degrades before the k-tile cap); empty if nothing fits (dense
    fallback). The probe in :func:`_flash_call` walks this ladder when a
    config's modeled working set sits close enough to the budget that the
    estimate alone cannot be trusted (round-4 advisor finding: a
    mis-modeled shape used to hard-fail at Mosaic compile)."""
    itemsize = max(jnp.dtype(q_dtype).itemsize, jnp.dtype(k_dtype).itemsize)
    k_align = max(sublane_align(q_dtype), sublane_align(k_dtype))
    ladder = []
    for tk_cap in (tile_k, 512, 256):
        tk = pick_tile(sk, tk_cap, k_align)
        for tq_cap in (tile_q, 512, 256, 128):
            tq = pick_tile(sq, tq_cap, 128)
            if (_tile_estimate(tq, tk, d, itemsize) <= _VMEM_BUDGET
                    and (tq, tk) not in ladder):
                ladder.append((tq, tk))
    return ladder


def _fit_tiles(sq: int, sk: int, d: int, q_dtype, k_dtype,
               tile_q: int, tile_k: int):
    """Best (tq, tk) within the VMEM budget; None if nothing fits."""
    ladder = _fit_ladder(sq, sk, d, q_dtype, k_dtype, tile_q, tile_k)
    return ladder[0] if ladder else None


# ---------------------------------------------------------------------------
# Dense (O(S²)-logit) reference path + online-LSE merge. These lived in
# ops/ring_attention.py in round 2; they are the golden for the tiled kernel
# and the fallback for shapes the kernel declines (flash_supported).
# ---------------------------------------------------------------------------

def _block_attn(q, k, v, mask):
    """Unnormalized blockwise attention with running-max stats (dense).

    q: (B, Sq, hq, d); k/v: (B, Sk, hkv, d); mask: (Sq, Sk) bool or None.
    Returns (acc (B,Sq,hq,d) fp32, m (B,Sq,hq), l (B,Sq,hq)).
    """
    import math

    b, sq, hq, d = q.shape
    sk, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    qf = q.astype(jnp.float32).reshape(b, sq, hkv, g, d)
    logits = jnp.einsum("bqhgd,bkhd->bqhgk", qf,
                        k.astype(jnp.float32)) / math.sqrt(d)
    if mask is not None:
        logits = jnp.where(mask[None, :, None, None, :], logits, -jnp.inf)
    m = jnp.max(logits, axis=-1)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(logits - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, :, None, None, :], p, 0.0)
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bqhgk,bkhd->bqhgd", p, v.astype(jnp.float32))
    return (acc.reshape(b, sq, hq, d), m_safe.reshape(b, sq, hq),
            l.reshape(b, sq, hq))


def _merge(state, update):
    """Online LSE merge of two (acc, m, l) blockwise-attention partials."""
    acc0, m0, l0 = state
    acc1, m1, l1 = update
    dead0, dead1 = l0 <= 0, l1 <= 0
    m_new = jnp.where(dead0, m1, jnp.where(dead1, m0, jnp.maximum(m0, m1)))
    s0 = jnp.where(dead0, 0.0, jnp.exp(m0 - m_new))
    s1 = jnp.where(dead1, 0.0, jnp.exp(m1 - m_new))
    return (acc0 * s0[..., None] + acc1 * s1[..., None],
            m_new, l0 * s0 + l1 * s1)


def _col_to_row(col, tq: int):
    """(tq, 1) fp32 column -> (tq,) lane vector, via an identity-mask
    reduction (guaranteed-lowerable: broadcast + iota + where + sum; avoids
    relying on Mosaic sublane->lane relayout of narrow vectors)."""
    eye = (jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 0)
           == jax.lax.broadcasted_iota(jnp.int32, (tq, tq), 1))
    return jnp.sum(jnp.where(eye, jnp.broadcast_to(col, (tq, tq)), 0.0),
                   axis=0)


def _flash_kernel(g: int, nk: int, tq: int, tk: int, scale: float,
                  causal: bool, normalize: bool,
                  offs_ref,                   # scalar prefetch: [q_off, k_off]
                  q_ref, k_ref, v_ref,        # (1,1,tq,d), (1,1,tk,d) blocks
                  o_ref, m_ref, l_ref,        # (1,1,tq,d), (1,1,tq), (1,1,tq)
                  acc, mstat, lstat):         # VMEM scratch
    i = pl.program_id(2)
    j = pl.program_id(3)
    q_off = offs_ref[0]
    k_off = offs_ref[1]

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        mstat[...] = jnp.full_like(mstat, _NEG)
        lstat[...] = jnp.zeros_like(lstat)

    # Tile-level causal skip: the last q position of this tile is before the
    # first k position -> every logit is masked; skip the dots entirely.
    first_k = k_off + j * tk
    last_q = q_off + i * tq + (tq - 1)
    visible = (last_q >= first_k) if causal else (first_k == first_k)

    @pl.when(visible)
    def _():
        q = q_ref[0, 0].astype(jnp.float32)          # (tq, d)
        k = k_ref[0, 0]                              # (tk, d)
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (tq, tk)
        if causal:
            qpos = (q_off + i * tq
                    + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 0))
            kpos = (k_off + j * tk
                    + jax.lax.broadcasted_iota(jnp.int32, (tq, tk), 1))
            mask = qpos >= kpos
            s = jnp.where(mask, s, _NEG)
        m_prev = mstat[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)              # kill exp(0)=1 on dead rows
        corr = jnp.exp(m_prev - m_new)
        pv = jnp.dot(p.astype(v_ref.dtype), v_ref[0, 0],
                     preferred_element_type=jnp.float32)  # (tq, d)
        acc[...] = acc[...] * corr + pv
        mstat[:, :1] = m_new
        lstat[:, :1] = lstat[:, :1] * corr + jnp.sum(p, axis=1, keepdims=True)

    @pl.when(j == nk - 1)
    def _():
        l_col = lstat[:, :1]
        if normalize:
            o_ref[0, 0] = (acc[...] / jnp.maximum(l_col, 1e-30)
                           ).astype(o_ref.dtype)
        else:
            o_ref[0, 0] = acc[...].astype(o_ref.dtype)
        # Stats ride an 8-sublane broadcast row block (Mosaic requires the
        # block's second-to-last dim be 8-divisible; a (1,1,tq) block isn't).
        m_row = _col_to_row(mstat[:, :1], tq)
        l_row = _col_to_row(l_col, tq)
        m_ref[0, 0] = jnp.broadcast_to(m_row[None, :], (8, tq))
        l_ref[0, 0] = jnp.broadcast_to(l_row[None, :], (8, tq))


class FlashCompileError(ValueError):
    """No flash tile configuration fits VMEM (modeled) or compiles
    (probed) for this shape — callers fall back to the dense path."""


# A config whose modeled working set exceeds this is probe-compiled on real
# TPU before dispatch (the model is calibrated on two points; near the
# 16MiB boundary it cannot be trusted to a few percent — round-4 advisor).
_PROBE_SAFE = 14_000_000
# Configs measured compiling + running on the real chip (rounds 3-4 sweeps):
# (tq, tk, d, itemsize). These skip the probe even inside the risk band —
# probing them would re-add a ~30 s trace-time compile to the default
# S=32k prefill path for nothing.
_KNOWN_GOOD = {(1024, 1024, 128, 2), (512, 1024, 128, 4)}
_probe_memory: dict = {}


def _probe_ok(hq: int, hkv: int, sq: int, sk: int, d: int, q_dtype, k_dtype,
              v_dtype, causal: bool, normalize: bool, tq: int, tk: int
              ) -> bool:
    """AOT-compile the kernel at this config (B=1 — batch is a parallel
    grid dim and does not change the per-block VMEM footprint); False on a
    Mosaic VMEM/resource failure, re-raising anything that doesn't look
    like one. Verdicts are disk-cached per chip so each shape pays the
    probe compile (~30 s through the relay) once."""
    import jax as _jax

    chip = _jax.devices()[0].device_kind
    key = (f"flash_probe::{hq},{hkv},{sq},{sk},{d},{jnp.dtype(q_dtype)},"
           f"{jnp.dtype(k_dtype)},{jnp.dtype(v_dtype)},{causal},"
           f"{normalize},{tq},{tk},{chip}")
    if key in _probe_memory:
        return _probe_memory[key]
    from triton_distributed_tpu.runtime.autotuner import (
        _load_disk_cache, _store_disk_cache,
    )

    disk = _load_disk_cache()
    if isinstance(disk.get(key), bool):
        _probe_memory[key] = disk[key]
        return disk[key]
    fn = _build_flash(1, hq, hkv, sq, sk, d, q_dtype, k_dtype, v_dtype,
                      causal=causal, normalize=normalize, tq=tq, tk=tk)
    cacheable = True
    try:
        jax.jit(fn).lower(
            jax.ShapeDtypeStruct((2,), jnp.int32),
            jax.ShapeDtypeStruct((1, hq, sq, d), q_dtype),
            jax.ShapeDtypeStruct((1, hkv, sk, d), k_dtype),
            jax.ShapeDtypeStruct((1, hkv, sk, d), v_dtype)).compile()
        ok = True
    except Exception as e:
        msg = str(e).lower()
        if any(s in msg for s in ("vmem", "scoped")):
            # Deterministic Mosaic VMEM rejection — safe to remember.
            ok = False
        else:
            # Anything else (relay HTTP 500, timeouts, transient compile
            # trouble) is INCONCLUSIVE: dispatch the config anyway — the
            # pre-probe code would have — and never cache the verdict, so a
            # network blip can't permanently demote the measured-best tile
            # or abort the caller's trace.
            ok = True
            cacheable = False
    _probe_memory[key] = ok
    if cacheable:
        disk = _load_disk_cache()
        disk[key] = ok
        _store_disk_cache(disk)
    return ok


def _build_flash(b: int, hq: int, hkv: int, sq: int, sk: int, d: int,
                 q_dtype, k_dtype, v_dtype, *, causal: bool, normalize: bool,
                 tq: int, tk: int):
    """Construct the pallas_call closure for one tile config; shared by the
    dispatch path and the compile probe."""
    g = hq // hkv
    nq, nk = sq // tq, sk // tk
    scale = d ** -0.5
    kernel = functools.partial(_flash_kernel, g, nk, tq, tk, scale,
                               causal, normalize)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, tq, d), lambda bb, h, i, j, *_: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, tk, d),
                         lambda bb, h, i, j, *_: (bb, h // g, j, 0)),
            pl.BlockSpec((1, 1, tk, d),
                         lambda bb, h, i, j, *_: (bb, h // g, j, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, tq, d), lambda bb, h, i, j, *_: (bb, h, i, 0)),
            pl.BlockSpec((1, 1, 8, tq), lambda bb, h, i, j, *_: (bb, h, 0, i)),
            pl.BlockSpec((1, 1, 8, tq), lambda bb, h, i, j, *_: (bb, h, 0, i)),
        ),
        scratch_shapes=[
            pltpu.VMEM((tq, d), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
            pltpu.VMEM((tq, 128), jnp.float32),
        ],
    )
    out_dtype = jnp.dtype(q_dtype) if normalize else jnp.float32
    interpret = _interpret_params() if use_interpret() else False
    nbytes = (jnp.dtype(q_dtype).itemsize * b * hq * sq * d
              + jnp.dtype(k_dtype).itemsize * b * hkv * sk * d
              + jnp.dtype(v_dtype).itemsize * b * hkv * sk * d)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b, hq, sq, d), out_dtype),
            jax.ShapeDtypeStruct((b, hq, 8, sq), jnp.float32),
            jax.ShapeDtypeStruct((b, hq, 8, sq), jnp.float32),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "parallel",
                                 "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=4 * b * hq * sq * sk * d,
            bytes_accessed=nbytes
            + b * hq * sq * d * jnp.dtype(out_dtype).itemsize,
            transcendentals=b * hq * sq * sk,
        ),
        interpret=interpret,
    )


def _flash_call(q4, k4, v4, q_offset, k_offset, *, causal: bool,
                normalize: bool, tile_q: int, tile_k: int):
    """Head-major flash attention. q4: (B, hq, Sq, d); k4/v4: (B, hkv, Sk, d).
    Returns (out (B,hq,Sq,d), m (B,hq,Sq), l (B,hq,Sq)).

    Tile selection: the best VMEM-modeled config from :func:`_fit_ladder`;
    on real TPU a config modeled inside the risk band (> _PROBE_SAFE) is
    probe-compiled first and the ladder degrades past configs Mosaic
    rejects — a mis-modeled shape falls down to a smaller tile (or raises
    :class:`FlashCompileError` for the dense fallback) instead of
    hard-failing the whole jit (round-4 advisor finding).
    """
    b, hq, sq, d = q4.shape
    hkv, sk = k4.shape[1], k4.shape[2]
    # tq doubles as the stats blocks' LANE dim: must be 128-divisible (or
    # the full Sq) — _fit_ladder/pick_tile(align=128) guarantee it.
    ladder = _fit_ladder(sq, sk, d, q4.dtype, k4.dtype, tile_q, tile_k)
    if not ladder:
        raise FlashCompileError(
            f"no tile configuration fits VMEM for Sq={sq} Sk={sk} d={d} — "
            "guard calls with flash_supported()")
    itemsize = max(q4.dtype.itemsize, k4.dtype.itemsize)
    probing = not use_interpret()
    chosen = None
    for cand in ladder:
        if (not probing
                or (cand[0], cand[1], d, itemsize) in _KNOWN_GOOD
                or _tile_estimate(cand[0], cand[1], d, itemsize) <= _PROBE_SAFE
                or _probe_ok(hq, hkv, sq, sk, d, q4.dtype, k4.dtype, v4.dtype,
                             causal, normalize, cand[0], cand[1])):
            chosen = cand
            break
    if chosen is None:
        raise FlashCompileError(
            f"no tile configuration compiles for Sq={sq} Sk={sk} d={d} "
            "(every probed candidate hit Mosaic VMEM limits)")
    tq, tk = chosen

    offs = jnp.stack([jnp.asarray(q_offset, jnp.int32).reshape(()),
                      jnp.asarray(k_offset, jnp.int32).reshape(())])
    call = _build_flash(b, hq, hkv, sq, sk, d, q4.dtype, k4.dtype, v4.dtype,
                        causal=causal, normalize=normalize, tq=tq, tk=tk)
    out, m, l = call(offs, q4, k4, v4)
    return out, m[:, :, 0, :], l[:, :, 0, :]


def flash_supported(q, k) -> bool:
    """Whether the tiled kernel handles these shapes within VMEM budget at
    SOME tile configuration (tile caps degrade before giving up; only
    shapes where even the smallest caps blow the budget — e.g. a prime S
    forcing whole-dimension tiles — fall back to the dense path)."""
    b, sq, hq, d = q.shape
    sk = k.shape[1]
    if q.shape[-1] != k.shape[-1] or hq % k.shape[2]:
        return False
    return _fit_tiles(sq, sk, d, q.dtype, k.dtype,
                      DEFAULT_TILE_Q, DEFAULT_TILE_K) is not None


def flash_attention_partial(q, k, v, *, q_offset=0, k_offset=0,
                            causal: bool = True,
                            tile_q: int = DEFAULT_TILE_Q, tile_k: int = DEFAULT_TILE_K):
    """Blockwise flash attention returning UNnormalized partials.

    q: (B, Sq, hq, d); k/v: (B, Sk, hkv, d). Positions are global:
    query row i has position ``q_offset + i``, key row j position
    ``k_offset + j``; causal masks q_pos >= k_pos. Returns
    (acc (B,Sq,hq,d) fp32, m (B,Sq,hq), l (B,Sq,hq)) — the
    ops/ring_attention.py ``_merge`` contract. A shard entirely hidden by
    causality returns l=0 (dead, skipped compute).
    """
    q4 = q.transpose(0, 2, 1, 3)
    k4 = k.transpose(0, 2, 1, 3)
    v4 = v.transpose(0, 2, 1, 3)
    out, m, l = _flash_call(q4, k4, v4, q_offset, k_offset, causal=causal,
                            normalize=False, tile_q=tile_q, tile_k=tile_k)
    return (out.transpose(0, 2, 1, 3), m.transpose(0, 2, 1),
            l.transpose(0, 2, 1))


def flash_attention(q, k, v, *, q_offset=0, k_offset=0, causal: bool = True,
                    tile_q: int = DEFAULT_TILE_Q, tile_k: int = DEFAULT_TILE_K):
    """Normalized flash attention: (B, Sq, hq, d) out in q.dtype — the
    drop-in for dense SDPA on prefill shapes (layers/tp_attn.py,
    ops/ulysses.py)."""
    q4 = q.transpose(0, 2, 1, 3)
    k4 = k.transpose(0, 2, 1, 3)
    v4 = v.transpose(0, 2, 1, 3)
    out, _, _ = _flash_call(q4, k4, v4, q_offset, k_offset, causal=causal,
                            normalize=True, tile_q=tile_q, tile_k=tile_k)
    return out.transpose(0, 2, 1, 3)


def _positional_mask(sq: int, sk: int, q_offset, k_offset, causal: bool):
    if not causal:
        return None
    qpos = jnp.asarray(q_offset) + jnp.arange(sq)
    kpos = jnp.asarray(k_offset) + jnp.arange(sk)
    return qpos[:, None] >= kpos[None, :]


def shard_attention_partial(q, k, v, *, q_offset=0, k_offset=0,
                            causal: bool = True,
                            tile_q: int = DEFAULT_TILE_Q,
                            tile_k: int = DEFAULT_TILE_K,
                            tiles: tuple[int, int] | None = None):
    """Partial attention over one KV shard: tiled flash kernel when the
    shapes support it, dense `_block_attn` otherwise. Same (acc, m, l)
    return contract either way — the single entry point the SP family
    (ring / SP-AG) uses per shard. ``tile_q/tile_k`` (or the ``tiles``
    pair, which wins when given — the host wrappers' autotuned caps)
    override the swept defaults."""
    if tiles is not None:
        tile_q, tile_k = tiles
    if flash_supported(q, k):
        try:
            return flash_attention_partial(q, k, v, q_offset=q_offset,
                                           k_offset=k_offset, causal=causal,
                                           tile_q=tile_q, tile_k=tile_k)
        except FlashCompileError:
            pass      # probed ladder exhausted — dense path below
    mask = _positional_mask(q.shape[1], k.shape[1], q_offset, k_offset,
                            causal)
    return _block_attn(q, k, v, mask)


def resolve_flash_tiles(sq: int, sk: int, hq: int, hkv: int, d: int,
                        dtype, *, cache_only: bool = False,
                        q_offset: int = 0) -> tuple[int, int]:
    """Tile caps for the SP wrappers: on-chip autotuned when tuning is on
    (runtime/autotuner.tuned_flash_tiles — the S=4k optimum measured
    512x1024 while S=32k measured 1024x1024), swept defaults otherwise.

    Call at the HOST level — inside a jit-cache make() (the SP wrappers,
    Engine._prefill_jit): the first call for a new (shape, dtype, chip)
    blocks on real measurements (~30s/candidate through the compile relay)
    and every later call is a disk-cache hit. At TRACE time of an outer
    jit pass ``cache_only=True`` — tuned caps are used when already
    cached, swept defaults otherwise, and measurements are NEVER launched
    mid-trace (round-4 advisor: tuning during Engine tracing stalled the
    default path for minutes)."""
    from triton_distributed_tpu.runtime.autotuner import tuned_flash_tiles

    tiles = tuned_flash_tiles(sq, sk, hq, hkv, d, dtype,
                              cache_only=cache_only, q_offset=q_offset)
    return tiles if tiles else (DEFAULT_TILE_Q, DEFAULT_TILE_K)


def shard_attention(q, k, v, *, causal: bool = True,
                    tile_q: int = DEFAULT_TILE_Q,
                    tile_k: int = DEFAULT_TILE_K,
                    tiles: tuple[int, int] | None = None):
    """Normalized single-shard attention (flash when supported) — the dense
    SDPA drop-in for prefill (ops/ulysses.py, layers/tp_attn.py).
    ``tile_q/tile_k`` (or the ``tiles`` pair, which wins when given)
    override the swept defaults."""
    if tiles is not None:
        tile_q, tile_k = tiles
    if flash_supported(q, k):
        try:
            return flash_attention(q, k, v, causal=causal, tile_q=tile_q,
                                   tile_k=tile_k)
        except FlashCompileError:
            pass      # probed ladder exhausted — dense path below
    mask = _positional_mask(q.shape[1], k.shape[1], 0, 0, causal)
    acc, _, l = _block_attn(q, k, v, mask)
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)
