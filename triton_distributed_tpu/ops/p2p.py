"""Point-to-point transport (pipeline-parallel stage boundary).

Reference: ``python/triton_dist/kernels/nvidia/p2p.py`` — ``p2p_copy_kernel``
push/pull over symmetric buffers (:31,54), wrapped by the PP ``CommOp`` layer
(layers/nvidia/p2p.py:30-132).

TPU form: an explicit-permutation remote copy — every source device pushes its
block into its destination's output; devices that receive wait the delivery,
devices that don't zero their output. ``jax.lax.ppermute`` is the XLA analog
and serves as the golden/fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _p2p_shift_kernel(n: int, axis: str, shift: int, x_ref, out_ref,
                      send_sem, recv_sem):
    """Uniform ring shift by ``shift`` (every device sends; the common PP and
    ring-exchange case — reference p2p push path)."""
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    dst = jax.lax.rem(me + shift + n, n)
    rdma = shmem.putmem_nbi_block(x_ref, out_ref, send_sem, recv_sem, dst,
                                  axis)
    rdma.wait()


def p2p_shift_local(x_local: jax.Array, shift: int = 1, axis: str = "tp",
                    num_ranks: int | None = None,
                    force_kernel: bool = False) -> jax.Array:
    """Device-local ring shift: out on device (d+shift)%n = x from device d.
    The PP stage-boundary transport (activations flow stage d → d+1).

    ``force_kernel``: compile the Pallas kernel even at n=1 (self-push
    loopback) — the on-chip compile gate for this family
    (scripts/check_on_chip.py), same idiom as ag_gemm / the parity
    streams."""
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1 and not force_kernel:
        return x_local
    kernel = functools.partial(_p2p_shift_kernel, n, axis, shift)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x_local.shape, x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
        uses_barrier=True,
    )(x_local)


def p2p_shift(x: jax.Array, ctx: DistContext | None = None, shift: int = 1,
              axis: str = "tp") -> jax.Array:
    """Host-level ring shift of per-device blocks (x sharded over ``axis``)."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, shift, x.shape, str(x.dtype))

    def make():
        return functools.partial(p2p_shift_local, shift=shift, axis=axis,
                                 num_ranks=n)

    return cached_shard_jit(ctx, "p2p_shift", key, make, P(axis), P(axis))(x)


# ---------------------------------------------------------------------------
# Arbitrary-pair P2P (round-4 VERDICT #7). Reference: p2p_copy_kernel
# push/pull between ANY two ranks (kernels/nvidia/p2p.py:31,54), wrapped by
# the PP CommOp layer (layers/nvidia/p2p.py:30-132). The ring shift above
# remains the fast path for the uniform-adjacent case.
# ---------------------------------------------------------------------------

def _as_shift(perm, n: int) -> int | None:
    """The uniform shift amount when ``perm`` is exactly a full ring shift
    (the fast-path detection), else None."""
    if len(perm) != n:
        return None
    shifts = {(d - s) % n for s, d in perm}
    if len(shifts) != 1:
        return None
    if {s for s, _ in perm} != set(range(n)):
        return None
    return shifts.pop()


def _p2p_permute_kernel(n: int, axis: str, perm: tuple, tile_m: int,
                        x_ref, out_ref, vz, send_sems, recv_sems, copy_sem):
    """Static-pair permutation: pair i = (src, dst) pushes src's block into
    dst's output. Per-SOURCE recv semaphores disambiguate concurrent
    transfers (a dst waits exactly the semaphore its src signals — the
    per-pair signal of the reference's CommOp); per-pair send semaphores
    let one src multicast to several dsts. Non-receiving devices zero
    their output (``jax.lax.ppermute`` semantics, which is the golden)."""
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    m = x_ref.shape[0]
    dsts = sorted({d for _, d in perm})
    is_recv = functools.reduce(
        lambda a, b: a | b, [me == d for d in dsts], me < 0)

    # Zero non-receivers FIRST-and-only: a receiver's delivery may already
    # be in flight, so receivers must never touch their output.
    @pl.when(~is_recv)
    def _():
        vz[...] = jnp.zeros_like(vz)
        for t in range(m // tile_m):
            rows = pl.ds(t * tile_m, tile_m)
            cp = pltpu.make_async_copy(vz, out_ref.at[rows], copy_sem)
            cp.start()
            cp.wait()

    # Starts, receives, and send-drains are three passes with IDENTICAL
    # predicates: a wait must run under the same predicate as the start it
    # matches (an unpredicated wait for a predicated start deadlocks), and
    # keeping the drains last lets one src's multicast sends overlap.
    for i, (s, d) in enumerate(perm):

        @pl.when(me == s)
        def _(i=i, s=s, d=d):
            shmem.putmem_nbi_block(
                x_ref, out_ref, send_sems.at[i], recv_sems.at[s], d, axis)

    for s, d in perm:

        @pl.when(me == d)
        def _(s=s):
            shmem.wait_deliveries(x_ref, recv_sems.at[s], 1)

    for i, (s, d) in enumerate(perm):

        @pl.when(me == s)
        def _(i=i):
            # wait_send: drain the pair's send semaphore (same
            # equal-shape-handle idiom as wait_deliveries).
            pltpu.make_async_copy(x_ref, x_ref, send_sems.at[i]).wait()


def p2p_permute_local(x_local: jax.Array, perm, axis: str = "tp",
                      num_ranks: int | None = None,
                      force_kernel: bool = False) -> jax.Array:
    """Device-local arbitrary-pair exchange inside shard_map.

    ``perm``: static sequence of (src, dst) rank pairs — any pairs, not
    just a ring: partial sends (idle devices allowed), multicast (one src,
    several dsts). Each dst appears at most once. Devices that receive
    nothing get zeros (``jax.lax.ppermute`` semantics). A perm that is a
    full uniform ring shift dispatches the single-semaphore shift kernel.

    ``force_kernel``: compile the per-pair-semaphore kernel even at n=1
    (self-push loopback — the on-chip gate; at n=1 the ring fast path is
    suppressed so THIS kernel's structure is what compiles).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    perm = tuple((int(s), int(d)) for s, d in perm)
    dsts = [d for _, d in perm]
    if len(set(dsts)) != len(dsts):
        raise ValueError(f"duplicate destination in perm {perm}")
    for s, d in perm:
        if not (0 <= s < n and 0 <= d < n):
            raise ValueError(f"pair ({s}, {d}) outside 0..{n - 1}")
    if n == 1 and not force_kernel:
        # Same ppermute semantics as n>1: zeros unless the (0, 0)
        # self-pair is present.
        return x_local if (0, 0) in perm else jnp.zeros_like(x_local)
    shift = _as_shift(perm, n)
    # At n=1 every non-empty perm is the full ring ((0,0)); the forced
    # gate must still compile THIS kernel's per-pair semaphore structure,
    # not fall through to the shift kernel (which has its own gate).
    if shift is not None and not (force_kernel and n == 1):
        return p2p_shift_local(x_local, shift=shift, axis=axis,
                               num_ranks=n, force_kernel=force_kernel)
    from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align

    m, cols = x_local.shape
    tile_m = pick_tile(m, 512, sublane_align(x_local.dtype))
    kernel = functools.partial(_p2p_permute_kernel, n, axis, perm, tile_m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x_local.shape, x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.SemaphoreType.DMA((max(len(perm), 1),)),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local)


def p2p_permute(x: jax.Array, perm, ctx: DistContext | None = None,
                axis: str = "tp") -> jax.Array:
    """Host-level arbitrary-pair exchange (x sharded over ``axis``)."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    perm = tuple((int(s), int(d)) for s, d in perm)
    key = (axis, perm, x.shape, str(x.dtype))

    def make():
        return functools.partial(p2p_permute_local, perm=perm, axis=axis,
                                 num_ranks=n)

    return cached_shard_jit(ctx, "p2p_permute", key, make, P(axis),
                            P(axis), ici_axes=(axis,))(x)
