"""Point-to-point transport (pipeline-parallel stage boundary).

Reference: ``python/triton_dist/kernels/nvidia/p2p.py`` — ``p2p_copy_kernel``
push/pull over symmetric buffers (:31,54), wrapped by the PP ``CommOp`` layer
(layers/nvidia/p2p.py:30-132).

TPU form: an explicit-permutation remote copy — every source device pushes its
block into its destination's output; devices that receive wait the delivery,
devices that don't zero their output. ``jax.lax.ppermute`` is the XLA analog
and serves as the golden/fallback.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _p2p_shift_kernel(n: int, axis: str, shift: int, x_ref, out_ref,
                      send_sem, recv_sem):
    """Uniform ring shift by ``shift`` (every device sends; the common PP and
    ring-exchange case — reference p2p push path)."""
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    dst = jax.lax.rem(me + shift + n, n)
    rdma = shmem.putmem_nbi_block(x_ref, out_ref, send_sem, recv_sem, dst,
                                  axis)
    rdma.wait()


def p2p_shift_local(x_local: jax.Array, shift: int = 1, axis: str = "tp",
                    num_ranks: int | None = None) -> jax.Array:
    """Device-local ring shift: out on device (d+shift)%n = x from device d.
    The PP stage-boundary transport (activations flow stage d → d+1)."""
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1:
        return x_local
    kernel = functools.partial(_p2p_shift_kernel, n, axis, shift)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x_local.shape, x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA(())],
        uses_barrier=True,
    )(x_local)


def p2p_shift(x: jax.Array, ctx: DistContext | None = None, shift: int = 1,
              axis: str = "tp") -> jax.Array:
    """Host-level ring shift of per-device blocks (x sharded over ``axis``)."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, shift, x.shape, str(x.dtype))

    def make():
        return functools.partial(p2p_shift_local, shift=shift, axis=axis,
                                 num_ranks=n)

    return cached_shard_jit(ctx, "p2p_shift", key, make, P(axis), P(axis))(x)
