"""Overlapped AllGather + GEMM — the flagship TP column-parallel pattern.

Reference: ``python/triton_dist/kernels/nvidia/allgather_gemm.py`` — a
copy-engine producer all-gathers A shards into a symmetric workspace and sets
per-rank barriers, while a persistent consumer GEMM spins per tile on
``dl.wait`` with a rank-swizzled tile order (:158-264), wrapped in
``AllGatherGEMMTensorParallelContext`` (:417-487) and the ``ag_gemm`` op
(:534).

TPU design (single fused Pallas kernel — the reference's "SM-driven" shape,
since TPU has no separate copy-engine streams):

1. entry barrier (launch alignment);
2. push the local A shard to every peer's workspace, each delivery signaling
   the *per-source-rank* recv semaphore — the analog of the per-rank barrier
   array;
3. consumer loop visits rank chunks in swizzled order (own chunk first),
   waiting each chunk's semaphore before running the tiled MXU matmul over
   it — so compute on chunk r overlaps deliveries of chunks r+1… .

C = all_gather(A_shards) @ B_local, i.e. per device (n·m, n_cols) with B
column-sharded (TP): full output rows for this device's output columns.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.ops.tiling import gemm_tiles, matmul_tiles
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


@dataclasses.dataclass(frozen=True)
class AGGemmConfig:
    """Tile configuration (the tunable surface the reference exposes through
    its autotuner configs; AllGatherGEMMTensorParallelContext analog).

    ``straggler``: optional (rank, cycles) fault injection — that rank spins
    ``cycles`` before producing, widening race windows (reference
    straggler_option, allgather_gemm.py:602-603 via torch.cuda._sleep).
    The rotating form ``("rotate", cycles)`` is accepted too (uniform
    fault coverage with the stream collectives): it resolves against the
    static ``call_index`` — rank ``call_index % n`` straggles; stress
    harnesses vary ``call_index`` across calls.

    ``sub_chunks``: split each rank's shard into this many sub-blocks with
    per-sub-block delivery semaphores — the consumer starts on a remote
    chunk after 1/sub of its rows land instead of the whole shard
    (VERDICT r3 #5; the reference waits per M-TILE, allgather_gemm.py:236).
    Shrinks automatically to a divisor of the shard rows that keeps
    sub-blocks sublane-aligned. Trade-off: the per-sub matmul caps tile_m
    at the sub-block rows, so B re-streams sub× per chunk — finer overlap
    buys earlier first-tile at some extra B traffic.
    """

    tile_m: int = 512
    tile_n: int = 1024
    tile_k: int = 1024
    straggler: tuple | None = None
    call_index: int = 0
    sub_chunks: int = 2
    # Run the degenerate 0-peer kernel at n=1 (single-chip Mosaic compile
    # check of the sub-chunk wait structure, scripts/check_on_chip.py).
    force_kernel: bool = False


def _ag_sub_chunks(m: int, want: int, dtype) -> int:
    from triton_distributed_tpu.ops.tiling import sublane_align

    sa = sublane_align(dtype)
    sub = max(1, want)
    while sub > 1 and (m % sub or (m // sub) % sa):
        sub -= 1
    return sub


def _ag_gemm_kernel(n: int, axis: str, m: int, k: int, ncols: int,
                    tiles, straggler, sub, x_ref, b_ref, out_ref, ws_ref,
                    vacc, send_sems, recv_sems):
    """See module docstring. ws_ref is the AG landing workspace (n·m, k).

    recv_sems: (n, sub) — one DMA semaphore per (source rank, sub-block).
    A single per-source byte-counting semaphore cannot order sub-block
    deliveries (DMA completion order is unspecified, so sub-block 2's
    bytes could satisfy a wait for sub-block 0); per-sub semaphores make
    each wait specific to its rows."""
    me = dl.rank(axis)
    if n > 1:    # n=1 compile checks: Mosaic rejects the barrier
        shmem.barrier_all(axis)    # semaphore on a single-device launch
    dl.maybe_straggle(straggler, me)
    m_sub = m // sub

    # --- producer: per-sub-block local copy + full-mesh push into slot
    # `me` (each delivery signals its own (me, s) semaphore).
    handles = []
    for s in range(sub):
        src = x_ref.at[pl.ds(s * m_sub, m_sub)]
        dst = ws_ref.at[pl.ds(me * m + s * m_sub, m_sub)]
        local = pltpu.make_async_copy(src, dst, recv_sems.at[me].at[s])
        local.start()
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(
                shmem.putmem_nbi_block(src, dst,
                                       send_sems.at[s * (n - 1) + i],
                                       recv_sems.at[me].at[s], peer, axis)
            )

    tm, tk, tn = tiles

    # --- consumer: rank-swizzled chunk loop, wait-then-matmul per
    # SUB-BLOCK (reference kernel_consumer_gemm_persistent waits per
    # M-tile, :217-264 — sub-block granularity is the TPU analog).
    for i in range(n):
        r = jax.lax.rem(me + i, n)
        for s in range(sub):
            rows = pl.ds(r * m + s * m_sub, m_sub)
            shmem.wait_deliveries(x_ref.at[pl.ds(0, m_sub)],
                                  recv_sems.at[r].at[s], 1)
            matmul_tiles(ws_ref.at[rows], b_ref, out_ref.at[rows],
                         m_sub, k, ncols, tm, tk, tn, vacc)
    shmem.quiet(*handles)


def ag_gemm_local(x_local: jax.Array, b_local: jax.Array, axis: str = "tp",
                  num_ranks: int | None = None,
                  cfg: AGGemmConfig = AGGemmConfig(),
                  return_gathered: bool = False):
    """Device-local overlapped AG+GEMM inside an existing shard_map region.

    x_local: (m, k) A shard; b_local: (k, ncols) local B columns.
    Returns (num_ranks·m, ncols) = all_gather(A) @ B_local.

    ``return_gathered``: also return the gathered A block (num_ranks·m, k)
    the kernel assembled in its landing workspace — the hierarchical ops
    (ops/hierarchical.py) ship exactly this block over DCN, so exposing it
    avoids a second intra-slice gather. The workspace is already a kernel
    output buffer (Mosaic has no HBM scratch, language/core.py); this flag
    just stops dropping it.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    m, k = x_local.shape
    k2, ncols = b_local.shape
    if k != k2:
        raise ValueError(f"inner dims mismatch: A has k={k}, B has k={k2}")
    if n == 1 and not cfg.force_kernel:
        # Degenerate world: no communication, but still run the real Pallas
        # compute core so single-chip compile checks exercise the kernel path.
        from triton_distributed_tpu.ops.gemm import pallas_matmul

        out = pallas_matmul(x_local, b_local, tile_m=cfg.tile_m,
                            tile_n=cfg.tile_n, tile_k=cfg.tile_k)
        return (out, x_local) if return_gathered else out
    sub = _ag_sub_chunks(m, cfg.sub_chunks, x_local.dtype)
    # Tiles derive from the SUB-BLOCK rows: a tile that divides m but not
    # m/sub would make matmul_tiles' floored grid silently drop the
    # sub-block's remainder rows.
    tm, tk, tn = gemm_tiles(m // sub, k, ncols, x_local.dtype, cfg)
    straggler = dl.resolve_straggler(cfg.straggler, n, cfg.call_index)
    kernel = functools.partial(_ag_gemm_kernel, n, axis, m, k, ncols,
                               (tm, tk, tn), straggler, sub)
    ws = jax.ShapeDtypeStruct((n * m, k), x_local.dtype)  # AG landing ws
    out_shape = jax.ShapeDtypeStruct((n * m, ncols), x_local.dtype)
    # With return_gathered the landing workspace is promoted to a real
    # output — the ref ordering the kernel sees is identical either way
    # (workspaces are appended after the real outputs, language/core.py).
    out = kernel_call(
        kernel,
        out_shape=(out_shape, ws) if return_gathered else out_shape,
        in_specs=[any_spec(), any_spec()],
        out_specs=(any_spec(), any_spec()) if return_gathered else any_spec(),
        workspaces=() if return_gathered else (ws,),
        scratch_shapes=[
            pltpu.VMEM((tm, tn), jnp.float32),
            pltpu.SemaphoreType.DMA((max((n - 1) * sub, 1),)),
            pltpu.SemaphoreType.DMA((n, sub)),
        ],
        uses_barrier=n > 1,
    )(x_local, b_local)
    return out


def resolve_gemm_cfg(cfg, cfg_cls, m_chunk: int, k: int, ncols: int, dtype):
    """``cfg=None`` resolves the tile config through the contextual
    autotuner on real TPU (disk-cached; measured at the per-chunk GEMM
    shape the consumer loop runs), static dataclass defaults otherwise.
    VERDICT r2 #3: the default path goes through the tuner."""
    if cfg is not None:
        return cfg
    from triton_distributed_tpu.runtime.autotuner import tuned_matmul_tiles

    tiles = tuned_matmul_tiles(m_chunk, k, ncols, dtype)
    if tiles is None:
        return cfg_cls()
    tm, tn, tk = tiles
    return cfg_cls(tile_m=tm, tile_n=tn, tile_k=tk)


def ag_gemm(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
            axis: str = "tp",
            cfg: AGGemmConfig | None = None) -> jax.Array:
    """Host-level overlapped AG+GEMM (reference ``ag_gemm`` allgather_gemm.py:534).

    a: (n·m, k) globally, row-sharded over ``axis`` (each device one shard);
    b: (k, n·ncols) globally, column-sharded over ``axis`` (TP weights).
    Returns (n·m, n·ncols) sharded over columns, i.e. the standard TP
    column-parallel activation layout.
    """
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    if cfg is None and n > 1:
        from triton_distributed_tpu.runtime.autotuner import (
            comm_autotune_enabled, tune_ag_gemm,
        )

        if comm_autotune_enabled():
            # Whole-thunk comm tuning (tiles + sub-chunk depth measured
            # with the real AG in the loop) — reference
            # contextual_autotune(is_dist=True), autotuner.py:97.
            cfg = tune_ag_gemm(a, b, ctx, axis=axis)
    cfg = resolve_gemm_cfg(cfg, AGGemmConfig, a.shape[0] // n, a.shape[1],
                           b.shape[1] // n, a.dtype)
    key = (axis, a.shape, b.shape, str(a.dtype), str(b.dtype), cfg)

    def make():
        fn = functools.partial(ag_gemm_local, axis=axis, num_ranks=n, cfg=cfg)
        return fn

    jfn = cached_shard_jit(ctx, "ag_gemm", key, make,
                           (P(axis), P(None, axis)), P(None, axis),
                           ici_axes=(axis,))
    return jfn(a, b)
