"""MoE TP kernels — AG+GroupGEMM and MoE+ReduceScatter.

Reference: ``python/triton_dist/kernels/nvidia/allgather_group_gemm.py``
(ctx :201, ``ag_group_gemm`` :401 — all-gather tokens then grouped GEMM over
experts with a sorted gather index built by the CUDA alignment op
``csrc/lib/moe_utils.cu:61``) and ``moe_reduce_rs.py`` (grouped GEMM →
topk-weighted reduce → reduce-scatter; ``run_moe_reduce_rs`` :569).

TPU design:
- token→expert alignment is pure XLA (stable argsort + segment_sum — the
  ``moe_utils.cu`` replacement; same approach as ops/all_to_all.py);
- the gather rides the Pallas full-mesh AllGather;
- the grouped GEMM is ``jax.lax.ragged_dot`` — XLA's native grouped matmul
  that tiles expert groups onto the MXU (the role of the reference's
  hand-written grouped-GEMM Triton kernel);
- the combine rides the Pallas ring ReduceScatter.

Overlap (round-3, VERDICT r2 #4): mode="ring" replaces the sequential
AG→GroupGEMM with a ring pipeline — token chunks rotate over the ICI ring
via ``ppermute`` while each hop runs the full per-chunk expert MLP
(router → sort → gate/up → weighted down-proj partial), so hop i+1's
communication overlaps hop i's grouped GEMMs (XLA's async collective
permute + latency-hiding scheduler; the same schedule ops/ring_attention.py
uses). This is the per-source-chunk readiness structure of the reference's
``MoEAllGatherGroupGEMMTensorParallelContext`` consumer
(allgather_group_gemm.py:201-608) expressed ring-wise.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import all_gather_local, AllGatherMethod
from triton_distributed_tpu.ops.reduce_scatter import reduce_scatter_local
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def sort_by_expert(expert_ids: jax.Array, num_experts: int):
    """Stable sort of flat expert assignments.

    Returns (sort_idx (T,), group_sizes (E,) int32) — the alignment metadata
    the reference builds with ``moe_ag_scatter_align_block_size``.
    """
    expert_ids = expert_ids.astype(jnp.int32)
    sort_idx = jnp.argsort(expert_ids, stable=True)
    group_sizes = jax.ops.segment_sum(
        jnp.ones_like(expert_ids), expert_ids, num_segments=num_experts)
    return sort_idx, group_sizes.astype(jnp.int32)


def ragged_dot_dtype_aware(x: jax.Array, w: jax.Array,
                           group_sizes: jax.Array) -> jax.Array:
    """The grouped matmul every expert GEMM routes through (ROADMAP 1a
    tail: the fp8 lane covers MoE experts too). Full-width weights run
    the plain ``ragged_dot``; ``float8_e4m3fn`` expert stacks
    (models/fp8.quantize_dense_weights) run the PURE fp8 configuration —
    the activation quantizes to e4m3 at the dot (saturating cast) and
    the e4m3×e4m3 products accumulate in fp32, exactly the
    :func:`~triton_distributed_tpu.models.fp8.fp8_dot` contract. The
    mixed bf16×fp8 form (upcast weights, wide activations) is NEVER run:
    it measured ~0.3× bf16 on this chip generation (docs/gemm_core.md).
    Output returns in the activation's dtype."""
    if w.dtype == jnp.float8_e4m3fn:
        from triton_distributed_tpu.models.fp8 import _to_e4m3

        out = jax.lax.ragged_dot(_to_e4m3(x), w, group_sizes,
                                 preferred_element_type=jnp.float32)
        out_dt = (x.dtype if x.dtype != jnp.float8_e4m3fn
                  else jnp.float32)
        return out.astype(out_dt)
    return jax.lax.ragged_dot(x, w, group_sizes)


def grouped_mlp(x_sorted: jax.Array, group_sizes: jax.Array,
                w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array):
    """SwiGLU expert MLP over expert-sorted tokens via ragged_dot.

    x_sorted: (T, h); w_*: (E, h, ffn) / (E, ffn, h). Returns (T, h)."""
    gate = ragged_dot_dtype_aware(x_sorted, w_gate, group_sizes)
    up = ragged_dot_dtype_aware(x_sorted, w_up, group_sizes)
    act = jax.nn.silu(gate) * up
    return ragged_dot_dtype_aware(act.astype(x_sorted.dtype), w_down,
                                  group_sizes)


def ag_group_gemm_local(x_local: jax.Array, expert_ids: jax.Array,
                        w_experts: jax.Array, topk_weights: jax.Array | None
                        = None, *, axis: str = "tp",
                        num_ranks: int | None = None,
                        method: AllGatherMethod | str = AllGatherMethod.AUTO):
    """Device-local AG+GroupGEMM inside shard_map.

    x_local: (M/n, h) row-sharded tokens; expert_ids: (M·topk,) replicated
    flat assignment (token t's k-th expert at t·topk+k); w_experts:
    (E, h, ffn_local) — expert weights column-sharded over ranks.

    Returns (y_sorted (M·topk, ffn_local), sort_idx, group_sizes): grouped
    GEMM output in expert-sorted order plus the alignment metadata needed to
    un-sort (reference ``ag_group_gemm``, allgather_group_gemm.py:401).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    E = w_experts.shape[0]
    x_full = (x_local if n == 1 else
              all_gather_local(x_local, axis=axis, num_ranks=n, method=method))
    M = x_full.shape[0]
    topk = expert_ids.shape[0] // M
    sort_idx, group_sizes = sort_by_expert(expert_ids, E)
    token_of_flat = sort_idx // topk
    x_sorted = x_full[token_of_flat]
    y_sorted = ragged_dot_dtype_aware(x_sorted, w_experts, group_sizes)
    if topk_weights is not None:
        y_sorted = y_sorted * topk_weights.reshape(-1)[sort_idx][:, None]
    return y_sorted.astype(x_local.dtype), sort_idx, group_sizes


def ag_group_gemm_ring_local(x_local: jax.Array, expert_ids: jax.Array,
                             w_experts: jax.Array,
                             topk_weights: jax.Array | None = None, *,
                             axis: str = "tp",
                             num_ranks: int | None = None):
    """AG+GroupGEMM with PER-SOURCE readiness: each source's token chunk
    runs its grouped GEMM the moment it arrives on the ring, instead of
    after the full AllGather (round-4 VERDICT #6; reference consumers wait
    per-chunk inside the grouped GEMM,
    ``allgather_group_gemm.py:201-608``). Same contract as
    :func:`ag_group_gemm_local` — (y_sorted (M·topk, ffn_local), sort_idx,
    group_sizes) in GLOBAL expert-sorted order — so the two are drop-in
    interchangeable; the cost of per-source compute is one extra row
    permutation pair (chunk-local scatter + global gather).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    E = w_experts.shape[0]
    if n == 1:
        return ag_group_gemm_local(x_local, expert_ids, w_experts,
                                   topk_weights, axis=axis, num_ranks=1)
    me = jax.lax.axis_index(axis)
    mc = x_local.shape[0]
    M = mc * n
    topk = expert_ids.shape[0] // M
    ffn = w_experts.shape[2]
    w_flat = (None if topk_weights is None else topk_weights.reshape(-1))

    def chunk_gemm(src, xc):
        """One source chunk: sort ITS tokens by expert, grouped GEMM,
        un-sort back to flat (token-major) order."""
        f0 = src * mc * topk
        e_c = jax.lax.dynamic_slice_in_dim(expert_ids, f0, mc * topk)
        sidx_c, gsz_c = sort_by_expert(e_c, E)
        y_c = ragged_dot_dtype_aware(xc[sidx_c // topk], w_experts, gsz_c)
        if w_flat is not None:
            wf = jax.lax.dynamic_slice_in_dim(w_flat, f0, mc * topk)
            y_c = y_c * wf[sidx_c][:, None]
        return jnp.zeros((mc * topk, ffn), y_c.dtype).at[sidx_c].set(y_c)

    out = jnp.zeros((n, mc * topk, ffn), x_local.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def compute_into(out, src, xc):
        y = chunk_gemm(src, xc).astype(x_local.dtype)
        return jax.lax.dynamic_update_slice(out, y[None], (src, 0, 0))

    # Ring rotation with compute under the DMA (the moe_ring schedule):
    # own chunk computes while hop 1 is in flight, etc.
    xc = jax.lax.ppermute(x_local, axis, perm)
    out = compute_into(out, me, x_local)

    def body(i, carry):
        out, xc = carry
        xc_next = jax.lax.ppermute(xc, axis, perm)
        src = jax.lax.rem(me - i + n, n)
        return compute_into(out, src, xc), xc_next

    out, xc = jax.lax.fori_loop(1, n - 1, body, (out, xc))
    out = compute_into(out, jax.lax.rem(me - (n - 1) + n, n), xc)

    y_flat = out.reshape(M * topk, ffn)        # flat token-major order
    sort_idx, group_sizes = sort_by_expert(expert_ids, E)
    return y_flat[sort_idx], sort_idx, group_sizes


def moe_reduce_rs_local(y_sorted: jax.Array, sort_idx: jax.Array,
                        group_sizes: jax.Array, w_down: jax.Array,
                        topk_weights: jax.Array, num_tokens: int, *,
                        axis: str = "tp", num_ranks: int | None = None,
                        mode: str = "overlap", ar_fn=None):
    """Device-local MoE down-proj + topk-combine + ReduceScatter.

    y_sorted: (M·topk, ffn_local) expert-sorted activations; w_down:
    (E, ffn_local, h) row-sharded expert down-proj; topk_weights: (M, topk).
    Returns (M/n, h) row-sharded (overlap/xla) or (M, h) replicated (ar
    modes): the fully-reduced token rows (reference ``run_moe_reduce_rs``,
    moe_reduce_rs.py:569 — grouped GEMM → weighted scatter-add →
    reduce-scatter).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    M = num_tokens
    topk = sort_idx.shape[0] // M
    partial_sorted = ragged_dot_dtype_aware(y_sorted, w_down, group_sizes)
    w_flat = topk_weights.reshape(-1)[sort_idx]
    partial_sorted = partial_sorted * w_flat[:, None]
    token_of_flat = sort_idx // topk
    combined = jax.ops.segment_sum(partial_sorted, token_of_flat,
                                   num_segments=M)  # (M, h) partial over ffn
    combined = combined.astype(y_sorted.dtype)
    if n == 1:
        return combined
    if mode == "overlap":
        return reduce_scatter_local(combined, axis=axis, num_ranks=n)
    if mode == "xla":
        return jax.lax.psum_scatter(combined, axis, scatter_dimension=0,
                                    tiled=True)
    if mode == "ar":
        if ar_fn is not None:
            return ar_fn(combined)
        from triton_distributed_tpu.ops.allreduce import all_reduce_local

        return all_reduce_local(combined, axis=axis, num_ranks=n)
    if mode == "xla_rep":
        return jax.lax.psum(combined, axis)
    raise ValueError(f"unknown MoE mode {mode!r}")


def moe_reduce_rs_overlap_local(act_sorted: jax.Array, sort_idx: jax.Array,
                                group_sizes: jax.Array, w_down: jax.Array,
                                topk_weights: jax.Array, num_tokens: int, *,
                                axis: str = "tp",
                                num_ranks: int | None = None) -> jax.Array:
    """Overlapped MoE tail: the RS accumulator leaves on the ring while
    LATER chunks' expert down-projections still compute — replacing the
    sequential grouped-GEMM → combine → ring-RS of
    :func:`moe_reduce_rs_local` (round-4 VERDICT #6; reference fuses the
    reduce into the grouped GEMM, ``moe_reduce_rs.py:167,293-546``).

    Schedule (the ``moe_ring_fwd_local`` trick applied to the OUTPUT side):
    the M token rows split into n ring chunks; at step s this device
    computes the down-proj + topk-combine partial for chunk (me-2-s) while
    the running ring-RS accumulator for the previous chunk is in flight
    via ``ppermute`` — XLA's async collective permute runs the DMA under
    the ragged_dot. After n-1 hops the accumulator this device holds is
    its own fully-reduced chunk.

    act_sorted: (M·topk, ffn_local) expert-sorted SwiGLU activations (the
    global sort of ``route_and_sort``); returns (M/n, h) row-sharded —
    the ``mode="overlap"`` layout of moe_reduce_rs_local.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    M = num_tokens
    topk = sort_idx.shape[0] // M
    E = w_down.shape[0]
    if n == 1 or M % n:
        out = moe_reduce_rs_local(act_sorted, sort_idx, group_sizes, w_down,
                                  topk_weights, M, axis=axis, num_ranks=n,
                                  mode="overlap" if n > 1 else "ar")
        return out
    me = jax.lax.axis_index(axis)
    mc = M // n
    # inv[f]: sorted position of flat slot f; expert of a sorted position
    # recovered from the group prefix sums (no expert_ids arg needed).
    inv = jnp.argsort(sort_idx)
    csum = jnp.cumsum(group_sizes)
    w_flat = topk_weights.reshape(-1)

    def chunk_partial(c):
        """Down-proj + topk-combine for token chunk c: re-sort just this
        chunk's topk rows by expert and ragged_dot them — the chunk's
        grouped GEMM starts without waiting for any other chunk."""
        f0 = c * mc * topk
        fr = f0 + jnp.arange(mc * topk)           # flat slots, token-major
        pos = inv[fr]                              # their sorted positions
        e_c = jnp.searchsorted(csum, pos, side="right").astype(jnp.int32)
        sidx_c, gsz_c = sort_by_expert(e_c, E)
        rows = act_sorted[pos[sidx_c]]
        part = ragged_dot_dtype_aware(rows, w_down, gsz_c)
        part = part * w_flat[fr][sidx_c][:, None]
        tloc = (fr // topk - c * mc)[sidx_c]
        return jax.ops.segment_sum(part, tloc, num_segments=mc
                                   ).astype(act_sorted.dtype)

    perm = [(i, (i + 1) % n) for i in range(n)]
    # Step 0: compute chunk me-1 (the accumulator this device originates).
    carry = chunk_partial(jax.lax.rem(me - 1 + n, n))
    for s in range(n - 1):
        sent = jax.lax.ppermute(carry, axis, perm)     # DMA in flight...
        nxt = chunk_partial(jax.lax.rem(me - 2 - s + 2 * n, n))  # ...under this GEMM
        carry = sent + nxt
    return carry


def route_and_sort(x: jax.Array, gate_w: jax.Array, topk: int):
    """THE routing convention, in one place: fp32 router logits → top-k →
    softmax over the selected experts (Qwen-MoE; hf_loader rejects
    norm_topk_prob=False because of exactly this) → expert-stable sort.

    Returns (x_sorted, sort_idx, group_sizes, token_of_flat, topk_weights).
    """
    E = gate_w.shape[1]
    logits = x.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    topk_logits, topk_ids = jax.lax.top_k(logits, topk)
    topk_weights = jax.nn.softmax(topk_logits, axis=-1)
    sort_idx, group_sizes = sort_by_expert(topk_ids.reshape(-1), E)
    token_of_flat = sort_idx // topk
    return x[token_of_flat], sort_idx, group_sizes, token_of_flat, \
        topk_weights


def _chunk_moe(xc: jax.Array, gate_w: jax.Array, w_gate: jax.Array,
               w_up: jax.Array, w_down: jax.Array, topk: int):
    """Full expert-MLP partial for one token chunk: router → top-k → sort →
    gate/up grouped GEMM → SwiGLU → weighted down-proj → per-token combine.
    xc: (mc, h). Returns (mc, h) — partial over this rank's ffn shard."""
    mc = xc.shape[0]
    x_sorted, sort_idx, group_sizes, token_of_flat, topk_weights = \
        route_and_sort(xc, gate_w, topk)
    act = grouped_mlp_gate_up(x_sorted, group_sizes, w_gate, w_up)
    part = ragged_dot_dtype_aware(act, w_down, group_sizes)
    part = part * topk_weights.reshape(-1)[sort_idx][:, None]
    return jax.ops.segment_sum(part, token_of_flat,
                               num_segments=mc).astype(xc.dtype)


def moe_ring_fwd_local(x_local: jax.Array, gate_w: jax.Array,
                       w_gate: jax.Array, w_up: jax.Array,
                       w_down: jax.Array, topk: int, *, axis: str,
                       num_ranks: int, combine: str = "overlap"):
    """Ring-pipelined TP-MoE: chunk rotation overlaps expert compute.

    Hop i computes the full per-chunk MoE partial for the chunk that just
    arrived while ``ppermute`` rotates the buffer onward — the
    communication of hop i+1 rides under the grouped GEMMs of hop i.
    Returns (M/n, h) row-sharded like mode="overlap".
    """
    n = num_ranks
    me = jax.lax.axis_index(axis)
    mc, h = x_local.shape
    out = jnp.zeros((n, mc, h), x_local.dtype)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def compute_into(out, src, xc):
        y = _chunk_moe(xc, gate_w, w_gate, w_up, w_down, topk)
        return jax.lax.dynamic_update_slice(out, y[None], (src, 0, 0))

    # Exactly n-1 rotations: each hop's ppermute is issued on data the hop's
    # compute does NOT consume, so the DMA rides under the grouped GEMMs;
    # the last arriving chunk is computed after the loop with no further
    # rotation.
    xc = jax.lax.ppermute(x_local, axis, perm)   # hop-1 data in flight...
    out = compute_into(out, me, x_local)         # ...under hop-0 compute

    def body(i, carry):
        out, xc = carry
        xc_next = jax.lax.ppermute(xc, axis, perm)
        src = jax.lax.rem(me - i + n, n)
        return compute_into(out, src, xc), xc_next

    out, xc = jax.lax.fori_loop(1, n - 1, body, (out, xc))
    out = compute_into(out, jax.lax.rem(me - (n - 1) + n, n), xc)
    combined = out.reshape(n * mc, h)        # (M, h) partial over ffn
    if combine == "overlap":
        return reduce_scatter_local(combined, axis=axis, num_ranks=n)
    return jax.lax.psum_scatter(combined, axis, scatter_dimension=0,
                                tiled=True)


def moe_tp_fwd_local(x_local: jax.Array, gate_w: jax.Array,
                     w_gate: jax.Array, w_up: jax.Array, w_down: jax.Array,
                     topk: int, *, axis: str = "tp",
                     num_ranks: int | None = None, mode: str = "ring",
                     ar_fn=None):
    """Full TP-MoE forward: router → AG+GroupGEMM (gate/up) → SwiGLU →
    MoE+RS (down) — the composition the reference's TP_MoE layer runs
    (layers/nvidia/tp_moe.py).

    x_local: (M/n, h) row-sharded (ring/overlap/xla) or (M, h) replicated
    (ar/xla_rep — the decode layout); gate_w: (h, E) replicated router;
    w_gate/w_up: (E, h, ffn_local); w_down: (E, ffn_local, h). Returns the
    same layout it was given. ``mode="ring"`` (default) pipelines chunk
    rotation under expert compute; "overlap" is the sequential Pallas
    AG → GroupGEMM; "xla" the lax.all_gather golden.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    E = gate_w.shape[1]
    if mode == "ring" and n > 1:
        return moe_ring_fwd_local(x_local, gate_w, w_gate, w_up, w_down,
                                  topk, axis=axis, num_ranks=n)
    if n == 1 or mode in ("ar", "xla_rep"):
        x_full = x_local
    elif mode == "overlap":
        x_full = all_gather_local(x_local, axis=axis, num_ranks=n)
    elif mode == "xla":
        x_full = jax.lax.all_gather(x_local, axis, tiled=True)
    else:
        raise ValueError(f"unknown MoE mode {mode!r}")
    M = x_full.shape[0]
    x_sorted, sort_idx, group_sizes, _, topk_weights = route_and_sort(
        x_full, gate_w, topk)
    act = grouped_mlp_gate_up(x_sorted, group_sizes, w_gate, w_up)
    if mode == "overlap" and n > 1 and M % n == 0:
        # Overlapped tail: RS accumulator hops ride under the next chunk's
        # down-proj grouped GEMM (VERDICT r4 #6) — replaces the sequential
        # combine-then-RS below on the row-sharded path.
        return moe_reduce_rs_overlap_local(
            act, sort_idx, group_sizes, w_down,
            topk_weights.astype(x_local.dtype), M, axis=axis, num_ranks=n)
    return moe_reduce_rs_local(
        act, sort_idx, group_sizes, w_down,
        topk_weights.astype(x_local.dtype), M, axis=axis, num_ranks=n,
        mode="overlap" if mode == "ring" else mode, ar_fn=ar_fn)


def grouped_mlp_gate_up(x_sorted, group_sizes, w_gate, w_up):
    gate = ragged_dot_dtype_aware(x_sorted, w_gate, group_sizes)
    up = ragged_dot_dtype_aware(x_sorted, w_up, group_sizes)
    return (jax.nn.silu(gate) * up).astype(x_sorted.dtype)


def moe_tp_fwd(x: jax.Array, gate_w: jax.Array, w_gate: jax.Array,
               w_up: jax.Array, w_down: jax.Array, topk: int,
               ctx: DistContext | None = None, axis: str = "tp",
               mode: str = "ring") -> jax.Array:
    """Host-level TP-MoE forward. x: (M, h) row-sharded over ``axis``;
    router replicated; expert ffn weights sharded on the ffn dim
    (w_gate/w_up dim 2, w_down dim 1). Returns (M, h) row-sharded."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, x.shape, w_gate.shape, topk, str(x.dtype), mode)

    def make():
        return functools.partial(moe_tp_fwd_local, topk=topk, axis=axis,
                                 num_ranks=n, mode=mode)

    jfn = cached_shard_jit(
        ctx, "moe_tp_fwd", key, make,
        (P(axis), P(), P(None, None, axis), P(None, None, axis),
         P(None, axis, None)), P(axis))
    return jfn(x, gate_w, w_gate, w_up, w_down)
