"""Tile-centric overlapped kernel library.

TPU-native analog of ``python/triton_dist/kernels/nvidia/`` (SURVEY.md §2.4):
each op ships a Pallas-TPU implementation (remote DMA + semaphores over ICI)
plus an XLA-collective reference used as golden and fallback.
"""

from triton_distributed_tpu.ops.allgather import (  # noqa: F401
    AllGatherMethod,
    ag_stream_workspace,
    all_gather,
    all_gather_stream,
    get_auto_all_gather_method,
)
from triton_distributed_tpu.ops.reduce_scatter import reduce_scatter  # noqa: F401
from triton_distributed_tpu.ops.allreduce import (  # noqa: F401
    AllReduceMethod,
    all_reduce,
    all_reduce_stream,
    ar_stream_workspace,
    get_auto_allreduce_method,
)
from triton_distributed_tpu.ops.allgather_gemm import (  # noqa: F401
    AGGemmConfig,
    ag_gemm,
    ag_gemm_local,
)
from triton_distributed_tpu.ops.gemm_reduce_scatter import (  # noqa: F401
    GemmRSConfig,
    gemm_rs,
    gemm_rs_local,
)
from triton_distributed_tpu.ops.gemm_allreduce import (  # noqa: F401
    gemm_allreduce,
    gemm_ar_local,
)
from triton_distributed_tpu.ops.p2p import (  # noqa: F401
    p2p_permute,
    p2p_permute_local,
    p2p_shift,
    p2p_shift_local,
)
from triton_distributed_tpu.ops.all_to_all import (  # noqa: F401
    a2a_stream_workspace,
    fast_all_to_all,
    fast_all_to_all_local,
    fast_all_to_all_stream,
    dispatch_layout,
    combine_layout,
)
from triton_distributed_tpu.ops.ring_attention import (  # noqa: F401
    ring_attention,
    ring_attention_local,
)
from triton_distributed_tpu.ops.sp_ag_attention import (  # noqa: F401
    sp_ag_attention,
    sp_ag_attention_local,
)
from triton_distributed_tpu.ops.ulysses import (  # noqa: F401
    ulysses_attention,
    ulysses_attention_local,
)
from triton_distributed_tpu.ops.flash_decode import (  # noqa: F401
    flash_decode,
    flash_decode_local,
    combine_partials,
)
from triton_distributed_tpu.ops.paged_attention import (  # noqa: F401
    PagedKVCache,
    init_paged_kv_cache,
    paged_append,
    paged_decode_attention,
)
from triton_distributed_tpu.ops.flash_attention import (  # noqa: F401
    flash_attention,
    flash_attention_partial,
    flash_supported,
    shard_attention,
    shard_attention_partial,
)
from triton_distributed_tpu.ops.gemm import (  # noqa: F401
    pallas_matmul,
    pallas_matmul_tuned,
)
from triton_distributed_tpu.ops.moe import (  # noqa: F401
    ag_group_gemm_local,
    ag_group_gemm_ring_local,
    moe_reduce_rs_overlap_local,
    grouped_mlp,
    moe_reduce_rs_local,
    moe_tp_fwd,
    moe_tp_fwd_local,
    sort_by_expert,
)
from triton_distributed_tpu.ops.low_latency_allgather import (  # noqa: F401
    AllGatherLayer,
    fast_allgather,
    fast_allgather_local,
)
from triton_distributed_tpu.ops.two_level import (  # noqa: F401
    all_gather_2d,
    all_reduce_2d,
    reduce_scatter_2d,
)
from triton_distributed_tpu.ops.hierarchical import (  # noqa: F401
    ag_gemm_2d,
    ag_gemm_2d_local,
    gemm_rs_2d,
    gemm_rs_2d_local,
    sp_ag_attention_2d,
    sp_ag_attention_2d_local,
)
from triton_distributed_tpu.ops.multi_axis import (  # noqa: F401
    all_gather_torus,
    all_gather_torus_local,
    all_reduce_torus,
    all_reduce_torus_local,
    reduce_scatter_torus,
    reduce_scatter_torus_local,
)
