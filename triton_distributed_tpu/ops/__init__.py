"""Tile-centric overlapped kernel library.

TPU-native analog of ``python/triton_dist/kernels/nvidia/`` (SURVEY.md §2.4):
each op ships a Pallas-TPU implementation (remote DMA + semaphores over ICI)
plus an XLA-collective reference used as golden and fallback.
"""

from triton_distributed_tpu.ops.allgather import (  # noqa: F401
    AllGatherMethod,
    all_gather,
    get_auto_all_gather_method,
)
from triton_distributed_tpu.ops.reduce_scatter import reduce_scatter  # noqa: F401
from triton_distributed_tpu.ops.allreduce import (  # noqa: F401
    AllReduceMethod,
    all_reduce,
    get_auto_allreduce_method,
)
from triton_distributed_tpu.ops.allgather_gemm import (  # noqa: F401
    AGGemmConfig,
    ag_gemm,
    ag_gemm_local,
)
from triton_distributed_tpu.ops.gemm_reduce_scatter import (  # noqa: F401
    GemmRSConfig,
    gemm_rs,
    gemm_rs_local,
)
from triton_distributed_tpu.ops.gemm_allreduce import (  # noqa: F401
    gemm_allreduce,
    gemm_ar_local,
)
from triton_distributed_tpu.ops.p2p import p2p_shift, p2p_shift_local  # noqa: F401
