"""Two-level collectives — Pallas over ICI within a slice, XLA over DCN.

Reference: the reference is two-tier everywhere — copy-engine/NVLink intra-
node + NVSHMEM/IB inter-node (e.g. ``allgather.py:293-378`` 2D inter-node
ring, ``reduce_scatter.py:506`` inter-node p2p, CommScope INTRA/INTER_NODE).
SURVEY.md §7 maps the inter tier to DCN, where Pallas remote DMA does not
reach: the idiomatic TPU split is Pallas kernels on the intra-slice axis and
``jax.lax`` collectives (XLA's DCN-aware transfers) on the inter-slice axis.

Mesh convention: 2-D mesh ``(inter_axis, intra_axis)`` — e.g.
``initialize_distributed(mesh_shape=(2, 4), axis_names=("dcn", "tp"))``.
Global shard index of a device = ``inter_idx * n_intra + intra_idx``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import all_gather_local, AllGatherMethod
from triton_distributed_tpu.ops.reduce_scatter import reduce_scatter_local
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def all_gather_2d_local(x_local: jax.Array, *, intra_axis: str = "tp",
                        inter_axis: str = "dcn",
                        n_intra: int | None = None,
                        n_inter: int | None = None) -> jax.Array:
    """Hierarchical AllGather: Pallas intra-slice, lax over DCN.

    x_local: (m, cols) per device → (n_inter·n_intra·m, cols), rows ordered
    by global shard index. Intra first (big ICI bandwidth), then the
    slice-gathered blocks cross DCN once (reference 2D inter-node AG,
    allgather.py:293-378).
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    intra = all_gather_local(x_local, axis=intra_axis, num_ranks=n_intra)
    if n_inter == 1:
        return intra
    return jax.lax.all_gather(intra, inter_axis, tiled=True)


def reduce_scatter_2d_local(x_local: jax.Array, *, intra_axis: str = "tp",
                            inter_axis: str = "dcn",
                            n_intra: int | None = None,
                            n_inter: int | None = None) -> jax.Array:
    """Hierarchical ReduceScatter: lax over DCN first (cuts DCN bytes to
    1/n_inter), then the Pallas ring within the slice.

    x_local: (N·m, cols) contributions, N = n_inter·n_intra →
    (m, cols): this device's fully-reduced global chunk.
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    if n_inter > 1:
        # DCN tier first: each slice keeps its (n_intra·m)-row block, summed
        # over slices — DCN carries 1/n_inter of the bytes, once.
        x_local = jax.lax.psum_scatter(x_local, inter_axis,
                                       scatter_dimension=0, tiled=True)
    if n_intra == 1:
        return x_local
    return reduce_scatter_local(x_local, axis=intra_axis, num_ranks=n_intra)


def all_reduce_2d_local(x_local: jax.Array, *, intra_axis: str = "tp",
                        inter_axis: str = "dcn",
                        n_intra: int | None = None,
                        n_inter: int | None = None) -> jax.Array:
    """Hierarchical AllReduce: intra RS (Pallas ring) → DCN psum (on 1/n_intra
    of the data) → intra AG (Pallas ring) — the classic two-tier two-shot
    (the reference's inter-node AR composition; multimem-free)."""
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    m, cols = x_local.shape
    if n_intra == 1 or m % n_intra:
        summed = x_local if n_intra == 1 else jax.lax.psum(x_local, intra_axis)
        return jax.lax.psum(summed, inter_axis) if n_inter > 1 else summed
    scattered = reduce_scatter_local(x_local, axis=intra_axis,
                                     num_ranks=n_intra)
    if n_inter > 1:
        scattered = jax.lax.psum(scattered, inter_axis)
    return all_gather_local(scattered, axis=intra_axis, num_ranks=n_intra,
                            method=AllGatherMethod.RING_1D)


def _two_level(ctx, name, local_fn, x, intra_axis, inter_axis, out_spec_fn,
               stacked: bool):
    n_intra = ctx.axis_size(intra_axis)
    n_inter = ctx.axis_size(inter_axis)
    key = (name, intra_axis, inter_axis, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(local_fn, intra_axis=intra_axis,
                               inter_axis=inter_axis, n_intra=n_intra,
                               n_inter=n_inter)
        return (lambda xl: fn(xl[0])) if stacked else fn

    in_spec = P((inter_axis, intra_axis))
    return cached_shard_jit(ctx, name, key, make, in_spec,
                            out_spec_fn(n_intra, n_inter),
                            ici_axes=(intra_axis,))(x)


def all_gather_2d(x: jax.Array, ctx: DistContext | None = None,
                  intra_axis: str = "tp", inter_axis: str = "dcn"):
    """Host-level hierarchical AllGather: ``x`` (N·m, cols) sharded over both
    axes (global shard d = inter·n_intra + intra) → replicated."""
    ctx = ctx or get_context()
    return _two_level(ctx, "all_gather_2d", all_gather_2d_local, x,
                      intra_axis, inter_axis, lambda ni, no: P(None),
                      stacked=False)


def all_reduce_2d(x: jax.Array, ctx: DistContext | None = None,
                  intra_axis: str = "tp", inter_axis: str = "dcn"):
    """Host-level hierarchical AllReduce: ``x`` globally (N, m, cols)
    stacked contributions → replicated (m, cols) sum."""
    ctx = ctx or get_context()
    return _two_level(ctx, "all_reduce_2d", all_reduce_2d_local, x,
                      intra_axis, inter_axis, lambda ni, no: P(None),
                      stacked=True)


def reduce_scatter_2d(x: jax.Array, ctx: DistContext | None = None,
                      intra_axis: str = "tp", inter_axis: str = "dcn"):
    """Host-level hierarchical ReduceScatter: ``x`` globally (N, N·m, cols)
    stacked contributions → (N·m, cols) scattered by global shard index."""
    ctx = ctx or get_context()
    return _two_level(ctx, "reduce_scatter_2d", reduce_scatter_2d_local, x,
                      intra_axis, inter_axis,
                      lambda ni, no: P((inter_axis, intra_axis)),
                      stacked=True)


def fast_all_to_all_2d_local(send_buf: jax.Array, send_splits: jax.Array, *,
                             intra_axis: str = "tp",
                             inter_axis: str = "dcn",
                             n_intra: int | None = None,
                             n_inter: int | None = None
                             ) -> tuple[jax.Array, jax.Array]:
    """Hierarchical EP AllToAll: one DCN hop groups token slots by
    destination slice, then the Pallas intra-slice AllToAll delivers each
    source slice's block over ICI.

    send_buf: (N, cap, hidden), N = n_inter·n_intra, slot g = tokens for
    global rank g's experts (g = inter·n_intra + intra — dispatch_layout's
    layout unchanged); send_splits: (N, epr). Returns (recv_buf (N, cap,
    hidden), recv_splits (N, epr)) ordered by global SOURCE rank — the
    same contract as ops/all_to_all.fast_all_to_all_local, so
    combine_layout and the EP-MoE layer compose unchanged.

    Reference: the 4-node low-latency MoE AllToAll (IB across nodes +
    NVLink within, low_latency_all_to_all.py / README.md:96-97); SURVEY.md
    §7 maps the inter tier to DCN where Pallas remote DMA does not reach.
    """
    if n_intra is None or n_inter is None:
        raise ValueError("n_intra/n_inter required inside shard_map")
    from triton_distributed_tpu.ops.all_to_all import fast_all_to_all_local

    N, cap, hidden = send_buf.shape
    epr = send_splits.shape[1]
    if N != n_inter * n_intra:
        raise ValueError(f"send_buf slots {N} != {n_inter}*{n_intra}")
    if n_inter == 1:
        return fast_all_to_all_local(send_buf, send_splits,
                                     axis=intra_axis, num_ranks=n_intra)

    # DCN hop: device (a, i) sends its dest-slice-b block to (b, i);
    # afterwards block [s] holds what slice-peer (s, i) destined for MY
    # slice's ranks. Splits ride the same exchange.
    buf = send_buf.reshape(n_inter, n_intra, cap, hidden)
    spl = send_splits.reshape(n_inter, n_intra, epr)
    buf = jax.lax.all_to_all(buf, inter_axis, split_axis=0, concat_axis=0,
                             tiled=False)
    spl = jax.lax.all_to_all(spl, inter_axis, split_axis=0, concat_axis=0,
                             tiled=False)

    # Intra tier: per source slice, the Pallas AllToAll delivers to the
    # final intra rank. recv block for global source (s, i) = rb_s[i].
    rbs, rss = [], []
    for s in range(n_inter):
        rb, rs = fast_all_to_all_local(buf[s], spl[s], axis=intra_axis,
                                       num_ranks=n_intra)
        rbs.append(rb)
        rss.append(rs)
    recv_buf = jnp.stack(rbs).reshape(N, cap, hidden)
    recv_splits = jnp.stack(rss).reshape(N, epr)
    return recv_buf, recv_splits


def sp_ag_attention_2d_local(q: jax.Array, k_shard: jax.Array,
                             v_shard: jax.Array, *,
                             intra_axis: str = "tp",
                             inter_axis: str = "dcn",
                             n_intra: int | None = None,
                             n_inter: int | None = None,
                             causal: bool = True,
                             tiles: tuple[int, int] | None = None
                             ) -> jax.Array:
    """Hierarchical SP attention — delegates to the PIPELINED implementation
    (ops/hierarchical.py): the slice's KV gathers over ICI via the Pallas
    AllGather, then the aggregated block ROTATES over DCN with each slice's
    flash merge overlapping the next hop, instead of barriering on a full
    ``jax.lax.all_gather`` (round-5 VERDICT #5).

    q/k_shard/v_shard: (B, S/N, h*, d) sequence shards by global index
    g = inter·n_intra + intra. Returns (B, S/N, hq, d).

    Reference: ``sp_ag_attention_inter_node.py`` (NVSHMEM inter-node KV
    gather feeding the same waiting flash consumer).
    """
    from triton_distributed_tpu.ops.hierarchical import (
        sp_ag_attention_2d_local as _pipelined,
    )

    return _pipelined(q, k_shard, v_shard, intra_axis=intra_axis,
                      inter_axis=inter_axis, n_intra=n_intra,
                      n_inter=n_inter, causal=causal, tiles=tiles)
