"""Single-device tiled Pallas matmul.

The compute core of the overlapped kernels exposed standalone — used for
benchmarking kernel efficiency against XLA's native dot (reference analog:
the persistent consumer GEMM of allgather_gemm.py:158-264 without its
readiness waits).

Round-4 structure: a classic *grid* ``pallas_call`` (Mosaic's own pipeline,
``parallel`` dimension semantics on the output tiles) instead of the former
single-ANY-kernel + ``emit_pipeline`` body. Measured on-chip at the
north-star shape (M=2048, K=N=5120 bf16), the grid form with (1024,1024,512)
tiles runs 1.04–1.18x XLA's dot where the emit_pipeline form peaked at
0.86x — Mosaic both pipelines the k-loop more tightly and fits tiles the
emit_pipeline form OOMs on (its scoped-VMEM overhead is ~25% larger).
The emit_pipeline core (``ops/tiling.matmul_tiles``) remains for kernels
that must interleave readiness waits with compute inside one kernel.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.language.core import kernel_call
from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align


def _grid_matmul_kernel(nk, a_ref, b_ref, out_ref, acc_ref):
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    bv = b_ref[...]
    if bv.dtype != a_ref.dtype:
        # Mixed-precision lane (bf16 activations x fp8 weights): the
        # low-precision B tile upcasts in VMEM after streaming at its
        # smaller byte size — the weight-streaming win fp8 exists for.
        bv = bv.astype(a_ref.dtype)
    acc_ref[...] += jnp.dot(a_ref[...], bv,
                            preferred_element_type=jnp.float32)

    @pl.when(kk == nk - 1)
    def _():
        out_ref[...] = acc_ref[...].astype(out_ref.dtype)


def pallas_matmul(a: jax.Array, b: jax.Array,
                  tile_m: int = 512, tile_n: int = 1024,
                  tile_k: int = 512, out_dtype=None) -> jax.Array:
    """out = a @ b with fp32 accumulation, tiled over a parallel grid.

    Low-precision lane: float8_e4m3fn operands are first-class — the fp8
    tiles stream at half bf16's HBM traffic and the MXU dot accumulates
    fp32 (the reference's fp8 kernels, README.md:96-97 headline payload).
    ``out_dtype`` defaults to a.dtype; fp8 callers usually want bf16/f32
    out (an fp8 store would quantize the accumulated result).
    """
    m, k = a.shape
    k2, ncols = b.shape
    if k != k2:
        raise ValueError(f"inner dims mismatch {k} vs {k2}")
    if b.dtype != a.dtype and b.dtype.itemsize >= a.dtype.itemsize:
        # Only LOW-precision B mixes (weights stream small, upcast in
        # VMEM); an implicit downcast of B would silently quantize it.
        raise ValueError(f"mixed dtypes need B ({b.dtype}) narrower than "
                         f"A ({a.dtype})")
    out_dtype = a.dtype if out_dtype is None else jnp.dtype(out_dtype)
    tm = pick_tile(m, tile_m, sublane_align(a.dtype))
    tk = pick_tile(k, tile_k, 128)
    tn = pick_tile(ncols, tile_n, 128)
    nk = k // tk
    return kernel_call(
        functools.partial(_grid_matmul_kernel, nk),
        out_shape=jax.ShapeDtypeStruct((m, ncols), out_dtype),
        grid=(m // tm, ncols // tn, nk),
        in_specs=[pl.BlockSpec((tm, tk), lambda i, j, q: (i, q)),
                  pl.BlockSpec((tk, tn), lambda i, j, q: (q, j))],
        out_specs=pl.BlockSpec((tm, tn), lambda i, j, q: (i, j)),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * ncols,
            bytes_accessed=(m * k + k * ncols + m * ncols) * a.dtype.itemsize,
            transcendentals=0,
        ),
    )(a, b)


def pallas_matmul_tuned(a: jax.Array, b: jax.Array) -> jax.Array:
    """pallas_matmul with the tile config resolved through the contextual
    autotuner (measured on-chip, disk-cached by shape/dtype/chip; static
    defaults off-chip). Reference: contextual_autotune-decorated kernels
    (autotuner.py:97)."""
    from triton_distributed_tpu.runtime.autotuner import tuned_matmul_tiles

    tiles = tuned_matmul_tiles(a.shape[0], a.shape[1], b.shape[1], a.dtype)
    if tiles is None:
        return pallas_matmul(a, b)
    tm, tn, tk = tiles
    return pallas_matmul(a, b, tile_m=tm, tile_n=tn, tile_k=tk)
