"""Single-device tiled Pallas matmul.

The compute core of the overlapped kernels exposed standalone — used for
benchmarking kernel efficiency against XLA's native dot (reference analog:
the persistent consumer GEMM of allgather_gemm.py:158-264 without its
readiness waits).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.ops.tiling import matmul_tiles, pick_tile, sublane_align


def _matmul_kernel(m, k, ncols, tm, tk, tn, a_ref, b_ref, out_ref, vacc):
    matmul_tiles(a_ref, b_ref, out_ref, m, k, ncols, tm, tk, tn, vacc)


def pallas_matmul(a: jax.Array, b: jax.Array,
                  tile_m: int = 512, tile_n: int = 1024,
                  tile_k: int = 1024) -> jax.Array:
    """out = a @ b with fp32 accumulation, staged through VMEM tiles."""
    m, k = a.shape
    k2, ncols = b.shape
    if k != k2:
        raise ValueError(f"inner dims mismatch {k} vs {k2}")
    tm = pick_tile(m, tile_m, sublane_align(a.dtype))
    tk = pick_tile(k, tile_k, 128)
    tn = pick_tile(ncols, tile_n, 128)
    kernel = functools.partial(_matmul_kernel, m, k, ncols, tm, tk, tn)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, ncols), a.dtype),
        in_specs=[any_spec(), any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[
            pltpu.VMEM((tm, tn), jnp.float32),
        ],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * ncols,
            bytes_accessed=(m * k + k * ncols + m * ncols) * a.dtype.itemsize,
            transcendentals=0,
        ),
    )(a, b)


def pallas_matmul_tuned(a: jax.Array, b: jax.Array) -> jax.Array:
    """pallas_matmul with the tile config resolved through the contextual
    autotuner (measured on-chip, disk-cached by shape/dtype/chip; static
    defaults off-chip). Reference: contextual_autotune-decorated kernels
    (autotuner.py:97)."""
    from triton_distributed_tpu.runtime.autotuner import tuned_matmul_tiles

    tiles = tuned_matmul_tiles(a.shape[0], a.shape[1], b.shape[1], a.dtype)
    if tiles is None:
        return pallas_matmul(a, b)
    tm, tn, tk = tiles
    return pallas_matmul(a, b, tile_m=tm, tile_n=tn, tile_k=tk)
