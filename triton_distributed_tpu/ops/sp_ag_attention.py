"""SP AllGather-attention — KV-gather prefill (reference-shaped variant).

Reference: ``sp_ag_attention_intra_node.py`` — producer CE all-gathers KV
shards into symmetric buffers (:105) while a consumer flash-attention waits
per-KV-chunk (:256); op at :432 (inter-node twin in
``sp_ag_attention_inter_node.py``).

TPU mapping: the KV shards ride the Pallas full-mesh-push AllGather (remote
DMA over ICI), then the consumer runs the tiled Pallas flash kernel
(ops/flash_attention.py — the analog of the reference's waiting consumer
:256) per KV chunk with the same online-LSE merge as ring attention —
chunk r's compute starts as soon as the math allows, and XLA overlaps the
Pallas AG kernel with the local-chunk flash call since there is no data
dependence between them. For a fully in-kernel waited consumer, see
ops/ring_attention.py — on TPU the rotating-shard schedule expresses the
same overlap with less machinery and is the preferred long-context path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allgather import all_gather_local, AllGatherMethod
from triton_distributed_tpu.ops.flash_attention import (
    _merge, shard_attention_partial,
)
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def sp_ag_attention_local(q: jax.Array, k_shard: jax.Array,
                          v_shard: jax.Array, *, axis: str = "sp",
                          num_ranks: int | None = None,
                          causal: bool = True,
                          method: AllGatherMethod | str = AllGatherMethod.AUTO,
                          tiles: tuple[int, int] | None = None) -> jax.Array:
    """Device-local SP AG attention inside shard_map.

    q/k_shard/v_shard: (B, S/n, h*, d) sequence shards. Returns
    (B, S/n, hq, d) — local queries attended over the full (causal) sequence.
    ``tiles``: (tile_q, tile_k) flash caps (host wrappers pass autotuned
    values; None = swept defaults).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    me = jax.lax.axis_index(axis)
    b, sq, hq, d = q.shape
    sk, hkv = k_shard.shape[1], k_shard.shape[2]

    if n == 1:
        acc, m, l = shard_attention_partial(q, k_shard, v_shard,
                                            causal=causal, tiles=tiles)
        return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    # Producer: Pallas AG of the KV shards (flattened to 2-D rows).
    flat = jnp.concatenate(
        [k_shard.reshape(b * sk, hkv * d), v_shard.reshape(b * sk, hkv * d)],
        axis=1)
    gathered = all_gather_local(flat, axis=axis, num_ranks=n, method=method)
    gathered = gathered.reshape(n, b, sk, 2, hkv, d)
    ks = gathered[:, :, :, 0]  # (n, B, sk, hkv, d)
    vs = gathered[:, :, :, 1]

    # Consumer: tiled flash attention per KV chunk + online-LSE merge
    # (reference kernel_consumer_flash_attn_forward :256). Positional
    # causality: rank r's chunk holds positions [r·sk, (r+1)·sk); chunks
    # entirely behind the diagonal skip their dots in-kernel.
    q_off = me * sq
    state = shard_attention_partial(q, k_shard, v_shard, q_offset=q_off,
                                    k_offset=me * sk, causal=causal, tiles=tiles)

    def body(r, state):
        acc, m, l = shard_attention_partial(q, ks[r], vs[r], q_offset=q_off,
                                            k_offset=r * sk, causal=causal,
                                            tiles=tiles)
        # r == me is the diagonal chunk already accumulated above.
        keep = (r != me).astype(jnp.float32)
        return _merge(state, (acc * keep, m, l * keep))

    state = jax.lax.fori_loop(0, n, body, state)
    acc, m, l = state
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def sp_ag_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                    ctx: DistContext | None = None, axis: str = "tp",
                    causal: bool = True) -> jax.Array:
    """Host-level SP AG attention (reference ``fused_sp_ag_attn_intra_node``,
    sp_ag_attention_intra_node.py:432). q/k/v: (B, S, h*, d) sharded on dim 1."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    key = (axis, causal, q.shape, k.shape, str(q.dtype))

    def make():
        # Tile caps resolved HERE (host level, once per shape signature) —
        # autotuned on-chip when tuning is on (VERDICT r3 #8: the non-ring
        # prefill paths ran static caps and left the measured S=4k optimum
        # on the table).
        from triton_distributed_tpu.ops.flash_attention import (
            resolve_flash_tiles,
        )

        tiles = resolve_flash_tiles(q.shape[1] // n, k.shape[1] // n,
                                    q.shape[2], k.shape[2], q.shape[3],
                                    q.dtype)
        return functools.partial(sp_ag_attention_local, axis=axis,
                                 num_ranks=n, causal=causal, tiles=tiles)

    jfn = cached_shard_jit(ctx, "sp_ag_attention", key, make,
                          (P(None, axis), P(None, axis), P(None, axis)),
                          P(None, axis))
    return jfn(q, k, v)
