"""Tiling helpers shared by the kernel library.

Plays the role of the reference's threadblock-swizzle helper modules
(ag_gemm_threadblock_swizzle.py etc., SURVEY.md §2.4): tile-size selection and
rank-swizzled visit orders for overlap-friendly consumption.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def pick_tile(dim: int, cap: int, align: int = 1) -> int:
    """Largest divisor of ``dim`` not exceeding ``cap`` that is a multiple of
    ``align``; falls back to ``dim`` itself when no aligned divisor exists
    (slicing the whole dimension never misaligns).

    Mosaic requires HBM slice offsets/shapes aligned to the memref tiling:
    last dim multiples of 128, second-to-last multiples of the dtype sublane
    count (8 for f32, 16 for bf16) — interpret mode does not enforce this,
    real compilation does.
    """
    t = min(dim, cap)
    while t >= align:
        if dim % t == 0 and t % align == 0:
            return t
        t -= 1
    return dim


SUBLANE = {2: 16, 4: 8, 1: 32}  # itemsize -> sublane alignment


def sublane_align(dtype) -> int:
    return SUBLANE.get(jnp.dtype(dtype).itemsize, 8)


def gemm_tiles(m: int, k: int, ncols: int, dtype, cfg) -> tuple[int, int, int]:
    """(tm, tk, tn) for a tiled matmul over (m, k) @ (k, ncols): row tiles
    sublane-aligned, contraction/column tiles lane(128)-aligned."""
    sa = sublane_align(dtype)
    return (
        pick_tile(m, cfg.tile_m, sa),
        pick_tile(k, cfg.tile_k, 128),
        pick_tile(ncols, cfg.tile_n, 128),
    )


def swizzled_ranks(me, n: int):
    """Visit order starting at own rank: me, me+1, …, me-1 (mod n) — the
    analog of the reference's rank-swizzled tile order so the consumer starts
    on data that is locally available first (allgather_gemm.py:221-229)."""
    return [jax.lax.rem(me + i, n) for i in range(n)]


def matmul_tiles(
    a_view,               # ref view (m, k) in HBM/ANY
    b_view,               # ref view (k, >= b_col_block_offset*tn + ncols)
    out_view,             # ref view (m, ncols)
    m: int, k: int, ncols: int,
    tm: int, tk: int, tn: int,
    acc,                  # VMEM (tm, tn) fp32 accumulator scratch
    b_col_block_offset: int = 0,
):
    """Pipelined tiled matmul: out = A @ B[:, off:off+ncols] with fp32 MXU
    accumulation (off = b_col_block_offset * tn).

    The compute core shared by the overlapped kernels (the analog of the
    reference's persistent consumer GEMM inner loop,
    allgather_gemm.py:217-264, minus readiness waits — callers interleave
    waits around chunk boundaries).

    ``b_col_block_offset`` selects a column-chunk of B through the
    BlockSpec index map instead of a lane-dim sliced ref view — Mosaic
    crashes (SIGABRT) pipelining over `.at[:, cols]` views, so chunked
    consumers (ops/gemm_allreduce.py) pass block offsets and keep every
    ref whole.

    Uses ``pltpu.emit_pipeline`` so every A/B tile fetch and out tile flush
    is double-buffered against the MXU dots — the DMA/compute overlap the
    reference gets from its software-pipelined persistent GEMM.
    """
    nk = k // tk
    off_j = b_col_block_offset

    def body(a_v, b_v, o_v, acc_ref):
        kk = pl.program_id(2)
        part = jnp.dot(a_v[...], b_v[...], preferred_element_type=jnp.float32)

        @pl.when(kk == 0)
        def _():
            acc_ref[...] = part

        @pl.when(kk != 0)
        def _():
            acc_ref[...] += part

        @pl.when(kk == nk - 1)
        def _():
            o_v[...] = acc_ref[...].astype(o_v.dtype)

    pltpu.emit_pipeline(
        body,
        grid=(m // tm, ncols // tn, nk),
        in_specs=[
            pl.BlockSpec((tm, tk), lambda i, j, q: (i, q)),
            pl.BlockSpec((tk, tn), lambda i, j, q: (q, j + off_j)),
        ],
        out_specs=[pl.BlockSpec((tm, tn), lambda i, j, q: (i, j))],
    )(a_view, b_view, out_view, scratches=[acc])
