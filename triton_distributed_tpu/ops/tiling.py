"""Tiling helpers shared by the kernel library.

Plays the role of the reference's threadblock-swizzle helper modules
(ag_gemm_threadblock_swizzle.py etc., SURVEY.md §2.4): tile-size selection and
rank-swizzled visit orders for overlap-friendly consumption.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu


def pick_tile(dim: int, cap: int, align: int = 1) -> int:
    """Largest divisor of ``dim`` not exceeding ``cap`` that is a multiple of
    ``align``; falls back to ``dim`` itself when no aligned divisor exists
    (slicing the whole dimension never misaligns).

    Mosaic requires HBM slice offsets/shapes aligned to the memref tiling:
    last dim multiples of 128, second-to-last multiples of the dtype sublane
    count (8 for f32, 16 for bf16) — interpret mode does not enforce this,
    real compilation does.
    """
    t = min(dim, cap)
    while t >= align:
        if dim % t == 0 and t % align == 0:
            return t
        t -= 1
    return dim


SUBLANE = {2: 16, 4: 8, 1: 32}  # itemsize -> sublane alignment


def sublane_align(dtype) -> int:
    return SUBLANE.get(jnp.dtype(dtype).itemsize, 8)


def gemm_tiles(m: int, k: int, ncols: int, dtype, cfg) -> tuple[int, int, int]:
    """(tm, tk, tn) for a tiled matmul over (m, k) @ (k, ncols): row tiles
    sublane-aligned, contraction/column tiles lane(128)-aligned."""
    sa = sublane_align(dtype)
    return (
        pick_tile(m, cfg.tile_m, sa),
        pick_tile(k, cfg.tile_k, 128),
        pick_tile(ncols, cfg.tile_n, 128),
    )


def swizzled_ranks(me, n: int):
    """Visit order starting at own rank: me, me+1, …, me-1 (mod n) — the
    analog of the reference's rank-swizzled tile order so the consumer starts
    on data that is locally available first (allgather_gemm.py:221-229)."""
    return [jax.lax.rem(me + i, n) for i in range(n)]


def matmul_tiles(
    a_tile_at,            # (im, kk) -> HBM ref slice (tm, tk)
    b_tile_at,            # (kk, jn) -> HBM ref slice (tk, tn)
    out_tile_at,          # (im, jn) -> HBM ref slice (tm, tn)
    m: int, k: int, ncols: int,
    tm: int, tk: int, tn: int,
    va, vb, vacc, vout, copy_sem,
):
    """Serial tiled matmul: out = A @ B staged through VMEM with fp32
    accumulation on the MXU.

    The compute core shared by the overlapped kernels (the analog of the
    reference's persistent consumer GEMM inner loop,
    allgather_gemm.py:217-264, minus readiness waits — callers interleave
    waits around chunk boundaries).
    """
    for jn in range(ncols // tn):
        for im in range(m // tm):
            vacc[...] = jnp.zeros_like(vacc)
            for kk in range(k // tk):
                ca = pltpu.make_async_copy(a_tile_at(im, kk), va, copy_sem)
                ca.start()
                ca.wait()
                cb = pltpu.make_async_copy(b_tile_at(kk, jn), vb, copy_sem)
                cb.start()
                cb.wait()
                vacc[...] = vacc[...] + jnp.dot(
                    va[...], vb[...], preferred_element_type=jnp.float32)
            vout[...] = vacc[...].astype(vout.dtype)
            co = pltpu.make_async_copy(vout, out_tile_at(im, jn), copy_sem)
            co.start()
            co.wait()
