"""Low-latency MoE AllToAll — EP dispatch/combine over ICI.

Reference: ``python/triton_dist/kernels/nvidia/low_latency_all_to_all.py``
(:36 ``all_to_all_kernel``, :198 ``fast_all_to_all``, :260 post-process) and
the training-style ``ep_a2a.py`` (:37 dispatch, :152 combine) — the
reference's headline op (137µs vs DeepEP on 32×H800, BASELINE.md).

TPU-first redesign (NOT a translation of the NVSHMEM protocol):

- **Static per-destination slots.** The reference packs tokens contiguously
  by expert and DMAs ``num_rows_cur_block`` rows at a dynamic offset; Mosaic
  wants static DMA sizes and aligned offsets. Here the send layout is
  ``(n_ranks, cap, hidden)`` — slot p holds the tokens destined to rank p
  (sorted by expert within the slot, zero-padded to ``cap``) — so every DMA
  offset is a static slot base plus a BLOCK-aligned offset.
- **BLOCK-granular transfer.** Only ``ceil(rows_p / BLOCK)`` blocks of BLOCK
  rows actually move per peer (the low-latency property: traffic follows the
  real token count, not MAX_M), via a dynamic-trip-count ``fori_loop`` of
  static-size DMAs.
- **Splits ride XLA.** The reference exchanges splits in-kernel and orders
  them with fence+signal parity; the splits matrix is a few hundred bytes, so
  here it rides a ``jax.lax.all_to_all`` XLA collective (latency-class ICI
  traffic XLA already schedules well) and block counts are *inputs* to the
  Pallas kernel — no header protocol, no ordering assumption on the fabric.
- **Count-based completion.** The receiver knows exactly how many BLOCK
  deliveries to expect (from the exchanged splits) and waits that many
  recv-semaphore increments; no NVSHMEM_CMP_EQ signal polling, no
  ``call_count`` parity double-buffer — the entry barrier plays the role of
  the parity slots (no rank can write into a peer's buffers before that peer
  has entered the kernel).

Dispatch and combine are the same op run in opposite directions (the
reference reuses ``fast_all_to_all`` for both as well).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec, smem_spec
from triton_distributed_tpu.ops.tiling import sublane_align
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _wait_n(like_ref, sem, count):
    """Wait ``count`` (traced) DMA completions of ``like_ref``'s byte size."""

    def body(i, _):
        pltpu.make_async_copy(like_ref, like_ref, sem).wait()
        return 0

    jax.lax.fori_loop(0, count, body, 0)


def _a2a_kernel(n: int, axis: str, cap: int, block: int,
                send_ref, send_rows, recv_rows, recv_ref,
                data_send_sem, data_recv_sem):
    """See module docstring.

    send_ref/recv_ref: (n, cap, hidden); send_rows/recv_rows: (n,) int32 in
    SMEM — actual token rows per destination/source rank.
    """
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    block_like = send_ref.at[0, pl.ds(0, block)]

    def nblocks(rows):
        return jax.lax.div(rows + (block - 1), block)

    def push_blocks(p, dst_rank, count):
        """Push ``count`` BLOCK-row pieces of slot p to ``dst_rank``'s
        recv slot ``me`` (local copy when dst == me)."""

        def body(j, _):
            src = send_ref.at[p, pl.ds(j * block, block)]
            dst = recv_ref.at[me, pl.ds(j * block, block)]
            if dst_rank is None:
                pltpu.make_async_copy(src, dst, data_recv_sem).start()
            else:
                shmem.putmem_nbi_block(src, dst, data_send_sem,
                                       data_recv_sem, dst_rank, axis)
            return 0

        jax.lax.fori_loop(0, count, body, 0)

    # --- producer: swizzled peer order (me+1 … me+n-1), own slot locally.
    total_sent = jnp.int32(0)
    for i in range(n - 1):
        p = jax.lax.rem(me + 1 + i, n)
        nb = nblocks(send_rows[p])
        push_blocks(p, p, nb)
        total_sent = total_sent + nb
    push_blocks(me, None, nblocks(send_rows[me]))

    # --- consumer: the splits exchange tells us exactly how many BLOCK
    # deliveries to expect (remote pushes + our own local copies).
    expected = jnp.int32(0)
    for p in range(n):
        expected = expected + nblocks(recv_rows[p])
    _wait_n(block_like, data_recv_sem, expected)

    # --- quiet: complete outgoing sends before returning.
    _wait_n(block_like, data_send_sem, total_sent)


def fast_all_to_all_local(
    send_buf: jax.Array,
    send_splits: jax.Array,
    axis: str = "tp",
    num_ranks: int | None = None,
    block_rows: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Device-local AllToAll inside a shard_map region.

    send_buf: (n, cap, hidden) — slot p: tokens for rank p's experts, sorted
      by expert, padded to cap;
    send_splits: (n, experts_per_rank) int32 — token counts per destination
      expert (rows used in slot p = send_splits[p].sum()).

    Returns (recv_buf, recv_splits):
    recv_buf: (n, cap, hidden) — slot p: tokens received from rank p (rows
      beyond the real count are unspecified);
    recv_splits: (n, experts_per_rank) int32 — recv_splits[p, j] = tokens
      rank p sent to my j-th local expert.

    Reference: ``fast_all_to_all`` (low_latency_all_to_all.py:198).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if send_buf.ndim != 3 or send_buf.shape[0] != n:
        raise ValueError(f"send_buf must be (n={n}, cap, hidden), "
                         f"got {send_buf.shape}")
    if send_splits.shape[0] != n:
        raise ValueError(f"send_splits must be (n={n}, experts_per_rank), "
                         f"got {send_splits.shape}")
    send_splits = send_splits.astype(jnp.int32)
    if n == 1:
        return send_buf, send_splits
    _, cap, hidden = send_buf.shape
    block = block_rows or max(16, sublane_align(send_buf.dtype))
    if block % sublane_align(send_buf.dtype):
        raise ValueError(f"block_rows {block} not sublane-aligned")
    if cap % block:
        raise ValueError(f"slot capacity {cap} not a multiple of "
                         f"block_rows {block}")

    # Splits matrix rides an XLA collective (tiny, latency-class): row p of
    # the result = my row as seen by rank p ⇒ recv_splits[p] = what p sends me.
    recv_splits = jax.lax.all_to_all(send_splits, axis, split_axis=0,
                                     concat_axis=0, tiled=True)
    send_rows = send_splits.sum(axis=1, dtype=jnp.int32)
    recv_rows = recv_splits.sum(axis=1, dtype=jnp.int32)

    kernel = functools.partial(_a2a_kernel, n, axis, cap, block)
    recv_buf = kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, cap, hidden), send_buf.dtype),
        in_specs=[any_spec(), smem_spec(), smem_spec()],
        out_specs=any_spec(),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(send_buf, send_rows, recv_rows)
    return recv_buf, recv_splits


def _a2a_parity_kernel(n: int, axis: str, cap: int, block: int, straggler,
                       idx_ref, send_ref, send_rows, recv_rows, _ws_in,
                       recv_ref, ws, data_send_sem, data_recv_sems,
                       copy_sem):
    """Barrier-free parity A2A for repeated decode-step calls.

    Reference: ``low_latency_all_to_all.py:125-175`` — the double-buffered
    ``call_count`` parity protocol itself (this op is its direct analog).
    The entry barrier is replaced by (a) a persistent caller-owned
    workspace (aliased input/output — remote writes always target a live
    allocation) and (b) the per-call XLA splits exchange, which is a
    full-axis rendezvous: a rank can only be at call t+2 after every peer
    completed call t+1's splits collective, hence finished reading its
    call-t parity slab. Per-parity recv semaphores keep early t+1
    deliveries from being miscounted against call t.
    """
    me = dl.rank(axis)
    p = jax.lax.rem(idx_ref[0], 2)
    straggler = dl.resolve_straggler(straggler, n, idx_ref[0])
    dl.maybe_straggle(straggler, me)
    slab = ws.at[p]                     # (n, cap, hidden) parity slab
    block_like = send_ref.at[0, pl.ds(0, block)]
    recv_sem = data_recv_sems.at[p]

    def nblocks(rows):
        return jax.lax.div(rows + (block - 1), block)

    def push_blocks(slot, dst_rank, count):
        def body(j, _):
            src = send_ref.at[slot, pl.ds(j * block, block)]
            dst = slab.at[me, pl.ds(j * block, block)]
            if dst_rank is None:
                pltpu.make_async_copy(src, dst, recv_sem).start()
            else:
                shmem.putmem_nbi_block(src, dst, data_send_sem,
                                       recv_sem, dst_rank, axis)
            return 0

        jax.lax.fori_loop(0, count, body, 0)

    total_sent = jnp.int32(0)
    for i in range(n - 1):
        q = jax.lax.rem(me + 1 + i, n)
        nb = nblocks(send_rows[q])
        push_blocks(q, q, nb)
        total_sent = total_sent + nb
    push_blocks(me, None, nblocks(send_rows[me]))

    expected = jnp.int32(0)
    for q in range(n):
        expected = expected + nblocks(recv_rows[q])
    _wait_n(block_like, recv_sem, expected)

    # Landed slab -> this call's output (local copy; remote hazards are
    # confined to the persistent slab).
    out_cp = pltpu.make_async_copy(slab, recv_ref, copy_sem)
    out_cp.start()
    out_cp.wait()
    _wait_n(block_like, data_send_sem, total_sent)


def a2a_stream_workspace(n: int, cap: int, hidden: int, dtype
                         ) -> tuple[jax.Array, jax.Array]:
    """Device-local persistent (workspace, call_index) for
    :func:`fast_all_to_all_stream`; allocate once, thread through the
    decode loop."""
    return (jnp.zeros((2, n, cap, hidden), dtype), jnp.zeros((), jnp.int32))


def fast_all_to_all_stream(send_buf: jax.Array, send_splits: jax.Array,
                           ws: jax.Array, call_index: jax.Array, *,
                           axis: str = "tp", num_ranks: int | None = None,
                           block_rows: int | None = None,
                           straggler: tuple | None = None,
                           force_kernel: bool = False):
    """Barrier-free steady-state AllToAll (EP decode path).

    Same contract as :func:`fast_all_to_all_local` plus the threaded
    (ws, call_index) pair from :func:`a2a_stream_workspace`. Returns
    (recv_buf, recv_splits, ws', call_index + 1). ``force_kernel`` runs the
    Pallas kernel even at n=1 (single-chip Mosaic compile check).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    send_splits = send_splits.astype(jnp.int32)
    if n == 1 and not force_kernel:
        return send_buf, send_splits, ws, call_index + 1
    _, cap, hidden = send_buf.shape
    block = block_rows or max(16, sublane_align(send_buf.dtype))
    if cap % block:
        raise ValueError(f"slot capacity {cap} not a multiple of "
                         f"block_rows {block}")
    if ws.shape != (2, n, cap, hidden):
        raise ValueError(f"workspace shape {ws.shape} != (2, {n}, {cap}, "
                         f"{hidden})")
    if ws.dtype != send_buf.dtype:
        raise ValueError(f"workspace dtype {ws.dtype} != payload "
                         f"{send_buf.dtype} — allocate a2a_stream_workspace "
                         "with the token dtype")

    recv_splits = jax.lax.all_to_all(send_splits, axis, split_axis=0,
                                     concat_axis=0, tiled=True)
    send_rows = send_splits.sum(axis=1, dtype=jnp.int32)
    recv_rows = recv_splits.sum(axis=1, dtype=jnp.int32)

    kernel = functools.partial(_a2a_parity_kernel, n, axis, cap, block,
                               straggler)
    recv_buf, ws_new = kernel_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n, cap, hidden), send_buf.dtype),
            jax.ShapeDtypeStruct(ws.shape, ws.dtype),
        ),
        in_specs=[smem_spec((1,)), any_spec(), smem_spec(), smem_spec(),
                  any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={4: 1},
    )(jnp.asarray(call_index, jnp.int32).reshape(1), send_buf, send_rows,
      recv_rows, ws)
    return recv_buf, recv_splits, ws_new, call_index + 1


def fast_all_to_all(send_buf: jax.Array, send_splits: jax.Array,
                    ctx: DistContext | None = None, axis: str = "tp",
                    block_rows: int | None = None):
    """Host-level AllToAll. Global layouts (stacked over ``axis``):

    send_buf: (n, n, cap, hidden) — [d, p] = device d's tokens for rank p;
    send_splits: (n, n, experts_per_rank) int32.
    Returns (recv_buf, recv_splits) with the same global shapes, where
    [d, p] = what device d received from rank p.

    With comm tuning opted in (TDTPU_AUTOTUNE_COMM=1), a None
    ``block_rows`` resolves by MEASUREMENT over the aligned candidates
    (disk-cached per shape/mesh/chip) instead of the static default.
    """
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    if block_rows is None and n > 1:
        from triton_distributed_tpu.runtime.autotuner import (
            comm_autotune_enabled, tuned_a2a_block_rows,
        )

        if comm_autotune_enabled():
            block_rows = tuned_a2a_block_rows(send_buf, send_splits, ctx,
                                              axis=axis)
    key = (axis, send_buf.shape, send_splits.shape, str(send_buf.dtype),
           block_rows)

    def make():
        fn = functools.partial(fast_all_to_all_local, axis=axis, num_ranks=n,
                               block_rows=block_rows)

        def wrapped(sb, ss):
            rb, rs = fn(sb[0], ss[0])
            return rb[None], rs[None]

        return wrapped

    jfn = cached_shard_jit(ctx, "fast_all_to_all", key, make,
                           (P(axis), P(axis)), (P(axis), P(axis)),
                           ici_axes=(axis,))
    return jfn(send_buf, send_splits)


# ---------------------------------------------------------------------------
# Token layout helpers (the analog of the reference's pre-sorted cumsum input
# contract + csrc/moe_utils.cu alignment, done in pure XLA: argsort/segment
# ops instead of a CUDA kernel).
# ---------------------------------------------------------------------------


class DispatchLayout(NamedTuple):
    """AllToAll send layout + the coordinates to invert it after combine."""

    send_buf: jax.Array      # (n, cap, hidden)
    send_splits: jax.Array   # (n, epr) int32
    sort_idx: jax.Array      # (m,) — expert-stable sort permutation
    sorted_rank: jax.Array   # (m,) — dest rank of sorted token i
    pos_in_slot: jax.Array   # (m,) — its row within that rank's slot
    overflow: jax.Array      # scalar int32 — tokens dropped by the cap (0 =
    #                          lossless; callers with cap < m must check)


def dispatch_layout(tokens: jax.Array, expert_ids: jax.Array,
                    num_experts: int, num_ranks: int, cap: int
                    ) -> DispatchLayout:
    """Build the AllToAll send layout from flat tokens + expert assignment.

    tokens: (m, hidden); expert_ids: (m,) int32 global expert per token
    (replicate tokens beforehand for topk>1).

    Tokens for the same destination rank are packed contiguously (sorted by
    expert) at the head of that rank's slot. Tokens beyond ``cap`` per rank
    are dropped, and the drop count is reported in ``layout.overflow`` —
    size cap for the worst case (m) to be lossless (the reference's MAX_M
    contract, low_latency_all_to_all.py:125-175, made checkable).

    Reference: the sorted-by-expert input contract of fast_all_to_all plus
    ``moe_ag_scatter_align_block_size`` (csrc/lib/moe_utils.cu:61).
    """
    m, hidden = tokens.shape
    epr = num_experts // num_ranks
    expert_ids = expert_ids.astype(jnp.int32)
    dest_rank = expert_ids // epr

    # Stable sort by expert id ⇒ grouped by rank, grouped by expert within.
    sort_idx = jnp.argsort(expert_ids, stable=True)
    sorted_tokens = tokens[sort_idx]
    sorted_rank = dest_rank[sort_idx]

    # Position of each sorted token within its destination rank's slot.
    ones = jnp.ones((m,), jnp.int32)
    rank_counts = jax.ops.segment_sum(ones, dest_rank, num_segments=num_ranks)
    rank_starts = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                                   jnp.cumsum(rank_counts)[:-1]])
    pos_in_slot = (jnp.arange(m, dtype=jnp.int32)
                   - rank_starts[sorted_rank])

    send_buf = jnp.zeros((num_ranks, cap, hidden), tokens.dtype)
    send_buf = send_buf.at[sorted_rank, pos_in_slot].set(
        sorted_tokens, mode="drop")
    overflow = jnp.sum((pos_in_slot >= cap).astype(jnp.int32))
    expert_counts = jax.ops.segment_sum(ones, expert_ids,
                                        num_segments=num_experts)
    # Clamp the splits to what the slot actually holds: rows past ``cap``
    # were dropped from the buffer above, so the advertised counts must
    # drop the same tail (per-expert groups are packed in order — the
    # receiver would otherwise read past the slot).
    within = expert_counts.reshape(num_ranks, epr)
    group_starts = jnp.cumsum(within, axis=1) - within
    send_splits = jnp.clip(cap - group_starts, 0, within).astype(jnp.int32)
    return DispatchLayout(send_buf, send_splits, sort_idx, sorted_rank,
                          pos_in_slot, overflow)


def combine_layout(recv_buf: jax.Array, recv_splits: jax.Array):
    """Flatten an AllToAll receive layout into (tokens, expert_ids) for the
    local expert MLP: rows grouped by (source rank, local expert) →
    per-local-expert contiguous groups with counts.

    recv_buf: (n, cap, hidden); recv_splits: (n, epr).
    Returns (flat_tokens (n*cap, hidden), local_expert_ids (n*cap,) int32 —
    id ``epr`` marks padding rows, group_sizes (epr,) int32).

    Reference: ``all_to_all_post_process`` (low_latency_all_to_all.py:260).
    """
    n, cap, hidden = recv_buf.shape
    epr = recv_splits.shape[1]
    # Expert id of each valid row within a slot: rows are sorted by expert,
    # so row i of slot p belongs to the expert whose cumsum covers i.
    bounds = jnp.cumsum(recv_splits.astype(jnp.int32), axis=1)  # (n, epr)
    rows = jnp.arange(cap, dtype=jnp.int32)
    eid = (rows[None, :, None] >= bounds[:, None, :]).sum(-1)   # (n, cap)
    valid = rows[None, :] < bounds[:, -1][:, None]              # (n, cap)
    eid = jnp.where(valid, eid, epr).astype(jnp.int32)
    group_sizes = recv_splits.sum(axis=0, dtype=jnp.int32)
    return recv_buf.reshape(n * cap, hidden), eid.reshape(-1), group_sizes
