"""GEMM + AllReduce epilogue (TP fallback path when the RS/AG layout is not
wanted, e.g. single-layer calls or decode with replicated activations).

Reference: ``python/triton_dist/kernels/nvidia/gemm_allreduce.py`` —
``create_gemm_ar_context`` / ``gemm_allreduce_op`` /
``low_latency_gemm_allreduce_op`` (the variant that overlaps the reduction
with the GEMM tail).

TPU design (round 4): :func:`gemm_ar_stream` is a FUSED kernel over a
persistent parity workspace — the output columns are computed in chunks,
each chunk's partial product written straight into this rank's symmetric
slot and pushed to every peer with non-blocking remote DMA *while the
next chunk's matmul runs on the MXU*; after the last chunk the kernel
waits all deliveries and reduces slots. The AR's transfer latency hides
under the GEMM tail instead of sitting fully on the decode critical path
(round-3 VERDICT missing #2: the previous compose was a sequential XLA
dot → AR kernel — kept as :func:`gemm_ar_local` for one-off calls, where
a transient workspace would make remote writes unsound, and as the
golden in tests). The stream kernel is barrier-free by construction AND
by necessity: Mosaic crashes on barrier_all combined with emit_pipeline
in one kernel (bisected round 4), so the call_count parity protocol of
ops/allreduce.all_reduce_stream is the only sound fused design here.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import any_spec, kernel_call
from triton_distributed_tpu.ops.allreduce import (
    AllReduceMethod, _reduce_slots, all_reduce_local,
)
from triton_distributed_tpu.ops.tiling import (
    matmul_tiles, pick_tile, sublane_align,
)
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def _gemm_ar_stream_kernel(n: int, axis: str, mp: int, k: int, ncols: int,
                           n_chunks: int, tm: int, tk: int, tn: int,
                           idx_ref, x_ref, w_ref, _ws_in, out_ref, ws,
                           vacc, va, vred, send_sems, recv_sems, copy_sem):
    """Fused GEMM+AR over a persistent parity workspace: chunk c's partial
    lands in my symmetric slot and its pushes fly while chunk c+1 computes
    on the MXU; reduce after the last delivery.

    ws: (2, n_chunks, n, mp, nc) parity slots, CHUNK-MAJOR so every DMA
    and pipeline target is addressed by leading dims only (Mosaic
    SIGABRTs pipelining over lane-dim `.at[:, cols]` views; B's column
    chunk is selected via matmul_tiles' block offset instead). out_ref is
    (n_chunks, mp, nc); the host recomposes (mp, ncols).

    Barrier-free: the call_count parity protocol of
    ops/allreduce._ar_one_shot_parity_kernel (caller-owned persistent
    workspace + per-parity recv semaphores) — also the only protocol this
    kernel CAN use, since Mosaic crashes on barrier_all combined with
    emit_pipeline in one kernel (bisected round 4)."""
    me = dl.rank(axis)
    p = jax.lax.rem(idx_ref[0], 2)
    slots = ws.at[p]                    # (n_chunks, n, mp, nc)
    nc = ncols // n_chunks
    handles = []
    for c in range(n_chunks):
        # Partial chunk straight into my own slot (emit_pipeline's flush
        # is the "local copy" of the plain one-shot AR).
        matmul_tiles(x_ref, w_ref, slots.at[c].at[me], mp, k, nc,
                     tm, tk, tn, vacc, b_col_block_offset=c * (nc // tn))
        # Non-blocking pushes: the DMA engines carry chunk c while the MXU
        # starts chunk c+1 — the overlap the reference's low-latency
        # variant gets from its fused epilogue.
        for i in range(n - 1):
            peer = jax.lax.rem(me + 1 + i, n)
            handles.append(shmem.putmem_nbi_block(
                slots.at[c].at[me], slots.at[c].at[me],
                send_sems.at[c * (n - 1) + i], recv_sems.at[p], peer, axis))
    shmem.quiet(*handles)
    shmem.wait_deliveries(slots.at[0].at[me], recv_sems.at[p],
                          (n - 1) * n_chunks)
    for c in range(n_chunks):
        _reduce_slots(n, mp, mp, slots.at[c], out_ref.at[c], va, vred,
                      copy_sem)


def _gemm_ar_chunks(ncols: int, n_chunks: int) -> int:
    col_tiles = ncols // 128 if ncols % 128 == 0 else 1
    while n_chunks > 1 and (col_tiles % n_chunks or ncols % n_chunks):
        n_chunks -= 1
    return n_chunks


def gemm_ar_stream_workspace(n: int, m: int, ncols: int, dtype, *,
                             n_chunks: int = 4
                             ) -> tuple[jax.Array, jax.Array]:
    """Persistent (workspace, call_index) for :func:`gemm_ar_stream`.
    Allocate ONCE per decode loop and thread through (the persistence is
    what makes the barrier-free parity protocol sound — see
    ops/allreduce.ar_stream_workspace)."""
    nch = _gemm_ar_chunks(ncols, n_chunks)
    mp = -(-m // sublane_align(dtype)) * sublane_align(dtype)
    return (jnp.zeros((2, nch, n, mp, ncols // nch), dtype),
            jnp.zeros((), jnp.int32))


def gemm_ar_stream(x_local: jax.Array, b_local: jax.Array, ws: jax.Array,
                   call_index: jax.Array, *, axis: str = "tp",
                   num_ranks: int | None = None, n_chunks: int = 4,
                   force_kernel: bool = False):
    """Device-local fused GEMM+AR inside shard_map (decode steady state).

    x_local: (m, k_local); b_local: (k_local, ncols) → (reduced (m, ncols),
    ws', call_index + 1). Chunks the output columns (decode has tiny m,
    wide ncols) so each chunk's AR pushes overlap the next chunk's matmul.
    ``force_kernel``: run the degenerate 0-peer kernel at n=1 (single-chip
    Mosaic compile check, scripts/check_on_chip.py).
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    m, k = x_local.shape
    ncols = b_local.shape[1]
    if n == 1 and not force_kernel:
        out = jnp.dot(x_local, b_local,
                      preferred_element_type=jnp.float32
                      ).astype(x_local.dtype)
        return out, ws, call_index + 1
    mp = -(-m // sublane_align(x_local.dtype)) * sublane_align(x_local.dtype)
    if mp != m:
        x_local = jnp.pad(x_local, ((0, mp - m), (0, 0)))
    nch = _gemm_ar_chunks(ncols, n_chunks)
    nc = ncols // nch
    if ws.shape != (2, nch, n, mp, nc):
        raise ValueError(f"workspace shape {ws.shape} != (2, {nch}, {n}, "
                         f"{mp}, {nc}) — allocate via gemm_ar_stream_workspace")
    if ws.dtype != x_local.dtype:
        raise ValueError(f"workspace dtype {ws.dtype} != {x_local.dtype}")
    from triton_distributed_tpu.language.core import smem_spec

    tm = mp
    tk = pick_tile(k, 1024, 128)
    tn = pick_tile(nc, 1024, 128)
    kernel = functools.partial(_gemm_ar_stream_kernel, n, axis, mp, k,
                               ncols, nch, tm, tk, tn)
    out, ws_new = kernel_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((nch, mp, nc), x_local.dtype),
            jax.ShapeDtypeStruct(ws.shape, ws.dtype),
        ),
        in_specs=[smem_spec((1,)), any_spec(), any_spec(), any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.VMEM((tm, tn), jnp.float32),            # matmul acc
            pltpu.VMEM((mp, nc), x_local.dtype),          # reduce stage
            pltpu.VMEM((mp, nc), jnp.float32),            # reduce acc
            pltpu.SemaphoreType.DMA((max((n - 1) * nch, 1),)),
            pltpu.SemaphoreType.DMA((2,)),                # per-parity recv
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={3: 1},   # ws input -> ws output (persistent)
    )(jnp.asarray(call_index, jnp.int32).reshape(1), x_local, b_local, ws)
    # chunk-major -> (mp, ncols)
    out = out.transpose(1, 0, 2).reshape(mp, ncols)[:m]
    return out, ws_new, call_index + 1


def gemm_ar_local(x_local: jax.Array, b_local: jax.Array, axis: str = "tp",
                  num_ranks: int | None = None,
                  method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Device-local GEMM+AR inside an existing shard_map region.

    x_local: (m, k_local); b_local: (k_local, ncols); returns the fully
    reduced (m, ncols) on every device. Sequential dot → AR compose — the
    sound protocol for ONE-OFF calls (a transient workspace could be
    remotely written before the peer's allocation exists). Steady-state
    loops should thread a persistent workspace through
    :func:`gemm_ar_stream`, the fused chunk-overlapped path.
    """
    partial = jnp.dot(x_local, b_local, preferred_element_type=jnp.float32)
    partial = partial.astype(x_local.dtype)
    return all_reduce_local(partial, axis=axis, num_ranks=num_ranks,
                            method=method)


def gemm_allreduce(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
                   axis: str = "tp",
                   method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Host-level GEMM+AR: a (m, n·k) k-sharded, b (n·k, ncols) row-sharded →
    replicated (m, ncols) = a @ b."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    method_key = method.value if isinstance(method, AllReduceMethod) else str(method)
    key = (axis, a.shape, b.shape, str(a.dtype), method_key)

    def make():
        return functools.partial(gemm_ar_local, axis=axis, num_ranks=n,
                                 method=method)

    return cached_shard_jit(ctx, "gemm_allreduce", key, make,
                            (P(None, axis), P(axis)), P(None))(a, b)
