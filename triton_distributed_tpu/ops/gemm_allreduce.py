"""GEMM + AllReduce epilogue (TP fallback path when the RS/AG layout is not
wanted, e.g. single-layer calls or decode with replicated activations).

Reference: ``python/triton_dist/kernels/nvidia/gemm_allreduce.py`` —
``create_gemm_ar_context`` / ``gemm_allreduce_op`` / low-latency variant.

TPU design note: for the *matmul itself* XLA's native dot is already optimal
(MXU-tiled, pipelined); a hand-written Pallas matmul only pays off when comm
waits must interleave with compute (ops/allgather_gemm.py). So this op is the
idiomatic composition: XLA dot producing the partial product + the Pallas
one-shot/two-shot AllReduce kernel (ops/allreduce.py) for the reduction.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu.ops.allreduce import AllReduceMethod, all_reduce_local
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


def gemm_ar_local(x_local: jax.Array, b_local: jax.Array, axis: str = "tp",
                  num_ranks: int | None = None,
                  method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Device-local GEMM+AR inside an existing shard_map region.

    x_local: (m, k_local); b_local: (k_local, ncols); returns the fully
    reduced (m, ncols) on every device.
    """
    partial = jnp.dot(x_local, b_local, preferred_element_type=jnp.float32)
    partial = partial.astype(x_local.dtype)
    return all_reduce_local(partial, axis=axis, num_ranks=num_ranks,
                            method=method)


def gemm_allreduce(a: jax.Array, b: jax.Array, ctx: DistContext | None = None,
                   axis: str = "tp",
                   method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Host-level GEMM+AR: a (m, n·k) k-sharded, b (n·k, ncols) row-sharded →
    replicated (m, ncols) = a @ b."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    method_key = method.value if isinstance(method, AllReduceMethod) else str(method)
    key = (axis, a.shape, b.shape, str(a.dtype), method_key)

    def make():
        return functools.partial(gemm_ar_local, axis=axis, num_ranks=n,
                                 method=method)

    return cached_shard_jit(ctx, "gemm_allreduce", key, make,
                            (P(None, axis), P(axis)), P(None))(a, b)
