"""AllReduce over ICI.

Reference: ``python/triton_dist/kernels/nvidia/allreduce.py`` (1208 LoC) —
one-shot push, two-shot, double-tree, multimem variants, auto-selected by size
(:1101). TPU method space (no NVLS multicast exists — SURVEY.md §7 maps
multimem → ring/tree):

- ``ONE_SHOT``: every device pushes its full block to all peers, each reduces
  locally — one network hop, n× traffic; latency-optimal for small payloads
  (decode activations).
- ``TWO_SHOT``: ring reduce-scatter + ring all-gather — 2(n-1) hops of 1/n
  payload each; bandwidth-optimal for large payloads.
- ``XLA``: ``jax.lax.psum`` golden/fallback.
"""

from __future__ import annotations

import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import PartitionSpec as P

from triton_distributed_tpu import language as dl
from triton_distributed_tpu.language import shmem_device as shmem
from triton_distributed_tpu.language.core import kernel_call, any_spec
from triton_distributed_tpu.ops.allgather import all_gather_local, AllGatherMethod
from triton_distributed_tpu.ops.reduce_scatter import reduce_scatter_local
from triton_distributed_tpu.ops.tiling import pick_tile, sublane_align
from triton_distributed_tpu.runtime.context import DistContext, get_context
from triton_distributed_tpu.runtime.jit_cache import cached_shard_jit


class AllReduceMethod(enum.Enum):
    """Reference allreduce.py methods, collapsed to the TPU space."""

    AUTO = "auto"
    ONE_SHOT = "one_shot"
    TWO_SHOT = "two_shot"
    TREE = "tree"
    XLA = "xla"


def get_auto_allreduce_method(nbytes: int, num_ranks: int,
                              tree_halves: int = 2) -> AllReduceMethod:
    """Perf-model selection (reference get_auto_allreduce_method,
    allreduce.py:1101 picks by size/NVLS support/tree): one-shot wins when
    the payload is latency-bound, the double binary tree in the middle
    band (log-depth hops of half payload), two-shot (RS+AG) when
    bandwidth-bound. The crossovers come from the ICI cost models in
    runtime/perf_model.py. ``tree_halves``: 1 when the shape forces the
    single-tree fallback (see :func:`_tree_halves`) so the model charges
    the full payload per hop."""
    if num_ranks <= 2:
        return AllReduceMethod.ONE_SHOT
    from triton_distributed_tpu.runtime.perf_model import allreduce_time_s

    times = {m: allreduce_time_s(nbytes, num_ranks, m,
                                 tree_halves=tree_halves)
             for m in ("one_shot", "two_shot", "tree")}
    best = min(times, key=times.get)
    return AllReduceMethod(best)


def _ar_one_shot_kernel(n: int, axis: str, m: int, tile_m: int,
                        x_ref, out_ref, ws, va, vacc,
                        send_sems, recv_sem, copy_sem):
    """One-shot push AR (reference one-shot variants, allreduce.py:214-…):
    push local block into slot ``me`` of every peer's workspace, reduce all
    slots locally, staged through VMEM with fp32 accumulation."""
    me = dl.rank(axis)
    shmem.barrier_all(axis)
    local = pltpu.make_async_copy(x_ref, ws.at[me], copy_sem)
    local.start()
    handles = []
    for i in range(n - 1):
        peer = jax.lax.rem(me + 1 + i, n)
        handles.append(
            shmem.putmem_nbi_block(x_ref, ws.at[me], send_sems.at[i],
                                   recv_sem, peer, axis)
        )
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, recv_sem, n - 1)
    _reduce_slots(n, m, tile_m, ws, out_ref, va, vacc, copy_sem)


def _reduce_slots(n, m, tile_m, ws, out_ref, va, vacc, copy_sem):
    for t in range(m // tile_m):
        rows = pl.ds(t * tile_m, tile_m)
        vacc[...] = jnp.zeros_like(vacc)
        for i in range(n):
            pltpu.make_async_copy(ws.at[i].at[rows], va, copy_sem).start()
            pltpu.make_async_copy(ws.at[i].at[rows], va, copy_sem).wait()
            vacc[...] = vacc[...] + va[...].astype(jnp.float32)
        va[...] = vacc[...].astype(va.dtype)
        pltpu.make_async_copy(va, out_ref.at[rows], copy_sem).start()
        pltpu.make_async_copy(va, out_ref.at[rows], copy_sem).wait()


def _ar_one_shot_parity_kernel(n: int, axis: str, m: int, tile_m: int,
                               straggler,
                               idx_ref, x_ref, _ws_in, out_ref, ws,
                               va, vacc, send_sems, recv_sems, copy_sem):
    """Barrier-free one-shot AR for repeated decode-step calls.

    Reference: the ``call_count`` parity double-buffering of
    ``low_latency_all_to_all.py:125-175`` — two PERSISTENT workspace slot
    sets and two recv semaphores, flipped by the caller-supplied call
    index, replace the full-mesh entry barrier (VERDICT r2 #6: two extra
    sync phases per transformer layer on the decode path).

    The workspace is caller-owned and threaded through the decode loop
    (input aliased to output) — persistence is what makes barrier-freedom
    sound: a per-call transient buffer could be remotely written before the
    peer's kernel (hence allocation) even exists, which is exactly what the
    barrier variant's entry barrier protects against.

    Safety (per parity p): for a rank to write parity-p slots of call t+2,
    it must have finished call t+1, which required every peer's call-t+1
    delivery, which each peer sends only after fully reducing its call-t
    (parity-p) workspace — reuse is ordered by the DMA-completion chain
    itself. Per-parity recv semaphores keep a fast peer's t+1 deliveries
    from being miscounted against call t's wait.
    """
    me = dl.rank(axis)
    p = jax.lax.rem(idx_ref[0], 2)
    straggler = dl.resolve_straggler(straggler, n, idx_ref[0])
    dl.maybe_straggle(straggler, me)
    slots = ws.at[p]                          # (n, m, cols) parity slab
    local = pltpu.make_async_copy(x_ref, slots.at[me], copy_sem)
    local.start()
    handles = []
    for i in range(n - 1):
        peer = jax.lax.rem(me + 1 + i, n)
        handles.append(
            shmem.putmem_nbi_block(x_ref, slots.at[me], send_sems.at[i],
                                   recv_sems.at[p], peer, axis)
        )
    local.wait()
    shmem.quiet(*handles)
    shmem.wait_deliveries(x_ref, recv_sems.at[p], n - 1)
    _reduce_slots(n, m, tile_m, slots, out_ref, va, vacc, copy_sem)


# ---------------------------------------------------------------------------
# Tree / double binary tree AllReduce — the latency class between one-shot
# and two-shot. Reference: kernels/nvidia/allreduce.py:214-1208 (double-tree
# variants), auto-selected at :1101; SURVEY §7 names "double-tree/two-shot
# tuned for ICI" as the multimem substitute.
# ---------------------------------------------------------------------------

def _tree_pos(me, n: int, tree: int):
    """This rank's position in ``tree`` (heap order). Tree 0 is the heap
    over rank order; tree 1 over REVERSED ranks, so (for even n) every
    interior node of one tree is a leaf of the other — the double binary
    tree property that lets the two half-payload trees progress
    concurrently."""
    return me if tree == 0 else n - 1 - me


def _tree_rank(pos, n: int, tree: int):
    return pos if tree == 0 else n - 1 - pos


def _ar_tree_kernel(n: int, axis: str, m: int, mh: int, n_trees: int,
                    tile_m: int, x_ref, out_ref, ws, va, vacc,
                    up_send_sems, down_send_sems, child_recv_sems,
                    bcast_recv_sems, copy_sem):
    """Reduce-up + broadcast-down over ``n_trees`` complementary binary
    trees, each owning an mh-row half of the payload.

    Phase order is leaf-sends (both trees) → interior reduce (both trees)
    → broadcast (both trees): a node is a leaf in one tree and interior in
    the other, so both trees' reduce chains are in flight at once instead
    of tree 1 waiting for tree 0 to finish.

    Partial sums travel in the payload dtype (one rounding per tree level,
    like the ring RS); accumulation is staged through fp32 VMEM tiles.
    """
    me = dl.rank(axis)
    shmem.barrier_all(axis)

    def rows(tree):
        return pl.ds(tree * mh, mh)

    def chunk_like(tree):
        return out_ref.at[rows(tree)]

    def send_up(tree, pos):
        # Child 2i+1 lands in parent slot 0, child 2i+2 in slot 1.
        slot = jax.lax.rem(pos + 1, 2)
        parent = _tree_rank((pos - 1) // 2, n, tree)
        h = shmem.putmem_nbi_block(
            out_ref.at[rows(tree)], ws.at[tree].at[slot],
            up_send_sems.at[tree], child_recv_sems.at[tree], parent, axis)
        h.wait_send()

    # -- leaf sends: out rows = x rows, push to parent -----------------------
    for tree in range(n_trees):
        pos = _tree_pos(me, n, tree)
        is_leaf = 2 * pos + 1 >= n

        @pl.when(is_leaf)
        def _(tree=tree, pos=pos):
            cp = pltpu.make_async_copy(x_ref.at[rows(tree)],
                                       out_ref.at[rows(tree)], copy_sem)
            cp.start()
            cp.wait()
            send_up(tree, pos)

    # -- interior reduce: wait children, accumulate, send up -----------------
    for tree in range(n_trees):
        pos = _tree_pos(me, n, tree)
        is_interior = 2 * pos + 1 < n
        has2 = 2 * pos + 2 < n

        @pl.when(is_interior)
        def _(tree=tree, pos=pos, has2=has2):
            shmem.wait_deliveries(chunk_like(tree), child_recv_sems.at[tree],
                                  1)

            @pl.when(has2)
            def _():
                shmem.wait_deliveries(chunk_like(tree),
                                      child_recv_sems.at[tree], 1)

            for t in range(mh // tile_m):
                tr = pl.ds(tree * mh + t * tile_m, tile_m)
                wr = pl.ds(t * tile_m, tile_m)
                pltpu.make_async_copy(x_ref.at[tr], va, copy_sem).start()
                pltpu.make_async_copy(x_ref.at[tr], va, copy_sem).wait()
                vacc[...] = va[...].astype(jnp.float32)
                w0 = ws.at[tree].at[0].at[wr]
                pltpu.make_async_copy(w0, va, copy_sem).start()
                pltpu.make_async_copy(w0, va, copy_sem).wait()
                vacc[...] = vacc[...] + va[...].astype(jnp.float32)

                @pl.when(has2)
                def _():
                    w1 = ws.at[tree].at[1].at[wr]
                    pltpu.make_async_copy(w1, va, copy_sem).start()
                    pltpu.make_async_copy(w1, va, copy_sem).wait()
                    vacc[...] = vacc[...] + va[...].astype(jnp.float32)

                va[...] = vacc[...].astype(va.dtype)
                pltpu.make_async_copy(va, out_ref.at[tr], copy_sem).start()
                pltpu.make_async_copy(va, out_ref.at[tr], copy_sem).wait()

            @pl.when(pos != 0)
            def _():
                send_up(tree, pos)

    # -- broadcast down ------------------------------------------------------
    for tree in range(n_trees):
        pos = _tree_pos(me, n, tree)

        @pl.when(pos != 0)
        def _(tree=tree):
            shmem.wait_deliveries(chunk_like(tree), bcast_recv_sems.at[tree],
                                  1)

        for child in (0, 1):
            c = 2 * pos + 1 + child

            @pl.when(c < n)
            def _(tree=tree, c=c, child=child):
                peer = _tree_rank(c, n, tree)
                h = shmem.putmem_nbi_block(
                    out_ref.at[rows(tree)], out_ref.at[rows(tree)],
                    down_send_sems.at[2 * tree + child],
                    bcast_recv_sems.at[tree], peer, axis)
                h.wait_send()


def _tree_halves(m: int, dtype) -> int:
    """2 when the rows split into two sublane-aligned halves (double
    tree), else 1 (single full-payload tree). Shared by the kernel builder
    and the AUTO cost model so they never disagree."""
    align = sublane_align(dtype)
    return 2 if (m % (2 * align) == 0 and m >= 2 * align) else 1


def _all_reduce_tree(x_local: jax.Array, axis: str, n: int) -> jax.Array:
    m, cols = x_local.shape
    align = sublane_align(x_local.dtype)
    n_trees = _tree_halves(m, x_local.dtype)
    mh = m // n_trees
    tile_m = pick_tile(mh, 512, align)
    kernel = functools.partial(_ar_tree_kernel, n, axis, m, mh, n_trees,
                               tile_m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, cols), x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        workspaces=[
            jax.ShapeDtypeStruct((n_trees, 2, mh, cols), x_local.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.VMEM((tile_m, cols), jnp.float32),
            pltpu.SemaphoreType.DMA((n_trees,)),       # up sends
            pltpu.SemaphoreType.DMA((2 * n_trees,)),   # down sends
            pltpu.SemaphoreType.DMA((n_trees,)),       # child recv
            pltpu.SemaphoreType.DMA((n_trees,)),       # bcast recv
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local)


def all_reduce_local(x_local: jax.Array, axis: str = "tp",
                     num_ranks: int | None = None,
                     method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Device-local AllReduce inside an existing shard_map region.
    ``x_local``: (m, cols) per device → (m, cols) = Σ_d x_d.

    For repeated steady-state calls (decode loops) see
    :func:`all_reduce_stream` — the barrier-free parity path.
    """
    if isinstance(axis, (tuple, list)):
        # Multi-axis form (ops/multi_axis.py; round-4 VERDICT #4/#5):
        # num_ranks is (n0, n1); AUTO maps to the hierarchical one-shot.
        if num_ranks is None:
            raise ValueError("num_ranks (n0, n1) required inside shard_map")
        from triton_distributed_tpu.ops.multi_axis import (
            all_reduce_torus_local,
        )

        m = method.value if isinstance(method, AllReduceMethod) else str(method)
        if m == "xla":
            return jax.lax.psum(x_local, tuple(axis))
        # "auto" passes through: the torus op maps it to the hierarchical
        # one-shot on a real 2-D grid but lets the 1-D AUTO cost model run
        # on degenerate (n,1)/(1,n) meshes.
        return all_reduce_torus_local(
            x_local, axes=tuple(axis), dims=tuple(num_ranks), method=m)
    method = AllReduceMethod(method) if not isinstance(method, AllReduceMethod) else method
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1:
        return x_local
    if method == AllReduceMethod.AUTO:
        method = get_auto_allreduce_method(
            x_local.size * x_local.dtype.itemsize, n,
            tree_halves=_tree_halves(x_local.shape[0], x_local.dtype))
    if method == AllReduceMethod.XLA:
        return jax.lax.psum(x_local, axis)
    m, cols = x_local.shape
    if method == AllReduceMethod.TREE:
        return _all_reduce_tree(x_local, axis, n)
    if method == AllReduceMethod.TWO_SHOT:
        if m % n:
            raise ValueError(
                f"two_shot requires rows {m} divisible by num_ranks {n}")
        scattered = reduce_scatter_local(x_local, axis=axis, num_ranks=n)
        return all_gather_local(scattered, axis=axis, num_ranks=n,
                                method=AllGatherMethod.RING_1D)
    tile_m = pick_tile(m, 512, sublane_align(x_local.dtype))
    kernel = functools.partial(_ar_one_shot_kernel, n, axis, m, tile_m)
    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, cols), x_local.dtype),
        in_specs=[any_spec()],
        out_specs=any_spec(),
        workspaces=[
            jax.ShapeDtypeStruct((n, m, cols), x_local.dtype),  # symmetric ws
        ],
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.VMEM((tile_m, cols), jnp.float32),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
        uses_barrier=True,
    )(x_local)


# ---------------------------------------------------------------------------
# Barrier-free steady-state AR (decode path). VERDICT r2 #6.
# ---------------------------------------------------------------------------

def _ar_rows_padded(m: int, dtype) -> int:
    """Row dim padded to the dtype's sublane tiling: Mosaic cannot slice a
    1-row bf16 block out of the (2, n, m, cols) workspace (tiling (2,128)),
    which is exactly the decode shape (batch 1, bf16)."""
    a = sublane_align(dtype)
    return -(-m // a) * a


def ar_stream_workspace(n: int, m: int, cols: int, dtype
                        ) -> tuple[jax.Array, jax.Array]:
    """Device-local persistent (workspace, call_index) pair for
    :func:`all_reduce_stream`. Allocate ONCE and thread through the decode
    loop (at the host level: a (n_dev,)-sharded leading dim, see
    models/engine.py). Both parities start clean. The row dim is padded to
    the sublane tiling internally (batch-1 bf16 decode otherwise fails to
    compile); all_reduce_stream pads/slices to match."""
    return (jnp.zeros((2, n, _ar_rows_padded(m, dtype), cols), dtype),
            jnp.zeros((), jnp.int32))


def all_reduce_stream(x_local: jax.Array, ws: jax.Array,
                      call_index: jax.Array, *, axis: str = "tp",
                      num_ranks: int | None = None,
                      straggler: tuple | None = None,
                      force_kernel: bool = False):
    """Barrier-free one-shot AllReduce over a persistent parity workspace.

    x_local: (m, cols); ws: (2, n, m, cols) from :func:`ar_stream_workspace`
    threaded through the loop (donated/aliased); call_index: traced int32,
    incremented once per call, SAME sequence on every rank (SPMD program
    order guarantees this). Returns (sum (m, cols), ws', call_index + 1).
    Zero full-mesh barriers in steady state — the reference's call_count
    parity protocol (low_latency_all_to_all.py:125-175) applied to AR.
    """
    if num_ranks is None:
        raise ValueError("num_ranks required inside shard_map")
    n = num_ranks
    if n == 1 and not force_kernel:
        # force_kernel: single-chip Mosaic compile check (scripts/
        # check_on_chip.py) — the degenerate kernel (0 peers) still
        # exercises the parity slicing + semaphore paths.
        return x_local, ws, call_index + 1
    m, cols = x_local.shape
    mp = _ar_rows_padded(m, x_local.dtype)
    if ws.shape != (2, n, mp, cols):
        raise ValueError(f"workspace shape {ws.shape} != (2, {n}, {mp}, "
                         f"{cols}) — allocate via ar_stream_workspace")
    if ws.dtype != x_local.dtype:
        raise ValueError(f"workspace dtype {ws.dtype} != input "
                         f"{x_local.dtype} — allocate ar_stream_workspace "
                         "with the activation dtype")
    from triton_distributed_tpu.language.core import smem_spec

    if mp != m:
        x_local = jnp.pad(x_local, ((0, mp - m), (0, 0)))
    tile_m = pick_tile(mp, 512, sublane_align(x_local.dtype))
    kernel = functools.partial(_ar_one_shot_parity_kernel, n, axis, mp,
                               tile_m, straggler)
    out, ws_new = kernel_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((mp, cols), x_local.dtype),
            jax.ShapeDtypeStruct(ws.shape, ws.dtype),
        ),
        in_specs=[smem_spec((1,)), any_spec(), any_spec()],
        out_specs=(any_spec(), any_spec()),
        scratch_shapes=[
            pltpu.VMEM((tile_m, cols), x_local.dtype),
            pltpu.VMEM((tile_m, cols), jnp.float32),
            pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA(()),
        ],
        input_output_aliases={2: 1},   # ws input -> ws output (persistent)
    )(jnp.asarray(call_index, jnp.int32).reshape(1), x_local, ws)
    return out[:m], ws_new, call_index + 1


def all_reduce(x: jax.Array, ctx: DistContext | None = None, axis: str = "tp",
               method: AllReduceMethod | str = AllReduceMethod.AUTO) -> jax.Array:
    """Host-level AllReduce: ``x`` globally (n, m, cols) stacked contributions
    over ``axis`` → replicated (m, cols) sum.

    With comm tuning opted in (TDTPU_AUTOTUNE_COMM=1), AUTO resolves by
    MEASUREMENT — the one/two-shot/xla crossover is timed on this mesh via
    the chain harness and disk-cached — instead of the perf model
    (reference contextual_autotune(is_dist=True), autotuner.py:97)."""
    ctx = ctx or get_context()
    n = ctx.axis_size(axis)
    if method in (AllReduceMethod.AUTO, "auto") and n > 1:
        from triton_distributed_tpu.runtime.autotuner import (
            comm_autotune_enabled, tuned_allreduce_method,
        )

        if comm_autotune_enabled():
            method = tuned_allreduce_method(x, ctx, axis=axis)
    method_key = method.value if isinstance(method, AllReduceMethod) else str(method)
    key = (axis, method_key, x.shape, str(x.dtype))

    def make():
        fn = functools.partial(all_reduce_local, axis=axis, num_ranks=n,
                               method=method)
        return lambda xl: fn(xl[0])

    return cached_shard_jit(ctx, "all_reduce", key, make, P(axis), P(None),
                            ici_axes=(axis,))(x)
