"""Paged-KV attention decode — page-table driven flash decode.

Reference: ``mega_triton_kernel/models/`` ``PagedKVCache`` + the paged
flash-attention decode task (SURVEY.md §2.7): the KV cache lives in
fixed-size pages; a per-sequence page table maps logical positions to
pool pages, so sequences of different lengths share one pool with no
per-sequence max_seq reservation.

TPU-native shape: the page table rides **scalar prefetch** into SMEM —
the idiomatic Mosaic pattern for data-dependent addressing (the grid's
DMA for page j is issued from a table value, exactly what
PrefetchScalarGridSpec exists for). The kernel walks (batch, page) grid
steps; online-softmax state for the current sequence lives in VMEM
scratch, carried across that sequence's page steps (TPU grid steps run
sequentially on the core).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.language.core import _interpret_params
from triton_distributed_tpu.runtime.context import use_interpret


class PagedKVCache(NamedTuple):
    """A paged KV pool + per-sequence page tables.

    k_pool/v_pool: (num_pages, page, hkv, d); page_table: (B, max_pages)
    int32 (pool page id per logical page); kv_lens: (B,) valid tokens.
    """

    k_pool: jax.Array
    v_pool: jax.Array
    page_table: jax.Array
    kv_lens: jax.Array

    @property
    def page_size(self) -> int:
        return self.k_pool.shape[1]


def init_paged_kv_cache(batch: int, *, num_pages: int, page_size: int,
                        num_kv_heads: int, head_dim: int, max_pages: int,
                        dtype=jnp.float32, kv_dtype=None) -> PagedKVCache:
    """Pool + identity page tables (page allocation policy is the host's;
    tables are data, so any allocator can rewrite them between steps).

    ``kv_dtype`` overrides the POOL storage dtype (tables/lengths stay
    int32) — ``float8_e4m3fn`` is the serving payload (ROADMAP 1a): half
    the attention DMA bytes per decode step, and at a fixed HBM budget
    the pool holds twice the pages. Appends quantize through the
    saturating ``models/fp8._to_e4m3`` cast; the kernel dequantizes to
    fp32 inside its flash accumulation (quantize-then-attend)."""
    dt = kv_dtype if kv_dtype is not None else dtype
    shape = (num_pages, page_size, num_kv_heads, head_dim)
    table = (jnp.arange(batch * max_pages, dtype=jnp.int32)
             .reshape(batch, max_pages) % num_pages)
    return PagedKVCache(jnp.zeros(shape, dt), jnp.zeros(shape, dt),
                        table, jnp.zeros((batch,), jnp.int32))


def _to_pool_dtype(a: jax.Array, pool_dtype) -> jax.Array:
    """Cast a k/v value to the pool's storage dtype — the shared
    ``models/fp8.saturate_cast``: for e4m3 pools the cast MUST saturate
    (jnp's plain float→float8_e4m3fn conversion NaNs past ±448, and one
    hot KV element would silently poison every later softmax over that
    page). Lazy import: ops must stay importable without models."""
    from triton_distributed_tpu.models.fp8 import saturate_cast

    return saturate_cast(a, pool_dtype)


def paged_append(cache: PagedKVCache, k_new: jax.Array,
                 v_new: jax.Array) -> PagedKVCache:
    """Append one token's k/v per sequence at each sequence's current
    length (k_new/v_new: (B, hkv, d)); pure-functional scatter.

    Sequences already at capacity (kv_lens == max_pages*page) are
    SATURATED: the append is dropped and kv_lens stays put — under jit a
    runtime error is impossible, and clamp-indexing would silently corrupt
    the last page instead. The host owns eviction/reallocation.
    """
    P = cache.page_size
    b = k_new.shape[0]
    capacity = cache.page_table.shape[1] * P
    pos = cache.kv_lens
    ok = pos < capacity
    safe_pos = jnp.minimum(pos, capacity - 1)
    page_idx = cache.page_table[jnp.arange(b), safe_pos // P]
    row = safe_pos % P

    def scatter(pool, new):
        cur = pool[page_idx, row]
        val = jnp.where(ok[:, None, None], _to_pool_dtype(new, pool.dtype),
                        cur)
        return pool.at[page_idx, row].set(val)

    return cache._replace(k_pool=scatter(cache.k_pool, k_new),
                          v_pool=scatter(cache.v_pool, v_new),
                          kv_lens=cache.kv_lens + ok.astype(jnp.int32))


def paged_append_window(cache: PagedKVCache, k_new: jax.Array,
                        v_new: jax.Array) -> PagedKVCache:
    """Append a WINDOW of W tokens' k/v per sequence at positions
    ``[kv_lens, kv_lens + W)`` (k_new/v_new: (B, W, hkv, d)) — the
    speculative-decode verify step's append (docs/serving.md
    "Speculative decode"): the last accepted token plus k draft
    candidates land in one scatter, then the verifier attends each
    candidate position causally and the host truncates ``kv_lens`` back
    to the accepted prefix (append-then-truncate; positions past the
    truncation are dead data the next append overwrites before they can
    ever be read).

    Per-(b, i) writes past capacity are dropped exactly like
    :func:`paged_append`'s saturation clamp; stored values are
    bit-identical to W sequential ``paged_append`` calls (same
    ``_to_pool_dtype`` quantization point). W = 1 IS ``paged_append``.
    """
    P = cache.page_size
    b, w = k_new.shape[0], k_new.shape[1]
    capacity = cache.page_table.shape[1] * P
    pos = cache.kv_lens[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    ok = pos < capacity                                   # (B, W)
    safe_pos = jnp.minimum(pos, capacity - 1)
    # Out-of-capacity rows must DROP, not clamp: a clamped index would
    # alias the last in-capacity position in the SAME scatter and could
    # overwrite a real candidate's just-appended k/v with the stale
    # pre-step value (duplicate-index scatter order is undefined).
    # Redirecting the page index past the pool and scattering with
    # mode="drop" discards them exactly like paged_append's saturation.
    page_idx = jnp.where(
        ok, cache.page_table[jnp.arange(b)[:, None], safe_pos // P],
        cache.k_pool.shape[0])
    row = safe_pos % P

    def scatter(pool, new):
        return pool.at[page_idx.reshape(-1), row.reshape(-1)].set(
            _to_pool_dtype(new.reshape(b * w, *new.shape[2:]),
                           pool.dtype), mode="drop")

    return cache._replace(
        k_pool=scatter(cache.k_pool, k_new),
        v_pool=scatter(cache.v_pool, v_new),
        kv_lens=cache.kv_lens + jnp.sum(ok.astype(jnp.int32), axis=1))


# ---------------------------------------------------------------------------
# Kernel.
# ---------------------------------------------------------------------------

def _paged_decode_kernel(max_pages: int, page: int, scale: float,
                         normalize: bool,
                         table_ref, lens_ref,       # scalar prefetch (SMEM)
                         q_ref, kp_ref, vp_ref,     # q block + pools (ANY)
                         o_ref, stat_ref,           # out blocks (VMEM)
                         kpg, vpg, acc, stat, sem, sem2):
    b = pl.program_id(0)
    j = pl.program_id(1)
    kv_len = lens_ref[b]

    @pl.when(j == 0)
    def _():
        acc[...] = jnp.zeros_like(acc)
        stat[...] = jnp.full_like(stat, -1e30)
        stat[:, 1:2] = jnp.zeros_like(stat[:, 1:2])

    valid_in_page = kv_len - j * page     # tokens of this seq in page j

    @pl.when(valid_in_page > 0)
    def _():
        pid = table_ref[b * max_pages + j]
        ck = pltpu.make_async_copy(kp_ref.at[pid], kpg, sem)
        cv = pltpu.make_async_copy(vp_ref.at[pid], vpg, sem2)
        ck.start()
        cv.start()          # both page DMAs in flight together
        ck.wait()
        cv.wait()

        q = q_ref[0].astype(jnp.float32)            # (hq, d)
        hq, d = q.shape
        hkv = kpg.shape[1]
        g = hq // hkv
        k = kpg[...].astype(jnp.float32)            # (page, hkv, d)
        v = vpg[...].astype(jnp.float32)
        # Per-kv-head 2D matmuls (static unroll): Mosaic rejects the
        # batched einsum form ("batch dims must be equal"), and hkv per
        # device is small.
        nt = (((1,), (1,)), ((), ()))               # contract last dims
        s = jnp.concatenate(
            [jax.lax.dot_general(q[h * g:(h + 1) * g], k[:, h, :], nt,
                                 preferred_element_type=jnp.float32)
             for h in range(hkv)], axis=0) * scale  # (hq, page)
        row_mask = jax.lax.broadcasted_iota(jnp.int32, (hq, page), 1)
        s = jnp.where(row_mask < valid_in_page, s, -1e30)

        m_prev = stat[:, 0:1]
        l_prev = stat[:, 1:2]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        corr = jnp.exp(m_prev - m_new)
        pv = jnp.concatenate(
            [jnp.dot(p[h * g:(h + 1) * g], v[:, h, :],
                     preferred_element_type=jnp.float32)
             for h in range(hkv)], axis=0)          # (hq, d)
        acc[...] = acc[...] * corr + pv
        stat[:, 0:1] = m_new
        stat[:, 1:2] = l_prev * corr + jnp.sum(p, axis=1, keepdims=True)

    @pl.when(j == max_pages - 1)
    def _():
        if normalize:
            o_ref[0] = (acc[...] / jnp.maximum(stat[:, 1:2], 1e-30)
                        ).astype(o_ref.dtype)
        else:
            # Split-KV partial contract (reference flash_decode.py:129-481):
            # UNnormalized fp32 numerator + running (m, l) for a later
            # combine (intra- or inter-rank).
            o_ref[0] = acc[...].astype(o_ref.dtype)
        stat_ref[0] = stat[...]


def paged_decode_attention(q: jax.Array, cache: PagedKVCache, *,
                           normalize: bool = True):
    """One-token GQA decode over the paged cache. q: (B, hq, d) → (B, hq, d).

    Pure-jax golden: gather pages, mask, softmax (see tests). The Pallas
    path walks each sequence's page table from SMEM and DMAs exactly the
    pages that hold valid tokens.

    fp8 KV pools (``init_paged_kv_cache(kv_dtype=float8_e4m3fn)``): the
    page DMAs move HALF the bytes — the decode-bandwidth lever — and the
    kernel dequantizes each landed page to fp32 inside the flash
    accumulation. Parity vs :func:`paged_decode_attention_golden` stays
    EXACT (not approximate): both paths read the same stored e4m3 values
    (quantize-then-attend — quantization happened once, at append).

    ``normalize=False`` returns the split-KV partial instead:
    (acc (B,hq,d) fp32 unnormalized, m (B,hq), l (B,hq)) — the combine
    contract of ops/flash_decode.py (reference flash_decode.py:129-481
    split-KV kernels feeding the inter-rank combine :482).
    """
    b, hq, d = q.shape
    num_pages, page, hkv, _ = cache.k_pool.shape
    max_pages = cache.page_table.shape[1]
    scale = d ** -0.5

    kernel = functools.partial(_paged_decode_kernel, max_pages, page, scale,
                               normalize)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, max_pages),
        in_specs=[
            pl.BlockSpec((1, hq, d), lambda tb, tj, *_: (tb, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec((1, hq, d), lambda tb, tj, *_: (tb, 0, 0)),
            pl.BlockSpec((1, hq, 128), lambda tb, tj, *_: (tb, 0, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((page, hkv, d), cache.k_pool.dtype),
            pltpu.VMEM((page, hkv, d), cache.v_pool.dtype),
            pltpu.VMEM((hq, d), jnp.float32),
            pltpu.VMEM((hq, 128), jnp.float32),   # stat: [:,0]=m, [:,1]=l
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
        ],
    )
    interpret = _interpret_params() if use_interpret() else False
    out_dtype = q.dtype if normalize else jnp.float32
    out, stat = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b, hq, d), out_dtype),
            jax.ShapeDtypeStruct((b, hq, 128), jnp.float32),
        ),
        interpret=interpret,
    )(cache.page_table.reshape(-1), cache.kv_lens, q,
      cache.k_pool, cache.v_pool)
    if normalize:
        return out
    return out, stat[:, :, 0], stat[:, :, 1]


def paged_decode_attention_golden(q: jax.Array,
                                  cache: PagedKVCache) -> np.ndarray:
    """Pure-numpy reference. Reads the pools AS STORED (ml_dtypes widens
    e4m3 → float64 exactly), so an fp8 cache is compared under the same
    quantize-then-attend semantics the kernel runs — parity is exact."""
    qn = np.asarray(q, np.float64)
    kp = np.asarray(cache.k_pool, np.float64)
    vp = np.asarray(cache.v_pool, np.float64)
    table = np.asarray(cache.page_table)
    lens = np.asarray(cache.kv_lens)
    b, hq, d = qn.shape
    page = cache.page_size
    hkv = kp.shape[2]
    g = hq // hkv
    out = np.zeros_like(qn)
    for i in range(b):
        n_tok = int(lens[i])
        if n_tok == 0:
            continue
        pages = table[i][: -(-n_tok // page)]
        k = kp[pages].reshape(-1, hkv, d)[:n_tok]
        v = vp[pages].reshape(-1, hkv, d)[:n_tok]
        kg = np.repeat(k, g, axis=1)
        vg = np.repeat(v, g, axis=1)
        s = np.einsum("hd,khd->hk", qn[i], kg) / np.sqrt(d)
        p = np.exp(s - s.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        out[i] = np.einsum("hk,khd->hd", p, vg)
    return out.astype(np.asarray(q).dtype)
