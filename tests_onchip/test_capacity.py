"""Capacity-shape gates: the exact configurations COVERAGE.md claims
compile and run on real TPU, as pytest red/green (round-4 VERDICT #8;
reference pattern: test/stress/stress_test_ag_gemm.py's real-shape
sweeps)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

pytestmark = pytest.mark.onchip


def test_kernel_families_check():
    """Every kernel family compiles + executes (scripts/check_on_chip.py
    as a gate: 28 checks incl. parity streams, megakernel task set, MoE,
    torus degenerates)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_on_chip",
        __file__.replace("tests_onchip/test_capacity.py",
                         "scripts/check_on_chip.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.main() == 0


def test_gemm_vmem_edge_tiles():
    """The documented cross-window-best GEMM config (1024, 1024, 512) at
    the north-star shape sits at the measured VMEM edge — it must keep
    compiling (docs/gemm_core.md pins it; a Mosaic regression here would
    silently fall back and cost ~10%)."""
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((2048, 5120)) * 0.05, jnp.bfloat16)
    b = jnp.asarray(rng.standard_normal((5120, 5120)) * 0.05, jnp.bfloat16)
    out = pallas_matmul(a, b, tile_m=1024, tile_n=1024, tile_k=512)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_flash_attention_s32k():
    """S=32k flash prefill at the swept-best 1024x1024 tiles — the
    long-context capacity claim (bf16, 8 q heads / 1 kv, d=128)."""
    from triton_distributed_tpu.ops.flash_attention import flash_attention

    rng = np.random.default_rng(1)
    S = 32768
    q = jnp.asarray(rng.standard_normal((1, S, 8, 128)) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.standard_normal((1, S, 1, 128)) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.standard_normal((1, S, 1, 128)) * 0.3, jnp.bfloat16)
    out = flash_attention(q, k, v, causal=True)
    assert np.isfinite(np.asarray(out[:, -64:], np.float32)).all()


def test_paged_attention_real_pool():
    """Paged decode over a REAL-sized shared pool (512 pages x 128 rows =
    64k cached positions) — the serving capacity shape, not the toy-pool
    interpret tests."""
    from triton_distributed_tpu.ops.paged_attention import (
        init_paged_kv_cache, paged_append, paged_decode_attention,
    )

    rng = np.random.default_rng(2)
    B, hkv, hq, d, P_, n_pages = 4, 2, 8, 128, 128, 512
    cache = init_paged_kv_cache(B, num_pages=n_pages, page_size=P_,
                                num_kv_heads=hkv, head_dim=d,
                                max_pages=64, dtype=jnp.bfloat16)
    cache = cache._replace(
        kv_lens=jnp.asarray([700, 1, 4000, 2500], jnp.int32))
    k1 = jnp.asarray(rng.standard_normal((B, hkv, d)) * 0.3, jnp.bfloat16)
    v1 = jnp.asarray(rng.standard_normal((B, hkv, d)) * 0.3, jnp.bfloat16)
    cache = paged_append(cache, k1, v1)
    q = jnp.asarray(rng.standard_normal((B, hq, d)) * 0.3, jnp.bfloat16)
    out = paged_decode_attention(q, cache)
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_moe_capacity_overflow_reporting():
    """EP A2A dispatch at the capacity edge: overflow must be REPORTED
    (not silently dropped) on the real chip exactly as the interpret
    suite asserts."""
    from triton_distributed_tpu.ops.all_to_all import dispatch_layout

    rng2 = np.random.default_rng(9)
    tokens = jnp.asarray(rng2.standard_normal((16, 64)), jnp.float32)
    expert_ids = jnp.zeros((16,), jnp.int32)       # all -> expert 0
    layout = dispatch_layout(tokens, expert_ids, num_experts=4,
                             num_ranks=1, cap=8)
    assert int(np.asarray(layout.overflow).sum()) > 0


def test_megakernel_decode_qwen3_shard_shapes():
    """The bench's Qwen3-8B TP=8 shard decode program (hidden=4096,
    S=1024, bf16) compiles and steps on-chip — the flagship claim's
    compile gate at the REAL shape (bench only gates it when timing)."""
    from triton_distributed_tpu.megakernel.models import (
        build_decode_step, rope_tables,
    )
    from triton_distributed_tpu.megakernel.tasks import TILE, MatHandle

    rng = np.random.default_rng(3)
    prog = build_decode_step(hidden=4096, hq_local=4, hkv_local=1,
                             ffn_local=1536, num_layers=1, max_seq=1024,
                             pos=1023, num_ranks=1)
    compiled = prog.mb.compile(dtype=jnp.bfloat16)
    feeds = {prog.x: rng.standard_normal((TILE, 4096)) * 0.1}
    cos, sin = rope_tables(1023, TILE, 1e6)
    feeds[prog.cos], feeds[prog.sin] = cos, sin
    h = prog.layers[0]
    import dataclasses

    for f in dataclasses.fields(h):
        hh = getattr(h, f.name)
        if hh is None or f.name.startswith("moe"):
            continue
        if isinstance(hh, list):
            for t in hh:
                feeds[t] = rng.standard_normal((t.rows, t.cols)) * 0.05
        elif isinstance(hh, MatHandle):
            feeds[hh] = (tuple(rng.standard_normal((hh.k, hh.n)) * 0.05
                               for _ in range(2)) if hh.pair
                         else rng.standard_normal((hh.k, hh.n)) * 0.05)
        else:
            feeds[hh] = rng.standard_normal((hh.rows, hh.cols)) * 0.05
    feeds = {k: (tuple(jnp.asarray(np.asarray(x, np.float32)) for x in v)
                 if isinstance(v, tuple)
                 else jnp.asarray(np.asarray(v, np.float32)))
             for k, v in feeds.items()}
    (out,) = compiled.run(feeds, outputs=[prog.x_out])
    assert np.isfinite(np.asarray(out, np.float32)).all()
