"""REAL-TPU capacity gates (`pytest tests_onchip -m onchip`).

Unlike tests/conftest.py this does NOT force the CPU backend: every test
here is a red/green gate for a "compiles and runs on real TPU" claim in
COVERAGE.md (round-4 VERDICT #8: those claims lived in scripts outside
the suite). Off-TPU the whole directory skips.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "onchip: real-TPU capacity/compile gates")


def pytest_collection_modifyitems(config, items):
    if jax.default_backend() == "tpu":
        return
    skip = pytest.mark.skip(reason="no real TPU backend")
    for item in items:
        item.add_marker(skip)
