"""REAL-TPU perf floors (ISSUE 4): hardware regressions can't ship
silently. Floor values live in ``obs/gate.py ON_CHIP_FLOORS`` (~2x slack
off the measured trajectory — these catch half clocks / broken MXU paths /
interpret-grade fallbacks, not window noise); the measurement functions
are shared with ``scripts/check_on_chip.py``'s floors section so the
script and the suite can never enforce different numbers.
"""

import importlib.util
import os
import sys

import pytest

pytestmark = pytest.mark.onchip

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _check_on_chip():
    spec = importlib.util.spec_from_file_location(
        "check_on_chip", os.path.join(_ROOT, "scripts", "check_on_chip.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules.setdefault("check_on_chip", mod)
    spec.loader.exec_module(mod)
    return mod


def test_floor_values_are_sane():
    from triton_distributed_tpu.obs.gate import ON_CHIP_FLOORS

    assert set(ON_CHIP_FLOORS) == {"gemm_tflops_min",
                                   "flash32k_prefill_ms_max",
                                   "megakernel_vs_jit_max"}
    assert all(v > 0 for v in ON_CHIP_FLOORS.values())


def test_gemm_tflops_floor():
    mod = _check_on_chip()
    tflops = mod.floor_gemm_tflops()     # raises FloorError on violation
    assert tflops > 0


def test_flash32k_prefill_ceiling():
    mod = _check_on_chip()
    ms = mod.floor_flash32k_ms()
    assert ms > 0


@pytest.mark.slow
def test_megakernel_vs_jit_ceiling():
    """Slow: compiles two 36-layer programs (the bench's own full-model
    rungs). Run explicitly: pytest tests_onchip -m 'onchip and slow'."""
    mod = _check_on_chip()
    ratio = mod.floor_megakernel_vs_jit()
    assert ratio > 0
