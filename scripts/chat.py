#!/usr/bin/env python
"""Chat client for scripts/model_server.py (reference chat.py analog).

  python scripts/chat.py --port 8400            # REPL (text if server has a
                                                #  tokenizer, else token ids)
  python scripts/chat.py --ids 1 2 3 --gen 8    # one-shot with raw ids
"""

import argparse
import json
import urllib.request


def post(port, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/generate",
        data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--port", type=int, default=8400)
    p.add_argument("--gen", type=int, default=16)
    p.add_argument("--ids", type=int, nargs="+", default=None)
    args = p.parse_args()

    if args.ids:
        print(post(args.port, {"input_ids": [args.ids],
                               "gen_len": args.gen}))
        return

    print("interactive mode — type a prompt (or ids: 1 2 3), ctrl-D to exit")
    while True:
        try:
            line = input("> ").strip()
        except EOFError:
            break
        if not line:
            continue
        try:
            toks = [int(t) for t in line.split()]
            payload = {"input_ids": [toks], "gen_len": args.gen}
        except ValueError:
            payload = {"prompt": line, "gen_len": args.gen}
        resp = post(args.port, payload)
        print(resp.get("text", resp.get("output_ids", resp)))


if __name__ == "__main__":
    main()
