"""On-chip evidence for the grouped-GEMM choice (r4 VERDICT weak #8).

``ops/moe.grouped_mlp`` rides ``jax.lax.ragged_dot`` where the reference
ships a hand-tuned grouped GEMM (moe_reduce_rs.py:167). This experiment
measures, at Qwen3-MoE per-device expert shapes, whether XLA's ragged_dot
is actually leaving performance on the table:

  ragged    — jax.lax.ragged_dot (the grouped_mlp path)
  dense     — ONE dense (m, k) @ (k, n) dot of the same total FLOPs
              (upper bound: what a perfect grouped kernel could approach
              if group switching were free)
  unrolled  — per-expert dynamic-slice + dense dot loop (the naive
              alternative a custom kernel must beat)

Chain-differential timing (bench.py method).

    TDTPU_BENCH_ON_TPU=1 python scripts/exp_ragged_dot.py
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmark"))

from _common import bootstrap, gated_differential  # noqa: E402

jax, ON_TPU = bootstrap(n_devices=1)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

if ON_TPU:
    # Qwen3-30B-A3B EP=8 decode-ish: 16 local experts, hidden 2048,
    # moe_intermediate 768; m = tokens*topk landing on this device.
    CASES = [("decode-ish m=1024", 1024, 2048, 768, 16),
             ("prefill-ish m=8192", 8192, 2048, 768, 16),
             ("fat experts m=4096", 4096, 4096, 1536, 8)]
    LENGTHS = (8, 48, 88)
else:
    CASES = [("smoke", 64, 128, 64, 4)]
    LENGTHS = (1, 2, 3)


def measure(fn, a, w, gs, lengths, trials=5):
    @functools.partial(jax.jit, static_argnums=3)
    def chain(a, w, gs, n, salt):
        def body(i, x):
            o = fn(x, w, gs)
            # fold the WHOLE output back in: a partial fold (o[0, :1])
            # let XLA dead-code-eliminate every group but the first
            # (observed: "5470 TFLOP/s" from the unrolled lane)
            return x + jnp.sum(o).astype(x.dtype) * 1e-9

        return jax.lax.fori_loop(0, n, body, a + salt)

    t = {n: float("inf") for n in lengths}
    for n in lengths:
        jax.block_until_ready(chain(a, w, gs, n, jnp.bfloat16(0)))
    s = [0]
    for _ in range(trials):
        for n in lengths:
            s[0] += 1
            t0 = time.perf_counter()
            _ = np.asarray(jnp.sum(chain(a, w, gs, n,
                                         jnp.bfloat16(s[0] * 1e-6))))
            t[n] = min(t[n], time.perf_counter() - t0)
    return gated_differential(t, lengths)


def main():
    rng = np.random.default_rng(0)
    for name, m, k, n, G in CASES:
        a = jnp.asarray(rng.standard_normal((m, k)) * 0.05, jnp.bfloat16)
        w = jnp.asarray(rng.standard_normal((G, k, n)) * 0.05, jnp.bfloat16)
        # equal group sizes (the padded-capacity layout grouped_mlp feeds)
        gs = jnp.full((G,), m // G, jnp.int32)
        wd = jnp.asarray(rng.standard_normal((k, n)) * 0.05, jnp.bfloat16)

        def ragged(x, w, gs):
            return jax.lax.ragged_dot(x, w, gs)

        def dense(x, w, gs, wd=wd):
            return x @ wd

        def unrolled(x, w, gs, m=m, G=G):
            rows = m // G
            outs = [jax.lax.dynamic_slice(x, (g * rows, 0), (rows, x.shape[1])
                                          ) @ w[g] for g in range(G)]
            return jnp.concatenate(outs, axis=0)

        # Lane-equivalence guard: the DCE incident below proved a lane
        # can silently compute a subset; ragged and unrolled must agree
        # exactly (equal group sizes) before any timing is trusted.
        assert bool(jnp.allclose(ragged(a, w, gs).astype(jnp.float32),
                                 unrolled(a, w, gs).astype(jnp.float32),
                                 atol=1e-2)), "lane mismatch"
        flops = 2.0 * m * k * n
        print(f"# {name}: ({m},{k}) x {G}x({k},{n}) bf16, "
              f"{flops/1e9:.1f} GFLOP")
        for label, fn in (("ragged_dot", ragged), ("dense-bound", dense),
                          ("unrolled", unrolled)):
            per, ok = measure(fn, a, w, gs, LENGTHS)
            tf = flops / per / 1e12
            flag = "" if ok else "  [INCONSISTENT]"
            print(f"  {label:12} {per*1e6:9.1f} us/iter "
                  f"{tf:7.1f} TFLOP/s{flag}")


if __name__ == "__main__":
    main()
