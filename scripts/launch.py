#!/usr/bin/env python
"""Multi-host launcher (reference scripts/launch.sh analog).

The reference wraps torchrun: it exports NVSHMEM bootstrap env, picks
nproc-per-node, and execs the test script on every rank
(launch.sh:146-180). On TPU pods the platform plays torchrun's role — each
host runs the same program and ``jax.distributed.initialize()`` discovers
peers from the TPU metadata — so the launcher reduces to:

  python scripts/launch.py my_script.py [args...]

which initializes the distributed runtime (env-driven overrides below),
then runs the script with the global mesh available. Environment:

  TDTPU_COORDINATOR   host:port of process 0 (non-TPU/manual bootstrap)
  TDTPU_NUM_PROCESSES total process count   (with TDTPU_COORDINATOR)
  TDTPU_PROCESS_ID    this process's id     (with TDTPU_COORDINATOR)

On a TPU pod slice none are needed. The reference's compute-sanitizer hook
maps to TDTPU_DETECT_RACES=1 (interpret-mode race detection, off-TPU).
"""

import os
import runpy
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def maybe_init_distributed():
    import jax

    coord = os.environ.get("TDTPU_COORDINATOR")
    if coord:
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=int(os.environ["TDTPU_NUM_PROCESSES"]),
            process_id=int(os.environ["TDTPU_PROCESS_ID"]))
        return
    # TPU pod: metadata-driven bootstrap; a single host needs nothing.
    try:
        if jax.default_backend() == "tpu" and jax.process_count() == 1:
            # single-process slice — initialize() would be a no-op or error
            return
        jax.distributed.initialize()
    except Exception:
        pass  # single-process run


def main():
    if len(sys.argv) < 2:
        print(__doc__)
        sys.exit(2)
    script, sys.argv = sys.argv[1], sys.argv[1:]
    maybe_init_distributed()
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    main()
