#!/usr/bin/env python
"""GEMM kernel-variant experiment (round 4, VERDICT #1).

Compares Pallas matmul structures against XLA's dot at the north-star shape
(M=2048, K=N=5120 bf16) with the chain-differential + interleaved + min-of-
passes methodology (the only trustworthy one on this shared chip — see
bench.py header). Also times a trivial pallas kernel to bound the fixed
Mosaic dispatch overhead per call.

Usage: python scripts/exp_gemm_variants.py [--lengths 8 40] [--trials 3]
"""

import argparse
import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from triton_distributed_tpu.language.core import any_spec, kernel_call


def grid_matmul(a, b, tm, tn, tk):
    """The PRODUCTION grid-form kernel (ops/gemm.py pallas_matmul) at an
    explicit tile config — the experiment must time the real code path,
    not a local copy that could drift."""
    from triton_distributed_tpu.ops.gemm import pallas_matmul

    return pallas_matmul(a, b, tile_m=tm, tile_n=tn, tile_k=tk)


def ep_matmul(a, b, tm, tn, tk, semantics=False):
    """Current repo structure: one ANY-space kernel + emit_pipeline, with
    optional parallel dimension semantics."""
    m, k = a.shape
    _, n = b.shape
    nk = k // tk

    def kernel(a_ref, b_ref, o_ref, acc):
        def body(a_v, b_v, o_v, acc_ref):
            kk = pl.program_id(2)
            part = jnp.dot(a_v[...], b_v[...],
                           preferred_element_type=jnp.float32)

            @pl.when(kk == 0)
            def _():
                acc_ref[...] = part

            @pl.when(kk != 0)
            def _():
                acc_ref[...] += part

            @pl.when(kk == nk - 1)
            def _():
                o_v[...] = acc_ref[...].astype(o_v.dtype)

        kw = {}
        if semantics:
            kw["dimension_semantics"] = (pltpu.PARALLEL, pltpu.PARALLEL,
                                         pltpu.ARBITRARY)
        pltpu.emit_pipeline(
            body,
            grid=(m // tm, n // tn, nk),
            in_specs=[
                pl.BlockSpec((tm, tk), lambda i, j, q: (i, q)),
                pl.BlockSpec((tk, tn), lambda i, j, q: (q, j)),
            ],
            out_specs=[pl.BlockSpec((tm, tn), lambda i, j, q: (i, j))],
            **kw,
        )(a_ref, b_ref, o_ref, scratches=[acc])

    return kernel_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        in_specs=[any_spec(), any_spec()],
        out_specs=any_spec(),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
        cost_estimate=pl.CostEstimate(
            flops=2 * m * k * n,
            bytes_accessed=(m * k + k * n + m * n) * a.dtype.itemsize,
            transcendentals=0),
    )(a, b)


def tiny_copy(x):
    """Trivial pallas kernel: bounds the fixed per-call Mosaic overhead."""
    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] + 1.0

    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


def _chain(matmul, a, b, n):
    def body(i, x):
        return matmul(x, b)

    out = jax.lax.fori_loop(0, n, body, a)
    return jnp.sum(out.astype(jnp.float32))


def _timed_once(fn, a, b, n):
    t0 = time.perf_counter()
    out = fn(a, b, n)
    _ = np.asarray(out)
    return time.perf_counter() - t0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lengths", type=int, nargs=2, default=[8, 40])
    ap.add_argument("--trials", type=int, default=3)
    args = ap.parse_args()

    assert jax.default_backend() == "tpu", "experiment needs the real chip"
    M, K = 2048, 5120
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((M, K)) * 0.05, jnp.bfloat16)
    # near-orthogonal B (kron of orthogonals) so the chain stays bounded
    q1 = np.linalg.qr(rng.standard_normal((64, 64)))[0]
    q2 = np.linalg.qr(rng.standard_normal((K // 64, K // 64)))[0]
    b = jnp.asarray(np.kron(q1, q2), jnp.bfloat16)

    variants = {
        "xla": lambda x, w: jnp.dot(
            x, w, preferred_element_type=jnp.float32).astype(x.dtype),
        "ep_cur_512_1024_1024": functools.partial(
            ep_matmul, tm=512, tn=1024, tk=1024),
        "ep_sem_512_1024_1024": functools.partial(
            ep_matmul, tm=512, tn=1024, tk=1024, semantics=True),
        "grid_512_1024_1024": functools.partial(
            grid_matmul, tm=512, tn=1024, tk=1024),
        "grid_1024_1024_512": functools.partial(
            grid_matmul, tm=1024, tn=1024, tk=512),
        "grid_512_1024_2560": functools.partial(
            grid_matmul, tm=512, tn=1024, tk=2560),
    }

    fns = {name: jax.jit(functools.partial(_chain, fn), static_argnums=2)
           for name, fn in variants.items()}

    n1, n2 = args.lengths
    flops = 2.0 * M * K * K

    # warmup/compile
    for name, fn in fns.items():
        t0 = time.perf_counter()
        try:
            _timed_once(fn, a, b, n1)
            print(f"compiled {name} in {time.perf_counter()-t0:.1f}s",
                  flush=True)
        except Exception as e:
            print(f"COMPILE FAIL {name}: {str(e)[:200]}", flush=True)
            fns[name] = None
    fns = {k: v for k, v in fns.items() if v is not None}

    best = {(name, n): float("inf") for name in fns for n in (n1, n2)}
    for _pass in range(2):
        for _t in range(args.trials):
            for name, fn in fns.items():
                for n in (n1, n2):
                    best[(name, n)] = min(best[(name, n)],
                                          _timed_once(fn, a, b, n))
        if _pass == 0:
            time.sleep(3)

    print(f"\nshape M={M} K=N={K} bf16, lengths {n1}/{n2}, "
          f"min over 2x{args.trials} interleaved trials")
    t_xla = None
    for name in fns:
        per = (best[(name, n2)] - best[(name, n1)]) / (n2 - n1)
        tf = flops / per / 1e12
        if name == "xla":
            t_xla = per
        ratio = (t_xla / per) if t_xla else float("nan")
        print(f"  {name:28s} {per*1e3:8.3f} ms/iter  {tf:7.1f} TF/s  "
              f"vs_xla={ratio:.4f}")

    # fixed-overhead probe: chain of tiny pallas calls
    xs = jnp.zeros((8, 128), jnp.float32)

    def tiny_chain(x, _unused, n):
        return jnp.sum(jax.lax.fori_loop(0, n, lambda i, v: tiny_copy(v), x))

    tfn = jax.jit(tiny_chain, static_argnums=2)
    _timed_once(tfn, xs, None, 8)
    tb = {n: float("inf") for n in (64, 256)}
    for _ in range(4):
        for n in (64, 256):
            tb[n] = min(tb[n], _timed_once(tfn, xs, None, n))
    per = (tb[256] - tb[64]) / (256 - 64)
    print(f"\ntiny pallas call fixed overhead: {per*1e6:.1f} us/call")


if __name__ == "__main__":
    main()
