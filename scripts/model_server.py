#!/usr/bin/env python
"""Minimal model server over the Engine.

Reference analog: ``mega_triton_kernel/test/model_server.py`` (a socket
server replaying the persistent kernel per request) + ``chat.py`` client.

Serves HTTP (stdlib only):
  POST /generate   {"input_ids": [[...]], "gen_len": N} |
                   {"prompt": "...", "gen_len": N}   (needs --tokenizer)
  GET  /health     config + mesh info

Run (no TPU needed — tiny random model on the virtual CPU mesh):
  python scripts/model_server.py --demo
Real checkpoint on a TPU slice:
  python scripts/model_server.py --checkpoint /path/to/qwen3 --tokenizer /path/to/qwen3
"""

import argparse
import json
import os
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_engine(args):
    if args.demo:
        os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                   + " --xla_force_host_platform_device_count=8")
    import jax

    if args.demo:
        jax.config.update("jax_platforms", "cpu")

    from triton_distributed_tpu.models import AutoLLM
    from triton_distributed_tpu.models.config import tiny_config
    from triton_distributed_tpu.runtime import initialize_distributed

    n = len(jax.devices())
    ctx = initialize_distributed(mesh_shape=(n,), axis_names=("tp",))
    kw = dict(backend=args.backend, max_seq=args.max_seq,
              page_size=args.page_size)
    if args.checkpoint:
        eng = AutoLLM.from_pretrained(args.checkpoint, ctx=ctx, **kw)
    else:
        eng = AutoLLM.from_config(tiny_config(), ctx=ctx, **kw)
    tok = None
    if args.tokenizer:
        from triton_distributed_tpu.models.auto import auto_tokenizer

        tok = auto_tokenizer(args.tokenizer)
    return eng, tok


def make_handler(eng, tok):
    # ThreadingHTTPServer handles requests concurrently, but Engine.serve
    # mutates the shared _jit_cache and interleaves device computation —
    # serialize generation (sufficient for this demo server).
    gen_lock = threading.Lock()
    import jax.numpy as jnp
    import numpy as np

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code, obj):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):  # quiet
            pass

        def do_GET(self):
            if self.path == "/health":
                self._send(200, {
                    "status": "ok",
                    "model": {"hidden": eng.cfg.hidden_size,
                              "layers": eng.cfg.num_layers,
                              "moe": eng.cfg.is_moe},
                    "tp": eng.n, "backend": eng.backend})
            else:
                self._send(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/generate":
                return self._send(404, {"error": "unknown path"})
            try:
                req = json.loads(self.rfile.read(
                    int(self.headers.get("Content-Length", 0))))
                gen_len = int(req.get("gen_len", 16))
                if "prompt" in req:
                    if tok is None:
                        return self._send(400, {
                            "error": "no tokenizer; pass input_ids"})
                    ids = np.asarray([tok.encode(req["prompt"])], np.int32)
                else:
                    ids = np.asarray(req["input_ids"], np.int32)
                with gen_lock:
                    out = eng.serve(jnp.asarray(ids), gen_len=gen_len)
                out_ids = np.asarray(out).tolist()
                resp = {"output_ids": out_ids}
                if tok is not None:
                    resp["text"] = [tok.decode(o) for o in out_ids]
                self._send(200, resp)
            except Exception as e:  # report, don't crash the server
                self._send(500, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--checkpoint", default=None,
                   help="local HF checkpoint dir (default: tiny random model)")
    p.add_argument("--tokenizer", default=None)
    p.add_argument("--backend", default="auto",
                   choices=["auto", "xla", "overlap", "megakernel"],
                   help="megakernel = persistent-kernel decode "
                        "(one pallas_call per token; TP=1, head_dim=128)")
    p.add_argument("--max-seq", type=int, default=512)
    p.add_argument("--page-size", type=int, default=None,
                   help="serve with the paged KV cache (continuous batching)")
    p.add_argument("--port", type=int, default=8400)
    p.add_argument("--demo", action="store_true",
                   help="force the 8-device virtual CPU mesh")
    args = p.parse_args()

    eng, tok = build_engine(args)
    srv = ThreadingHTTPServer(("127.0.0.1", args.port),
                              make_handler(eng, tok))
    print(f"serving on http://127.0.0.1:{args.port} "
          f"(tp={eng.n}, backend={eng.backend})", flush=True)
    srv.serve_forever()


if __name__ == "__main__":
    main()
