"""Per-task-type megakernel profile — attribute the decode-step residual.

Round-4 VERDICT #1: the megakernel trails jit ~5.6-6.1x with the ~0.35 ms
residual un-attributed ("task-body serialized load/store round-trips" was
a hypothesis, not a measurement). This script measures each task TYPE at
its Qwen3-8B TP=8 decode shape by building a queue of L identical tasks
and timing R replays of the whole launch at three R values — the same
chain-differential discipline as benchmark/bench_megakernel.py (the only
method that survives the shared chip's dispatch swing).

Per-task cost = d(total)/dR / L. The layer total predicted from the
per-type costs × the real 27-task layer composition is printed against the
measured layer step, so the attribution can be checked for completeness.

    python scripts/mk_profile.py              # CPU smoke (tiny shapes)
    TDTPU_BENCH_ON_TPU=1 python scripts/mk_profile.py
    python scripts/mk_profile.py --json costs.json   # measured per-type
        # costs in the obs.kernel_profile.attach_durations(measured=...)
        # form — feed them to KernelProfile for measured (not est:) lanes
    python scripts/mk_profile.py --full-model [--json out.json]
        # round-6 FULL-MODEL attribution: build the whole num_layers
        # decode queue (the bench rung's program), decode its per-task
        # composition, attach measured/estimated per-type costs, and
        # account the measured step into per-class lanes + the host
        # embed/logits slice + the unattributed/stall residual — where
        # the extra milliseconds beyond layer-scale live.
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmark"))

from _common import bootstrap  # noqa: E402

jax, ON_TPU = bootstrap(n_devices=1)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from triton_distributed_tpu.megakernel.builder import MegaKernelBuilder  # noqa: E402
from triton_distributed_tpu.megakernel.models import rope_tables  # noqa: E402
from triton_distributed_tpu.megakernel.tasks import TILE, MatHandle  # noqa: E402


def time_replays(compiled, ws0, wsm0, lengths, trials=5):
    """min-of-trials wall time of R queue replays, per R in lengths."""

    @functools.partial(jax.jit, static_argnums=2)
    def chain(ws, wsm, r, salt):
        return jax.lax.fori_loop(
            0, r, lambda i, w_: compiled.step(w_, wsm=wsm),
            ws + salt.astype(ws.dtype))

    t = {r: float("inf") for r in lengths}
    salt = [0]

    def once(r):
        salt[0] += 1
        t0 = time.perf_counter()
        out = chain(ws0, wsm0, r, jnp.float32(salt[0] * 1e-6))
        _ = np.asarray(jnp.sum(out))
        return time.perf_counter() - t0

    for r in lengths:
        once(r)           # compile + warm
    for _ in range(trials):
        for r in lengths:
            t[r] = min(t[r], once(r))
    return t


def per_task_seconds(compiled, ws0, wsm0, n_tasks, lengths):
    t = time_replays(compiled, ws0, wsm0, lengths)
    r1, r2, r3 = lengths
    t1, t2, t3 = t[r1], t[r2], t[r3]
    if not (t3 > t2 > t1):
        return None, f"non-monotone {t1:.4f}/{t2:.4f}/{t3:.4f}"
    d21 = (t2 - t1) / (r2 - r1)
    d32 = (t3 - t2) / (r3 - r2)
    if not (0.33 < d21 / max(d32, 1e-12) < 3.0):
        return None, f"inconsistent {d21:.3e} vs {d32:.3e}"
    return (t3 - t1) / (r3 - r1) / n_tasks, None


def build_case(name, emit, L, feeds_fn, dtype):
    """Build a queue of L identical tasks; emit(mb, handles) appends one."""
    mb = MegaKernelBuilder()
    handles = feeds_fn(mb)
    for _ in range(L):
        emit(mb, handles)
    compiled = mb.compile(dtype=dtype)
    rng = np.random.default_rng(0)
    feeds = {}
    for h in handles.values():
        if isinstance(h, list):
            for hh in h:
                feeds[hh] = rng.standard_normal(
                    (hh.rows, hh.cols)).astype(np.float32) * 0.05
        elif isinstance(h, MatHandle):
            mk = lambda: rng.standard_normal(
                (h.k, h.n)).astype(np.float32) * 0.05
            feeds[h] = (mk(), mk()) if h.pair else mk()
        else:
            feeds[h] = rng.standard_normal(
                (h.rows, h.cols)).astype(np.float32) * 0.05
    main, _w8, wm = compiled.split_feeds(feeds)
    ws = compiled.make_workspace(
        {k: jnp.asarray(v) for k, v in main.items()})
    wsm = compiled.make_workspace_mat(wm) if wm else None
    return compiled, ws, wsm


def _full_model_program(dtype, batch=1, head_dim=TILE):
    """The bench rung's full-model program (TPU: bench.py's OWN builder,
    so the attribution measures exactly the program the rung ships) or
    the CPU-smoke miniature — returns (prog, comp, ws, wsm, embed,
    shapes); ``embed`` is None off-TPU (the smoke path never times the
    whole-model chain). ``batch``/``head_dim`` (CPU smoke only, round
    9): exercise the row-blocked batch > TILE emission and the
    padded-head head_dim-64 layout — CI runs the attribution on a
    batch=2·TILE, head_dim-64 queue."""
    from triton_distributed_tpu.megakernel.models import (
        broadcast_rows, build_decode_step, feed_layer_weights,
        pad_head_vec, rope_tables,
    )

    if ON_TPU:
        import bench

        prog, comp, ws, wsm, embed, hidden = bench._build_mega_program()
        return prog, comp, ws, wsm, embed, (hidden, 4, 1, 1536, 36, 512)
    hidden, hq, hkv, ffn, L, S, pos = 256, 2, 1, 256, 2, 256, 100
    hd = head_dim
    d = TILE
    bt = -(-batch // TILE)
    rng = np.random.default_rng(0)
    prog = build_decode_step(hidden=hidden, hq_local=hq, hkv_local=hkv,
                             ffn_local=ffn, num_layers=L, max_seq=S,
                             pos=pos, num_ranks=1, final_norm=True,
                             batch=batch, head_dim=hd,
                             mat_prefetch=True)
    comp = prog.mb.compile(dtype=dtype, head_dim=hd)
    cos, sin = rope_tables(pos, hd, 1e6)
    feeds = {prog.cos: cos, prog.sin: sin,
             prog.x: rng.standard_normal(
                 (bt * TILE, hidden)).astype(np.float32) * 0.05,
             prog.fnorm: broadcast_rows(np.ones(hidden, np.float32))}
    for h in prog.layers:
        for nh, width in ((h.attn_norm, hidden), (h.mlp_norm, hidden)):
            feeds[nh] = broadcast_rows(
                rng.standard_normal(width).astype(np.float32) * .1 + 1)
        for nh in (h.q_norm, h.k_norm):
            feeds[nh] = broadcast_rows(pad_head_vec(
                rng.standard_normal(hd).astype(np.float32) * .1 + 1, hd))
        feed_layer_weights(
            feeds, h, head_dim=hd,
            wq=rng.standard_normal((hidden, hq * hd)).astype(np.float32) * .02,
            wk=rng.standard_normal((hidden, hkv * hd)).astype(np.float32) * .02,
            wv=rng.standard_normal((hidden, hkv * hd)).astype(np.float32) * .02,
            wo=rng.standard_normal((hq * hd, hidden)).astype(np.float32) * .02,
            w_gate=rng.standard_normal((hidden, ffn)).astype(np.float32) * .02,
            w_up=rng.standard_normal((hidden, ffn)).astype(np.float32) * .02,
            w_down=rng.standard_normal((ffn, hidden)).astype(np.float32) * .02)
        for tk, tv in zip(h.kT, h.v):
            kc = np.zeros((d, S), np.float32)
            kc[:hd] = rng.standard_normal((hd, S)).astype(np.float32) * .3
            vc = np.zeros((S, d), np.float32)
            vc[:, :hd] = rng.standard_normal((S, hd)).astype(np.float32) * .3
            feeds[tk] = kc
            feeds[tv] = vc
    main_f, _w8, mat_f = comp.split_feeds(feeds)
    ws = comp.make_workspace(
        {k: jnp.asarray(v) for k, v in main_f.items()})
    wsm = comp.make_workspace_mat(mat_f)
    return prog, comp, ws, wsm, None, (hidden, hq, hkv, ffn, L, S)


def full_model_fp8kv_main(json_out):
    """Round-12 fp8-KV attribution smoke: build the PAGED serving-form
    program with ``kv_fp8=True`` (the fp8 pool workspace), classify the
    queue — the F8 task variants must attribute cleanly (no
    unclassified lanes) — and on CPU run one profiled interpret-mode
    step checking the stamped dump against the queue-derived plan."""
    import collections
    import json

    import jax.random as jrandom

    from triton_distributed_tpu.megakernel.serving import (
        PagedMegakernelDecoder,
    )
    from triton_distributed_tpu.models.config import ModelConfig
    from triton_distributed_tpu.models.dense import init_dense_llm
    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile, attach_durations, decode_records, records_from_queue,
    )

    cfg = ModelConfig(hidden_size=256, intermediate_size=256, num_layers=2,
                      num_heads=2, num_kv_heads=1, head_dim=128,
                      vocab_size=512, qk_norm=True, dtype="float32")
    params = init_dense_llm(jrandom.PRNGKey(0), cfg)
    dec = PagedMegakernelDecoder(cfg, params, num_slots=2, num_pages=3,
                                 max_pages=2, dtype=jnp.float32,
                                 kv_dtype=jnp.float8_e4m3fn)
    comp = dec.comp
    recs = records_from_queue(comp.queue, comp.num_exec)
    composition = dict(collections.Counter(r.type_name for r in recs))
    for needed in ("ATTN_DECODE_PAGED_F8", "APPEND_KV_F8"):
        assert composition.get(needed, 0) > 0, \
            f"fp8-KV serving queue emitted no {needed} tasks"
    if not ON_TPU:
        ws, wk8 = dec.start()
        queue = dec._retarget(np.zeros(dec.num_slots, np.int64),
                              np.full((dec.num_slots, dec.max_pages), -1))
        ws, wk8, prof = comp.step(ws, queue, wsm=dec._wsm, wkv8=wk8,
                                  profile=True)
        jax.block_until_ready(ws)
        stamped = decode_records(np.asarray(prof))
        assert len(stamped) == len(recs), \
            f"stamped {len(stamped)} records vs queue {len(recs)}"
    attach_durations(recs, itemsize=1)
    kp = KernelProfile(records=recs, label="full_model_fp8kv")
    acct = kp.accounting()
    acct["composition"] = composition
    print(f"# fp8-KV paged serving attribution ({acct['n_tasks']} tasks)")
    for cls, d_ in sorted(acct["classes"].items()):
        print(f"{cls:16} {d_['tasks']:5d} tasks  "
              f"{d_['seconds'] * 1e3:9.3f} ms  [{d_['duration_kind']}]")
    assert acct["unclassified"] == 0, \
        "fp8-KV serving queue contains unclassified task types"
    if json_out is not None:
        with open(json_out, "w") as f:
            json.dump({"full_model_fp8kv": acct}, f, indent=2, default=str)
        print(f"wrote {json_out}")


def full_model_main(json_out, measured=None, batch=1, head_dim=TILE):
    """Round-6 full-model attribution: per-task accounting of the whole
    num_layers decode queue — where the extra milliseconds beyond
    layer-scale live (ISSUE 5 tentpole step 1; round 9 adds --batch /
    --head-dim so CI attributes the generalized queues too)."""
    import collections
    import json

    from triton_distributed_tpu.obs.kernel_profile import (
        KernelProfile, attach_durations, decode_records, records_from_queue,
    )

    dtype = jnp.bfloat16 if ON_TPU else jnp.float32
    prog, comp, ws0, wsm0, embed, shapes = _full_model_program(
        dtype, batch=batch, head_dim=head_dim)
    hidden, hq, hkv, ffn, L, S = shapes
    itemsize = jnp.dtype(dtype).itemsize

    # The queue IS the dispatch plan — composition needs no device run.
    recs = records_from_queue(comp.queue, comp.num_exec)
    composition = dict(collections.Counter(r.type_name for r in recs))

    step_s = host_s = None
    if ON_TPU:
        # Kernel-only step (differential over replay chains — the only
        # method that survives the relay's dispatch swing).
        t = time_replays(comp, ws0, wsm0, (4, 14, 24))
        r1, r2, r3 = sorted(t)
        if t[r3] > t[r2] > t[r1]:
            step_s = (t[r3] - t[r1]) / (r3 - r1)
        # Whole-model step (embed + in-kernel final norm + logits argmax):
        # host_s = whole - kernel-only, the embed/logits lane.
        whole = _whole_model_seconds(comp, prog, ws0, wsm0, embed, hidden)
        if whole is not None and step_s is not None:
            host_s = max(whole - step_s, 0.0)
    else:
        # CPU smoke: one profiled interpret-mode step — the stamped dump
        # must agree with the queue-derived plan (the attribution's own
        # regression check, also gated by tests/test_megakernel_decode).
        ws, prof = comp.step(ws0, wsm=wsm0, profile=True)
        jax.block_until_ready(ws)
        stamped = decode_records(np.asarray(prof))
        assert len(stamped) == len(recs), \
            f"stamped {len(stamped)} records vs queue {len(recs)}"

    attach_durations(recs, itemsize=itemsize, measured=measured)
    kp = KernelProfile(records=recs, measured_step_s=step_s,
                       label="full_model")
    acct = kp.accounting(host_s=host_s)
    acct["composition"] = composition
    acct["shapes"] = {"hidden": hidden, "hq_local": hq, "hkv_local": hkv,
                      "ffn_local": ffn, "num_layers": L, "max_seq": S,
                      "dtype": jnp.dtype(dtype).name}

    print(f"# full-model per-task accounting ({L} layers, "
          f"{acct['n_tasks']} tasks, "
          f"{'TPU' if ON_TPU else 'CPU smoke — est: lanes'})")
    for cls, d_ in sorted(acct["classes"].items()):
        print(f"{cls:16} {d_['tasks']:5d} tasks  "
              f"{d_['seconds'] * 1e3:9.3f} ms  [{d_['duration_kind']}]")
    print(f"{'task sum':16} {'':5s}        {acct['task_sum_s'] * 1e3:9.3f} ms")
    if step_s is not None:
        print(f"{'measured step':16} {'':5s}        {step_s * 1e3:9.3f} ms  "
              f"(unattributed/stall "
              f"{acct.get('unattributed_stall_s', 0) * 1e3:.3f} ms)")
    if host_s is not None:
        print(f"{'host embed/logits':16} {'':4s}        "
              f"{host_s * 1e3:9.3f} ms")
    assert acct["unclassified"] == 0, \
        "full-model queue contains unclassified task types"
    if json_out is not None:
        with open(json_out, "w") as f:
            json.dump({"full_model": acct,
                       "per_type_seconds": dict(measured or {})}, f,
                      indent=2, default=str)
        print(f"wrote {json_out}")


def _whole_model_seconds(comp, prog, ws0, wsm0, embed, hidden,
                         gen=(4, 14, 24)):
    """Differential seconds/step of the whole-model chain (embed lookup +
    kernel step + logits argmax) — bench.py's OWN harness
    (_mega_chain_times / _mega_per_step_ms), so the attribution times
    exactly the chain the rung ships, at profile-sized chain lengths."""
    import bench

    best = bench._mega_chain_times(prog, comp, ws0, wsm0, embed, hidden,
                                   gen)
    out = bench._mega_per_step_ms(best, gen, "s")
    return out["s"] / 1e3 if isinstance(out["s"], float) else None


def main():
    # Parse --json BEFORE measuring: a malformed invocation must fail in
    # milliseconds, not after minutes of on-chip profiling.
    json_out = None
    if "--json" in sys.argv:
        i = sys.argv.index("--json")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("-"):
            sys.exit("usage: mk_profile.py [--full-model] [--json OUT_PATH]")
        json_out = sys.argv[i + 1]
    measured = None
    if "--costs" in sys.argv:
        # Per-type costs from a prior `--json costs.json` run: the
        # full-model accounting then renders measured (not est:) lanes.
        import json as _json

        i = sys.argv.index("--costs")
        if i + 1 >= len(sys.argv) or sys.argv[i + 1].startswith("-"):
            sys.exit("usage: mk_profile.py [--full-model] [--costs IN] "
                     "[--json OUT]")
        with open(sys.argv[i + 1]) as f:
            measured = _json.load(f).get("per_type_seconds") or None

    def _int_flag(name, default):
        if name not in sys.argv:
            return default
        i = sys.argv.index(name)
        if i + 1 >= len(sys.argv):
            sys.exit(f"usage: mk_profile.py [--full-model] [{name} N]")
        return int(sys.argv[i + 1])

    if "--full-model" in sys.argv:
        # --fp8-kv (round 12): attribute the PAGED serving-form queue
        # with fp8 KV pools (ATTN_DECODE_PAGED_F8 / APPEND_KV_F8
        # classified, stamped dump checked against the plan on CPU).
        if "--fp8-kv" in sys.argv:
            return full_model_fp8kv_main(json_out)
        # --batch / --head-dim (round 9, CPU smoke): attribute the
        # row-blocked batch>TILE and padded-head head_dim-64 queues.
        return full_model_main(json_out, measured=measured,
                               batch=_int_flag("--batch", 1),
                               head_dim=_int_flag("--head-dim", TILE))
    if ON_TPU:
        hidden, hq, hkv, ffn, S = 4096, 4, 1, 1536, 1024
        # Post-rework tasks run ~3-20 us: the differential needs tens of
        # thousands of task-executions to clear the relay's dispatch
        # swing (first measurement pass came back all-UNRELIABLE at
        # L=48 x 16 replays).
        L = 192
        lengths_heavy = (4, 24, 44)
        lengths_light = (4, 24, 44)
        dtype = jnp.bfloat16
    else:
        hidden, hq, hkv, ffn, S = 512, 2, 1, 256, 256
        L = 4
        lengths_heavy = lengths_light = (1, 2, 3)
        dtype = jnp.float32
    ht, ft = hidden // TILE, ffn // TILE
    d = TILE

    cases = []

    # TaskType dispatched by each case (for the --json per-type export).
    _CASE_TYPE = {
        "qkv_mat": "GEMM_MAT", "gateup_mat": "GEMM_MAT",
        "down_mat": "GEMM_MAT", "o_mat": "GEMM_MAT",
        "down_mat3": "GEMM_MAT", "o_mat3": "GEMM_MAT",
        "gemm": "GEMM_WIDE", "rms_norm": "RMS_NORM", "add": "ADD",
        "add_norm": "ADD_NORM", "norm_rope_qkv": "NORM_ROPE_QKV",
        "silu_mul": "SILU_MUL", "norm_rope": "NORM_ROPE",
        "attn_gqa": "ATTN_DECODE_GQA", "append_kv": "APPEND_KV",
    }

    def add_case(name, count_per_layer, lengths, emit, feeds_fn):
        cases.append((name, count_per_layer, lengths, emit, feeds_fn))

    # -- GEMM_MAT at the layer's four shapes (round-5 matrix path; round 6
    # adds the epilogue-3 +resid+norm forms the fused assembly dispatches)
    def mat_feeds(k, n, pair=False, resid=False, norm=False):
        def f(mb):
            h = {"a": mb.tensor(TILE, k),
                 "w": mb.tensor_mat(k, n, pair=pair),
                 "o": mb.tensor(TILE, n)}
            if resid:
                h["r"] = mb.tensor(TILE, n)
            if norm:
                h["nw"] = mb.tensor(TILE, n)
                h["no"] = mb.tensor(TILE, n)
            return h
        return f

    def mat_emit(mb, h):
        mb.gemm_mat(h["o"], h["a"], h["w"], residual=h.get("r"),
                    norm_w=h.get("nw"), norm_out=h.get("no"))

    qkv_n = (hq + 2 * hkv) * d
    add_case(f"qkv_mat fused ({qkv_n} out)", 1,
             lengths_heavy, mat_emit, mat_feeds(hidden, qkv_n))
    add_case(f"gateup_mat pair+silu ({ffn} act)", 1,
             lengths_heavy, mat_emit, mat_feeds(hidden, ffn, pair=True))
    add_case("down_mat3 +resid+norm (epi3)", 1, lengths_heavy, mat_emit,
             mat_feeds(ffn, hidden, resid=True, norm=True))
    add_case("o_mat3 +resid+norm (epi3)", 1, lengths_heavy, mat_emit,
             mat_feeds(hq * d, hidden, resid=True, norm=True))
    # Legacy epilogue-2 forms (0/layer in the round-6 fused assembly) for
    # before/after comparison of the fused-norm epilogue.
    add_case("down_mat +resid", 0,
             lengths_heavy, mat_emit, mat_feeds(ffn, hidden, resid=True))
    add_case("o_mat +resid", 0,
             lengths_heavy, mat_emit, mat_feeds(hq * d, hidden, resid=True))

    # -- legacy GEMM_WIDE (tile path) for comparison (0/layer in the
    # matrix-path decode assembly) -----------------------------------------
    def gemm_feeds(kt, nt):
        def f(mb):
            return {"a": mb.tensor(TILE, kt * TILE),
                    "b": mb.tensor(kt * TILE, nt * TILE),
                    "o": mb.tensor(TILE, nt * TILE)}
        return f

    def gemm_emit(mb, h):
        mb.gemm(h["o"], h["a"], h["b"])

    add_case(f"gemm k={ht} w=8 legacy (gate-shape)", 0,
             lengths_heavy, gemm_emit, gemm_feeds(ht, ft))

    # -- RMS_NORM / elementwise over the hidden row -------------------------
    def row_feeds(mb):
        return {"a": mb.tensor(TILE, hidden), "b": mb.tensor(TILE, hidden),
                "o": mb.tensor(TILE, hidden)}

    # Round-6 fused assembly: the standalone rms_norm/add pairs are folded
    # into GEMM_MAT epilogue 3 / ADD_NORM — 0/layer here; counts reflect
    # the CURRENT n=1 matrix-path decode queue.
    add_case(f"rms_norm k={ht}", 0, lengths_light,
             lambda mb, h: mb.rms_norm(h["o"], h["a"], h["b"]), row_feeds)
    add_case(f"add k={ht}", 0, lengths_light,
             lambda mb, h: mb.add(h["o"], h["a"], h["b"]), row_feeds)

    def an_feeds(mb):
        return {"a": mb.tensor(TILE, hidden), "b": mb.tensor(TILE, hidden),
                "w": mb.tensor(TILE, hidden), "o": mb.tensor(TILE, hidden),
                "on": mb.tensor(TILE, hidden)}

    # 0/layer at n=1 matrix path (epi-3 covers both fusion sites); 2/layer
    # on the multi-rank path, where an AllReduce sits between GEMM and add.
    add_case(f"add_norm k={ht}", 0, lengths_light,
             lambda mb, h: mb.add_norm(h["o"], h["a"], h["b"], h["w"],
                                       h["on"]), an_feeds)

    def ffn_row_feeds(mb):
        return {"a": mb.tensor(TILE, ffn), "b": mb.tensor(TILE, ffn),
                "o": mb.tensor(TILE, ffn)}

    add_case(f"silu_mul k={ft}", 0, lengths_light,
             lambda mb, h: mb.silu_mul(h["o"], h["a"], h["b"]),
             ffn_row_feeds)

    # -- NORM_ROPE (per q+k head; 0/layer since the round-6 whole-row
    # NORM_ROPE_QKV task) ---------------------------------------------------
    def nr_feeds(mb):
        return {"a": mb.tensor(TILE, TILE), "w": mb.tensor(TILE, TILE),
                "c": mb.tensor(TILE, TILE), "s": mb.tensor(TILE, TILE),
                "o": mb.tensor(TILE, TILE)}

    add_case("norm_rope", 0, lengths_light,
             lambda mb, h: mb.norm_rope(h["o"], h["a"], h["w"], h["c"],
                                        h["s"]), nr_feeds)

    def nrq_feeds(mb):
        qkv = mb.tensor(TILE, (hq + 2 * hkv) * d)
        return {"qkv": qkv, "qn": mb.tensor(TILE, TILE),
                "kn": mb.tensor(TILE, TILE), "c": mb.tensor(TILE, TILE),
                "s": mb.tensor(TILE, TILE)}

    def nrq_emit(mb, h):
        from triton_distributed_tpu.megakernel.tasks import TensorHandle
        q = TensorHandle(h["qkv"].base, TILE, hq * d)
        k = TensorHandle(h["qkv"].base + hq, TILE, hkv * d)
        mb.norm_rope_qkv(q, hq, k, hkv, h["qn"], h["kn"], h["c"], h["s"])

    add_case(f"norm_rope_qkv hq={hq} hkv={hkv}", 1, lengths_light,
             nrq_emit, nrq_feeds)

    # -- ATTN_DECODE_GQA over the full cache --------------------------------
    def attn_feeds(mb):
        return {"q": mb.tensor(TILE, hq * d), "kT": mb.tensor(d, S),
                "v": mb.tensor(S, d), "kn": mb.tensor(TILE, d),
                "vn": mb.tensor(TILE, d), "o": mb.tensor(TILE, hq * d)}

    add_case(f"attn_gqa g={hq} S={S}", hkv, lengths_light,
             lambda mb, h: mb.attn_decode_gqa(
                 h["o"], 0, h["q"], 0, hq, h["kT"], h["v"],
                 valid_len=S - 1, scale=d ** -0.5, k_new=h["kn"],
                 v_new=h["vn"]), attn_feeds)

    # -- APPEND_KV ----------------------------------------------------------
    def app_feeds(mb):
        return {"kT": mb.tensor(d, S), "v": mb.tensor(S, d),
                "kn": mb.tensor(TILE, d), "vn": mb.tensor(TILE, d)}

    # 0/layer in the bench rung (fixed-pos steady state, host append);
    # hkv/layer when serving with inkernel_append=True.
    add_case("append_kv", 0, lengths_light,
             lambda mb, h: mb.append_kv(h["kT"], h["v"], S - 1, h["kn"],
                                        h["vn"]), app_feeds)

    print(f"# per-task profile at hidden={hidden} hq={hq} hkv={hkv} "
          f"ffn={ffn} S={S} dtype={jnp.dtype(dtype).name} L={L} "
          f"({'TPU' if ON_TPU else 'CPU smoke'})")
    total = 0.0
    rows = []
    for name, count, lengths, emit, feeds_fn in cases:
        compiled, ws0, wsm0 = build_case(name, emit, L, feeds_fn, dtype)
        per, err = per_task_seconds(compiled, ws0, wsm0, L, lengths)
        if per is None:
            print(f"{name:36} UNRELIABLE ({err})")
            rows.append((name, count, None))
            continue
        rows.append((name, count, per))
        total += count * per
        print(f"{name:36} {per * 1e6:9.2f} us/task x{count:3d}/layer "
              f"= {count * per * 1e6:9.1f} us")
    print(f"{'PREDICTED layer-step total':36} {total * 1e3:9.3f} ms "
          "(compare bench_megakernel measured step)")

    if json_out is not None:
        # Measured per-TaskType costs in the form
        # obs.kernel_profile.attach_durations(measured=...) consumes
        # (KernelProfile then renders measured, not `est:`, lanes).
        # Multiple cases per type (the four GEMM_MAT shapes) reduce by
        # median — the representative per-task cost, robust to one
        # outlier shape.
        import json

        out_path = json_out
        by_type: dict = {}
        for name, _count, per in rows:
            if per is None:
                continue
            tt = _CASE_TYPE.get(name.split()[0])
            if tt:
                by_type.setdefault(tt, []).append(per)
        per_type = {tt: sorted(v)[len(v) // 2] for tt, v in by_type.items()}
        with open(out_path, "w") as f:
            json.dump({"per_type_seconds": per_type,
                       "cases": [{"case": n, "count_per_layer": c,
                                  "seconds": p} for n, c, p in rows]},
                      f, indent=2)
        print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
