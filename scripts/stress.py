#!/usr/bin/env python
"""Long-running randomized stress sweep (reference test/stress analog:
stress_test_ag_gemm.py sweeps random shapes + stragglers for many
iterations to shake out shape-dependent and race bugs).

    python scripts/stress.py [--iters 50] [--seed 0] [--on-tpu]

Every iteration draws a random op, random (aligned) shapes, a random
straggler rank, runs it on the 8-device virtual CPU mesh (or the real
mesh with --on-tpu), and checks the golden. Exit 0 = all iterations clean.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

N_DEVICES = 8
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + f" --xla_force_host_platform_device_count={N_DEVICES}")


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--iters", type=int, default=30)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--on-tpu", action="store_true")
    args = p.parse_args()

    import jax

    if not args.on_tpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from triton_distributed_tpu.ops import (
        ag_gemm, all_gather, all_reduce, fast_all_to_all, gemm_rs,
        reduce_scatter,
    )
    from triton_distributed_tpu.ops.allgather_gemm import AGGemmConfig
    from triton_distributed_tpu.ops.gemm_reduce_scatter import GemmRSConfig
    from triton_distributed_tpu.runtime import initialize_distributed

    n = min(N_DEVICES, len(jax.devices()))
    ctx = initialize_distributed(devices=jax.devices()[:n],
                                 axis_names=("tp",))
    rng = np.random.default_rng(args.seed)
    fails = 0

    def run_one(i):
        op = rng.choice(["ag_gemm", "gemm_rs", "ag", "rs", "ar", "a2a"])
        straggler = (int(rng.integers(0, n)), 3000) if rng.random() < 0.5 else None
        if op == "ag_gemm":
            m = int(rng.choice([8, 16, 24, 40]))
            k = int(rng.choice([128, 256]))
            cols = int(rng.choice([128, 256]))
            a = jnp.asarray(rng.standard_normal((n * m, k)) * .1, jnp.float32)
            b = jnp.asarray(rng.standard_normal((k, n * cols)) * .1, jnp.float32)
            out = ag_gemm(a, b, ctx, cfg=AGGemmConfig(straggler=straggler))
            ref = np.asarray(a) @ np.asarray(b)
        elif op == "gemm_rs":
            m = int(rng.choice([32, 64])) * n // n * n  # divisible by n
            k = int(rng.choice([16, 32]))
            cols = int(rng.choice([128, 256]))
            a = jnp.asarray(rng.standard_normal((m, n * k)) * .1, jnp.float32)
            b = jnp.asarray(rng.standard_normal((n * k, cols)) * .1, jnp.float32)
            out = gemm_rs(a, b, ctx, cfg=GemmRSConfig(straggler=straggler))
            ref = np.asarray(a) @ np.asarray(b)
        elif op == "ag":
            m = int(rng.choice([8, 16, 32]))
            cols = int(rng.choice([128, 256, 384]))
            x = jnp.asarray(rng.standard_normal((n * m, cols)), jnp.float32)
            out = all_gather(x, ctx)
            ref = np.asarray(x)
        elif op == "rs":
            m = int(rng.choice([8, 16]))
            cols = int(rng.choice([128, 256]))
            x = jnp.asarray(rng.standard_normal((n, n * m, cols)), jnp.float32)
            out = reduce_scatter(x, ctx)
            ref = np.asarray(x).sum(0)
        elif op == "ar":
            m = int(rng.choice([8, 16, 32]))
            cols = int(rng.choice([128, 256]))
            x = jnp.asarray(rng.standard_normal((n, m, cols)), jnp.float32)
            out = all_reduce(x, ctx)
            ref = np.asarray(x).sum(0)
        else:  # a2a
            epr, cap, hidden = 2, 32, 128
            splits = rng.integers(0, cap // n, (n, n, epr)).astype(np.int32)
            send = np.zeros((n, n, cap, hidden), np.float32)
            for d_ in range(n):
                for p_ in range(n):
                    r_ = int(splits[d_, p_].sum())
                    send[d_, p_, :r_] = rng.standard_normal((r_, hidden))
            recv, rsplits = fast_all_to_all(jnp.asarray(send),
                                            jnp.asarray(splits), ctx)
            np.testing.assert_array_equal(np.asarray(rsplits),
                                          np.swapaxes(splits, 0, 1))
            recv = np.asarray(recv)
            for d_ in range(n):
                for p_ in range(n):
                    r_ = int(splits[p_, d_].sum())
                    np.testing.assert_allclose(recv[d_, p_, :r_],
                                               send[p_, d_, :r_])
            return op, None
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-3, atol=1e-3)
        return op, straggler

    for i in range(args.iters):
        try:
            op, straggler = run_one(i)
            print(f"  [{i + 1}/{args.iters}] {op:8} "
                  f"{'straggler=' + str(straggler) if straggler else '':24} OK",
                  flush=True)
        except Exception as e:
            fails += 1
            print(f"  [{i + 1}/{args.iters}] FAIL: {type(e).__name__}: "
                  f"{str(e)[:200]}", flush=True)

    print("parity streams (barrier-free decode collectives):", flush=True)
    parity_failed = False
    try:
        stress_parity_streams(ctx, iters=max(args.iters * 5, 100),
                              seed=args.seed)
    except AssertionError as e:
        parity_failed = True
        print(f"  parity-stream FAIL: {e}", flush=True)

    print(f"\n{args.iters - fails}/{args.iters} iterations clean"
          + ("" if not parity_failed else "; parity-stream phase FAILED"))
    return 1 if (fails or parity_failed) else 0


def stress_parity_streams(ctx, iters: int = 300, seed: int = 0):
    """Randomized stress for the barrier-free parity streams (AR/AG/A2A):
    random shapes per round, rotating stragglers, repeated calls over one
    workspace each — the steady-state decode-loop contract under the
    widest race windows the interpreter can produce."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    from triton_distributed_tpu.ops.allgather import (
        ag_stream_workspace, all_gather_stream,
    )
    from triton_distributed_tpu.ops.allreduce import (
        all_reduce_stream, ar_stream_workspace,
    )
    from triton_distributed_tpu.runtime import shard_map_on

    rng = np.random.default_rng(seed)
    n = ctx.num_ranks
    for case in range(3):
        m = int(rng.choice([8, 16, 24]))
        cols = int(rng.choice([128, 256]))
        base = rng.standard_normal((n, m, cols)).astype(np.float32)

        def run(xl):
            xl = xl[0]
            ws_r, idx_r = ar_stream_workspace(n, m, cols, xl.dtype)
            ws_g, idx_g = ag_stream_workspace(n, m, cols, xl.dtype)
            want_sum = jax.lax.psum(xl, "tp")
            want_cat = jax.lax.all_gather(xl, "tp", tiled=True)

            def body(t, carry):
                ws_r, idx_r, ws_g, idx_g, err = carry
                x_t = xl * (1.0 + t)
                s, ws_r, idx_r = all_reduce_stream(
                    x_t, ws_r, idx_r, axis="tp", num_ranks=n,
                    straggler=("rotate", 512))
                g, ws_g, idx_g = all_gather_stream(
                    x_t, ws_g, idx_g, axis="tp", num_ranks=n,
                    straggler=("rotate", 512))
                err = jnp.maximum(err, jnp.max(jnp.abs(
                    s / (1.0 + t) - want_sum)))
                err = jnp.maximum(err, jnp.max(jnp.abs(
                    g / (1.0 + t) - want_cat)))
                return ws_r, idx_r, ws_g, idx_g, err

            init = (ws_r, idx_r, ws_g, idx_g, jnp.float32(0))
            *_, err = jax.lax.fori_loop(0, iters, body, init)
            return err[None]

        fn = shard_map_on(ctx, run, P("tp"), P("tp"))
        err = float(np.max(np.asarray(fn(jnp.asarray(base)))))
        print(f"  parity-stream case {case}: m={m} cols={cols} "
              f"iters={iters} max_err={err:.2e}")
        assert err < 1e-3, err
if __name__ == "__main__":
    sys.exit(main())
