"""On-chip probe: which inner-loop structure lets a megakernel GEMM task
reach the HBM roofline?

Round-5 megakernel attribution (scripts/mk_profile.py): the gate/up
GEMM_WIDE task measures ~61 us against a ~15 us weight-streaming roofline
at (128, 4096) @ (4096, 1536) bf16.  Hypothesis: the statically-unrolled
PREDICATED 128x128x128 dot pile (4-row super-strip x width @pl.when dots
per k-step, ~384 predicated dots per task) is the bound, not the DMA
schedule.  This probe times three bodies, all streaming B from HBM with a
depth-2 double buffer:

  tiles  — B as (T, 128, 128) tile-of-tiles, 4-row super-strips,
           per-(r, w) predicated 128^3 dots   (= current GEMM_WIDE body)
  ktile  — B as a 2D (K, N) matrix, (512, 1024)-row chunk fetches,
           per-k-tile (128,128)@(128,1024) dots (A stays in tile form)
  mat    — same fetches, A resident as a (128, K) matrix,
           per-chunk (128,512)@(512,1024) dots (fewest, deepest dots)

    TDTPU_BENCH_ON_TPU=1 python scripts/probe_gemm_task.py
"""

import functools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "benchmark"))

from _common import bootstrap  # noqa: E402

jax, ON_TPU = bootstrap(n_devices=1)

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

TILE = 128
if ON_TPU:
    K, N = 4096, 1536          # gate/up per-device shard shape
else:
    K, N = 512, 256
KT, NT = K // TILE, N // TILE
KCH = min(512, K)              # B chunk rows for the matrix bodies
NSTRIP = 1024 if N % 1024 == 0 or N > 1024 else N
NS = -(-N // NSTRIP)           # strips per matrix


def _tiles_kernel(a_ref, b_ref, o_ref, vrow, vbw, vacc, vout, csem, psems):
    """Current GEMM_WIDE structure: super-strips + predicated 128^3 dots."""
    width = NT
    # resident A row (chunked DMAs)
    ch = min(8, KT)
    nc = (KT + ch - 1) // ch

    def ld(c):
        return pltpu.make_async_copy(a_ref.at[pl.ds(c * ch, ch)],
                                     vrow.at[pl.ds(c * ch, ch)], csem)
    for c in range(nc):
        ld(c).start()
    for c in range(nc):
        ld(c).wait()
    vacc[...] = jnp.zeros_like(vacc)
    n_steps = KT // 4

    def sdesc(j, slot):
        return pltpu.make_async_copy(
            b_ref.at[pl.ds(j * 4 * width, 4 * width)],
            vbw.at[slot], psems.at[slot])

    sdesc(0, 0).start()

    @pl.when(n_steps > 1)
    def _():
        sdesc(1, 1).start()

    def jbody(j, _):
        slot = jax.lax.rem(j, 2)
        sdesc(j, slot).wait()
        for r in range(4):
            a_t = vrow[4 * j + r]
            for w in range(width):
                @pl.when(w < width)   # predication as in the real kernel
                def _(w=w, r=r, a_t=a_t):
                    vacc[w, :, :] = vacc[w] + jnp.dot(
                        a_t, vbw[slot, r * width + w],
                        preferred_element_type=jnp.float32)

        @pl.when(j + 2 < n_steps)
        def _():
            sdesc(j + 2, jax.lax.rem(j + 2, 2)).start()
        return 0

    jax.lax.fori_loop(0, n_steps, jbody, 0)
    for w in range(width):
        vout[w, :, :] = vacc[w].astype(o_ref.dtype)
    cp = pltpu.make_async_copy(vout, o_ref, csem)
    cp.start()
    cp.wait()


def _ktile_kernel(a_ref, b_ref, o_ref, vrow, vbm, vacc, vout, csem, psems):
    """Matrix-B chunks, per-k-tile (128,128)@(128,NSTRIP) dots."""
    ch = min(8, KT)
    nc = (KT + ch - 1) // ch

    def ld(c):
        return pltpu.make_async_copy(a_ref.at[pl.ds(c * ch, ch)],
                                     vrow.at[pl.ds(c * ch, ch)], csem)
    for c in range(nc):
        ld(c).start()
    for c in range(nc):
        ld(c).wait()
    n_ch = K // KCH

    for s in range(NS):
        def sdesc(j, slot, s=s):
            return pltpu.make_async_copy(
                b_ref.at[pl.ds((s * n_ch + j) * KCH, KCH)],
                vbm.at[slot], psems.at[slot])

        sdesc(0, 0).start()

        @pl.when(n_ch > 1)
        def _(s=s):
            sdesc(1, 1).start()

        vacc[...] = jnp.zeros_like(vacc)

        def jbody(j, _, s=s):
            slot = jax.lax.rem(j, 2)
            sdesc(j, slot).wait()
            for q in range(KCH // TILE):
                vacc[...] += jnp.dot(
                    vrow[j * (KCH // TILE) + q],
                    vbm[slot, pl.ds(q * TILE, TILE), :],
                    preferred_element_type=jnp.float32)

            @pl.when(j + 2 < n_ch)
            def _():
                sdesc(j + 2, jax.lax.rem(j + 2, 2)).start()
            return 0

        jax.lax.fori_loop(0, n_ch, jbody, 0)
        vout[...] = vacc[...].astype(o_ref.dtype)
        cp = pltpu.make_async_copy(
            vout, o_ref.at[:, pl.ds(s * NSTRIP, NSTRIP)], csem)
        cp.start()
        cp.wait()


def _mat_kernel(a_ref, b_ref, o_ref, vam, vbm, vacc, vout, csem, psems):
    """Matrix A and B: per-chunk (128, KCH)@(KCH, NSTRIP) dots."""
    for q in range(KT):   # A tiles -> matrix columns, all DMAs in flight
        pltpu.make_async_copy(a_ref.at[q], vam.at[:, pl.ds(q * TILE, TILE)],
                              psems.at[2]).start()
    for q in range(KT):
        pltpu.make_async_copy(a_ref.at[q], vam.at[:, pl.ds(q * TILE, TILE)],
                              psems.at[2]).wait()
    n_ch = K // KCH

    for s in range(NS):
        def sdesc(j, slot, s=s):
            return pltpu.make_async_copy(
                b_ref.at[pl.ds((s * n_ch + j) * KCH, KCH)],
                vbm.at[slot], psems.at[slot])

        sdesc(0, 0).start()

        @pl.when(n_ch > 1)
        def _(s=s):
            sdesc(1, 1).start()

        vacc[...] = jnp.zeros_like(vacc)

        def jbody(j, _, s=s):
            slot = jax.lax.rem(j, 2)
            sdesc(j, slot).wait()
            vacc[...] += jnp.dot(
                vam[:, pl.ds(j * KCH, KCH)], vbm[slot],
                preferred_element_type=jnp.float32)

            @pl.when(j + 2 < n_ch)
            def _():
                sdesc(j + 2, jax.lax.rem(j + 2, 2)).start()
            return 0

        jax.lax.fori_loop(0, n_ch, jbody, 0)
        vout[...] = vacc[...].astype(o_ref.dtype)
        cp = pltpu.make_async_copy(
            vout, o_ref.at[:, pl.ds(s * NSTRIP, NSTRIP)], csem)
        cp.start()
        cp.wait()


def build(kind):
    dt = jnp.bfloat16
    any_ = pl.BlockSpec(memory_space=pltpu.MemorySpace.ANY)
    if kind == "tiles":
        kernel, b_shape = _tiles_kernel, (KT * NT, TILE, TILE)
        scratch = [pltpu.VMEM((KT, TILE, TILE), dt),
                   pltpu.VMEM((2, 4 * NT, TILE, TILE), dt),
                   pltpu.VMEM((NT, TILE, TILE), jnp.float32),
                   pltpu.VMEM((NT, TILE, TILE), dt)]
        o_shape = (NT, TILE, TILE)
    elif kind == "ktile":
        kernel, b_shape = _ktile_kernel, (NS * K, NSTRIP)
        scratch = [pltpu.VMEM((KT, TILE, TILE), dt),
                   pltpu.VMEM((2, KCH, NSTRIP), dt),
                   pltpu.VMEM((TILE, NSTRIP), jnp.float32),
                   pltpu.VMEM((TILE, NSTRIP), dt)]
        o_shape = (TILE, NS * NSTRIP)
    else:
        kernel, b_shape = _mat_kernel, (NS * K, NSTRIP)
        scratch = [pltpu.VMEM((TILE, K), dt),
                   pltpu.VMEM((2, KCH, NSTRIP), dt),
                   pltpu.VMEM((TILE, NSTRIP), jnp.float32),
                   pltpu.VMEM((TILE, NSTRIP), dt)]
        o_shape = (TILE, NS * NSTRIP)
    scratch += [pltpu.SemaphoreType.DMA(()), pltpu.SemaphoreType.DMA((3,))]

    f = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=0, grid=(1,), in_specs=[any_, any_],
            out_specs=any_, scratch_shapes=scratch),
        out_shape=jax.ShapeDtypeStruct(o_shape, dt),
        compiler_params=pltpu.CompilerParams(has_side_effects=True),
        interpret=not ON_TPU,
    )
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.standard_normal((KT, TILE, TILE)) * 0.1, dt)
    b = jnp.asarray(rng.standard_normal(b_shape) * 0.1, dt)

    @functools.partial(jax.jit, static_argnums=2)
    def chain(a, b, n):
        def body(i, cur):
            o = f(cur, b)
            # fold a scalar of the output back into A: data dependency
            s = (o[0, 0, 0] if o.ndim == 3 else o[0, 0]).astype(a.dtype)
            return cur + s * 1e-6

        return jax.lax.fori_loop(0, n, body, a)

    return chain, a, b


def time_kind(kind, lengths=(64, 320, 576), trials=5):
    chain, a, b = build(kind)
    t = {n: float("inf") for n in lengths}
    for n in lengths:
        jax.block_until_ready(chain(a, b, n))
    for _ in range(trials):
        for n in lengths:
            t0 = time.perf_counter()
            _ = np.asarray(jnp.sum(chain(a, b, n)))
            t[n] = min(t[n], time.perf_counter() - t0)
    n1, n2, n3 = lengths
    d21 = (t[n2] - t[n1]) / (n2 - n1)
    d32 = (t[n3] - t[n2]) / (n3 - n2)
    per = (t[n3] - t[n1]) / (n3 - n1)
    ok = t[n3] > t[n2] > t[n1] and 0.33 < d21 / max(d32, 1e-12) < 3.0
    return per, ok, (d21, d32)


def main():
    gb = KT * NT * TILE * TILE * 2 / 1e9
    gb_pad = NS * K * NSTRIP * 2 / 1e9   # ktile/mat stream strip padding
    print(f"# ({TILE},{K}) @ ({K},{N}) bf16; B bytes {gb*1e3:.1f} MB "
          f"(~{gb/0.819*1e6:.1f} us roofline); ktile/mat stream "
          f"{gb_pad*1e3:.1f} MB incl. strip pad "
          f"(~{gb_pad/0.819*1e6:.1f} us) "
          f"({'TPU' if ON_TPU else 'CPU smoke'})")
    for kind in ("tiles", "ktile", "mat"):
        per, ok, (d21, d32) = time_kind(kind)
        flag = "" if ok else "  [INCONSISTENT]"
        print(f"{kind:6} {per*1e6:9.2f} us/iter  "
              f"(d21 {d21*1e6:.2f} d32 {d32*1e6:.2f}){flag}")


if __name__ == "__main__":
    main()
